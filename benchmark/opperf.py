#!/usr/bin/env python
"""opperf: per-operator micro-benchmark sweep (reference:
``benchmark/opperf/opperf.py``).

Times every registered op it can synthesize inputs for, on the default
device, measuring steady-state dispatch+execute latency through the
SAME eager path users hit (the persistent per-op jit cache).  Prints one
JSON object per op and a summary line; ``--json FILE`` dumps the full
table.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


# ops with simple (data,) or (lhs, rhs) tensor signatures we can drive
# blind; everything else needs the curated entries below
_CURATED = {
    "FullyConnected": (lambda mx, np: ([mx.nd.array(np.random.randn(
        32, 64).astype(np.float32)), mx.nd.array(np.random.randn(
            128, 64).astype(np.float32)), mx.nd.array(np.zeros(
                128, np.float32))], {"num_hidden": 128})),
    "Convolution": (lambda mx, np: ([mx.nd.array(np.random.randn(
        8, 8, 16, 16).astype(np.float32)), mx.nd.array(np.random.randn(
            16, 8, 3, 3).astype(np.float32)), mx.nd.array(np.zeros(
                16, np.float32))], {"num_filter": 16, "kernel": (3, 3),
                                    "pad": (1, 1)})),
    "dot": (lambda mx, np: ([mx.nd.array(np.random.randn(
        128, 128).astype(np.float32))] * 2, {})),
    "batch_dot": (lambda mx, np: ([mx.nd.array(np.random.randn(
        8, 64, 64).astype(np.float32))] * 2, {})),
    "softmax": None, "relu": None, "sigmoid": None, "tanh": None,
    "exp": None, "log": None, "sqrt": None, "square": None,
    "sum": None, "mean": None, "max": None, "min": None, "argmax": None,
    "elemwise_add": None, "elemwise_mul": None, "broadcast_add": None,
    "broadcast_mul": None, "transpose": None, "reshape_like": None,
    "abs": None, "negative": None, "LayerNorm": (lambda mx, np: (
        [mx.nd.array(np.random.randn(32, 128).astype(np.float32)),
         mx.nd.ones((128,)), mx.nd.zeros((128,))], {})),
}

_UNARY = {"softmax", "relu", "sigmoid", "tanh", "exp", "log", "sqrt",
          "square", "sum", "mean", "max", "min", "argmax", "transpose",
          "abs", "negative"}
_BINARY = {"elemwise_add", "elemwise_mul", "broadcast_add",
           "broadcast_mul", "reshape_like"}


def run(ops=None, warmup=5, runs=50, shape=(64, 64)):
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.ops.registry import OP_REGISTRY

    x = mx.nd.array(np.random.rand(*shape).astype(np.float32) + 0.5)
    results = []
    names = ops or sorted(_CURATED)
    for name in names:
        if name not in OP_REGISTRY:
            results.append({"op": name, "error": "unknown op"})
            continue
        spec = _CURATED.get(name)
        if spec is not None:
            args, kwargs = spec(mx, np)
        elif name in _UNARY:
            args, kwargs = [x], {}
        elif name in _BINARY:
            args, kwargs = [x, x], {}
        else:
            results.append({"op": name,
                            "skipped": "no input synthesizer"})
            continue
        fn = getattr(mx.nd, name)
        try:
            for _ in range(warmup):
                out = fn(*args, **kwargs)
            mx.nd.waitall()
            t0 = time.time()
            for _ in range(runs):
                out = fn(*args, **kwargs)
            mx.nd.waitall()
            dt = (time.time() - t0) / runs
            results.append({"op": name, "avg_us": round(dt * 1e6, 2)})
        except Exception as e:
            results.append({"op": name, "error": str(e)[:120]})
    return results


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--ops", nargs="*", default=None)
    p.add_argument("--runs", type=int, default=50)
    p.add_argument("--json", default=None)
    args = p.parse_args(argv)
    results = run(ops=args.ops, runs=args.runs)
    for r in results:
        print(json.dumps(r))
    ok = [r for r in results if "avg_us" in r]
    print(json.dumps({"opperf_ops": len(ok),
                      "median_us": sorted(r["avg_us"] for r in ok)[
                          len(ok) // 2] if ok else None}))
    if args.json:
        with open(args.json, "w") as f:
            json.dump(results, f, indent=2)


if __name__ == "__main__":
    main()
