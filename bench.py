"""Benchmark harness (driver contract + BASELINE.md configs).

Measures steady-state training throughput on the available accelerator
(the one real TPU chip under the driver; CPU otherwise):

- config 1: LeNet-style convnet, MNIST shapes, hybridized Gluon
- config 2: ResNet-50 v1, synthetic ImageNet batches (the headline)

Each config times the FULL training step (forward + loss + backward +
optimizer update) as one compiled program (``mxnet_tpu.parallel.TrainStep``)
with device-resident synthetic data, after warmup.  Reference analog:
``example/image-classification/common/fit.py :: Speedometer`` samples/sec.

Prints one progress JSON object per config, then the final parseable line:
``{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}``.
vs_baseline denominator: BASELINE.md's A100 anchor for MXNet-CUDA
ResNet-50 (~3000 img/s with DALI+AMP; unverified memory anchor).
"""
import json
import os as _os
import time

import numpy as np


def _ctx():
    import mxnet_tpu as mx
    return mx.tpu() if mx.num_tpus() else mx.cpu()


def _subprocess_value(expr, timeout=600, force_cpu=False):
    """Evaluate ``expr`` (a bench.* call) in a fresh interpreter and
    return its printed float.  ``force_cpu`` keeps the CPU backend out
    of this process (local-dispatch measurements); without it the child
    sees the same accelerator but with a FRESH tunnel -- host->device
    transfers collapse to ~10 MB/s in any process whose TPU has already
    run compute (docs/perf_resnet50.md), so transfer-sensitive configs
    must not share this process."""
    import subprocess
    import sys
    code = ("import sys; sys.path.insert(0, %r); import bench; "
            "print(%s)" % (_os.path.dirname(_os.path.abspath(__file__)),
                           expr))
    env = dict(_os.environ)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=timeout)
    return float(out.stdout.strip().splitlines()[-1])


def _cpu_subprocess_value(expr, timeout=600):
    return _subprocess_value(expr, timeout=timeout, force_cpu=True)


def _bench_train(net, loss_fn, data_shape, label_shape, n_classes,
                 batch_size, lr=0.05, warmup=5, iters=30, dtype="float32"):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep

    import contextlib
    from mxnet_tpu import amp
    ctx = _ctx()
    net.initialize(ctx=ctx, force_reinit=True)
    net.hybridize()
    # mixed precision: params stay fp32, MXU ops run in the target dtype
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, loss_fn, trainer, mesh=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(*data_shape).astype(np.float32), ctx=ctx)
    y = mx.nd.array(
        rng.randint(0, n_classes, size=label_shape).astype(np.float32),
        ctx=ctx)
    with amp_ctx:
        for _ in range(warmup):
            step(x, y)
        # Synchronize via a scalar host fetch: on the axon tunnel
        # block_until_ready can return before execution finishes, so a
        # value dependency is the only trustworthy barrier.  Steps are
        # chained through the parameters, so fetching the last loss
        # drains the queue.
        float(step(x, y).asscalar())
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            last = step(x, y)
        float(last.asscalar())
        dt = time.perf_counter() - t0
    return batch_size * iters / dt


def _lenet_net():
    from mxnet_tpu import gluon
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(20, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(50, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(500, activation="relu"),
            gluon.nn.Dense(10))
    return net


def bench_lenet(batch_size=256):
    from mxnet_tpu import gluon
    net = _lenet_net()
    return _bench_train(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        (batch_size, 1, 28, 28), (batch_size,), 10,
                        batch_size, warmup=5, iters=50)


def bench_lenet_imperative(batch_size=256, iters=30):
    """Config 1's stated mode: NON-hybridized eager training -- every op
    call dispatches through the persistent per-op jit cache (SURVEY §7
    hard-part #1).  Measured honestly (r3): with LOCAL dispatch (CPU
    backend, uncontended) the eager loop is ~3.3x slower than the
    hybridized one -- per-op execution forgoes XLA fusion and
    materializes every intermediate, the usual eager/compiled gap; the
    tunneled remote chip pays an extra round-trip per op (~4x).  The
    driver artifact carries both numbers
    (``lenet_imperative_local_dispatch_cpu``)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    ctx = _ctx()
    net = _lenet_net()
    net.initialize(ctx=ctx, force_reinit=True)   # NOT hybridized
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch_size, 1, 28, 28).astype(np.float32),
                    ctx=ctx)
    y = mx.nd.array(rng.randint(0, 10, (batch_size,)).astype(np.float32),
                    ctx=ctx)

    def step():
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(batch_size)
        return loss

    for _ in range(5):
        step()
    float(step().asscalar())
    t0 = time.perf_counter()
    last = None
    for _ in range(iters):
        last = step()
    float(last.asscalar())
    return batch_size * iters / (time.perf_counter() - t0)


def bench_resnet50(batch_size=128, dtype="float32"):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    net = resnet50_v1()
    return _bench_train(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        (batch_size, 3, 224, 224), (batch_size,), 1000,
                        batch_size, warmup=5, iters=20, dtype=dtype)


# v5e bf16 peak; used only to contextualize throughput as MFU
_TPU_PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v5e": 197e12,
                   "TPU v5": 459e12, "TPU v4": 275e12}


def _peak_flops():
    import jax
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for k, v in _TPU_PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return None


def bench_resnet50_scan(batch_size=256, k=10, dtype="bfloat16", reps=4):
    """ResNet-50 with the compiled multi-step train loop
    (``TrainStep.run_steps``): K full steps per dispatch -- the
    TPU-idiomatic inner loop, no per-step host round-trip.  Returns
    (img/s, mfu_or_None)."""
    import contextlib
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    net = resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer,
                     mesh=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(k, batch_size, 3, 224, 224).astype(np.float32),
                    ctx=ctx)
    y = mx.nd.array(rng.randint(0, 1000, (k, batch_size)).astype(np.float32),
                    ctx=ctx)
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    with amp_ctx:
        step.run_steps(x, y)
        float(step.run_steps(x, y).asnumpy()[-1])
        t0 = time.perf_counter()
        last = None
        for _ in range(reps):
            last = step.run_steps(x, y)
        float(last.asnumpy()[-1])
        dt = (time.perf_counter() - t0) / (reps * k)
        # single-step program for an honest per-step flop count (the scan
        # program reports its loop body once)
        step(mx.nd.array(x.asnumpy()[0], ctx=ctx),
             mx.nd.array(y.asnumpy()[0], ctx=ctx))
        ca = step.cost_analysis()
    mfu = None
    peak = _peak_flops()
    if ca and ca.get("flops") and peak:
        mfu = round(ca["flops"] / dt / peak, 4)
    return batch_size / dt, mfu


def bench_bert_base(batch_size=16, seq_len=128, vocab=30522,
                    dtype="float32", use_flash=None, iters=20):
    """BERT-base masked-LM pretraining step (config 3).
    Returns (tokens/s, mfu_or_None)."""
    import contextlib
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    mx.random.seed(0)
    net = gluon.model_zoo.bert_base(vocab_size=vocab, max_length=seq_len,
                                    dropout=0.0, use_flash=use_flash)
    net.initialize(ctx=ctx)
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class MLMLoss(gluon.HybridBlock):
        def hybrid_forward(self, F, outs, labels):
            mlm, _nsp = outs
            return ce(mlm.reshape((-1, vocab)), labels.reshape((-1,)))

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-4}, kvstore=None)
    step = TrainStep(net, MLMLoss(), trainer, mesh=None)
    rng = np.random.RandomState(0)
    ids = mx.nd.array(
        rng.randint(0, vocab, (batch_size, seq_len)).astype(np.float32),
        ctx=ctx)
    labels = mx.nd.array(
        rng.randint(0, vocab, (batch_size, seq_len)).astype(np.float32),
        ctx=ctx)
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    with amp_ctx:
        for _ in range(5):
            step(ids, labels)
        float(step(ids, labels).asscalar())
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            last = step(ids, labels)
        float(last.asscalar())
        dt = time.perf_counter() - t0
        ca = step.cost_analysis()
    mfu = None
    peak = _peak_flops()
    if ca and ca.get("flops") and peak:
        mfu = round(ca["flops"] * iters / (dt * peak), 4)
    return batch_size * seq_len * iters / dt, mfu


def _build_rec(path, n, fmt="jpg", hw=256, crop=224, seed=0):
    """Synthetic .rec dataset for the pipeline benchmarks.

    Images are natural-like (low-frequency content + mild noise), not
    uniform noise: noise JPEGs are pathological for the entropy coder
    (~2x the decode cost of a photo), which would understate pipeline
    throughput."""
    import mxnet_tpu as mx
    from mxnet_tpu import recordio
    from mxnet_tpu.image.image import _resize_np
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        base = rng.randint(0, 255, (16, 16, 3), dtype=np.uint8)
        img = _resize_np(base, hw, hw).astype(np.int16)
        img += rng.randint(-8, 9, img.shape, dtype=np.int16)
        img = np.clip(img, 0, 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        if fmt == "raw":
            rec.write_idx(i, recordio.pack(
                header, img[:crop, :crop].tobytes()))
        else:
            rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()
    return path + ".rec"


def _pipeline_epoch_rate(rec, batch_size, dtype, epochs=3, **iter_kw):
    from mxnet_tpu.image import ImageIter
    it = ImageIter(batch_size, (3, 224, 224), path_imgrec=rec,
                   dtype=dtype, **iter_kw)
    try:
        count = 0
        t0 = time.perf_counter()
        for _ in range(epochs):
            it.reset()
            try:
                while True:
                    d, _l, _pad = it.next_np()
                    count += d.shape[0]
            except StopIteration:
                pass
        return count / (time.perf_counter() - t0)
    finally:
        it.close()


def bench_pipeline(n=512, batch_size=64, threads=2):
    """Input pipeline host throughput (reference bar:
    ``iter_image_recordio_2.cc`` threaded decode).  Returns
    (jpeg_img_per_s, raw_uint8_img_per_s, scaling) where ``scaling``
    maps worker configs (threads=N / procs=N) to jpeg img/s -- the
    measured scaling table.  Numbers are per-host; this box has one
    core, so the process-pool rows document the contention floor rather
    than the multi-core ceiling."""
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="mxtpu_bench_rec_")
    try:
        rec_jpg = _build_rec(_os.path.join(tmp, "jpg"), n, "jpg")
        rec_raw = _build_rec(_os.path.join(tmp, "raw"), n, "raw")
        scaling = {}
        for label, kw in (("threads=1", dict(preprocess_threads=0)),
                          ("threads=2", dict(preprocess_threads=2)),
                          ("threads=4", dict(preprocess_threads=4)),
                          ("procs=2", dict(preprocess_procs=2)),
                          ("procs=4", dict(preprocess_procs=4))):
            scaling[label] = round(_pipeline_epoch_rate(
                rec_jpg, batch_size, "float32", **kw), 1)
        jpeg = max(scaling.values())
        raw = _pipeline_epoch_rate(rec_raw, batch_size, "uint8",
                                   preprocess_threads=threads)
        return jpeg, raw, scaling
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_resnet50_e2e(batch_size=256, n_images=2048, dtype="bfloat16",
                       epochs=3):
    """End-to-end ResNet-50 training fed by the REAL input pipeline
    (raw-record uint8 decode through ImageIter), not synthetic tensors.

    The decoded dataset is staged onto the device in ONE transfer
    BEFORE training starts, then every epoch trains from the staged
    uint8 batches with on-device slice + cast.  The timed window
    includes the decode and the staging transfer.

    Why not per-batch host feeding: measured on the axon tunnel, any
    host->device transfer issued after the training program has run
    collapses to ~10 MB/s (idle-process H2D is ~0.7-1.6 GB/s; see
    docs/perf_resnet50.md) -- an environment pathology, not a pipeline
    property.  On a PCIe-local host the producer/consumer overlap is
    the normal mode; here the bench measures what the tunnel admits
    while still exercising decode -> stage -> train end to end.
    """
    import contextlib
    import shutil
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.image import ImageIter
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    tmp = tempfile.mkdtemp(prefix="mxtpu_bench_e2e_")
    rec = _build_rec(_os.path.join(tmp, "train"), n_images, "raw")
    it = ImageIter(batch_size, (3, 224, 224), path_imgrec=rec,
                   preprocess_threads=0, dtype="uint8")

    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0] if mx.num_tpus() else jax.devices("cpu")[0]
    compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    pick = jax.jit(lambda s, i: jax.lax.dynamic_index_in_dim(
        s, i, 0, keepdims=False).astype(compute_dtype))

    n_batches = n_images // batch_size
    host = np.empty((n_batches, batch_size, 3, 224, 224), np.uint8)
    host_labels = np.empty((n_batches, batch_size), np.float32)

    t_start = time.perf_counter()
    it.reset()
    for k in range(n_batches):
        _d, l, _pad = it.next_np(out=host[k])
        host_labels[k] = l
    it.close()
    shutil.rmtree(tmp, ignore_errors=True)
    staged = jax.device_put(host, dev)
    labels_dev = jax.device_put(host_labels, dev)
    jax.block_until_ready(staged)
    t_staged = time.perf_counter()

    net = resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer,
                     mesh=None)
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    with amp_ctx:
        xw = mx.nd.NDArray(pick(staged, 0))
        yw = mx.nd.NDArray(labels_dev[0])
        for _ in range(3):
            step(xw, yw)
        float(step(xw, yw).asscalar())

        count = 0
        last = None
        t0 = time.perf_counter()
        for _ in range(epochs):
            for k in range(n_batches):
                x = mx.nd.NDArray(pick(staged, k))
                y = mx.nd.NDArray(labels_dev[k])
                last = step(x, y)
                count += batch_size
        float(last.asscalar())
        dt = (time.perf_counter() - t0) + (t_staged - t_start)
    return count / dt


def _emit_with_retry(metric, fn, attempts=2, unit="tokens/s",
                     extra=None, extra_fn=None):
    """Run fn() with retries (the tunneled compile service can drop a
    connection mid-build); emit one JSON line either way, keyed by the
    SAME metric name on success and failure.  ``extra_fn`` is called
    after a successful run for fields computed during it."""
    for attempt in range(attempts):
        try:
            val = fn()
            rec = {"metric": metric, "value": round(val, 1), "unit": unit,
                   "vs_baseline": None}
            if extra:
                rec.update(extra)
            if extra_fn is not None:
                rec.update(extra_fn())
            print(json.dumps(rec))
            return val
        except Exception as e:
            if attempt == attempts - 1:
                print(json.dumps({"metric": metric,
                                  "error": str(e)[:200]}))
            else:
                time.sleep(5)
    return None


def main():
    import mxnet_tpu as mx
    results = {}
    on_tpu = mx.num_tpus() > 0
    # CPU fallback keeps the harness runnable in dev; shrink the work.
    if on_tpu:
        lenet_bs, rn_bs, = 256, 128
    else:
        lenet_bs, rn_bs = 64, 8

    lenet = bench_lenet(lenet_bs)
    results["lenet_mnist_train"] = lenet
    print(json.dumps({"metric": "lenet_mnist_train", "value": round(lenet, 1),
                      "unit": "img/s", "vs_baseline": None}))

    try:
        lenet_imp = bench_lenet_imperative(lenet_bs,
                                           iters=30 if on_tpu else 5)
        results["lenet_mnist_train_imperative"] = lenet_imp
        print(json.dumps({"metric": "lenet_mnist_train_imperative",
                          "value": round(lenet_imp, 1), "unit": "img/s",
                          "vs_baseline": None}))
    except Exception as e:
        print(json.dumps({"metric": "lenet_mnist_train_imperative",
                          "error": str(e)[:200]}))

    if on_tpu:
        # Evidence for the dispatch-gap claim: the same imperative loop
        # with LOCAL dispatch (CPU backend, no tunnel RTT per op).  Run in
        # subprocesses so the CPU backend can't disturb this process.
        try:
            val = _cpu_subprocess_value(
                "bench.bench_lenet_imperative(64, iters=20)")
            val2 = _cpu_subprocess_value("bench.bench_lenet(64)")
            print(json.dumps({"metric":
                              "lenet_imperative_local_dispatch_cpu",
                              "value": round(val, 1), "unit": "img/s",
                              "vs_baseline": None,
                              "hybridized_local_cpu": round(val2, 1),
                              "imperative_over_hybridized":
                              round(val / val2, 3)}))
        except Exception as e:
            print(json.dumps({"metric": "lenet_imperative_local_dispatch",
                              "error": str(e)[:200]}))

    rn = bench_resnet50(rn_bs)
    results["resnet50_train_fp32"] = rn
    print(json.dumps({"metric": "resnet50_imagenet_train_fp32",
                      "value": round(rn, 1), "unit": "img/s",
                      "vs_baseline": None}))

    headline = rn
    try:
        # bf16 halves activation memory: double the batch for MXU util
        rn_bf16 = bench_resnet50(rn_bs * 2 if on_tpu else rn_bs,
                                 dtype="bfloat16")
        results["resnet50_train_bf16"] = rn_bf16
        print(json.dumps({"metric": "resnet50_imagenet_train_bf16",
                          "value": round(rn_bf16, 1), "unit": "img/s",
                          "vs_baseline": None}))
        headline = max(headline, rn_bf16)
    except Exception as e:  # bf16 path optional until AMP lands fully
        print(json.dumps({"metric": "resnet50_imagenet_train_bf16",
                          "error": str(e)[:200]}))

    try:
        # compiled K-step train loop: kills the per-step dispatch gap
        # (bandwidth-bound model; see docs/perf_resnet50.md)
        rn_scan, rn_mfu = bench_resnet50_scan(
            rn_bs * 2 if on_tpu else rn_bs, k=10 if on_tpu else 2,
            dtype="bfloat16" if on_tpu else "float32",
            reps=4 if on_tpu else 1)
        results["resnet50_train_bf16_scan"] = rn_scan
        print(json.dumps({"metric": "resnet50_imagenet_train_bf16_scan",
                          "value": round(rn_scan, 1), "unit": "img/s",
                          "mfu": rn_mfu, "vs_baseline": None}))
        headline = max(headline, rn_scan)
    except Exception as e:
        print(json.dumps({"metric": "resnet50_imagenet_train_bf16_scan",
                          "error": str(e)[:200]}))

    try:
        jpeg_ips, raw_ips, scaling = bench_pipeline(
            n=512 if on_tpu else 128, threads=2)
        print(json.dumps({"metric": "pipeline_jpeg_decode",
                          "value": round(jpeg_ips, 1),
                          "unit": "img/s/host",
                          "host_cores": _os.cpu_count(),
                          "scaling": scaling,
                          "vs_baseline": None}))
        print(json.dumps({"metric": "pipeline_raw_uint8",
                          "value": round(raw_ips, 1),
                          "unit": "img/s/host",
                          "host_cores": _os.cpu_count(),
                          "vs_baseline": None}))
    except Exception as e:
        print(json.dumps({"metric": "pipeline", "error": str(e)[:200]}))

    if on_tpu:
        try:
            # fresh subprocess: the dataset staging transfer must happen
            # before any compute touches this process's tunnel
            e2e = _subprocess_value(
                "bench.bench_resnet50_e2e(%d, dtype='bfloat16')"
                % (rn_bs * 2), timeout=1200)
            results["resnet50_e2e"] = e2e
            print(json.dumps({"metric": "resnet50_imagenet_train_e2e_bf16",
                              "value": round(e2e, 1), "unit": "img/s",
                              "vs_baseline": None}))
        except Exception as e:
            print(json.dumps({"metric": "resnet50_imagenet_train_e2e_bf16",
                              "error": str(e)[:200]}))

    # bs=256 is the single-chip throughput knee with the r4 attention
    # path (measured: 114k tok/s at bs128 -> 126k at bs256, down at
    # bs384, compile-service OOM at bs512).  The seq sweep captures the
    # XLA/Pallas crossover in the driver artifact itself: the auto path
    # routes seq 128 to plain XLA attention and seq >= 256 to the Pallas
    # flash kernels.
    def _emit_bert(metric, bs, seq, dt_name, iters):
        out = {}

        def run():
            tok, mfu = bench_bert_base(bs, seq, dtype=dt_name,
                                       iters=iters)
            out["mfu"] = mfu
            return tok
        val = _emit_with_retry(metric, run, attempts=3,
                               extra_fn=lambda: {"mfu": out.get("mfu"),
                                                 "seq_len": seq,
                                                 "batch_size": bs})
        return val

    if on_tpu:
        tok = _emit_bert("bert_base_pretrain_bfloat16", 256, 128,
                         "bfloat16", 12)
        if tok is not None:
            results["bert_base_bfloat16"] = tok
        _emit_bert("bert_base_pretrain_seq512_bf16", 64, 512,
                   "bfloat16", 10)
        # long-context config: seq 1024 is where the Pallas flash
        # fwd+bwd kernels pull away from XLA (81k vs 60k tok/s, r3)
        _emit_bert("bert_base_pretrain_seq1024_bf16_flash", 16, 1024,
                   "bfloat16", 10)
    else:
        _emit_bert("bert_base_pretrain_float32", 2, 32, "float32", 3)

    # BASELINE.md anchor: MXNet-CUDA A100 ResNet-50 ~3000 img/s (AMP+DALI)
    baseline = 3000.0
    print(json.dumps({"metric": "resnet50_imagenet_train",
                      "value": round(headline, 1), "unit": "img/s",
                      "vs_baseline": round(headline / baseline, 4)}))


if __name__ == "__main__":
    main()
