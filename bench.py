"""Benchmark harness (driver contract + BASELINE.md configs).

Measures steady-state training throughput on the available accelerator
(the one real TPU chip under the driver; CPU otherwise):

- config 1: LeNet-style convnet, MNIST shapes, hybridized Gluon
- config 2: ResNet-50 v1, synthetic ImageNet batches (the headline)

Each config times the FULL training step (forward + loss + backward +
optimizer update) as one compiled program (``mxnet_tpu.parallel.TrainStep``)
with device-resident synthetic data, after warmup.  Reference analog:
``example/image-classification/common/fit.py :: Speedometer`` samples/sec.

Prints one progress JSON object per config, then the final parseable line:
``{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}``.
vs_baseline denominator: BASELINE.md's A100 anchor for MXNet-CUDA
ResNet-50 (~3000 img/s with DALI+AMP; unverified memory anchor).
"""
import json
import os as _os
import statistics
import time

import numpy as np

# Self-budget (VERDICT r4 #1): the bench must NEVER outlive the
# driver's time allowance again.  Headline metrics emit first; every
# optional config is gated on the remaining budget and prints a
# {"skipped": ...} line instead of dying at rc=124.
_T_START = time.monotonic()
_BUDGET_S = float(_os.environ.get("MXNET_TPU_BENCH_BUDGET_S", "1500"))


def _remaining():
    return _BUDGET_S - (time.monotonic() - _T_START)


def _budget_ok(metric, est_s):
    """True if ``est_s`` seconds still fit the budget; else emits the
    skip line for ``metric`` and returns False."""
    if _remaining() < est_s:
        print(json.dumps({"metric": metric, "skipped": True,
                          "reason": "bench budget: %.0fs remaining < "
                                    "%.0fs estimate"
                                    % (max(_remaining(), 0), est_s)}))
        return False
    return True


def _ctx():
    import mxnet_tpu as mx
    return mx.tpu() if mx.num_tpus() else mx.cpu()


# CostReport artifact paths by tag, filled by the bench fns and read by
# main()'s extra_fn so the JSONL line carries the artifact it sits
# next to (ISSUE 6: regression-attributable headline numbers)
_COST_ARTIFACTS = {}


def _persist_cost_report(tag, step, step_time_s=None,
                         items_per_step=None):
    """Persist the compiled step's CostReport (per-HLO-category FLOPs/
    bytes + roofline at the measured step time) next to the bench's
    JSONL output.  Never raises: a failed capture costs the artifact,
    not the benchmark."""
    try:
        from mxnet_tpu import profiling
        rep = profiling.report_for(step, label=tag,
                                   step_time_s=step_time_s,
                                   items_per_step=items_per_step)
        if rep is None:
            return None
        outdir = _os.environ.get("MXNET_TPU_PROFILING_DIR") \
            or "bench_artifacts"
        _os.makedirs(outdir, exist_ok=True)
        path = _os.path.join(outdir, tag + ".cost.json")
        with open(path, "w") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
        _COST_ARTIFACTS[tag] = path
        return path
    except Exception:
        return None


# r05 shipped on a collapsed tunnel (dispatch RTT ~90ms vs ~2ms
# healthy) and its headline read as a perf regression until the
# env_health line was cross-checked by hand.  Every emitted JSONL line
# now carries `degraded_env`, derived ONCE from the health probe, so a
# tunnel collapse can never again be read as a model regression.
_DEGRADED_RTT_US = 10000.0
_ENV_DEGRADED = {"flag": None}     # None until the health probe ran


def _mark_env_health(health):
    """Derive the degraded-environment flag from the env_health probe
    (dispatch_roundtrip threshold); returns the flag for the line.
    The threshold is THE goodput sentinel's env guard
    (obs.goodput.env_degraded / DEGRADED_RTT_US), so the per-line flag
    and a goodput.env_degraded event can never disagree
    (test_bench_contract).  The probe numbers also land as telemetry
    gauges (env.dispatch_roundtrip_us / env.h2d_mb_per_s) so the basis
    of a degraded_env verdict survives in summarize output and the
    flight-recorder dump, not just this process's stdout."""
    rtt = health.get("dispatch_roundtrip_us")
    try:
        from mxnet_tpu.obs import goodput as _goodput
        flag = _goodput.env_degraded(rtt) if rtt is not None else False
    except Exception:
        flag = bool(rtt is not None and rtt > _DEGRADED_RTT_US)
    _ENV_DEGRADED["flag"] = flag
    try:
        from mxnet_tpu import telemetry as _telemetry
        if _telemetry._ENABLED and rtt is not None:
            _telemetry.hooks.env_health(rtt,
                                        health.get("h2d_mb_per_s"))
    except Exception:
        pass                  # health marking must never fail a bench
    return _ENV_DEGRADED["flag"]


# ----------------------------------------------------------------------
# goodput breakdowns (ISSUE 14): the scan/LARS/e2e lines carry the
# StepLedger's per-category wall attribution + bottleneck verdict, so
# the synthetic-vs-e2e gap is auto-attributed in the artifact itself.
# ----------------------------------------------------------------------

_GOODPUT = {}                 # tag -> compact goodput line summary


def _goodput_begin():
    """Open a StepLedger over a measured window (arming telemetry +
    profiling if off, so the category instruments record); returns
    ``(ledger, restore_fn)``, or ``(None, noop)`` when obs is
    unavailable -- a failed ledger costs the breakdown, never the
    benchmark."""
    try:
        from mxnet_tpu import profiling, telemetry
        from mxnet_tpu.obs import goodput as _gp
        was_t = telemetry.enabled()
        was_p = profiling.enabled()
        telemetry.enable()
        profiling.enable()
        ledger = _gp.StepLedger(window_steps=1 << 30)  # manual flush

        def restore():
            if not was_t:
                telemetry.disable()
            if not was_p:
                profiling.disable()
        return ledger, restore
    except Exception:
        return None, lambda: None


def _goodput_end(tag, ledger, restore, steps):
    """Close the measured window and stash the compact breakdown for
    the JSONL line under ``tag``; never fatal."""
    try:
        if ledger is None:
            return None
        from mxnet_tpu.obs import goodput as _gp
        ledger.step(steps)
        win = ledger.flush(reason="bench")
        _GOODPUT[tag] = _gp.line_summary(win)
        return _GOODPUT[tag]
    except Exception:
        return None
    finally:
        restore()


def _goodput_extra(tag):
    """extra_fn fields: the goodput breakdown riding the JSONL line."""
    gp = _GOODPUT.get(tag)
    return {"goodput": gp} if gp else {}


# ----------------------------------------------------------------------
# kernel-tier before/after HLO diff (ISSUE 11): the resnet50-scan and
# BERT-flash lines carry per-category compiled-HLO byte deltas of the
# SAME probe model built with the Pallas kernel tier off vs armed --
# the `mxprof diff` of the kernel tier, riding the JSONL line itself.
# ----------------------------------------------------------------------

def _hlo_category_bytes(step):
    """Category byte counters of a TrainStep's most recent compiled
    program (analysis.perf.audit_hlo_text over the compiled HLO)."""
    from mxnet_tpu.analysis.perf import audit_hlo_text
    fn, arg_shapes = step._last_call
    text = fn.lower(*arg_shapes).compile().as_text()
    c = audit_hlo_text(text)
    out = {k: int(v) for k, v in c["category_bytes"].items()}
    out["unfused_elementwise"] = int(c["unfused_elementwise_bytes"])
    out["bytes_total"] = int(c["bytes_total"])
    return out


def _kernels_probe_step(model):
    """Compile one small fwd+bwd+update step of the probe model under
    the CURRENT kernel-tier mode and return the TrainStep.  NHWC +
    LARS for the resnet probe (the fused BN+ReLU sites and the
    bucket-flattened optimizer both engage); a small flash BERT for
    the attention probe."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep
    ctx = _ctx()
    rng = np.random.RandomState(0)
    if model == "resnet":
        from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
        net = resnet18_v1(classes=10, thumbnail=True, layout="NHWC")
        net.initialize(ctx=ctx)
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "lars",
                                {"learning_rate": 0.1}, kvstore=None)
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         trainer, mesh=None)
        x = mx.nd.array(rng.rand(2, 32, 32, 3).astype(np.float32),
                        ctx=ctx)
        y = mx.nd.array(rng.randint(0, 10, (2,)).astype(np.float32),
                        ctx=ctx)
    else:                             # "bert": the flash-attention probe
        vocab = 512
        net = gluon.model_zoo.bert_small(vocab_size=vocab,
                                         max_length=256, dropout=0.0)
        net.initialize(ctx=ctx)
        net.hybridize()
        ce = gluon.loss.SoftmaxCrossEntropyLoss()

        class _MLM(gluon.HybridBlock):
            def hybrid_forward(self, F, outs, labels):
                mlm, _nsp = outs
                return ce(mlm.reshape((-1, vocab)),
                          labels.reshape((-1,)))

        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 1e-4}, kvstore=None)
        step = TrainStep(net, _MLM(), trainer, mesh=None)
        x = mx.nd.array(rng.randint(0, vocab, (1, 256))
                        .astype(np.float32), ctx=ctx)
        y = mx.nd.array(rng.randint(0, vocab, (1, 256))
                        .astype(np.float32), ctx=ctx)
    step(x, y)
    return step


def _kernels_diff(model):
    """Before/after category bytes of the probe model's compiled step:
    kernel tier off (MXNET_TPU_KERNELS=0) vs armed (=1).  Returns the
    {probe, before, after, delta} dict the JSONL line carries, or None
    when pallas is unavailable."""
    from mxnet_tpu import kernels as _k
    if not _k.available():
        return None
    saved = _os.environ.get("MXNET_TPU_KERNELS")
    try:
        _os.environ["MXNET_TPU_KERNELS"] = "0"
        before = _hlo_category_bytes(_kernels_probe_step(model))
        _os.environ["MXNET_TPU_KERNELS"] = "1"
        after = _hlo_category_bytes(_kernels_probe_step(model))
    finally:
        if saved is None:
            _os.environ.pop("MXNET_TPU_KERNELS", None)
        else:
            _os.environ["MXNET_TPU_KERNELS"] = saved
    keys = sorted(set(before) | set(after))
    import jax
    interp = jax.default_backend() != "tpu"
    return {
        "probe": ("resnet18v1-nhwc-lars-b2-32x32" if model == "resnet"
                  else "bert_small-flash-b1-seq256"),
        # on a non-TPU backend the 'after' program is the INTERPRET-
        # mode lowering of the kernels (correctness only -- its byte
        # counts are not a perf statement); on TPU it is the real
        # Mosaic program and the deltas are the kernel tier's win
        "after_interpret": interp,
        "before": before,
        "after": after,
        "delta": {k: after.get(k, 0) - before.get(k, 0) for k in keys},
    }


def _kernels_diff_extra(model, est_s=240):
    """extra_fn fields: the kernel-tier HLO diff, budget-gated and
    never fatal to the line that carries it."""
    if _remaining() < est_s:
        return {}
    try:
        diff = _kernels_diff(model)
    except Exception as e:
        return {"kernels_diff_error": str(e)[:120]}
    return {"kernels_diff": diff} if diff else {}


def _cost_extra(tag):
    """extra_fn fields for the emitted JSONL line: artifact path plus
    the top category + its roofline bound, so the line itself says
    where the FLOPs went."""
    path = _COST_ARTIFACTS.get(tag)
    if not path:
        return {}
    try:
        with open(path) as f:
            rep = json.load(f)
        top = max(rep["categories"],
                  key=lambda c: rep["categories"][c]["flops"])
        extra = {"cost_report": path, "hlo_top_category": top}
        rl = rep.get("roofline")
        if rl and top in rl.get("categories", {}):
            extra["top_category_bound"] = rl["categories"][top]["bound"]
        return extra
    except Exception:
        return {"cost_report": path}


def bench_env_health(h2d_mb=64, pingpong=20):
    """Environment-health probe, emitted BEFORE any other compute so
    the H2D number reflects a fresh tunnel (compute degrades later
    transfers on the axon tunnel; docs/perf_resnet50.md).  Lets a 3x
    swing in a dispatch-bound config (r3 LeNet 34.5k -> r4 11.8k) be
    attributed to the environment inside the artifact itself."""
    import jax
    import jax.numpy as jnp
    dev = jax.devices()[0]
    # two-stage probe: a 4 MB scout first -- on a collapsed tunnel
    # (~1 MB/s measured this round) the full probe alone would eat a
    # minute of budget; the big transfer only runs when the scout says
    # the tunnel is fast enough that latency would skew a small sample
    t0 = time.perf_counter()
    y = jax.device_put(np.zeros(1024 * 1024, np.float32), dev)
    float(y[0])                      # value fetch = trustworthy barrier
    scout_dt = time.perf_counter() - t0
    if scout_dt < 0.5:
        buf = np.zeros(h2d_mb * 1024 * 1024 // 4, np.float32)
        t0 = time.perf_counter()
        y = jax.device_put(buf, dev)
        float(y[0])
        h2d_mb_s = h2d_mb / (time.perf_counter() - t0)
    else:
        h2d_mb_s = 4 / scout_dt
    f = jax.jit(lambda v: v + 1.0)
    x = jax.device_put(jnp.zeros(()), dev)
    float(f(x))                      # compile outside the window
    t0 = time.perf_counter()
    for _ in range(pingpong):
        x = f(x)
        float(x)
    lat_us = (time.perf_counter() - t0) / pingpong * 1e6
    return {"h2d_mb_per_s": round(h2d_mb_s, 1),
            "dispatch_roundtrip_us": round(lat_us, 1)}


def _subprocess_value(expr, timeout=600, force_cpu=False):
    """Evaluate ``expr`` (a bench.* call) in a fresh interpreter and
    return its printed float.  ``force_cpu`` keeps the CPU backend out
    of this process (local-dispatch measurements); without it the child
    sees the same accelerator but with a FRESH tunnel -- host->device
    transfers collapse to ~10 MB/s in any process whose TPU has already
    run compute (docs/perf_resnet50.md), so transfer-sensitive configs
    must not share this process."""
    import subprocess
    import sys
    code = ("import sys; sys.path.insert(0, %r); import bench; "
            "print(%s)" % (_os.path.dirname(_os.path.abspath(__file__)),
                           expr))
    env = dict(_os.environ)
    if force_cpu:
        env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=timeout)
    _check_subprocess(out, expr)
    return float(out.stdout.strip().splitlines()[-1])


def _check_subprocess(out, expr):
    """Raise with a stderr tail when a bench subprocess failed, so the
    emitted error line carries the real cause instead of an IndexError
    from parsing empty stdout (ADVICE round-5 low)."""
    if out.returncode == 0:
        return
    tail = "\n".join((out.stderr or "").strip().splitlines()[-12:])
    raise RuntimeError(
        "bench subprocess for %s exited %d; stderr tail:\n%s"
        % (expr, out.returncode, tail or "<empty>"))


def _cpu_subprocess_value(expr, timeout=600):
    return _subprocess_value(expr, timeout=timeout, force_cpu=True)


def _subprocess_json(expr, timeout=600):
    """Like _subprocess_value but for an expr returning a JSON-able
    dict (``print(json.dumps(fn()))``); returns the parsed dict."""
    import subprocess
    import sys
    code = ("import sys, json; sys.path.insert(0, %r); import bench; "
            "print(json.dumps(%s))"
            % (_os.path.dirname(_os.path.abspath(__file__)), expr))
    out = subprocess.run([sys.executable, "-c", code],
                         env=dict(_os.environ), capture_output=True,
                         text=True, timeout=timeout)
    _check_subprocess(out, expr)
    return json.loads(out.stdout.strip().splitlines()[-1])


def _subprocess_pair(expr, timeout=600):
    """Like _subprocess_value but for an expr printing two floats
    (``print(*fn())``); returns them as a (float, float) tuple."""
    import subprocess
    import sys
    code = ("import sys; sys.path.insert(0, %r); import bench; "
            "print(*%s)" % (_os.path.dirname(_os.path.abspath(__file__)),
                            expr))
    out = subprocess.run([sys.executable, "-c", code],
                         env=dict(_os.environ), capture_output=True,
                         text=True, timeout=timeout)
    _check_subprocess(out, expr)
    a, b = out.stdout.strip().splitlines()[-1].split()
    return float(a), float(b)


def _bench_train(net, loss_fn, data_shape, label_shape, n_classes,
                 batch_size, lr=0.05, warmup=5, iters=30, dtype="float32"):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep

    import contextlib
    from mxnet_tpu import amp
    ctx = _ctx()
    net.initialize(ctx=ctx, force_reinit=True)
    net.hybridize()
    # mixed precision: params stay fp32, MXU ops run in the target dtype
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, loss_fn, trainer, mesh=None)
    # synthetic inputs are GENERATED ON-DEVICE (mx.nd.random is
    # jax.random-backed): a host randn + device_put would stage the
    # whole tensor through the tunnel, whose H2D throughput swings by
    # orders of magnitude (env_health line) and has nothing to do with
    # training throughput
    x = mx.nd.random.normal(shape=data_shape, ctx=ctx)
    y = mx.nd.random.randint(0, n_classes, shape=label_shape,
                             ctx=ctx).astype("float32")
    with amp_ctx:
        for _ in range(warmup):
            step(x, y)
        # Synchronize via a scalar host fetch: on the axon tunnel
        # block_until_ready can return before execution finishes, so a
        # value dependency is the only trustworthy barrier.  Steps are
        # chained through the parameters, so fetching the last loss
        # drains the queue.
        float(step(x, y).asscalar())
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            last = step(x, y)
        float(last.asscalar())
        dt = time.perf_counter() - t0
    return batch_size * iters / dt


def _lenet_net(layout="NCHW"):
    from mxnet_tpu import gluon
    net = gluon.nn.HybridSequential()
    # reference LeNet-5 dims (20/50/500) kept verbatim so the bench line
    # stays comparable across rounds; the tile padding they cost is the
    # linter's point, not this net's
    net.add(gluon.nn.Conv2D(20, kernel_size=5, activation="relu",  # mxlint: disable=pad-waste
                            layout=layout),
            gluon.nn.MaxPool2D(2, 2, layout=layout),
            gluon.nn.Conv2D(50, kernel_size=5, activation="relu",  # mxlint: disable=pad-waste
                            layout=layout),
            gluon.nn.MaxPool2D(2, 2, layout=layout),
            gluon.nn.Flatten(),
            gluon.nn.Dense(500, activation="relu"),  # mxlint: disable=pad-waste
            gluon.nn.Dense(10))
    return net


def bench_lenet(batch_size=256):
    from mxnet_tpu import gluon
    net = _lenet_net()
    return _bench_train(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        (batch_size, 1, 28, 28), (batch_size,), 10,
                        batch_size, warmup=5, iters=50)


def bench_lenet_scan(batch_size=256, k=50, reps=3):
    """Config 1 with the compiled K-step loop: the per-step variant's
    throughput tracks the tunnel's dispatch RTT (9.5k-34.5k img/s
    across rounds for identical code); this one is dispatch-independent
    -- K steps per host round-trip -- so it measures the MODEL, not
    the tunnel."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    net = _lenet_net()
    net.initialize(ctx=ctx, force_reinit=True)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer,
                     mesh=None)
    x = mx.nd.random.normal(shape=(k, batch_size, 1, 28, 28), ctx=ctx)
    y = mx.nd.random.randint(0, 10, shape=(k, batch_size),
                             ctx=ctx).astype("float32")
    step.run_steps(x, y)
    float(step.run_steps(x, y).asnumpy()[-1])
    wins = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = step.run_steps(x, y)
        float(out.asnumpy()[-1])
        wins.append(batch_size * k / (time.perf_counter() - t0))
    return statistics.median(wins)


def bench_lenet_imperative(batch_size=256, iters=30):
    """Config 1's stated mode: NON-hybridized eager training -- every op
    call dispatches through the persistent per-op jit cache (SURVEY §7
    hard-part #1).  Measured honestly (r3): with LOCAL dispatch (CPU
    backend, uncontended) the eager loop is ~3.3x slower than the
    hybridized one -- per-op execution forgoes XLA fusion and
    materializes every intermediate, the usual eager/compiled gap; the
    tunneled remote chip pays an extra round-trip per op (~4x).  The
    driver artifact carries both numbers
    (``lenet_imperative_local_dispatch_cpu``)."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    ctx = _ctx()
    net = _lenet_net()
    net.initialize(ctx=ctx, force_reinit=True)   # NOT hybridized
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch_size, 1, 28, 28).astype(np.float32),
                    ctx=ctx)
    y = mx.nd.array(rng.randint(0, 10, (batch_size,)).astype(np.float32),
                    ctx=ctx)

    def step():
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(batch_size)
        return loss

    for _ in range(5):
        step()
    float(step().asscalar())
    t0 = time.perf_counter()
    last = None
    for _ in range(iters):
        last = step()
    float(last.asscalar())
    return batch_size * iters / (time.perf_counter() - t0)


def bench_resnet50(batch_size=128, dtype="float32"):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    net = resnet50_v1()
    return _bench_train(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        (batch_size, 3, 224, 224), (batch_size,), 1000,
                        batch_size, warmup=5, iters=20, dtype=dtype)


def _hbm_sweep_step(batch):
    """One compiled ResNet train step at ``batch`` (ResNet-50 NCHW on
    TPU, the thumbnail ResNet-18 off-TPU so the sweep stays runnable in
    dev); returns the executed TrainStep, whose ``_last_call`` carries
    the (jitted fn, abstract args) pair hbm_plan anchors on."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep
    ctx = _ctx()
    rng = np.random.RandomState(0)
    if mx.num_tpus() > 0:
        from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
        net = resnet50_v1()
        x_shape = (batch, 3, 224, 224)
    else:
        from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
        net = resnet18_v1(classes=10, thumbnail=True, layout="NHWC")
        x_shape = (batch, 32, 32, 3)
    net.initialize(ctx=ctx)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     trainer, mesh=None)
    x = mx.nd.array(rng.rand(*x_shape).astype(np.float32), ctx=ctx)
    y = mx.nd.array(rng.randint(0, 10, (batch,)).astype(np.float32),
                    ctx=ctx)
    step(x, y)
    return step


def bench_batch_hbm_sweep(buckets=None, hbm_budget_bytes=None):
    """ROADMAP item 1's "sweep batch at fixed HBM budget", as an
    instrument (ISSUE 20): fit ``analysis.memory.hbm_plan``'s
    const+per-item peak-HBM line from two anchor compiles of the
    ResNet train step, then for EVERY bucket put the plan's predicted
    peak next to the real compile's measured peak -- the emitted line
    is the planner's accuracy contract, and ``largest_fit_bucket``
    answers the ROADMAP question under the budget (the device's
    reported HBM when it reports one, a 16 GB stand-in otherwise)."""
    import mxnet_tpu as mx
    from mxnet_tpu.analysis import memory as _memory
    on_tpu = mx.num_tpus() > 0
    if buckets is None:
        buckets = (64, 128, 256, 512) if on_tpu else (2, 4, 8)
    buckets = tuple(sorted(int(b) for b in buckets))
    b0 = buckets[0]
    if hbm_budget_bytes is None:
        hbm_budget_bytes = _memory.device_hbm_bytes() or (16 << 30)
    step = _hbm_sweep_step(b0)
    fn, arg_shapes = step._last_call
    plan = _memory.hbm_plan("bench:resnet-hbm-sweep",
                            device_hbm_bytes=int(hbm_budget_bytes),
                            buckets=buckets, batch_size=b0,
                            fn=fn, args=arg_shapes)
    rows = []
    for brec in plan["buckets"]:
        b = brec["batch"]
        measured = _memory.executable_memory(
            fn.lower(*_memory._resize_batch(arg_shapes, b0, b))
            .compile())["peak_hbm_bytes"]
        predicted = brec["predicted_peak_hbm_bytes"]
        rows.append({
            "batch": b,
            "predicted_peak_hbm_bytes": predicted,
            "measured_peak_hbm_bytes": measured,
            "rel_error": (round((predicted - measured) / measured, 4)
                          if measured else None),
            "fits": brec["fits"],
        })
    return {
        "probe": ("resnet50v1-nchw-sgd-224" if on_tpu
                  else "resnet18v1-nhwc-sgd-thumbnail"),
        "hbm_budget_bytes": int(hbm_budget_bytes),
        "const_bytes": plan["const_bytes"],
        "per_item_bytes": plan["per_item_bytes"],
        "buckets": rows,
        "largest_fit_bucket": plan["largest_fit_bucket"],
    }


# v5e bf16 peak; used only to contextualize throughput as MFU
_TPU_PEAK_FLOPS = {"TPU v5 lite": 197e12, "TPU v5e": 197e12,
                   "TPU v5": 459e12, "TPU v4": 275e12}


def _peak_flops():
    import jax
    try:
        kind = jax.devices()[0].device_kind
    except Exception:
        return None
    for k, v in _TPU_PEAK_FLOPS.items():
        if kind.startswith(k):
            return v
    return None


def bench_resnet50_scan(batch_size=256, k=10, dtype="bfloat16", reps=4):
    """ResNet-50 with the compiled multi-step train loop
    (``TrainStep.run_steps``): K full steps per dispatch -- the
    TPU-idiomatic inner loop, no per-step host round-trip.  Returns
    (median img/s, mfu_or_None, per-window img/s list) -- each rep is
    its own measured window so the artifact carries dispersion
    (VERDICT r4 #4)."""
    import contextlib
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    net = resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer,
                     mesh=None)
    # on-device synthetic data: staging (k, 256, 3, 224, 224) fp32
    # through a degraded tunnel can cost minutes and measures nothing
    x = mx.nd.random.normal(shape=(k, batch_size, 3, 224, 224), ctx=ctx)
    y = mx.nd.random.randint(0, 1000, shape=(k, batch_size),
                             ctx=ctx).astype("float32")
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    with amp_ctx:
        step.run_steps(x, y)
        float(step.run_steps(x, y).asnumpy()[-1])
        # goodput ledger over the measured reps ONLY (the single-step
        # flop-count compile below would pollute the recompile
        # category); the window's breakdown rides the JSONL line
        ledger, _restore_gp = _goodput_begin()
        wins = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = step.run_steps(x, y)
            float(out.asnumpy()[-1])
            wins.append(batch_size * k / (time.perf_counter() - t0))
        _goodput_end("resnet50_bf16", ledger, _restore_gp,
                     steps=k * reps)
        # single-step program for an honest per-step flop count (the scan
        # program reports its loop body once); slice ON DEVICE -- an
        # asnumpy here would fetch the whole (k, B, ...) tensor
        step(x[0], y[0])
        ca = step.cost_analysis()
    med = statistics.median(wins)
    dt = batch_size / med
    mfu = None
    peak = _peak_flops()
    if ca and ca.get("flops") and peak:
        mfu = round(ca["flops"] / dt / peak, 4)
    if "resnet50_bf16" in _GOODPUT:
        _GOODPUT["resnet50_bf16"]["mfu"] = mfu
    # persist the per-HLO cost accounting of the measured single-step
    # program next to the JSONL line (ISSUE 6 / ROADMAP item 2)
    _persist_cost_report("resnet50_bf16", step, step_time_s=dt,
                         items_per_step=batch_size)
    return med, mfu, [round(w, 1) for w in wins]


def bench_resnet50_lars(batch_size=512, k=10, dtype="bfloat16", reps=3):
    """BASELINE config 5: bf16 AMP + LARS large-batch ResNet-50 --
    the large-batch scaling recipe (layer-wise trust ratios keep SGD
    stable at batch sizes where plain momentum diverges), measured on
    the compiled K-step loop like the headline config.  Returns
    (median img/s, mfu_or_None, per-window img/s list)."""
    import contextlib
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    net = resnet50_v1()
    net.initialize(ctx=ctx)
    net.hybridize()
    # the trace-safe fused LARS (opt.create('lars') is pinned to the
    # in-graph impl by test); skip_list keeps bias/gamma/beta on the
    # plain momentum path as the reference does
    trainer = gluon.Trainer(net.collect_params(), "lars",
                            {"learning_rate": 0.1, "momentum": 0.9,
                             "eta": 0.001}, kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer,
                     mesh=None)
    x = mx.nd.random.normal(shape=(k, batch_size, 3, 224, 224), ctx=ctx)
    y = mx.nd.random.randint(0, 1000, shape=(k, batch_size),
                             ctx=ctx).astype("float32")
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    with amp_ctx:
        step.run_steps(x, y)
        float(step.run_steps(x, y).asnumpy()[-1])
        ledger, _restore_gp = _goodput_begin()
        wins = []
        for _ in range(reps):
            t0 = time.perf_counter()
            out = step.run_steps(x, y)
            float(out.asnumpy()[-1])
            wins.append(batch_size * k / (time.perf_counter() - t0))
        _goodput_end("resnet50_lars_bf16", ledger, _restore_gp,
                     steps=k * reps)
        step(x[0], y[0])
        ca = step.cost_analysis()
    med = statistics.median(wins)
    dt = batch_size / med
    mfu = None
    peak = _peak_flops()
    if ca and ca.get("flops") and peak:
        mfu = round(ca["flops"] / dt / peak, 4)
    if "resnet50_lars_bf16" in _GOODPUT:
        _GOODPUT["resnet50_lars_bf16"]["mfu"] = mfu
    _persist_cost_report("resnet50_lars_bf16", step, step_time_s=dt,
                         items_per_step=batch_size)
    return med, mfu, [round(w, 1) for w in wins]


def bench_multichip_scaling(device_counts=(1, 2, 4, 8),
                            batch_per_device=32, iters=6, warmup=2,
                            devices=None):
    """Device-count scaling line (ISSUE 9): the SAME convnet trains as
    ONE compiled SPMD program (``parallel.TrainStep``) over a 1/2/4/8
    device ``dp`` mesh at fixed per-device batch; each row reports
    img/s, per-device parallel efficiency vs the 1-device run, and the
    compiled step's IN-GRAPH collective kinds/bytes pulled from the
    sharding sanitizer (``analysis.sharding.collective_profile``) --
    the gradient all-reduce GSPMD inserted, not host kvstore traffic.
    On CPU the virtual devices share one host's cores, so efficiency
    documents the contention floor; on a pod the same line measures the
    ICI. Returns the list of row dicts."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.analysis.sharding import collective_profile
    from mxnet_tpu.parallel import TrainStep, make_mesh, shard_batch
    import jax

    devices = list(devices if devices is not None else jax.devices())
    rng = np.random.RandomState(0)
    rows, base_img_s = [], None
    for n in device_counts:
        if n > len(devices):
            rows.append({"n_devices": n,
                         "skipped": "only %d devices" % len(devices)})
            continue
        mesh = make_mesh({"dp": n}, devices=devices[:n])
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1,
                                activation="relu", layout="NCHW"),
                gluon.nn.MaxPool2D(2, layout="NCHW"),
                gluon.nn.Flatten(),
                gluon.nn.Dense(32, activation="relu"),
                gluon.nn.Dense(10))
        net.initialize(ctx=mx.cpu(), force_reinit=True)
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9},
                                kvstore=None)
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         trainer, mesh=mesh)
        batch = batch_per_device * n
        x = shard_batch(rng.randn(batch, 3, 16, 16).astype(np.float32),
                        mesh)
        y = shard_batch(rng.randint(0, 10, batch).astype(np.float32),
                        mesh)
        for _ in range(warmup):
            step(x, y)
        float(np.asarray(step(x, y)._data))     # drain before the window
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            last = step(x, y)
        float(np.asarray(last._data))
        dt = time.perf_counter() - t0
        img_s = batch * iters / dt
        fn, args = step._last_call
        prof = collective_profile(fn.lower(*args).compile().as_text())
        row = {"n_devices": n,
               "img_per_s": round(img_s, 1),
               "per_device_img_per_s": round(img_s / n, 1),
               "collectives": prof,
               "collective_bytes": sum(rec["bytes"]
                                       for rec in prof.values())}
        if base_img_s is None:
            base_img_s = img_s / n
            row["efficiency"] = 1.0
        else:
            row["efficiency"] = round(img_s / n / base_img_s, 3)
        rows.append(row)
    return rows


def _multichip_scaling_rows(device_counts=(1, 2, 4, 8), timeout=600):
    """Run the scaling sweep in a fresh CPU subprocess with enough
    virtual host devices (the calling process may own a single real
    chip; the sweep needs a 1..8-device ladder and must not disturb
    this process's backend)."""
    import re
    import subprocess
    import sys
    n_max = max(device_counts)
    env = dict(_os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                   env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (flags +
                        " --xla_force_host_platform_device_count=%d"
                        % n_max).strip()
    code = ("import sys, json; sys.path.insert(0, %r); import bench; "
            "print(json.dumps(bench.bench_multichip_scaling(%r)))"
            % (_os.path.dirname(_os.path.abspath(__file__)),
               tuple(device_counts)))
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=timeout)
    if out.returncode != 0:
        raise RuntimeError(out.stderr[-500:])
    return json.loads(out.stdout.strip().splitlines()[-1])


def bench_serving(offered_qps=(100, 400, 1600), duration_s=2.0,
                  clients=8, buckets=(1, 2, 4, 8, 16), max_wait_ms=3.0):
    """Serving-tier latency-vs-QPS curve (ISSUE 8 bench contract).

    A LeNet servable behind the PRODUCT serving path
    (``mx.serving.ModelRegistry``: AOT per-bucket executables + dynamic
    batcher) takes open-loop traffic from ``clients`` threads at each
    offered rate for ``duration_s``; per level the curve records
    achieved QPS, p50/p95/p99 latency, mean batch occupancy (from the
    ``serving.*`` telemetry counters), and shed count -- the knee where
    p99 lifts off IS the capacity number a capacity planner needs.
    """
    import threading
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    net = _lenet_net()
    net.initialize(force_reinit=True)
    net.hybridize()
    x0 = mx.nd.array(np.zeros((1, 1, 28, 28), np.float32))
    net(x0)
    reg = mx.serving.ModelRegistry(compile_cache=False)
    servable = reg.register("lenet", block=net, input_shape=(1, 28, 28),
                            buckets=buckets, max_wait_ms=max_wait_ms,
                            max_queue=1024)
    was_enabled = telemetry.enabled()
    telemetry.enable()
    sample = np.random.RandomState(0) \
        .rand(1, 28, 28).astype(np.float32)
    curve = []
    try:
        for rate in offered_qps:
            telemetry.reset("serving.")
            latencies = []          # list.append is GIL-atomic
            shed = [0]
            interval = clients / float(rate)

            def client():
                t_end = time.perf_counter() + duration_s
                while time.perf_counter() < t_end:
                    t0 = time.perf_counter()
                    try:
                        servable.infer(sample, timeout=2.0)
                        latencies.append(time.perf_counter() - t0)
                    except Exception:
                        shed[0] += 1
                    pace = interval - (time.perf_counter() - t0)
                    if pace > 0:
                        # open-loop rate pacing, not state polling: the
                        # sleep IS the offered-QPS control variable
                        time.sleep(pace)  # mxlint: disable=sleep-poll

            threads = [threading.Thread(target=client, daemon=True)
                       for _ in range(clients)]
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t_start
            lats = sorted(latencies)

            def pct(q):
                return round(1e3 * lats[min(len(lats) - 1,
                                            int(q * len(lats)))], 3) \
                    if lats else None
            batches = telemetry.counter("serving.batches").value
            responses = telemetry.counter("serving.responses").value
            curve.append({
                "offered_qps": rate,
                "qps": round(len(lats) / wall, 1) if wall > 0 else None,
                "p50_ms": pct(0.50), "p95_ms": pct(0.95),
                "p99_ms": pct(0.99),
                "mean_occupancy": round(responses / batches, 3)
                if batches else None,
                "shed": shed[0] + telemetry.counter("serving.shed").value,
            })
    finally:
        reg.shutdown(drain=True)
        if not was_enabled:
            telemetry.disable()
    return curve


def bench_serving_hotswap(duration_s=2.0, clients=4, buckets=(1, 2, 4, 8),
                          max_wait_ms=3.0, publish_every=2):
    """Hot-swap cost under live traffic (ISSUE 12 bench contract).

    A servable behind the PRODUCT always-on loop
    (``serving.ContinuousTrainer`` publishing atomic checkpoints +
    ``serving.RegistryWatcher`` re-registering the servable) takes
    open-loop traffic from ``clients`` threads; mid-run the trainer
    publishes a newer step and the watcher hot-swaps it in
    (warm-compile the replacement while the old one serves, install,
    drain).  Recorded: the swap wall (checkpoint-visible -> new step
    serving), p50/p99 split into during-swap vs steady windows (a
    request is "during" when its lifetime overlaps the swap), and the
    zero-dropped contract (``dropped`` must be 0 -- registry-path
    clients never see the swap).  Runs on CPU.
    """
    import shutil
    import tempfile
    import threading
    import mxnet_tpu as mx
    from mxnet_tpu.chaos import scenarios as _scen
    from mxnet_tpu.serving.loop import ContinuousTrainer, RegistryWatcher

    root = tempfile.mkdtemp(prefix="mxtpu_hotswap_bench_")
    reg = None
    try:
        net, trainer, loss_fn, data = _scen.train_fixtures(seed=0)
        ct = ContinuousTrainer(net, trainer, loss_fn, data, root,
                               publish_every=publish_every)
        reg = mx.serving.ModelRegistry(compile_cache=False)
        watcher = RegistryWatcher(reg, "model", ct.manager,
                                  _scen.make_mlp(), input_shape=(8,),
                                  buckets=buckets, swap_retries=0,
                                  max_wait_ms=max_wait_ms,
                                  max_queue=1024)
        ct.run_steps(publish_every)
        watcher.poll_once()                  # initial servable
        records = []          # (t_submit, latency); append is GIL-atomic
        dropped = [0]
        stop = threading.Event()
        sample = np.random.RandomState(0).rand(8).astype(np.float32)

        def client():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    reg.infer("model", sample, timeout=10)
                    records.append((t0, time.perf_counter() - t0))
                except Exception:
                    dropped[0] += 1
                # open-loop pacing, not state polling
                time.sleep(0.001)  # mxlint: disable=sleep-poll

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(clients)]
        for t in threads:
            t.start()
        time.sleep(duration_s * 0.4)         # steady window (old model)
        ct.run_steps(publish_every)          # publish the newer step
        t_swap0 = time.perf_counter()
        swapped = watcher.poll_once()        # restore+warm+install+drain
        t_swap1 = time.perf_counter()
        time.sleep(duration_s * 0.4)         # steady window (new model)
        stop.set()
        for t in threads:
            t.join()
        ct.close()
        watcher.close()
        reg.shutdown(drain=True)
        reg = None
        during = [lat for (t0, lat) in records
                  if t0 <= t_swap1 and t0 + lat >= t_swap0]
        steady = [lat for (t0, lat) in records
                  if not (t0 <= t_swap1 and t0 + lat >= t_swap0)]

        def pct(lats, q):
            lats = sorted(lats)
            return round(1e3 * lats[min(len(lats) - 1,
                                        int(q * len(lats)))], 3) \
                if lats else None

        return {
            "swap_step": swapped,
            "swap_latency_ms": round(1e3 * (t_swap1 - t_swap0), 3),
            "p50_steady_ms": pct(steady, 0.50),
            "p99_steady_ms": pct(steady, 0.99),
            "p50_during_swap_ms": pct(during, 0.50),
            "p99_during_swap_ms": pct(during, 0.99),
            "requests": len(records) + dropped[0],
            "requests_during_swap": len(during),
            "dropped": dropped[0],
        }
    finally:
        if reg is not None:
            reg.shutdown(drain=True)
        shutil.rmtree(root, ignore_errors=True)


def bench_serving_decode(duration_s=2.0, clients=4, max_new=24,
                         decode_buckets=(1, 2, 4, 8),
                         prefill_buckets=(8, 16)):
    """Generative serving throughput + token-latency tail (ISSUE 18
    bench contract).

    A tiny GPT behind the PRODUCT generative path
    (``ModelRegistry.register_generative`` + ``generate()``: bucketed
    prefill/decode AOT executables, paged KV cache, continuous
    batching) takes closed-loop streaming traffic from ``clients``
    threads for ``duration_s``.  Recorded: decoded tokens/s, TTFT
    p50/p99 (submit -> first token, through the product stream), and
    inter-token p50/p99 across all streams -- the two numbers a
    generative SLO is written against -- plus mean step occupancy
    (tokens/steps from the ``decode.*`` counters) and the shed count.
    Runs on CPU.
    """
    import threading
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry
    from mxnet_tpu.serving.decode import tiny_gpt

    model = tiny_gpt(vocab_size=64, units=32, num_layers=2,
                     num_heads=2, max_seq=64)
    params = model.init_params(0)
    reg = mx.serving.ModelRegistry(compile_cache=False)
    reg.register_generative("gpt", model, params=params,
                            prefill_buckets=prefill_buckets,
                            decode_buckets=decode_buckets,
                            block_size=8, num_blocks=256,
                            max_queue=64)
    was_enabled = telemetry.enabled()
    telemetry.enable()
    telemetry.reset("decode.")
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 64, size=n)) for n in (3, 5, 8, 12)]
    ttfts, gaps = [], []      # list.append is GIL-atomic
    tokens = [0]
    shed = [0]
    try:
        stop = time.perf_counter() + duration_s

        def client(tid):
            i = 0
            while time.perf_counter() < stop:
                t0 = time.perf_counter()
                prev = None
                try:
                    stream = reg.generate(
                        "gpt", prompts[(tid + i) % len(prompts)],
                        max_new, timeout=30)
                    for _tok in stream:
                        now = time.perf_counter()
                        if prev is None:
                            ttfts.append(now - t0)
                        else:
                            gaps.append(now - prev)
                        prev = now
                        tokens[0] += 1
                except Exception:
                    shed[0] += 1
                i += 1

        threads = [threading.Thread(target=client, args=(t,),
                                    daemon=True)
                   for t in range(clients)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t_start

        def pct(lats, q):
            lats = sorted(lats)
            return round(1e3 * lats[min(len(lats) - 1,
                                        int(q * len(lats)))], 3) \
                if lats else None

        steps = telemetry.counter("decode.steps").value
        decoded = telemetry.counter("decode.tokens").value
        return {
            "tokens_per_s": round(tokens[0] / wall, 1)
            if wall > 0 else None,
            "streams": len(ttfts),
            "ttft_p50_ms": pct(ttfts, 0.50),
            "ttft_p99_ms": pct(ttfts, 0.99),
            "inter_token_p50_ms": pct(gaps, 0.50),
            "inter_token_p99_ms": pct(gaps, 0.99),
            "mean_occupancy": round(decoded / steps, 3)
            if steps else None,
            "shed": shed[0],
        }
    finally:
        reg.shutdown(drain=True)
        if not was_enabled:
            telemetry.disable()


def bench_bert_base(batch_size=16, seq_len=128, vocab=30522,
                    dtype="float32", use_flash=None, iters=20,
                    windows=1):
    """BERT-base masked-LM pretraining step (config 3).
    Returns (median tokens/s, mfu_or_None, per-window tokens/s list);
    ``windows`` splits ``iters`` into that many separately-synced
    measurement windows for dispersion (VERDICT r4 #4)."""
    import contextlib
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    mx.random.seed(0)
    net = gluon.model_zoo.bert_base(vocab_size=vocab, max_length=seq_len,
                                    dropout=0.0, use_flash=use_flash)
    net.initialize(ctx=ctx)
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class MLMLoss(gluon.HybridBlock):
        def hybrid_forward(self, F, outs, labels):
            mlm, _nsp = outs
            return ce(mlm.reshape((-1, vocab)), labels.reshape((-1,)))

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-4}, kvstore=None)
    step = TrainStep(net, MLMLoss(), trainer, mesh=None)
    # on-device synthetic tokens (see bench_resnet50_scan's comment)
    ids = mx.nd.random.randint(0, vocab, shape=(batch_size, seq_len),
                               ctx=ctx).astype("float32")
    labels = mx.nd.random.randint(0, vocab, shape=(batch_size, seq_len),
                                  ctx=ctx).astype("float32")
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    with amp_ctx:
        for _ in range(5):
            step(ids, labels)
        float(step(ids, labels).asscalar())
        per_win = max(1, iters // max(1, windows))
        wins = []
        for _ in range(max(1, windows)):
            t0 = time.perf_counter()
            last = None
            for _ in range(per_win):
                last = step(ids, labels)
            float(last.asscalar())
            wins.append(batch_size * seq_len * per_win
                        / (time.perf_counter() - t0))
        ca = step.cost_analysis()
    med = statistics.median(wins)
    mfu = None
    peak = _peak_flops()
    if ca and ca.get("flops") and peak:
        mfu = round(ca["flops"] * med / (batch_size * seq_len) / peak, 4)
    _persist_cost_report("bert_base_seq%d_%s" % (seq_len, dtype), step,
                         step_time_s=batch_size * seq_len / med,
                         items_per_step=batch_size * seq_len)
    return med, mfu, [round(w, 1) for w in wins]


def _build_rec(path, n, fmt="jpg", hw=256, crop=224, seed=0):
    """Synthetic .rec dataset for the pipeline benchmarks.

    Images are natural-like (low-frequency content + mild noise), not
    uniform noise: noise JPEGs are pathological for the entropy coder
    (~2x the decode cost of a photo), which would understate pipeline
    throughput."""
    import mxnet_tpu as mx
    from mxnet_tpu import recordio
    from mxnet_tpu.image.image import _resize_np
    rng = np.random.RandomState(seed)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    for i in range(n):
        base = rng.randint(0, 255, (16, 16, 3), dtype=np.uint8)
        img = _resize_np(base, hw, hw).astype(np.int16)
        img += rng.randint(-8, 9, img.shape, dtype=np.int16)
        img = np.clip(img, 0, 255).astype(np.uint8)
        header = recordio.IRHeader(0, float(i % 1000), i, 0)
        if fmt == "raw":
            rec.write_idx(i, recordio.pack(
                header, img[:crop, :crop].tobytes()))
        else:
            rec.write_idx(i, recordio.pack_img(header, img, quality=90))
    rec.close()
    return path + ".rec"


def _pipeline_epoch_rate(rec, batch_size, dtype, epochs=3, **iter_kw):
    from mxnet_tpu.image import ImageIter
    it = ImageIter(batch_size, (3, 224, 224), path_imgrec=rec,
                   dtype=dtype, **iter_kw)
    try:
        count = 0
        t0 = time.perf_counter()
        for _ in range(epochs):
            it.reset()
            try:
                while True:
                    d, _l, _pad = it.next_np()
                    count += d.shape[0]
            except StopIteration:
                pass
        return count / (time.perf_counter() - t0)
    finally:
        it.close()


def bench_pipeline(n=512, batch_size=64, threads=2):
    """Input pipeline host throughput (reference bar:
    ``iter_image_recordio_2.cc`` threaded decode).  Returns
    (jpeg_img_per_s, raw_uint8_img_per_s, scaling) where ``scaling``
    maps worker configs (threads=N / procs=N) to jpeg img/s -- the
    measured scaling table.  Numbers are per-host; this box has one
    core, so the process-pool rows document the contention floor rather
    than the multi-core ceiling."""
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="mxtpu_bench_rec_")
    try:
        rec_jpg = _build_rec(_os.path.join(tmp, "jpg"), n, "jpg")
        rec_raw = _build_rec(_os.path.join(tmp, "raw"), n, "raw")
        scaling = {}
        for label, kw in (("threads=1", dict(preprocess_threads=0)),
                          ("threads=2", dict(preprocess_threads=2)),
                          ("threads=4", dict(preprocess_threads=4)),
                          ("procs=2", dict(preprocess_procs=2)),
                          ("procs=4", dict(preprocess_procs=4))):
            scaling[label] = round(_pipeline_epoch_rate(
                rec_jpg, batch_size, "float32", **kw), 1)
        jpeg = max(scaling.values())
        raw = _pipeline_epoch_rate(rec_raw, batch_size, "uint8",
                                   preprocess_threads=threads)
        return jpeg, raw, scaling
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_resnet50_e2e(batch_size=256, n_images=2048, dtype="bfloat16",
                       epochs=4, feed_depth=2):
    """End-to-end ResNet-50 training fed by the REAL input pipeline
    (raw-record uint8 decode through ImageIter), not synthetic tensors.

    The staging now runs on the LIBRARY path (ISSUE 4):
    ``mxnet_tpu.dataio.DeviceFeed`` wraps the uint8 ImageIter -- a
    background producer issues async ``jax.device_put`` through a
    bounded double buffer while the compiled train step consumes the
    previous batch, and the feed's jitted ``DeviceTransform`` casts
    uint8 -> compute dtype after landing (reference:
    ``iter_prefetcher.h``).  Epoch 0 streams decode -> stage -> train;
    the compact staged batches (``DeviceBatch.raw``) are retained on
    device, so later epochs are pure compute.  The timed window covers
    everything from the first decoded record to the last step's sync.

    Returns ``(img/s, staging_overlap_frac, goodput)`` where the
    overlap fraction -- the share of producer (decode+transfer) time
    hidden behind training compute, ``1 - consumer_wait /
    producer_busy`` -- is computed from the library's ``feed.*``
    telemetry instruments (docs/observability.md), not bench-local
    accounting, and ``goodput`` is the StepLedger's per-category wall
    attribution + bottleneck verdict over the timed window (ISSUE 14:
    the e2e-vs-synthetic gap is auto-attributed -- an input-bound
    verdict here names decode/transfer with numbers instead of a
    hand-read of feed counters).  The axon tunnel's H2D throughput
    swings by orders of magnitude (see the env_health line /
    docs/perf_resnet50.md); when transfers dominate, the breakdown
    plus the health probe make the bottleneck attributable in the
    artifact itself.
    """
    import contextlib
    import shutil
    import tempfile
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon, telemetry
    from mxnet_tpu.dataio import DeviceFeed, DeviceTransform
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.image import ImageIter
    from mxnet_tpu.parallel import TrainStep

    import jax.numpy as jnp
    ctx = _ctx()
    compute_dtype = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32

    tmp = tempfile.mkdtemp(prefix="mxtpu_bench_e2e_")
    # EVERY constructor (.rec build, net compile warmup, ImageIter,
    # DeviceFeed) runs inside the try: a failure surfaces immediately
    # with the tmp dir removed and telemetry state restored, instead of
    # leaking state or -- in the pre-ISSUE-4 producer-thread shape of
    # this bench -- deadlocking the consumer until the subprocess
    # timeout (ADVICE round-5 medium)
    it = None
    feed = None
    was_enabled = telemetry.enabled()
    try:
        rec = _build_rec(_os.path.join(tmp, "train"), n_images, "raw")

        # compile the train step BEFORE the timed window (on zeros) so
        # the stream measures steady-state training, not compilation
        net = resnet50_v1()
        net.initialize(ctx=ctx)
        net.hybridize()
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9},
                                kvstore=None)
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         trainer, mesh=None)
        amp_ctx = amp.scope(dtype) if dtype != "float32" \
            else contextlib.nullcontext()

        it = ImageIter(batch_size, (3, 224, 224), path_imgrec=rec,
                       preprocess_threads=0, dtype="uint8")
        telemetry.enable()             # source of the overlap fraction
        telemetry.reset("feed.")
        feed = DeviceFeed(it, ctx=ctx, depth=feed_depth,
                          transform=DeviceTransform(dtype=dtype))
        with amp_ctx:
            zx = mx.nd.NDArray(jnp.zeros((batch_size, 3, 224, 224),
                                         jnp.uint8).astype(compute_dtype))
            zy = mx.nd.NDArray(jnp.zeros((batch_size,), jnp.float32))
            for _ in range(3):
                step(zx, zy)
            float(step(zx, zy).asscalar())

            # goodput ledger over the timed window (decode -> stage ->
            # train): the breakdown rides the e2e JSONL line
            ledger, _restore_gp = _goodput_begin()
            if ledger is not None:
                ledger.flops_per_step = \
                    lambda: (step.cost_analysis() or {}).get("flops")
            count = 0
            last = None
            staged = []
            t_start = time.perf_counter()
            for batch in feed:            # epoch 0: streaming
                last = step(batch)
                count += batch_size
                # retain the COMPACT (uint8) staged arrays, not the
                # float expansion -- 4x less HBM
                staged.append((batch.raw[0], batch.label))
            for _ in range(epochs - 1):   # staged epochs: pure compute
                for raw, y in staged:
                    x = mx.nd.NDArray(feed.apply_transform(raw))
                    last = step(x, y)
                    count += batch_size
            float(last.asscalar())
            dt = time.perf_counter() - t_start
            goodput = _goodput_end("resnet50_e2e", ledger, _restore_gp,
                                   steps=count // batch_size)
        busy = telemetry.timer("feed.producer_busy").sum
        wait = telemetry.timer("feed.consumer_wait").sum
        overlap = max(0.0, 1.0 - wait / busy) if busy > 0 else 0.0
    finally:
        if feed is not None:
            feed.close()
        if it is not None:
            it.close()
        if not was_enabled:
            telemetry.disable()
        shutil.rmtree(tmp, ignore_errors=True)
    return count / dt, round(overlap, 3), goodput


def _e2e_line(batch_size, dtype="bfloat16", **kw):
    """The dict the e2e subprocess prints as JSON (rate + overlap +
    goodput breakdown ride one line back to the parent)."""
    rate, overlap, goodput = bench_resnet50_e2e(batch_size,
                                                dtype=dtype, **kw)
    return {"img_per_s": round(rate, 1),
            "staging_overlap_frac": overlap,
            "goodput": goodput}



def _print_line(rec):
    """Emit one JSONL record carrying the degraded-environment flag
    (bench-hygiene contract: no emitted measurement without it)."""
    rec.setdefault("degraded_env", _ENV_DEGRADED["flag"])
    print(json.dumps(rec))

def _emit_with_retry(metric, fn, attempts=2, unit="tokens/s",
                     extra=None, extra_fn=None):
    """Run fn() with retries (the tunneled compile service can drop a
    connection mid-build); emit one JSON line either way, keyed by the
    SAME metric name on success and failure.  ``extra_fn`` is called
    after a successful run for fields computed during it."""
    for attempt in range(attempts):
        try:
            val = fn()
            rec = {"metric": metric, "value": round(val, 1), "unit": unit,
                   "vs_baseline": None,
                   "degraded_env": _ENV_DEGRADED["flag"]}
            if extra:
                rec.update(extra)
            if extra_fn is not None:
                rec.update(extra_fn())
            print(json.dumps(rec))
            return val
        except Exception as e:
            if attempt == attempts - 1:
                print(json.dumps({"metric": metric,
                                  "error": str(e)[:200],
                                  "degraded_env": _ENV_DEGRADED["flag"]}))
            else:
                time.sleep(5)
    return None


def main():
    """Emission order is the contract (VERDICT r4 #1): environment
    health first (must precede any compute for a fresh-tunnel H2D
    reading), then the HEADLINE metrics -- ResNet bf16-scan + MFU,
    BERT bf16 + MFU, and the final vs_baseline line -- then the
    budget-gated garnish (LeNet, fp32, pipeline, e2e, seq sweep).  A
    driver timeout can only ever cost the garnish."""
    import mxnet_tpu as mx
    on_tpu = mx.num_tpus() > 0
    # CPU fallback keeps the harness runnable in dev; shrink the work.
    if on_tpu:
        lenet_bs, rn_bs = 256, 128
    else:
        lenet_bs, rn_bs = 64, 8

    # -- 0: environment health (fresh process, before any compute) ----
    try:
        health = bench_env_health(h2d_mb=64 if on_tpu else 8)
        health.update({"metric": "env_health", "budget_s": _BUDGET_S,
                       "degraded_env": _mark_env_health(health)})
        print(json.dumps(health))
    except Exception as e:
        print(json.dumps({"metric": "env_health", "error": str(e)[:200],
                          "degraded_env": None}))

    # -- 1: headline ResNet (compiled K-step loop, bf16, dispersion) --
    rn_scan = None
    rn_out = {}

    def _run_scan():
        med, mfu, wins = bench_resnet50_scan(
            rn_bs * 2 if on_tpu else rn_bs, k=10 if on_tpu else 2,
            dtype="bfloat16" if on_tpu else "float32",
            reps=4 if on_tpu else 2)
        rn_out["mfu"], rn_out["wins"] = mfu, wins
        return med
    rn_scan = _emit_with_retry(
        "resnet50_imagenet_train_bf16_scan", _run_scan, attempts=2,
        unit="img/s",
        extra_fn=lambda: {"mfu": rn_out.get("mfu"),
                          "min": min(rn_out.get("wins") or [0]),
                          "max": max(rn_out.get("wins") or [0]),
                          "windows": rn_out.get("wins"),
                          **_cost_extra("resnet50_bf16"),
                          **_goodput_extra("resnet50_bf16"),
                          **_kernels_diff_extra("resnet")})

    # -- 2: headline BERT (bs=256 is the single-chip knee, r4) --------
    def _emit_bert(metric, bs, seq, dt_name, iters, windows=1,
                   attempts=2, kernels_probe=False):
        out = {}

        def run():
            tok, mfu, wins = bench_bert_base(bs, seq, dtype=dt_name,
                                             iters=iters,
                                             windows=windows)
            out["mfu"], out["wins"] = mfu, wins
            return tok

        def extra():
            rec = {"mfu": out.get("mfu"), "seq_len": seq,
                   "batch_size": bs,
                   **_cost_extra("bert_base_seq%d_%s" % (seq, dt_name))}
            if windows > 1:
                rec.update({"min": min(out["wins"]),
                            "max": max(out["wins"]),
                            "windows": out["wins"]})
            if kernels_probe:
                rec.update(_kernels_diff_extra("bert"))
            return rec
        return _emit_with_retry(metric, run, attempts=attempts,
                                extra_fn=extra)

    if on_tpu:
        _emit_bert("bert_base_pretrain_bfloat16", 256, 128,
                   "bfloat16", 12, windows=3)
    else:
        _emit_bert("bert_base_pretrain_float32", 2, 32, "float32", 3)

    # -- 3: the final vs_baseline line, emitted BEFORE any garnish ----
    # BASELINE.md anchor: MXNet-CUDA A100 ResNet-50 ~3000 img/s (AMP+DALI)
    headline = rn_scan
    if headline is None:
        # scan path failed twice: fall back to the per-step program so
        # the headline line still carries a real number
        try:
            headline = bench_resnet50(rn_bs * 2 if on_tpu else rn_bs,
                                      dtype="bfloat16")
        except Exception:
            headline = None
    baseline = 3000.0
    print(json.dumps({"metric": "resnet50_imagenet_train",
                      "value": round(headline, 1) if headline else None,
                      "unit": "img/s",
                      "vs_baseline": round(headline / baseline, 4)
                      if headline else None,
                      "degraded_env": _ENV_DEGRADED["flag"]}))

    # -- garnish (budget-gated; order = value per second) -------------
    # BASELINE config 5: bf16 AMP + LARS large-batch (the last named
    # BASELINE config without a bench line)
    if _budget_ok("resnet50_imagenet_train_bf16_lars_largebatch", 300):
        lars_out = {}

        def _run_lars():
            med, mfu, wins = bench_resnet50_lars(
                512 if on_tpu else rn_bs, k=10 if on_tpu else 2,
                dtype="bfloat16" if on_tpu else "float32",
                reps=3 if on_tpu else 1)
            lars_out["mfu"], lars_out["wins"] = mfu, wins
            return med
        _emit_with_retry(
            "resnet50_imagenet_train_bf16_lars_largebatch", _run_lars,
            attempts=1, unit="img/s",
            extra={"batch_size": 512 if on_tpu else rn_bs,
                   "optimizer": "lars"},
            extra_fn=lambda: {"mfu": lars_out.get("mfu"),
                              "windows": lars_out.get("wins"),
                              **_cost_extra("resnet50_lars_bf16"),
                              **_goodput_extra("resnet50_lars_bf16")})

    # MULTICHIP scaling line (ISSUE 9 bench contract): 1/2/4/8-device
    # SPMD train step, per-host efficiency + in-graph collective bytes
    if _budget_ok("multichip_scaling", 240):
        try:
            rows = _multichip_scaling_rows()
            _print_line({"metric": "multichip_scaling",
                         "unit": "img/s", "scaling": rows,
                         "vs_baseline": None})
        except Exception as e:
            _print_line({"metric": "multichip_scaling",
                         "error": str(e)[:200]})

    # batch-at-fixed-HBM sweep (ISSUE 20 bench contract: ROADMAP
    # item 1's sweep, predicted-vs-measured peak HBM per bucket)
    if _budget_ok("batch_hbm_sweep", 180):
        try:
            rec = bench_batch_hbm_sweep()
            _print_line({"metric": "batch_hbm_sweep", "unit": "bytes",
                         "vs_baseline": None, **rec})
        except Exception as e:
            _print_line({"metric": "batch_hbm_sweep",
                         "error": str(e)[:200]})

    # serving tier: latency-vs-QPS curve (ISSUE 8 bench contract)
    if _budget_ok("serving_latency_qps", 120):
        try:
            curve = bench_serving(
                offered_qps=(100, 400, 1600) if on_tpu else (50, 200),
                duration_s=2.0 if on_tpu else 1.0,
                clients=8 if on_tpu else 4)
            _print_line({"metric": "serving_latency_qps",
                         "curve": curve, "unit": "qps/ms",
                         "vs_baseline": None})
        except Exception as e:
            _print_line({"metric": "serving_latency_qps",
                         "error": str(e)[:200]})

    # always-on loop: hot-swap cost under live traffic (ISSUE 12 bench
    # contract: swap latency + p99-during-swap, zero dropped)
    if _budget_ok("serving_hotswap", 90):
        try:
            rec = bench_serving_hotswap(
                duration_s=3.0 if on_tpu else 2.0)
            _print_line({"metric": "serving_hotswap", "unit": "ms",
                         "vs_baseline": None, **rec})
        except Exception as e:
            _print_line({"metric": "serving_hotswap",
                         "error": str(e)[:200]})

    # generative tier: tokens/s + TTFT + inter-token tail through the
    # PRODUCT decode path (ISSUE 18 bench contract)
    if _budget_ok("serving_decode", 90):
        try:
            rec = bench_serving_decode(
                duration_s=3.0 if on_tpu else 2.0)
            _print_line({"metric": "serving_decode",
                         "unit": "tokens/s", "vs_baseline": None,
                         **rec})
        except Exception as e:
            _print_line({"metric": "serving_decode",
                         "error": str(e)[:200]})

    if _budget_ok("lenet_mnist_train", 120):
        _emit_with_retry("lenet_mnist_train",
                         lambda: bench_lenet(lenet_bs), attempts=1,
                         unit="img/s")

    if _budget_ok("lenet_mnist_train_scan", 120):
        _emit_with_retry(
            "lenet_mnist_train_scan",
            lambda: bench_lenet_scan(lenet_bs, k=50 if on_tpu else 4,
                                     reps=3 if on_tpu else 1),
            attempts=1, unit="img/s")

    if _budget_ok("lenet_mnist_train_imperative", 120):
        _emit_with_retry(
            "lenet_mnist_train_imperative",
            lambda: bench_lenet_imperative(lenet_bs,
                                           iters=30 if on_tpu else 5),
            attempts=1, unit="img/s")

    if on_tpu and _budget_ok("lenet_imperative_local_dispatch_cpu", 180):
        # Evidence for the dispatch-gap claim: the same imperative loop
        # with LOCAL dispatch (CPU backend, no tunnel RTT per op).  Run in
        # subprocesses so the CPU backend can't disturb this process.
        try:
            val = _cpu_subprocess_value(
                "bench.bench_lenet_imperative(64, iters=20)")
            val2 = _cpu_subprocess_value("bench.bench_lenet(64)")
            _print_line({"metric":
                         "lenet_imperative_local_dispatch_cpu",
                         "value": round(val, 1), "unit": "img/s",
                         "vs_baseline": None,
                         "hybridized_local_cpu": round(val2, 1),
                         "imperative_over_hybridized":
                         round(val / val2, 3)})
        except Exception as e:
            _print_line({"metric": "lenet_imperative_local_dispatch",
                         "error": str(e)[:200]})

    if _budget_ok("resnet50_imagenet_train_fp32", 180):
        _emit_with_retry("resnet50_imagenet_train_fp32",
                         lambda: bench_resnet50(rn_bs), attempts=1,
                         unit="img/s")

    if _budget_ok("pipeline", 240):
        try:
            jpeg_ips, raw_ips, scaling = bench_pipeline(
                n=512 if on_tpu else 128, threads=2)
            _print_line({"metric": "pipeline_jpeg_decode",
                         "value": round(jpeg_ips, 1),
                         "unit": "img/s/host",
                         "host_cores": _os.cpu_count(),
                         "scaling": scaling,
                         "vs_baseline": None})
            _print_line({"metric": "pipeline_raw_uint8",
                         "value": round(raw_ips, 1),
                         "unit": "img/s/host",
                         "host_cores": _os.cpu_count(),
                         "vs_baseline": None})
        except Exception as e:
            _print_line({"metric": "pipeline", "error": str(e)[:200]})

    if on_tpu and _budget_ok("resnet50_imagenet_train_e2e_bf16", 600):
        try:
            # fresh subprocess: the dataset staging transfer must happen
            # before any compute touches this process's tunnel.  The
            # child prints rate + overlap + the goodput breakdown as
            # one JSON object, so the e2e-vs-synthetic gap arrives
            # auto-attributed (ISSUE 14).
            rec = _subprocess_json(
                "bench._e2e_line(%d, dtype='bfloat16')" % (rn_bs * 2),
                timeout=max(300, min(900, int(_remaining()) - 60)))
            _print_line({"metric": "resnet50_imagenet_train_e2e_bf16",
                         "value": rec["img_per_s"], "unit": "img/s",
                         "staging_overlap_frac":
                         rec["staging_overlap_frac"],
                         "goodput": rec.get("goodput"),
                         "vs_baseline": None})
        except Exception as e:
            _print_line({"metric": "resnet50_imagenet_train_e2e_bf16",
                         "error": str(e)[:200]})

    if on_tpu:
        # seq sweep: captures the XLA/Pallas crossover in the artifact
        # (auto path: seq 128 -> plain XLA attention, seq >= 256 ->
        # Pallas flash kernels)
        if _budget_ok("bert_base_pretrain_seq512_bf16", 300):
            _emit_bert("bert_base_pretrain_seq512_bf16", 64, 512,
                       "bfloat16", 10, attempts=1)
        if _budget_ok("bert_base_pretrain_seq1024_bf16_flash", 600):
            # long-context config: seq 1024 is where the Pallas flash
            # fwd+bwd kernels pull away from XLA (81k vs 60k tok/s, r3);
            # the line carries the kernel-tier before/after HLO diff
            _emit_bert("bert_base_pretrain_seq1024_bf16_flash", 16,
                       1024, "bfloat16", 10, attempts=1,
                       kernels_probe=True)

    print(json.dumps({"metric": "bench_complete",
                      "elapsed_s": round(time.monotonic() - _T_START, 1),
                      "budget_s": _BUDGET_S}))


if __name__ == "__main__":
    main()
