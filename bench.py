"""Benchmark harness (driver contract + BASELINE.md configs).

Measures steady-state training throughput on the available accelerator
(the one real TPU chip under the driver; CPU otherwise):

- config 1: LeNet-style convnet, MNIST shapes, hybridized Gluon
- config 2: ResNet-50 v1, synthetic ImageNet batches (the headline)

Each config times the FULL training step (forward + loss + backward +
optimizer update) as one compiled program (``mxnet_tpu.parallel.TrainStep``)
with device-resident synthetic data, after warmup.  Reference analog:
``example/image-classification/common/fit.py :: Speedometer`` samples/sec.

Prints one progress JSON object per config, then the final parseable line:
``{"metric": ..., "value": ..., "unit": ..., "vs_baseline": ...}``.
vs_baseline denominator: BASELINE.md's A100 anchor for MXNet-CUDA
ResNet-50 (~3000 img/s with DALI+AMP; unverified memory anchor).
"""
import json
import time

import numpy as np


def _ctx():
    import mxnet_tpu as mx
    return mx.tpu() if mx.num_tpus() else mx.cpu()


def _bench_train(net, loss_fn, data_shape, label_shape, n_classes,
                 batch_size, lr=0.05, warmup=5, iters=30, dtype="float32"):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep

    import contextlib
    from mxnet_tpu import amp
    ctx = _ctx()
    net.initialize(ctx=ctx, force_reinit=True)
    net.hybridize()
    # mixed precision: params stay fp32, MXU ops run in the target dtype
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, loss_fn, trainer, mesh=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(*data_shape).astype(np.float32), ctx=ctx)
    y = mx.nd.array(
        rng.randint(0, n_classes, size=label_shape).astype(np.float32),
        ctx=ctx)
    with amp_ctx:
        for _ in range(warmup):
            step(x, y)
        # Synchronize via a scalar host fetch: on the axon tunnel
        # block_until_ready can return before execution finishes, so a
        # value dependency is the only trustworthy barrier.  Steps are
        # chained through the parameters, so fetching the last loss
        # drains the queue.
        float(step(x, y).asscalar())
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            last = step(x, y)
        float(last.asscalar())
        dt = time.perf_counter() - t0
    return batch_size * iters / dt


def _lenet_net():
    from mxnet_tpu import gluon
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(20, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(50, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(500, activation="relu"),
            gluon.nn.Dense(10))
    return net


def bench_lenet(batch_size=256):
    from mxnet_tpu import gluon
    net = _lenet_net()
    return _bench_train(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        (batch_size, 1, 28, 28), (batch_size,), 10,
                        batch_size, warmup=5, iters=50)


def bench_lenet_imperative(batch_size=256, iters=30):
    """Config 1's stated mode: NON-hybridized eager training -- every op
    call dispatches through the persistent per-op jit cache (SURVEY §7
    hard-part #1).  The gap to the hybridized number is dispatch
    overhead; measured with LOCAL dispatch (CPU backend) imperative is
    within 2x of (and can beat) hybridized, while the tunneled remote
    chip adds a network round-trip per op call, so the on-axon ratio
    (~10x) reflects the tunnel, not the dispatcher."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    ctx = _ctx()
    net = _lenet_net()
    net.initialize(ctx=ctx, force_reinit=True)   # NOT hybridized
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(batch_size, 1, 28, 28).astype(np.float32),
                    ctx=ctx)
    y = mx.nd.array(rng.randint(0, 10, (batch_size,)).astype(np.float32),
                    ctx=ctx)

    def step():
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(batch_size)
        return loss

    for _ in range(5):
        step()
    float(step().asscalar())
    t0 = time.perf_counter()
    last = None
    for _ in range(iters):
        last = step()
    float(last.asscalar())
    return batch_size * iters / (time.perf_counter() - t0)


def bench_resnet50(batch_size=128, dtype="float32"):
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    net = resnet50_v1()
    return _bench_train(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        (batch_size, 3, 224, 224), (batch_size,), 1000,
                        batch_size, warmup=5, iters=20, dtype=dtype)


def bench_bert_base(batch_size=16, seq_len=128, vocab=30522,
                    dtype="float32", use_flash=True, iters=20):
    """BERT-base masked-LM pretraining step, tokens/s (config 3)."""
    import contextlib
    import mxnet_tpu as mx
    from mxnet_tpu import amp, gluon
    from mxnet_tpu.parallel import TrainStep

    ctx = _ctx()
    mx.random.seed(0)
    net = gluon.model_zoo.bert_base(vocab_size=vocab, max_length=seq_len,
                                    dropout=0.0, use_flash=use_flash)
    net.initialize(ctx=ctx)
    net.hybridize()
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    class MLMLoss(gluon.HybridBlock):
        def hybrid_forward(self, F, outs, labels):
            mlm, _nsp = outs
            return ce(mlm.reshape((-1, vocab)), labels.reshape((-1,)))

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-4}, kvstore=None)
    step = TrainStep(net, MLMLoss(), trainer, mesh=None)
    rng = np.random.RandomState(0)
    ids = mx.nd.array(
        rng.randint(0, vocab, (batch_size, seq_len)).astype(np.float32),
        ctx=ctx)
    labels = mx.nd.array(
        rng.randint(0, vocab, (batch_size, seq_len)).astype(np.float32),
        ctx=ctx)
    amp_ctx = amp.scope(dtype) if dtype != "float32" \
        else contextlib.nullcontext()
    with amp_ctx:
        for _ in range(5):
            step(ids, labels)
        float(step(ids, labels).asscalar())
        t0 = time.perf_counter()
        last = None
        for _ in range(iters):
            last = step(ids, labels)
        float(last.asscalar())
        dt = time.perf_counter() - t0
    return batch_size * seq_len * iters / dt


def main():
    import mxnet_tpu as mx
    results = {}
    on_tpu = mx.num_tpus() > 0
    # CPU fallback keeps the harness runnable in dev; shrink the work.
    if on_tpu:
        lenet_bs, rn_bs, = 256, 128
    else:
        lenet_bs, rn_bs = 64, 8

    lenet = bench_lenet(lenet_bs)
    results["lenet_mnist_train"] = lenet
    print(json.dumps({"metric": "lenet_mnist_train", "value": round(lenet, 1),
                      "unit": "img/s", "vs_baseline": None}))

    try:
        lenet_imp = bench_lenet_imperative(lenet_bs,
                                           iters=30 if on_tpu else 5)
        results["lenet_mnist_train_imperative"] = lenet_imp
        print(json.dumps({"metric": "lenet_mnist_train_imperative",
                          "value": round(lenet_imp, 1), "unit": "img/s",
                          "vs_baseline": None}))
    except Exception as e:
        print(json.dumps({"metric": "lenet_mnist_train_imperative",
                          "error": str(e)[:200]}))

    rn = bench_resnet50(rn_bs)
    results["resnet50_train_fp32"] = rn
    print(json.dumps({"metric": "resnet50_imagenet_train_fp32",
                      "value": round(rn, 1), "unit": "img/s",
                      "vs_baseline": None}))

    headline = rn
    try:
        # bf16 halves activation memory: double the batch for MXU util
        rn_bf16 = bench_resnet50(rn_bs * 2 if on_tpu else rn_bs,
                                 dtype="bfloat16")
        results["resnet50_train_bf16"] = rn_bf16
        print(json.dumps({"metric": "resnet50_imagenet_train_bf16",
                          "value": round(rn_bf16, 1), "unit": "img/s",
                          "vs_baseline": None}))
        headline = max(headline, rn_bf16)
    except Exception as e:  # bf16 path optional until AMP lands fully
        print(json.dumps({"metric": "resnet50_imagenet_train_bf16",
                          "error": str(e)[:200]}))

    # bs=128 is the single-chip throughput knee (measured: 38k tok/s at
    # bs16 -> 116k at bs128, flat beyond)
    bert_bs = 128 if on_tpu else 2
    bert_seq = 128 if on_tpu else 32
    bert_iters = 20 if on_tpu else 3
    for dt_name in (("bfloat16",) if on_tpu else ("float32",)):
        # the tunneled compile service can drop a connection mid-build;
        # retry a couple of times before reporting failure
        for attempt in range(3):
            try:
                tok = bench_bert_base(bert_bs, bert_seq, dtype=dt_name,
                                      iters=bert_iters)
                results["bert_base_%s" % dt_name] = tok
                print(json.dumps(
                    {"metric": "bert_base_pretrain_%s" % dt_name,
                     "value": round(tok, 1), "unit": "tokens/s",
                     "vs_baseline": None}))
                break
            except Exception as e:
                if attempt == 2:
                    print(json.dumps({"metric": "bert_base_pretrain",
                                      "error": str(e)[:200]}))
                else:
                    time.sleep(5)

    # BASELINE.md anchor: MXNet-CUDA A100 ResNet-50 ~3000 img/s (AMP+DALI)
    baseline = 3000.0
    print(json.dumps({"metric": "resnet50_imagenet_train",
                      "value": round(headline, 1), "unit": "img/s",
                      "vs_baseline": round(headline / baseline, 4)}))


if __name__ == "__main__":
    main()
