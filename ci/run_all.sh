#!/usr/bin/env bash
# Single CI entry point (reference: ci/docker/runtime_functions.sh --
# the one script that gates a change).  Stages:
#   lint  -> compile-level sanity over the whole package
#   suite -> full pytest run (8 virtual CPU devices, same as a PR gate)
#   examples -> the runnable examples smoke-tested via their test file
#   telemetry -> 3-step smoke train (fed through mx.dataio.DeviceFeed)
#                with the JSONL sink on, then the summarize CLI must
#                report non-empty step/compile/feed data
#   checkpoint -> save-every-step smoke train, simulated preemption
#                 (kill-mid-write corruption of the newest step),
#                 resume must fall back to the previous good step and
#                 the telemetry JSONL must record the restore event
#   tsan -> threaded smoke train + the threaded test files under
#           MXNET_TPU_TSAN=1 (lock-order sanitizer + deadlock watchdog
#           armed), including the injected-deadlock fixtures
#   profiling -> 3-step smoke train with cost accounting on; mxprof
#                report must show non-empty step + category sections
#                and mxprof diff of the run against itself must report
#                zero drift (the regression-attribution contract)
#   serving -> register a LeNet servable, fire concurrent requests
#              from threads; gates: mean batch occupancy > 1 (dynamic
#              batching is real), zero dropped responses after a
#              graceful drain, per-request numerics vs the direct
#              forward, and a non-empty `serving` section (ordered
#              p50<=p99 percentiles) from the summarize CLI
#   chaos -> the always-on loop under injected faults (docs/chaos.md,
#            fixed seed): the chaos test file, then a REAL
#            kill-mid-commit (subprocess dies with os._exit between the
#            staged data files and the manifest commit -> discovery
#            must cost one step, never the job, and the next manager
#            sweeps the orphaned staging dir), a torn-publish hot-swap
#            scenario (watcher must quarantine the corrupt step and
#            keep serving the previous verified one, zero dropped
#            requests), and a batcher flood (sheds counted, accepted
#            requests all complete, tail bounded by the queue depth)
#   chaos_dist -> distributed resilience gate (docs/chaos.md multi-host
#                 section, seed 0): a REAL 2-proc supervised run where
#                 rank 1 is chaos-KILLed between the "written" and
#                 "committed" barriers of a sharded publish -- the
#                 survivor must abort with a typed BarrierTimeout
#                 naming rank 1 within the bound, NO merged manifest
#                 may exist, the elastic supervisor must relaunch
#                 generation 1, and both ranks must resume parameters
#                 BIT-IDENTICAL to the last verified step; plus the
#                 restart-budget exhaustion path gated NOT_READY
#   spmd -> one-program multi-host gate (docs/distributed.md): a REAL
#           2-process gloo smoke train through tools/launch.py -- the
#           dist train step must be ONE compiled SPMD program whose
#           steady-state steps run under transfer_guard("disallow"),
#           kv push/pull byte counters must stay ZERO across steps
#           (kvstore is a veneer; gradients all-reduce in-graph), and
#           rank 0's collective contract must match the committed
#           ci/sharding_baseline.json (the gradient all-reduce is
#           blessed; anything else fails naming executable+kind)
#   perflint -> TPU performance linter gates (docs/perf_lint.md): the
#               full-tree static pass with all five perf rules armed
#               (layout-hostile-conv, pad-waste, python-loop-unroll,
#               scalar-recompile, eager-in-step-loop), then a LeNet
#               TrainStep + ResNet18-thumbnail forward smoke whose
#               compiled-HLO efficiency audit (transpose share,
#               unfused elementwise bytes, MXU pad waste, intensity)
#               must show zero drift against the committed
#               ci/perf_baseline.json (mxlint --perf-diff)
#   shardlint -> sharding sanitizer gates (docs/sharding.md): the
#                full-tree static pass (mesh axes, shard_map arity,
#                donation audit, implicit reshard), then a LeNet
#                TrainStep smoke over an 8-way dp mesh whose GSPMD
#                collectives must match the committed
#                ci/sharding_baseline.json exactly (an unblessed
#                all-gather fails naming the executable and kind),
#                with the steady-state steps run under
#                transfer_guard("disallow") and a seeded implicit
#                host transfer proven to raise
#   numlint -> numerics sanitizer gates (docs/numerics.md): the
#              full-tree static pass (five dtype-hazard rules armed),
#              then a LeNet TrainStep + bf16-ResNet18 TrainStep smoke
#              under MXNET_TPU_NUMERICS_CHECK=1 -- two clean sentinel
#              steps, then a chaos-seeded NaN at step 3 must raise
#              NonFiniteError naming a real parameter -- whose
#              compiled-HLO precision audit (half-accumulated dots,
#              convert storms, bf16 reductions) must show zero drift
#              against the committed ci/numerics_baseline.json
#              (mxlint --numerics-diff)
#   memlint -> memory-pressure sanitizer gates (docs/memory.md): the
#              full-tree static pass (five HBM-hazard rules armed:
#              device-ref-accumulation, unbounded-shape-cache,
#              host-materialize-large, retained-temp-across-step,
#              feed-depth-unbounded), then a LeNet TrainStep smoke
#              whose peak-HBM audit must show zero drift against the
#              committed ci/memory_baseline.json (mxlint
#              --memory-diff), a SEEDED +50% peak regression that must
#              exit 1, an hbm_plan anchor check (predicted == compiled
#              at both probe buckets), and the leak-sentinel gate
#              under MXNET_TPU_MEMORY_WATCH=1 (seed 0): clean windows
#              must never flag, chaos-pinned arrays must flag within
#              3 windows naming the pinned shape bucket
#   kernels -> Pallas kernel tier gates (docs/kernels.md): the
#              interpret-mode kernel tests (registry policy, fused
#              BN+ReLU numerics+vjp, flash op-level pallas path incl.
#              the masked backward, bucket-flattened LARS/LAMB), an
#              explicit fallback proof (Pallas monkeypatched away ->
#              every choice lands on XLA, numerics intact), then a
#              kernels-armed smoke train (NHWC BN+ReLU fusion sites +
#              bucketed LARS through one compiled TrainStep, kernels
#              in interpret mode on CPU) whose perf audit must show
#              zero drift against the blessed train_step:KernelSmokeNet
#              row of ci/perf_baseline.json (mxlint --perf-diff)
#   obs -> observability ops plane (docs/observability.md): a traced
#          smoke train+serve run whose request spans must reconcile
#          with the serving.requests/batches counters and whose
#          dispatch+device_get span walls must equal the
#          serving.dispatch_time timer; a chaos KILL mid-commit
#          (seed 0) with the flight recorder installed -- the process
#          dies 137 and the blackbox dump's final events must name the
#          injected fault and the in-flight trace; a /healthz flip
#          gate -- READY while the watcher is good, NOT_READY after
#          the swap failure budget suspends it; and the goodput gate
#          -- a ContinuousTrainer fed through a DeviceFeed with a
#          chaos sleep injected on feed.produce must close windows
#          whose reconciliation (categories sum to wall within tol)
#          holds on EVERY window, read input-bound, and emit a
#          goodput.regression event NAMING input_wait
#   fleet -> fleet observability gate (docs/observability.md fleet
#            section, seed 0): a REAL 2-replica supervised serving
#            fleet discovered through MXNET_TPU_OBS_ENDPOINTS_DIR;
#            rank 1 is chaos-KILLed mid-flood (serving.dispatch) --
#            the FleetMonitor's replica_down alert must FIRE naming
#            rank 1 + generation 0, the supervisor relaunch must
#            RESOLVE it, every replica that drains reports zero
#            accepted-request drops, and the `mxtelemetry fleet` CLI
#            exit codes gate both ways (0 on the healthy relaunched
#            fleet, 1 once the endpoints are withdrawn)
#   bench -> bench.py import + dry entry (no device time burned)
#   wheel -> build a wheel, install into a clean venv, import + smoke
#
# Usage: ci/run_all.sh [stage...]   (default: all stages in order)
set -euo pipefail
cd "$(dirname "$0")/.."

stages=("$@")
[ ${#stages[@]} -eq 0 ] && stages=(lint suite examples telemetry checkpoint tsan profiling perflint shardlint numlint memlint kernels spmd serving serving_decode chaos chaos_dist obs fleet bench wheel)

log() { printf '\n== %s ==\n' "$1"; }

run_lint() {
    log "lint: byte-compile every source file"
    python -m compileall -q mxnet_tpu tools benchmark bench.py \
        __graft_entry__.py
    log "lint: incremental pass (changed files vs committed baseline)"
    # the pre-commit-speed path: only `git diff` files are linted and
    # findings recorded in the committed baseline stay suppressed, so
    # this stage stays fast as the rule count grows (docs/analysis.md)
    python -m mxnet_tpu.analysis --changed \
        --baseline ci/lint_baseline.json --json
    log "lint: mxnet_tpu.analysis full self-check (trace safety + concurrency + retrace audit)"
    # the authoritative gate, same pass developers run as `mxlint
    # --self` -- CI and the CLI cannot drift; exits non-zero on any
    # violation, --json keeps the record machine-readable
    python -m mxnet_tpu.analysis --self --json
}

run_suite() {
    log "suite: full pytest"
    python -m pytest tests/ -q
}

run_examples() {
    log "examples: smoke via tests/test_examples.py"
    python -m pytest tests/test_examples.py -q
}

run_telemetry() {
    log "telemetry: 3-step smoke train -> JSONL -> summarize gate"
    tjsonl=$(mktemp /tmp/mxtpu_telemetry_ci.XXXXXX.jsonl)
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 \
        MXNET_TPU_TELEMETRY_JSONL="$tjsonl" python - <<'EOF'
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry

net = gluon.nn.Dense(4)
net.initialize()
net.hybridize()
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1})
ds = gluon.data.ArrayDataset(
    mx.nd.array(np.random.rand(12, 8).astype(np.float32)),
    mx.nd.array(np.random.rand(12, 4).astype(np.float32)))
# the device-feed path (ISSUE 4): batches stage through
# mx.dataio.DeviceFeed, so the summarize gate below can assert a
# non-empty feed section alongside the host-loader instruments
loader = gluon.data.DataLoader(ds, batch_size=4, ctx=mx.cpu())
loss_fn = gluon.loss.L2Loss()
for x, y in loader:                     # 3 steps
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(4)
loss.asnumpy()
telemetry.flush()
print("smoke train done:", telemetry.counter("trainer.steps").value,
      "steps")
EOF
    # the CLI must exit 0 and report non-empty step/compile sections
    python -m mxnet_tpu.telemetry summarize "$tjsonl" --json > "$tjsonl.agg"
    python - "$tjsonl.agg" <<'EOF'
import json, sys
agg = json.load(open(sys.argv[1]))
assert agg["records"] > 0, "empty telemetry log"
assert agg["steps"]["count"] >= 3, agg["steps"]
assert agg["compile"]["count"] > 0, agg["compile"]
assert agg["kvstore"]["bytes"] > 0, agg["kvstore"]
assert agg["data"]["batches"] >= 3, agg["data"]
assert agg["feed"]["batches"] >= 3, agg["feed"]
assert agg["feed"]["bytes_staged"] > 0, agg["feed"]
assert agg["feed"]["producer_busy_s"] is not None, agg["feed"]
print("telemetry gate ok: %d steps, %d compiles, %d kv bytes, "
      "%d fed batches"
      % (agg["steps"]["count"], agg["compile"]["count"],
         agg["kvstore"]["bytes"], agg["feed"]["batches"]))
EOF
    rm -f "$tjsonl" "$tjsonl.agg"
}

run_checkpoint() {
    log "checkpoint: train+save every step -> preempt -> verified resume"
    ckdir=$(mktemp -d /tmp/mxtpu_ckpt_ci.XXXXXX)
    # phase 1: 3 steps, a managed save per step, then a simulated
    # preemption: the newest step's params are truncated (the on-disk
    # state a SIGKILL mid-write leaves) and the process dies abruptly
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 \
        MXNET_TPU_TELEMETRY_JSONL="$ckdir/run.jsonl" \
        python - "$ckdir" <<'EOF'
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

ckdir = sys.argv[1]
mgr = mx.checkpoint.CheckpointManager(os.path.join(ckdir, "ckpts"))
net = gluon.nn.Dense(4)
net.initialize(); net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                   kvstore=None)
loss_fn = gluon.loss.L2Loss()
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(4, 8).astype(np.float32))
y = mx.nd.array(rng.rand(4, 4).astype(np.float32))
for step in range(1, 4):                  # 3 steps, save EVERY step
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    tr.step(4)
    mgr.save_training(step, net, tr, metadata={"step": step})
assert mgr.latest_step() == 3
# simulated preemption: SIGKILL lands mid-write of a 4th checkpoint --
# fake the torn on-disk state by truncating the newest step's params
with open(os.path.join(mgr.step_dir(3), "params.params"), "r+b") as f:
    f.truncate(8)
print("phase-1 trained 3 steps, tore step 3", flush=True)
os._exit(0)                               # abrupt exit: no atexit, no flush
EOF
    # phase 2: fresh process resumes; must fall back to step 2
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 \
        MXNET_TPU_TELEMETRY_JSONL="$ckdir/run.jsonl" \
        python - "$ckdir" <<'EOF'
import os, sys, warnings
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry

ckdir = sys.argv[1]
mgr = mx.checkpoint.CheckpointManager(os.path.join(ckdir, "ckpts"))
net = gluon.nn.Dense(4)
net.initialize(); net.hybridize()
x = mx.nd.array(np.zeros((4, 8), np.float32))
net(x)
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                   kvstore=None)
with warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)   # the torn step 3
    ckpt = mgr.restore_training(net, tr)
assert ckpt is not None, "resume found no checkpoint"
assert ckpt.step == 2, "expected fallback to step 2, got %r" % ckpt.step
assert ckpt.metadata["step"] == 2
# step continuity: training resumes at the step after the checkpoint
y = mx.nd.array(np.zeros((4, 4), np.float32))
loss_fn = gluon.loss.L2Loss()
for step in range(ckpt.step + 1, ckpt.step + 3):
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    tr.step(4)
    mgr.save_training(step, net, tr, metadata={"step": step})
assert mgr.latest_step() == 4
telemetry.flush()
print("phase-2 resumed at step %d, continued to %d"
      % (ckpt.step, mgr.latest_step()), flush=True)
EOF
    # gate: the shared JSONL must record the restore event
    python - "$ckdir/run.jsonl" <<'EOF'
import json, sys
actions = []
for line in open(sys.argv[1]):
    rec = json.loads(line)
    if rec.get("kind") == "event" and rec.get("name") == "checkpoint":
        actions.append((rec.get("payload") or {}).get("action"))
assert "restore" in actions, "no restore event in telemetry: %s" % actions
# phase 1's buffered lines died with os._exit (as they would under a
# real SIGKILL); phase 2's post-resume saves must be here
assert actions.count("save") >= 2, actions
print("checkpoint gate ok: %d saves, %d restores recorded"
      % (actions.count("save"), actions.count("restore")))
EOF
    rm -rf "$ckdir"
}

run_tsan() {
    log "tsan: threaded smoke train under the concurrency sanitizer"
    # same shape as the telemetry smoke train, but with the lock-order
    # sanitizer + deadlock watchdog armed: a silent A/B inversion or a
    # stuck producer raises here instead of hanging a real run
    JAX_PLATFORMS=cpu MXNET_TPU_TSAN=1 MXNET_TPU_TSAN_WATCHDOG_S=60 \
        MXNET_TPU_TELEMETRY=1 python - <<'EOF'
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, sync, telemetry

assert sync.tsan_enabled(), "MXNET_TPU_TSAN=1 did not arm the sanitizer"
seeded = sync.seed_static_order()
net = gluon.nn.Dense(4)
net.initialize()
net.hybridize()
trainer = gluon.Trainer(net.collect_params(), "sgd",
                        {"learning_rate": 0.1})
ds = gluon.data.ArrayDataset(
    mx.nd.array(np.random.rand(16, 8).astype(np.float32)),
    mx.nd.array(np.random.rand(16, 4).astype(np.float32)))
# threaded end to end: worker-pool decode + DeviceFeed staging
loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=2,
                               ctx=mx.cpu())
loss_fn = gluon.loss.L2Loss()
for x, y in loader:                     # 4 steps
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(4)
loss.asnumpy()
assert not sync.recorded_reports(), sync.recorded_reports()
print("tsan smoke train ok: %d steps, %d static edges seeded, "
      "order graph %r"
      % (telemetry.counter("trainer.steps").value, seeded,
         sync.order_graph()))
EOF
    log "tsan: threaded test files under MXNET_TPU_TSAN=1"
    # the tier-1 threaded suites must stay green with the sanitizer
    # armed, and tests/test_sync.py carries the injected-deadlock
    # fixture the watchdog must catch with a both-stacks report
    JAX_PLATFORMS=cpu MXNET_TPU_TSAN=1 MXNET_TPU_TSAN_WATCHDOG_S=60 \
        python -m pytest tests/test_sync.py tests/test_dataio.py \
        tests/test_checkpoint.py tests/test_telemetry.py \
        tests/test_serving.py tests/test_chaos.py tests/test_obs.py \
        tests/test_resilience.py tests/test_numerics.py \
        tests/test_memory.py tests/test_fleet.py \
        -q -m 'not slow'
    log "tsan: gloo multi-process tests under MXNET_TPU_TSAN=1"
    # the launched workers inherit the env, so the 2-/4-proc gloo SPMD
    # paths (ISSUE 9) run with the lock sanitizer armed end to end
    JAX_PLATFORMS=cpu MXNET_TPU_TSAN=1 MXNET_TPU_TSAN_WATCHDOG_S=120 \
        python -m pytest tests/test_distributed.py -q -k "gloo or spmd"
}

run_profiling() {
    log "profiling: smoke train with cost accounting -> mxprof gates"
    pdir=$(mktemp -d /tmp/mxtpu_prof_ci.XXXXXX)
    JAX_PLATFORMS=cpu MXNET_TPU_PROFILING=1 MXNET_TPU_TELEMETRY=1 \
        MXNET_TPU_PROFILING_DIR="$pdir" python - <<'EOF'
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, profiling
from mxnet_tpu.parallel import TrainStep

assert profiling.enabled(), "MXNET_TPU_PROFILING=1 did not arm capture"
net = gluon.nn.Dense(4)
net.initialize(); net.hybridize()
tr = gluon.Trainer(net.collect_params(), "lars",
                   {"learning_rate": 0.1}, kvstore=None)
step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(8, 16).astype(np.float32))
y = mx.nd.array(rng.rand(8, 4).astype(np.float32))
for _ in range(3):                       # 3 steps (trace-safe LARS)
    loss = step(x, y)
loss.asnumpy()
path = profiling.save_reports()
print("profiling smoke train done ->", path)
EOF
    # gate 1: the report must carry non-empty step + category sections
    python -m mxnet_tpu.profiling report --dir "$pdir" --json > "$pdir/agg.json"
    python - "$pdir/agg.json" <<'EOF'
import json, sys
agg = json.load(open(sys.argv[1]))
assert agg["executables"], "no executables in cost report"
assert agg["steps"], "no step section in cost report"
assert any(st.get("count", 0) >= 3 for st in agg["steps"].values()), \
    agg["steps"]
assert sum(v["flops"] for v in agg["categories"].values()) > 0, \
    agg["categories"]
for rep in agg["executables"]:
    tf = rep["totals"]["flops"]
    s = sum(c["flops"] for c in rep["categories"].values())
    assert abs(s - tf) < 1, (rep["label"], s, tf)
    rl = rep.get("roofline")
    if rl:
        for cat, v in rl["categories"].items():
            assert v["bound"] in ("compute", "memory"), (cat, v)
print("profiling gate ok: %d executables, %d step labels, "
      "%.0f total flops"
      % (len(agg["executables"]), len(agg["steps"]),
         sum(v["flops"] for v in agg["categories"].values())))
EOF
    # gate 2: a run diffed against itself must report ZERO drift
    python -m mxnet_tpu.profiling diff "$pdir/report.json" "$pdir/report.json"
    rm -rf "$pdir"
}

run_perflint() {
    log "perflint: full-tree static pass (five perf rules armed)"
    # same framework as the lint stage; running --self here keeps the
    # stage self-contained when invoked alone (ci/run_all.sh perflint)
    python -m mxnet_tpu.analysis --self --json
    log "perflint: compiled-audit zero-drift gate (LeNet TrainStep + ResNet18 forward)"
    pfdir=$(mktemp -d /tmp/mxtpu_perf_ci.XXXXXX)
    JAX_PLATFORMS=cpu MXNET_TPU_PROFILING=1 python - "$pfdir" <<'EOF'
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, profiling
from mxnet_tpu.analysis import perf
from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
from mxnet_tpu.parallel import TrainStep

pfdir = sys.argv[1]
assert profiling.enabled(), "MXNET_TPU_PROFILING=1 did not arm capture"


class PerfLeNet(gluon.nn.HybridSequential):
    """Named so the audit row is stable across CI runs."""


net = PerfLeNet()
net.add(gluon.nn.Conv2D(8, 5, padding=2, activation="relu",
                        layout="NCHW"),
        gluon.nn.MaxPool2D(2, layout="NCHW"),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                   kvstore=None)
step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                 mesh=None)
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(8, 1, 16, 16).astype(np.float32))
y = mx.nd.array(rng.randint(0, 10, (8,)).astype(np.float32))
for _ in range(2):
    loss = step(x, y)
loss.asnumpy()

res = resnet18_v1(classes=10, thumbnail=True)
res.initialize(ctx=mx.cpu())
res.hybridize()
rx = mx.nd.array(rng.rand(2, 3, 32, 32).astype(np.float32))
res(rx).asnumpy()     # first pass runs eagerly (deferred shape init)
res(rx).asnumpy()     # second pass compiles the whole net: hybrid:ResNetV1

audit = perf.save_audit(os.path.join(pfdir, "current.json"))
labels = set(audit["executables"])
assert "train_step:PerfLeNet" in labels, labels
assert "hybrid:ResNetV1" in labels, labels
print("perflint smoke ok: %d executables audited, %d advisories"
      % (len(labels), len(audit["advisories"])))
EOF
    # gate: efficiency metrics vs the committed baseline -- a grown
    # transpose/unfused/pad-waste share or an unblessed advisory exits
    # 1 naming executable + kind; improvements pass
    python -m mxnet_tpu.analysis --perf-diff \
        ci/perf_baseline.json "$pfdir/current.json" --json
    rm -rf "$pfdir"
}

run_numlint() {
    log "numlint: full-tree static pass (five dtype-hazard rules armed)"
    # the numerics rules ride the same framework as the lint stage;
    # running --self here keeps this stage self-contained when invoked
    # alone (ci/run_all.sh numlint)
    python -m mxnet_tpu.analysis --self --json
    log "numlint: sentinel + precision-audit gate (LeNet + bf16 ResNet18 TrainStep)"
    nmdir=$(mktemp -d /tmp/mxtpu_num_ci.XXXXXX)
    JAX_PLATFORMS=cpu MXNET_TPU_PROFILING=1 MXNET_TPU_NUMERICS_CHECK=1 \
        python - "$nmdir" <<'EOF'
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import amp, chaos, gluon, profiling
from mxnet_tpu.analysis import numerics
from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
from mxnet_tpu.parallel import TrainStep

nmdir = sys.argv[1]
assert profiling.enabled(), "MXNET_TPU_PROFILING=1 did not arm capture"
assert numerics.check_enabled(), \
    "MXNET_TPU_NUMERICS_CHECK=1 did not arm the sentinel"
assert mx.runtime.Features().is_enabled("NUMERICS")


class NumLeNet(gluon.nn.HybridSequential):
    """Named so the audit row is stable across CI runs."""


net = NumLeNet()
net.add(gluon.nn.Conv2D(8, 5, padding=2, activation="relu",
                        layout="NCHW"),
        gluon.nn.MaxPool2D(2, layout="NCHW"),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                   kvstore=None)
step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                 mesh=None)
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(8, 1, 16, 16).astype(np.float32))
y = mx.nd.array(rng.randint(0, 10, (8,)).astype(np.float32))

# the detection gate: two clean sentinel-checked steps, then a
# chaos-seeded NaN at step 3 must surface as a typed NonFiniteError
# naming a REAL parameter, caught by the sentinel (the injector only
# poisons the batch; the fault flows through forward/backward)
with chaos.scenario(seed=0):
    chaos.on("numerics.nonfinite", numerics.poison_action, nth=3)
    for _ in range(2):
        loss = step(x, y)
    loss.asnumpy()
    try:
        step(x, y)
        raise SystemExit("chaos NaN at step 3 did not raise NonFiniteError")
    except numerics.NonFiniteError as e:
        pnames = {p.name for p in tr._params}
        assert e.param in pnames, (e.param, pnames)
        assert e.step == 3, e.step
        assert e.kind == "nan", e.kind
        print("sentinel gate ok: NonFiniteError(%s, step=%s, %s)"
              % (e.param, e.step, e.kind))
row = numerics.status_row()
assert row["checks"] >= 3 and row["nonfinite"] == 1 \
    and row["last"]["kind"] == "nan", row

# bf16 half of the audit: the same net shape trained under amp bf16 +
# a bf16 ResNet18 TrainStep give the auditor real half-precision HLO
res = resnet18_v1(classes=10, thumbnail=True)
res.initialize(ctx=mx.cpu())
res.hybridize()
rtr = gluon.Trainer(res.collect_params(), "sgd", {"learning_rate": 0.1},
                    kvstore=None)
rstep = TrainStep(res, gluon.loss.SoftmaxCrossEntropyLoss(), rtr,
                  mesh=None)
rx = mx.nd.array(rng.rand(2, 3, 32, 32).astype(np.float32))
ry = mx.nd.array(rng.randint(0, 10, (2,)).astype(np.float32))
with amp.scope("bfloat16"):
    for _ in range(2):
        rloss = rstep(rx, ry)
rloss.asnumpy()

audit = numerics.save_audit(os.path.join(nmdir, "current.json"))
labels = set(audit["executables"])
assert "train_step:NumLeNet" in labels, labels
assert "train_step:ResNetV1" in labels, labels
print("numlint smoke ok: %d executables audited, %d advisories"
      % (len(labels), len(audit["advisories"])))
EOF
    # gate: precision metrics vs the committed baseline -- a grown
    # half-accum-dot/convert-storm/half-reduce share or an unblessed
    # advisory exits 1 naming executable + kind; improvements pass
    python -m mxnet_tpu.analysis --numerics-diff \
        ci/numerics_baseline.json "$nmdir/current.json" --json
    rm -rf "$nmdir"
}

run_memlint() {
    log "memlint: full-tree static pass (five HBM-hazard rules armed)"
    # the memory rules ride the same framework as the lint stage;
    # running --self here keeps this stage self-contained when invoked
    # alone (ci/run_all.sh memlint)
    python -m mxnet_tpu.analysis --self --json
    log "memlint: peak-HBM audit + hbm_plan + leak-sentinel gate (LeNet TrainStep, seed 0)"
    mmdir=$(mktemp -d /tmp/mxtpu_mem_ci.XXXXXX)
    # PYTHONHASHSEED is pinned: hash ordering feeds the flattened
    # argument order of the train step, and XLA's input-output alias
    # assignment (alias_bytes, hence peak) depends on it -- the
    # committed baseline is blessed under the same seed (docs/memory.md)
    JAX_PLATFORMS=cpu MXNET_TPU_PROFILING=1 MXNET_TPU_MEMORY_WATCH=1 \
        PYTHONHASHSEED=0 python - "$mmdir" <<'EOF'
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import chaos, gluon, profiling
from mxnet_tpu.analysis import memory
from mxnet_tpu.parallel import TrainStep

mmdir = sys.argv[1]
assert profiling.enabled(), "MXNET_TPU_PROFILING=1 did not arm capture"
assert memory.watch_enabled(), \
    "MXNET_TPU_MEMORY_WATCH=1 did not arm the live-buffer watch"
assert mx.runtime.Features().is_enabled("MEMORY_WATCH")


class MemLeNet(gluon.nn.HybridSequential):
    """Named so the audit row is stable across CI runs."""


net = MemLeNet()
net.add(gluon.nn.Conv2D(8, 5, padding=2, activation="relu",
                        layout="NCHW"),
        gluon.nn.MaxPool2D(2, layout="NCHW"),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                   kvstore=None)
step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                 mesh=None)
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(4, 1, 16, 16).astype(np.float32))
y = mx.nd.array(rng.randint(0, 10, (4,)).astype(np.float32))
for _ in range(2):
    loss = step(x, y)
loss.asnumpy()

audit = memory.save_audit(os.path.join(mmdir, "current.json"))
labels = set(audit["executables"])
assert "train_step:MemLeNet" in labels, labels
print("memlint audit ok: %d executables, %d advisories"
      % (len(labels), len(audit["advisories"])))

# hbm_plan anchor gate: the predicted peak at both probe buckets must
# match a real compile -- the extrapolation line is anchored on real
# compiles, so the planner cannot silently drift from the backend
fn, arg_shapes = step._last_call
plan = memory.hbm_plan(
    "train_step:MemLeNet", buckets=(4, 8), batch_size=4,
    fn=fn, args=arg_shapes,
    device_hbm_bytes=memory.device_hbm_bytes() or (16 << 30))
pred = {r["batch"]: r["predicted_peak_hbm_bytes"]
        for r in plan["buckets"]}
for b in (4, 8):
    measured = memory.executable_memory(
        fn.lower(*memory._resize_batch(arg_shapes, 4, b))
        .compile())["peak_hbm_bytes"]
    assert abs(pred[b] - measured) <= 1, (b, pred[b], measured)
print("memlint hbm_plan ok: const %d B + %d B/item, largest fit %s"
      % (plan["const_bytes"], plan["per_item_bytes"],
         plan["largest_fit_bucket"]))

# leak-sentinel gate (seed 0): clean windows must never flag;
# chaos-pinned arrays must flag within 3 windows naming the pinned
# shape bucket (the SENTINEL, not the injector, catches the leak)
sent = memory.sentinel(window_steps=1, min_baseline=3,
                       min_growth_frac=0.01)
chaos.reset()
chaos.on("memory.leak", memory.pin_action)
for i in range(5):                      # disarmed: the point no-ops
    chaos.fail_point("memory.leak", step=i)
    sent.step()
assert memory._STATE["leaks"] == 0, "clean windows flagged a leak"
nbytes = int(memory._STATE["live_bytes"] * 0.3) + (16 << 20)
chaos.arm(seed=0)
flagged_at = None
for i in range(6):
    chaos.fail_point("memory.leak", step=i, nbytes=nbytes)
    sent.step()
    if memory._STATE["leaks"]:
        flagged_at = i
        break
chaos.disarm()
chaos.reset()
assert flagged_at is not None and flagged_at < 3, \
    "chaos-pinned growth not flagged within 3 windows"
leak = memory._STATE["last_leak"]
assert leak["bucket"] == "(%d,)/float32" % max(1, nbytes // 4), leak
print("memlint sentinel ok: leak flagged at window %d naming %s "
      "(+%d B)" % (flagged_at, leak["bucket"], leak["growth_bytes"]))
EOF
    # gate: peak HBM vs the committed baseline -- a grown peak or an
    # unblessed executable/advisory exits 1 naming executable + kind;
    # shrinkage passes
    python -m mxnet_tpu.analysis --memory-diff \
        ci/memory_baseline.json "$mmdir/current.json" --json
    # the gate must also CATCH: a seeded +50% peak regression exits 1
    python - "$mmdir" <<'EOF'
import json, sys
mmdir = sys.argv[1]
with open(mmdir + "/current.json") as f:
    cur = json.load(f)
for row in cur["executables"].values():
    row["metrics"]["peak_hbm_bytes"] = \
        int(row["metrics"]["peak_hbm_bytes"] * 1.5)
with open(mmdir + "/regress.json", "w") as f:
    json.dump(cur, f)
EOF
    if python -m mxnet_tpu.analysis --memory-diff \
        ci/memory_baseline.json "$mmdir/regress.json" --json \
        > /dev/null; then
        echo "memlint: seeded +50% peak-HBM regression was NOT caught"
        exit 1
    fi
    echo "memlint: seeded peak regression caught (exit 1, as gated)"
    rm -rf "$mmdir"
}

run_shardlint() {
    log "shardlint: full-tree sharding pass (mesh axes, shard_map arity, donation, reshard)"
    # the sharding rules ride the same framework as the lint stage;
    # running --self here keeps this stage self-contained when invoked
    # alone (ci/run_all.sh shardlint)
    python -m mxnet_tpu.analysis --self --json
    log "shardlint: collective-contract + transfer-guard gate (LeNet TrainStep over dp mesh)"
    sdir=$(mktemp -d /tmp/mxtpu_shard_ci.XXXXXX)
    JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
        MXNET_TPU_SHARD_CHECK=1 python - "$sdir" <<'EOF'
import os, sys
import numpy as np
import jax
import mxnet_tpu as mx
from mxnet_tpu import gluon, profiling
from mxnet_tpu.analysis import sharding
from mxnet_tpu.parallel import TrainStep, make_mesh

sdir = sys.argv[1]
assert profiling.enabled(), "MXNET_TPU_SHARD_CHECK=1 did not arm capture"
assert mx.runtime.Features().is_enabled("SHARD_CHECK")

mesh = make_mesh({"dp": 8})
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Conv2D(6, 5, padding=2, activation="relu"),
        gluon.nn.MaxPool2D(2),
        gluon.nn.Conv2D(16, 3, activation="relu"),
        gluon.nn.MaxPool2D(2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                   kvstore=None)
step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr, mesh=mesh)
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(16, 1, 16, 16).astype(np.float32))
y = mx.nd.array(rng.randint(0, 10, (16,)).astype(np.float32))
step(x, y)                               # compile + state init, unguarded

# steady-state steps under the transfer guard: the compiled step must
# be free of IMPLICIT host transfers (scalar feeds ride device_put)
with sharding.transfer_guard("disallow"):
    for _ in range(2):
        loss = step(x, y)
    loss._data.block_until_ready()

# and a seeded in-step leak must raise -- the guard is live, not a no-op
try:
    with sharding.transfer_guard("disallow"):
        (loss * 1.5)._data.block_until_ready()   # py scalar -> implicit h2d
except Exception:
    pass
else:
    raise SystemExit("transfer guard did not catch the seeded host transfer")

cur = sharding.save_contract(os.path.join(sdir, "current.json"))
label = "train_step:HybridSequential"
assert label in cur["executables"], cur["executables"].keys()
print("shardlint smoke ok: %s collectives %s"
      % (label, cur["executables"][label]))
EOF
    # gate: the smoke's GSPMD collectives vs the committed baseline --
    # an unblessed kind or a grown count exits 1 naming executable+kind
    python -m mxnet_tpu.analysis --collective-diff \
        ci/sharding_baseline.json "$sdir/current.json" --json
    rm -rf "$sdir"
}

run_spmd() {
    log "spmd: 2-proc gloo one-program smoke train (transfer guard + zero kv bytes)"
    pdir=$(mktemp -d /tmp/mxtpu_spmd_ci.XXXXXX)
    cat > "$pdir/spmd_worker.py" <<'EOF'
import os, sys, re
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = re.sub(
    r"--xla_force_host_platform_device_count=\d+", "",
    os.environ.get("XLA_FLAGS", "")).strip()   # one device per rank
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, telemetry
from mxnet_tpu import distributed as dist
from mxnet_tpu.analysis import sharding
from mxnet_tpu.parallel import TrainStep, global_mesh

outdir = sys.argv[1]
assert mx.distributed_init() is True
assert jax.process_count() == 2, jax.process_count()
nproc, rank = dist.world()


class SpmdSmokeNet(gluon.nn.HybridSequential):
    """Named so the dist executable gets its own blessed baseline row."""


net = SpmdSmokeNet()
net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9},
                   kvstore="dist_sync")
step = TrainStep(net, gluon.loss.L2Loss(), tr)   # auto global mesh
assert step._mesh.shape["dp"] == 2

rng = np.random.RandomState(100 + rank)
w = np.random.RandomState(0).randn(8, 4).astype(np.float32)
x = rng.randn(8, 8).astype(np.float32)           # per-rank LOCAL batch
y = (x @ w).astype(np.float32)
l0 = float(np.asarray(step(x, y)._data))         # compile + init sync
telemetry.reset("kvstore.")
with sharding.transfer_guard("disallow"):        # steady state, guarded
    for _ in range(8):
        loss = step(x, y)
    last = float(np.asarray(loss._data))
assert last < l0, (l0, last)
for verb in ("push", "pull", "pushpull", "bytes"):
    assert telemetry.counter("kvstore." + verb).value == 0, \
        "kv.%s moved host bytes on the hot path" % verb
assert dist._KV_FALLBACK_WARNED[0] is False, "KV fallback latch warm"
if rank == 0:
    cur = sharding.save_contract(os.path.join(outdir, "current.json"))
    kinds = cur["executables"]["train_step:SpmdSmokeNet"]
    assert "all-reduce" in kinds, kinds
dist.barrier("spmd_ci_done")
print("SPMD_CI_OK rank=%d loss %.4f -> %.4f" % (rank, l0, last))
EOF
    JAX_PLATFORMS=cpu MXNET_TPU_SHARD_CHECK=1 MXNET_TPU_TELEMETRY=1 \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python tools/launch.py -n 2 python -u "$pdir/spmd_worker.py" "$pdir"
    log "spmd: collective-baseline diff gate (rank 0's dist executable)"
    # the gradient all-reduce is blessed in ci/sharding_baseline.json;
    # an unblessed kind or a grown count exits 1 naming executable+kind
    python -m mxnet_tpu.analysis --collective-diff \
        ci/sharding_baseline.json "$pdir/current.json" --json
    rm -rf "$pdir"
}

run_serving() {
    log "serving: concurrent-load smoke (dynamic batching + graceful drain)"
    svjsonl=$(mktemp /tmp/mxtpu_serving_ci.XXXXXX.jsonl)
    svcache=$(mktemp -d /tmp/mxtpu_serving_cache.XXXXXX)
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 \
        MXNET_TPU_TELEMETRY_JSONL="$svjsonl" \
        MXNET_TPU_SERVING_CACHE_DIR="$svcache" python - <<'EOF'
import threading
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, telemetry

# a LeNet servable, registered from a Gluon block (buckets warmed at
# registration: no request below pays a first-compile)
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Conv2D(8, kernel_size=5, activation="relu"),
        gluon.nn.MaxPool2D(2, 2),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10))
net.initialize(); net.hybridize()
net(mx.nd.array(np.zeros((1, 1, 28, 28), np.float32)))

reg = mx.serving.ModelRegistry()
s = reg.register("lenet", block=net, input_shape=(1, 28, 28),
                 buckets=(1, 2, 4, 8), max_wait_ms=50, max_queue=256)

# concurrent requests from threads: the dynamic batcher must assemble
# real micro-batches (mean occupancy > 1), and the graceful drain must
# lose NO in-flight response
n_threads, per_thread = 4, 8
results = [[None] * per_thread for _ in range(n_threads)]
barrier = threading.Barrier(n_threads)
sample = np.random.RandomState(0).rand(1, 28, 28).astype(np.float32)

def client(tid):
    barrier.wait()
    futs = [s.submit(sample, timeout=30) for _ in range(per_thread)]
    for i, f in enumerate(futs):
        results[tid][i] = f.result(timeout=30)

threads = [threading.Thread(target=client, args=(t,), daemon=True)
           for t in range(n_threads)]
for t in threads:
    t.start()
for t in threads:
    t.join()
reg.shutdown(drain=True)          # graceful drain

dropped = sum(1 for row in results for r in row if r is None)
assert dropped == 0, "%d responses dropped after graceful drain" % dropped
want = net(mx.nd.array(sample[None])).asnumpy()[0]
for row in results:
    for r in row:
        np.testing.assert_allclose(r, want, rtol=1e-4, atol=1e-4)
batches = telemetry.counter("serving.batches").value
responses = telemetry.counter("serving.responses").value
occ = responses / batches
assert occ > 1, "mean batch occupancy %.2f (no dynamic batching)" % occ
telemetry.flush()
print("serving smoke ok: %d responses in %d batches (occupancy %.2f)"
      % (responses, batches, occ))
EOF
    # gate: the summarize CLI must report a non-empty serving section
    python -m mxnet_tpu.telemetry summarize "$svjsonl" --json > "$svjsonl.agg"
    python - "$svjsonl.agg" <<'EOF'
import json, sys
agg = json.load(open(sys.argv[1]))
sv = agg["serving"]
assert sv["requests"] >= 32, sv
assert sv["responses"] == sv["requests"], sv
assert sv["batches"] > 0 and sv["mean_occupancy"] > 1, sv
assert sv["shed"] == 0 and sv["timeouts"] == 0, sv
assert sv["latency_p50_s"] is not None and sv["latency_p99_s"] is not None, sv
assert sv["latency_p50_s"] <= sv["latency_p99_s"], sv
print("serving gate ok: %d requests, occupancy %.2f, p99 %.1fms"
      % (sv["requests"], sv["mean_occupancy"], 1e3 * sv["latency_p99_s"]))
EOF
    rm -rf "$svjsonl" "$svjsonl.agg" "$svcache"
}

run_serving_decode() {
    log "serving_decode: generative tier smoke (continuous batching + paged KV cache + mid-decode swap)"
    gdcache=$(mktemp -d /tmp/mxtpu_gdec_cache.XXXXXX)
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 \
        MXNET_TPU_SERVING_CACHE_DIR="$gdcache" python - <<'EOF'
import threading
import time

import mxnet_tpu as mx
from mxnet_tpu import chaos, telemetry
from mxnet_tpu.serving.decode import tiny_gpt

model = tiny_gpt(vocab_size=32, units=16, num_layers=2, num_heads=2,
                 max_seq=32)
p0 = model.init_params(0)
reg = mx.serving.ModelRegistry()
reg.register_generative("gpt", model, params=p0,
                        prefill_buckets=(8,), decode_buckets=(1, 2, 4),
                        block_size=4, num_blocks=64, max_queue=16)

# staggered concurrent streams: joins happen at step boundaries of a
# RUNNING batch, and every stream must be bit-identical to the
# single-shot full-forward reference (the numerics oracle).  Decode
# steps are throttled (chaos sleep, seed 0) so the stagger lands every
# later stream INSIDE the running batch deterministically.
prompts = [[3, 7, 1, 9, 2], [5, 5, 6], [1, 2, 3, 4], [9, 8, 7]]
solo = [model.reference_decode(p0, p, 10) for p in prompts]
results = [None] * len(prompts)

def client(i):
    time.sleep(0.01 * i)
    results[i] = list(reg.generate("gpt", prompts[i], 10))

with chaos.scenario(seed=0):
    chaos.on("serving.decode.step", action=lambda ctx: time.sleep(0.02))
    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

dropped = sum(1 for r in results if r is None or len(r) != 10)
assert dropped == 0, "%d streams dropped/truncated" % dropped
for i, r in enumerate(results):
    assert r == solo[i], "stream %d diverged from the oracle" % i
tokens = telemetry.counter("decode.tokens").value
steps = telemetry.counter("decode.steps").value
assert tokens > steps, \
    "no continuous batching: %d tokens in %d steps" % (tokens, steps)
sv = reg.servable("gpt")
assert sv.kvcache_stats()["blocks_in_use"] == 0, sv.kvcache_stats()

# mid-decode hot-swap chaos gate at seed 0: throttled decode steps pin
# a half-generated sequence across the swap; it must drain to
# completion on the OLD weights (zero dropped) while new requests land
# on the new servable
p1 = model.init_params(1)
with chaos.scenario(seed=0):
    chaos.on("serving.decode.step", action=lambda ctx: time.sleep(0.03))
    stream = reg.generate("gpt", [3, 7, 1, 9, 2], 20)
    first = next(stream)
    reg.register_generative("gpt", model, params=p1,
                            prefill_buckets=(8,),
                            decode_buckets=(1, 2, 4), block_size=4,
                            num_blocks=64, max_queue=16)
    drained = [first] + list(stream)
    assert drained == model.reference_decode(p0, [3, 7, 1, 9, 2], 20), \
        "mid-swap sequence diverged from old-weight oracle"
    assert chaos.stats()["survived"].get("serving.decode_swap") == 1, \
        chaos.stats()["survived"]
    fresh = list(reg.generate("gpt", [3, 7, 1], 5))
    assert fresh == model.reference_decode(p1, [3, 7, 1], 5), \
        "post-swap request did not use the new weights"
occ = tokens / steps
reg.shutdown(drain=True)
print("serving_decode gate ok: %d tokens in %d steps (occupancy %.2f), "
      "mid-decode swap drained, 0 dropped" % (tokens, steps, occ))
EOF
    rm -rf "$gdcache"
}

run_chaos() {
    log "chaos: deterministic fault-injection tests (quick tier)"
    JAX_PLATFORMS=cpu python -m pytest tests/test_chaos.py -q -m 'not slow'
    chdir=$(mktemp -d /tmp/mxtpu_chaos_ci.XXXXXX)
    log "chaos: REAL kill-mid-commit (seed 0) -> one-step rollback gate"
    # phase 1: a trainer publishing every step dies SIGKILL-shaped
    # (os._exit 137) between the staged data files and the manifest
    # commit of step 3 -- the staged dir must never become loadable
    set +e
    JAX_PLATFORMS=cpu python - "$chdir" <<'EOF'
import sys
import mxnet_tpu as mx
from mxnet_tpu import chaos
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.serving.loop import ContinuousTrainer

net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
ct = ContinuousTrainer(net, trainer, loss_fn, data,
                       sys.argv[1] + "/ckpts", publish_every=1)
chaos.arm(seed=0)
chaos.on("checkpoint.commit.pre_manifest", nth=3, action=chaos.KILL)
ct.run_steps(3)                         # dies mid-commit of step 3
raise SystemExit("chaos KILL did not fire")
EOF
    rc=$?
    set -e
    [ "$rc" -eq 137 ] || { echo "expected exit 137, got $rc"; exit 1; }
    # phase 2: a fresh process (the restarted job + the serving side)
    # must see step 2 as the newest verified step, sweep the orphaned
    # staging dir, and hot-swap the servable to step 2
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 python - "$chdir" <<'EOF'
import os, sys
import mxnet_tpu as mx
from mxnet_tpu import serving, telemetry
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.serving.loop import RegistryWatcher

root = sys.argv[1] + "/ckpts"
assert any(d.endswith(".tmp") for d in os.listdir(root)), \
    "kill left no staging dir -- the scenario tested nothing"
mgr = mx.checkpoint.CheckpointManager(root)     # init sweeps dead tmps
assert not any(d.endswith(".tmp") for d in os.listdir(root))
assert mgr.latest_step() == 2, mgr.all_steps()
reg = serving.ModelRegistry(compile_cache=False)
watcher = RegistryWatcher(reg, "model", mgr, scenarios.make_mlp(),
                          input_shape=(8,), buckets=(1, 2),
                          max_wait_ms=2)
assert watcher.poll_once() == 2
assert telemetry.counter("serving.swaps").value == 1
import numpy as np
out = reg.infer("model", np.zeros(8, np.float32), timeout=30)
assert out is not None
reg.shutdown(drain=True); watcher.close()
print("kill-mid-commit gate ok: rolled back to step 2, tmp swept, "
      "servable swapped")
EOF
    log "chaos: torn-publish hot-swap scenario (seed 0) -> quarantine + zero dropped"
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 python - "$chdir" <<'EOF'
import sys
from mxnet_tpu import telemetry
from mxnet_tpu.chaos import scenarios

rep = scenarios.hotswap_scenario(sys.argv[1] + "/torn", torn=True,
                                 seed=0)
assert rep["second_swap_step"] is None, rep
assert rep["served_step"] == 2, rep             # the rollback gate
assert rep["quarantined"] == ["step_00000004.corrupt"], rep
assert rep["errors"] == [] and rep["shed"] == 0, rep
assert rep["completed"] == rep["requests"] > 0, rep   # zero dropped
assert rep["completed_after_swap"] >= 1, rep
assert rep["chaos"]["injected"]["checkpoint.commit.post_commit"] == 1
assert telemetry.counter("checkpoint.quarantined").value == 1
assert telemetry.counter("chaos.injected").value == 1
assert telemetry.counter("chaos.survived").value >= 1
print("torn-publish gate ok: quarantined, served step %d, "
      "%d/%d requests completed"
      % (rep["served_step"], rep["completed"], rep["requests"]))
EOF
    log "chaos: batcher flood scenario (seed 0) -> shed counted, tail bounded"
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 python - <<'EOF'
from mxnet_tpu import telemetry
from mxnet_tpu.chaos import scenarios

rep = scenarios.flood_scenario(seed=0, max_queue=4, clients=8,
                               per_client=8, hold_s=0.03)
assert rep["shed"] > 0, "flood did not overflow the bounded queue"
assert rep["errors"] == [], rep["errors"]       # sheds are DISTINCT
assert rep["completed"] + rep["shed"] == rep["requests"], rep
assert rep["completed"] > 0, rep                # in-flight completed
assert rep["max_latency_s"] < rep["latency_bound_s"], rep
assert telemetry.counter("serving.shed").value == rep["shed"]
print("flood gate ok: %d sheds, %d completed, max latency %.0fms "
      "(bound %.0fms)"
      % (rep["shed"], rep["completed"], 1e3 * rep["max_latency_s"],
         1e3 * rep["latency_bound_s"]))
EOF
    log "chaos: distributed resilience tests (typed failures, spec replay, supervisor)"
    JAX_PLATFORMS=cpu python -m pytest tests/test_resilience.py -q \
        -m 'not slow'
    rm -rf "$chdir"
}

run_chaos_dist() {
    log "chaos_dist: 2-proc kill-mid-sharded-commit -> abort -> supervised relaunch -> bit-identical resume (seed 0)"
    cdir=$(mktemp -d /tmp/mxtpu_chaos_dist.XXXXXX)
    cat > "$cdir/worker.py" <<'EOF'
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import chaos, telemetry
from mxnet_tpu import distributed as dist
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.serving.loop import ContinuousTrainer

outdir = sys.argv[1]
assert mx.distributed_init() is True
nproc, rank = dist.world()
gen = dist.generation()
telemetry.enable()
chaos.arm_from_spec()            # EXPLICIT harness opt-in; the rule is
                                 # rank-1 + generation-0 scoped
# identical replicated params on every rank (the SPMD init contract)
np.random.seed(0)
mx.random.seed(0)
net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
ct = ContinuousTrainer(net, trainer, loss_fn, data, outdir + "/ckpts",
                       publish_every=1)
ckpt = ct.resume()

def dump_params(tag):
    arrs = {k: p._reduce().asnumpy() for k, p in
            net._collect_params_with_prefix().items()}
    np.savez(outdir + "/%s_rank%d.npz" % (tag, rank), **arrs)

if gen == 0:
    assert ckpt is None
    ct.run_steps(1)              # publish step 1 (verified)
    dump_params("step1")         # the bit-identical reference
    try:
        ct.run_steps(2)          # step-2 publish: rank 1 dies between
                                 # the "written" and "committed" barriers
    except dist.BarrierTimeout as e:
        assert 1 in e.ranks, e.ranks
        assert e.tag == "ckpt_committed", e.tag
        assert ct.manager.latest_step() == 1, ct.manager.all_steps()
        assert not os.path.isdir(ct.manager.step_dir(2)), \
            "merged manifest committed past a dead rank!"
        assert telemetry.counter("checkpoint.commit_aborted").value == 1
        print("SURVIVOR_ABORT rank=%d %s: %s" % (
            rank, type(e).__name__, e), flush=True)
        dist.failfast_exit(3)    # surface to the supervisor per policy
    raise SystemExit("chaos kill did not fire (rank %d)" % rank)

assert gen == 1, gen
assert ckpt is not None and ckpt.step == 1, ckpt
side = np.load(outdir + "/step1_rank%d.npz" % rank)
for k, p in sorted(net._collect_params_with_prefix().items()):
    a = p.data().asnumpy()
    b = side[k]
    assert a.dtype == b.dtype and a.tobytes() == b.tobytes(), k
print("RESUME_BIT_IDENTICAL rank=%d generation=%d step=%d"
      % (rank, gen, ckpt.step), flush=True)
ct.run_steps(2)                  # steps 2..3 publish clean
dist.barrier("gen1_steps_done")  # rename visibility (read-after-save)
assert ct.manager.latest_step() == 3, ct.manager.all_steps()
ct.close()
print("GEN1_DONE rank=%d" % rank, flush=True)
EOF
    spec=$(JAX_PLATFORMS=cpu python - <<'EOF'
from mxnet_tpu import chaos
print(chaos.make_spec(seed=0, rules=[
    {"point": "checkpoint.sharded.barrier.committed",
     "action": "kill", "nth": 2, "rank": 1, "generation": 0}]))
EOF
)
    JAX_PLATFORMS=cpu MXNET_TPU_CHAOS_SPEC="$spec" \
        MXNET_TPU_DIST_BARRIER_TIMEOUT_MS=8000 \
        MXNET_TPU_DIST_LEASE_TTL_S=4 \
        PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python tools/launch.py -n 2 --supervise --max-restarts 2 \
        --grace 30 python -u "$cdir/worker.py" "$cdir" \
        | tee "$cdir/out.log"
    # the gates: typed abort naming the dead rank, one relaunch, and a
    # bit-identical resume on BOTH ranks of generation 1
    grep -q "SURVIVOR_ABORT rank=0 BarrierTimeout" "$cdir/out.log"
    grep -q "rank(s) \[1\]" "$cdir/out.log"
    grep -q "relaunching generation 1" "$cdir/out.log"
    grep -q "RESUME_BIT_IDENTICAL rank=0 generation=1 step=1" "$cdir/out.log"
    grep -q "RESUME_BIT_IDENTICAL rank=1 generation=1 step=1" "$cdir/out.log"
    [ "$(grep -c GEN1_DONE "$cdir/out.log")" -eq 2 ]
    log "chaos_dist: restart-budget exhaustion -> /healthz NOT_READY gate"
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 python - <<'EOF'
import sys
from mxnet_tpu import telemetry
from mxnet_tpu.obs import status
from mxnet_tpu.supervisor import Supervisor

sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(2)"], 2,
                 max_restarts=1, grace_s=2)
rc = sup.run()
assert rc == 2 and sup.exhausted and sup.restarts == 1, (rc, sup.restarts)
assert telemetry.counter("supervisor.restarts").value == 1
assert telemetry.counter("supervisor.budget_exhausted").value == 1
ready, reasons = status.health()
assert not ready and "restart_budget_exhausted:1" in reasons, reasons
print("budget-exhaustion gate ok: NOT_READY reasons =", reasons)
EOF
    rm -rf "$cdir"
}

run_kernels() {
    log "kernels: interpret-mode kernel tests (registry + numerics + vjp + fallback)"
    # tests arm MXNET_TPU_KERNELS themselves (fixtures) so the CPU
    # backend runs the REAL Pallas kernel bodies in interpret mode
    JAX_PLATFORMS=cpu python -m pytest tests/test_kernels.py \
        tests/test_flash_attention.py -q -m 'not slow'
    log "kernels: fallback proof (Pallas unavailable -> XLA, numerics intact)"
    JAX_PLATFORMS=cpu MXNET_TPU_KERNELS=1 python - <<'EOF'
import numpy as np
import jax.numpy as jnp
from mxnet_tpu import kernels
from mxnet_tpu.kernels import fused_bn_relu as fbr
from mxnet_tpu.kernels import registry as kreg

# simulate a build without pallas: every choice must land on XLA
kreg._has_pallas = lambda: False
for name, kw in (("flash_attention",
                  dict(seq=512, block_q=256, block_k=256)),
                 ("fused_bn_relu", dict(axis=3, ndim=4)),
                 ("bucket_optimizer", {})):
    ch = kernels.choose(name, force=True, **kw)
    assert not ch.use_pallas, (name, ch)
    assert "unavailable" in ch.reason, ch.reason
rng = np.random.RandomState(0)
x = jnp.asarray(rng.randn(2, 4, 4, 8).astype(np.float32))
g = jnp.asarray(rng.rand(8).astype(np.float32) + 0.5)
b = jnp.asarray(rng.randn(8).astype(np.float32))
mm, mv = jnp.zeros(8, jnp.float32), jnp.ones(8, jnp.float32)
out, _, _ = fbr.fused_bn_relu(x, g, b, mm, mv, fix_gamma=False,
                              axis=3, training=True)
ro, _, _ = fbr.xla_reference(x, g, b, mm, mv, fix_gamma=False,
                             axis=3, training=True)
np.testing.assert_allclose(np.asarray(out), np.asarray(ro),
                           rtol=1e-6, atol=1e-6)
print("fallback proof ok: 3 kernels decline, fused op == XLA reference")
EOF
    log "kernels: zero-drift perf audit with the kernel tier armed"
    kdir=$(mktemp -d /tmp/mxtpu_kernels_ci.XXXXXX)
    JAX_PLATFORMS=cpu MXNET_TPU_KERNELS=1 MXNET_TPU_PROFILING=1 \
        python - "$kdir" <<'EOF'
import os, sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, kernels, profiling
from mxnet_tpu.analysis import perf
from mxnet_tpu.parallel import TrainStep

kdir = sys.argv[1]
assert profiling.enabled(), "MXNET_TPU_PROFILING=1 did not arm capture"
assert kernels.mode() == "on", "MXNET_TPU_KERNELS=1 did not arm the tier"
assert mx.runtime.Features().is_enabled("KERNELS")


class KernelSmokeNet(gluon.nn.HybridSequential):
    """Named so the kernels-armed audit row is stable across CI runs."""


net = KernelSmokeNet()
net.add(gluon.nn.Conv2D(8, 3, padding=1, layout="NHWC"),
        gluon.nn.BatchNorm(axis=3),
        gluon.nn.Activation("relu"),
        gluon.nn.Flatten(),
        gluon.nn.Dense(32, activation="relu"),
        gluon.nn.Dense(10))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "lars", {"learning_rate": 0.1},
                   kvstore=None)
step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                 mesh=None)
rng = np.random.RandomState(0)
x = mx.nd.array(rng.rand(8, 12, 12, 1).astype(np.float32))
y = mx.nd.array(rng.randint(0, 10, (8,)).astype(np.float32))
for _ in range(2):                      # fused BN+ReLU + bucketed LARS
    loss = step(x, y)
loss.asnumpy()
# the compiled step really selected the kernels (interpret on CPU)
assert kernels.choose("fused_bn_relu", axis=3, ndim=4).use_pallas
from mxnet_tpu.kernels import optimizer_update as kopt
assert kopt.bucket_active(tr._optimizer)
# audit scoped to the kernels-armed executable: the eager/hybrid op
# labels belong to the perflint smoke's blessed rows
audit = perf.perf_audit()
label = "train_step:KernelSmokeNet"
assert label in audit["executables"], audit["executables"].keys()
audit["executables"] = {label: audit["executables"][label]}
audit["advisories"] = [a for a in audit["advisories"]
                       if a.get("executable") == label]
perf.save_audit(os.path.join(kdir, "current.json"), audit)
print("kernels smoke ok: %s audited (%d advisories)"
      % (label, len(audit["advisories"])))
EOF
    # gate: the kernels-armed executable's efficiency metrics vs the
    # blessed train_step:KernelSmokeNet row -- growth errors naming the
    # executable + kind (with the remedy kernel), improvements pass
    python -m mxnet_tpu.analysis --perf-diff \
        ci/perf_baseline.json "$kdir/current.json" --json
    rm -rf "$kdir"
}

run_obs() {
    log "obs: traced train+serve smoke -> span/counter reconciliation gate"
    obsdir=$(mktemp -d /tmp/mxtpu_obs_ci.XXXXXX)
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 MXNET_TPU_OBS_TRACE=1 \
        MXNET_TPU_TELEMETRY_JSONL="$obsdir/run.jsonl" python - <<'EOF'
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import obs, telemetry
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.serving.loop import ContinuousTrainer

assert obs.tracing_enabled(), "MXNET_TPU_OBS_TRACE=1 did not arm tracing"
assert mx.runtime.Features().is_enabled("OBS_TRACE")
import tempfile
net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
ct = ContinuousTrainer(net, trainer, loss_fn, data,
                       tempfile.mkdtemp(), publish_every=2)
ct.run_steps(4)                          # 4 traced steps, 2 publishes
reg = mx.serving.ModelRegistry(compile_cache=False)
reg.register("m", block=scenarios.make_mlp(), input_shape=(8,),
             buckets=(1, 2, 4), max_wait_ms=5, max_queue=64)
sample = np.random.RandomState(0).rand(8).astype(np.float32)
for _ in range(10):
    reg.infer("m", sample, timeout=30)
reg.shutdown(drain=True); ct.close()
telemetry.flush()
print("traced smoke done:",
      len(obs.spans()), "spans recorded")
EOF
    python - "$obsdir/run.jsonl" <<'EOF'
import json, sys
from mxnet_tpu.telemetry import cli as tcli
agg = tcli.summarize_file(sys.argv[1])
sp, c, t = agg["spans"], agg["counters"], agg["timers"]
# causality <-> counters: one queue-wait + request span per accepted
# request, one batch span per compiled dispatch
assert sp["serving.queue_wait"]["count"] == c["serving.requests"], \
    (sp.get("serving.queue_wait"), c.get("serving.requests"))
assert sp["serving.request"]["count"] == c["serving.requests"]
assert sp["serving.batch"]["count"] == c["serving.batches"]
# span walls <-> timer telemetry: dispatch + device_get spans cover
# EXACTLY the window the serving.dispatch_time timer observed
span_wall = sp["serving.dispatch"]["sum"] + sp["serving.device_get"]["sum"]
timer_wall = t["serving.dispatch_time"]["sum"]
assert abs(span_wall - timer_wall) < 1e-4, (span_wall, timer_wall)
# the training side of the causal tree
assert sp["train.step"]["count"] == 4, sp.get("train.step")
assert sp["train.publish"]["count"] == 2
assert sp["checkpoint.commit"]["count"] == 2
print("obs trace gate ok: %d request spans reconcile, dispatch wall "
      "%.3fms == timer %.3fms" % (sp["serving.request"]["count"],
                                  1e3 * span_wall, 1e3 * timer_wall))
EOF
    log "obs: chaos KILL mid-commit (seed 0) -> blackbox postmortem gate"
    set +e
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 MXNET_TPU_OBS_TRACE=1 \
        MXNET_TPU_OBS_BLACKBOX="$obsdir/crash.bbox" python - "$obsdir" <<'EOF'
import sys
from mxnet_tpu import chaos, obs
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.serving.loop import ContinuousTrainer

assert obs.flight.installed() is not None, "blackbox did not install"
net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
ct = ContinuousTrainer(net, trainer, loss_fn, data,
                       sys.argv[1] + "/ckpts", publish_every=1)
chaos.arm(seed=0)
chaos.on("checkpoint.commit.pre_manifest", nth=2, action=chaos.KILL)
ct.run_steps(2)                          # dies mid-commit of step 2
raise SystemExit("chaos KILL did not fire")
EOF
    rc=$?
    set -e
    [ "$rc" -eq 137 ] || { echo "expected exit 137, got $rc"; exit 1; }
    # the blackbox CLI must render it, and the machine gate must find
    # the injected fault + the in-flight trace as the FINAL events
    python -m mxnet_tpu.telemetry blackbox "$obsdir/crash.bbox"
    python - "$obsdir/crash.bbox" <<'EOF'
import sys
from mxnet_tpu.obs import flight
recs = flight.read(sys.argv[1])
assert recs, "empty blackbox after a KILL"
last = recs[-1]
assert last.get("name") == "chaos.kill", last
assert last["payload"]["point"] == "checkpoint.commit.pre_manifest"
# the in-flight trace: the kill landed inside the traced
# step->publish->commit chain, so the dump names the dying span
assert last["payload"].get("trace") and last["payload"].get("span"), last
names = [r.get("name") for r in recs]
assert "chaos.inject" in names, "injected-fault event missing from ring"
spans = [r for r in recs if r.get("kind") == "span"]
assert any(s["name"] == "train.step" for s in spans), \
    "no traced spans in the ring"
print("obs blackbox gate ok: %d records, final=%s point=%s"
      % (len(recs), last["name"], last["payload"]["point"]))
EOF
    log "obs: /healthz READY -> NOT_READY flip under the swap failure budget"
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 python - "$obsdir" <<'EOF'
import json, sys, urllib.request, warnings
import mxnet_tpu as mx
from mxnet_tpu import chaos, obs, telemetry
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.serving.loop import ContinuousTrainer, RegistryWatcher

def get(port, path):
    try:
        r = urllib.request.urlopen("http://127.0.0.1:%d%s" % (port, path))
        return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())

port = obs.serve(0)                      # ephemeral: CI-safe
root = sys.argv[1] + "/health_ckpts"
net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
ct = ContinuousTrainer(net, trainer, loss_fn, data, root, publish_every=1)
ct.run_steps(1)
reg = mx.serving.ModelRegistry(compile_cache=False)
watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                          input_shape=(8,), buckets=(1, 2),
                          max_wait_ms=2, swap_retries=0,
                          failure_budget=1)
assert watcher.poll_once() == 1
code, body = get(port, "/healthz")
assert code == 200 and body["status"] == "READY", (code, body)
prom = urllib.request.urlopen(
    "http://127.0.0.1:%d/metrics" % port).read().decode()
assert "mxnet_tpu_serving_swaps 1" in prom, prom[:400]
code, st = get(port, "/statusz")
assert st["served_step"] == 1 and st["watchers"][0]["name"] == "m", st
# now every install aborts: publish a new step, let the watcher
# exhaust its budget (retries=0, budget=1) and suspend
ct.run_steps(1)
chaos.arm(seed=0)
chaos.on("serving.swap", action=chaos.RAISE)
with warnings.catch_warnings():
    warnings.simplefilter("ignore", RuntimeWarning)
    assert watcher.poll_once() is None
chaos.disarm(); chaos.reset()
assert watcher.suspended
assert telemetry.counter("serving.watcher_suspensions").value == 1
ev = telemetry.event("serving.watcher_suspended").recent[-1]
assert ev["model"] == "m", ev
code, body = get(port, "/healthz")
assert code == 503 and body["status"] == "NOT_READY", (code, body)
assert any(r.startswith("watcher_suspended:m") for r in body["reasons"])
reg.shutdown(drain=True); watcher.close(); ct.close(); obs.server.stop()
print("obs healthz gate ok: READY -> NOT_READY on suspension "
      "(reasons=%s)" % body["reasons"])
EOF
    log "obs: goodput gate -- injected feed stall must read input-bound"
    JAX_PLATFORMS=cpu MXNET_TPU_TELEMETRY=1 MXNET_TPU_OBS_GOODPUT=1 \
        MXNET_TPU_OBS_GOODPUT_WINDOW=4 python - <<'EOF'
import tempfile
import mxnet_tpu as mx
from mxnet_tpu import chaos, obs, telemetry
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.dataio import DeviceFeed
from mxnet_tpu.obs import goodput
from mxnet_tpu.serving.loop import ContinuousTrainer

assert obs.goodput_enabled(), "MXNET_TPU_OBS_GOODPUT=1 did not arm"
assert mx.runtime.Features().is_enabled("OBS_GOODPUT")
net, trainer, loss_fn, (x, y) = scenarios.train_fixtures(seed=0)
xn, yn = x.asnumpy(), y.asnumpy()


def batches():
    while True:
        yield (xn, yn)


# the PRODUCT wiring: ContinuousTrainer ticks the process ledger every
# step; its data callable pulls staged batches off a DeviceFeed, so
# the feed.produce chaos rule below starves the consumer for real
feed = DeviceFeed(batches(), ctx=mx.cpu())


def data(step):
    b = next(feed)
    return b.data, b.label


ct = ContinuousTrainer(net, trainer, loss_fn, data,
                       tempfile.mkdtemp(), publish_every=10 ** 6)
ct.run_steps(20)                         # 5 healthy windows = baseline
led = goodput.ledger()
healthy = led.windows()
assert len(healthy) == 5, len(healthy)
# injected chaos stall on the input path: input_wait must dominate
chaos.arm(seed=0)
chaos.on("feed.produce", action=chaos.sleep(0.03))
ct.run_steps(12)                         # 3 stalled windows
chaos.disarm(); chaos.reset()
ct.close()
feed.close()
wins = led.windows()
# the reconciliation contract holds on EVERY window (sum == wall
# within tol; only overshoot/double-counting can break it)
for w in wins:
    assert w["reconciliation"]["ok"], w["reconciliation"]
stalled = [w for w in wins[5:] if w["steps"]]
assert stalled, "no stalled windows closed"
last = stalled[-1]
assert last["verdict"]["bound"] == "input", last["verdict"]
assert last["categories"]["input_wait"]["share"] > 0.5, \
    last["categories"]
# the sentinel NAMED the category that moved
regs = telemetry.event("goodput.regression").recent
assert any(r["category"] == "input_wait" for r in regs), regs
assert telemetry.counter("goodput.env_degraded_windows").value == 0
print("obs goodput gate ok: %d windows reconciled, verdict=%r, "
      "sentinel named input_wait"
      % (len(wins), last["verdict"]["detail"]))
EOF
    rm -rf "$obsdir"
}

run_fleet() {
    log "fleet: 2-replica kill-mid-flood -> replica_down fires -> relaunch resolves (seed 0)"
    fdir=$(mktemp -d /tmp/mxtpu_fleet_ci.XXXXXX)
    cat > "$fdir/replica.py" <<'EOF'
import os, sys, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import chaos, gluon, obs, telemetry

workdir = sys.argv[1]
rank = int(os.environ.get("MXNET_TPU_PROC_ID", "0"))
gen = int(os.environ.get("MXNET_TPU_GENERATION", "0"))
telemetry.enable()
chaos.arm_from_spec()            # the kill rule is rank-1 gen-0 scoped

net = gluon.nn.Dense(4)
net.initialize(); net.hybridize()
net(mx.nd.array(np.zeros((1, 8), np.float32)))
reg = mx.serving.ModelRegistry()
s = reg.register("mlp", block=net, input_shape=(8,),
                 buckets=(1, 2, 4), max_wait_ms=20, max_queue=256)
port = obs.serve(0)              # publishes r<rank>.<pid>.json
print("SERVING rank=%d gen=%d port=%d" % (rank, gen, port), flush=True)

if rank == 1 and gen == 0:
    # flood only after rank 0 drained: the chaos kill then lands in a
    # window where rank 0's zero-drop accounting is already banked
    deadline = time.time() + 120
    while not os.path.exists(workdir + "/rank0_done"):
        time.sleep(0.05)
        assert time.time() < deadline, "rank0_done never appeared"

sample = np.random.RandomState(0).rand(8).astype(np.float32)
futs = [s.submit(sample, timeout=30) for _ in range(40)]
for f in futs:                   # every ACCEPTED request must answer
    assert f.result(timeout=30) is not None
print("FLOOD_OK rank=%d gen=%d dropped=0" % (rank, gen), flush=True)
if rank == 0 and gen == 0:
    open(workdir + "/rank0_done", "w").close()
# park until the harness says stop; gen-0 survivors instead die by the
# supervisor's kill-tree when the chaos kill triggers the relaunch
deadline = time.time() + 300
while not os.path.exists(workdir + "/stop"):
    time.sleep(0.1)
    assert time.time() < deadline, "stop never appeared"
reg.shutdown(drain=True)
obs.server.stop()                # withdraws the endpoint file
print("CLEAN_EXIT rank=%d gen=%d" % (rank, gen), flush=True)
EOF
    JAX_PLATFORMS=cpu PYTHONPATH="$PWD${PYTHONPATH:+:$PYTHONPATH}" \
        python - "$fdir" <<'EOF' | tee "$fdir/out.log"
import json, os, subprocess, sys, threading, time
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from mxnet_tpu import chaos
from mxnet_tpu.obs.fleet import FleetMonitor
from mxnet_tpu.supervisor import Supervisor

workdir = sys.argv[1]
eps = os.path.join(workdir, "eps")
spec = chaos.make_spec(seed=0, rules=[
    {"point": "serving.dispatch", "action": "kill", "nth": 5,
     "rank": 1, "generation": 0}])
env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_TPU_TELEMETRY="1",
           MXNET_TPU_CHAOS_SPEC=spec)
sup = Supervisor([sys.executable, "-u", workdir + "/replica.py",
                  workdir], 2, max_restarts=2, grace_s=3,
                 env=env, endpoints_dir=eps)
rc = []
th = threading.Thread(target=lambda: rc.append(sup.run()), daemon=True)
th.start()

mon = FleetMonitor(eps, scrape_ms=100, ttl_s=5.0, timeout_s=2.0,
                   retries=0)
deadline = time.time() + 240
# phase 1: the chaos kill must FIRE replica_down naming rank+gen
fired = None
while time.time() < deadline and fired is None:
    mon.poll_once()
    for a in mon.engine.firing():
        if a.rule == "replica_down" and "rank 1" in a.reason:
            fired = a
    time.sleep(0.1)
assert fired is not None, "replica_down never fired for rank 1"
assert "generation 0" in fired.reason, fired.reason
print("FLEET_FIRED: %s" % fired.reason, flush=True)
# phase 2: the supervisor relaunch must RESOLVE it
while time.time() < deadline and mon.engine.firing():
    mon.poll_once()
    time.sleep(0.1)
assert not mon.engine.firing(), \
    "still firing after relaunch: %r" % mon.engine.firing()
assert any(h["rule"] == "replica_down" and h["state"] == "resolved"
           for h in mon.engine.history()), mon.engine.history()
agg = mon.last["aggregate"]
assert agg["up"] == 2 and agg["down"] == 0, agg
gens = {r["rank"]: r["generation"] for r in mon.last["replicas"]}
assert gens == {0: 1, 1: 1}, gens
mon.close()
print("FLEET_RESOLVED: generation 1 up on both ranks", flush=True)
# gate the CLI exit-code contract both ways: 0 on the healthy
# relaunched fleet...
cp = subprocess.run([sys.executable, "-m", "mxnet_tpu.telemetry",
                     "fleet", eps, "--rounds", "2",
                     "--interval-ms", "100"],
                    env=env, capture_output=True, text=True)
sys.stdout.write(cp.stdout)
assert cp.returncode == 0, (cp.returncode, cp.stdout, cp.stderr)
print("FLEET_CLI_HEALTHY_EXIT_0", flush=True)
open(os.path.join(workdir, "stop"), "w").close()
th.join(timeout=120)
assert rc and rc[0] == 0, "supervisor rc %r" % (rc,)
# ...and 1 once every endpoint is withdrawn (nothing scrapeable)
cp = subprocess.run([sys.executable, "-m", "mxnet_tpu.telemetry",
                     "fleet", eps],
                    env=env, capture_output=True, text=True)
assert cp.returncode == 1, (cp.returncode, cp.stdout, cp.stderr)
print("FLEET_CLI_EMPTY_EXIT_1", flush=True)
print("FLEET_STAGE_OK", flush=True)
EOF
    # the gates, re-checked off the transcript: zero-drop floods on
    # every drained replica, the fire->resolve arc, both CLI exits
    grep -q "FLOOD_OK rank=0 gen=0 dropped=0" "$fdir/out.log"
    grep -q "FLOOD_OK rank=0 gen=1 dropped=0" "$fdir/out.log"
    grep -q "FLOOD_OK rank=1 gen=1 dropped=0" "$fdir/out.log"
    grep -q "FLEET_FIRED:.*rank 1 generation 0" "$fdir/out.log"
    grep -q "relaunching generation 1" "$fdir/out.log"
    grep -q "FLEET_RESOLVED" "$fdir/out.log"
    [ "$(grep -c "CLEAN_EXIT" "$fdir/out.log")" -eq 2 ]
    grep -q "FLEET_CLI_HEALTHY_EXIT_0" "$fdir/out.log"
    grep -q "FLEET_CLI_EMPTY_EXIT_1" "$fdir/out.log"
    grep -q "FLEET_STAGE_OK" "$fdir/out.log"
    rm -rf "$fdir"
}

run_bench() {
    log "bench: harness self-check (no device time)"
    python - <<'EOF'
import bench
# the driver contract: main exists, headline fns are callable, and the
# budget machinery is wired
assert callable(bench.main)
assert callable(bench.bench_resnet50_scan)
assert callable(bench.bench_bert_base)
assert bench._BUDGET_S > 0
print("bench harness ok")
EOF
}

run_wheel() {
    log "wheel: build + clean-target install + import smoke"
    rm -rf dist
    # --no-isolation: this environment has zero egress; setuptools
    # comes from the ambient site-packages
    python -m build --wheel --no-isolation --outdir dist >/dev/null
    whl=$(ls dist/*.whl)
    # clean-target install (a nested venv cannot see this venv's
    # site-packages for jax/numpy); run OUTSIDE the repo dir so the
    # installed wheel, not the source tree, is what imports
    target=$(mktemp -d /tmp/mxtpu_wheel_ci.XXXXXX)
    python -m pip install --no-deps -q --target "$target" "$whl"
    (cd /tmp && PYTHONPATH="$target:${PYTHONPATH:-}" python - <<'EOF'
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
assert mx.nd.ones((2, 2)).asnumpy().sum() == 4.0
net = gluon.nn.Dense(3)
net.initialize()
x = mx.nd.array(np.ones((2, 4), np.float32))
with autograd.record():
    y = net(x).sum()
y.backward()
import mxnet_tpu
assert "mxtpu_wheel_ci" in mxnet_tpu.__file__, mxnet_tpu.__file__
print("wheel import + train smoke ok:", mxnet_tpu.__file__)
EOF
    )
    rm -rf "$target"
}

for s in "${stages[@]}"; do
    "run_$s"
done
log "ALL STAGES GREEN"
