"""Typed env registry (reference: docs/faq/env_var.md convention) and
preemption-aware checkpointing (SURVEY §5 failure detection)."""
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- env registry ------------------------------------------------------

def test_env_typed_reads(monkeypatch):
    assert mx.env.get("MXNET_OPTIMIZER_AGGREGATION_SIZE") == 60
    monkeypatch.setenv("MXNET_OPTIMIZER_AGGREGATION_SIZE", "8")
    assert mx.env.get("MXNET_OPTIMIZER_AGGREGATION_SIZE") == 8
    monkeypatch.setenv("MXNET_TPU_EAGER_JIT", "0")
    assert mx.env.get("MXNET_TPU_EAGER_JIT") is False
    monkeypatch.setenv("MXNET_OPTIMIZER_AGGREGATION_SIZE", "not-an-int")
    with pytest.raises(MXNetError):
        mx.env.get("MXNET_OPTIMIZER_AGGREGATION_SIZE")
    with pytest.raises(MXNetError):
        mx.env.get("MXNET_NO_SUCH_VAR")


def test_env_registry_covers_code_usages():
    """Every MXNET_* env var read anywhere in the package must be
    registered (the registry is the doc page's source of truth)."""
    import re
    used = set()
    pkg = os.path.join(REPO, "mxnet_tpu")
    for root, _dirs, files in os.walk(pkg):
        for f in files:
            if not f.endswith(".py"):
                continue
            src = open(os.path.join(root, f)).read()
            for m in re.finditer(
                    r"environ(?:\.get)?[(\[]\s*['\"](MXNET_[A-Z_0-9]+)",
                    src):
                used.add(m.group(1))
    missing = used - set(mx.env.REGISTRY)
    assert not missing, "unregistered env vars: %s" % sorted(missing)


def test_env_doc_page_fresh():
    generated = mx.env.generate_doc()
    on_disk = open(os.path.join(REPO, "docs", "env_vars.md")).read()
    assert generated == on_disk, \
        "docs/env_vars.md is stale; regenerate with mx.env.generate_doc"


def test_runtime_lists_env_vars():
    listing = mx.runtime.env_vars()
    assert "MXNET_TPU_EAGER_JIT" in listing
    val, default, doc = listing["MXNET_TPU_EAGER_JIT"]
    assert doc


# -- preemption checkpointing -----------------------------------------

def _net_and_trainer():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    return net, tr


def test_sigterm_checkpoints_and_resumes(tmp_path):
    from mxnet_tpu import autograd
    prefix = str(tmp_path / "job")
    net, tr = _net_and_trainer()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 6).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (8,)).astype(np.float32))

    handler = mx.preemption.install(prefix, net, tr)
    step = 0
    for _ in range(20):
        if handler.triggered:
            break
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        tr.step(1)
        step += 1
        handler.extra_state["step"] = step
        if step == 5:
            os.kill(os.getpid(), signal.SIGTERM)
    handler.uninstall()

    assert handler.triggered and handler.saved
    assert step == 5
    assert os.path.exists(handler.params_path)
    assert os.path.exists(handler.states_path)

    # fresh process state: restore and verify params + momentum match
    net2, tr2 = _net_and_trainer()
    net2(x)  # materialize
    meta = mx.preemption.resume(prefix, net2, tr2)
    assert meta["extra"]["step"] == 5
    from conftest import paired_params
    for p1, p2 in paired_params(net, net2):
        np.testing.assert_array_equal(p1.data().asnumpy(),
                                      p2.data().asnumpy())
    # trained nets continue identically after resume -> states match
    for t, n in ((tr, net), (tr2, net2)):
        with autograd.record():
            l = loss_fn(n(x), y).mean()
        l.backward()
        t.step(1)
    for p1, p2 in paired_params(net, net2):
        np.testing.assert_allclose(p1.data().asnumpy(),
                                   p2.data().asnumpy(), rtol=1e-6)


def test_resume_without_checkpoint_returns_none(tmp_path):
    net, tr = _net_and_trainer()
    assert mx.preemption.resume(str(tmp_path / "none"), net, tr) is None


def test_external_sigterm_subprocess(tmp_path):
    """Realistic shape: the OS delivers SIGTERM to a training process;
    it must exit cleanly having written the checkpoint."""
    prefix = str(tmp_path / "ext")
    code = """
import os, signal, sys, time
sys.path.insert(0, %r)
os.environ.setdefault("JAX_PLATFORMS", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(8), gluon.nn.Dense(4))
net.initialize(ctx=mx.cpu()); net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                   kvstore=None)
loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
handler = mx.preemption.install(%r, net, tr)
x = mx.nd.array(np.random.randn(4, 6).astype("float32"))
y = mx.nd.array(np.zeros(4, "float32"))
print("READY", flush=True)
i = 0
while not handler.triggered:
    with autograd.record():
        l = loss_fn(net(x), y).mean()
    l.backward(); tr.step(1); i += 1
print("CHECKPOINTED after", i, "steps", flush=True)
""" % (REPO, prefix)
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    assert proc.stdout.readline().strip() == "READY"
    import time
    time.sleep(1.0)
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=120)
    assert "CHECKPOINTED" in out, out
    assert os.path.exists(prefix + "-preempt.params")
    meta = json.load(open(prefix + "-preempt.meta"))
    assert "step" in meta


def test_fallback_save_is_provisional(tmp_path):
    """A fallback-timer save may catch a torn mid-step state, so it
    must NOT satisfy the handler: the next consistent boundary save
    re-saves over it (advisor r4: the old behavior let the torn
    checkpoint win permanently)."""
    prefix = str(tmp_path / "fb")
    net, tr = _net_and_trainer()
    x = mx.nd.array(np.random.randn(4, 6).astype(np.float32))
    net(x)
    handler = mx.preemption.install(prefix, net, tr)
    try:
        # simulate the fallback timer firing mid-step
        handler.save_now(provisional=True)
        assert os.path.exists(handler.params_path)
        assert not handler.saved        # provisional: job not done
        first_mtime = os.path.getmtime(handler.meta_path)
        # a second fallback fire is a no-op
        handler.save_now(provisional=True)
        assert os.path.getmtime(handler.meta_path) == first_mtime
        # the boundary save overwrites the provisional checkpoint
        handler.save_now(step=7)
        assert handler.saved
        meta = json.load(open(handler.meta_path))
        assert meta["step"] == 7
    finally:
        handler.uninstall()
