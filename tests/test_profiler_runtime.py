"""Profiler + runtime-features + eager-dispatch tests (reference:
``tests/python/unittest/test_profiler.py`` / ``test_runtime.py``)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def test_profiler_trace_lifecycle(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "profile.json"))
    assert mx.profiler.state() == "stop"
    mx.profiler.start()
    assert mx.profiler.state() == "run"
    x = mx.nd.ones((8, 8))
    (x * 2).asnumpy()
    trace_dir = mx.profiler.dump()
    assert mx.profiler.state() == "stop"
    assert trace_dir and os.path.isdir(trace_dir)
    # jax writes TensorBoard plugins/profile data under the dir
    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found.extend(files)
    assert found, "no trace files written"
    # dumps() now returns real aggregate stats (mx.profiling store),
    # not a pointer string
    assert "Profile Statistics" in mx.profiler.dumps()


def test_profiler_bad_config():
    with pytest.raises(mx.MXNetError):
        mx.profiler.set_config(bogus_option=1)


def test_runtime_features():
    feats = mx.runtime.Features()
    assert feats.is_enabled("XLA")
    assert feats.is_enabled("CPU")
    assert not feats.is_enabled("CUDA")
    assert any(f.name == "TPU" for f in mx.runtime.feature_list())
    with pytest.raises(RuntimeError):
        feats.is_enabled("NOT_A_FEATURE")


def test_eager_jit_cache_populates_and_reuses():
    from mxnet_tpu.ndarray import ndarray as ndmod
    # cache keys are (op, arity, STATIC params) -- shapes and float
    # scalars are traced, not keyed -- so use static clip bounds no
    # other test uses to get a deterministically fresh entry
    x = mx.nd.ones((4, 5))
    before = len(ndmod._EAGER_JIT_CACHE)
    y = mx.nd.clip(x, a_min=0.1234, a_max=7.5678)
    after = len(ndmod._EAGER_JIT_CACHE)
    assert after == before + 1     # populated
    for _ in range(3):
        y = mx.nd.clip(x, a_min=0.1234, a_max=7.5678)
    assert len(ndmod._EAGER_JIT_CACHE) == after   # reused, no growth
    np.testing.assert_allclose(y.asnumpy(), np.full((4, 5), 1.0))


def test_eager_jit_no_recompile_on_varying_float_params():
    """Per-step lr/wd/scalar values are traced, not baked into the cache
    key -- Adam-style bias-corrected lr must not compile per step."""
    from mxnet_tpu.ndarray import ndarray as ndmod
    w = mx.nd.ones((8,))
    g = mx.nd.ones((8,))
    m = mx.nd.zeros((8,))
    v = mx.nd.zeros((8,))
    mx.nd.adam_update(w, g, m, v, lr=0.001, out=w)
    before = set(ndmod._EAGER_JIT_CACHE)
    for t in range(1, 5):
        lr = 0.001 * (1 - 0.999 ** t) ** 0.5 / (1 - 0.9 ** t)
        mx.nd.adam_update(w, g, m, v, lr=lr, out=w)
        x = mx.nd.ones((4,)) + (0.5 * t)
    assert set(ndmod._EAGER_JIT_CACHE) - before <= \
        {("_plus_scalar", (0,), 1, (), ("scalar",), None)}


def test_scalar_binop_preserves_int_dtype():
    x = mx.nd.array(np.array([1, 2, 3]), dtype="int32")
    y = x + 2
    assert y.dtype == np.int32
    np.testing.assert_array_equal(y.asnumpy(), [3, 4, 5])
    z = x * 3
    assert z.dtype == np.int32


def test_scalar_binops_use_scalar_ops():
    """Python-scalar operands must not materialize device arrays
    (they dispatch to the *_scalar op family)."""
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose((1.0 - x).asnumpy(), 1.0 - np.arange(
        6, dtype=np.float32).reshape(2, 3))
    np.testing.assert_allclose((2.0 / (x + 1)).asnumpy(),
                               2.0 / (np.arange(6, dtype=np.float32)
                                      .reshape(2, 3) + 1))
    np.testing.assert_allclose((x ** 2).asnumpy(),
                               np.arange(6, dtype=np.float32)
                               .reshape(2, 3) ** 2)
    np.testing.assert_allclose((x > 2.0).asnumpy(),
                               (np.arange(6).reshape(2, 3) > 2)
                               .astype(np.float32))
    np.testing.assert_allclose((3.0 > x).asnumpy(),
                               (3 > np.arange(6).reshape(2, 3))
                               .astype(np.float32))
    np.testing.assert_allclose((x == 2.0).asnumpy(),
                               (np.arange(6).reshape(2, 3) == 2)
                               .astype(np.float32))


def test_memory_info_surface():
    used, limit = mx.cpu().memory_info()
    assert used >= 0 and limit >= 0
    free, total = mx.context.gpu_memory_info() if mx.num_tpus() \
        else (0, 0)
    assert free >= 0 and total >= 0


def test_profiler_custom_objects(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "p.json"))
    mx.profiler.start()
    with mx.profiler.Task("io_phase"):
        mx.nd.ones((4,)).asnumpy()
    ev = mx.profiler.Event("step")
    ev.start()
    mx.profiler.marker("tick")
    ev.stop()
    c = mx.profiler.Counter("batches")
    c.increment()
    c.increment(2)
    assert c.value == 3
    mx.profiler.stop()
    mx.profiler.reset()


def test_profiler_counter_reset_and_registry_backing():
    """The former class-global ``Counter._counters`` dict leaked values
    across instances and tests; counters are now backed by the
    telemetry registry and ``profiler.reset()`` zeroes them."""
    from mxnet_tpu import telemetry
    c1 = mx.profiler.Counter("reset_check")
    c1.increment(5)
    # attach semantics preserved (reference behavior): same name, no
    # value argument -> attaches without resetting
    c2 = mx.profiler.Counter("reset_check")
    assert c2.value == 5
    c2.decrement(2)
    assert c1.value == 3
    # explicit value argument resets (reference behavior)
    c3 = mx.profiler.Counter("reset_check", value=10)
    assert c1.value == 10 and c3.value == 10
    # visible through the telemetry registry (one store, all sinks)
    assert telemetry.registry().get("profiler.reset_check").value == 10
    mx.profiler.reset()
    assert c1.value == 0 and c2.value == 0 and c3.value == 0
    # reset scopes to profiler counters only
    telemetry.counter("not_profiler").inc(4)
    mx.profiler.reset()
    assert telemetry.counter("not_profiler").value == 4
    telemetry.registry().clear("not_profiler")


def test_profiler_counter_domain_naming():
    d = mx.profiler.Domain("io")
    c = mx.profiler.Counter(d, "reads", value=2)
    assert c.name == "io::reads"
    c.increment()
    assert mx.profiler.Counter(d, "reads").value == 3
    mx.profiler.reset()


def test_profiler_dumps_real_aggregates_with_sort_and_format():
    """ISSUE 6 satellite: dumps() returns real per-executable stats
    from the CostReport store, honoring format=/sort_by=/ascending=."""
    import json
    from mxnet_tpu import profiling
    profiling.reset()
    profiling.enable()
    try:
        mx.nd.clip(mx.nd.ones((4, 4)), a_min=0.31, a_max=8.7).asnumpy()
        mx.nd.dot(mx.nd.ones((32, 32)), mx.nd.ones((32, 32))).asnumpy()
        table = mx.profiler.dumps()
        assert "Profile Statistics" in table
        assert "eager:dot" in table and "eager:clip" in table
        rows = json.loads(mx.profiler.dumps(format="json",
                                            sort_by="flops"))
        assert len(rows) >= 2
        flops = [r["flops"] for r in rows]
        assert flops == sorted(flops, reverse=True)   # descending
        rows_asc = json.loads(mx.profiler.dumps(format="json",
                                                sort_by="flops",
                                                ascending=True))
        assert [r["flops"] for r in rows_asc] == sorted(flops)
        # the dot row dominates the clip row in flops
        by = {r["name"]: r for r in rows}
        assert by["eager:dot"]["flops"] > by["eager:clip"]["flops"]
        with pytest.raises(mx.MXNetError):
            mx.profiler.dumps(sort_by="bogus")
        with pytest.raises(mx.MXNetError):
            mx.profiler.dumps(format="xml")
        # reset=True clears the store
        mx.profiler.dumps(reset=True)
        assert json.loads(mx.profiler.dumps(format="json")) == []
    finally:
        profiling.disable()
        profiling.reset()


def test_profiler_pause_resume(tmp_path):
    """Direct pause()/resume() coverage: pause turns scopes off while
    the trace keeps running; resume re-arms them only in 'run' state."""
    mx.profiler.set_config(filename=str(tmp_path / "pr.json"))
    assert not mx.profiler._scopes_enabled
    # resume while stopped must NOT arm scopes
    mx.profiler.resume()
    assert not mx.profiler._scopes_enabled
    mx.profiler.start()
    try:
        assert mx.profiler._scopes_enabled
        mx.profiler.pause()
        assert not mx.profiler._scopes_enabled
        assert mx.profiler.state() == "run"     # trace still running
        # a scope entered while paused is a no-op (no annotation cm)
        with mx.profiler.scope("paused_region"):
            pass
        mx.profiler.resume()
        assert mx.profiler._scopes_enabled
        with mx.profiler.scope("resumed_region"):
            mx.nd.ones((2,)).asnumpy()
    finally:
        mx.profiler.stop()
    assert mx.profiler.state() == "stop"
