"""mxnet_tpu.sync: the instrumented synchronization layer (ISSUE 5
runtime half) -- zero-overhead pass-through when off, lock-order
sanitizer + deadlock watchdog when armed."""
import os
import threading
import time

import pytest

from mxnet_tpu import sync

_TSAN_ENV = os.environ.get("MXNET_TPU_TSAN", "0") != "0"


@pytest.fixture(autouse=True)
def _restore_sync_state():
    """Each test leaves the sanitizer exactly as it found it (the CI
    tsan stage runs this file with the env flag armed; tier-1 runs it
    unarmed)."""
    was_on = sync.tsan_enabled()
    yield
    if was_on:
        sync.enable(seed_static=False)
    else:
        sync.disable()
    sync.configure(raise_on_inversion=True,
                   watchdog_s=sync._watchdog_default())
    sync.reset_state()


# ----------------------------------------------------------------------
# off mode: raw primitives, nothing to measure
# ----------------------------------------------------------------------

@pytest.mark.skipif(_TSAN_ENV, reason="suite running under TSAN")
def test_off_mode_returns_raw_primitives():
    """The zero-overhead contract: with the flag off the factories
    return the *raw* threading primitives -- there is no wrapper to
    pay for on acquire/release."""
    assert type(sync.Lock()) is type(threading.Lock())
    assert type(sync.RLock()) is type(threading.RLock())
    assert isinstance(sync.Condition(), threading.Condition)
    assert isinstance(sync.Event(), threading.Event)
    # a sanitized lock shared into a raw Condition still works
    lk = sync.Lock(name="probe")
    cond = sync.Condition(lk)
    with cond:
        cond.notify_all()


def test_enable_switches_factories():
    sync.enable(seed_static=False)
    try:
        assert isinstance(sync.Lock(name="a"), sync._TsanLock)
        assert isinstance(sync.RLock(name="b"), sync._TsanRLock)
        assert isinstance(sync.Condition(name="c"), sync._TsanCondition)
        assert isinstance(sync.Event(name="d"), sync._TsanEvent)
    finally:
        sync.disable()
    if not _TSAN_ENV:
        assert type(sync.Lock()) is type(threading.Lock())


def test_wrappers_turn_inert_after_disable():
    sync.enable(seed_static=False)
    a = sync.Lock(name="inert.a")
    b = sync.Lock(name="inert.b")
    sync.disable()
    # order bookkeeping is off: opposite nestings never raise
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert "inert.a" not in sync.order_graph()


# ----------------------------------------------------------------------
# lock-order sanitizer
# ----------------------------------------------------------------------

def test_lock_order_inversion_raises():
    """The injected A/B--B/A inversion: observed on ONE thread is
    enough -- the graph, not a lucky schedule, is the oracle."""
    sync.enable(watchdog_s=30, seed_static=False)
    a = sync.Lock(name="inv.a")
    b = sync.Lock(name="inv.b")
    with a:
        with b:
            pass
    with pytest.raises(sync.LockOrderError) as ei:
        with b:
            with a:
                pass
    msg = str(ei.value)
    assert "inv.a" in msg and "inv.b" in msg
    assert "acquired at" in msg          # both stacks are in the report
    # the failed acquire must NOT leave the lock held
    assert a._inner.acquire(timeout=1)
    a._inner.release()


def test_inversion_report_only_mode_records():
    sync.enable(watchdog_s=30, seed_static=False)
    sync.configure(raise_on_inversion=False)
    a = sync.Lock(name="rep.a")
    b = sync.Lock(name="rep.b")
    with a:
        with b:
            pass
    with b:
        with a:                           # recorded, not raised
            pass
    reports = sync.recorded_reports()
    assert len(reports) == 1
    assert "rep.a" in reports[0] and "rep.b" in reports[0]


def test_rlock_reentry_adds_no_edges():
    sync.enable(watchdog_s=30, seed_static=False)
    r = sync.RLock(name="re.r")
    other = sync.Lock(name="re.other")
    with r:
        with r:                           # reentry: no self edge
            with other:
                pass
    graph = sync.order_graph()
    assert graph.get("re.r") == {"re.other"}
    # and the reverse order now trips
    with pytest.raises(sync.LockOrderError):
        with other:
            with r:
                pass


def test_three_lock_cycle_detected():
    """A -> B, B -> C observed; C -> A closes the cycle through the
    transitive path, not a direct edge."""
    sync.enable(watchdog_s=30, seed_static=False)
    a, b, c = (sync.Lock(name="cyc.%s" % n) for n in "abc")
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(sync.LockOrderError) as ei:
        with c:
            with a:
                pass
    assert "cyc.b" in str(ei.value)       # the path names the middleman


def test_static_seed_is_best_effort_and_idempotent():
    sync.enable(seed_static=True)
    n1 = sync.seed_static_order()         # second call: already seeded
    assert n1 == 0
    # the graph is usable either way
    lk = sync.Lock(name="seed.probe")
    with lk:
        pass


# ----------------------------------------------------------------------
# deadlock watchdog
# ----------------------------------------------------------------------

def test_watchdog_fires_on_crossed_lock_deadlock():
    """The artificial deadlock: two threads, crossed locks, report-only
    mode so the ordering check does not defuse it first.  The watchdog
    must fire and the report must name BOTH held stacks."""
    sync.enable(watchdog_s=1.0, seed_static=False)
    sync.configure(raise_on_inversion=False)
    a = sync.Lock(name="dead.a")
    b = sync.Lock(name="dead.b")
    barrier = threading.Barrier(2, timeout=5)
    errs = {}

    def cross(first, second, key):
        try:
            with first:
                barrier.wait()            # both hold their first lock
                with second:
                    pass
        except sync.DeadlockError as e:
            errs[key] = str(e)

    t1 = threading.Thread(target=cross, args=(a, b, "t1"), daemon=True)
    t2 = threading.Thread(target=cross, args=(b, a, "t2"), daemon=True)
    t1.start()
    t2.start()
    t1.join(timeout=10)
    t2.join(timeout=10)
    assert errs, "no watchdog fired on a crossed-lock deadlock"
    report = next(iter(errs.values()))
    assert "DEADLOCK watchdog" in report
    # both held stacks: each lock appears as held, with its acquire site
    assert "holds 'dead.a' acquired at" in report
    assert "holds 'dead.b' acquired at" in report
    assert "all thread stacks" in report
    assert "cross" in report              # the frames name the function


def test_watchdog_respects_caller_timeouts():
    """A caller-supplied finite timeout keeps ``acquire`` semantics:
    return False, never raise."""
    sync.enable(watchdog_s=1.0, seed_static=False)
    lk = sync.Lock(name="to.lk")
    hold = threading.Event()
    release = threading.Event()

    def holder():
        with lk:
            hold.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert hold.wait(5)
    assert lk.acquire(timeout=0.1) is False
    assert lk.acquire(blocking=False) is False
    release.set()
    t.join(timeout=5)


def test_event_untimed_wait_watchdogged():
    sync.enable(watchdog_s=0.3, seed_static=False)
    ev = sync.Event(name="ev.never")
    with pytest.raises(sync.DeadlockError):
        ev.wait()
    # timed waits keep Event semantics
    assert ev.wait(0.05) is False
    ev.set()
    assert ev.wait() is True


def test_condition_wait_notify_under_tsan():
    sync.enable(watchdog_s=5, seed_static=False)
    cond = sync.Condition(name="cv.test")
    items = []

    def producer():
        for i in range(3):
            with cond:
                items.append(i)
                cond.notify_all()

    t = threading.Thread(target=producer, daemon=True)
    got = []
    with cond:
        t.start()
        ok = cond.wait_for(lambda: len(items) == 3, timeout=5)
        got = list(items)
    t.join(timeout=5)
    assert ok and got == [0, 1, 2]
    # while waiting, the condition's lock must NOT count as held
    # (producer acquired it without the sanitizer seeing a nesting)
    graph = sync.order_graph()
    assert "cv.test" not in graph.get("cv.test.lock", set())


def test_condition_untimed_wait_watchdogged():
    sync.enable(watchdog_s=0.3, seed_static=False)
    cond = sync.Condition(name="cv.stuck")
    with pytest.raises(sync.DeadlockError):
        with cond:
            cond.wait()                   # nobody will ever notify


# ----------------------------------------------------------------------
# telemetry integration
# ----------------------------------------------------------------------

def test_sync_telemetry_counts_watchdog_and_inversions():
    from mxnet_tpu import telemetry
    telemetry.reset("sync.")
    telemetry.enable()
    try:
        sync.enable(watchdog_s=0.2, seed_static=False)
        sync.configure(raise_on_inversion=False)
        a = sync.Lock(name="tel.a")
        b = sync.Lock(name="tel.b")
        with a:
            with b:
                pass
        with b:
            with a:
                pass
        assert telemetry.counter("sync.inversions").value >= 1
        ev = sync.Event(name="tel.ev")
        with pytest.raises(sync.DeadlockError):
            ev.wait()
        assert telemetry.counter("sync.watchdog_fires").value >= 1
    finally:
        telemetry.disable()
