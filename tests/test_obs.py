"""Observability ops plane tests (ISSUE 13): context-propagated
tracing with the zero-call disabled contract, serving/training span
reconciliation against the telemetry counters, the crash-safe flight
recorder (including a real os._exit subprocess), the /healthz //statusz
/metrics introspection server, the watcher-suspension event, the
multi-rank skew summarizer, and the generated instrument index."""
import ast
import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, obs, telemetry
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.obs import flight
from mxnet_tpu.serving.loop import ContinuousTrainer, RegistryWatcher
from mxnet_tpu.telemetry import cli as tcli
from mxnet_tpu.telemetry import hooks as thooks


@pytest.fixture(autouse=True)
def _clean_obs():
    """Every test starts with tracing off, empty rings, no recorder,
    no server, and a clean status board (obs state is process-global
    by design, like telemetry)."""
    obs.disable_tracing()
    obs.trace.clear()
    obs.status.reset()
    flight.uninstall()
    telemetry.disable()
    telemetry.registry().clear()
    yield
    obs.disable_tracing()
    obs.trace.clear()
    obs.status.reset()
    flight.uninstall()
    obs.server.stop()
    telemetry.disable()
    if telemetry._jsonl_sink is not None:
        telemetry.registry().detach(telemetry._jsonl_sink)
        telemetry._jsonl_sink.close()
        telemetry._jsonl_sink = None
    telemetry.registry().clear()


def _spans_by_name():
    out = {}
    for s in obs.spans():
        out.setdefault(s["name"], []).append(s)
    return out


# ---------------------------------------------------------------------
# trace core
# ---------------------------------------------------------------------

def test_trace_context_parenting_and_restore():
    obs.enable_tracing()
    with obs.start_trace("root") as rc:
        assert obs.current().trace_id == rc.trace_id
        with obs.span("child") as cc:
            assert cc.trace_id == rc.trace_id
            assert obs.current().span_id == cc.span_id
        assert obs.current().span_id == rc.span_id
    assert obs.current() is None
    spans = obs.spans()
    assert [s["name"] for s in spans] == ["child", "root"]
    child, root = spans
    assert child["parent"] == rc.span_id
    assert root["parent"] is None
    assert child["trace"] == root["trace"] == rc.trace_id
    assert child["dur"] >= 0


def test_contextvar_isolation_across_threads():
    obs.enable_tracing()
    seen = {}

    def worker():
        seen["ctx"] = obs.current()      # no inherited context
        with obs.span("t2"):
            seen["inner"] = obs.current()

    with obs.start_trace("main"):
        t = threading.Thread(target=worker)
        t.start()
        t.join()
        assert obs.current() is not None
    assert seen["ctx"] is None           # threads don't leak context
    assert seen["inner"] is not None


def test_fresh_context_adopts_current_trace():
    obs.enable_tracing()
    with obs.start_trace("outer") as rc:
        ctx = obs.trace.fresh_context()
        assert ctx.trace_id == rc.trace_id
        assert ctx.span_id != rc.span_id
    ctx2 = obs.trace.fresh_context()
    assert ctx2.trace_id != rc.trace_id  # no active trace -> new one


def test_span_ring_bounded():
    obs.enable_tracing()
    cap = obs.trace._MAX_SPANS
    ctx = obs.TraceContext("t" * 16, "s" * 16)
    for i in range(cap + 100):
        obs.record_span("spam", ctx, t0=0.0, dur=0.0)
    assert len(obs.spans()) <= cap
    assert obs.trace.dropped() > 0


def test_chrome_export_shape(tmp_path):
    obs.enable_tracing()
    with obs.start_trace("root"):
        with obs.span("inner", step=3):
            pass
    path = str(tmp_path / "trace.json")
    doc = obs.export_chrome_trace(path)
    with open(path) as f:
        assert json.load(f) == doc
    evs = doc["traceEvents"]
    assert len(evs) == 2
    for ev in evs:
        assert ev["ph"] == "X"
        assert ev["args"]["trace"] and ev["args"]["span"]
    inner = [e for e in evs if e["name"] == "inner"][0]
    assert inner["args"]["parent"]
    assert inner["args"]["step"] == 3


# ---------------------------------------------------------------------
# the zero-call disabled contract (the PR-2 proof, for tracing)
# ---------------------------------------------------------------------

def _exercise_traced_paths(tmp_path, tag):
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data,
                           str(tmp_path / ("ck_%s" % tag)),
                           publish_every=1)
    ct.run_steps(1)
    reg = mx.serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1, 2),
                              max_wait_ms=2)
    watcher.poll_once()
    reg.infer("m", np.zeros(8, np.float32), timeout=30)
    reg.shutdown(drain=True)
    watcher.close()
    ct.close()


def test_tracing_disabled_makes_zero_trace_calls(tmp_path, monkeypatch):
    """The acceptance-criteria proof: with tracing off, the serving
    path, the training loop, the watcher, and checkpoint commit make
    ZERO calls into obs.trace -- each site costs its one module-flag
    check."""
    calls = []
    for name in ("begin_span", "end_span", "record_span",
                 "fresh_context"):
        orig = getattr(obs.trace, name)

        def counted(*a, _name=name, _orig=orig, **kw):
            calls.append(_name)
            return _orig(*a, **kw)

        monkeypatch.setattr(obs.trace, name, counted)
        if hasattr(obs, name):          # package-level re-exports
            monkeypatch.setattr(obs, name, counted)

    assert not obs.tracing_enabled()
    _exercise_traced_paths(tmp_path, "off")
    assert calls == [], "trace hooks fired while disabled: %r" % calls

    obs.enable_tracing()
    _exercise_traced_paths(tmp_path, "on")
    fired = set(calls)
    assert {"begin_span", "end_span", "record_span",
            "fresh_context"} <= fired, sorted(fired)


# ---------------------------------------------------------------------
# serving path spans
# ---------------------------------------------------------------------

def test_serving_spans_reconcile_with_counters():
    telemetry.enable()
    obs.enable_tracing()
    net = scenarios.make_mlp()
    reg = mx.serving.ModelRegistry(compile_cache=False)
    reg.register("m", block=net, input_shape=(8,), buckets=(1, 2, 4),
                 max_wait_ms=5)
    for _ in range(6):
        reg.infer("m", np.random.RandomState(0).rand(8)
                  .astype(np.float32), timeout=30)
    reg.shutdown(drain=True)
    by = _spans_by_name()
    requests = telemetry.counter("serving.requests").value
    batches = telemetry.counter("serving.batches").value
    assert len(by["serving.queue_wait"]) == requests == 6
    assert len(by["serving.request"]) == requests
    assert len(by["serving.respond"]) == requests
    for name in ("serving.batch", "serving.batch_assembly",
                 "serving.dispatch", "serving.device_get"):
        assert len(by[name]) == batches, name
    # dispatch + device_get span walls == the dispatch_time timer
    span_wall = sum(s["dur"] for s in by["serving.dispatch"]) \
        + sum(s["dur"] for s in by["serving.device_get"])
    assert abs(span_wall
               - telemetry.timer("serving.dispatch_time").sum) < 1e-6
    # fan-in links: every request root span is linked by some batch
    req_ids = {s["span"] for s in by["serving.request"]}
    linked = set()
    for b in by["serving.batch"]:
        linked.update(b.get("links", ()))
    assert linked == req_ids
    # queue/respond spans are children of their request root
    parents = {s["parent"] for s in by["serving.queue_wait"]}
    assert parents <= req_ids


def test_submit_joins_callers_trace():
    """A client that roots its own trace sees the request spans land in
    THAT trace -- end-to-end causality across the thread hop."""
    obs.enable_tracing()
    net = scenarios.make_mlp()
    reg = mx.serving.ModelRegistry(compile_cache=False)
    reg.register("m", block=net, input_shape=(8,), buckets=(1,),
                 max_wait_ms=2)
    with obs.start_trace("client") as rc:
        fut = reg.submit("m", np.zeros(8, np.float32), timeout=30)
        fut.result(timeout=30)
    reg.shutdown(drain=True)
    reqs = _spans_by_name()["serving.request"]
    assert any(s["trace"] == rc.trace_id for s in reqs)


# ---------------------------------------------------------------------
# training loop spans
# ---------------------------------------------------------------------

def test_training_loop_span_chain(tmp_path):
    obs.enable_tracing()
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data,
                           str(tmp_path / "ck"), publish_every=2)
    ct.run_steps(4)
    reg = mx.serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1, 2),
                              max_wait_ms=2)
    assert watcher.poll_once() == 4
    by = _spans_by_name()
    assert len(by["train.step"]) == 4
    assert len(by["train.publish"]) == 2
    assert len(by["checkpoint.commit"]) == 2
    assert len(by["serving.watcher.discover"]) == 1
    assert len(by["serving.swap"]) == 1
    # the causal chain: commit under publish under step; warm/install
    # under the watcher's swap span
    by_id = {s["span"]: s for s in obs.spans()}
    pub = by["train.publish"][0]
    assert by_id[pub["parent"]]["name"] == "train.step"
    com = by["checkpoint.commit"][0]
    assert by_id[com["parent"]]["name"] == "train.publish"
    for child in ("serving.register.warm", "serving.register.install"):
        sp = by[child][0]
        assert by_id[sp["parent"]]["name"] == "serving.swap"
        assert sp["trace"] == by["serving.swap"][0]["trace"]
    reg.shutdown(drain=True)
    watcher.close()
    ct.close()


def test_spans_stream_to_jsonl_and_summarize_folds(tmp_path):
    telemetry.enable()
    obs.enable_tracing()
    path = str(tmp_path / "run.jsonl")
    telemetry.attach_jsonl(path)
    with obs.start_trace("work"):
        with obs.span("phase"):
            pass
    telemetry.flush()
    agg = tcli.summarize_file(path)
    assert agg["spans"]["phase"]["count"] == 1
    assert agg["spans"]["work"]["count"] == 1
    assert agg["rank"] == 0
    # raw records carry the trace wiring + the rank tag
    recs = [json.loads(line) for line in open(path)]
    spans = [r for r in recs if r["kind"] == "span"]
    assert {s["name"] for s in spans} == {"work", "phase"}
    assert all("rank" in s and "trace" in s and "span" in s
               for s in spans)


# ---------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------

def test_flight_ring_roundtrip_and_wrap(tmp_path):
    path = str(tmp_path / "x.bbox")
    rec = flight.FlightRecorder(path, capacity=4096)
    for i in range(400):
        rec.note("spam", i=i)
    rec.sync()
    out = flight.read(path)
    assert out, "empty ring"
    assert len(out) < 400                      # wrapped: oldest gone
    assert out[-1]["payload"]["i"] == 399      # newest survives
    idx = [r["payload"]["i"] for r in out]
    assert idx == sorted(idx)                  # order preserved
    rec.close()


def test_flight_is_a_telemetry_sink(tmp_path):
    telemetry.enable()
    rec = flight.install(str(tmp_path / "x.bbox"), capacity=8192)
    telemetry.event("myevent").emit(k=1)
    telemetry.timer("mytimer").observe(0.001)
    rec.sync()
    names = [r.get("name") for r in flight.read(rec.path)]
    assert "myevent" in names and "mytimer" in names


def test_flight_survives_os_exit_kill(tmp_path):
    """The acceptance gate: a chaos KILL mid-commit leaves a readable
    dump whose final events include the injected fault and the
    in-flight trace -- proven with a REAL os._exit(137) subprocess."""
    bbox = str(tmp_path / "crash.bbox")
    code = (
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import chaos, obs, telemetry\n"
        "telemetry.enable(); obs.enable_tracing()\n"
        "obs.install_blackbox(%r, capacity=65536)\n"
        "mgr = mx.checkpoint.CheckpointManager(%r)\n"
        "chaos.arm(seed=0)\n"
        "chaos.on('checkpoint.commit.pre_manifest', nth=2,\n"
        "         action=chaos.KILL)\n"
        "mgr.save(1, {'blob': b'one'})\n"
        "mgr.save(2, {'blob': b'two'})\n"     # dies mid-commit
        "raise SystemExit('kill did not fire')\n"
        % (bbox, str(tmp_path / "ck")))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 137, (out.returncode, out.stderr[-500:])
    recs = flight.read(bbox)
    assert recs, "ring empty after KILL"
    last = recs[-1]
    assert last["name"] == "chaos.kill"
    assert last["payload"]["point"] == "checkpoint.commit.pre_manifest"
    # the in-flight trace: the kill landed inside checkpoint.commit
    assert last["payload"]["trace"] and last["payload"]["span"]
    names = [r.get("name") for r in recs]
    assert "chaos.inject" in names             # the injected fault event
    spans = [r for r in recs if r.get("kind") == "span"]
    assert any(s["name"] == "checkpoint.commit" for s in spans)


def test_sigusr2_snapshots_thread_stacks(tmp_path):
    rec = flight.install(str(tmp_path / "x.bbox"), capacity=131072)
    os.kill(os.getpid(), signal.SIGUSR2)
    deadline = time.time() + 10
    while time.time() < deadline:              # signal delivery is async
        recs = [r for r in flight.read(rec.path)
                if r.get("name") == "obs.sigusr2"]
        if recs:
            break
        time.sleep(0.01)  # mxlint: disable=sleep-poll
    assert recs, "SIGUSR2 left no stack snapshot"
    stacks = recs[-1]["payload"]["stacks"]
    assert any("MainThread" in label for label in stacks)
    assert "test_sigusr2" in "".join(stacks.values())


def test_preemption_signal_marks_blackbox(tmp_path):
    from mxnet_tpu import preemption
    rec = flight.install(str(tmp_path / "x.bbox"), capacity=65536)
    net = scenarios.make_mlp()
    handler = preemption.install(str(tmp_path / "job"), net,
                                 save_in_handler=True)
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 10
        marks = []
        while time.time() < deadline:
            marks = [r for r in flight.read(rec.path)
                     if r.get("name") == "preemption.signal"]
            if marks:
                break
            time.sleep(0.01)  # mxlint: disable=sleep-poll
        assert marks, "preemption handler left no blackbox mark"
        assert marks[-1]["payload"]["signum"] == int(signal.SIGTERM)
        assert handler.saved
    finally:
        handler.uninstall()


def test_flight_rejects_non_ring_and_tiny_capacity(tmp_path):
    bad = tmp_path / "notaring"
    bad.write_bytes(b"hello world, definitely not a ring header")
    with pytest.raises(mx.MXNetError):
        flight.read(str(bad))
    with pytest.raises(mx.MXNetError):
        flight.FlightRecorder(str(tmp_path / "t.bbox"), capacity=16)


# ---------------------------------------------------------------------
# introspection server + status board
# ---------------------------------------------------------------------

def _get(port, path):
    try:
        r = urllib.request.urlopen(
            "http://127.0.0.1:%d%s" % (port, path), timeout=10)
        return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def test_healthz_flips_on_watcher_suspension(tmp_path):
    port = obs.serve(0)
    code, body = _get(port, "/healthz")
    assert code == 200 and json.loads(body)["status"] == "READY"
    watcher = RegistryWatcher(mx.serving.ModelRegistry(
        compile_cache=False), "m", str(tmp_path / "ck"),
        scenarios.make_mlp(), input_shape=(8,))
    code, _ = _get(port, "/healthz")
    assert code == 200                        # healthy watcher: READY
    with watcher._lock:
        watcher._suspended = True             # the failure-budget state
    code, body = _get(port, "/healthz")
    body = json.loads(body)
    assert code == 503 and body["status"] == "NOT_READY"
    assert "watcher_suspended:m" in body["reasons"]
    watcher.close()


def test_healthz_flags_writer_failures_and_queue_saturation():
    telemetry.enable()
    ready, reasons = obs.status.health()
    assert ready
    telemetry.counter("checkpoint.write_failures").inc()
    ready, reasons = obs.status.health()
    assert not ready and reasons == ["checkpoint_write_failures:1"]
    telemetry.registry().clear()
    reg = mx.serving.ModelRegistry(compile_cache=False)
    reg.register("m", block=scenarios.make_mlp(), input_shape=(8,),
                 buckets=(1,), max_queue=0)   # always saturated
    ready, reasons = obs.status.health()
    assert not ready and "queue_saturated:m" in reasons
    reg.shutdown(drain=True)


def test_statusz_and_metrics_endpoints(tmp_path):
    telemetry.enable()
    port = obs.serve(0)
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data,
                           str(tmp_path / "ck"), publish_every=1)
    ct.run_steps(1)
    reg = mx.serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1, 2),
                              max_wait_ms=2)
    assert watcher.poll_once() == 1
    code, body = _get(port, "/statusz")
    st = json.loads(body)
    assert code == 200
    assert st["served_step"] == 1 and st["published_step"] == 1
    assert st["watchers"][0]["name"] == "m"
    assert st["trainers"][0]["step"] == 1
    assert st["servables"][0]["name"] == "m"
    assert st["heartbeats"]                   # the loop beat
    assert st["swap_history"][-1]["ok"] is True
    code, prom = _get(port, "/metrics")
    assert code == 200
    assert b"mxnet_tpu_serving_swaps 1" in prom
    code, _ = _get(port, "/nope")
    assert code == 404
    reg.shutdown(drain=True)
    watcher.close()
    ct.close()


# ---------------------------------------------------------------------
# satellite: watcher suspension is an alertable event
# ---------------------------------------------------------------------

def test_watcher_suspension_emits_terminal_event(tmp_path):
    from mxnet_tpu import chaos
    telemetry.enable()
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data,
                           str(tmp_path / "ck"), publish_every=1)
    ct.run_steps(1)
    reg = mx.serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1,),
                              swap_retries=0, failure_budget=1)
    with chaos.scenario(seed=0):
        chaos.on("serving.swap", action=chaos.RAISE)
        with pytest.warns(RuntimeWarning):
            assert watcher.poll_once() is None
    assert watcher.suspended
    assert telemetry.counter(
        "serving.watcher_suspensions").value == 1
    ev = telemetry.event("serving.watcher_suspended").recent[-1]
    assert ev["model"] == "m" and ev["step"] == 1 and ev["budget"] == 1
    watcher.close()
    ct.close()


# ---------------------------------------------------------------------
# satellite: bench env-health lands in telemetry
# ---------------------------------------------------------------------

def test_bench_env_health_records_gauges():
    import bench
    telemetry.enable()
    flag = bench._mark_env_health({"dispatch_roundtrip_us": 123.4,
                                   "h2d_mb_per_s": 55.0})
    assert flag is False
    assert telemetry.gauge("env.dispatch_roundtrip_us").value == 123.4
    assert telemetry.gauge("env.h2d_mb_per_s").value == 55.0
    ev = telemetry.event("env.health").recent[-1]
    assert ev["dispatch_roundtrip_us"] == 123.4
    # a collapsed tunnel flips degraded AND still records the number
    flag = bench._mark_env_health({"dispatch_roundtrip_us": 90000.0})
    assert flag is True
    assert telemetry.gauge("env.dispatch_roundtrip_us").value == 90000.0
    # telemetry off: the probe marks the flag with zero instrument calls
    telemetry.disable()
    telemetry.registry().clear()
    assert bench._mark_env_health({"dispatch_roundtrip_us": 1.0}) is False
    assert telemetry.registry().get("env.dispatch_roundtrip_us") is None


# ---------------------------------------------------------------------
# multi-rank summarize + skew (satellite + tentpole part 4)
# ---------------------------------------------------------------------

def _rank_file(tmp_path, rank, step_s, n=5):
    path = str(tmp_path / ("r%d.jsonl" % rank))
    sink = telemetry.JsonlSink(path, rank=rank)
    reg = telemetry.Registry()
    reg.attach(sink)
    t = reg.timer("trainer.step_time")
    for _ in range(n):
        t.observe(step_s)
    reg.flush()
    sink.close()
    return path


def test_jsonl_records_carry_rank_tag(tmp_path):
    path = _rank_file(tmp_path, 3, 0.01)
    recs = [json.loads(line) for line in open(path)]
    assert recs and all(r["rank"] == 3 for r in recs)
    assert tcli.summarize_file(path)["rank"] == 3


def test_multi_rank_skew_and_straggler_flag(tmp_path):
    p0 = _rank_file(tmp_path, 0, 0.010)
    p1 = _rank_file(tmp_path, 1, 0.011)
    p2 = _rank_file(tmp_path, 2, 0.030)       # the straggler
    agg = tcli.summarize_files([p0, p1, p2])
    assert [r["rank"] for r in agg["ranks"]] == [0, 1, 2]
    sk = agg["skew"]
    assert sk["straggler"] and sk["straggler_ranks"] == [2]
    assert sk["max_over_median"] == pytest.approx(30 / 11, rel=1e-3)
    # balanced ranks: no straggler
    agg = tcli.summarize_files([p0, p1])
    assert not agg["skew"]["straggler"]
    assert agg["skew"]["max_over_median"] == pytest.approx(1.1,
                                                           rel=1e-3)


def test_summarize_multi_file_cli_contract(tmp_path, capsys):
    p0 = _rank_file(tmp_path, 0, 0.010)
    p1 = _rank_file(tmp_path, 1, 0.030)
    assert tcli.main(["summarize", p0, p1, "--json"]) == 0
    agg = json.loads(capsys.readouterr().out)
    assert agg["skew"]["straggler_ranks"] == [1]
    assert tcli.main(["summarize", p0, p1]) == 0
    out = capsys.readouterr().out
    assert "STRAGGLER" in out and "rank" in out
    # a missing rank file fails the whole summarize (exit 1)
    assert tcli.main(["summarize", p0,
                      str(tmp_path / "missing.jsonl")]) == 1


# ---------------------------------------------------------------------
# satellite: blackbox CLI exit-code contract (mxlint convention)
# ---------------------------------------------------------------------

def test_blackbox_cli_exit_codes(tmp_path, capsys):
    path = str(tmp_path / "x.bbox")
    rec = flight.FlightRecorder(path, capacity=8192)
    rec.note("chaos.kill", point="p")
    rec.sync()
    assert tcli.main(["blackbox", path]) == 0          # success
    assert "chaos.kill" in capsys.readouterr().out
    assert tcli.main(["blackbox", path, "--json"]) == 0
    recs = json.loads(capsys.readouterr().out)
    assert recs[-1]["name"] == "chaos.kill"
    rec.close()
    # missing file -> 1
    assert tcli.main(["blackbox", str(tmp_path / "nope.bbox")]) == 1
    # a ring with zero records -> 1 (nothing to render is a failed gate)
    empty = flight.FlightRecorder(str(tmp_path / "e.bbox"),
                                  capacity=8192)
    empty.close()
    assert tcli.main(["blackbox", str(tmp_path / "e.bbox")]) == 1
    # not a ring at all -> 1, not a traceback
    bad = tmp_path / "garbage"
    bad.write_bytes(b"x" * 64)
    assert tcli.main(["blackbox", str(bad)]) == 1
    # usage errors -> 2
    assert tcli.main([]) == 2
    capsys.readouterr()


# ---------------------------------------------------------------------
# satellite: the generated instrument index cannot drift
# ---------------------------------------------------------------------

def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_observability_doc_matches_generator():
    path = os.path.join(_repo_root(), "docs", "observability.md")
    with open(path) as f:
        text = f.read()
    begin, end = thooks._INDEX_BEGIN, thooks._INDEX_END
    assert begin in text and end in text
    inside = text.split(begin, 1)[1].split(end, 1)[0]
    assert inside.strip("\n") == thooks.instrument_index_md().strip("\n"), \
        "docs/observability.md instrument index is stale -- run " \
        "python -c 'from mxnet_tpu.telemetry import hooks; " \
        "hooks.update_observability_doc()'"


def test_every_hook_literal_is_catalogued():
    """AST sweep of telemetry/hooks.py: every literal instrument name
    passed to reg.counter/gauge/timer/event must appear in INSTRUMENTS
    (dynamic `prefix + key` families must have a `<placeholder>` row),
    so a new hook cannot ship unindexed."""
    catalogued = {ii.name for ii in thooks.INSTRUMENTS}
    prefixes = {ii.name.split("<", 1)[0] for ii in thooks.INSTRUMENTS
                if "<" in ii.name}
    src = open(thooks.__file__.rstrip("c")).read()
    tree = ast.parse(src)
    checked = 0
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "timer",
                                       "event")
                and node.args):
            continue
        arg = node.args[0]
        # take only the NAME positions: a bare literal, both arms of a
        # conditional, or the literal prefix of a `"x." + key` concat
        if isinstance(arg, ast.IfExp):
            cands = [arg.body, arg.orelse]
        elif isinstance(arg, ast.BinOp):
            cands = [arg.left]
        else:
            cands = [arg]
        for const in cands:
            if not (isinstance(const, ast.Constant)
                    and isinstance(const.value, str)):
                continue
            name = const.value
            if "%" in name:                   # e.g. "checkpoint.%ss"
                continue
            checked += 1
            if name.endswith("."):            # dynamic family prefix
                assert name in prefixes or any(
                    c.startswith(name) for c in catalogued), \
                    "uncatalogued instrument family %r" % name
            else:
                assert name in catalogued, \
                    "uncatalogued instrument %r" % name
    assert checked > 60, "AST sweep found too few instruments (%d)" \
        % checked


def test_kind_consistency_between_catalogue_and_doc():
    md = thooks.instrument_index_md()
    for ii in thooks.INSTRUMENTS:
        assert "`%s` | %s" % (ii.name, ii.kind) in md
        assert ii.kind in ("counter", "gauge", "timer", "event")


# ---------------------------------------------------------------------
# wiring: env vars + feature row
# ---------------------------------------------------------------------

def test_obs_env_vars_registered():
    desc = mx.env.describe()
    for var in ("MXNET_TPU_OBS_TRACE", "MXNET_TPU_OBS_BLACKBOX",
                "MXNET_TPU_OBS_BLACKBOX_KB", "MXNET_TPU_OBS_PORT"):
        assert var in desc, var
    assert mx.env.get("MXNET_TPU_OBS_PORT") == 0
    assert mx.env.get("MXNET_TPU_OBS_BLACKBOX_KB") == 256


def test_obs_trace_feature_row():
    assert not mx.runtime.Features().is_enabled("OBS_TRACE")
    obs.enable_tracing()
    assert mx.runtime.Features().is_enabled("OBS_TRACE")
    obs.disable_tracing()
    assert not mx.runtime.Features().is_enabled("OBS_TRACE")
