"""Regression tests for round-2 correctness fixes (ADVICE.md round 1)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError


def test_symbol_attr_parse_no_eval():
    """Attrs from -symbol.json must not hit eval(): a code-exec payload
    parses as a plain string instead of executing."""
    from mxnet_tpu.symbol.symbol import _parse_attr_value
    payload = "().__class__.__base__.__subclasses__()"
    assert _parse_attr_value(payload) == payload
    assert _parse_attr_value("(1, 2)") == (1, 2)
    assert _parse_attr_value("True") is True
    assert _parse_attr_value("1.5") == 1.5
    assert _parse_attr_value("None") is None


def test_deep_toposort_no_recursion_error():
    """~1100 sequential recorded ops (above the default Python recursion
    limit) must not blow the stack."""
    x = mx.nd.ones((4,))
    x.attach_grad()
    with autograd.record():
        y = x
        for _ in range(1100):
            y = y + 0.001
        loss = (y * y).sum()
    loss.backward()
    assert np.isfinite(x.grad.asnumpy()).all()


def test_deep_symbol_topo():
    import mxnet_tpu.symbol as sym
    s = sym.var("x")
    for _ in range(1100):
        s = s + 1.0
    assert len(s.list_arguments()) == 1


def test_ctc_loss_respects_pred_lengths():
    """Loss for a padded sequence must equal the loss for the unpadded
    sequence (the alpha recursion must freeze past pred_length)."""
    loss_fn = gluon.loss.CTCLoss()
    B, T, V, L = 2, 8, 5, 3
    rng = np.random.RandomState(0)
    logits_short = rng.randn(B, T, V).astype(np.float32)
    labels = np.array([[1, 2, 3], [2, 1, 4]], np.float32)
    # pad time dim with garbage; pred_lengths masks it out
    pad = rng.randn(B, 4, V).astype(np.float32) * 10
    logits_padded = np.concatenate([logits_short, pad], axis=1)
    l_short = loss_fn(mx.nd.array(logits_short), mx.nd.array(labels))
    l_padded = loss_fn(mx.nd.array(logits_padded), mx.nd.array(labels),
                       mx.nd.array([T, T]))
    np.testing.assert_allclose(l_short.asnumpy(), l_padded.asnumpy(),
                               rtol=1e-4)


def test_recordio_chunked_roundtrip(tmp_path):
    """Multi-chunk framing: payloads > max chunk split and re-assemble.

    Uses a small chunk bound via monkeypatch so the test doesn't need a
    512MB record to exercise the cflag 1/2/3 path.
    """
    from mxnet_tpu import recordio

    path = str(tmp_path / "t.rec")
    orig = recordio.MXRecordIO._MAX_CHUNK
    recordio.MXRecordIO._MAX_CHUNK = 100
    try:
        w = recordio.MXRecordIO(path, "w")
        big = bytes(range(256)) * 3  # 768 bytes -> 8 chunks
        small = b"hello"
        w.write(big)
        w.write(small)
        w.close()
        r = recordio.MXRecordIO(path, "r")
        assert r.read() == big
        assert r.read() == small
        assert r.read() is None
        r.close()
    finally:
        recordio.MXRecordIO._MAX_CHUNK = orig


def test_dataloader_timeout_raises():
    class SlowDataset(gluon.data.Dataset):
        def __len__(self):
            return 4

        def __getitem__(self, i):
            import time
            time.sleep(10)
            return np.zeros(2, np.float32)

    loader = gluon.data.DataLoader(SlowDataset(), batch_size=2,
                                   num_workers=1, timeout=0.5)
    with pytest.raises(MXNetError):
        next(iter(loader))


def test_dataloader_bounded_prefetch_completes():
    data = np.arange(400, dtype=np.float32).reshape(100, 4)
    ds = gluon.data.ArrayDataset(data)
    loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=2)
    seen = [b.asnumpy() for b in loader]
    assert len(seen) == 25
    np.testing.assert_allclose(np.concatenate(seen), data)
