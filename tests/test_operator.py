"""Operator numeric correctness (reference:
``tests/python/unittest/test_operator.py`` -- numpy-reference checks +
finite-difference gradient checks via the ported test_utils contract)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import (assert_almost_equal, check_numeric_gradient,
                                  check_consistency)


def test_elemwise_vs_numpy():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(3, 4).astype(np.float32)
    na, nb = mx.nd.array(a), mx.nd.array(b)
    assert_almost_equal(mx.nd.elemwise_add(na, nb), a + b)
    assert_almost_equal(mx.nd.broadcast_mul(na, nb), a * b)
    assert_almost_equal(mx.nd.maximum(na, nb), np.maximum(a, b))
    assert_almost_equal(mx.nd.exp(na), np.exp(a), rtol=1e-5)
    assert_almost_equal(mx.nd.sigmoid(na), 1 / (1 + np.exp(-a)), rtol=1e-5)
    assert_almost_equal(mx.nd.relu(na), np.maximum(a, 0))
    assert_almost_equal(mx.nd.tanh(na), np.tanh(a), rtol=1e-5)
    assert_almost_equal(mx.nd.square(na), a * a, rtol=1e-5)
    assert_almost_equal(mx.nd.abs(na), np.abs(a))


def test_broadcasting():
    a = np.random.randn(3, 1, 4).astype(np.float32)
    b = np.random.randn(1, 5, 4).astype(np.float32)
    assert_almost_equal(mx.nd.broadcast_add(mx.nd.array(a), mx.nd.array(b)), a + b)


def test_dot():
    a = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4, 5).astype(np.float32)
    assert_almost_equal(mx.nd.dot(mx.nd.array(a), mx.nd.array(b)), a @ b, rtol=1e-4)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a), mx.nd.array(b.T), transpose_b=True), a @ b,
        rtol=1e-4)
    assert_almost_equal(
        mx.nd.dot(mx.nd.array(a.T), mx.nd.array(b), transpose_a=True), a @ b,
        rtol=1e-4)


def test_batch_dot():
    a = np.random.randn(2, 3, 4).astype(np.float32)
    b = np.random.randn(2, 4, 5).astype(np.float32)
    assert_almost_equal(mx.nd.batch_dot(mx.nd.array(a), mx.nd.array(b)),
                        a @ b, rtol=1e-4)


def test_fully_connected():
    x = np.random.randn(2, 5).astype(np.float32)
    w = np.random.randn(3, 5).astype(np.float32)
    b = np.random.randn(3).astype(np.float32)
    out = mx.nd.FullyConnected(mx.nd.array(x), mx.nd.array(w), mx.nd.array(b),
                               num_hidden=3)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4)


def test_convolution_identity():
    # 1x1 identity kernel must reproduce the input
    x = np.random.randn(1, 2, 5, 5).astype(np.float32)
    w = np.zeros((2, 2, 1, 1), np.float32)
    w[0, 0] = w[1, 1] = 1
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), mx.nd.zeros((2,)),
                            kernel=(1, 1), num_filter=2)
    assert_almost_equal(out, x, rtol=1e-5)


def test_convolution_vs_manual():
    x = np.random.randn(2, 3, 6, 6).astype(np.float32)
    w = np.random.randn(4, 3, 3, 3).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), None,
                            kernel=(3, 3), num_filter=4, no_bias=True).asnumpy()
    assert out.shape == (2, 4, 4, 4)
    # brute-force reference at one location
    manual = (x[0, :, 0:3, 0:3] * w[1]).sum()
    assert abs(out[0, 1, 0, 0] - manual) < 1e-3


def test_conv_grouped_strided():
    x = np.random.randn(1, 4, 8, 8).astype(np.float32)
    w = np.random.randn(4, 2, 3, 3).astype(np.float32)
    out = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), None, kernel=(3, 3),
                            num_filter=4, num_group=2, stride=(2, 2),
                            pad=(1, 1), no_bias=True)
    assert out.shape == (1, 4, 4, 4)


def test_deconvolution_shape():
    x = mx.nd.random.normal(shape=(1, 3, 4, 4))
    w = mx.nd.random.normal(shape=(3, 2, 3, 3))
    out = mx.nd.Deconvolution(x, w, None, kernel=(3, 3), num_filter=2,
                              stride=(2, 2), no_bias=True)
    assert out.shape == (1, 2, 9, 9)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    mp = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                       pool_type="max")
    assert_almost_equal(mp, [[[[5, 7], [13, 15]]]])
    ap = mx.nd.Pooling(mx.nd.array(x), kernel=(2, 2), stride=(2, 2),
                       pool_type="avg")
    assert_almost_equal(ap, [[[[2.5, 4.5], [10.5, 12.5]]]])
    gp = mx.nd.Pooling(mx.nd.array(x), global_pool=True, pool_type="max")
    assert gp.shape == (1, 1, 1, 1) and gp.asscalar() == 15


def test_batchnorm_train_stats():
    x = np.random.randn(8, 3, 4, 4).astype(np.float32) * 2 + 1
    g = np.ones(3, np.float32)
    b = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    out, nm, nv = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b),
                                  mx.nd.array(mm), mx.nd.array(mv),
                                  fix_gamma=False, training=True, momentum=0.9)
    o = out.asnumpy()
    assert abs(o.mean(axis=(0, 2, 3))).max() < 1e-4
    assert abs(o.std(axis=(0, 2, 3)) - 1).max() < 1e-3
    expect_m = 0.1 * x.mean(axis=(0, 2, 3))
    assert_almost_equal(nm, expect_m, rtol=1e-3, atol=1e-5)


def test_batchnorm_inference_uses_moving():
    x = np.random.randn(4, 2).astype(np.float32)
    out, _, _ = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.ones((2,)), mx.nd.zeros((2,)),
                                mx.nd.array([1., 2.]), mx.nd.array([4., 9.]),
                                fix_gamma=False, training=False, axis=1)
    expect = (x - [1, 2]) / np.sqrt(np.array([4, 9]) + 1e-5)
    assert_almost_equal(out, expect, rtol=1e-4)


def test_layernorm():
    x = np.random.randn(4, 10).astype(np.float32)
    g = np.random.rand(10).astype(np.float32)
    b = np.random.randn(10).astype(np.float32)
    out = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g), mx.nd.array(b))
    mu = x.mean(-1, keepdims=True)
    sd = np.sqrt(x.var(-1, keepdims=True) + 1e-5)
    assert_almost_equal(out, (x - mu) / sd * g + b, rtol=1e-4, atol=1e-5)


def test_softmax():
    x = np.random.randn(3, 5).astype(np.float32)
    s = mx.nd.softmax(mx.nd.array(x)).asnumpy()
    e = np.exp(x - x.max(-1, keepdims=True))
    assert_almost_equal(s, e / e.sum(-1, keepdims=True), rtol=1e-5)
    assert_almost_equal(mx.nd.log_softmax(mx.nd.array(x)),
                        np.log(e / e.sum(-1, keepdims=True)), rtol=1e-4, atol=1e-5)


def test_softmax_output_grad():
    x = np.random.randn(4, 5).astype(np.float32)
    label = np.array([0, 2, 4, 1], np.float32)
    nx = mx.nd.array(x)
    nx.attach_grad()
    with autograd.record():
        prob = mx.nd.SoftmaxOutput(nx, mx.nd.array(label))
    prob.backward()
    e = np.exp(x - x.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    onehot = np.eye(5, dtype=np.float32)[label.astype(int)]
    assert_almost_equal(nx.grad, p - onehot, rtol=1e-4, atol=1e-5)


def test_take_embedding():
    w = np.random.randn(10, 4).astype(np.float32)
    idx = np.array([1, 3, 1], np.float32)
    out = mx.nd.Embedding(mx.nd.array(idx), mx.nd.array(w), input_dim=10,
                          output_dim=4)
    assert_almost_equal(out, w[[1, 3, 1]])
    out2 = mx.nd.take(mx.nd.array(w), mx.nd.array(idx))
    assert_almost_equal(out2, w[[1, 3, 1]])


def test_embedding_grad_scatter():
    w = mx.nd.array(np.zeros((5, 2), np.float32) + 1)
    idx = mx.nd.array([0, 0, 3], dtype="int32")
    w.attach_grad()
    with autograd.record():
        out = mx.nd.Embedding(idx, w, input_dim=5, output_dim=2)
        loss = out.sum()
    loss.backward()
    g = w.grad.asnumpy()
    assert g[0].tolist() == [2, 2]  # two gathers of row 0
    assert g[3].tolist() == [1, 1]
    assert g[1].tolist() == [0, 0]


def test_pick_onehot_gathernd():
    x = np.random.randn(3, 4).astype(np.float32)
    idx = np.array([0, 2, 3], np.float32)
    assert_almost_equal(mx.nd.pick(mx.nd.array(x), mx.nd.array(idx)),
                        x[np.arange(3), idx.astype(int)])
    oh = mx.nd.one_hot(mx.nd.array(idx), depth=4).asnumpy()
    assert (oh.argmax(-1) == idx).all()
    ind = mx.nd.array(np.array([[0, 1], [1, 2]], np.float32))
    assert_almost_equal(mx.nd.gather_nd(mx.nd.array(x), ind), x[[0, 1], [1, 2]])


def test_slicing_ops():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    nx = mx.nd.array(x)
    assert_almost_equal(mx.nd.slice(nx, begin=(0, 1), end=(2, 3)), x[0:2, 1:3])
    assert_almost_equal(mx.nd.slice_axis(nx, axis=2, begin=1, end=3), x[:, :, 1:3])
    y = np.zeros((2, 2, 2), np.float32)
    assert mx.nd.slice_like(nx, mx.nd.array(y)).shape == (2, 2, 2)


def test_ordering():
    x = np.array([[3., 1., 2.], [0., 5., 4.]], np.float32)
    nx = mx.nd.array(x)
    assert_almost_equal(mx.nd.sort(nx), np.sort(x))
    assert_almost_equal(mx.nd.sort(nx, is_ascend=False), -np.sort(-x))
    assert mx.nd.argsort(nx).asnumpy()[0].tolist() == [1, 2, 0]
    vals, idx = mx.nd.topk(nx, k=2, ret_typ="both")
    assert vals.asnumpy()[0].tolist() == [3, 2]
    assert idx.asnumpy()[0].tolist() == [0, 2]


def test_topk_grad_not_needed():
    x = mx.nd.array([[3., 1., 2.]])
    out = mx.nd.topk(x, k=1, ret_typ="value")
    assert out.asscalar() == 3


def test_where_clip():
    c = mx.nd.array([1., 0., 1.])
    x = mx.nd.array([1., 2., 3.])
    y = mx.nd.array([10., 20., 30.])
    assert mx.nd.where(c, x, y).asnumpy().tolist() == [1, 20, 3]
    assert mx.nd.clip(x, 1.5, 2.5).asnumpy().tolist() == [1.5, 2, 2.5]


def test_sequence_ops():
    x = np.arange(24, dtype=np.float32).reshape(4, 2, 3)  # (T,N,C)
    lens = np.array([2, 3], np.float32)
    masked = mx.nd.SequenceMask(mx.nd.array(x), mx.nd.array(lens), value=-1.0,
                                use_sequence_length=True)
    m = masked.asnumpy()
    assert (m[2, 0] == -1).all() and (m[2, 1] != -1).all() and (m[3, 1] == -1).all()
    last = mx.nd.SequenceLast(mx.nd.array(x), mx.nd.array(lens),
                              use_sequence_length=True)
    assert_almost_equal(last, np.stack([x[1, 0], x[2, 1]]))
    # default (no lengths) is identity / plain last / plain reverse
    assert_almost_equal(mx.nd.SequenceMask(mx.nd.array(x)), x)
    assert_almost_equal(mx.nd.SequenceLast(mx.nd.array(x)), x[-1])
    assert_almost_equal(mx.nd.SequenceReverse(mx.nd.array(x)), x[::-1])


def test_gradient_elemwise():
    check_numeric_gradient(lambda a, b: (a * b + a).sum(),
                           [np.random.randn(3, 3).astype(np.float32),
                            np.random.randn(3, 3).astype(np.float32)])


def test_gradient_dense():
    x = np.random.randn(2, 4).astype(np.float32)
    w = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(3).astype(np.float32)
    check_numeric_gradient(
        lambda xx, ww, bb: mx.nd.FullyConnected(xx, ww, bb, num_hidden=3).sum(),
        [x, w, b])


def test_gradient_conv():
    x = np.random.randn(1, 2, 5, 5).astype(np.float32)
    w = np.random.randn(2, 2, 3, 3).astype(np.float32) * 0.5
    check_numeric_gradient(
        lambda xx, ww: mx.nd.Convolution(xx, ww, None, kernel=(3, 3),
                                         num_filter=2, no_bias=True).sum(),
        [x, w], rtol=2e-2, atol=1e-3)


def test_gradient_softmax_ce():
    x = np.random.randn(3, 4).astype(np.float32)
    check_numeric_gradient(
        lambda xx: -(mx.nd.log_softmax(xx) *
                     mx.nd.one_hot(mx.nd.array([0., 1., 2.]), depth=4)).sum(),
        [x], rtol=2e-2)


def test_check_consistency_cpu_tpu():
    # On CPU-only runs this degenerates to a single-context check.
    check_consistency("dot", [np.random.randn(3, 4).astype(np.float32),
                              np.random.randn(4, 2).astype(np.float32)])


def test_activation_variants():
    x = np.random.randn(4, 4).astype(np.float32)
    nx = mx.nd.array(x)
    assert_almost_equal(mx.nd.Activation(nx, act_type="relu"), np.maximum(x, 0))
    assert_almost_equal(mx.nd.Activation(nx, act_type="softrelu"),
                        np.log1p(np.exp(x)), rtol=1e-4, atol=1e-5)
    assert_almost_equal(mx.nd.LeakyReLU(nx, act_type="leaky", slope=0.1),
                        np.where(x > 0, x, 0.1 * x), rtol=1e-5)
    elu = mx.nd.LeakyReLU(nx, act_type="elu", slope=1.0).asnumpy()
    assert_almost_equal(elu, np.where(x > 0, x, np.expm1(x)), rtol=1e-4, atol=1e-6)


def test_random_ops():
    u = mx.nd.random.uniform(0, 1, shape=(1000,))
    assert 0 <= u.asnumpy().min() and u.asnumpy().max() <= 1
    assert abs(u.asnumpy().mean() - 0.5) < 0.05
    n = mx.nd.random.normal(0, 1, shape=(2000,))
    assert abs(n.asnumpy().mean()) < 0.1
    r = mx.nd.random.randint(0, 10, shape=(100,))
    assert r.dtype == np.int32 and r.asnumpy().max() < 10
    mx.random.seed(42)
    a = mx.nd.random.uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = mx.nd.random.uniform(shape=(5,)).asnumpy()
    assert (a == b).all()


def test_rnn_lstm_shapes_and_grad():
    from mxnet_tpu.ops.nn import rnn_param_size
    T, N, I, H = 4, 2, 3, 5
    ps = rnn_param_size("lstm", I, H, 2, True)
    data = mx.nd.random.normal(shape=(T, N, I))
    params = mx.nd.random.normal(shape=(ps,), scale=0.1)
    h0 = mx.nd.zeros((4, N, H))
    c0 = mx.nd.zeros((4, N, H))
    params.attach_grad()
    with autograd.record():
        out, hy, cy = mx.nd.RNN(data, params, h0, c0, state_size=H,
                                num_layers=2, bidirectional=True, mode="lstm")
        loss = out.sum()
    loss.backward()
    assert out.shape == (T, N, 2 * H)
    assert hy.shape == (4, N, H)
    assert float(mx.nd.abs(params.grad).sum().asscalar()) > 0


def test_optimizer_ops():
    w = np.random.randn(5).astype(np.float32)
    g = np.random.randn(5).astype(np.float32)
    out = mx.nd.sgd_update(mx.nd.array(w), mx.nd.array(g), lr=0.1)
    assert_almost_equal(out, w - 0.1 * g, rtol=1e-5)
    mom = np.zeros(5, np.float32)
    w2, m2 = mx.nd.sgd_mom_update(mx.nd.array(w), mx.nd.array(g), mx.nd.array(mom),
                                  lr=0.1, momentum=0.9)
    assert_almost_equal(m2, -0.1 * g, rtol=1e-5)
    assert_almost_equal(w2, w - 0.1 * g, rtol=1e-5)
    m = np.zeros(5, np.float32)
    v = np.zeros(5, np.float32)
    w3, m3, v3 = mx.nd.adam_update(mx.nd.array(w), mx.nd.array(g), mx.nd.array(m),
                                   mx.nd.array(v), lr=0.01)
    assert_almost_equal(m3, 0.1 * g, rtol=1e-5)


def test_all_finite():
    good = mx.nd.ones((3,))
    bad = mx.nd.array([1.0, np.inf, 0.0])
    assert mx.nd.multi_all_finite(good).asscalar() == 1.0
    assert mx.nd.multi_all_finite(good, bad).asscalar() == 0.0


def test_cast_bf16():
    x = mx.nd.ones((4,))
    b = mx.nd.Cast(x, dtype="bfloat16")
    assert str(b.dtype) == "bfloat16"
    back = mx.nd.Cast(b, dtype="float32")
    assert back.asnumpy().tolist() == [1, 1, 1, 1]


def test_batchnorm_large_offset_stability():
    """The fused one-pass moments are shifted by moving_mean so a
    large common offset (|mean| >> std) cannot catastrophically cancel
    the variance in fp32 (advisor r4: the naive E[x^2]-E[x]^2 form
    clamps var to 0 here and scales by 1/sqrt(eps))."""
    off = 1.0e4
    x = (np.random.randn(8, 3, 6, 6) + off).astype(np.float32)
    g = np.ones(3, np.float32)
    b = np.zeros(3, np.float32)
    mm = np.full(3, off, np.float32)    # steady-state moving mean
    mv = np.ones(3, np.float32)
    out, _, _ = mx.nd.BatchNorm(mx.nd.array(x), mx.nd.array(g),
                                mx.nd.array(b), mx.nd.array(mm),
                                mx.nd.array(mv), fix_gamma=False,
                                training=True)
    o = out.asnumpy()
    assert abs(o.mean(axis=(0, 2, 3))).max() < 1e-2
    assert abs(o.std(axis=(0, 2, 3)) - 1).max() < 0.05
