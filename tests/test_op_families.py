"""Linalg / control-flow / contrib op-family tests (reference:
``test_operator.py`` linalg cases, ``test_contrib_control_flow.py``,
``test_quantization.py``, bounding-box tests)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

_R = np.random.RandomState(0)


# ----------------------------------------------------------------------
# linalg
# ----------------------------------------------------------------------

def _spd(n=4):
    a = _R.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


def test_linalg_gemm_family():
    A = _R.randn(3, 4).astype(np.float32)
    B = _R.randn(4, 5).astype(np.float32)
    C = _R.randn(3, 5).astype(np.float32)
    out = mx.nd.linalg_gemm(mx.nd.array(A), mx.nd.array(B),
                            mx.nd.array(C), alpha=2.0, beta=0.5)
    np.testing.assert_allclose(out.asnumpy(), 2 * A @ B + 0.5 * C,
                               rtol=1e-5)
    out2 = mx.nd.linalg_gemm2(mx.nd.array(A), mx.nd.array(A),
                              transpose_b=True)
    np.testing.assert_allclose(out2.asnumpy(), A @ A.T, rtol=1e-5)


def test_linalg_cholesky_chain():
    S = _spd()
    L = mx.nd.linalg_potrf(mx.nd.array(S))
    np.testing.assert_allclose(L.asnumpy() @ L.asnumpy().T, S,
                               rtol=1e-4, atol=1e-4)
    inv = mx.nd.linalg_potri(L)
    np.testing.assert_allclose(inv.asnumpy(), np.linalg.inv(S),
                               rtol=1e-3, atol=1e-3)
    sld = mx.nd.linalg_sumlogdiag(L)
    assert abs(2 * float(sld.asscalar())
               - np.linalg.slogdet(S)[1]) < 1e-3


def test_linalg_trsm_trmm():
    S = _spd()
    L = np.linalg.cholesky(S).astype(np.float32)
    B = _R.randn(4, 3).astype(np.float32)
    X = mx.nd.linalg_trsm(mx.nd.array(L), mx.nd.array(B))
    np.testing.assert_allclose(L @ X.asnumpy(), B, rtol=1e-4, atol=1e-4)
    M = mx.nd.linalg_trmm(mx.nd.array(L), mx.nd.array(B))
    np.testing.assert_allclose(M.asnumpy(), np.tril(L) @ B, rtol=1e-4)


def test_linalg_decompositions():
    S = _spd()
    UT, w = mx.nd.linalg_syevd(mx.nd.array(S))
    recon = UT.asnumpy().T @ np.diag(w.asnumpy()) @ UT.asnumpy()
    np.testing.assert_allclose(recon, S, rtol=1e-3, atol=1e-3)
    sign, logabs = mx.nd.linalg_slogdet(mx.nd.array(S))
    assert sign.asscalar() == 1.0
    d = mx.nd.linalg_det(mx.nd.array(S))
    np.testing.assert_allclose(d.asscalar(), np.linalg.det(S), rtol=1e-3)
    inv = mx.nd.linalg_inverse(mx.nd.array(S))
    np.testing.assert_allclose(inv.asnumpy() @ S, np.eye(4), atol=1e-3)


def test_linalg_grad_flows():
    S = _spd()
    x = mx.nd.array(S)
    x.attach_grad()
    with autograd.record():
        y = mx.nd.linalg_sumlogdiag(mx.nd.linalg_potrf(x))
    y.backward()
    # d/dA 0.5*logdet(A) = 0.5*A^-1 for SPD A
    np.testing.assert_allclose(x.grad.asnumpy(),
                               0.5 * np.linalg.inv(S), rtol=1e-3,
                               atol=1e-4)


def test_moments():
    x = _R.randn(4, 5).astype(np.float32)
    mean, var = mx.nd.moments(mx.nd.array(x), axes=(1,))
    np.testing.assert_allclose(mean.asnumpy(), x.mean(1), rtol=1e-5)
    np.testing.assert_allclose(var.asnumpy(), x.var(1), rtol=1e-4)


# ----------------------------------------------------------------------
# control flow
# ----------------------------------------------------------------------

def test_foreach_cumsum_and_grad():
    data = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    outs, final = mx.nd.contrib.foreach(
        lambda x, s: (x + s, x + s), data, mx.nd.zeros((3,)))
    expect = np.cumsum(np.arange(12).reshape(4, 3), axis=0)
    np.testing.assert_allclose(outs.asnumpy(), expect)
    np.testing.assert_allclose(final.asnumpy(), expect[-1])

    x = mx.nd.ones((4, 3))
    x.attach_grad()
    with autograd.record():
        o, _ = mx.nd.contrib.foreach(
            lambda t, s: (t * 2.0 + s, s + t), x, mx.nd.zeros((3,)))
        o.sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy()[:, 0], [5, 4, 3, 2])


def test_while_loop():
    def cond_fn(i, s):
        return i < 5.0

    def body_fn(i, s):
        return s, (i + 1.0, s + i)

    outs, (i_f, s_f) = mx.nd.contrib.while_loop(
        cond_fn, body_fn, (mx.nd.zeros(()), mx.nd.zeros(())),
        max_iterations=8)
    assert i_f.asscalar() == 5.0
    assert s_f.asscalar() == 10.0
    with pytest.raises(mx.MXNetError):
        mx.nd.contrib.while_loop(cond_fn, body_fn,
                                 (mx.nd.zeros(()), mx.nd.zeros(())))


def test_cond():
    five = mx.nd.array(np.array(5.0, np.float32))
    hi = mx.nd.contrib.cond(mx.nd.array(np.array(1.0)),
                            lambda a: a * 2, lambda a: a * 3, [five])
    lo = mx.nd.contrib.cond(mx.nd.array(np.array(0.0)),
                            lambda a: a * 2, lambda a: a * 3, [five])
    assert hi.asscalar() == 10.0 and lo.asscalar() == 15.0


# ----------------------------------------------------------------------
# im2col / quantization / boxes / CTC
# ----------------------------------------------------------------------

def test_im2col_col2im_adjoint():
    x = mx.nd.array(_R.randn(2, 3, 6, 6).astype(np.float32))
    cols = mx.nd.im2col(x, kernel=(3, 3), pad=(1, 1))
    assert cols.shape == (2, 27, 36)
    back = mx.nd.col2im(cols, output_size=(6, 6), kernel=(3, 3),
                        pad=(1, 1))
    assert back.shape == x.shape
    # center pixels participate in 9 patches
    np.testing.assert_allclose(back.asnumpy()[:, :, 2, 2],
                               9 * x.asnumpy()[:, :, 2, 2], rtol=1e-5)


def test_quantize_roundtrip():
    x = np.array([0.5, -1.0, 1.0, 0.0], np.float32)
    q, mn, mxr = mx.nd.quantize_v2(mx.nd.array(x))
    assert q.dtype == np.int8
    d = mx.nd.dequantize(q, mn, mxr)
    np.testing.assert_allclose(d.asnumpy(), x, atol=0.02)


def test_quantized_fully_connected_close_to_fp32():
    x = _R.randn(4, 8).astype(np.float32)
    w = _R.randn(16, 8).astype(np.float32)
    qx, xn, xx = mx.nd.quantize_v2(mx.nd.array(x))
    qw, wn, wx = mx.nd.quantize_v2(mx.nd.array(w))
    acc, on, ox = mx.nd.quantized_fully_connected(
        qx, qw, None, xn, xx, wn, wx, None, None, num_hidden=16,
        no_bias=True)
    deq = mx.nd.dequantize(acc, on, ox)
    np.testing.assert_allclose(deq.asnumpy(), x @ w.T, rtol=0.1,
                               atol=0.15)


def test_box_iou_nms():
    boxes = mx.nd.array(np.array(
        [[0, 0.9, 0, 0, 2, 2],
         [1, 0.8, 0.1, 0.1, 2.1, 2.1],
         [2, 0.7, 5, 5, 7, 7]], np.float32))
    out = mx.nd.box_nms(boxes, overlap_thresh=0.5, coord_start=2,
                        score_index=1)
    scores = out.asnumpy()[:, 1]
    # the overlapping second box is suppressed, the far one survives
    assert (scores == np.array([0.9, -1.0, 0.7], np.float32)).all()

    iou = mx.nd.contrib.box_iou(
        mx.nd.array(np.array([[0, 0, 2, 2]], np.float32)),
        mx.nd.array(np.array([[1, 1, 3, 3]], np.float32)))
    np.testing.assert_allclose(iou.asnumpy(), [[1.0 / 7]], rtol=1e-5)


def test_roi_pooling_shapes():
    data = mx.nd.array(_R.randn(1, 4, 8, 8).astype(np.float32))
    rois = mx.nd.array(np.array([[0, 0, 0, 7, 7],
                                 [0, 2, 2, 6, 6]], np.float32))
    out = mx.nd.ROIPooling(data, rois, pooled_size=(2, 2))
    assert out.shape == (2, 4, 2, 2)
    # full-image ROI max-pools the quadrants
    top_left = data.asnumpy()[0, :, :4, :4].max(axis=(1, 2))
    np.testing.assert_allclose(out.asnumpy()[0, :, 0, 0], top_left,
                               rtol=1e-5)
    out2 = mx.nd.ROIAlign(data, rois, pooled_size=(2, 2))
    assert out2.shape == (2, 4, 2, 2)


def test_ctc_op_matches_gluon_loss():
    from mxnet_tpu import gluon
    T, N, C = 8, 3, 5
    pred = _R.randn(N, T, C).astype(np.float32)
    label = np.stack([[1, 2], [2, 3], [1, -1]]).astype(np.float32)
    layer = gluon.loss.CTCLoss()
    want = layer(mx.nd.array(pred), mx.nd.array(label)).asnumpy()
    got = mx.nd.CTCLoss(mx.nd.array(pred.transpose(1, 0, 2)),
                        mx.nd.array(label)).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
