"""Checkpoint subsystem (ISSUE 3): atomic commit, manifest
verification, corruption fallback, retention, async overlap, sharded
save/reshard, and the rebased legacy save paths."""
import json
import os
import subprocess
import threading
import time
import warnings

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.checkpoint import CheckpointError, CheckpointManager
from mxnet_tpu.checkpoint import async_writer, core as ckpt_core

from conftest import paired_params


# ----------------------------------------------------------------------
# helpers
# ----------------------------------------------------------------------

def _net_and_trainer():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    return net, tr


def _train(net, tr, x, y, steps, loss_fn=None):
    loss_fn = loss_fn or gluon.loss.L2Loss()
    for _ in range(steps):
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        tr.step(x.shape[0])


def _data(seed=0):
    rng = np.random.RandomState(seed)
    return (mx.nd.array(rng.randn(4, 6).astype(np.float32)),
            mx.nd.array(rng.randn(4, 4).astype(np.float32)))


def _dead_pid():
    """A pid guaranteed dead: a subprocess that already exited."""
    proc = subprocess.Popen(["true"])
    proc.wait()
    return proc.pid


# ----------------------------------------------------------------------
# manager round trip (acceptance: save -> kill -> restore resumes at
# the saved step, params/optimizer state bit-identical)
# ----------------------------------------------------------------------

def test_manager_round_trip_bit_identical(tmp_path):
    x, y = _data()
    net, tr = _net_and_trainer()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    _train(net, tr, x, y, 5)
    mgr.save_training(5, net, tr, metadata={"epoch": 1})

    # "kill": brand-new objects, fresh manager over the same root
    net2, tr2 = _net_and_trainer()
    net2(x)  # materialize params
    mgr2 = CheckpointManager(str(tmp_path / "ck"))
    ckpt = mgr2.restore_training(net2, tr2)
    assert ckpt.step == 5
    assert ckpt.metadata == {"epoch": 1}
    for p1, p2 in paired_params(net, net2):
        np.testing.assert_array_equal(p1.data().asnumpy(),
                                      p2.data().asnumpy())
    # optimizer state (momentum) bit-identical => identical continuation
    _train(net, tr, x, y, 1)
    _train(net2, tr2, x, y, 1)
    for p1, p2 in paired_params(net, net2):
        np.testing.assert_array_equal(p1.data().asnumpy(),
                                      p2.data().asnumpy())


def test_restore_fresh_start_returns_none(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "empty"))
    assert mgr.restore() is None
    assert mgr.latest_step() is None
    net, tr = _net_and_trainer()
    assert mgr.restore_training(net, tr) is None


def test_generic_items_round_trip(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    w = np.arange(12, dtype=np.float32).reshape(3, 4)
    mgr.save(7, {"params": {"w": mx.nd.array(w)}, "blob": b"\x00state"},
             metadata={"note": "x"})
    ckpt = mgr.restore()
    assert ckpt.step == 7
    np.testing.assert_array_equal(ckpt.items["params"]["w"].asnumpy(), w)
    assert ckpt.items["blob"] == b"\x00state"
    assert ckpt.metadata == {"note": "x"}


# ----------------------------------------------------------------------
# corruption fallback (acceptance: survives an injected truncated-shard
# corruption by falling back to the previous step)
# ----------------------------------------------------------------------

def _two_step_manager(tmp_path):
    x, y = _data()
    net, tr = _net_and_trainer()
    mgr = CheckpointManager(str(tmp_path / "ck"))
    _train(net, tr, x, y, 1)
    mgr.save_training(1, net, tr)
    _train(net, tr, x, y, 1)
    mgr.save_training(2, net, tr)
    return mgr, net, tr, x, y


def test_truncated_file_falls_back_to_previous_step(tmp_path):
    mgr, net, tr, x, y = _two_step_manager(tmp_path)
    with open(os.path.join(mgr.step_dir(2), "params.params"),
              "r+b") as f:
        f.truncate(10)
    with pytest.warns(RuntimeWarning, match="failed verification"):
        assert mgr.latest_step() == 1
    net2, tr2 = _net_and_trainer()
    net2(x)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        ckpt = mgr.restore_training(net2, tr2)
    assert ckpt.step == 1


def test_missing_manifest_falls_back(tmp_path):
    mgr, *_ = _two_step_manager(tmp_path)
    os.remove(os.path.join(mgr.step_dir(2), ckpt_core.MANIFEST_NAME))
    with pytest.warns(RuntimeWarning):
        assert mgr.latest_step() == 1


def test_bitflip_same_size_falls_back(tmp_path):
    mgr, *_ = _two_step_manager(tmp_path)
    fpath = os.path.join(mgr.step_dir(2), "trainer.bin")
    with open(fpath, "r+b") as f:
        f.seek(max(0, os.path.getsize(fpath) // 2))
        byte = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.warns(RuntimeWarning, match="crc32 mismatch"):
        assert mgr.latest_step() == 1


def test_explicit_restore_of_corrupt_step_raises(tmp_path):
    mgr, *_ = _two_step_manager(tmp_path)
    os.remove(os.path.join(mgr.step_dir(2), "params.params"))
    with pytest.warns(RuntimeWarning):
        with pytest.raises(CheckpointError):
            mgr.restore(step=2)
    # the good step still restores explicitly
    assert mgr.restore(step=1).step == 1


def test_all_steps_and_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    for s in (3, 1, 7):
        mgr.save(s, {"blob": b"x"})
    assert mgr.all_steps() == [1, 3, 7]
    assert mgr.latest_step() == 7


# ----------------------------------------------------------------------
# retention
# ----------------------------------------------------------------------

def test_retention_max_to_keep(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2)
    for s in range(1, 6):
        mgr.save(s, {"blob": b"s%d" % s})
    assert mgr.all_steps() == [4, 5]


def test_retention_keep_every_n_interaction(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), max_to_keep=2,
                            keep_every_n_steps=5)
    for s in range(1, 13):
        mgr.save(s, {"blob": b"s%d" % s})
    # multiples of 5 immune to max_to_keep; last 2 others retained
    assert mgr.all_steps() == [5, 10, 11, 12]


def test_retention_env_default(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CKPT_MAX_TO_KEEP", "1")
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr.max_to_keep == 1
    for s in (1, 2, 3):
        mgr.save(s, {"blob": b"x"})
    assert mgr.all_steps() == [3]


# ----------------------------------------------------------------------
# stale-temp sweep (satellite)
# ----------------------------------------------------------------------

def test_sweep_stale_tmps_at_manager_init(tmp_path):
    root = tmp_path / "ck"
    root.mkdir()
    dead = _dead_pid()
    stale_file = root / ("step_00000001.%d.tmp" % dead)
    stale_file.mkdir()          # a stranded staging DIR
    (stale_file / "params.params").write_bytes(b"torn")
    live_file = root / ("step_00000002.%d.tmp" % os.getpid())
    live_file.mkdir()           # our own in-flight write: must survive
    CheckpointManager(str(root))
    assert not stale_file.exists()
    assert live_file.exists()


def test_commit_sweeps_sibling_stale_tmps(tmp_path):
    dead = _dead_pid()
    target = tmp_path / "state.bin"
    stale = tmp_path / ("state.bin.%d.tmp" % dead)
    stale.write_bytes(b"half-written")
    ckpt_core.atomic_write_bytes(str(target), b"good")
    assert target.read_bytes() == b"good"
    assert not stale.exists()


def test_commit_failure_leaves_no_tmp_and_old_file(tmp_path):
    target = tmp_path / "state.bin"
    target.write_bytes(b"old")

    def boom(tmp):
        with open(tmp, "wb") as f:
            f.write(b"partial")
        raise RuntimeError("writer died")

    with pytest.raises(RuntimeError):
        ckpt_core.commit(str(target), boom)
    assert target.read_bytes() == b"old"
    assert [p for p in os.listdir(tmp_path) if p.endswith(".tmp")] == []


# ----------------------------------------------------------------------
# async writer (acceptance: an async save returns to the training loop
# before the bytes hit disk)
# ----------------------------------------------------------------------

@pytest.fixture
def write_gate():
    gate = threading.Event()
    async_writer._TEST_WRITE_GATE = gate
    yield gate
    async_writer._TEST_WRITE_GATE = None


def test_async_save_overlaps_training(tmp_path, write_gate):
    x, y = _data()
    net, tr = _net_and_trainer()
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    net(x)
    mgr.save_training(1, net, tr)
    # the writer is blocked on the gate: nothing committed yet...
    assert mgr.all_steps() == []
    assert mgr._writer.in_flight
    # ...and the training loop advances regardless
    _train(net, tr, x, y, 2)
    assert mgr.all_steps() == []
    write_gate.set()
    mgr.wait_until_finished()
    assert mgr.all_steps() == [1]
    assert mgr.restore().step == 1


def test_async_snapshot_is_immutable_to_later_steps(tmp_path,
                                                    write_gate):
    x, y = _data()
    net, tr = _net_and_trainer()
    net(x)
    before = {k: p._reduce().asnumpy() for k, p in
              net._collect_params_with_prefix().items()}
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    mgr.save_training(1, net, tr)
    _train(net, tr, x, y, 3)      # mutate params while save in flight
    write_gate.set()
    mgr.wait_until_finished()
    ckpt = mgr.restore()
    for k, v in before.items():
        np.testing.assert_array_equal(ckpt.items["params"][k].asnumpy(),
                                      v)


def test_async_at_most_one_in_flight(tmp_path, write_gate):
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    mgr.save(1, {"blob": b"one"})
    done = threading.Event()

    def second_save():
        mgr.save(2, {"blob": b"two"})   # must drain save 1 first
        done.set()

    t = threading.Thread(target=second_save, daemon=True)
    t.start()
    time.sleep(0.1)
    assert not done.is_set()            # blocked behind save 1
    assert mgr.all_steps() == []
    write_gate.set()
    t.join(timeout=30)
    assert done.is_set()
    mgr.wait_until_finished()
    assert mgr.all_steps() == [1, 2]


def test_async_error_reraised_at_next_save(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    # retries off: this test is about error SURFACING; a transient
    # failure being rescued by the bounded retry is tests/test_chaos.py
    mgr._writer._retries = 0
    orig = mgr._write_step

    def boom(*a, **k):
        raise RuntimeError("disk on fire")

    mgr._write_step = boom
    mgr.save(1, {"blob": b"x"})         # fails on the writer thread
    mgr._write_step = orig
    with pytest.raises(RuntimeError, match="disk on fire"):
        mgr.save(2, {"blob": b"y"})
    # the error is consumed: the SAME save retried now succeeds
    mgr.save(2, {"blob": b"y"})
    mgr.wait_until_finished()
    assert mgr.all_steps() == [2]


def test_async_error_reraised_at_wait(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
    mgr._writer._retries = 0            # permanent failure, not weather
    mgr._write_step = lambda *a, **k: (_ for _ in ()).throw(
        OSError("enospc"))
    mgr.save(1, {"blob": b"x"})
    with pytest.raises(OSError, match="enospc"):
        mgr.wait_until_finished()


def test_async_env_default(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_CKPT_ASYNC", "1")
    mgr = CheckpointManager(str(tmp_path / "ck"))
    assert mgr._writer is not None
    mgr.save(1, {"blob": b"x"})
    mgr.wait_until_finished()
    assert mgr.all_steps() == [1]


# ----------------------------------------------------------------------
# sharded
# ----------------------------------------------------------------------

def _mesh_sharded_array():
    mesh = mx.parallel.mesh.make_mesh({"dp": 8})
    sh = NamedSharding(mesh, PartitionSpec("dp"))
    return jax.device_put(np.arange(16, dtype=np.float32), sh), sh


def test_sharded_round_trip_and_reshard(tmp_path):
    arr, sh = _mesh_sharded_array()
    mgr = CheckpointManager(str(tmp_path / "ck"), sharded=True)
    mgr.save(5, {"params": {"emb": arr, "b": np.ones(3, np.float32)},
                 "blob": b"opaque"}, metadata={"k": 1})
    assert mgr.latest_step() == 5
    manifest = ckpt_core.load_manifest(mgr.step_dir(5))
    assert any(e["kind"] == "shard" for e in manifest["files"].values())
    assert manifest["topology"]["num_devices"] == 8

    # restore WITHOUT a mesh (host arrays): topology-independent
    ckpt = mgr.restore()
    np.testing.assert_array_equal(ckpt.items["params"]["emb"].asnumpy(),
                                  np.arange(16))
    np.testing.assert_array_equal(ckpt.items["params"]["b"].asnumpy(),
                                  np.ones(3))
    assert ckpt.items["blob"] == b"opaque"

    # restore WITH a different sharding than saved: reshard-on-restore
    mesh2 = mx.parallel.mesh.make_mesh({"dp": 4})
    sh2 = NamedSharding(mesh2, PartitionSpec("dp"))
    ckpt = mgr.restore(sharding=lambda item, key, shape:
                       sh2 if key == "emb" else None)
    emb = ckpt.items["params"]["emb"]._data
    assert emb.sharding.num_devices == 4
    np.testing.assert_array_equal(np.asarray(emb), np.arange(16))


def test_sharded_corruption_falls_back(tmp_path):
    arr, _ = _mesh_sharded_array()
    mgr = CheckpointManager(str(tmp_path / "ck"), sharded=True)
    mgr.save(1, {"params": {"emb": arr}})
    mgr.save(2, {"params": {"emb": arr}})
    shard = [f for f in os.listdir(mgr.step_dir(2))
             if f.endswith(".params")][0]
    with open(os.path.join(mgr.step_dir(2), shard), "r+b") as f:
        f.truncate(4)
    with pytest.warns(RuntimeWarning):
        assert mgr.latest_step() == 1


# ----------------------------------------------------------------------
# rebased legacy paths (satellites)
# ----------------------------------------------------------------------

def test_trainer_save_states_atomic_on_failure(tmp_path):
    x, y = _data()
    net, tr = _net_and_trainer()
    _train(net, tr, x, y, 1)
    fname = str(tmp_path / "t.states")
    tr.save_states(fname)
    good = open(fname, "rb").read()
    assert good

    orig = tr._updater.get_states
    tr._updater.get_states = lambda **kw: (_ for _ in ()).throw(
        RuntimeError("serializer died"))
    with pytest.raises(RuntimeError):
        tr.save_states(fname)
    tr._updater.get_states = orig
    # old file intact, no tmp litter
    assert open(fname, "rb").read() == good
    assert [p for p in os.listdir(tmp_path)
            if p.endswith(".tmp")] == []
    # round trip still works
    tr.load_states(fname)


def test_kvstore_save_optimizer_states_atomic(tmp_path):
    kv = mx.kv.create("local")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    fname = str(tmp_path / "kv.states")
    kv.save_optimizer_states(fname)
    assert os.path.exists(fname)
    assert [p for p in os.listdir(tmp_path)
            if p.endswith(".tmp")] == []
    kv.load_optimizer_states(fname)


def test_model_save_checkpoint_atomic(tmp_path):
    prefix = str(tmp_path / "m")
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    arg = {"fc_weight": mx.nd.ones((4, 6)), "fc_bias": mx.nd.zeros((4,))}
    mx.model.save_checkpoint(prefix, 3, net, arg, {})
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 3)
    np.testing.assert_array_equal(arg2["fc_weight"].asnumpy(),
                                  np.ones((4, 6)))
    assert [p for p in os.listdir(tmp_path)
            if p.endswith(".tmp")] == []


def test_callback_managed_checkpoint(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    cb = mx.callback.managed_checkpoint(mgr, period=2,
                                        metadata_fn=lambda i: {"it": i})
    arg = {"w": mx.nd.ones((2, 2))}
    cb(0, None, arg, {})            # epoch 1: period 2 -> no save
    assert mgr.all_steps() == []
    cb(1, None, arg, {})            # epoch 2 -> save
    assert mgr.all_steps() == [2]
    ckpt = mgr.restore()
    np.testing.assert_array_equal(
        ckpt.items["params"]["arg:w"].asnumpy(), np.ones((2, 2)))
    assert ckpt.metadata == {"it": 1}


# ----------------------------------------------------------------------
# preemption rebase (satellite: resume verifies checksums)
# ----------------------------------------------------------------------

def test_preemption_meta_carries_digests(tmp_path):
    x, _ = _data()
    net, tr = _net_and_trainer()
    net(x)
    handler = mx.preemption.install(str(tmp_path / "job"), net, tr)
    try:
        handler.save_now(step=4)
    finally:
        handler.uninstall()
    meta = json.load(open(handler.meta_path))
    assert meta["step"] == 4
    files = meta["files"]
    assert set(files) == {os.path.basename(handler.params_path),
                          os.path.basename(handler.states_path)}
    for entry in files.values():
        assert entry["bytes"] > 0 and isinstance(entry["crc32"], int)
        assert 0 <= entry["crc32"] <= 0xFFFFFFFF


def test_preemption_resume_rejects_corrupt_params(tmp_path):
    x, _ = _data()
    net, tr = _net_and_trainer()
    net(x)
    handler = mx.preemption.install(str(tmp_path / "job"), net, tr)
    try:
        handler.save_now(step=4)
    finally:
        handler.uninstall()
    # bit-rot the params, keeping size (presence checks can't see this)
    with open(handler.params_path, "r+b") as f:
        f.seek(os.path.getsize(handler.params_path) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    net2, tr2 = _net_and_trainer()
    net2(x)
    with pytest.warns(RuntimeWarning, match="failed verification"):
        assert mx.preemption.resume(str(tmp_path / "job"),
                                    net2, tr2) is None


def test_preemption_resume_still_loads_good_checkpoint(tmp_path):
    x, _ = _data()
    net, tr = _net_and_trainer()
    net(x)
    handler = mx.preemption.install(str(tmp_path / "job"), net, tr)
    try:
        handler.save_now(step=9)
    finally:
        handler.uninstall()
    net2, tr2 = _net_and_trainer()
    net2(x)
    meta = mx.preemption.resume(str(tmp_path / "job"), net2, tr2)
    assert meta["step"] == 9
    for p1, p2 in paired_params(net, net2):
        np.testing.assert_array_equal(p1.data().asnumpy(),
                                      p2.data().asnumpy())


def test_preemption_resume_accepts_legacy_meta(tmp_path):
    """Metas from before the subsystem (no 'files' key) keep loading."""
    x, _ = _data()
    net, tr = _net_and_trainer()
    net(x)
    handler = mx.preemption.install(str(tmp_path / "job"), net, tr)
    try:
        handler.save_now(step=2)
    finally:
        handler.uninstall()
    meta = json.load(open(handler.meta_path))
    del meta["files"]
    with open(handler.meta_path, "w") as f:
        json.dump(meta, f)
    net2, tr2 = _net_and_trainer()
    net2(x)
    assert mx.preemption.resume(str(tmp_path / "job"),
                                net2, tr2)["step"] == 2


def test_preemption_install_sweeps_stale_tmps(tmp_path):
    dead = _dead_pid()
    stale = tmp_path / ("job-preempt.params.%d.tmp" % dead)
    stale.write_bytes(b"torn")
    unrelated = tmp_path / "other-file.params"
    unrelated.write_bytes(b"keep me")
    net, tr = _net_and_trainer()
    handler = mx.preemption.install(str(tmp_path / "job"), net, tr)
    handler.uninstall()
    assert not stale.exists()
    assert unrelated.exists()


# ----------------------------------------------------------------------
# telemetry wiring
# ----------------------------------------------------------------------

def test_manager_telemetry_events(tmp_path):
    from mxnet_tpu import telemetry
    telemetry.reset()
    telemetry.enable()
    try:
        mgr = CheckpointManager(str(tmp_path / "ck"))
        mgr.save(1, {"blob": b"0123456789"})
        mgr.restore()
        events = telemetry.event("checkpoint").recent
        actions = [e["action"] for e in events]
        assert actions == ["save", "restore"]
        assert events[0]["nbytes"] == 10
        assert events[0]["seconds"] >= 0
        assert telemetry.counter("checkpoint.bytes_written").value == 10
        assert telemetry.counter("checkpoint.bytes_read").value == 10
        assert telemetry.timer("checkpoint.save_time").count == 1
        assert telemetry.timer("checkpoint.restore_time").count == 1
    finally:
        telemetry.disable()
        telemetry.reset()


def test_async_wait_timer_recorded(tmp_path, write_gate):
    from mxnet_tpu import telemetry
    telemetry.reset()
    telemetry.enable()
    try:
        mgr = CheckpointManager(str(tmp_path / "ck"), async_save=True)
        mgr.save(1, {"blob": b"x"})
        write_gate.set()
        mgr.save(2, {"blob": b"y"})     # drains save 1 -> records wait
        mgr.wait_until_finished()
        assert telemetry.timer("checkpoint.async_wait").count >= 1
    finally:
        telemetry.disable()
        telemetry.reset()


# ----------------------------------------------------------------------
# misc API
# ----------------------------------------------------------------------

def test_save_rejects_bad_items(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    with pytest.raises(CheckpointError):
        mgr.save(1, {})
    with pytest.raises(mx.base.MXNetError):
        mgr.save(1, {"bad": 42})


def test_resave_same_step_overwrites(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr.save(1, {"blob": b"first"})
    mgr.save(1, {"blob": b"second"})
    assert mgr.all_steps() == [1]
    assert mgr.restore().items["blob"] == b"second"
