"""AMP (mixed precision), LARS, and fused multi-tensor optimizer updates.

Reference analogs: ``tests/python/unittest/test_amp.py``, LARS/LAMB tests,
``multi_sgd_update`` kernels in ``optimizer_op.cc``."""
import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd, gluon
from mxnet_tpu.base import MXNetError


def _mlp(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    return net


def test_amp_casts_matmul_to_bf16():
    with amp.scope("bfloat16"):
        a = mx.nd.ones((4, 8))
        b = mx.nd.ones((8, 4))
        out = mx.nd.dot(a, b)
        assert out.dtype == np.dtype(jnp.bfloat16.dtype)
    # outside the scope: fp32 again
    out2 = mx.nd.dot(a, b)
    assert out2.dtype == np.float32


def test_amp_fp32_ops_stay_fp32():
    with amp.scope("bfloat16"):
        x = mx.nd.ones((4, 8)).astype("bfloat16")
        s = mx.nd.softmax(x)
        assert s.dtype == np.float32  # FP32_OPS list


def test_amp_params_keep_fp32_master_grads():
    """bf16 compute, fp32 weights and fp32 gradients (the cast's vjp)."""
    net = _mlp()
    loss_fn = gluon.loss.L2Loss()
    X = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
    Y = mx.nd.array(np.random.RandomState(1).randn(8, 4).astype(np.float32))
    with amp.scope("bfloat16"):
        with autograd.record():
            l = loss_fn(net(X), Y)
        l.backward()
    for p in net.collect_params().values():
        assert p.data().dtype == np.float32
        assert p.grad().dtype == np.float32
        assert np.abs(p.grad().asnumpy()).sum() > 0


def test_amp_bf16_training_converges():
    net = _mlp(seed=3)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(2)
    X = rng.randn(64, 8).astype(np.float32)
    Y = X @ rng.randn(8, 4).astype(np.float32)
    losses = []
    with amp.scope("bfloat16"):
        for _ in range(40):
            x, y = mx.nd.array(X), mx.nd.array(Y)
            with autograd.record():
                l = loss_fn(net(x), y)
            l.backward()
            trainer.step(64)
            losses.append(float(l.mean().asscalar()))
    assert losses[-1] < losses[0] / 3, (losses[0], losses[-1])


def test_amp_trainstep_compiled_bf16():
    from mxnet_tpu.parallel import TrainStep
    net = _mlp(seed=5)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer)
    rng = np.random.RandomState(4)
    X = rng.randn(32, 8).astype(np.float32)
    Y = X @ rng.randn(8, 4).astype(np.float32)
    with amp.scope("bfloat16"):
        first = float(step(mx.nd.array(X), mx.nd.array(Y)).asscalar())
        for _ in range(80):
            last = float(step(mx.nd.array(X), mx.nd.array(Y)).asscalar())
    assert last < first / 3
    for p in net.collect_params().values():
        assert p.data().dtype == np.float32


def test_loss_scaler_dynamics():
    s = amp.LossScaler(init_scale=1024.0, scale_window=2)
    assert not s.has_overflow([mx.nd.ones((3,))])
    assert s.has_overflow([mx.nd.ones((3,)),
                           mx.nd.array(np.array([np.inf, 1, 2],
                                                np.float32))])
    s.update_scale(True)
    assert s.loss_scale == 512.0
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 1024.0


def test_fp16_trainer_skips_on_overflow():
    net = _mlp(seed=7)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    amp.init_trainer(trainer, amp.LossScaler(init_scale=4.0))
    loss_fn = gluon.loss.L2Loss()
    X = mx.nd.array(np.random.RandomState(0).randn(8, 8).astype(np.float32))
    Y = mx.nd.zeros((8, 4))
    with autograd.record():
        l = loss_fn(net(X), Y)
    l.backward()
    # poison one gradient with inf: the whole update must be skipped
    p0 = list(net.collect_params().values())[0]
    before = {p.name: p.data().asnumpy().copy()
              for p in net.collect_params().values()}
    p0.grad()._data = (p0.grad()._data * np.inf)
    trainer.step(8)
    for p in net.collect_params().values():
        np.testing.assert_array_equal(before[p.name], p.data().asnumpy())
    assert trainer._amp_loss_scaler.loss_scale == 2.0  # halved


def test_amp_scale_loss_context():
    net = _mlp(seed=9)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01}, kvstore=None)
    amp.init_trainer(trainer, amp.LossScaler(init_scale=8.0))
    loss_fn = gluon.loss.L2Loss()
    X = mx.nd.array(np.random.RandomState(0).randn(4, 8).astype(np.float32))
    Y = mx.nd.zeros((4, 4))
    with autograd.record():
        l = loss_fn(net(X), Y)
        with amp.scale_loss(l, trainer) as scaled:
            scaled.backward()
    g = list(net.collect_params().values())[0].grad().asnumpy()
    # grads carry the 8x scale until step() folds in 1/scale
    with autograd.record():
        l2 = loss_fn(net(X), Y)
    l2.backward()
    g2 = list(net.collect_params().values())[0].grad().asnumpy()
    np.testing.assert_allclose(g, 8.0 * g2, rtol=1e-5)


def test_lars_optimizer_converges_and_uses_trust_ratio():
    w, g, m = (mx.nd.array(np.full((4,), 2.0, np.float32)),
               mx.nd.array(np.full((4,), 0.5, np.float32)),
               mx.nd.zeros((4,)))
    nw, nm = mx.nd.lars_update(w, g, m, lr=1.0, momentum=0.0, eta=0.1,
                               wd=0.0)
    # trust = eta*||w||/||g|| = 0.1*4/1 = 0.4 ; step = lr*trust*g = 0.2
    np.testing.assert_allclose(nw.asnumpy(), 2.0 - 0.4 * 0.5, rtol=1e-5)

    net = _mlp(seed=11)
    trainer = gluon.Trainer(net.collect_params(), "lars",
                            {"learning_rate": 1.0, "momentum": 0.9,
                             "eta": 0.01}, kvstore=None)
    loss_fn = gluon.loss.L2Loss()
    rng = np.random.RandomState(3)
    X = rng.randn(64, 8).astype(np.float32)
    Y = X @ rng.randn(8, 4).astype(np.float32)
    losses = []
    for _ in range(40):
        x, y = mx.nd.array(X), mx.nd.array(Y)
        with autograd.record():
            l = loss_fn(net(x), y)
        l.backward()
        trainer.step(64)
        losses.append(float(l.mean().asscalar()))
    assert losses[-1] < losses[0] / 3


def test_multi_sgd_matches_single():
    rng = np.random.RandomState(0)
    ws = [rng.randn(5, 3).astype(np.float32) for _ in range(3)]
    gs = [rng.randn(5, 3).astype(np.float32) for _ in range(3)]
    lrs, wds = (0.1, 0.2, 0.3), (0.0, 0.01, 0.1)
    data = []
    for w, g in zip(ws, gs):
        data += [mx.nd.array(w), mx.nd.array(g)]
    outs = mx.nd.multi_sgd_update(*data, lrs=lrs, wds=wds, num_weights=3)
    for k in range(3):
        ref = mx.nd.sgd_update(mx.nd.array(ws[k]), mx.nd.array(gs[k]),
                               lr=lrs[k], wd=wds[k])
        np.testing.assert_allclose(outs[k].asnumpy(), ref.asnumpy(),
                                   rtol=1e-6)


def test_multi_sgd_mom_matches_single():
    rng = np.random.RandomState(1)
    n = 3
    ws = [rng.randn(4).astype(np.float32) for _ in range(n)]
    gs = [rng.randn(4).astype(np.float32) for _ in range(n)]
    ms = [rng.randn(4).astype(np.float32) for _ in range(n)]
    lrs, wds = (0.1, 0.2, 0.3), (0.0, 0.01, 0.1)
    data = []
    for w, g, m in zip(ws, gs, ms):
        data += [mx.nd.array(w), mx.nd.array(g), mx.nd.array(m)]
    outs = mx.nd.multi_sgd_mom_update(*data, lrs=lrs, wds=wds, momentum=0.9,
                                      num_weights=n)
    for k in range(n):
        rw, rm = mx.nd.sgd_mom_update(mx.nd.array(ws[k]), mx.nd.array(gs[k]),
                                      mx.nd.array(ms[k]), lr=lrs[k],
                                      wd=wds[k], momentum=0.9)
        np.testing.assert_allclose(outs[k].asnumpy(), rw.asnumpy(), rtol=1e-6)
        np.testing.assert_allclose(outs[n + k].asnumpy(), rm.asnumpy(),
                                   rtol=1e-6)


def test_fused_trainer_update_matches_per_param():
    """Trainer's multi_sgd fused path must produce identical params to the
    per-parameter updater path."""
    rng = np.random.RandomState(5)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 4).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    def run(agg):
        import os
        os.environ["MXNET_OPTIMIZER_AGGREGATION_SIZE"] = str(agg)
        try:
            net = _mlp(seed=21)
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9,
                                "wd": 0.01}, kvstore=None)
            for _ in range(3):
                with autograd.record():
                    l = loss_fn(net(mx.nd.array(X)), mx.nd.array(Y))
                l.backward()
                tr.step(16)
            return [p.data().asnumpy()
                    for p in net.collect_params().values()]
        finally:
            del os.environ["MXNET_OPTIMIZER_AGGREGATION_SIZE"]

    fused = run(60)
    unfused = run(1)  # agg < 2 disables the fused path
    for a, b in zip(fused, unfused):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_amp_init_rejects_bad_dtype():
    with pytest.raises(MXNetError):
        amp.init("float64")


def test_unscale_then_step_no_double_divide():
    """amp.unscale followed by trainer.step must divide by the loss scale
    exactly once."""
    def run(use_unscale):
        net = _mlp(seed=31)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=None)
        X = mx.nd.array(np.random.RandomState(0).randn(8, 8)
                        .astype(np.float32))
        Y = mx.nd.array(np.random.RandomState(1).randn(8, 4)
                        .astype(np.float32))
        loss_fn = gluon.loss.L2Loss()
        if use_unscale:
            amp.init_trainer(tr, amp.LossScaler(init_scale=1024.0,
                                                scale_window=10**9))
            with autograd.record():
                l = loss_fn(net(X), Y)
                with amp.scale_loss(l, tr) as sl:
                    sl.backward()
            amp.unscale(tr)
        else:
            with autograd.record():
                l = loss_fn(net(X), Y)
            l.backward()
        tr.step(8)
        return [p.data().asnumpy() for p in net.collect_params().values()]

    plain = run(False)
    scaled = run(True)
    for a, b in zip(plain, scaled):
        np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-6)


def test_trainstep_fp16_scaler_skips_and_backs_off():
    """TrainStep must honor an attached loss scaler: overflowing steps
    leave weights/states untouched and halve the scale."""
    from mxnet_tpu.parallel import TrainStep
    net = _mlp(seed=33)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9},
                       kvstore=None)
    amp.init_trainer(tr, amp.LossScaler(init_scale=8.0, scale_window=10**9))
    step = TrainStep(net, gluon.loss.L2Loss(), tr)
    X = np.random.RandomState(0).randn(8, 8).astype(np.float32)
    Y = np.random.RandomState(1).randn(8, 4).astype(np.float32)
    step(mx.nd.array(X), mx.nd.array(Y))  # clean step
    assert tr._amp_loss_scaler.loss_scale == 8.0
    before = [p.data().asnumpy().copy()
              for p in net.collect_params().values()]
    bad = X.copy()
    bad[0, 0] = np.inf  # forward produces non-finite grads
    step(mx.nd.array(bad), mx.nd.array(Y))
    assert tr._amp_loss_scaler.loss_scale == 4.0  # backed off
    after = [p.data().asnumpy() for p in net.collect_params().values()]
    for a, b in zip(before, after):
        np.testing.assert_array_equal(a, b)  # update skipped


def test_trainstep_fp16_scaler_matches_unscaled_updates():
    """With a scaler attached and no overflow, TrainStep updates must match
    the no-scaler run (scale cancels exactly)."""
    from mxnet_tpu.parallel import TrainStep
    X = np.random.RandomState(2).randn(16, 8).astype(np.float32)
    Y = np.random.RandomState(3).randn(16, 4).astype(np.float32)

    def run(with_scaler):
        net = _mlp(seed=35)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=None)
        if with_scaler:
            amp.init_trainer(tr, amp.LossScaler(init_scale=256.0,
                                                scale_window=10**9))
        step = TrainStep(net, gluon.loss.L2Loss(), tr)
        for _ in range(3):
            step(mx.nd.array(X), mx.nd.array(Y))
        return [p.data().asnumpy() for p in net.collect_params().values()]

    a, b = run(False), run(True)
    for x, y in zip(a, b):
        np.testing.assert_allclose(x, y, rtol=1e-4, atol=1e-6)


# ----------------------------------------------------------------------
# LossScaler edge cases + the fused overflow check (ISSUE 16 satellites)
# ----------------------------------------------------------------------

def test_loss_scaler_min_scale_floor():
    s = amp.LossScaler(init_scale=2.0, min_scale=1.0)
    s.update_scale(True)
    assert s.loss_scale == 1.0
    s.update_scale(True)                  # floored, not 0.5
    assert s.loss_scale == 1.0


def test_loss_scaler_doubles_exactly_at_window():
    s = amp.LossScaler(init_scale=4.0, scale_window=3)
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 4.0            # not before the window
    s.update_scale(False)
    assert s.loss_scale == 8.0            # exactly at it
    s.update_scale(False)
    s.update_scale(False)
    assert s.loss_scale == 8.0            # clean-step counter reset


def test_loss_scaler_overflow_restarts_window():
    s = amp.LossScaler(init_scale=8.0, scale_window=2)
    s.update_scale(False)                 # 1 clean step banked
    s.update_scale(True)                  # overflow: halve + reset
    assert s.loss_scale == 4.0
    s.update_scale(False)
    assert s.loss_scale == 4.0            # window restarted, not 1/2 in
    s.update_scale(False)
    assert s.loss_scale == 8.0            # recovered


def test_amp_overflow_event_pairs_scale_halving():
    from mxnet_tpu import telemetry
    telemetry.enable()
    try:
        telemetry.reset()
        s = amp.LossScaler(init_scale=16.0)
        s.update_scale(True)
        reg = telemetry.registry()
        assert reg.counter("amp.overflows").value == 1
        assert reg.event("amp.overflow").recent[-1] == \
            {"scale_before": 16.0, "scale_after": 8.0}
    finally:
        telemetry.disable()
        telemetry.reset()


def test_has_overflow_single_device_get_per_step():
    """The fused finite check (analysis.numerics.finite_all): one
    jitted reduction and ONE device_get per has_overflow() call no
    matter how many gradient arrays -- pinned via the host_sync
    counter it books its boolean fetch under."""
    from mxnet_tpu import telemetry
    s = amp.LossScaler()
    dirty = [mx.nd.ones((8,)), mx.nd.ones((4, 4)),
             mx.nd.array(np.array([1.0, np.inf], np.float32))]
    clean = [mx.nd.ones((8,)), mx.nd.ones((4, 4)), mx.nd.ones((2,))]
    s.has_overflow(dirty)                 # warm both fused programs
    s.has_overflow(clean)
    telemetry.enable()
    try:
        telemetry.reset()
        c = telemetry.registry().counter(
            "dispatch.host_sync.amp.overflow_check")
        assert s.has_overflow(dirty)
        assert c.value == 1               # one sync for 3 arrays
        assert not s.has_overflow(clean)
        assert c.value == 2
        # the sync wall time lands in the host_sync ledger
        t = telemetry.registry().timer("dispatch.host_sync_time")
        assert t.count >= 2
    finally:
        telemetry.disable()
        telemetry.reset()
