"""Tensor-parallel BERT encoder (reference: §2.4 "TP -- native win";
Megatron-style sharding over a dp x tp mesh).

The tp-mode model (separate column-parallel q/k/v, row-parallel out,
col+row FFN) must match the plain model numerically when loaded with
the same weights, sharded or not.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import make_mesh

VOCAB, UNITS, SEQ = 64, 32, 16


def _tiny_bert(tp_mesh=None):
    from mxnet_tpu.gluon.model_zoo.bert import BERTModel
    return BERTModel(vocab_size=VOCAB, units=UNITS, hidden_size=64,
                     num_layers=2, num_heads=4, max_length=SEQ,
                     dropout=0.0, tp_mesh=tp_mesh)


def _copy_weights(src, dst):
    """Copy plain-model weights into a tp-mode model (fused qkv splits
    into query/key/value thirds)."""
    import re

    def norm(n):
        return re.sub(r"^bertmodel\d+_", "", n)

    sp = {norm(n): p for n, p in src.collect_params().items()}
    for name, p in dst.collect_params().items():
        key = norm(name)
        if key in sp:
            p.set_data(mx.nd.array(sp[key].data().asnumpy()))
            continue
        for i, nm in enumerate(("query", "key", "value")):
            for kind in ("weight", "bias"):
                tag = "_%s_%s" % (nm, kind)
                if tag in key:
                    fused = sp[key.replace(tag, "_qkv_%s" % kind)]
                    w = fused.data().asnumpy()
                    u = w.shape[0] // 3
                    p.set_data(mx.nd.array(w[i * u:(i + 1) * u]))


def test_tp_bert_matches_plain():
    rng = np.random.RandomState(0)
    ids = rng.randint(0, VOCAB, (4, SEQ)).astype(np.float32)
    types = np.zeros((4, SEQ), np.float32)

    plain = _tiny_bert()
    plain.initialize(ctx=mx.cpu())
    plain.hybridize()
    mlm_want, nsp_want = plain(mx.nd.array(ids), mx.nd.array(types))

    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices("cpu")[:4])
    tp = _tiny_bert(tp_mesh=mesh)
    tp.initialize(ctx=mx.cpu())
    tp.hybridize()
    tp(mx.nd.array(ids), mx.nd.array(types))  # materialize shapes
    _copy_weights(plain, tp)

    # unsharded tp-mode forward must already match
    mlm_got, nsp_got = tp(mx.nd.array(ids), mx.nd.array(types))
    np.testing.assert_allclose(mlm_got.asnumpy(), mlm_want.asnumpy(),
                               rtol=2e-4, atol=2e-5)

    # now shard over the mesh and run the jitted sharded forward
    tp.shard_tp()
    pure_fn, pnames, pmap = tp.functionalize(training=False)
    pvals = {n: pmap[n]._data._data for n in pnames}
    xs = jax.device_put(jnp.asarray(ids),
                        NamedSharding(mesh, P("dp", None)))
    ts = jax.device_put(jnp.asarray(types),
                        NamedSharding(mesh, P("dp", None)))

    @jax.jit
    def fwd(pv, a, b):
        outs, _ = pure_fn(pv, [a, b], jax.random.PRNGKey(0))
        return outs

    mlm_sh, nsp_sh = fwd(pvals, xs, ts)
    np.testing.assert_allclose(np.asarray(mlm_sh), mlm_want.asnumpy(),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(nsp_sh), nsp_want.asnumpy(),
                               rtol=2e-3, atol=2e-4)

    # the encoder params really are tp-sharded
    cell = tp.encoder.cells[0]
    qw = cell.attention.query_weight._data._data
    assert len(qw.sharding.device_set) == 4
    spec = qw.sharding.spec
    assert spec[0] == "tp", spec


def test_tp_bert_train_step_grads():
    """Sharded training step: grads flow, loss finite, params stay
    sharded after an update."""
    mesh = make_mesh({"dp": 2, "tp": 2}, devices=jax.devices("cpu")[:4])
    rng = np.random.RandomState(1)
    ids = rng.randint(0, VOCAB, (4, SEQ)).astype(np.float32)
    labels = rng.randint(0, VOCAB, (4, SEQ)).astype(np.int32)

    net = _tiny_bert(tp_mesh=mesh)
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    net(mx.nd.array(ids))
    net.shard_tp()
    pure_fn, pnames, pmap = net.functionalize(training=True)
    pvals = {n: pmap[n]._data._data for n in pnames}
    xs = jax.device_put(jnp.asarray(ids),
                        NamedSharding(mesh, P("dp", None)))
    ys = jax.device_put(jnp.asarray(labels),
                        NamedSharding(mesh, P("dp", None)))

    def loss_fn(pv):
        (mlm, _nsp), _ = pure_fn(pv, [xs], jax.random.PRNGKey(0))
        logp = jax.nn.log_softmax(mlm, axis=-1)
        picked = jnp.take_along_axis(logp, ys[..., None], axis=-1)
        return -jnp.mean(picked)

    loss, grads = jax.jit(jax.value_and_grad(loss_fn))(pvals)
    assert np.isfinite(float(loss))
    qname = [n for n in pnames if "query_weight" in n][0]
    g = grads[qname]
    assert len(g.sharding.device_set) == 4
    assert float(jnp.abs(g).max()) > 0
