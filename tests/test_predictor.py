"""Predictor / AOT artifact tests (reference: C predict API tests +
amalgamation deploy)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _trained_net(tmp_path):
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(5))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(2, 3, 8, 8).astype(np.float32))
    net(x)
    return net, x


def test_predictor_from_export(tmp_path):
    net, x = _trained_net(tmp_path)
    want = net(x).asnumpy()
    prefix = str(tmp_path / "m")
    net.export(prefix)

    pred = mx.Predictor(prefix + "-symbol.json", prefix + "-0000.params")
    pred.forward(data=x)
    got = pred.get_output(0).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    with pytest.raises(mx.MXNetError):
        pred.set_input("not_an_input", x)


def test_predictor_jit_cache_lru_bound(tmp_path):
    """ISSUE 8 satellite: the per-input-shape jit cache is LRU-bounded
    (one compiled program per shape class cannot grow without bound);
    evictions count into serving.compile_evictions and an evicted shape
    still serves correctly on return (it just recompiles)."""
    from mxnet_tpu import telemetry
    net, x = _trained_net(tmp_path)
    prefix = str(tmp_path / "m")
    net.export(prefix)
    telemetry.enable()
    telemetry.reset("serving.")
    try:
        pred = mx.Predictor(prefix + "-symbol.json",
                            prefix + "-0000.params", jit_cache_size=2)
        shapes = [(1, 3, 8, 8), (2, 3, 8, 8), (3, 3, 8, 8)]
        wants = {}
        for s in shapes:
            xs = np.random.RandomState(s[0]).randn(*s).astype(np.float32)
            wants[s] = (xs, net(mx.nd.array(xs)).asnumpy())
        for s in shapes:
            pred.forward(data=wants[s][0])
        assert len(pred._jit_cache) == 2          # bounded
        assert telemetry.counter("serving.compile_evictions").value == 1
        # the evicted (oldest) shape still serves -- recompiled, correct
        xs, want = wants[shapes[0]]
        pred.forward(data=xs)
        np.testing.assert_allclose(pred.get_output(0).asnumpy(), want,
                                   rtol=1e-4, atol=1e-4)
        assert telemetry.counter("serving.compile_evictions").value == 2
        # hitting a cached shape moves it to MRU instead of evicting
        pred.forward(data=xs)
        assert telemetry.counter("serving.compile_evictions").value == 2
    finally:
        telemetry.reset("serving.")
        telemetry.disable()


def test_compiled_artifact_roundtrip(tmp_path):
    net, x = _trained_net(tmp_path)
    want = net(x).asnumpy()
    path = str(tmp_path / "model.mxa")
    mx.predictor.export_compiled(net, path, [(2, 3, 8, 8)])

    served = mx.CompiledPredictor(path)
    outs = served(x)
    # cross-platform artifact: tolerate platform numeric differences
    np.testing.assert_allclose(outs[0].asnumpy(), want, rtol=1e-2,
                               atol=1e-3)
    # the artifact is self-contained: callable with raw numpy too
    outs2 = served(x.asnumpy())
    np.testing.assert_allclose(outs2[0].asnumpy(), want, rtol=1e-2,
                               atol=1e-3)
    assert served.meta["num_outputs"] == 1
