"""Predictor / AOT artifact tests (reference: C predict API tests +
amalgamation deploy)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _trained_net(tmp_path):
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(5))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(2, 3, 8, 8).astype(np.float32))
    net(x)
    return net, x


def test_predictor_from_export(tmp_path):
    net, x = _trained_net(tmp_path)
    want = net(x).asnumpy()
    prefix = str(tmp_path / "m")
    net.export(prefix)

    pred = mx.Predictor(prefix + "-symbol.json", prefix + "-0000.params")
    pred.forward(data=x)
    got = pred.get_output(0).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)

    with pytest.raises(mx.MXNetError):
        pred.set_input("not_an_input", x)


def test_compiled_artifact_roundtrip(tmp_path):
    net, x = _trained_net(tmp_path)
    want = net(x).asnumpy()
    path = str(tmp_path / "model.mxa")
    mx.predictor.export_compiled(net, path, [(2, 3, 8, 8)])

    served = mx.CompiledPredictor(path)
    outs = served(x)
    # cross-platform artifact: tolerate platform numeric differences
    np.testing.assert_allclose(outs[0].asnumpy(), want, rtol=1e-2,
                               atol=1e-3)
    # the artifact is self-contained: callable with raw numpy too
    outs2 = served(x.asnumpy())
    np.testing.assert_allclose(outs2[0].asnumpy(), want, rtol=1e-2,
                               atol=1e-3)
    assert served.meta["num_outputs"] == 1
