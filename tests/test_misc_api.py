"""Small API-parity surfaces: gluon.contrib, mx.name, mx.AttrScope,
mx.lr_scheduler alias (reference: the corresponding python/mxnet
modules)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon


def test_hybrid_concurrent_and_identity():
    net = gluon.contrib.nn.HybridConcurrent(axis=1)
    net.add(gluon.nn.Dense(3, flatten=False),
            gluon.contrib.nn.Identity(),
            gluon.nn.Dense(2, flatten=False))
    net.initialize()
    x = mx.nd.ones((4, 5))
    out = net(x)
    assert out.shape == (4, 3 + 5 + 2)
    net.hybridize()
    out2 = net(x)
    np.testing.assert_allclose(out2.asnumpy(), out.asnumpy(), rtol=1e-6)


def test_concurrent_imperative():
    net = gluon.contrib.nn.Concurrent(axis=-1)
    net.add(gluon.contrib.nn.Identity(), gluon.contrib.nn.Identity())
    out = net(mx.nd.ones((2, 3)))
    assert out.shape == (2, 6)


def test_sparse_embedding_forward():
    emb = gluon.contrib.nn.SparseEmbedding(10, 4)
    emb.initialize()
    out = emb(mx.nd.array(np.array([1.0, 3.0])))
    assert out.shape == (2, 4)


def test_name_prefix_scope():
    with mx.name.Prefix("stage1_"):
        s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2)
    assert s.name.startswith("stage1_")


def test_attr_scope_on_symbols():
    with mx.AttrScope(ctx_group="dev1", __custom__="yes"):
        s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2,
                                  name="fc")
    assert s.attr("ctx_group") == "dev1"
    assert s.attr("__custom__") == "yes"
    # attrs survive the json round trip
    s2 = mx.sym.load_json(s.tojson())
    assert s2.attr("ctx_group") == "dev1"


def test_lr_scheduler_alias():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5,
                                            base_lr=1.0)
    assert sched(0) == 1.0
    assert sched(11) == 0.5   # reference decays once num_update > step


def test_attr_scope_covers_variables_and_auto_vars():
    with mx.AttrScope(ctx_group="dev2"):
        v = mx.sym.var("data")
        s = mx.sym.FullyConnected(v, num_hidden=2, name="fc")
    assert v.attr("ctx_group") == "dev2"
    weight_nodes = [n for n in s._topo()
                    if n.op is None and n.name == "fc_weight"]
    assert weight_nodes and weight_nodes[0].attrs.get(
        "ctx_group") == "dev2"


def test_pipeline_stage_count_mismatch_raises():
    import jax
    import jax.numpy as jnp
    import pytest
    from mxnet_tpu.parallel import (make_mesh, pipeline_apply,
                                    stack_stage_params)
    devs = jax.devices("cpu")
    if len(devs) < 4:
        pytest.skip("need 4 cpu devices")
    mesh = make_mesh({"pp": 4}, devices=devs[:4])
    trees = [{"w": jnp.ones((2, 2))} for _ in range(8)]   # 8 != 4
    stacked = stack_stage_params(trees)
    xs = jnp.ones((2, 2, 2))
    with pytest.raises(mx.MXNetError, match="stage"):
        pipeline_apply(lambda p, x: x @ p["w"], stacked, xs, mesh)
