"""Transformer ops, attention layers, BERT (BASELINE config 3).

Reference analogs: ``tests/python/unittest/test_operator.py`` transformer
op tests, GluonNLP BERT tests."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _naive_mha(qkv, heads):
    seq, b, emb3 = qkv.shape
    hd = emb3 // (3 * heads)
    x = qkv.reshape(seq, b, heads, 3, hd)
    q = np.transpose(x[:, :, :, 0], (1, 2, 0, 3)).reshape(b * heads, seq, hd)
    k = np.transpose(x[:, :, :, 1], (1, 2, 0, 3)).reshape(b * heads, seq, hd)
    v = np.transpose(x[:, :, :, 2], (1, 2, 0, 3)).reshape(b * heads, seq, hd)
    s = np.einsum("bqd,bkd->bqk", q, k) / np.sqrt(hd)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    o = np.einsum("bqk,bkd->bqd", p, v)
    return s, np.transpose(o.reshape(b, heads, seq, hd),
                           (2, 0, 1, 3)).reshape(seq, b, heads * hd)


def test_interleaved_selfatt_matches_naive():
    rng = np.random.RandomState(0)
    seq, b, h, hd = 6, 2, 3, 4
    qkv = rng.randn(seq, b, h * 3 * hd).astype(np.float32)
    scores_ref, out_ref = _naive_mha(qkv, h)
    scores = mx.nd.interleaved_matmul_selfatt_qk(mx.nd.array(qkv), heads=h)
    np.testing.assert_allclose(scores.asnumpy(), scores_ref, rtol=1e-4,
                               atol=1e-5)
    att = mx.nd.softmax(scores, axis=-1)
    out = mx.nd.interleaved_matmul_selfatt_valatt(mx.nd.array(qkv), att,
                                                  heads=h)
    np.testing.assert_allclose(out.asnumpy(), out_ref, rtol=1e-4, atol=1e-5)


def test_interleaved_encdec_matches_naive():
    rng = np.random.RandomState(1)
    qlen, kvlen, b, h, hd = 5, 7, 2, 2, 4
    q = rng.randn(qlen, b, h * hd).astype(np.float32)
    kv = rng.randn(kvlen, b, h * 2 * hd).astype(np.float32)
    scores = mx.nd.interleaved_matmul_encdec_qk(mx.nd.array(q),
                                                mx.nd.array(kv), heads=h)
    x = kv.reshape(kvlen, b, h, 2, hd)
    kn = np.transpose(x[:, :, :, 0], (1, 2, 0, 3)).reshape(b * h, kvlen, hd)
    vn = np.transpose(x[:, :, :, 1], (1, 2, 0, 3)).reshape(b * h, kvlen, hd)
    qn = np.transpose(q.reshape(qlen, b, h, hd),
                      (1, 2, 0, 3)).reshape(b * h, qlen, hd)
    s_ref = np.einsum("bqd,bkd->bqk", qn, kn) / np.sqrt(hd)
    np.testing.assert_allclose(scores.asnumpy(), s_ref, rtol=1e-4, atol=1e-5)
    att = mx.nd.softmax(scores, axis=-1)
    out = mx.nd.interleaved_matmul_encdec_valatt(mx.nd.array(kv), att,
                                                 heads=h)
    p = att.asnumpy()
    o_ref = np.einsum("bqk,bkd->bqd", p, vn)
    o_ref = np.transpose(o_ref.reshape(b, h, qlen, hd),
                         (2, 0, 1, 3)).reshape(qlen, b, h * hd)
    np.testing.assert_allclose(out.asnumpy(), o_ref, rtol=1e-4, atol=1e-5)


def test_flash_attention_xla_matches_naive_and_grads():
    rng = np.random.RandomState(2)
    q = rng.randn(4, 8, 16).astype(np.float32)
    k = rng.randn(4, 8, 16).astype(np.float32)
    v = rng.randn(4, 8, 16).astype(np.float32)
    qn, kn, vn = mx.nd.array(q), mx.nd.array(k), mx.nd.array(v)
    out = mx.nd.flash_attention(qn, kn, vn)
    s = np.einsum("bqd,bkd->bqk", q, k) / 4.0
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    ref = np.einsum("bqk,bkd->bqd", p, v)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)
    # causal
    outc = mx.nd.flash_attention(qn, kn, vn, causal=True).asnumpy()
    sc = np.where(np.tril(np.ones((8, 8))) > 0, s, -1e30)
    pc = np.exp(sc - sc.max(-1, keepdims=True))
    pc /= pc.sum(-1, keepdims=True)
    np.testing.assert_allclose(outc, np.einsum("bqk,bkd->bqd", pc, v),
                               rtol=1e-4, atol=1e-5)
    # custom-vjp gradients vs finite differences on a scalar loss
    for t in (qn, kn, vn):
        t.attach_grad()
    with autograd.record():
        o = mx.nd.flash_attention(qn, kn, vn)
        loss = (o * o).sum()
    loss.backward()
    eps = 1e-3
    qpert = q.copy()
    qpert[0, 0, 0] += eps
    o1 = mx.nd.flash_attention(mx.nd.array(qpert), kn, vn)
    l1 = float((o1 * o1).sum().asscalar())
    l0 = float(loss.asscalar())
    fd = (l1 - l0) / eps
    np.testing.assert_allclose(float(qn.grad.asnumpy()[0, 0, 0]), fd,
                               rtol=5e-2, atol=1e-2)


def test_flash_attention_pallas_interpret_matches_xla():
    """Run the actual Pallas kernel in interpreter mode (CPU) against the
    XLA reference path."""
    from mxnet_tpu.ops.pallas.flash_attention import \
        flash_attention_fwd_pallas
    from mxnet_tpu.ops.transformer import _attention_reference
    rng = np.random.RandomState(3)
    import jax
    import jax.numpy as jnp
    cpu = jax.devices("cpu")[0]
    q = jax.device_put(jnp.asarray(rng.randn(2, 16, 8).astype(np.float32)), cpu)
    k = jax.device_put(jnp.asarray(rng.randn(2, 16, 8).astype(np.float32)), cpu)
    v = jax.device_put(jnp.asarray(rng.randn(2, 16, 8).astype(np.float32)), cpu)
    for causal in (False, True):
        out, _lse = flash_attention_fwd_pallas(
            q, k, v, causal=causal, scale=0.3, block_q=8, block_k=8,
            interpret=True)
        ref = _attention_reference(q, k, v, causal, 0.3)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-4, atol=1e-4)


def test_multihead_attention_layer_masked_vs_unmasked():
    mx.random.seed(0)
    layer = gluon.nn.MultiHeadAttention(units=16, num_heads=4)
    layer.initialize(ctx=mx.cpu())
    rng = np.random.RandomState(4)
    x = mx.nd.array(rng.randn(2, 6, 16).astype(np.float32))
    out = layer(x)
    assert out.shape == (2, 6, 16)
    # full-ones mask must match the unmasked (flash) path
    mask = mx.nd.ones((2, 6, 6))
    out_masked = layer(x, mask)
    np.testing.assert_allclose(out_masked.asnumpy(), out.asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_transformer_encoder_shapes_and_grad():
    mx.random.seed(0)
    enc = gluon.nn.TransformerEncoder(units=16, hidden_size=32,
                                      num_layers=2, num_heads=2,
                                      max_length=32)
    enc.initialize(ctx=mx.cpu())
    x = mx.nd.array(np.random.RandomState(5).randn(2, 8, 16)
                    .astype(np.float32))
    names = list(enc.collect_params().keys())
    assert len(names) == len(set(names))
    out = enc(x)
    assert out.shape == (2, 8, 16)
    for p in enc.collect_params().values():
        p._data.attach_grad() if False else None
    tr = gluon.Trainer(enc.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    with autograd.record():
        l = (enc(x) ** 2.0).mean()
    l.backward()
    tr.step(2)


def test_bert_small_pretrain_step_and_hybridize():
    mx.random.seed(0)
    net = gluon.model_zoo.bert_small(vocab_size=500, max_length=64)
    net.initialize(ctx=mx.cpu())
    rng = np.random.RandomState(6)
    ids = mx.nd.array(rng.randint(0, 500, (2, 16)).astype(np.float32))
    tt = mx.nd.zeros((2, 16))
    mlm, nsp = net(ids, tt)
    assert mlm.shape == (2, 16, 500)
    assert nsp.shape == (2, 2)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3}, kvstore=None)
    labels = mx.nd.array(rng.randint(0, 500, (2, 16)).astype(np.float32))
    nsp_labels = mx.nd.array(np.array([0, 1], np.float32))
    losses = []
    for _ in range(8):
        with autograd.record():
            mlm, nsp = net(ids, tt)
            l = loss_fn(mlm.reshape((-1, 500)), labels.reshape((-1,))) \
                .mean() + loss_fn(nsp, nsp_labels).mean()
        l.backward()
        tr.step(2)
        losses.append(float(l.asscalar()))
    assert losses[-1] < losses[0]
    net.hybridize()
    mlm2, nsp2 = net(ids, tt)
    assert mlm2.shape == (2, 16, 500)


def test_bert_trainstep_compiled():
    """BERT through the fused TrainStep (the bench path)."""
    from mxnet_tpu.parallel import TrainStep
    mx.random.seed(0)
    net = gluon.model_zoo.bert_small(vocab_size=200, max_length=32,
                                     dropout=0.0)
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    class MLMLoss(gluon.HybridBlock):
        def hybrid_forward(self, F, outs, labels):
            mlm, nsp = outs
            v = mlm.shape[-1]
            return loss_fn(mlm.reshape((-1, v)), labels.reshape((-1,)))

    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3}, kvstore=None)
    step = TrainStep(net, MLMLoss(), tr)
    rng = np.random.RandomState(7)
    ids = mx.nd.array(rng.randint(0, 200, (4, 16)).astype(np.float32))
    labels = mx.nd.array(rng.randint(0, 200, (4, 16)).astype(np.float32))
    first = float(step(ids, labels).asscalar())
    for _ in range(5):
        last = float(step(ids, labels).asscalar())
    assert last < first


def test_layernorm_pallas_interpret_matches_xla():
    """The fused Pallas LayerNorm kernel in interpreter mode against the
    XLA path (the same kernel runs compiled on TPU)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu.ops.pallas.layernorm import layernorm_fwd_pallas
    rng = np.random.RandomState(0)
    x = rng.randn(64, 96).astype(np.float32)
    g = (rng.rand(96) + 0.5).astype(np.float32)
    b = rng.randn(96).astype(np.float32)
    got = np.asarray(layernorm_fwd_pallas(x, g, b, interpret=True))
    ref = mx.nd.LayerNorm(mx.nd.array(x), mx.nd.array(g),
                          mx.nd.array(b)).asnumpy()
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)
