"""Module API tests (reference: ``tests/python/unittest/test_module.py``)."""
import glob
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _mlp_symbol(num_hidden=32, num_classes=4):
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, num_hidden=num_hidden, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")


def _toy_iter(n=64, dim=8, num_classes=4, batch_size=16, seed=0):
    centers = np.random.RandomState(42).randn(num_classes, dim) * 3
    rng = np.random.RandomState(seed)
    y = rng.randint(0, num_classes, size=n)
    x = centers[y] + rng.randn(n, dim) * 0.3
    return mx.io.NDArrayIter(x.astype(np.float32),
                             y.astype(np.float32), batch_size,
                             shuffle=True)


def test_infer_shape_deduces_weights():
    s = _mlp_symbol(num_hidden=32, num_classes=4)
    arg_shapes, out_shapes, _ = s.infer_shape(data=(16, 8))
    shapes = dict(zip(s.list_arguments(), arg_shapes))
    assert shapes["fc1_weight"] == (32, 8)
    assert shapes["fc1_bias"] == (32,)
    assert shapes["fc2_weight"] == (4, 32)
    assert shapes["softmax_label"] == (16,)
    assert out_shapes == [(16, 4)]


def test_infer_shape_conv():
    data = sym.var("data")
    c = sym.Convolution(data, num_filter=8, kernel=(3, 3), pad=(1, 1),
                        name="conv0")
    b = sym.BatchNorm(c, name="bn0")
    arg_shapes, out_shapes, _ = b.infer_shape(data=(2, 3, 8, 8))
    shapes = dict(zip(b.list_arguments(), arg_shapes))
    assert shapes["conv0_weight"] == (8, 3, 3, 3)
    assert shapes["bn0_gamma"] == (8,)
    assert out_shapes[0] == (2, 8, 8, 8)


def test_infer_shape_partial():
    s = _mlp_symbol()
    arg_shapes, _, _ = s.infer_shape_partial()
    # nothing known -> every shape None, no raise
    assert all(a is None for a in arg_shapes)


def test_module_fit_mnist_style():
    """An end-to-end Module.fit run must drive training accuracy well
    above chance (reference: ``test_module.py :: test_module_fit``)."""
    train = _toy_iter()
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(16, 2))
    metric = mx.metric.Accuracy()
    mod.score(_toy_iter(seed=1), metric)
    assert metric.get()[1] > 0.8, metric.get()


def test_module_forward_backward_update():
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    batch = mx.io.DataBatch(
        data=[mx.nd.array(np.random.randn(16, 8).astype(np.float32))],
        label=[mx.nd.array(np.random.randint(0, 4, 16).astype(np.float32))])
    before, _ = mod.get_params()
    mod.forward(batch, is_train=True)
    out = mod.get_outputs()[0]
    assert out.shape == (16, 4)
    np.testing.assert_allclose(out.asnumpy().sum(axis=1),
                               np.ones(16), rtol=1e-5)
    mod.backward()
    mod.update()
    after, _ = mod.get_params()
    assert not np.allclose(before["fc1_weight"].asnumpy(),
                           after["fc1_weight"].asnumpy())


def test_module_save_load_checkpoint(tmp_path):
    prefix = str(tmp_path / "mlp")
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 8))])
    mod.init_params()
    mod.save_checkpoint(prefix, 3)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0003.params")

    loaded = mx.mod.Module.load(prefix, 3)
    loaded.bind(data_shapes=[("data", (4, 8))])
    loaded.init_params()
    a0, _ = mod.get_params()
    a1, _ = loaded.get_params()
    for k in a0:
        np.testing.assert_allclose(a0[k].asnumpy(), a1[k].asnumpy())

    # the model.py free functions agree
    symbol, arg_params, aux_params = mx.model.load_checkpoint(prefix, 3)
    assert set(arg_params) == set(a0)


def test_module_optimizer_state_resume(tmp_path):
    """save_optimizer_states=True + Module.load(load_optimizer_states=True)
    must restore momentum buffers (reference: ``Module.load``)."""
    prefix = str(tmp_path / "resume")
    train = _toy_iter(n=32)
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=2, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)
    states0 = mod._updater.states

    loaded = mx.mod.Module.load(prefix, 2, load_optimizer_states=True)
    loaded.bind(data_shapes=[("data", (16, 8))],
                label_shapes=[("softmax_label", (16,))])
    loaded.init_params()
    loaded.init_optimizer(optimizer="sgd",
                          optimizer_params={"learning_rate": 0.1,
                                            "momentum": 0.9})
    assert set(loaded._updater.states) == set(states0)
    for k, v in states0.items():
        np.testing.assert_allclose(loaded._updater.states[k].asnumpy(),
                                   v.asnumpy(), rtol=1e-6)


def test_do_checkpoint_callback(tmp_path):
    prefix = str(tmp_path / "cb")
    train = _toy_iter(n=32)
    mod = mx.mod.Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(train, num_epoch=2,
            optimizer_params={"learning_rate": 0.1},
            epoch_end_callback=mx.callback.do_checkpoint(prefix))
    assert sorted(os.path.basename(p)
                  for p in glob.glob(prefix + "-*.params")) == \
        ["cb-0001.params", "cb-0002.params"]


def test_bucketing_module():
    """Per-bucket executors share parameters (reference:
    ``test_module.py :: test_bucket_module``) -- the TPU shape-class
    answer to variable-length batches."""
    def sym_gen(seq_len):
        data = sym.var("data")
        fc = sym.FullyConnected(data, num_hidden=8, name="fc_shared",
                                flatten=False)
        pooled = sym.mean(fc, axis=1)
        out = sym.FullyConnected(pooled, num_hidden=2, name="out")
        return sym.SoftmaxOutput(out, name="softmax"), ("data",), \
            ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 10, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})

    for seq_len in (10, 5, 10, 7):
        batch = mx.io.DataBatch(
            data=[mx.nd.array(
                np.random.randn(4, seq_len, 6).astype(np.float32))],
            label=[mx.nd.array(np.zeros(4, dtype=np.float32))],
            provide_data=[mx.io.DataDesc("data", (4, seq_len, 6))],
            provide_label=[mx.io.DataDesc("softmax_label", (4,))])
        batch.bucket_key = seq_len
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
        assert mod.get_outputs()[0].shape == (4, 2)
    # shared parameter must be consistent across buckets after updates
    w_cur = mod._buckets[7]._exec.arg_dict["fc_shared_weight"]
    w_def = mod._buckets[10]._exec.arg_dict["fc_shared_weight"]
    np.testing.assert_allclose(w_cur.asnumpy(), w_def.asnumpy())
