"""Input pipeline: threaded decode, raw records, staging buffers
(reference: ``iter_image_recordio_2.cc :: ImageRecordIOParser2``)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu.image import ImageIter
from mxnet_tpu.io import ImageRecordIter


def _build(path, n, fmt="jpg", hw=64, crop=48):
    rng = np.random.RandomState(42)
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    raws = []
    for i in range(n):
        img = rng.randint(0, 255, (hw, hw, 3), dtype=np.uint8)
        header = recordio.IRHeader(0, float(i), i, 0)
        if fmt == "raw":
            raws.append(img[:crop, :crop].copy())
            rec.write_idx(i, recordio.pack(header, raws[-1].tobytes()))
        else:
            rec.write_idx(i, recordio.pack_img(header, img, quality=95))
    rec.close()
    return raws


def test_raw_records_roundtrip_exactly(tmp_path):
    p = str(tmp_path / "raw")
    raws = _build(p, 12, "raw")
    it = ImageIter(4, (3, 48, 48), path_imgrec=p + ".rec",
                   preprocess_threads=0, dtype="uint8")
    got = []
    labels = []
    try:
        while True:
            d, l, _pad = it.next_np()
            got.append(d)
            labels.append(l)
    except StopIteration:
        pass
    got = np.concatenate(got)
    labels = np.concatenate(labels)
    assert got.dtype == np.uint8
    for i in range(12):
        k = int(labels[i])
        np.testing.assert_array_equal(got[i], raws[k].transpose(2, 0, 1))


def test_threaded_decode_matches_sequential(tmp_path):
    """Regression for the shared-reader race: concurrent decode must
    produce the same batches as sequential (deterministic augmenters)."""
    p = str(tmp_path / "jpg")
    _build(p, 32, "jpg")

    def run(threads):
        it = ImageIter(8, (3, 48, 48), path_imgrec=p + ".rec",
                       preprocess_threads=threads)
        out = []
        try:
            while True:
                d, l, _pad = it.next_np()
                out.append((d, l))
        except StopIteration:
            pass
        return out

    seq = run(0)
    par = run(4)
    assert len(seq) == len(par) == 4
    for (d1, l1), (d2, l2) in zip(seq, par):
        np.testing.assert_array_equal(l1, l2)
        np.testing.assert_array_equal(d1, d2)


def test_next_np_out_buffer(tmp_path):
    p = str(tmp_path / "raw2")
    _build(p, 8, "raw")
    it = ImageIter(4, (3, 48, 48), path_imgrec=p + ".rec",
                   preprocess_threads=2, dtype="uint8")
    buf = np.empty((4, 3, 48, 48), np.uint8)
    d, l, _pad = it.next_np(out=buf)
    assert d is buf
    d2, _l2, _ = it.next_np()
    assert not np.array_equal(buf, d2)


def test_image_record_iter_normalizes(tmp_path):
    p = str(tmp_path / "jpg2")
    _build(p, 8, "jpg")
    it = ImageRecordIter(path_imgrec=p + ".rec", data_shape=(3, 48, 48),
                         batch_size=4, preprocess_threads=2,
                         mean_r=127.0, mean_g=127.0, mean_b=127.0,
                         std_r=58.0, std_g=58.0, std_b=58.0)
    batch = it.next()
    d = batch.data[0].asnumpy()
    assert d.shape == (4, 3, 48, 48)
    assert abs(float(d.mean())) < 1.0  # roughly centered


def test_im2rec_raw_encoding(tmp_path):
    from PIL import Image
    root = tmp_path / "imgs" / "cat"
    root.mkdir(parents=True)
    rng = np.random.RandomState(0)
    for i in range(4):
        Image.fromarray(rng.randint(0, 255, (32, 32, 3), dtype=np.uint8)) \
            .save(str(root / ("im%d.png" % i)))
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import im2rec
    prefix = str(tmp_path / "ds")
    im2rec.main([prefix, str(tmp_path / "imgs"), "--list"])
    im2rec.main([prefix + ".lst", str(tmp_path / "imgs"),
                 "--encoding", ".raw"])
    it = ImageIter(2, (3, 32, 32), path_imgrec=prefix + ".rec",
                   preprocess_threads=0, dtype="uint8")
    d, l, _pad = it.next_np()
    assert d.shape == (2, 3, 32, 32) and d.dtype == np.uint8


def test_uint8_batch_trains(tmp_path):
    """uint8 image batches feed Conv nets directly (cast to the weight
    dtype inside the op) -- the 4x-less-transfer pipeline contract."""
    from mxnet_tpu import autograd, gluon
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1, activation="relu"),
            gluon.nn.Flatten(), gluon.nn.Dense(3))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0).randint(
        0, 255, (2, 3, 8, 8)).astype(np.uint8))
    assert x.dtype == np.uint8
    out = net(x)
    assert out.shape == (2, 3)
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                       kvstore=None)
    from mxnet_tpu.parallel import TrainStep
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                     mesh=None)
    y = mx.nd.array(np.zeros((2,), np.float32))
    l = float(step(x, y).asscalar())
    assert np.isfinite(l)


def test_process_pool_decode_matches_serial(tmp_path):
    """preprocess_procs: forkserver workers decode into the
    SharedMemory slab; batches must match the serial path exactly
    (deterministic augs).  The pool must NOT fork this
    (JAX-multithreaded) process: the os.fork RuntimeWarning is
    escalated to an error here (VERDICT r4 #5 -- the fork-based pool
    was a deadlock time bomb)."""
    import warnings
    p = str(tmp_path / "procjpg")
    _build(p, 24, "jpg")

    def run(**kw):
        it = ImageIter(8, (3, 48, 48), path_imgrec=p + ".rec", **kw)
        try:
            out = []
            while True:
                d, l, _pad = it.next_np()
                out.append((d.copy(), l.copy()))
        except StopIteration:
            return out
        finally:
            it.close()

    serial = run(preprocess_threads=0)
    with warnings.catch_warnings():
        # CPython emits the multithreaded-fork hazard as
        # DeprecationWarning (3.12+) and RuntimeWarning in other
        # paths/versions; escalate any fork warning
        warnings.filterwarnings("error", message=".*fork.*",
                                category=Warning)
        pooled = run(preprocess_procs=2)
    assert len(serial) == len(pooled) == 3
    for (d0, l0), (d1, l1) in zip(serial, pooled):
        np.testing.assert_array_equal(l0, l1)
        np.testing.assert_allclose(d0, d1)


def test_process_pool_requires_recordio(tmp_path):
    lst = tmp_path / "x.lst"
    lst.write_text("0\t1.0\tnope.jpg\n")
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        ImageIter(2, (3, 8, 8), path_imglist=str(lst),
                  preprocess_procs=2)
