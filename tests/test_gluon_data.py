"""gluon.data (reference: ``tests/python/unittest/test_gluon_data.py``)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.data import ArrayDataset, BatchSampler, DataLoader
from mxnet_tpu.gluon.data import RandomSampler, SequentialSampler
from mxnet_tpu.gluon.data.vision import transforms


def test_array_dataset():
    X = np.random.rand(10, 3).astype(np.float32)
    y = np.arange(10, dtype=np.float32)
    ds = ArrayDataset(X, y)
    assert len(ds) == 10
    x0, y0 = ds[3]
    assert (x0 == X[3]).all() and y0 == 3


def test_dataset_transform():
    ds = ArrayDataset(np.arange(5, dtype=np.float32))
    t = ds.transform(lambda x: x * 2)
    assert t[2] == 4
    ds2 = ArrayDataset(np.arange(4, dtype=np.float32),
                       np.arange(4, dtype=np.float32))
    tf = ds2.transform_first(lambda x: x + 100)
    x, y = tf[1]
    assert x == 101 and y == 1


def test_samplers():
    assert list(SequentialSampler(4)) == [0, 1, 2, 3]
    assert sorted(RandomSampler(5)) == list(range(5))
    bs = BatchSampler(SequentialSampler(5), 2, "keep")
    assert list(bs) == [[0, 1], [2, 3], [4]]
    bs2 = BatchSampler(SequentialSampler(5), 2, "discard")
    assert list(bs2) == [[0, 1], [2, 3]]


def test_dataloader_basic():
    X = np.random.rand(10, 3).astype(np.float32)
    y = np.arange(10, dtype=np.float32)
    loader = DataLoader(ArrayDataset(X, y), batch_size=4)
    batches = list(loader)
    assert len(batches) == 3
    xb, yb = batches[0]
    assert xb.shape == (4, 3)
    assert yb.asnumpy().tolist() == [0, 1, 2, 3]


def test_dataloader_shuffle_lastbatch():
    ds = ArrayDataset(np.arange(10, dtype=np.float32))
    loader = DataLoader(ds, batch_size=3, shuffle=True, last_batch="discard")
    batches = list(loader)
    assert len(batches) == 3
    seen = np.concatenate([b.asnumpy() for b in batches])
    assert len(set(seen.tolist())) == 9


def test_dataloader_workers():
    X = np.random.rand(20, 3).astype(np.float32)
    loader = DataLoader(ArrayDataset(X), batch_size=5, num_workers=2)
    batches = list(loader)
    assert len(batches) == 4
    got = np.concatenate([b.asnumpy() for b in batches])
    np.testing.assert_allclose(got, X)  # order preserved


def test_transforms():
    img = (np.random.rand(8, 6, 3) * 255).astype(np.uint8)
    t = transforms.ToTensor()(mx.nd.array(img, dtype="uint8"))
    assert t.shape == (3, 8, 6)
    assert t.asnumpy().max() <= 1.0
    n = transforms.Normalize(mean=(0.5, 0.5, 0.5), std=(0.5, 0.5, 0.5))(t)
    assert n.asnumpy().min() >= -1.001
    r = transforms.Resize(4)(mx.nd.array(img, dtype="uint8"))
    assert r.shape == (4, 4, 3)
    c = transforms.CenterCrop(4)(mx.nd.array(img, dtype="uint8"))
    assert c.shape == (4, 4, 3)
    rc = transforms.RandomResizedCrop(5)(mx.nd.array(img, dtype="uint8"))
    assert rc.shape == (5, 5, 3)
    comp = transforms.Compose([transforms.Resize(4), transforms.ToTensor()])
    assert comp(mx.nd.array(img, dtype="uint8")).shape == (3, 4, 4)


def test_mnist_synthetic_fallback():
    ds = gluon.data.vision.MNIST(root="/nonexistent-path", train=False)
    assert ds.synthetic
    assert len(ds) == 10000
    x, y = ds[0]
    assert x.shape == (28, 28, 1)
    assert 0 <= int(y) < 10


def test_recordio_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(5):
        header = recordio.IRHeader(0, float(i), i, 0)
        w.write_idx(i, recordio.pack(header, b"payload%d" % i))
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert r.keys == [0, 1, 2, 3, 4]
    h, payload = recordio.unpack(r.read_idx(3))
    assert h.label == 3.0
    assert payload == b"payload3"


def test_recordio_image_roundtrip(tmp_path):
    from mxnet_tpu import recordio
    img = (np.random.rand(16, 16, 3) * 255).astype(np.uint8)
    packed = recordio.pack_img(recordio.IRHeader(0, 7.0, 0, 0), img,
                               img_fmt=".png")
    header, decoded = recordio.unpack_img(packed)
    assert header.label == 7.0
    np.testing.assert_array_equal(decoded, img)  # png is lossless


def test_image_record_dataset(tmp_path):
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "imgs.rec")
    idx_path = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(4):
        img = (np.random.rand(8, 8, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 2), i, 0), img, img_fmt=".png"))
    w.close()
    ds = gluon.data.vision.ImageRecordDataset(rec_path)
    assert len(ds) == 4
    img, label = ds[1]
    assert img.shape == (8, 8, 3)
    assert label == 1.0


def test_ndarray_iter():
    from mxnet_tpu.io import NDArrayIter
    X = np.random.rand(10, 4).astype(np.float32)
    y = np.arange(10, dtype=np.float32)
    it = NDArrayIter(X, y, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it.reset()
    assert len(list(it)) == 3


def test_prefetching_iter():
    from mxnet_tpu.io import NDArrayIter, PrefetchingIter
    X = np.random.rand(8, 2).astype(np.float32)
    it = PrefetchingIter(NDArrayIter(X, np.zeros(8), batch_size=4))
    batches = list(it)
    assert len(batches) == 2
