"""Post-training int8 quantization workflow (reference:
``mx.contrib.quantization :: quantize_model, calibrate``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib.quantization import (calibrate, quantize_graph,
                                            quantize_model)


def _export_sym(net, x):
    """Trace a hybrid block to (sym, arg_params, aux_params)."""
    net(mx.nd.array(x))
    sym = net(mx.sym.var("data"))
    arg, aux = {}, {}
    for p in net._all_params():
        if p._data is None:
            continue
        (aux if p._grad_req == "null" else arg)[p.name] = p.data()
    return sym, arg, aux


def _eval(sym, arg, aux, x):
    feeds = dict(arg)
    feeds.update(aux)
    feeds["data"] = mx.nd.array(x)
    out = sym.eval(**feeds)
    return (out[0] if isinstance(out, (list, tuple)) else out).asnumpy()


def _lenet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    return net


def test_quantized_graph_close_to_fp32():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 1, 12, 12).astype(np.float32)
    net = _lenet()
    sym, arg, aux = _export_sym(net, x)
    want = _eval(sym, arg, aux, x)

    for mode in ("naive", "entropy"):
        qsym, qarg, qaux = quantize_model(
            sym, arg, aux, calib_mode=mode,
            calib_data=[x, rng.randn(4, 1, 12, 12).astype(np.float32)])
        got = _eval(qsym, qarg, qaux, x)
        assert got.shape == want.shape
        # int8 sim: expect close-but-not-exact
        scale = np.abs(want).max() or 1.0
        assert np.abs(got - want).max() / scale < 0.1, mode


def test_calibrate_thresholds():
    rng = np.random.RandomState(1)
    x = rng.randn(4, 1, 12, 12).astype(np.float32)
    net = _lenet()
    sym, arg, aux = _export_sym(net, x)
    th = calibrate(sym, arg, aux, [x], calib_mode="naive")
    assert th, "no thresholds collected"
    for lo, hi in th.values():
        assert lo == -hi and hi > 0
    th_e = calibrate(sym, arg, aux, [x], calib_mode="entropy")
    assert set(th_e) == set(th)
    for k in th:
        assert 0 < th_e[k][1] <= th[k][1] * 1.001


def test_excluded_sym_names():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 1, 12, 12).astype(np.float32)
    net = _lenet()
    sym, arg, aux = _export_sym(net, x)
    conv_names = [n.name for n in sym._topo() if n.op == "Convolution"]
    qsym, qarg, _ = quantize_graph(sym, arg, aux, {},
                                   excluded_sym_names=tuple(conv_names))
    ops = [n.op for n in qsym._topo()]
    assert "Convolution" in ops           # excluded stays fp32
    assert "quantized_fully_connected" in ops


def test_quantize_model_validations():
    net = _lenet()
    x = np.zeros((2, 1, 12, 12), np.float32)
    sym, arg, aux = _export_sym(net, x)
    with pytest.raises(MXNetError):
        quantize_model(sym, arg, aux, calib_mode="entropy",
                       calib_data=None)
    with pytest.raises(MXNetError):
        quantize_model(sym, arg, aux, quantized_dtype="uint8",
                       calib_mode="none")


def test_mnist_accuracy_drop_below_1pct():
    """The reference's acceptance bar: int8 accuracy within 1% of fp32
    on the MNIST-style classification task (synthetic digits here; the
    separable structure mirrors the example pipeline)."""
    rng = np.random.RandomState(3)
    n_class, n, d = 4, 256, (1, 12, 12)
    protos = rng.randn(n_class, *d).astype(np.float32) * 2.0
    ys = rng.randint(0, n_class, (n,))
    xs = protos[ys] + rng.randn(n, *d).astype(np.float32) * 0.7

    net = _lenet()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3}, kvstore=None)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    from mxnet_tpu import autograd
    bs = 32
    net(mx.nd.array(xs[:bs]))
    for epoch in range(6):
        for i in range(0, n, bs):
            xb = mx.nd.array(xs[i:i + bs])
            yb = mx.nd.array(ys[i:i + bs].astype(np.float32))
            with autograd.record():
                loss = loss_fn(net(xb), yb).mean()
            loss.backward()
            trainer.step(bs)

    sym, arg, aux = _export_sym(net, xs[:bs])
    fp32_out = _eval(sym, arg, aux, xs)
    fp32_acc = float((fp32_out.argmax(1) == ys).mean())
    assert fp32_acc > 0.9, "fp32 net failed to train (acc %.2f)" % fp32_acc

    qsym, qarg, qaux = quantize_model(
        sym, arg, aux, calib_mode="entropy",
        calib_data=[xs[i:i + bs] for i in range(0, 128, bs)])
    q_out = _eval(qsym, qarg, qaux, xs)
    q_acc = float((q_out.argmax(1) == ys).mean())
    assert fp32_acc - q_acc < 0.01, \
        "int8 accuracy dropped %.3f -> %.3f" % (fp32_acc, q_acc)
