"""Channels-last (NHWC) layout support through conv/pool/BN and the
Gluon layers (reference: ``layout`` parameter of ``Convolution``,
``Pooling``; ``BatchNorm(axis=...)``).

A channels-last network with weights permuted from a channels-first one
must produce identical outputs -- the TPU-relevant property is that the
layout only permutes the logical view, never the math.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def _small_net(layout):
    c_axis = layout.index("C")
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, layout=layout,
                      activation="relu"),
            nn.BatchNorm(axis=c_axis),
            nn.MaxPool2D(2, 2, layout=layout),
            nn.Conv2D(16, kernel_size=3, strides=2, padding=1,
                      use_bias=False, layout=layout),
            nn.BatchNorm(axis=c_axis),
            nn.GlobalAvgPool2D(layout=layout),
            nn.Flatten(),
            nn.Dense(5))
    return net


def test_nhwc_matches_nchw():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 16, 16).astype(np.float32)

    a = _small_net("NCHW")
    a.initialize(ctx=mx.cpu())
    a.hybridize()
    ya = a(mx.nd.array(x)).asnumpy()

    b = _small_net("NHWC")
    b.initialize(ctx=mx.cpu())
    b.hybridize()
    xb = mx.nd.array(np.transpose(x, (0, 2, 3, 1)))
    b(xb)  # materialize deferred shapes
    from conftest import paired_params
    for pa, pb in paired_params(a, b):
        w = pa.data().asnumpy()
        # conv weights go OIHW -> OHWI (shape compare alone is ambiguous
        # when I == kh == kw)
        if w.ndim == 4 and "conv" in pa.name:
            w = np.transpose(w, (0, 2, 3, 1))
        assert pb.shape == w.shape
        pb.set_data(mx.nd.array(w))
    yb = b(xb).asnumpy()
    np.testing.assert_allclose(ya, yb, rtol=1e-5, atol=1e-5)


def test_nhwc_train_step():
    net = _small_net("NHWC")
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    from mxnet_tpu.parallel import TrainStep
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer,
                     mesh=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(8, 16, 16, 3).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 5, (8,)).astype(np.float32))
    l0 = float(step(x, y).asscalar())
    for _ in range(8):
        l1 = float(step(x, y).asscalar())
    assert np.isfinite(l0) and l1 < l0


def test_pooling_nhwc_matches_nchw():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    for pool_type in ("max", "avg"):
        for ceil_mode in (False, True):
            a = mx.nd.Pooling(mx.nd.array(x), kernel=(3, 3), stride=(2, 2),
                              pad=(1, 1), pool_type=pool_type,
                              pooling_convention="full" if ceil_mode
                              else "valid").asnumpy()
            b = mx.nd.Pooling(
                mx.nd.array(np.transpose(x, (0, 2, 3, 1))), kernel=(3, 3),
                stride=(2, 2), pad=(1, 1), pool_type=pool_type,
                pooling_convention="full" if ceil_mode else "valid",
                layout="NHWC").asnumpy()
            np.testing.assert_allclose(a, np.transpose(b, (0, 3, 1, 2)),
                                       rtol=1e-6, atol=1e-6)


def test_conv_transpose_nhwc_matches_nchw():
    rng = np.random.RandomState(2)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)
    w = rng.randn(4, 3, 3, 6).astype(np.float32)  # (in, kh, kw, out)
    out_nhwc = mx.nd.Deconvolution(
        mx.nd.array(np.transpose(x, (0, 2, 3, 1))), mx.nd.array(w), None,
        kernel=(3, 3), stride=(2, 2), pad=(1, 1), adj=(1, 1), num_filter=6,
        no_bias=True, layout="NHWC").asnumpy()
    out_nchw = mx.nd.Deconvolution(
        mx.nd.array(x), mx.nd.array(np.transpose(w, (0, 3, 1, 2))), None,
        kernel=(3, 3), stride=(2, 2), pad=(1, 1), adj=(1, 1), num_filter=6,
        no_bias=True).asnumpy()
    np.testing.assert_allclose(np.transpose(out_nhwc, (0, 3, 1, 2)),
                               out_nchw, rtol=1e-5, atol=1e-5)


def test_resnet_layout_kwarg():
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    net = resnet18_v1(layout="NHWC")
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    out = net(mx.nd.zeros((1, 32, 32, 3)))
    assert out.shape == (1, 1000)
