"""Optimizers/schedulers (reference: ``tests/python/unittest/test_optimizer.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import optimizer as opt
from mxnet_tpu.ndarray import NDArray
from mxnet_tpu.test_utils import assert_almost_equal


def _run_steps(optimizer, w0, grads):
    w = mx.nd.array(w0)
    state = optimizer.create_state(0, w)
    for g in grads:
        optimizer.update(0, w, mx.nd.array(g), state)
    return w.asnumpy()


def test_sgd_matches_numpy():
    w0 = np.array([1.0, -2.0], np.float32)
    g = np.array([0.5, 0.5], np.float32)
    o = opt.create("sgd", learning_rate=0.1, wd=0.0)
    got = _run_steps(o, w0, [g, g])
    assert_almost_equal(got, w0 - 0.1 * g * 2, rtol=1e-5)


def test_sgd_momentum_matches_numpy():
    w = np.array([1.0], np.float32)
    g = np.array([1.0], np.float32)
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    got = _run_steps(o, w, [g, g])
    # manual: m1=-0.1, w1=0.9; m2=0.9*-0.1-0.1=-0.19, w2=0.71
    assert_almost_equal(got, [0.71], rtol=1e-5)


def test_sgd_wd():
    w = np.array([1.0], np.float32)
    g = np.array([0.0], np.float32)
    o = opt.create("sgd", learning_rate=0.1, wd=0.1)
    got = _run_steps(o, w, [g])
    assert_almost_equal(got, [1.0 - 0.1 * 0.1], rtol=1e-5)


def test_adam_first_step():
    w = np.array([1.0], np.float32)
    g = np.array([0.5], np.float32)
    o = opt.create("adam", learning_rate=0.1)
    got = _run_steps(o, w, [g])
    # bias-corrected first step ~ lr * sign(g)
    assert abs(got[0] - (1.0 - 0.1)) < 1e-2


def test_rmsprop_runs():
    o = opt.create("rmsprop", learning_rate=0.01)
    got = _run_steps(o, np.ones(3, np.float32), [np.ones(3, np.float32)] * 3)
    assert (got < 1).all()


def test_adagrad_ftrl_signum_nag():
    for name in ("adagrad", "ftrl", "signum", "nag"):
        o = opt.create(name)
        got = _run_steps(o, np.ones(2, np.float32),
                         [np.full(2, 0.5, np.float32)] * 2)
        assert got.shape == (2,)


def test_lamb_trust_ratio():
    o = opt.create("lamb", learning_rate=0.01)
    w = np.full(4, 2.0, np.float32)
    got = _run_steps(o, w, [np.full(4, 0.1, np.float32)])
    assert (got < 2.0).all()


def test_lars_runs():
    o = opt.create("lars", learning_rate=0.1, momentum=0.9)
    got = _run_steps(o, np.ones(4, np.float32),
                     [np.full(4, 0.5, np.float32)] * 2)
    assert (got < 1.0).all()


def test_lars_single_trace_safe_registration():
    """ISSUE 6 satellite (ROADMAP item 1): the two ``class LARS``
    definitions are merged -- ``opt.create('lars')`` is pinned to the
    in-graph fused-op implementation (skip_list kept; no host-syncing
    ``.asscalar()`` trust ratio)."""
    import inspect
    o = opt.create("lars", learning_rate=0.1)
    assert o.skip_list == ("bias", "gamma", "beta")
    src = inspect.getsource(type(o).update)
    assert "asscalar" not in src, "host-syncing LARS copy resurfaced"
    assert "lars_update" in src
    # exactly one LARS definition in the module
    import mxnet_tpu.optimizer.optimizer as om
    count = inspect.getsource(om).count("class LARS")
    assert count == 1, "duplicate class LARS definitions: %d" % count


def test_lars_runs_in_graph_under_jit():
    """The merged LARS must trace: a whole compiled TrainStep (fwd +
    bwd + LARS update in ONE jit program) runs without
    TracerArrayConversionError and moves the weights."""
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "lars",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(8, 6).astype(np.float32))
    y = mx.nd.array(rng.rand(8, 4).astype(np.float32))
    losses = [float(step(x, y).asscalar())]   # materializes deferred init
    before = [p.data().asnumpy().copy()
              for p in net.collect_params().values()]
    losses += [float(step(x, y).asscalar()) for _ in range(4)]
    after = [p.data().asnumpy()
             for p in net.collect_params().values()]
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
    assert any(not np.allclose(b, a) for b, a in zip(before, after))


def test_optimizer_register_rejects_duplicates():
    with pytest.raises(mx.MXNetError):
        @opt.optimizer.register
        class SGD:   # noqa: F811 -- the point of the test
            pass


def test_clip_gradient():
    o = opt.create("sgd", learning_rate=1.0, clip_gradient=0.1)
    got = _run_steps(o, np.zeros(1, np.float32), [np.array([10.0], np.float32)])
    assert_almost_equal(got, [-0.1], rtol=1e-5)


def test_rescale_grad():
    o = opt.create("sgd", learning_rate=1.0, rescale_grad=0.5)
    got = _run_steps(o, np.zeros(1, np.float32), [np.array([1.0], np.float32)])
    assert_almost_equal(got, [-0.5], rtol=1e-5)


def test_updater_state_roundtrip():
    o = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    u = opt.get_updater(o)
    w = mx.nd.ones((3,))
    u(0, mx.nd.ones((3,)), w)
    blob = u.get_states()
    u2 = opt.get_updater(opt.create("sgd", learning_rate=0.1, momentum=0.9))
    u2.set_states(blob)
    assert 0 in u2.states


def test_lr_schedulers():
    s = opt.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    ms = opt.MultiFactorScheduler(step=[5, 10], factor=0.1, base_lr=1.0)
    assert ms(1) == 1.0
    assert abs(ms(6) - 0.1) < 1e-9
    assert abs(ms(11) - 0.01) < 1e-9
    ps = opt.PolyScheduler(max_update=100, base_lr=1.0, pwr=1)
    assert abs(ps(50) - 0.5) < 1e-6
    cs = opt.CosineScheduler(max_update=100, base_lr=1.0)
    assert abs(cs(50) - 0.5) < 1e-6
    assert cs(100) < 1e-6


def test_warmup():
    s = opt.PolyScheduler(max_update=100, base_lr=1.0, warmup_steps=10,
                          warmup_begin_lr=0.0)
    assert s(0) == 0.0
    assert abs(s(5) - 0.5) < 1e-6


def test_optimizer_with_scheduler():
    sched = opt.FactorScheduler(step=1, factor=0.5, base_lr=1.0)
    o = opt.create("sgd", learning_rate=1.0, lr_scheduler=sched)
    w = mx.nd.zeros((1,))
    st = o.create_state(0, w)
    o.update(0, w, mx.nd.ones((1,)), st)
    lr1 = o.learning_rate
    for _ in range(5):
        o.update(0, w, mx.nd.ones((1,)), st)
    assert o.learning_rate < lr1
