"""Pipeline (pp) and expert (ep) parallelism tests on the virtual CPU
mesh -- same shard_map/GSPMD paths as a v5e pod."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu.parallel import (MixtureOfExperts, make_mesh,
                                moe_load_balancing_loss, pipeline_apply,
                                shard_stacked_params, stack_stage_params)


def _mesh(shape):
    devs = jax.devices("cpu")
    n = int(np.prod(list(shape.values())))
    if len(devs) < n:
        pytest.skip("need %d cpu devices" % n)
    return make_mesh(shape, devices=devs[:n])


def _stage_fn(params, x):
    return jnp.tanh(x @ params["w"] + params["b"])


def test_pipeline_matches_sequential():
    mesh = _mesh({"pp": 4})
    rng = np.random.RandomState(0)
    d = 16
    trees = [{"w": jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.3),
              "b": jnp.asarray(rng.randn(d).astype(np.float32) * 0.1)}
             for _ in range(4)]
    stacked = shard_stacked_params(stack_stage_params(trees), mesh)
    xs = jnp.asarray(rng.randn(6, 8, d).astype(np.float32))  # M=6 mb=8

    got = np.asarray(pipeline_apply(_stage_fn, stacked, xs, mesh))

    want = np.asarray(xs)
    for t in trees:
        want = np.tanh(want @ np.asarray(t["w"]) + np.asarray(t["b"]))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_pipeline_grads_flow():
    mesh = _mesh({"pp": 4})
    rng = np.random.RandomState(1)
    d = 8
    trees = [{"w": jnp.asarray(rng.randn(d, d).astype(np.float32) * 0.3),
              "b": jnp.zeros((d,), jnp.float32)} for _ in range(4)]
    stacked_host = stack_stage_params(trees)
    xs = jnp.asarray(rng.randn(4, 4, d).astype(np.float32))

    def loss(params):
        out = pipeline_apply(_stage_fn, params, xs, mesh)
        return jnp.sum(out ** 2)

    # reference loss/grad: sequential stage application
    def ref_loss(params):
        y = xs
        for s in range(4):
            st = jax.tree_util.tree_map(lambda p: p[s], params)
            y = _stage_fn(st, y)
        return jnp.sum(y ** 2)

    sharded = shard_stacked_params(stacked_host, mesh)
    g = jax.grad(loss)(sharded)
    # reference on a pinned CPU device: uncommitted arrays would run on
    # the default accelerator whose matmul precision differs
    with jax.default_device(jax.devices("cpu")[0]):
        g_ref = jax.grad(ref_loss)(
            jax.device_put(stacked_host, jax.devices("cpu")[0]))
    np.testing.assert_allclose(np.asarray(g["w"]), np.asarray(g_ref["w"]),
                               rtol=5e-4, atol=1e-5)


def test_moe_forward_and_sharding():
    mesh = _mesh({"ep": 8})
    mx.random.seed(0)
    moe = MixtureOfExperts(num_experts=8, d_model=16, d_hidden=32,
                           capacity_factor=2.0, mesh=mesh)
    moe.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(64, 16).astype(np.float32))
    want = moe(x).asnumpy()          # single-device reference
    assert want.shape == (64, 16)
    assert np.abs(want).sum() > 0

    moe.shard(mesh)
    assert len(moe.w_up.data()._data.sharding.device_set) == 8
    pure_fn, pnames, pmap = moe.functionalize(training=False)
    pvals = {n: pmap[n]._data._data for n in pnames}

    @jax.jit
    def fwd(pvals, xv):
        outs, _ = pure_fn(pvals, [xv], jax.random.PRNGKey(0))
        return outs[0]

    xv = jax.device_put(x._data, NamedSharding(mesh, P()))
    got = np.asarray(fwd(pvals, xv))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-5)


def test_moe_capacity_drops_overflow():
    """With capacity far below load, overflowing tokens pass through as
    zeros (static shapes: drops, not reshards)."""
    mx.random.seed(0)
    moe = MixtureOfExperts(num_experts=2, d_model=4, d_hidden=8,
                           capacity_factor=0.1)
    moe.initialize()
    x = mx.nd.array(np.random.RandomState(1)
                    .randn(40, 4).astype(np.float32))
    out = moe(x).asnumpy()
    # capacity = 2 per expert -> at most 4 nonzero rows
    nonzero_rows = (np.abs(out).sum(axis=1) > 1e-7).sum()
    assert nonzero_rows <= 4


def test_moe_load_balance_loss():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(32, 8).astype(np.float32))
    gw = jnp.asarray(rng.randn(8, 4).astype(np.float32))
    aux = float(moe_load_balancing_loss(x, gw))
    assert aux >= 1.0 - 1e-3        # minimum at perfect balance is 1
