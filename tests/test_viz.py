"""Visualization + onnx-gate tests (reference: ``test_viz.py``)."""
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym


def _net():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, name="softmax")


def test_print_summary(capsys):
    total = mx.viz.print_summary(_net(), shape={"data": (2, 8)})
    out = capsys.readouterr().out
    assert "FullyConnected" in out and "fc1" in out
    assert "(2, 4)" in out            # output shape of fc2
    # learnable params only: fc1 16*8+16, fc2 4*16+4 (label excluded)
    assert total == 16 * 8 + 16 + 4 * 16 + 4


def test_plot_network_gated_or_works():
    try:
        dot = mx.viz.plot_network(_net())
        assert "fc1" in dot.source
    except mx.MXNetError as e:
        assert "graphviz" in str(e)


def test_onnx_error_paths():
    # real converter now (tests/test_onnx.py): unsupported op -> clean
    # MXNetError, missing file -> FileNotFoundError
    bad = sym.SoftmaxOutput(sym.var("data"), name="softmax")
    with pytest.raises(mx.MXNetError, match="no converter"):
        mx.onnx.export_model(bad, {}, onnx_file_path="/tmp/_gone.onnx")
    with pytest.raises(FileNotFoundError):
        mx.onnx.import_model("/tmp/_does_not_exist.onnx")
