"""Examples stay runnable (reference: CI runs example scripts).  Each
runs as a subprocess with tiny workloads."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout=420, env_extra=None):
    return subprocess.run(
        [sys.executable] + args, capture_output=True, text=True,
        timeout=timeout,
        env={**os.environ, "PYTHONPATH": REPO + os.pathsep +
             os.environ.get("PYTHONPATH", ""), **(env_extra or {})})


def test_module_mnist_example():
    out = _run([os.path.join(REPO, "examples", "module_mnist.py"),
                "--epochs", "1"])
    assert out.returncode == 0, out.stderr[-1500:]
    assert "final validation" in out.stdout


def test_rnn_bucketing_example():
    out = _run([os.path.join(REPO, "examples", "rnn_bucketing.py"),
                "--epochs", "1", "--batch-size", "16"])
    assert out.returncode == 0, out.stderr[-1500:]
    assert "buckets compiled: [8, 16, 32]" in out.stdout


def test_data_parallel_example():
    out = _run([os.path.join(REPO, "examples", "data_parallel.py"),
                "--steps", "3", "--batch-size", "32"])
    assert out.returncode == 0, out.stderr[-1500:]
    assert "img/s" in out.stdout


def test_gluon_mnist_example():
    out = _run([os.path.join(REPO, "examples", "gluon_mnist.py"),
                "--epochs", "1", "--batch-size", "64",
                "--max-batches", "20"], timeout=540)
    assert out.returncode == 0, out.stderr[-1500:]
    assert "accuracy=" in out.stdout


def test_dist_sync_train_example():
    out = _run([os.path.join(REPO, "tools", "launch.py"), "-n", "2",
                sys.executable, "-u",
                os.path.join(REPO, "examples", "dist_sync_train.py"),
                "--epochs", "2", "--samples", "128"],
               env_extra={"JAX_PLATFORMS": "cpu"})
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-1500:])
    assert out.stdout.count("TRAINED OK") == 2
