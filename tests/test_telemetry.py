"""Runtime telemetry subsystem tests (ISSUE 2): instrument semantics,
the disabled-mode zero-instrument-call contract, sink round-trips, the
summarize CLI exit-code contract, and the runtime retrace counter that
catches LAMB-style recompiles the static auditor can't see."""
import json
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry
from mxnet_tpu.telemetry import cli as tcli
from mxnet_tpu.telemetry import hooks as thooks
from mxnet_tpu.telemetry.core import Registry
from mxnet_tpu.telemetry.sinks import prom_text, summary_table


@pytest.fixture(autouse=True)
def _clean_telemetry():
    """Each test starts disabled with an empty registry and leaves the
    process the same way (telemetry state is global by design)."""
    telemetry.disable()
    telemetry.registry().clear()
    yield
    telemetry.disable()
    if telemetry._jsonl_sink is not None:
        telemetry.registry().detach(telemetry._jsonl_sink)
        telemetry._jsonl_sink.close()
        telemetry._jsonl_sink = None
    telemetry.registry().clear()


# ---------------------------------------------------------------------
# instrument semantics
# ---------------------------------------------------------------------

def test_counter_gauge_semantics():
    reg = Registry()
    c = reg.counter("c")
    c.inc()
    c.inc(4)
    c.dec()
    assert c.value == 4
    assert reg.counter("c") is c          # get-or-create attaches
    g = reg.gauge("g")
    g.set(2.0)
    g.set(0.5)
    g.set(1.0)
    snap = g.snapshot()
    assert snap["value"] == 1.0 and snap["min"] == 0.5 \
        and snap["max"] == 2.0 and snap["count"] == 3


def test_timer_histogram_and_context():
    reg = Registry()
    t = reg.timer("t")
    t.observe(0.010)
    t.observe(0.002)
    with t.time():
        pass
    snap = t.snapshot()
    assert snap["count"] == 3
    assert snap["min"] <= 0.002 and snap["max"] >= 0.010
    assert abs(snap["sum"] - (snap["mean"] * 3)) < 1e-9
    assert sum(snap["buckets"].values()) == 3


def test_event_ring_and_payload():
    reg = Registry()
    e = reg.event("e")
    for i in range(300):
        e.emit(i=i)
    assert e.count == 300
    assert len(e.recent) == 256           # bounded ring
    assert e.recent[-1] == {"i": 299}
    assert e.snapshot()["last_payload"] == {"i": 299}


def test_kind_conflict_raises():
    reg = Registry()
    reg.counter("x")
    with pytest.raises(ValueError):
        reg.timer("x")


def test_registry_thread_safety():
    reg = Registry()
    c = reg.counter("n")

    def work():
        for _ in range(1000):
            c.inc()
            reg.timer("t").observe(1e-6)

    threads = [threading.Thread(target=work) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 8000
    assert reg.timer("t").count == 8000


def test_reset_and_prefix_reset():
    reg = Registry()
    reg.counter("a.x").inc(3)
    reg.counter("b.y").inc(5)
    reg.reset(prefix="a.")
    assert reg.counter("a.x").value == 0
    assert reg.counter("b.y").value == 5
    reg.reset()
    assert reg.counter("b.y").value == 0


# ---------------------------------------------------------------------
# disabled-mode contract: hot paths make ZERO instrument calls
# ---------------------------------------------------------------------

def _exercise_hot_paths():
    """Touch every instrumented path once: imperative dispatch, host
    syncs, hybrid cache, trainer step, kvstore, dataloader, amp."""
    x = mx.nd.ones((4, 5))
    y = x * 2 + 1
    y.asnumpy()
    y.wait_to_read()
    mx.nd.waitall()

    net = gluon.nn.Dense(3, in_units=5)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(4)

    ds = gluon.data.ArrayDataset(mx.nd.ones((4, 2)), mx.nd.ones((4,)))
    for _batch in gluon.data.DataLoader(ds, batch_size=2):
        pass

    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((3,)))
    out = mx.nd.zeros((3,))
    kv.push("w", mx.nd.ones((3,)))
    kv.pull("w", out=out)
    kv.pushpull("w", mx.nd.ones((3,)), out=out)

    from mxnet_tpu.amp.loss_scaler import LossScaler
    sc = LossScaler(scale_window=1)
    sc.update_scale(overflow=True)
    sc.update_scale(overflow=False)


def test_disabled_mode_makes_zero_instrument_calls(monkeypatch):
    """The acceptance-criteria proof: with telemetry off, the hot-path
    hooks are never entered -- each instrumented site costs exactly its
    one module-flag check."""
    calls = []
    for name in thooks.__all__:
        orig = getattr(thooks, name)

        def counted(*a, _name=name, _orig=orig, **kw):
            calls.append(_name)
            return _orig(*a, **kw)

        monkeypatch.setattr(thooks, name, counted)

    assert not telemetry.enabled()
    _exercise_hot_paths()
    assert calls == [], "hooks fired while telemetry disabled: %r" % calls

    telemetry.enable()
    _exercise_hot_paths()
    fired = set(calls)
    assert {"op_dispatch", "host_sync", "trainer_step", "kv_op",
            "dataloader_wait", "amp_overflow", "amp_rescale"} <= fired, \
        "expected hooks missing: fired=%r" % sorted(fired)


def test_enable_disable_and_feature_row():
    assert not telemetry.enabled()
    feats = mx.runtime.Features()
    assert "TELEMETRY" in feats
    assert not feats.is_enabled("TELEMETRY")
    telemetry.enable()
    assert mx.runtime.Features().is_enabled("TELEMETRY")
    assert any(f.name == "TELEMETRY" and f.enabled
               for f in mx.runtime.feature_list())
    telemetry.disable()
    assert not mx.runtime.Features().is_enabled("TELEMETRY")


# ---------------------------------------------------------------------
# sinks
# ---------------------------------------------------------------------

def test_jsonl_sink_round_trip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    telemetry.enable()
    telemetry.attach_jsonl(path)
    try:
        telemetry.counter("demo.count").inc(7)
        telemetry.gauge("demo.gauge").set(1.5)
        telemetry.timer("demo.timer").observe(0.25)
        telemetry.event("demo.event").emit(reason="test", n=1)
        telemetry.flush()
    finally:
        telemetry._jsonl_sink.close()
    records = [json.loads(line) for line in open(path)]
    kinds = {r["kind"] for r in records}
    # streamed records AND the flush snapshot
    assert {"sample", "event", "snapshot.counter", "snapshot.gauge",
            "snapshot.timer", "snapshot.event"} <= kinds
    agg = tcli.summarize_file(path)
    assert agg["counters"]["demo.count"] == 7
    assert agg["gauges"]["demo.gauge"]["value"] == 1.5
    assert agg["timers"]["demo.timer"]["count"] == 1
    assert agg["events"]["demo.event"]["last_payload"] == \
        {"reason": "test", "n": 1}


def test_jsonl_survives_unflushed_run(tmp_path):
    """A run killed before flush still yields a usable summary from the
    streamed event/sample records alone."""
    path = str(tmp_path / "run.jsonl")
    telemetry.enable()
    telemetry.attach_jsonl(path)
    telemetry.timer("trainer.step_time").observe(0.05)
    telemetry.event("compile").emit(site="hybrid_cache", retrace=False)
    telemetry._jsonl_sink.flush()   # file write only, no snapshot
    agg = tcli.summarize_file(path)
    telemetry._jsonl_sink.close()
    assert agg["steps"]["count"] == 1
    assert agg["compile"]["count"] == 1


def test_prom_exposition_format():
    telemetry.counter("a.calls").inc(3)
    telemetry.gauge("a.speed").set(12.5)
    telemetry.timer("a.lat").observe(0.002)
    telemetry.event("a.ev").emit(k=1)
    text = telemetry.prom_dump()
    assert "# TYPE mxnet_tpu_a_calls counter" in text
    assert "mxnet_tpu_a_calls 3" in text
    assert "mxnet_tpu_a_speed 12.5" in text
    assert "mxnet_tpu_a_lat_count 1" in text
    assert 'mxnet_tpu_a_lat_bucket{le="+Inf"} 1' in text
    assert "mxnet_tpu_a_ev 1" in text


def test_prom_dump_to_file(tmp_path):
    telemetry.counter("z").inc()
    p = tmp_path / "metrics.prom"
    text = telemetry.prom_dump(str(p))
    assert p.read_text() == text


def test_console_summary_table():
    telemetry.counter("c1").inc(2)
    telemetry.timer("t1").observe(0.5)
    table = telemetry.summary()
    assert "counters" in table and "c1" in table
    assert "timers" in table and "t1" in table
    # empty registry renders, not crashes
    assert "no telemetry" in summary_table([])
    assert prom_text([]) == ""


# ---------------------------------------------------------------------
# CLI contract (mirrors the mxlint contract: 0 ok / 1 nothing / 2 usage)
# ---------------------------------------------------------------------

def _write_demo_log(path):
    telemetry.enable()
    telemetry.attach_jsonl(str(path))
    telemetry.timer("trainer.step_time").observe(0.01)
    telemetry.counter("trainer.samples").inc(8)
    telemetry.event("compile").emit(site="eager_jit", retrace=False)
    telemetry.counter("compile.count").inc()
    telemetry.flush()
    telemetry._jsonl_sink.close()


def test_cli_json_exit_code_contract(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    _write_demo_log(log)
    rc = tcli.main(["summarize", str(log), "--json"])
    out = capsys.readouterr().out
    assert rc == 0
    agg = json.loads(out)
    assert agg["steps"]["count"] == 1
    assert agg["compile"]["count"] == 1
    assert agg["records"] > 0


def test_cli_human_and_prom_render(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    _write_demo_log(log)
    assert tcli.main(["summarize", str(log)]) == 0
    human = capsys.readouterr().out
    assert "telemetry summary" in human and "steps: 1" in human
    assert tcli.main(["summarize", str(log), "--prom"]) == 0
    prom = capsys.readouterr().out
    assert "mxnet_tpu_trainer_step_time_count 1" in prom


def test_cli_empty_and_missing_exit_1(tmp_path, capsys):
    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert tcli.main(["summarize", str(empty)]) == 1
    assert tcli.main(["summarize", str(tmp_path / "nope.jsonl")]) == 1
    capsys.readouterr()


def test_cli_usage_exit_2(capsys):
    assert tcli.main([]) == 2
    capsys.readouterr()


def test_cli_skips_malformed_lines(tmp_path, capsys):
    log = tmp_path / "run.jsonl"
    _write_demo_log(log)
    with open(log, "a") as f:
        f.write("not json at all\n{\"kind\": \"mystery\"}\n")
    rc = tcli.main(["summarize", str(log), "--json"])
    agg = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert agg["skipped"] >= 1


# ---------------------------------------------------------------------
# runtime retrace counter (the LAMB class of regression, caught live)
# ---------------------------------------------------------------------

def test_runtime_retrace_counter_lamb_style():
    """PR 1 found the LAMB recompile statically (``t`` baked into the
    eager-jit key).  This proves the RUNTIME side: (a) the fixed LAMB
    op does not retrace as ``t`` varies, and (b) an op whose static
    param varies per call -- the same regression class -- fires the
    retrace event with the changed param named in the payload."""
    telemetry.enable()
    w = mx.nd.ones((4,))
    g = mx.nd.ones((4,)) * 0.1
    m = mx.nd.zeros((4,))
    v = mx.nd.zeros((4,))
    # warm the cache entry for this signature
    mx.nd.lamb_update_phase1(w, g, m, v, t=1)
    retraces_before = telemetry.counter("compile.retraces").value
    for t in range(2, 6):
        mx.nd.lamb_update_phase1(w, g, m, v, t=t)
    assert telemetry.counter("compile.retraces").value == retraces_before, \
        "varying t recompiled LAMB -- the PR 1 regression is back"

    # LAMB-style regression reproduced: a float param that is NOT in
    # _DYNAMIC_PARAMS enters the cache key, so varying it per step
    # compiles per step -- the runtime counter must catch it
    x = mx.nd.ones((2, 3))
    ev = telemetry.event("compile")
    before = telemetry.counter("compile.retraces").value
    for i in range(3):
        mx.nd.clip(x, a_min=0.001 * i + 0.5101, a_max=9.3303)
    after = telemetry.counter("compile.retraces").value
    assert after >= before + 2, "per-step static-param recompile not flagged"
    last = [e for e in ev.recent
            if e.get("site") == "eager_jit" and e.get("retrace")][-1]
    assert last["op"] == "clip"
    assert "a_min" in last["changed"]


def test_hybrid_retrace_event_payload_names_cache_key_diff():
    telemetry.enable()
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    net.hybridize()
    net(mx.nd.ones((1, 3)))
    ev = telemetry.event("compile")
    n_before = ev.count
    net(mx.nd.ones((5, 3)))          # bucketing: new leading dim
    hybrid = [e for e in ev.recent if e.get("site") == "hybrid_cache"]
    assert ev.count > n_before
    assert hybrid[-1]["retrace"] is True
    assert hybrid[-1]["changed"] == ["arg0.shape"]
    assert hybrid[-1]["block"] == "Dense"
    assert telemetry.timer("compile.build_time").count >= 1


def test_trainer_step_and_kvstore_metrics():
    telemetry.enable()
    net = gluon.nn.Dense(2, in_units=4)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    for _ in range(2):
        with autograd.record():
            loss = net(mx.nd.ones((8, 4))).sum()
        loss.backward()
        trainer.step(8)
    assert telemetry.counter("trainer.steps").value == 2
    assert telemetry.counter("trainer.samples").value == 16
    assert telemetry.timer("trainer.step_time").count == 2
    assert telemetry.gauge("trainer.samples_per_sec").value > 0
    # Dense(2, in 4): weight 4*2*4B + bias 2*4B = 40B per step
    assert telemetry.counter("kvstore.bytes").value == 80
    assert telemetry.counter("kvstore.pushpull").value == 4
    assert telemetry.timer("kvstore.time").count == 4


def test_dataloader_wait_time_metrics():
    telemetry.enable()
    ds = gluon.data.ArrayDataset(
        mx.nd.array(np.arange(24, dtype=np.float32).reshape(12, 2)),
        mx.nd.array(np.arange(12, dtype=np.float32)))
    for _x, _y in gluon.data.DataLoader(ds, batch_size=4, num_workers=2):
        pass
    assert telemetry.counter("data.batches").value == 3
    t = telemetry.timer("data.wait_time").snapshot()
    assert t["count"] == 3 and t["sum"] > 0


def test_speedometer_feeds_throughput_gauge():
    from collections import namedtuple
    telemetry.enable()
    BatchEndParam = namedtuple("BatchEndParam",
                               ["epoch", "nbatch", "eval_metric", "locals"])
    speedo = mx.callback.Speedometer(batch_size=32, frequent=2,
                                     auto_reset=False)
    for nbatch in range(1, 5):
        speedo(BatchEndParam(epoch=0, nbatch=nbatch, eval_metric=None,
                             locals=None))
    gauge = telemetry.gauge("trainer.samples_per_sec")
    assert gauge.value is not None and gauge.value > 0
    assert gauge.value == speedo.last_speed


def test_amp_overflow_and_rescale_events():
    from mxnet_tpu.amp.loss_scaler import LossScaler
    telemetry.enable()
    sc = LossScaler(init_scale=2.0 ** 10, scale_window=2)
    sc.update_scale(overflow=True)
    assert telemetry.counter("amp.overflows").value == 1
    ov = telemetry.event("amp.overflow").recent[-1]
    assert ov["scale_before"] == 2.0 ** 10
    assert ov["scale_after"] == 2.0 ** 9
    sc.update_scale(overflow=False)
    sc.update_scale(overflow=False)   # window met -> rescale event
    rs = telemetry.event("amp.rescale").recent[-1]
    assert rs["scale_after"] == 2.0 ** 10
    assert telemetry.gauge("amp.loss_scale").value == 2.0 ** 10


def test_preemption_checkpoint_events(tmp_path):
    telemetry.enable()
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    net(mx.nd.ones((1, 3)))
    prefix = str(tmp_path / "job")
    handler = mx.preemption.install(prefix, net)
    try:
        handler.save_now(step=7)
    finally:
        handler.uninstall()
    saves = telemetry.event("checkpoint").recent
    assert saves[-1]["action"] == "save" and saves[-1]["step"] == 7
    meta = mx.preemption.resume(prefix, net)
    assert meta["step"] == 7
    assert telemetry.event("checkpoint").recent[-1]["action"] == "restore"
    assert telemetry.counter("checkpoint.saves").value == 1
    assert telemetry.counter("checkpoint.restores").value == 1


def test_executor_compile_event():
    telemetry.enable()
    data = mx.sym.Variable("data")
    out = mx.sym.FullyConnected(data, num_hidden=2, name="fc")
    ex = out.simple_bind(mx.cpu(), data=(2, 3))
    ev = telemetry.event("compile")
    n0 = len([e for e in ev.recent if str(e.get("site", ""))
              .startswith("executor.")])
    ex.forward(is_train=False)
    ex.forward(is_train=False)   # second call: cache hit, no new event
    exec_events = [e for e in ev.recent
                   if str(e.get("site", "")).startswith("executor.")]
    assert len(exec_events) == n0 + 1
    assert exec_events[-1]["seconds"] > 0


def test_env_vars_registered():
    desc = mx.env.describe()
    assert "MXNET_TPU_TELEMETRY" in desc
    assert "MXNET_TPU_TELEMETRY_JSONL" in desc
    assert mx.env.get("MXNET_TPU_TELEMETRY") in (False, True)


def test_instrument_increments_atomic_under_hammer():
    """ISSUE 5 satellite: N threads x M increments must land exactly
    N*M on every instrument kind -- the registry/instrument locks make
    the += read-modify-write atomic."""
    import threading

    from mxnet_tpu.telemetry import Registry

    reg = Registry()
    c = reg.counter("hammer.count")
    t = reg.timer("hammer.time")
    e = reg.event("hammer.event")
    N, M = 8, 2500

    def pound():
        for _ in range(M):
            c.inc()
            t.observe(1e-6)
            e.emit(k=1)

    threads = [threading.Thread(target=pound, daemon=True)
               for _ in range(N)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=60)
    assert c.value == N * M
    assert t.count == N * M
    assert e.count == N * M


def test_concurrent_sink_flush_no_torn_lines(tmp_path):
    """ISSUE 13 satellite (extends the PR-5 hammer): 8 threads hammer
    events + timer samples through an attached JSONL sink while the
    main thread flushes repeatedly and renders the Prometheus
    exposition -- every line in the file must parse (no torn/interleaved
    writes) and the streamed counts must be exact."""
    import threading

    from mxnet_tpu.telemetry import Registry
    from mxnet_tpu.telemetry.sinks import JsonlSink, prom_text

    path = str(tmp_path / "hammer.jsonl")
    reg = Registry()
    sink = reg.attach(JsonlSink(path))
    e = reg.event("hammer.event")
    t = reg.timer("hammer.time")
    N, M = 8, 400
    barrier = threading.Barrier(N + 1)

    def pound(tid):
        barrier.wait()
        for i in range(M):
            e.emit(tid=tid, i=i)
            t.observe(1e-6)

    threads = [threading.Thread(target=pound, args=(k,), daemon=True)
               for k in range(N)]
    for th in threads:
        th.start()
    barrier.wait()
    for _ in range(50):                   # flush + render MID-hammer
        reg.flush()
        prom_text(reg.snapshot())
    for th in threads:
        th.join(timeout=60)
    reg.flush()
    sink.close()
    # writes after close are dropped silently, never raise
    e.emit(tid=-1, i=-1)

    events = samples = 0
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            rec = json.loads(line)        # a torn line would raise here
            if rec["kind"] == "event" and rec["name"] == "hammer.event":
                events += 1
            elif rec["kind"] == "sample" and rec["name"] == "hammer.time":
                samples += 1
    assert events == N * M, events        # exact: nothing lost or torn
    assert samples == N * M, samples
    assert e.count == N * M + 1           # the post-close emit counted
    assert t.count == N * M


def test_registry_get_or_create_race_returns_one_instance():
    import threading

    from mxnet_tpu.telemetry import Registry

    reg = Registry()
    out = []
    barrier = threading.Barrier(8)

    def grab():
        barrier.wait()
        out.append(reg.counter("race.one"))

    threads = [threading.Thread(target=grab, daemon=True)
               for _ in range(8)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=30)
    assert len({id(o) for o in out}) == 1
