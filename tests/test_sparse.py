"""Sparse NDArray tests (reference:
``tests/python/unittest/test_sparse_ndarray.py`` /
``test_sparse_operator.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def _rand_csr(n, m, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.randn(n, m) * (rng.rand(n, m) < density)
    return dense.astype(np.float32)


def test_csr_roundtrip():
    dense = _rand_csr(8, 5)
    csr = sparse.csr_matrix(dense)
    assert csr.stype == "csr"
    np.testing.assert_allclose(csr.asnumpy(), dense, rtol=1e-6)
    # component access matches scipy-style construction
    assert csr.indptr.shape == (9,)
    assert csr.nnz == int((dense != 0).sum())
    # explicit (data, indices, indptr) constructor
    csr2 = sparse.csr_matrix(
        (csr.data.asnumpy(), csr.indices.asnumpy(),
         csr.indptr.asnumpy()), shape=(8, 5))
    np.testing.assert_allclose(csr2.asnumpy(), dense, rtol=1e-6)


def test_csr_dot_dense():
    dense = _rand_csr(8, 5)
    rhs = np.random.RandomState(1).randn(5, 3).astype(np.float32)
    csr = sparse.csr_matrix(dense)
    out = sparse.dot(csr, mx.nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5)
    outT = sparse.dot(csr, mx.nd.array(
        np.random.RandomState(2).randn(8, 3).astype(np.float32)),
        transpose_a=True)
    assert outT.shape == (5, 3)


def test_row_sparse_roundtrip_and_retain():
    data = np.arange(12, dtype=np.float32).reshape(4, 3) + 1
    idx = np.array([1, 3, 5, 7], dtype=np.int32)
    rs = sparse.row_sparse_array((data, idx), shape=(10, 3))
    dense = rs.asnumpy()
    assert dense.shape == (10, 3)
    np.testing.assert_allclose(dense[idx], data)
    assert dense.sum() == data.sum()

    kept = rs.retain(mx.nd.array(np.array([3, 4, 7], np.float32)))
    np.testing.assert_allclose(kept.asnumpy()[[3, 7]], data[[1, 3]])
    assert kept.asnumpy()[4].sum() == 0


def test_row_sparse_add():
    a = sparse.row_sparse_array(
        (np.ones((2, 3), np.float32), np.array([0, 2])), shape=(5, 3))
    b = sparse.row_sparse_array(
        (2 * np.ones((2, 3), np.float32), np.array([2, 4])), shape=(5, 3))
    s = sparse.elemwise_add(a, b)
    assert s.stype == "row_sparse"
    expect = np.zeros((5, 3), np.float32)
    expect[0] = 1
    expect[2] = 3
    expect[4] = 2
    np.testing.assert_allclose(s.asnumpy(), expect)
    # sparse + dense -> dense
    d = sparse.elemwise_add(a, mx.nd.ones((5, 3)))
    np.testing.assert_allclose(
        d.asnumpy(), np.ones((5, 3)) + a.asnumpy())


def test_sparse_zeros():
    z = sparse.zeros("row_sparse", (6, 2))
    assert z.asnumpy().sum() == 0
    zc = sparse.zeros("csr", (4, 4))
    assert zc.asnumpy().sum() == 0


def test_kvstore_row_sparse_pull_no_densify():
    kv = mx.kv.create("local")
    table = np.random.RandomState(0).randn(100, 8).astype(np.float32)
    kv.init("emb", mx.nd.array(table))
    rows = mx.nd.array(np.array([5, 17, 99], np.float32))
    pulled = kv.row_sparse_pull("emb", row_ids=rows)
    assert pulled.stype == "row_sparse"
    assert pulled.data.shape == (3, 8)      # only k rows moved
    np.testing.assert_allclose(pulled.data.asnumpy(),
                               table[[5, 17, 99]], rtol=1e-6)


def test_kvstore_sparse_push_with_optimizer():
    """Pushing row-sparse grads applies a row-level update server-side
    (reference: sparse sgd on the kvstore server)."""
    kv = mx.kv.create("local")
    w0 = np.ones((10, 4), np.float32)
    kv.init("w", mx.nd.array(w0))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5, momentum=0.0))
    g = sparse.row_sparse_array(
        (np.ones((2, 4), np.float32), np.array([2, 7])), shape=(10, 4))
    kv.push("w", g)
    out = mx.nd.zeros((10, 4))
    kv.pull("w", out=out)
    got = out.asnumpy()
    expect = w0.copy()
    expect[[2, 7]] -= 0.5
    np.testing.assert_allclose(got, expect, rtol=1e-6)


def test_sparse_adagrad_rows_only():
    opt = mx.optimizer.AdaGrad(learning_rate=1.0)
    w = mx.nd.ones((6, 2))
    state = opt.create_state(0, w)
    g = sparse.row_sparse_array(
        (np.full((2, 2), 2.0, np.float32), np.array([1, 4])), shape=(6, 2))
    opt.update_row_sparse(0, w, g, state)
    got = w.asnumpy()
    assert np.allclose(got[[0, 2, 3, 5]], 1.0)     # untouched rows
    assert (got[[1, 4]] < 1.0).all()                # updated rows
    h = state.asnumpy()
    assert np.allclose(h[[0, 2, 3, 5]], 0.0)
    assert np.allclose(h[[1, 4]], 4.0)


def test_updater_dispatches_sparse():
    upd = mx.optimizer.get_updater(
        mx.optimizer.SGD(learning_rate=0.1, momentum=0.0))
    w = mx.nd.ones((5, 3))
    g = sparse.row_sparse_array(
        (np.ones((1, 3), np.float32), np.array([3])), shape=(5, 3))
    upd(0, g, w)
    got = w.asnumpy()
    assert np.allclose(got[3], 0.9) and np.allclose(got[0], 1.0)


def test_momentum_sgd_densifies_correctly():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9)
    w = mx.nd.ones((4, 2))
    state = opt.create_state(0, w)
    g = sparse.row_sparse_array(
        (np.ones((1, 2), np.float32), np.array([1])), shape=(4, 2))
    opt.update_row_sparse(0, w, g, state)   # falls back to dense math
    got = w.asnumpy()
    assert not np.allclose(got[1], 1.0)
    assert np.allclose(got[0], 1.0)
