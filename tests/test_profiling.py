"""Compiled-step cost accounting (ISSUE 6): CostReport capture across
the compiled dispatch paths, category attribution summing to XLA
totals, stable fingerprints, roofline bound labels, the mxprof CLI's
report/diff contract, the step timeline, and the satellite surfaces
(profiler.dumps, telemetry instruments, Features row)."""
import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, profiling
from mxnet_tpu.profiling import cli, cost, hlo, roofline, timeline


@pytest.fixture()
def prof():
    """Profiling armed with a clean store; fully torn down after."""
    profiling.reset()
    profiling.enable()
    yield profiling
    profiling.disable()
    profiling.reset()


def _tiny_fn(width):
    def f(x, w1, w2):
        h = jnp.maximum(x @ w1, 0.0)
        return jnp.tanh(h @ w2).sum()
    return f


def _tiny_args(width):
    return (jnp.ones((8, 16)), jnp.ones((16, width)),
            jnp.ones((width, 4)))


# -- core: analysis, reconciliation, fingerprint -----------------------

def test_cost_report_nonzero_and_categories_sum_to_totals():
    rep = cost.analyze_jit(jax.jit(_tiny_fn(32)), _tiny_args(32),
                           label="tiny")
    assert rep is not None
    assert rep["schema"] == cost.SCHEMA
    assert rep["totals"]["flops"] > 0
    assert rep["totals"]["bytes_accessed"] > 0
    assert rep["categories"]["conv_dot"]["flops"] > 0
    f_sum = sum(c["flops"] for c in rep["categories"].values())
    b_sum = sum(c["bytes"] for c in rep["categories"].values())
    assert abs(f_sum - rep["totals"]["flops"]) < 1
    assert abs(b_sum - rep["totals"]["bytes_accessed"]) < 1
    # memory section is populated and internally consistent
    m = rep["memory"]
    assert m["argument_bytes"] > 0
    assert m["peak_hbm_bytes"] >= m["temp_bytes"]


def test_fingerprint_stable_across_identical_recompiles():
    args = _tiny_args(32)
    r1 = cost.analyze_jit(jax.jit(_tiny_fn(32)), args)
    # a FRESH jit of structurally identical code (new trace, new
    # compile, different source line) must fingerprint identically
    r2 = cost.analyze_jit(jax.jit(_tiny_fn(32)), args)
    assert r1["fingerprint"] == r2["fingerprint"]
    # and a different program must not
    r3 = cost.analyze_jit(jax.jit(_tiny_fn(64)), _tiny_args(64))
    assert r3["fingerprint"] != r1["fingerprint"]


def test_hlo_parser_attributes_conv_and_layout():
    def f(x, w):
        y = jax.lax.conv_general_dilated(x, w, (1, 1), "SAME")
        return y.transpose(0, 2, 3, 1).sum()
    rep = cost.analyze_jit(jax.jit(f),
                           (jnp.zeros((2, 3, 8, 8)),
                            jnp.zeros((4, 3, 3, 3))), label="conv")
    cats = rep["categories"]
    assert cats["conv_dot"]["flops"] > 0
    # NCHW->NHWC relayout shows up as data movement
    assert cats["transpose_layout"]["instructions"] > 0
    # provenance: best-effort from op_name metadata (XLA may drop it on
    # rewritten instructions, so assert shape, not full coverage)
    assert rep["provenance"], "op_name provenance missing"
    for p in rep["provenance"]:
        assert p["flops"] > 0 and p["category"] in hlo.CATEGORIES


def test_roofline_labels_every_category():
    rep = cost.analyze_jit(jax.jit(_tiny_fn(32)), _tiny_args(32))
    rl = roofline.build(rep, step_time_s=1e-3)
    assert rl["peaks_assumed"] is True          # CPU dev box
    assert rl["mfu"] >= 0
    assert rl["categories"], "empty roofline category section"
    for cat, v in rl["categories"].items():
        assert v["bound"] in ("compute", "memory"), (cat, v)
        assert 0.0 <= v["time_share"] <= 1.0
    # a known-compute-bound synthetic: huge intensity forces 'compute'
    fake = {"device": "TPU v5e", "totals": {"flops": 1e12,
                                            "bytes_accessed": 1e3},
            "categories": {"conv_dot": {"flops": 10**12, "bytes": 10**3,
                                        "instructions": 1}},
            "memory": {"peak_hbm_bytes": 0}}
    rl2 = roofline.build(fake, 1.0)
    assert rl2["peaks_assumed"] is False
    assert rl2["categories"]["conv_dot"]["bound"] == "compute"


# -- capture paths -----------------------------------------------------

def test_eager_jit_path_captured(prof):
    x = mx.nd.ones((4, 5))
    y = mx.nd.clip(x, a_min=0.111, a_max=5.222)
    y.asnumpy()
    reps = prof.reports()
    assert any(r["label"] == "eager:clip" and r["kind"] == "eager_jit"
               for r in reps)


def test_hybrid_cache_path_captured(prof):
    net = gluon.nn.Dense(3)
    net.initialize()
    net.hybridize()
    net(mx.nd.ones((2, 7))).asnumpy()   # deferred init: imperative
    out = net(mx.nd.ones((2, 7)))       # compiled cache path
    out.asnumpy()
    reps = prof.reports()
    hyb = [r for r in reps if r["kind"] == "hybrid_cache"]
    assert hyb and hyb[0]["label"].startswith("hybrid:Dense")
    assert hyb[0]["totals"]["flops"] > 0


def test_executor_path_captured(prof):
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = mx.sym.dot(a, b)
    ex = c.bind(mx.cpu(), {"a": mx.nd.ones((4, 8)),
                           "b": mx.nd.ones((8, 2))})
    ex.forward()
    reps = prof.reports()
    assert any(r["label"] == "executor.eval" for r in reps)


def test_train_step_captured_with_step_and_roofline(prof):
    from mxnet_tpu.parallel import TrainStep
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
    x = mx.nd.array(np.random.rand(8, 16).astype(np.float32))
    y = mx.nd.array(np.random.rand(8, 4).astype(np.float32))
    for _ in range(3):
        step(x, y)
    reps = {r["label"]: r for r in prof.reports()}
    rep = reps.get("train_step:Dense")
    assert rep is not None
    assert rep["step"]["count"] == 3
    assert rep["roofline"] is not None
    for v in rep["roofline"]["categories"].values():
        assert v["bound"] in ("compute", "memory")
    # capture is lazy: the store holds at most one report per compiled
    # program however many steps ran
    assert rep["totals"]["flops"] > 0


def test_disabled_mode_captures_nothing():
    profiling.reset()
    assert not profiling.enabled()
    x = mx.nd.ones((3, 3))
    (x * 2 + 1).asnumpy()
    assert profiling.reports() == []
    assert timeline.events() == []


# -- CLI: report + diff ------------------------------------------------

def _save_run(tmp_path, width, sub):
    rep = cost.analyze_jit(jax.jit(_tiny_fn(width)), _tiny_args(width),
                           label="tiny")
    d = tmp_path / sub
    d.mkdir()
    path = d / "tiny.cost.json"
    path.write_text(json.dumps(rep))
    return str(path)


def test_mxprof_diff_zero_on_identical_and_flags_widened_dot(tmp_path,
                                                             capsys):
    old = _save_run(tmp_path, 32, "old")
    new = _save_run(tmp_path, 128, "new")
    # identical -> exit 0
    assert cli.main(["diff", old, old]) == 0
    out = capsys.readouterr().out
    assert "no drift" in out
    # widened layer -> exit non-zero naming the dot category
    rc = cli.main(["diff", old, new])
    out = capsys.readouterr().out
    assert rc == 1
    assert "conv_dot" in out
    # machine-readable form carries the same verdict
    rc = cli.main(["diff", old, new, "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert any(d["scope"] == "category:conv_dot" and
               d["field"] == "flops" for d in out["drifts"])


def test_mxprof_report_renders_saved_store(tmp_path, prof, capsys):
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    net(mx.nd.ones((2, 3))).asnumpy()
    combined = prof.save_reports(str(tmp_path))
    assert os.path.basename(combined) == "report.json"
    assert cli.main(["report", "--dir", str(tmp_path), "--json"]) == 0
    agg = json.loads(capsys.readouterr().out)
    assert agg["executables"]
    assert sum(v["flops"] for v in agg["categories"].values()) > 0
    # human rendering mentions every populated category
    assert cli.main(["report", "--dir", str(tmp_path)]) == 0
    human = capsys.readouterr().out
    assert "conv_dot" in human and "executables:" in human


def test_mxprof_report_empty_dir_fails_gate(tmp_path, capsys):
    assert cli.main(["report", "--dir", str(tmp_path)]) == 1


def test_mxprof_diff_self_zero_with_repeated_labels(tmp_path, prof,
                                                    capsys):
    """Two layers of the same op type produce two executables with the
    SAME label (`eager:FullyConnected` twice); a report diffed against
    itself must still align each with itself and report zero drift
    (caught live: a label-keyed dict paired the first against the
    last)."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize()
    net(mx.nd.ones((2, 16))).asnumpy()       # two FullyConnected shapes
    labels = [r["label"] for r in prof.reports()]
    assert labels.count("eager:FullyConnected") == 2
    path = os.path.join(prof.save_reports(str(tmp_path)))
    assert cli.main(["diff", path, path]) == 0
    assert "no drift" in capsys.readouterr().out


# -- timeline ----------------------------------------------------------

def test_timeline_records_and_exports_chrome_trace(tmp_path, prof):
    with timeline.span("phase1", detail="x"):
        pass
    timeline.instant("marker")
    evs = timeline.events()
    names = [e["name"] for e in evs]
    assert "phase1" in names and "marker" in names
    path = tmp_path / "trace.json"
    trace = timeline.export_chrome_trace(str(path))
    assert trace["traceEvents"]
    loaded = json.loads(path.read_text())
    assert loaded["traceEvents"][0]["ph"] in ("X", "i")
    span_ev = next(e for e in loaded["traceEvents"]
                   if e["name"] == "phase1")
    assert span_ev["ph"] == "X" and span_ev["dur"] >= 0
    assert span_ev["args"] == {"detail": "x"}


def test_timeline_train_step_span(prof):
    from mxnet_tpu.parallel import TrainStep
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
    step(mx.nd.ones((4, 3)), mx.nd.ones((4, 2)))
    names = [e["name"] for e in timeline.events()]
    assert "train_step:Dense" in names
    assert "train_step:Dense.donate" in names


# -- satellites wired through ------------------------------------------

def test_telemetry_profiling_instruments(prof):
    from mxnet_tpu import telemetry
    was = telemetry.enabled()
    telemetry.enable()
    telemetry.reset("profiling.")
    try:
        mx.nd.clip(mx.nd.ones((2, 2)), a_min=0.017, a_max=9.3).asnumpy()
        reps = prof.reports()
        assert reps
        assert telemetry.counter("profiling.reports").value >= 1
        ev = telemetry.event("profiling.capture")
        assert ev.count >= 1 and ev.recent[-1]["label"].startswith(
            "eager:")
    finally:
        if not was:
            telemetry.disable()


def test_runtime_features_profiling_row(prof):
    feats = mx.runtime.Features()
    assert feats.is_enabled("PROFILING")
    profiling.disable()
    assert not mx.runtime.Features().is_enabled("PROFILING")


@pytest.mark.slow
def test_resnet_bf16_train_step_cost_report():
    """Acceptance shape (ISSUE 6): a bf16 ResNet train step's
    CostReport has conv/dot-dominated per-category FLOPs/bytes summing
    to the executable totals, and the roofline labels every category
    compute- or memory-bound.  resnet18 @ 32px keeps CPU compile
    tolerable; the program structure (convs + BN fusions + relayouts)
    matches the bench's resnet50 headline step."""
    from mxnet_tpu import amp
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    from mxnet_tpu.parallel import TrainStep
    net = resnet18_v1()
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                     mesh=None)
    x = mx.nd.array(np.random.rand(2, 3, 32, 32).astype(np.float32))
    y = mx.nd.array(np.zeros((2,), np.float32))
    with amp.scope("bfloat16"):
        step(x, y)
        rep = profiling.report_for(step, label="resnet_bf16",
                                   step_time_s=0.05, items_per_step=2)
    assert rep["totals"]["flops"] > 1e8
    f_sum = sum(c["flops"] for c in rep["categories"].values())
    b_sum = sum(c["bytes"] for c in rep["categories"].values())
    assert abs(f_sum - rep["totals"]["flops"]) < 1
    assert abs(b_sum - rep["totals"]["bytes_accessed"]) < 1
    # a ResNet step is MXU-dominated
    assert rep["categories"]["conv_dot"]["flops_share"] > 0.5
    for cat, v in rep["roofline"]["categories"].items():
        assert v["bound"] in ("compute", "memory"), (cat, v)


def test_report_for_train_step_helper():
    """bench.py's artifact path: report_for on a dispatched TrainStep
    works without the store (profiling disabled)."""
    from mxnet_tpu.parallel import TrainStep
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
    assert profiling.report_for(step) is None     # nothing dispatched
    step(mx.nd.ones((4, 3)), mx.nd.ones((4, 2)))
    rep = profiling.report_for(step, label="bench_probe",
                               step_time_s=0.01, items_per_step=4)
    assert rep["label"] == "bench_probe"
    assert rep["roofline"]["items_per_sec"] == 400.0
    f_sum = sum(c["flops"] for c in rep["categories"].values())
    assert abs(f_sum - rep["totals"]["flops"]) < 1
