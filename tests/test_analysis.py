"""mxnet_tpu.analysis: graph checker, trace-safety linter, retrace
auditor, CLI, and the bind gate (reference for the lint half: the
repo's old inline CI AST check, now rule ``bare-except``)."""
import json
import subprocess
import sys

import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis as an
from mxnet_tpu.base import MXNetError


def _rules_of(diags):
    return sorted({d.rule for d in diags})


def _lint(src):
    return an.lint_source(src, "probe.py")


# ----------------------------------------------------------------------
# trace linter: one positive and one negative fixture per rule
# ----------------------------------------------------------------------

def test_bare_except_fires_and_clean_twin_silent():
    bad = "try:\n    pass\nexcept:\n    pass\n"
    good = "try:\n    pass\nexcept ValueError:\n    pass\n"
    assert _rules_of(_lint(bad)) == ["bare-except"]
    assert _lint(good) == []


def test_mutable_default_fires_and_clean_twin_silent():
    bad = "def f(a=[], b={}):\n    return a, b\n"
    good = "def f(a=None, b=()):\n    return a, b\n"
    assert _rules_of(_lint(bad)) == ["mutable-default"]
    assert _lint(good) == []


def test_host_sync_fires_and_clean_twin_silent():
    bad = (
        "class M:\n"
        "    def hybrid_forward(self, F, x, weight):\n"
        "        v = float(x.sum())\n"
        "        n = x.asnumpy()\n"
        "        a = np.asarray(weight)\n"
        "        y = x + weight\n"
        "        return y.item()\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["host-sync"]
    assert len(diags) == 4  # float(), .asnumpy(), np.asarray, .item()
    good = (
        "class M:\n"
        "    def hybrid_forward(self, F, x, weight):\n"
        "        return F.relu(x * weight)\n"
        "    def forward(self, x):\n"
        "        return float(x.sum())\n"  # eager scope: fine
    )
    assert _lint(good) == []


def test_tracer_branch_fires_and_clean_twin_silent():
    bad = (
        "class M:\n"
        "    def hybrid_forward(self, F, x):\n"
        "        if x > 0:\n"
        "            return x\n"
        "        y = x * 2\n"
        "        while y.mean():\n"
        "            pass\n"
        "        return y\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["tracer-branch"]
    assert len(diags) == 2  # the if, and the while on tainted y
    # structural branches (None/isinstance/shape) are trace-safe
    good = (
        "class M:\n"
        "    def hybrid_forward(self, F, x, mask=None):\n"
        "        if mask is None:\n"
        "            return F.relu(x)\n"
        "        if not isinstance(x, tuple):\n"
        "            pass\n"
        "        if len(x.shape) == 2:\n"
        "            x = x + 1\n"
        "        return x * mask\n"
    )
    assert _lint(good) == []


def test_bare_state_write_fires_in_save_paths():
    bad = ("def save_states(self, fname):\n"
           "    with open(fname, 'wb') as f:\n"
           "        f.write(b'x')\n")
    assert _rules_of(_lint(bad)) == ["bare-state-write"]
    # keyword-mode spelling fires too
    bad_kw = ("def export_model(path, blob):\n"
              "    f = open(path, mode='wb')\n"
              "    f.write(blob)\n")
    assert _rules_of(_lint(bad_kw)) == ["bare-state-write"]


def test_bare_state_write_clean_twins_silent():
    # non-state function name: not a checkpoint path
    ok_name = ("def append_log(fname):\n"
               "    with open(fname, 'wb') as f:\n"
               "        f.write(b'x')\n")
    assert _lint(ok_name) == []
    # reads and text writes in save paths are fine
    ok_mode = ("def save_states(fname):\n"
               "    with open(fname, 'rb') as f:\n"
               "        return f.read()\n")
    assert _lint(ok_mode) == []
    # the atomic helper is what the rule demands
    ok_helper = ("def save_states(fname, blob):\n"
                 "    from mxnet_tpu.checkpoint.core import "
                 "atomic_write_bytes\n"
                 "    atomic_write_bytes(fname, blob)\n")
    assert _lint(ok_helper) == []


def test_bare_state_write_exempts_checkpoint_core():
    src = ("def save_stage(fname):\n"
           "    with open(fname, 'wb') as f:\n"
           "        f.write(b'x')\n")
    diags = an.lint_source(src, "mxnet_tpu/checkpoint/core.py")
    assert diags == []
    assert _rules_of(an.lint_source(src, "elsewhere.py")) == \
        ["bare-state-write"]


def test_suppression_comment_silences_rule():
    bad = "try:\n    pass\nexcept:  # mxlint: disable=bare-except\n    pass\n"
    assert _lint(bad) == []
    # a directive for a different rule does not suppress
    other = "try:\n    pass\nexcept:  # mxlint: disable=host-sync\n    pass\n"
    assert _rules_of(_lint(other)) == ["bare-except"]
    # bare `disable` silences everything on the line
    blanket = "try:\n    pass\nexcept:  # mxlint: disable\n    pass\n"
    assert _lint(blanket) == []


def test_lint_paths_on_repo_is_clean():
    assert an.lint_paths(["mxnet_tpu", "examples"]) == []


# ----------------------------------------------------------------------
# concurrency pass (ISSUE 5): one positive and one negative fixture
# per rule
# ----------------------------------------------------------------------

def test_unguarded_shared_write_fires_and_guarded_twin_silent():
    bad = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"
        "    def _run(self):\n"
        "        self.count += 1\n"           # thread side, no lock
        "    def start(self):\n"
        "        t = threading.Thread(target=self._run, daemon=True)\n"
        "        t.start()\n"
        "        self.count = 5\n"            # main side
    )
    assert _rules_of(_lint(bad)) == ["unguarded-shared-write"]
    good = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.count = 0\n"            # __init__ is construction
        "    def _run(self):\n"
        "        with self._lock:\n"
        "            self.count += 1\n"
        "    def start(self):\n"
        "        t = threading.Thread(target=self._run, daemon=True)\n"
        "        t.start()\n"
        "        with self._lock:\n"
        "            self.count = 5\n"
    )
    assert _lint(good) == []


def test_unguarded_shared_write_sees_container_mutation():
    bad = (
        "import threading\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self.stats = {'n': 0}\n"
        "    def _run(self):\n"
        "        self.stats['n'] += 1\n"
        "    def go(self):\n"
        "        threading.Thread(target=self._run, daemon=True).start()\n"
        "        self.stats['n'] = 9\n"
    )
    assert _rules_of(_lint(bad)) == ["unguarded-shared-write"]


def test_blocking_under_lock_fires_and_clean_twin_silent():
    bad = (
        "import queue, threading, time\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = queue.Queue()\n"
        "    def drain(self):\n"
        "        with self._lock:\n"
        "            item = self._q.get()\n"      # blocking under lock
        "            time.sleep(1)\n"             # and this
        "            f = open('x')\n"             # and this
        "        return item, f\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["blocking-under-lock"]
    assert len(diags) == 3
    good = (
        "import queue, threading, time\n"
        "class B:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._q = queue.Queue()\n"
        "    def drain(self):\n"
        "        item = self._q.get()\n"          # outside the lock
        "        with self._lock:\n"
        "            self.last = item\n"
        "        return item\n"
    )
    assert _lint(good) == []


def test_blocking_under_lock_allows_condition_idiom():
    ok = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "    def take(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait(0.1)\n"        # the condition protocol
    )
    assert _lint(ok) == []
    # waiting on a DIFFERENT primitive while holding is still flagged
    bad = (
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._ev = threading.Event()\n"
        "    def take(self):\n"
        "        with self._lock:\n"
        "            self._ev.wait()\n"
    )
    assert _rules_of(_lint(bad)) == ["blocking-under-lock"]


def test_bare_thread_fires_and_daemonized_twins_silent():
    bad = ("import threading\n"
           "def go(fn):\n"
           "    t = threading.Thread(target=fn)\n"
           "    t.start()\n")
    assert _rules_of(_lint(bad)) == ["bare-thread"]
    good_kw = ("import threading\n"
               "def go(fn):\n"
               "    t = threading.Thread(target=fn, daemon=True)\n"
               "    t.start()\n")
    assert _lint(good_kw) == []
    good_attr = ("import threading\n"
                 "def go(fn):\n"
                 "    t = threading.Thread(target=fn)\n"
                 "    t.daemon = True\n"
                 "    t.start()\n")
    assert _lint(good_attr) == []


def test_sleep_poll_fires_and_event_wait_twin_silent():
    bad = ("import time\n"
           "def spin(ready):\n"
           "    while not ready():\n"
           "        time.sleep(0.1)\n")
    assert _rules_of(_lint(bad)) == ["sleep-poll"]
    good = ("def spin(ev):\n"
            "    while not ev.is_set():\n"
            "        ev.wait(0.1)\n")
    assert _lint(good) == []
    # a one-shot backoff sleep outside a loop is not polling
    single = ("import time\n"
              "def backoff():\n"
              "    time.sleep(5)\n")
    assert _lint(single) == []


_INVERT_A = (
    "import threading\n"
    "a = threading.Lock()\n"
    "b = threading.Lock()\n"
    "def fwd():\n"
    "    with a:\n"
    "        with b:\n"
    "            pass\n"
)
_INVERT_B = (
    "from probe_a import a, b\n"
    "import threading\n"
    "a = threading.Lock()\n"
    "b = threading.Lock()\n"
    "def rev():\n"
    "    with b:\n"
    "        with a:\n"
    "            pass\n"
)


def test_lock_order_inversion_cycle_across_files(tmp_path):
    """The cross-file half: opposite nestings of the same named locks
    in two modules close a cycle."""
    conc = __import__("mxnet_tpu.analysis.concurrency",
                      fromlist=["audit_lock_order"])
    # named sync locks share identity across files
    (tmp_path / "probe_a.py").write_text(
        "import sync\n"
        "a = sync.Lock(name='L.a')\n"
        "b = sync.Lock(name='L.b')\n"
        "def fwd():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n")
    (tmp_path / "probe_b.py").write_text(
        "import sync\n"
        "a = sync.Lock(name='L.a')\n"
        "b = sync.Lock(name='L.b')\n"
        "def rev():\n"
        "    with b:\n"
        "        with a:\n"
        "            pass\n")
    diags = conc.audit_lock_order([str(tmp_path)])
    assert diags and all(d.rule == "lock-order-inversion" for d in diags)
    assert any("L.a" in d.message and "L.b" in d.message for d in diags)
    # consistent order across both files: clean
    (tmp_path / "probe_b.py").write_text(
        "import sync\n"
        "a = sync.Lock(name='L.a')\n"
        "b = sync.Lock(name='L.b')\n"
        "def fwd2():\n"
        "    with a:\n"
        "        with b:\n"
        "            pass\n")
    assert conc.audit_lock_order([str(tmp_path)]) == []


def test_lock_order_inversion_single_file_and_suppression(tmp_path):
    p = tmp_path / "single.py"
    p.write_text(_INVERT_A + _INVERT_B.replace("from probe_a import a, b\n",
                                               "")
                 .replace("import threading\n", "", 1)
                 .replace("a = threading.Lock()\n", "", 1)
                 .replace("b = threading.Lock()\n", "", 1))
    conc = __import__("mxnet_tpu.analysis.concurrency",
                      fromlist=["audit_lock_order"])
    diags = conc.audit_lock_order([str(p)])
    assert diags and {d.rule for d in diags} == {"lock-order-inversion"}
    # suppression on the closing-edge line silences that site
    src = p.read_text().replace(
        "        with a:\n",
        "        with a:  # mxlint: disable=lock-order-inversion\n")
    p.write_text(src)
    remaining = conc.audit_lock_order([str(p)])
    assert all("# mxlint" not in line for line in
               [src.splitlines()[d.line - 1] for d in remaining])


def test_static_order_edges_cover_package():
    """The bridge the runtime sanitizer seeds from: the package-wide
    edge set computes without error and contains only role names."""
    edges = an.static_order_edges(["mxnet_tpu"])
    assert isinstance(edges, set)
    for a, b in edges:
        assert isinstance(a, str) and isinstance(b, str)


# ----------------------------------------------------------------------
# --changed / --baseline (incremental lint)
# ----------------------------------------------------------------------

def _git(cwd, *args):
    subprocess.run(["git", "-c", "user.email=ci@test",
                    "-c", "user.name=ci"] + list(args),
                   cwd=cwd, check=True, capture_output=True)


def test_cli_changed_lints_only_diffed_files(tmp_path, monkeypatch):
    repo = tmp_path / "repo"
    repo.mkdir()
    _git(repo, "init", "-q")
    clean = repo / "clean.py"
    clean.write_text("def f(a=[]):\n    return a\n")   # pre-existing bug
    _git(repo, "add", ".")
    _git(repo, "commit", "-qm", "seed")
    monkeypatch.chdir(repo)
    # clean tree: --changed falls back to the last commit's files
    assert an.main(["--changed"]) == 1
    # now the committed bug is baselined away
    assert an.main(["--changed", "--write-baseline", "base.json"]) == 0
    assert an.main(["--changed", "--baseline", "base.json"]) == 0
    # a NEW finding in a newly-changed file still fails
    bad = repo / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    rc = an.main(["--changed", "--baseline", "base.json"])
    assert rc == 1


def test_cli_baseline_suppresses_known_findings(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(a=[]):\n    return a\n")
    base = tmp_path / "base.json"
    assert an.main([str(bad), "--write-baseline", str(base)]) == 0
    assert an.main([str(bad), "--baseline", str(base)]) == 0
    # an unrelated new finding is NOT covered by the baseline
    bad.write_text("def f(a=[]):\n    return a\n"
                   "try:\n    pass\nexcept:\n    pass\n")
    assert an.main([str(bad), "--baseline", str(base)]) == 1


# ----------------------------------------------------------------------
# graph checker
# ----------------------------------------------------------------------

def _mlp():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc, act_type="relu", name="relu1")
    fc2 = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def test_graph_clean_mlp_no_diagnostics():
    diags = an.check_symbol(_mlp(), shapes={"data": (4, 16),
                                            "softmax_label": (4,)})
    assert diags == []


def test_graph_duplicate_input():
    a, b = mx.sym.var("x"), mx.sym.var("x")
    diags = an.check_symbol(a + b, structural_only=True)
    assert _rules_of(diags) == ["duplicate-input"]
    clean = mx.sym.var("x") + mx.sym.var("y")
    assert an.check_symbol(clean, structural_only=True) == []


def test_graph_shape_contradiction():
    d = mx.sym.var("d", shape=(4, 5))
    w = mx.sym.var("w", shape=(3, 7))
    diags = an.check_symbol(mx.sym.dot(d, w))
    assert "shape-contradiction" in _rules_of(diags)
    ok = mx.sym.dot(mx.sym.var("a", shape=(4, 5)),
                    mx.sym.var("b", shape=(5, 7)))
    assert an.check_symbol(ok) == []


def test_graph_unknown_shape_warns():
    s = mx.sym.var("p") + mx.sym.var("q")
    diags = an.check_symbol(s)
    assert _rules_of(diags) == ["unknown-shape"]
    assert all(d.severity == an.WARNING for d in diags)


def test_graph_dtype_promotion_warns():
    lo = mx.sym.var("lo", shape=(2, 2), dtype="float16")
    hi = mx.sym.var("hi", shape=(2, 2), dtype="float32")
    diags = an.check_symbol(lo + hi)
    assert "dtype-promotion" in _rules_of(diags)
    assert all(d.severity == an.WARNING for d in diags)


def test_graph_unknown_op():
    from mxnet_tpu.symbol.symbol import Symbol, _Node
    v = _Node(None, "x", {}, [])
    bad = _Node("NoSuchOp2077", "bad0", {}, [(v, 0)])
    diags = an.check_symbol(Symbol([(bad, 0)]), structural_only=True)
    assert _rules_of(diags) == ["unknown-op"]


def test_graph_checker_accepts_model_zoo():
    """Every vision zoo family + BERT builds a graph the checker
    accepts (the acceptance bar for later perf/sharding rules)."""
    from mxnet_tpu.gluon.model_zoo import bert, vision
    cases = [("resnet18_v1", (1, 3, 224, 224)),
             ("resnet50_v2", (1, 3, 224, 224)),
             ("alexnet", (1, 3, 224, 224)),
             ("vgg11_bn", (1, 3, 224, 224)),
             ("mobilenet1.0", (1, 3, 224, 224)),
             ("mobilenetv2_1.0", (1, 3, 224, 224)),
             ("squeezenet1.0", (1, 3, 224, 224)),
             ("densenet121", (1, 3, 224, 224)),
             ("inceptionv3", (1, 3, 299, 299))]
    for name, shape in cases:
        net = vision.get_model(name)
        sym = net(mx.sym.var("data"))
        if isinstance(sym, (list, tuple)):
            sym = mx.sym.Group(list(sym))
        errors = [d for d in an.check_symbol(sym, shapes={"data": shape})
                  if d.severity == an.ERROR]
        assert not errors, (name, [d.format() for d in errors])


# ----------------------------------------------------------------------
# bind gate
# ----------------------------------------------------------------------

def test_executor_gate_raises_on_broken_graph():
    a, b = mx.sym.var("x"), mx.sym.var("x")
    with pytest.raises(an.GraphCheckError) as ei:
        (a + b).simple_bind(grad_req="null", check=True, x=(2, 2))
    assert "duplicate-input" in str(ei.value)
    assert isinstance(ei.value, MXNetError)


def test_executor_gate_env_var(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_GRAPH_CHECK", "1")
    a, b = mx.sym.var("x"), mx.sym.var("x")
    with pytest.raises(an.GraphCheckError):
        (a + b).simple_bind(grad_req="null", x=(2, 2))


def test_executor_gate_clean_bind_runs():
    ex = _mlp().simple_bind(grad_req="null", check=True, data=(2, 16),
                            softmax_label=(2,))
    out = ex.forward()[0]
    assert out.shape == (2, 4)


# ----------------------------------------------------------------------
# registry error paths (feed the checker's diagnostics)
# ----------------------------------------------------------------------

def test_get_op_did_you_mean():
    from mxnet_tpu.ops.registry import get_op
    with pytest.raises(MXNetError, match="did you mean 'Convolution'"):
        get_op("Convolutionn")
    with pytest.raises(MXNetError, match="unknown operator"):
        get_op("completely_unrelated_zzz")


def test_register_rejects_duplicates():
    from mxnet_tpu.ops.registry import OP_REGISTRY, register
    with pytest.raises(MXNetError, match="duplicate op registration"):
        @register("elemwise_add")
        def _dup(data):
            return data
    assert "_dup_alias_probe" not in OP_REGISTRY
    with pytest.raises(MXNetError, match="duplicate op alias"):
        @register("_dup_alias_probe", aliases=("elemwise_add",))
        def _dup2(data):
            return data
    # the failed registration must not leave the op name behind
    OP_REGISTRY.pop("_dup_alias_probe", None)


# ----------------------------------------------------------------------
# retrace auditor
# ----------------------------------------------------------------------

def test_retrace_audit_clean_and_anchors_present():
    diags = an.audit_retrace()
    assert [d.format() for d in diags] == []
    from mxnet_tpu.analysis.retrace import (cache_key_fields,
                                            eager_dynamic_params)
    assert set(cache_key_fields()) >= {"training", "shape", "dtype"}
    assert "lr" in eager_dynamic_params()
    # the seed's one real hazard, fixed by threading t dynamically:
    assert "t" in eager_dynamic_params()


def test_retrace_audit_flags_varying_param():
    from mxnet_tpu.analysis.retrace import _audit_varying_params
    from mxnet_tpu.ops.registry import OP_REGISTRY, Op, OpParam
    probe = Op(name="_probe_sched_op", fcompute=lambda data, lr=0.1: data,
               arg_names=("data",),
               params=[OpParam("lr", 0.1), OpParam("loss_scale", 1.0)])
    OP_REGISTRY["_probe_sched_op"] = probe
    try:
        diags = [d for d in _audit_varying_params(None)
                 if d.node == "_probe_sched_op"]
        # lr is dynamically threaded by the eager engine; loss_scale is not
        assert len(diags) == 1
        assert "['loss_scale']" in diags[0].message
    finally:
        del OP_REGISTRY["_probe_sched_op"]


def test_lamb_t_does_not_recompile():
    """The hazard the auditor caught in the seed: per-step ``t`` must
    hit one cached executable, not compile per step."""
    import numpy as np
    from mxnet_tpu.ndarray.ndarray import _EAGER_JIT_CACHE
    w = mx.nd.ones((4,))
    g = mx.nd.ones((4,))
    m = mx.nd.zeros((4,))
    v = mx.nd.zeros((4,))
    mx.nd.lamb_update_phase1(w, g, m, v, t=1)[0].asnumpy()
    keys = {k for k in _EAGER_JIT_CACHE if k[0] == "lamb_update_phase1"}
    for t in (2, 3, 4):
        mx.nd.lamb_update_phase1(w, g, m, v, t=t)[0].asnumpy()
    after = {k for k in _EAGER_JIT_CACHE if k[0] == "lamb_update_phase1"}
    assert keys == after  # no new cache entries => no recompiles
    # and the math still sees the right t
    out2 = mx.nd.lamb_update_phase1(w, g, m, v, t=2)[0].asnumpy()
    out9 = mx.nd.lamb_update_phase1(w, g, m, v, t=9)[0].asnumpy()
    assert not np.allclose(out2, out9)


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------

def test_cli_json_and_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(a=[]):\n    return a\n")
    good = tmp_path / "good.py"
    good.write_text("def f(a=None):\n    return a\n")

    rc = an.main([str(good)])
    assert rc == 0
    rc = an.main([str(bad)])
    assert rc == 1
    rc = an.main([str(bad), "--disable", "mutable-default"])
    assert rc == 0


def test_cli_subprocess_json_contract(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("try:\n    pass\nexcept:\n    pass\n")
    proc = subprocess.run(
        [sys.executable, "-m", "mxnet_tpu.analysis", str(bad), "--json"],
        capture_output=True, text=True)
    assert proc.returncode == 1
    payload = json.loads(proc.stdout)
    assert payload["errors"] == 1
    assert payload["diagnostics"][0]["rule"] == "bare-except"
    assert payload["diagnostics"][0]["line"] == 3


def test_cli_graph_mode(tmp_path):
    sym = _mlp()
    path = tmp_path / "m-symbol.json"
    sym.save(str(path))
    rc = an.main(["--graph", str(path), "--shape", "data=2,16",
                  "--shape", "softmax_label=2"])
    assert rc == 0


@pytest.mark.slow
def test_cli_self_check_clean():
    """`ci/run_all.sh lint`'s exact gate: the repo lints itself clean."""
    rc = an.main(["--self", "--json"])
    assert rc == 0
