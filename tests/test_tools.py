"""Tools tests: im2rec, launch, opperf (reference: ``tools/`` +
``benchmark/opperf``)."""
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_images(root):
    from PIL import Image
    rng = np.random.RandomState(0)
    for cls in ("a", "b"):
        os.makedirs(os.path.join(root, cls), exist_ok=True)
        for i in range(4):
            Image.fromarray(
                rng.randint(0, 255, (24, 30, 3), dtype=np.uint8)).save(
                os.path.join(root, cls, "img%d.jpg" % i))


def test_im2rec_list_and_pack(tmp_path):
    root = str(tmp_path / "imgs")
    prefix = str(tmp_path / "ds")
    _make_images(root)
    from tools import im2rec
    im2rec.main([prefix, root, "--list"])
    assert os.path.exists(prefix + ".lst")
    im2rec.main([prefix + ".lst", root, "--resize", "16",
                 "--center-crop"])
    from mxnet_tpu import recordio
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "r")
    assert len(rec.keys) == 8
    hdr, img = recordio.unpack_img(rec.read_idx(rec.keys[0]))
    assert img.shape == (16, 16, 3)
    assert hdr.label in (0.0, 1.0)


def test_launch_local_env():
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", sys.executable, "-c",
         "import os; print(os.environ['MXNET_TPU_PROC_ID'],"
         "os.environ['MXNET_TPU_NUM_PROCS'])"],
        capture_output=True, text=True, timeout=60)
    assert out.returncode == 0, out.stderr
    # the launcher relays each worker line atomically with a "[rank] "
    # prefix (dmlc tracker behavior), so lines can never interleave
    lines = out.stdout.strip().splitlines()
    assert all(line.startswith("[") for line in lines), lines
    ranks = sorted(line.split()[1] for line in lines)
    assert ranks == ["0", "1"]
    prefixes = sorted(line.split()[0] for line in lines)
    assert prefixes == ["[0]", "[1]"]


def test_opperf_runs():
    from benchmark import opperf
    results = opperf.run(ops=["relu", "dot"], warmup=1, runs=2)
    by_op = {r["op"]: r for r in results}
    assert "avg_us" in by_op["relu"] and "avg_us" in by_op["dot"]


def test_distributed_init_noop_single_process(monkeypatch):
    import mxnet_tpu as mx
    monkeypatch.delenv("MXNET_TPU_COORDINATOR", raising=False)
    assert mx.distributed_init() is False
