"""Tensor-parallel sharding tests (8 virtual CPU devices; the same
GSPMD path runs on a v5e pod)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.parallel import (TensorParallelMLP, make_mesh,
                                shard_block_tp)


def _cpu_mesh(shape):
    devs = jax.devices("cpu")
    n = int(np.prod(list(shape.values())))
    if len(devs) < n:
        pytest.skip("need %d cpu devices" % n)
    return make_mesh(shape, devices=devs[:n])


def test_tp_mlp_matches_single_device():
    mesh = _cpu_mesh({"dp": 2, "tp": 4})
    mx.random.seed(0)
    mlp = TensorParallelMLP(64, 32, mesh=mesh)
    mlp.initialize()
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(8, 32).astype(np.float32))
    want = mlp(x).asnumpy()          # single-device reference

    mlp.shard(mesh)                  # annotate + place params
    w = mlp.up.weight.data()._data
    assert len(w.sharding.device_set) == 8
    # jit over the mesh: XLA partitions the matmuls, inserting the
    # all-reduce at the row-parallel output
    pure_fn, pnames, pmap = mlp.functionalize(training=False)
    pvals = {n: pmap[n]._data._data for n in pnames}
    key = jax.random.PRNGKey(0)
    xs = jax.device_put(x._data, NamedSharding(mesh, P("dp", None)))

    @jax.jit
    def fwd(pvals, xv):
        outs, _ = pure_fn(pvals, [xv], key)
        return outs[0]

    got = np.asarray(fwd(pvals, xs))
    np.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-5)


def test_tp_grad_matches_single_device():
    mesh = _cpu_mesh({"dp": 2, "tp": 4})
    mx.random.seed(0)
    mlp = TensorParallelMLP(48, 16, mesh=mesh)
    mlp.initialize()
    x = mx.nd.array(np.random.RandomState(1)
                    .randn(4, 16).astype(np.float32))

    pure_fn, pnames, pmap = mlp.functionalize(training=False)
    pvals = {n: pmap[n]._data._data for n in pnames}
    key = jax.random.PRNGKey(0)

    def loss(pvals, xv):
        outs, _ = pure_fn(pvals, [xv], key)
        return jnp.sum(outs[0] ** 2)

    ref_grads = jax.grad(loss)(pvals, x._data)

    mlp.shard(mesh)
    pvals_sh = {n: pmap[n]._data._data for n in pnames}
    xs = jax.device_put(x._data, NamedSharding(mesh, P("dp", None)))
    got_grads = jax.jit(jax.grad(loss))(pvals_sh, xs)
    for n in pnames:
        np.testing.assert_allclose(np.asarray(got_grads[n]),
                                   np.asarray(ref_grads[n]),
                                   rtol=2e-4, atol=2e-5)


def test_shard_block_tp_rules():
    mesh = _cpu_mesh({"tp": 8})
    net = gluon.nn.HybridSequential()
    with net.name_scope():
        net.add(gluon.nn.Dense(32, flatten=False, prefix="up_"),
                gluon.nn.Dense(16, flatten=False, prefix="down_"))
    net.initialize()
    net(mx.nd.zeros((2, 16)))
    sharded = shard_block_tp(net, mesh)
    assert any("up_weight" in s for s in sharded)
    assert any("down_weight" in s for s in sharded)
    w = [p for p in net.collect_params().values()
         if "up_weight" in p.name][0]
    assert len(w.data()._data.sharding.device_set) == 8
