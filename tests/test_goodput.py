"""Goodput ledger (ISSUE 14): per-window step-time attribution with a
hard reconciliation contract, rolling MFU, and the regression sentinel
with its env/publish guards.

The acceptance contracts live here: categories sum to window wall
within tolerance on every window; a seeded input stall classifies
input-bound (and the sentinel NAMES input_wait); a seeded slow-dispatch
run under a degraded env gauge classifies degraded-env, NOT regression;
edge windows (zero-step, first-window, publish-spanning) never divide
by zero or flag spuriously.
"""
import json
import os
import time

import numpy as np
import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.obs import goodput
from mxnet_tpu.obs.goodput import CATEGORIES, StepLedger


@pytest.fixture()
def telem():
    """Telemetry armed with a clean slate for the instruments the
    ledger reads/writes; restores the prior enable state."""
    was = telemetry.enabled()
    telemetry.enable()
    for prefix in ("goodput.", "profiling.", "trainer.", "feed.",
                   "data.", "dispatch.", "checkpoint.", "compile.",
                   "env."):
        telemetry.reset(prefix)
    yield telemetry
    for prefix in ("goodput.", "env."):
        telemetry.reset(prefix)
    goodput.reset()
    if not was:
        telemetry.disable()


def _spin(seconds):
    """Sleep-free wall burn (sleep granularity on loaded CI boxes can
    exceed the window walls these tests build)."""
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < seconds:
        pass


def _window(led, per_step, steps=4, pad=0.002):
    """Drive one window: observe per-category seconds per step and burn
    at least as much wall so attribution can never overshoot."""
    out = None
    for _ in range(steps):
        total = 0.0
        for name, v in per_step.items():
            telemetry.timer(name).observe(v)
            total += v
        _spin(total + pad)
        out = led.step() or out
    return out


# -- attribution + reconciliation --------------------------------------

def test_window_attribution_and_reconciliation(telem):
    led = StepLedger(window_steps=4)
    w = _window(led, {"profiling.step_time": 0.008,
                      "feed.consumer_wait": 0.002})
    assert w is not None and w["steps"] == 4
    cats = w["categories"]
    assert cats["device_compute"]["seconds"] == pytest.approx(0.032,
                                                              rel=1e-3)
    assert cats["input_wait"]["seconds"] == pytest.approx(0.008,
                                                          rel=1e-3)
    rec = w["reconciliation"]
    assert rec["ok"] and rec["error"] == 0.0
    # categories + other sum EXACTLY to wall (other is the remainder)
    assert rec["sum_s"] == pytest.approx(rec["wall_s"], abs=1e-5)
    assert set(cats) == set(CATEGORIES)
    shares = sum(c["share"] for c in cats.values())
    assert shares == pytest.approx(1.0, abs=1e-6)


def test_overshoot_fails_reconciliation(telem):
    """Attributed time exceeding wall (double counting) is the ONE way
    the contract can fail -- and it must fail loudly, not clamp."""
    led = StepLedger(window_steps=1, tol=0.25)
    telemetry.timer("profiling.step_time").observe(30.0)  # >> wall
    w = led.step()
    assert not w["reconciliation"]["ok"]
    assert w["reconciliation"]["error"] > 0.25
    assert w["categories"]["other"]["seconds"] == 0.0


def test_trainer_and_profiling_step_time_both_count(telem):
    """Eager loops record trainer.step_time, compiled TrainSteps record
    profiling.step_time; both land in device_compute."""
    led = StepLedger(window_steps=2)
    w = _window(led, {"trainer.step_time": 0.005}, steps=2)
    assert w["categories"]["device_compute"]["seconds"] == \
        pytest.approx(0.01, rel=1e-3)


# -- verdicts ----------------------------------------------------------

def test_input_stall_classified_input_bound(telem):
    """Acceptance: a seeded input stall reads input-bound, with the
    feed-supply percentage in the verdict sentence."""
    led = StepLedger(window_steps=4)
    w = _window(led, {"profiling.step_time": 0.004,
                      "feed.consumer_wait": 0.012})
    assert w["verdict"]["bound"] == "input"
    assert w["verdict"]["detail"].startswith("input-bound: feed supplies")
    assert "25%" in w["verdict"]["detail"]   # 0.004 / 0.016


def test_compute_bound_and_checkpoint_bound_verdicts(telem):
    led = StepLedger(window_steps=2)
    w = _window(led, {"profiling.step_time": 0.02}, steps=2)
    assert w["verdict"]["bound"] == "compute"
    w = _window(led, {"profiling.step_time": 0.004,
                      "checkpoint.save_time": 0.01}, steps=2)
    assert w["verdict"]["bound"] == "checkpoint"


# -- edge windows (satellite) ------------------------------------------

def test_zero_step_window_is_idle_not_crash(telem):
    """Serving-only windows: no steps, no division by zero, no
    sentinel, reconciliation still holds."""
    led = StepLedger(window_steps=4)
    _spin(0.005)
    w = led.flush()
    assert w["steps"] == 0
    assert w["verdict"]["bound"] == "idle"
    assert w["reconciliation"]["ok"]
    assert w["regressions"] == []
    assert w["mfu"] is None
    for c in w["categories"].values():
        assert c["per_step_s"] is None


def test_first_window_has_no_baseline_no_regression(telem):
    """The very first window -- even a pathological one -- cannot flag
    (no baseline yet)."""
    led = StepLedger(window_steps=2)
    w = _window(led, {"feed.consumer_wait": 0.05}, steps=2)
    assert w["regressions"] == []


def test_publish_window_no_spurious_checkpoint_regression(telem):
    """A window spanning a checkpoint publish expects its
    checkpoint_stall spike: guarded, not flagged."""
    led = StepLedger(window_steps=2, min_baseline=2)
    for _ in range(3):                       # healthy baseline windows
        _window(led, {"profiling.step_time": 0.004}, steps=2)
    led.note_publish()
    w = _window(led, {"profiling.step_time": 0.004,
                      "checkpoint.save_time": 0.03}, steps=2)
    assert w["publishes"] == 1
    assert w["regressions"] == []
    # the SAME spike without a publish in the window DOES flag
    w2 = _window(led, {"profiling.step_time": 0.004,
                       "checkpoint.save_time": 0.03}, steps=2)
    assert [r["category"] for r in w2["regressions"]] == \
        ["checkpoint_stall"]


# -- the sentinel ------------------------------------------------------

def test_sentinel_names_the_category_that_moved(telem):
    led = StepLedger(window_steps=4, min_baseline=3)
    for _ in range(4):                       # baseline: healthy feed
        w = _window(led, {"profiling.step_time": 0.005,
                          "feed.consumer_wait": 0.001})
        assert w["regressions"] == []
    w = _window(led, {"profiling.step_time": 0.005,
                      "feed.consumer_wait": 0.02})   # 20x stall
    cats = [r["category"] for r in w["regressions"]]
    assert cats == ["input_wait"], w["regressions"]
    r = w["regressions"][0]
    assert r["per_step_s"] == pytest.approx(0.02, rel=0.05)
    assert r["ratio"] and r["ratio"] > 5
    # published as the named event + counter
    ev = telemetry.event("goodput.regression").recent[-1]
    assert ev["category"] == "input_wait"
    assert telemetry.counter("goodput.regressions").value >= 1


def test_sentinel_ignores_insignificant_jitter(telem):
    """A category that doubles but moves < 5% of the window wall is
    jitter, not a regression."""
    led = StepLedger(window_steps=4, min_baseline=3)
    for _ in range(4):
        _window(led, {"profiling.step_time": 0.01,
                      "feed.consumer_wait": 0.0001})
    w = _window(led, {"profiling.step_time": 0.01,
                      "feed.consumer_wait": 0.0003})
    assert w["regressions"] == []


def test_env_guard_degraded_env_not_regression(telem):
    """Acceptance (the r05 lesson): a slow-dispatch window while the
    env health gauge reads degraded is reported as environment --
    goodput.env_degraded -- and NEVER as a regression; the baseline
    stays clean of the degraded sample."""
    led = StepLedger(window_steps=4, min_baseline=3)
    for _ in range(4):
        _window(led, {"profiling.step_time": 0.004})
    base_before = led.baseline()["device_compute"]["mean"]
    # the bench health probe's gauge says the tunnel collapsed
    telemetry.gauge("env.dispatch_roundtrip_us").set(90000.0)
    w = _window(led, {"profiling.step_time": 0.015})  # ~4x slower
    assert w["env_degraded"] is True
    assert w["regressions"] == []
    assert telemetry.counter("goodput.env_degraded_windows").value == 1
    ev = telemetry.event("goodput.env_degraded").recent[-1]
    assert ev["dispatch_roundtrip_us"] == 90000.0
    assert led.baseline()["device_compute"]["mean"] == \
        pytest.approx(base_before)
    # tunnel recovers: the same slowdown now IS a regression
    telemetry.gauge("env.dispatch_roundtrip_us").set(2.0)
    w2 = _window(led, {"profiling.step_time": 0.015})
    assert w2["env_degraded"] is False
    assert [r["category"] for r in w2["regressions"]] == \
        ["device_compute"]


def test_env_degraded_threshold_matches_bench_flag():
    """The sentinel's env guard and bench.py's per-line degraded_env
    flag derive from ONE constant, so they cannot disagree."""
    import sys
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        import bench
    finally:
        sys.path.pop(0)
    assert bench._DEGRADED_RTT_US == goodput.DEGRADED_RTT_US
    assert goodput.env_degraded(90000.0) is True
    assert goodput.env_degraded(2.0) is False


# -- MFU ---------------------------------------------------------------

def test_mfu_from_flops_per_step(telem):
    from mxnet_tpu.profiling import roofline
    led = StepLedger(window_steps=4, flops_per_step=1e9)
    w = _window(led, {"profiling.step_time": 0.005})
    peak, _bw, _assumed = roofline.device_peaks()
    assert w["flops"] == pytest.approx(4e9)
    assert w["mfu"] == pytest.approx(4e9 / w["wall_s"] / peak, rel=0.01)
    assert telemetry.gauge("goodput.mfu").value == w["mfu"]


def test_mfu_from_profiling_store(telem):
    """flops_per_step resolves from the captured TrainStep's CostReport
    (the 'executable's cost report' MFU source the issue names)."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, profiling
    from mxnet_tpu.parallel import TrainStep
    was = profiling.enabled()
    profiling.enable()
    try:
        profiling.reset()
        net = gluon.nn.Dense(4)
        net.initialize()
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=None)
        step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
        step(mx.nd.array(np.ones((8, 6), np.float32)),
             mx.nd.array(np.ones((8, 4), np.float32)))
        fps = profiling.flops_per_step()        # first train_step kind
        assert fps and fps > 0
        assert profiling.flops_per_step("no-such-label") is None
        led = StepLedger(window_steps=2, flops_per_step=fps)
        w = _window(led, {"profiling.step_time": 0.004}, steps=2)
        assert w["mfu"] is not None and w["flops"] == \
            pytest.approx(2 * fps)
    finally:
        profiling.reset()
        if not was:
            profiling.disable()


def test_mfu_callable_and_failure_tolerated(telem):
    led = StepLedger(window_steps=2)
    led.flops_per_step = lambda: (_ for _ in ()).throw(RuntimeError())
    w = _window(led, {"profiling.step_time": 0.004}, steps=2)
    assert w["mfu"] is None                   # failed callable = no MFU


# -- publication + status ----------------------------------------------

def test_window_publishes_goodput_instruments(telem):
    led = StepLedger(window_steps=2)
    _window(led, {"profiling.step_time": 0.006,
                  "feed.consumer_wait": 0.002}, steps=2)
    assert telemetry.counter("goodput.windows").value == 1
    assert telemetry.counter("goodput.steps").value == 2
    assert telemetry.timer("goodput.device_compute_s").count == 1
    assert telemetry.timer("goodput.device_compute_s").sum == \
        pytest.approx(0.012, rel=1e-3)
    assert telemetry.gauge("goodput.input_wait_share").value > 0
    ev = telemetry.event("goodput.window").recent[-1]
    for key in ("index", "steps", "wall_s", "shares", "verdict",
                "bound", "reconciled", "env_degraded"):
        assert key in ev, key
    assert set(ev["shares"]) == set(CATEGORIES)


def test_line_summary_shape(telem):
    led = StepLedger(window_steps=2)
    w = _window(led, {"profiling.step_time": 0.006}, steps=2)
    line = goodput.line_summary(w)
    assert set(line) == {"steps", "wall_s", "mfu", "shares", "verdict",
                         "bound", "reconciled", "env_degraded"}
    json.dumps(line)                          # JSONL-safe
    assert goodput.line_summary(None) is None


def test_statusz_carries_latest_window(telem):
    from mxnet_tpu.obs import status
    goodput.reset()
    led = goodput.ledger(window_steps=2)
    _window(led, {"profiling.step_time": 0.004}, steps=2)
    st = status.statusz()
    assert st["goodput"] is not None
    assert st["goodput"]["steps"] == 2
    goodput.reset()


def test_windows_ring_bounded(telem):
    led = StepLedger(window_steps=1, history=5)
    for _ in range(8):
        telemetry.timer("profiling.step_time").observe(0.0005)
        led.step()
    wins = led.windows()
    assert len(wins) == 5
    assert wins[-1]["index"] == 7


# -- loop wiring -------------------------------------------------------

def test_continuous_trainer_ticks_process_ledger(telem, tmp_path,
                                                 monkeypatch):
    from mxnet_tpu import obs
    from mxnet_tpu.chaos import scenarios
    from mxnet_tpu.serving.loop import ContinuousTrainer
    goodput.reset()
    monkeypatch.setenv("MXNET_TPU_OBS_GOODPUT_WINDOW", "3")
    obs.enable_goodput()
    try:
        net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
        ct = ContinuousTrainer(net, trainer, loss_fn, data,
                               str(tmp_path), publish_every=3)
        ct.run_steps(7)
        ct.close()
    finally:
        obs.disable_goodput()
    wins = goodput.ledger().windows()
    # 2 full windows of 3 + the tail window flushed by close()
    assert len(wins) == 3
    assert [w["steps"] for w in wins] == [3, 3, 1]
    assert wins[-1]["reason"] == "close"
    # the publish guard was marked on the publishing windows
    assert wins[0]["publishes"] == 1 and wins[1]["publishes"] == 1
    for w in wins:
        assert w["reconciliation"]["ok"]
    goodput.reset()


def test_disabled_mode_makes_zero_ledger_calls(tmp_path, monkeypatch):
    """The telemetry zero-overhead contract, applied to the goodput
    hooks: with the flag off, the loop never touches obs.goodput."""
    from mxnet_tpu import obs
    from mxnet_tpu.chaos import scenarios
    from mxnet_tpu.serving.loop import ContinuousTrainer
    assert not obs.goodput_enabled()
    calls = []
    monkeypatch.setattr(goodput, "ledger",
                        lambda **kw: calls.append(kw))
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data, str(tmp_path),
                           publish_every=2)
    ct.run_steps(4)
    ct.close()
    assert calls == []


def test_host_sync_timer_records_seconds(telem):
    import mxnet_tpu as mx
    telemetry.reset("dispatch.")
    mx.nd.array(np.ones((4,), np.float32)).asnumpy()
    t = telemetry.registry().get("dispatch.host_sync_time")
    assert t is not None and t.count >= 1
    assert telemetry.counter("dispatch.host_sync.asnumpy").value >= 1


# -- summarize CLI -----------------------------------------------------

def _ledger_run_jsonl(path, stall_s, rank=None, step_s=0.004):
    """One rank's JSONL: 2 windows of 4 steps with the given per-step
    input stall (written through the real sink + ledger)."""
    from mxnet_tpu.telemetry import JsonlSink
    for prefix in ("goodput.", "trainer.", "feed.", "profiling."):
        telemetry.reset(prefix)
    sink = telemetry.registry().attach(JsonlSink(str(path), rank=rank))
    try:
        led = StepLedger(window_steps=4)
        for _ in range(2):
            _window(led, {"trainer.step_time": step_s,
                          "feed.consumer_wait": stall_s})
        led.flush()           # zero-step tail (the trainer-close shape)
        telemetry.flush()
    finally:
        telemetry.registry().detach(sink)
        sink.close()


def test_summarize_goodput_section_and_verdict_line(telem, tmp_path):
    from mxnet_tpu.telemetry import cli as tcli
    path = tmp_path / "run.jsonl"
    _ledger_run_jsonl(path, stall_s=0.012)
    agg = tcli.summarize_file(str(path))
    gp = agg["goodput"]
    assert gp["windows"] == 3 and gp["steps"] == 8
    # the verdict comes from the last ACTIVE window -- the zero-step
    # tail flush must not mask it with "idle"
    assert gp["bound"] == "input"
    assert gp["verdict"].startswith("input-bound: feed supplies")
    assert gp["categories"]["input_wait"]["total_s"] == \
        pytest.approx(0.096, rel=0.01)
    assert gp["categories"]["input_wait"]["share"] > \
        gp["categories"]["device_compute"]["share"]
    text = tcli._render_human(agg)
    assert "bottleneck: input-bound: feed supplies" in text
    assert "goodput: 3 windows / 8 steps" in text


def test_per_rank_skew_names_the_category(telem, tmp_path):
    """ISSUE 14 satellite: the multi-file skew verdict names WHICH
    category differs on the slow rank (rank 1 input_wait ~Nx median),
    not just that it is slow."""
    from mxnet_tpu.telemetry import cli as tcli
    r0, r1 = tmp_path / "r0.jsonl", tmp_path / "r1.jsonl"
    _ledger_run_jsonl(r0, stall_s=0.001, rank=0)     # healthy rank
    # rank 1 is slow (2x step wall trips the skew flag) but the CAUSE
    # is the 20x input stall -- the attribution must name input_wait,
    # not just repeat "slow"
    _ledger_run_jsonl(r1, stall_s=0.02, rank=1, step_s=0.008)
    agg = tcli.summarize_files([str(r0), str(r1)], skew_threshold=1.25)
    sk = agg["skew"]
    assert sk["straggler"] and sk["straggler_ranks"] == [1]
    attr = sk["category_attribution"]
    assert len(attr) == 1
    assert attr[0]["rank"] == 1
    assert attr[0]["category"] == "input_wait"
    assert attr[0]["ratio"] > 3
    text = tcli._render_ranks(agg)
    assert "rank 1 slow: input_wait" in text


def test_balanced_ranks_no_attribution(telem, tmp_path):
    from mxnet_tpu.telemetry import cli as tcli
    r0, r1 = tmp_path / "r0.jsonl", tmp_path / "r1.jsonl"
    _ledger_run_jsonl(r0, stall_s=0.004, rank=0)
    _ledger_run_jsonl(r1, stall_s=0.004, rank=1)
    agg = tcli.summarize_files([str(r0), str(r1)])
    assert not agg["skew"]["straggler"]
    assert agg["skew"]["category_attribution"] == []


# -- registration ------------------------------------------------------

def test_env_vars_registered():
    from mxnet_tpu import env as _env
    for name in ("MXNET_TPU_OBS_GOODPUT", "MXNET_TPU_OBS_GOODPUT_WINDOW",
                 "MXNET_TPU_OBS_GOODPUT_TOL",
                 "MXNET_TPU_OBS_GOODPUT_MAD_K"):
        assert name in _env.REGISTRY, name
    assert _env.get("MXNET_TPU_OBS_GOODPUT_WINDOW") == 20


def test_features_row():
    import mxnet_tpu as mx
    from mxnet_tpu import obs
    assert mx.runtime.Features().is_enabled("OBS_GOODPUT") \
        == obs.goodput_enabled()
