"""Data-pipeline tests: io iterators, image augmenters, record
iterators (reference: ``tests/python/unittest/test_io.py`` /
``test_image.py``)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image, io, recordio


def _make_rec(tmp_path, n=12, hw=(32, 36)):
    prefix = str(tmp_path / "ds")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, hw + (3,), dtype=np.uint8)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img))
    rec.close()
    return prefix


def test_ndarray_iter_pad_and_discard():
    x = np.arange(10, dtype=np.float32).reshape(10, 1)
    it = io.NDArrayIter(x, x[:, 0], batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].pad == 2
    it2 = io.NDArrayIter(x, x[:, 0], batch_size=4,
                         last_batch_handle="discard")
    assert len(list(it2)) == 2


def test_resize_iter():
    x = np.zeros((8, 2), np.float32)
    base = io.NDArrayIter(x, batch_size=4)
    it = io.ResizeIter(base, size=5)
    assert len(list(it)) == 5


def test_image_record_iter(tmp_path):
    prefix = _make_rec(tmp_path)
    it = io.ImageRecordIter(path_imgrec=prefix + ".rec",
                            data_shape=(3, 24, 24), batch_size=4,
                            mean_r=128, mean_g=128, mean_b=128,
                            preprocess_threads=2)
    batch = next(iter(it))
    assert batch.data[0].shape == (4, 3, 24, 24)
    assert batch.label[0].shape[0] == 4
    # mean-normalized floats, not raw uint8
    assert batch.data[0].asnumpy().min() < 0


def test_image_iter_sharding(tmp_path):
    # distinct labels per record so shard contents are identifiable
    prefix = str(tmp_path / "ds")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    rng = np.random.RandomState(0)
    for i in range(12):
        img = rng.randint(0, 255, (32, 36, 3), dtype=np.uint8)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img))
    rec.close()
    parts = []
    for pi in range(2):
        it = image.ImageIter(4, (3, 24, 24), path_imgrec=prefix + ".rec",
                             num_parts=2, part_index=pi)
        labels = []
        for b in it:
            labels.extend(b.label[0].asnumpy().tolist())
        parts.append(set(labels))
    # the two shards are disjoint and together cover every record
    assert parts[0].isdisjoint(parts[1])
    assert parts[0] | parts[1] == set(float(i) for i in range(12))


def test_augmenters():
    rng = np.random.RandomState(0)
    img = mx.nd.array(rng.randint(0, 255, (40, 50, 3),
                                  dtype=np.uint8).astype(np.float32))
    out = image.ResizeAug(32)(img)
    assert min(out.shape[:2]) == 32
    out = image.CenterCropAug((24, 24))(img)
    assert out.shape[:2] == (24, 24)
    out = image.RandomCropAug((24, 24))(img)
    assert out.shape[:2] == (24, 24)
    flipped = image.HorizontalFlipAug(1.0)(img)
    np.testing.assert_allclose(flipped.asnumpy(),
                               img.asnumpy()[:, ::-1])
    jit = image.ColorJitterAug(0.3, 0.3, 0.3)(img)
    assert jit.shape == img.shape
    auglist = image.CreateAugmenter((3, 24, 24), resize=32,
                                    rand_mirror=True, brightness=0.1)
    assert len(auglist) >= 4


def test_imdecode_imresize():
    import io as _io
    from PIL import Image
    rng = np.random.RandomState(0)
    arr = rng.randint(0, 255, (20, 30, 3), dtype=np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, "PNG")
    img = image.imdecode(buf.getvalue())
    assert img.shape == (20, 30, 3)
    small = image.imresize(img, 10, 8)
    assert small.shape[:2] == (8, 10)


def test_csv_iter(tmp_path):
    path = str(tmp_path / "d.csv")
    np.savetxt(path, np.arange(12).reshape(4, 3), delimiter=",")
    it = io.CSVIter(data_csv=path, data_shape=(3,), batch_size=2)
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 3)
