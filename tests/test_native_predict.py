"""C-callable edge predict runtime (reference: ``c_predict_api.cc`` +
``amalgamation/``): a compiled C program must run LeNet inference from
an exported artifact with no Python in the loop."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.onnx import export_model
from mxnet_tpu.predictor import NativePredictor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lenet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(16, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    return net


def _export(net, x, tmp_path, name):
    want = net(mx.nd.array(x)).asnumpy()
    sym_f, par_f = net.export(str(tmp_path / name))
    onnx_file = str(tmp_path / (name + ".onnx"))
    export_model(sym_f, par_f, in_shapes=[x.shape],
                 onnx_file_path=onnx_file)
    return onnx_file, want


def test_native_predictor_matches_python(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 1, 28, 28).astype(np.float32)
    onnx_file, want = _export(_lenet(), x, tmp_path, "lenet")
    pred = NativePredictor(onnx_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    pred.close()


def test_native_predictor_batchnorm_resnet_block(tmp_path):
    rng = np.random.RandomState(1)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, use_bias=False),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    onnx_file, want = _export(net, x, tmp_path, "bnblock")
    pred = NativePredictor(onnx_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def _repack_tensor_dims(model_bytes):
    """Re-encode every initializer TensorProto's dims (field 1) as a
    proto3 *packed* repeated int64 -- the encoding the official onnx
    package emits -- leaving everything else byte-identical."""
    from mxnet_tpu.onnx import wire

    def repack_tensor(tbuf):
        out = b""
        dims = []
        pos = 0
        while pos < len(tbuf):
            key, npos = wire._read_uvarint(tbuf, pos)
            num, wt = key >> 3, key & 7
            if wt == 0:
                val, npos = wire._read_uvarint(tbuf, npos)
                if num == 1:
                    dims.append(val)
                    pos = npos
                    continue
            elif wt == 2:
                ln, npos = wire._read_uvarint(tbuf, npos)
                npos += ln
            elif wt == 5:
                npos += 4
            elif wt == 1:
                npos += 8
            out += tbuf[pos:npos]
            pos = npos
        packed = b"".join(wire._uvarint(d) for d in dims)
        return wire.field_bytes(1, packed) + out

    def rewrite(buf, field_num, fn):
        out = b""
        pos = 0
        while pos < len(buf):
            key, npos = wire._read_uvarint(buf, pos)
            num, wt = key >> 3, key & 7
            if wt == 0:
                _, npos = wire._read_uvarint(buf, npos)
            elif wt == 2:
                ln, vpos = wire._read_uvarint(buf, npos)
                if num == field_num:
                    payload = fn(buf[vpos:vpos + ln])
                    out += wire.field_bytes(num, payload)
                    pos = vpos + ln
                    continue
                npos = vpos + ln
            elif wt == 5:
                npos += 4
            elif wt == 1:
                npos += 8
            out += buf[pos:npos]
            pos = npos
        return out

    # ModelProto.graph = field 7; GraphProto.initializer = field 5
    return rewrite(model_bytes, 7,
                   lambda g: rewrite(g, 5, repack_tensor))


def test_native_predictor_packed_dims(tmp_path):
    """proto3 serializers (the official onnx package) emit TensorProto
    dims packed; the native parser must accept that encoding too."""
    rng = np.random.RandomState(3)
    x = rng.randn(2, 1, 28, 28).astype(np.float32)
    onnx_file, want = _export(_lenet(), x, tmp_path, "lenet_packed")
    raw = open(onnx_file, "rb").read()
    repacked = _repack_tensor_dims(raw)
    assert repacked != raw  # the rewrite really changed the encoding
    packed_file = str(tmp_path / "lenet_packed2.onnx")
    open(packed_file, "wb").write(repacked)
    # sanity: the python importer agrees on shapes after the repack
    from mxnet_tpu.onnx import wire
    pred = NativePredictor(packed_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    pred.close()


def test_cpp_example_runs_without_python(tmp_path):
    """Compile examples/cpp_predict/main.cc against the runtime and run
    LeNet inference as a plain OS process."""
    from mxnet_tpu._native import load_predict, predict_so_path
    if load_predict() is None:
        pytest.skip("no native toolchain")
    rng = np.random.RandomState(2)
    x = rng.randn(1, 1, 28, 28).astype(np.float32)
    onnx_file, _want = _export(_lenet(), x, tmp_path, "lenet_c")

    exe = str(tmp_path / "cpp_predict")
    src = os.path.join(REPO, "examples", "cpp_predict", "main.cc")
    so = predict_so_path()
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", src, "-o", exe, so,
         "-Wl,-rpath," + os.path.dirname(so)],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    params_file = str(tmp_path / "weights.params")
    mx.nd.save(params_file,
               {"w": mx.nd.array(np.ones((2, 2), np.float32) * 7)})
    run = subprocess.run([exe, onnx_file, "1", "1", "28", "28",
                          params_file],
                         capture_output=True, text=True, timeout=120)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "output shape: (1, 10)" in run.stdout, run.stdout
    assert "params: 1 arrays" in run.stdout, run.stdout
    assert "w rank=2 first=7.0" in run.stdout, run.stdout


def test_ndlist_reads_params_without_python(tmp_path):
    """The MXNDList* ABI slice (reference: ``c_predict_api.h ::
    MXNDListCreate``): a C caller loads the framework's .params
    container -- names, shapes, values across dtypes -- with no Python
    in the loop (this test only USES ctypes to drive the C ABI)."""
    import ctypes

    import jax.numpy as jnp
    from mxnet_tpu._native import load_predict
    lib = load_predict()
    if lib is None:
        pytest.skip("no C++ toolchain")

    rng = np.random.RandomState(0)
    fixture = {
        "w": rng.randn(3, 4).astype(np.float32),
        "idx": np.array([5, 1, 9], np.int32),
        "bytes": np.arange(6, dtype=np.uint8).reshape(2, 3),
        "half": np.array([0.5, -2.25, 64.0], np.float16),
    }
    path = str(tmp_path / "mixed.params")
    arrs = {k: mx.nd.array(v, dtype=v.dtype) for k, v in fixture.items()}
    arrs["bf"] = mx.nd.array(np.array([1.5, -3.0], np.float32)).astype(
        jnp.bfloat16.dtype)
    fixture["bf"] = np.array([1.5, -3.0], np.float32)
    mx.nd.save(path, arrs)

    lib.MXNDListCreateFromFile.restype = ctypes.c_int
    lib.MXNDListGet.restype = ctypes.c_int
    lib.MXPredGetLastError.restype = ctypes.c_char_p
    h = ctypes.c_void_p()
    count = ctypes.c_int64()
    rc = lib.MXNDListCreateFromFile(path.encode(), ctypes.byref(h),
                                    ctypes.byref(count))
    assert rc == 0, lib.MXPredGetLastError().decode()
    assert count.value == len(fixture)
    seen = {}
    for i in range(count.value):
        key = ctypes.c_char_p()
        data = ctypes.POINTER(ctypes.c_float)()
        shape = ctypes.POINTER(ctypes.c_int64)()
        ndim = ctypes.c_int()
        rc = lib.MXNDListGet(h, ctypes.c_int64(i), ctypes.byref(key),
                             ctypes.byref(data), ctypes.byref(shape),
                             ctypes.byref(ndim))
        assert rc == 0, lib.MXPredGetLastError().decode()
        shp = tuple(shape[d] for d in range(ndim.value))
        n = int(np.prod(shp)) if shp else 1
        vals = np.array([data[j] for j in range(n)],
                        np.float32).reshape(shp)
        seen[key.value.decode()] = vals
    lib.MXNDListFree(h)

    assert set(seen) == set(fixture)
    for k, v in fixture.items():
        np.testing.assert_allclose(seen[k], v.astype(np.float32),
                                   rtol=1e-3, err_msg=k)

    # corrupt input must error cleanly, not crash
    import struct

    def expect_reject(name, payload, needle):
        p = str(tmp_path / name)
        open(p, "wb").write(payload)
        rc = lib.MXNDListCreateFromFile(p.encode(), ctypes.byref(h),
                                        ctypes.byref(count))
        assert rc != 0, name
        assert needle in lib.MXPredGetLastError(), (
            name, lib.MXPredGetLastError())

    expect_reject("bad.params", b"\x00" * 16, b"magic")
    # a tiny file claiming 2^24 arrays must not allocate for them
    expect_reject("bigcount.params",
                  struct.pack("<QQQ", 0x112, 0, 1 << 24), b"header")
    # dims whose product overflows int64 must be rejected, not wrapped
    expect_reject(
        "dimflow.params",
        struct.pack("<QQQ", 0x112, 0, 1)
        + struct.pack("<IiI", 0xF993FAC9, 0, 2)
        + struct.pack("<qq", 1 << 32, 1 << 32)
        + struct.pack("<iii", 1, 0, 0) + b"\x00" * 64,
        b"dims")
