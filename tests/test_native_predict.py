"""C-callable edge predict runtime (reference: ``c_predict_api.cc`` +
``amalgamation/``): a compiled C program must run LeNet inference from
an exported artifact with no Python in the loop."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.onnx import export_model
from mxnet_tpu.predictor import NativePredictor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lenet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(16, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    return net


def _export(net, x, tmp_path, name):
    want = net(mx.nd.array(x)).asnumpy()
    sym_f, par_f = net.export(str(tmp_path / name))
    onnx_file = str(tmp_path / (name + ".onnx"))
    export_model(sym_f, par_f, in_shapes=[x.shape],
                 onnx_file_path=onnx_file)
    return onnx_file, want


def test_native_predictor_matches_python(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 1, 28, 28).astype(np.float32)
    onnx_file, want = _export(_lenet(), x, tmp_path, "lenet")
    pred = NativePredictor(onnx_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    pred.close()


def test_native_predictor_batchnorm_resnet_block(tmp_path):
    rng = np.random.RandomState(1)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, use_bias=False),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    onnx_file, want = _export(net, x, tmp_path, "bnblock")
    pred = NativePredictor(onnx_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_cpp_example_runs_without_python(tmp_path):
    """Compile examples/cpp_predict/main.cc against the runtime and run
    LeNet inference as a plain OS process."""
    from mxnet_tpu._native import load_predict, predict_so_path
    if load_predict() is None:
        pytest.skip("no native toolchain")
    rng = np.random.RandomState(2)
    x = rng.randn(1, 1, 28, 28).astype(np.float32)
    onnx_file, _want = _export(_lenet(), x, tmp_path, "lenet_c")

    exe = str(tmp_path / "cpp_predict")
    src = os.path.join(REPO, "examples", "cpp_predict", "main.cc")
    so = predict_so_path()
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", src, "-o", exe, so,
         "-Wl,-rpath," + os.path.dirname(so)],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    run = subprocess.run([exe, onnx_file, "1", "1", "28", "28"],
                         capture_output=True, text=True, timeout=120)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "output shape: (1, 10)" in run.stdout, run.stdout
