"""C-callable edge predict runtime (reference: ``c_predict_api.cc`` +
``amalgamation/``): a compiled C program must run LeNet inference from
an exported artifact with no Python in the loop."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.onnx import export_model
from mxnet_tpu.predictor import NativePredictor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _lenet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(16, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    return net


def _export(net, x, tmp_path, name):
    want = net(mx.nd.array(x)).asnumpy()
    sym_f, par_f = net.export(str(tmp_path / name))
    onnx_file = str(tmp_path / (name + ".onnx"))
    export_model(sym_f, par_f, in_shapes=[x.shape],
                 onnx_file_path=onnx_file)
    return onnx_file, want


def test_native_predictor_matches_python(tmp_path):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 1, 28, 28).astype(np.float32)
    onnx_file, want = _export(_lenet(), x, tmp_path, "lenet")
    pred = NativePredictor(onnx_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    pred.close()


def test_native_predictor_batchnorm_resnet_block(tmp_path):
    rng = np.random.RandomState(1)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, use_bias=False),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.GlobalAvgPool2D(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    x = rng.randn(2, 3, 16, 16).astype(np.float32)
    onnx_file, want = _export(net, x, tmp_path, "bnblock")
    pred = NativePredictor(onnx_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def _repack_tensor_dims(model_bytes):
    """Re-encode every initializer TensorProto's dims (field 1) as a
    proto3 *packed* repeated int64 -- the encoding the official onnx
    package emits -- leaving everything else byte-identical."""
    from mxnet_tpu.onnx import wire

    def repack_tensor(tbuf):
        out = b""
        dims = []
        pos = 0
        while pos < len(tbuf):
            key, npos = wire._read_uvarint(tbuf, pos)
            num, wt = key >> 3, key & 7
            if wt == 0:
                val, npos = wire._read_uvarint(tbuf, npos)
                if num == 1:
                    dims.append(val)
                    pos = npos
                    continue
            elif wt == 2:
                ln, npos = wire._read_uvarint(tbuf, npos)
                npos += ln
            elif wt == 5:
                npos += 4
            elif wt == 1:
                npos += 8
            out += tbuf[pos:npos]
            pos = npos
        packed = b"".join(wire._uvarint(d) for d in dims)
        return wire.field_bytes(1, packed) + out

    def rewrite(buf, field_num, fn):
        out = b""
        pos = 0
        while pos < len(buf):
            key, npos = wire._read_uvarint(buf, pos)
            num, wt = key >> 3, key & 7
            if wt == 0:
                _, npos = wire._read_uvarint(buf, npos)
            elif wt == 2:
                ln, vpos = wire._read_uvarint(buf, npos)
                if num == field_num:
                    payload = fn(buf[vpos:vpos + ln])
                    out += wire.field_bytes(num, payload)
                    pos = vpos + ln
                    continue
                npos = vpos + ln
            elif wt == 5:
                npos += 4
            elif wt == 1:
                npos += 8
            out += buf[pos:npos]
            pos = npos
        return out

    # ModelProto.graph = field 7; GraphProto.initializer = field 5
    return rewrite(model_bytes, 7,
                   lambda g: rewrite(g, 5, repack_tensor))


def test_native_predictor_packed_dims(tmp_path):
    """proto3 serializers (the official onnx package) emit TensorProto
    dims packed; the native parser must accept that encoding too."""
    rng = np.random.RandomState(3)
    x = rng.randn(2, 1, 28, 28).astype(np.float32)
    onnx_file, want = _export(_lenet(), x, tmp_path, "lenet_packed")
    raw = open(onnx_file, "rb").read()
    repacked = _repack_tensor_dims(raw)
    assert repacked != raw  # the rewrite really changed the encoding
    packed_file = str(tmp_path / "lenet_packed2.onnx")
    open(packed_file, "wb").write(repacked)
    # sanity: the python importer agrees on shapes after the repack
    from mxnet_tpu.onnx import wire
    pred = NativePredictor(packed_file)
    got = pred.forward(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    pred.close()


def test_cpp_example_runs_without_python(tmp_path):
    """Compile examples/cpp_predict/main.cc against the runtime and run
    LeNet inference as a plain OS process."""
    from mxnet_tpu._native import load_predict, predict_so_path
    if load_predict() is None:
        pytest.skip("no native toolchain")
    rng = np.random.RandomState(2)
    x = rng.randn(1, 1, 28, 28).astype(np.float32)
    onnx_file, _want = _export(_lenet(), x, tmp_path, "lenet_c")

    exe = str(tmp_path / "cpp_predict")
    src = os.path.join(REPO, "examples", "cpp_predict", "main.cc")
    so = predict_so_path()
    proc = subprocess.run(
        ["g++", "-O2", "-std=c++17", src, "-o", exe, so,
         "-Wl,-rpath," + os.path.dirname(so)],
        capture_output=True, text=True, timeout=180)
    assert proc.returncode == 0, proc.stderr[-2000:]
    run = subprocess.run([exe, onnx_file, "1", "1", "28", "28"],
                         capture_output=True, text=True, timeout=120)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "output shape: (1, 10)" in run.stdout, run.stdout
