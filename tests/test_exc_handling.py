"""Error-propagation tests (reference:
``tests/python/unittest/test_exc_handling.py``): errors surface with
clear types/messages at the call or sync point, never silently."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


def test_unknown_op_kwarg():
    with pytest.raises(mx.MXNetError, match="unknown argument"):
        mx.nd.relu(mx.nd.ones((2,)), bogus_flag=1)


def test_shape_mismatch_surfaces():
    with pytest.raises(Exception):
        mx.nd.dot(mx.nd.ones((2, 3)), mx.nd.ones((4, 5))).asnumpy()


def test_backward_outside_record():
    x = mx.nd.ones((2,))
    x.attach_grad()
    y = x * 2  # not recorded
    with pytest.raises(mx.MXNetError, match="record"):
        y.backward()


def test_double_backward_without_retain():
    x = mx.nd.ones((3,))
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    with pytest.raises(mx.MXNetError, match="retain"):
        y.backward()


def test_inplace_write_on_tracked_array():
    x = mx.nd.ones((3,))
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with pytest.raises(mx.MXNetError, match="in-place"):
            y += 1


def test_waitall_does_not_swallow():
    """waitall() is a sync point, not an exception sink: work queued
    before it still raises there or earlier, and waitall itself never
    masks failures (reference contract: Engine::WaitForAll rethrows)."""
    ok = mx.nd.ones((4,)) * 2
    mx.nd.waitall()
    np.testing.assert_allclose(ok.asnumpy(), np.full(4, 2.0))
    with pytest.raises(Exception):
        # invalid reshape: surfaces as an exception, not a silent pass
        bad = mx.nd.reshape(mx.nd.ones((4,)), shape=(3, 5))
        mx.nd.waitall()
        bad.asnumpy()


def test_uninitialized_parameter_access():
    from mxnet_tpu import gluon
    net = gluon.nn.Dense(4)
    with pytest.raises(Exception):
        net(mx.nd.ones((2, 3)))  # never initialized


def test_module_errors():
    s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4)
    mod = mx.mod.Module(mx.sym.SoftmaxOutput(s, name="softmax"))
    with pytest.raises(AssertionError):
        mod.forward(mx.io.DataBatch(data=[mx.nd.ones((2, 3))]))
    with pytest.raises(mx.MXNetError):
        mx.mod.Module(mx.sym.SoftmaxOutput(s, name="softmax"),
                      data_names=("wrong_name",))
