"""Multi-device data parallelism over the 8 virtual CPU devices.

These tests exercise the same Mesh/NamedSharding/jit code paths that run
on a real v5e-8 (reference analog: tests/nightly dist kvstore tests run
as local multi-process; SURVEY.md §4)."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import (TrainStep, make_mesh, replicate_block,
                                shard_batch, split_and_load)


def _cpu_devices():
    return jax.devices("cpu")


def _mesh(n=8):
    return make_mesh({"dp": n}, devices=_cpu_devices()[:n])


def _small_net(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    return net


def test_make_mesh_sizes():
    mesh = _mesh(8)
    assert mesh.shape["dp"] == 8
    mesh2 = make_mesh({"dp": -1}, devices=_cpu_devices())
    assert mesh2.shape["dp"] == len(_cpu_devices())
    mesh3 = make_mesh({"dp": 2, "mp": 4}, devices=_cpu_devices())
    assert mesh3.shape == {"dp": 2, "mp": 4}
    with pytest.raises(MXNetError):
        make_mesh({"dp": 3, "mp": -1}, devices=_cpu_devices())


def test_shard_batch_places_shards():
    mesh = _mesh(8)
    x = mx.nd.array(np.arange(64, dtype=np.float32).reshape(16, 4))
    sx = shard_batch(x, mesh)
    assert sx.shape == (16, 4)
    assert len(sx._data.sharding.device_set) == 8
    # each device holds 16/8 = 2 rows
    shard = sx._data.addressable_shards[0]
    assert shard.data.shape == (2, 4)
    np.testing.assert_allclose(sx.asnumpy(), x.asnumpy())
    with pytest.raises(MXNetError):
        shard_batch(mx.nd.ones((10, 4)), mesh)  # 10 % 8 != 0


def test_split_and_load_ctx_list():
    ctxs = [mx.cpu(0), mx.cpu(1)]
    data = np.arange(32, dtype=np.float32).reshape(8, 4)
    parts = split_and_load(data, ctx_list=ctxs)
    assert len(parts) == 2
    assert parts[0].shape == (4, 4)
    np.testing.assert_allclose(
        np.concatenate([p.asnumpy() for p in parts]), data)


def test_replicated_forward_matches_single_device():
    mesh = _mesh(8)
    net = _small_net()
    x = np.random.RandomState(0).randn(16, 8).astype(np.float32)
    ref = net(mx.nd.array(x)).asnumpy()
    replicate_block(net, mesh)
    out = net(shard_batch(mx.nd.array(x), mesh))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)


def test_sharded_backward_matches_single_device():
    """Gradients computed from a dp-sharded batch must equal the
    single-device gradients (XLA inserts the cross-device psum)."""
    rng = np.random.RandomState(1)
    x = rng.randn(16, 8).astype(np.float32)
    y = rng.randn(16, 4).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    def run(mesh):
        net = _small_net(seed=3)
        if mesh is not None:
            net.hybridize()
            replicate_block(net, mesh)
            xs = shard_batch(mx.nd.array(x), mesh)
            ys = shard_batch(mx.nd.array(y), mesh)
        else:
            xs, ys = mx.nd.array(x), mx.nd.array(y)
        with autograd.record():
            l = loss_fn(net(xs), ys)
        l.backward()
        return [p.grad().asnumpy()
                for p in net.collect_params().values()]

    g_single = run(None)
    g_mesh = run(_mesh(8))
    assert len(g_single) == len(g_mesh)
    for a, b in zip(g_single, g_mesh):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-5)


def test_trainstep_trains_and_stays_replicated():
    mesh = _mesh(8)
    net = _small_net(seed=5)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer, mesh=mesh)
    rng = np.random.RandomState(2)
    X = rng.randn(32, 8).astype(np.float32)
    W = rng.randn(8, 4).astype(np.float32)
    Y = X @ W
    losses = []
    for _ in range(30):
        losses.append(float(step(mx.nd.array(X), mx.nd.array(Y)).asscalar()))
    assert losses[-1] < losses[0] / 5, losses
    # params must remain replicated across all 8 devices and identical
    for p in net.collect_params().values():
        arr = p.data()._data
        assert len(arr.sharding.device_set) == 8
        shards = [np.asarray(s.data) for s in arr.addressable_shards]
        for s in shards[1:]:
            np.testing.assert_array_equal(shards[0], s)


def test_trainstep_matches_eager_trainer():
    """One compiled TrainStep must produce the same parameters as the
    eager record/backward/trainer.step path."""
    rng = np.random.RandomState(7)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 4).astype(np.float32)
    loss_fn = gluon.loss.L2Loss()

    def eager():
        net = _small_net(seed=11)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore=None)
        for _ in range(3):
            with autograd.record():
                l = loss_fn(net(mx.nd.array(X)), mx.nd.array(Y))
            l.backward()
            tr.step(16)
        return [p.data().asnumpy()
                for p in net.collect_params().values()]

    def compiled():
        net = _small_net(seed=11)
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore=None)
        step = TrainStep(net, loss_fn, tr, mesh=_mesh(8))
        for _ in range(3):
            step(mx.nd.array(X), mx.nd.array(Y))
        return [p.data().asnumpy()
                for p in net.collect_params().values()]

    pe, pc = eager(), compiled()
    assert len(pe) == len(pc)
    for a, b in zip(pe, pc):
        np.testing.assert_allclose(b, a, rtol=2e-4, atol=1e-5)


def test_trainstep_adam_scheduler_and_states():
    """Adam's bias correction (traced t) and an lr schedule must both take
    effect inside the compiled step, and optimizer state must advance."""
    mesh = _mesh(4)
    net = _small_net(seed=13)
    net.hybridize()
    sched = mx.optimizer.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01, "lr_scheduler": sched},
                            kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer, mesh=mesh)
    rng = np.random.RandomState(3)
    X = rng.randn(16, 8).astype(np.float32)
    Y = rng.randn(16, 4).astype(np.float32)
    l0 = float(step(mx.nd.array(X), mx.nd.array(Y)).asscalar())
    for _ in range(10):
        l = float(step(mx.nd.array(X), mx.nd.array(Y)).asscalar())
    assert l < l0
    assert trainer._optimizer.num_update == 11
    # momentum states must be non-zero after steps
    st = trainer._updater.states[0]
    assert any(np.abs(s.asnumpy()).sum() > 0
               for s in st if s is not None)


def test_trainstep_frozen_params_survive_donation():
    """Frozen (grad_req='null') params must come back out of the donated
    step buffers instead of being left deleted."""
    mesh = _mesh(4)
    net = _small_net(seed=17)
    net.hybridize()
    rng = np.random.RandomState(5)
    X = rng.randn(8, 8).astype(np.float32)
    Y = rng.randn(8, 4).astype(np.float32)
    net(mx.nd.array(X))  # materialize
    frozen = list(net.collect_params().values())[0]
    frozen.grad_req = "null"
    before = frozen.data().asnumpy().copy()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer, mesh=mesh)
    for _ in range(3):
        step(mx.nd.array(X), mx.nd.array(Y))
    after = frozen.data().asnumpy()  # must not raise 'Array has been deleted'
    np.testing.assert_array_equal(before, after)


def test_trainstep_batchnorm_aux_updates():
    """Aux state (BN running stats) must update through the compiled
    step."""
    mesh = _mesh(8)
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8), gluon.nn.BatchNorm(), gluon.nn.Dense(2))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer, mesh=mesh)
    rng = np.random.RandomState(4)
    X = rng.randn(16, 4).astype(np.float32) + 3.0
    Y = rng.randn(16, 2).astype(np.float32)
    step(mx.nd.array(X), mx.nd.array(Y))  # materializes deferred params
    bn_mean = [p for p in net.collect_params().values()
               if "running_mean" in p.name][0]
    after1 = bn_mean.data().asnumpy().copy()
    for _ in range(5):
        step(mx.nd.array(X), mx.nd.array(Y))
    after6 = bn_mean.data().asnumpy()
    # running mean starts at zero and EMA-tracks the (shifted) batch mean
    assert np.abs(after6).max() > np.abs(after1).max() > 0.0


def test_ring_attention_matches_flash():
    """Sequence-parallel ring attention over 8 devices must match the
    single-device reference attention, causal and full."""
    import jax.numpy as jnp
    from mxnet_tpu.ops.transformer import _attention_reference
    from mxnet_tpu.parallel import ring_attention_sharded
    mesh = make_mesh({"sp": 8}, devices=_cpu_devices()[:8])
    rng = np.random.RandomState(9)
    bh, seq, d = 4, 64, 16
    cpu = _cpu_devices()[0]
    q = jax.device_put(jnp.asarray(rng.randn(bh, seq, d).astype(np.float32)), cpu)
    k = jax.device_put(jnp.asarray(rng.randn(bh, seq, d).astype(np.float32)), cpu)
    v = jax.device_put(jnp.asarray(rng.randn(bh, seq, d).astype(np.float32)), cpu)
    for causal in (False, True):
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        ref = _attention_reference(q, k, v, causal, 1.0 / np.sqrt(d))
        np.testing.assert_allclose(out.asnumpy(), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


def test_ring_attention_composes_with_dp():
    """mesh {'dp':2,'sp':4}: batch axis sharded over dp, seq over sp."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import ring_attention
    from mxnet_tpu.ops.transformer import _attention_reference
    mesh = make_mesh({"dp": 2, "sp": 4}, devices=_cpu_devices()[:8])
    rng = np.random.RandomState(11)
    bh, seq, d = 4, 32, 8
    cpu = _cpu_devices()[0]
    qn = jax.device_put(jnp.asarray(rng.randn(bh, seq, d).astype(np.float32)), cpu)
    kn = jax.device_put(jnp.asarray(rng.randn(bh, seq, d).astype(np.float32)), cpu)
    vn = jax.device_put(jnp.asarray(rng.randn(bh, seq, d).astype(np.float32)), cpu)
    sh = NamedSharding(mesh, P("dp", "sp", None))
    q = jax.device_put(qn, sh)
    k = jax.device_put(kn, sh)
    v = jax.device_put(vn, sh)
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh=mesh,
                                                 causal=True))(q, k, v)
    ref = _attention_reference(qn, kn, vn, True, 1.0 / np.sqrt(d))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_run_steps_matches_sequential():
    """The compiled K-step scan (TrainStep.run_steps) must reproduce K
    sequential single-dispatch steps exactly: losses, parameters,
    optimizer state, and BN running stats all thread on device."""
    def mknet():
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(4, 3, padding=1, activation="relu"),
                gluon.nn.BatchNorm(),
                gluon.nn.Flatten(),
                gluon.nn.Dense(10))
        net.initialize(ctx=mx.cpu())
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore=None)
        return net, TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              tr, mesh=None)

    rng = np.random.RandomState(0)
    x = rng.randn(3, 8, 1, 8, 8).astype(np.float32)
    y = rng.randint(0, 10, (3, 8)).astype(np.float32)

    net_a, step_a = mknet()
    net_b, step_b = mknet()
    net_a(mx.nd.array(x[0]))
    net_b(mx.nd.array(x[0]))
    from conftest import paired_params
    for pa, pb in paired_params(net_a, net_b):
        pb.set_data(mx.nd.array(pa.data().asnumpy()))

    ref = [float(step_a(mx.nd.array(x[i]), mx.nd.array(y[i])).asscalar())
           for i in range(3)]
    losses = step_b.run_steps(mx.nd.array(x), mx.nd.array(y)).asnumpy()
    np.testing.assert_allclose(losses, ref, rtol=1e-4, atol=1e-5)
    for pa, pb in paired_params(net_a, net_b):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=2e-4,
                                   atol=1e-5)
    ca = step_b.cost_analysis()
    assert ca is None or ca.get("flops", 0) > 0


def test_run_steps_sharded_mesh():
    """run_steps over a dp mesh: batches shard, params stay replicated."""
    mesh = _mesh(4)
    net = _small_net(3)
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                     mesh=mesh)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(2, 8, 6).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 4, (2, 8)).astype(np.float32))
    losses = step.run_steps(x, y).asnumpy()
    assert losses.shape == (2,) and np.isfinite(losses).all()
    for p in net.collect_params().values():
        arr = p.data()._data
        assert len(arr.sharding.device_set) == 4
