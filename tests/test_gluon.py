"""Gluon behavior (reference: ``tests/python/unittest/test_gluon.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_parameter_basic():
    p = gluon.Parameter("w", shape=(3, 4))
    p.initialize(init="ones")
    assert p.data().shape == (3, 4)
    assert (p.data().asnumpy() == 1).all()
    assert p.grad() is not None
    p.set_data(mx.nd.zeros((3, 4)))
    assert (p.data().asnumpy() == 0).all()


def test_parameter_deferred():
    p = gluon.Parameter("w", shape=(5, 0), allow_deferred_init=True)
    p.initialize()
    with pytest.raises(Exception):
        p.data()
    p.shape = (5, 7)
    p._finish_deferred_init()
    assert p.data().shape == (5, 7)


def test_dense_forward_shapes():
    layer = nn.Dense(8, in_units=4)
    layer.initialize()
    out = layer(mx.nd.ones((2, 4)))
    assert out.shape == (2, 8)
    # deferred in_units
    layer2 = nn.Dense(8)
    layer2.initialize()
    assert layer2(mx.nd.ones((2, 6))).shape == (2, 8)
    assert layer2.weight.shape == (8, 6)


def test_dense_no_flatten():
    layer = nn.Dense(8, flatten=False)
    layer.initialize()
    out = layer(mx.nd.ones((2, 3, 6)))
    assert out.shape == (2, 3, 8)


def test_sequential_and_children():
    net = nn.HybridSequential()
    net.add(nn.Dense(4), nn.Dense(2))
    net.initialize()
    assert len(net) == 2
    assert net(mx.nd.ones((1, 3))).shape == (1, 2)
    params = net.collect_params()
    assert len(params) == 4  # 2 weights + 2 biases


def test_hybridize_matches_imperative():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    x = mx.nd.random.normal(shape=(8, 10))
    y_imp = net(x).asnumpy()
    net.hybridize()
    y_hyb = net(x).asnumpy()
    # atol covers TPU MXU bf16-accumulation differences between the eager
    # per-op and fused jit paths (reference relaxes similarly for gpu)
    np.testing.assert_allclose(y_hyb, y_imp, rtol=1e-2, atol=5e-4)


def test_hybridize_shape_respecialization():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    assert net(mx.nd.ones((2, 3))).shape == (2, 4)
    assert net(mx.nd.ones((5, 3))).shape == (5, 4)  # second specialization
    assert len(net._cached_entries) == 2


def test_hybrid_training_gradients():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.nd.random.normal(shape=(8, 10))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    for p in net.collect_params().values():
        g = p.data()._grad
        assert g is not None
    # compare hybrid grads vs imperative grads
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net2.initialize()
    # copy params
    from conftest import paired_params
    for p1, p2 in paired_params(net, net2):
        p2.set_data(p1.data())
    with autograd.record():
        loss2 = net2(x).sum()
    loss2.backward()
    for p1, p2 in paired_params(net, net2):
        np.testing.assert_allclose(p2.data()._grad.asnumpy(),
                                   p1.data()._grad.asnumpy(),
                                   rtol=5e-3, atol=1e-5)


def test_batchnorm_layer_stats():
    net = nn.BatchNorm(in_channels=4)
    net.initialize()
    x = mx.nd.random.normal(shape=(16, 4), scale=2.0)
    with autograd.record():
        net(x)
    rm = net.running_mean.data().asnumpy()
    assert not np.allclose(rm, 0)  # updated toward batch mean


def test_trainer_step_decreases_loss():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(2))
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    x = mx.nd.random.normal(shape=(32, 8))
    y = mx.nd.array(np.random.randint(0, 2, 32))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(60):
        with autograd.record():
            l = loss_fn(net(x), y).mean()
        l.backward()
        trainer.step(1)
        losses.append(l.asscalar())
    assert losses[-1] < losses[0] * 0.5


def test_trainer_states_roundtrip(tmp_path):
    net = nn.Dense(4, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.nd.ones((2, 3))
    with autograd.record():
        l = net(x).sum()
    l.backward()
    trainer.step(1)
    f = str(tmp_path / "t.states")
    trainer.save_states(f)
    trainer.load_states(f)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
    net2.load_parameters(f)
    x = mx.nd.random.normal(shape=(2, 4))
    assert_almost_equal(net(x), net2(x), rtol=1e-5, atol=1e-6)


def test_save_load_deferred(tmp_path):
    net = nn.Dense(4)
    net.initialize()
    net(mx.nd.ones((1, 7)))
    f = str(tmp_path / "d.params")
    net.save_parameters(f)
    net2 = nn.Dense(4)
    net2.load_parameters(f)
    assert net2.weight.shape == (4, 7)
    assert net2(mx.nd.ones((2, 7))).shape == (2, 4)


def test_losses():
    pred = mx.nd.array([[1., 2., 3.], [3., 2., 1.]])
    label = mx.nd.array([2., 0.])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    e = np.exp([[1, 2, 3], [3, 2, 1]])
    p = e / e.sum(-1, keepdims=True)
    expect = -np.log([p[0, 2], p[1, 0]])
    assert_almost_equal(l, expect, rtol=1e-4)

    l2 = gluon.loss.L2Loss()(mx.nd.array([1., 2.]), mx.nd.array([0., 0.]))
    assert_almost_equal(l2, [0.5, 2.0], rtol=1e-5)

    l1 = gluon.loss.L1Loss()(mx.nd.array([1., -2.]), mx.nd.array([0., 0.]))
    assert_almost_equal(l1, [1.0, 2.0], rtol=1e-5)

    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()(
        mx.nd.array([0.0]), mx.nd.array([1.0]))
    assert_almost_equal(bce, [np.log(2)], rtol=1e-4)


def test_huber_hinge():
    h = gluon.loss.HuberLoss()(mx.nd.array([2.0]), mx.nd.array([0.0]))
    assert_almost_equal(h, [1.5], rtol=1e-5)
    hg = gluon.loss.HingeLoss()(mx.nd.array([0.5]), mx.nd.array([1.0]))
    assert_almost_equal(hg, [0.5], rtol=1e-5)


def test_conv_block():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(16, 3, padding=1),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.GlobalAvgPool2D(),
            nn.Flatten(),
            nn.Dense(10))
    net.initialize()
    x = mx.nd.random.normal(shape=(2, 3, 16, 16))
    assert net(x).shape == (2, 10)
    net.hybridize()
    assert net(x).shape == (2, 10)


def test_embedding_layer():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    out = emb(mx.nd.array([1, 2, 3], dtype="int32"))
    assert out.shape == (3, 4)


def test_block_repr_and_summary():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3))
    net.initialize()
    assert "Dense" in repr(net)
    s = net.summary(mx.nd.ones((1, 3)))
    assert "Total params" in s


def test_dropout_behavior():
    d = nn.Dropout(0.5)
    d.initialize()
    x = mx.nd.ones((100, 100))
    out_eval = d(x)
    assert (out_eval.asnumpy() == 1).all()
    with autograd.record():
        out_train = d(x)
    zeros = (out_train.asnumpy() == 0).mean()
    assert 0.3 < zeros < 0.7


def test_lstm_layer():
    lstm = gluon.rnn.LSTM(16, num_layers=2)
    lstm.initialize()
    x = mx.nd.random.normal(shape=(5, 3, 8))  # TNC
    out = lstm(x)
    assert out.shape == (5, 3, 16)
    states = lstm.begin_state(batch_size=3)
    out, new_states = lstm(x, states)
    assert out.shape == (5, 3, 16)
    assert new_states[0].shape == (2, 3, 16)
    assert new_states[1].shape == (2, 3, 16)


def test_gru_bidirectional():
    gru = gluon.rnn.GRU(8, num_layers=1, bidirectional=True)
    gru.initialize()
    x = mx.nd.random.normal(shape=(4, 2, 5))
    out = gru(x)
    assert out.shape == (4, 2, 16)


def test_lstm_cell_unroll():
    cell = gluon.rnn.LSTMCell(8)
    cell.initialize()
    x = mx.nd.random.normal(shape=(2, 5, 4))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC")
    assert outputs.shape == (2, 5, 8)
    assert states[0].shape == (2, 8)


def test_lstm_trains():
    lstm = gluon.rnn.LSTM(8)
    lstm.initialize()
    x = mx.nd.random.normal(shape=(4, 2, 5))
    with autograd.record():
        loss = lstm(x).sum()
    loss.backward()
    p = lstm.collect_params()
    some_grad = [pp.data()._grad for pp in p.values()][0]
    assert float(abs(some_grad.asnumpy()).sum()) > 0


def test_prelu_swish():
    p = nn.PReLU()
    p.initialize()
    x = mx.nd.array([[-1.0, 2.0]])
    assert p(x).shape == (1, 2)
    s = nn.Swish()
    out = s(mx.nd.array([0.0]))
    assert abs(out.asscalar()) < 1e-6
