"""Generative serving tier tests (ISSUE 18): paged KV cache block
lifecycle, decode-step paged attention numerics (Pallas interpret vs
XLA reference), prefill+decode vs the full-forward oracle, continuous
batching (join mid-batch bit-identical, occupancy > 1), admission
backpressure, token streaming with per-token trace spans, mid-decode
hot swap under chaos, and the GenerativeWatcher."""
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, obs, serving, telemetry
from mxnet_tpu.serving import (RequestTimeout, ServableClosed,
                               ServingQueueFull)
from mxnet_tpu.serving.decode import (DecodeEngine, GenerativeWatcher,
                                      KVCacheExhausted, PagedKVCache,
                                      tiny_gpt)
from mxnet_tpu.serving.decode.kvcache import SCRATCH_BLOCK

MODEL = tiny_gpt(vocab_size=32, units=16, num_layers=2, num_heads=2,
                 max_seq=32)
ENGINE_KW = dict(prefill_buckets=(8, 16), decode_buckets=(1, 2, 4),
                 block_size=4, num_blocks=64, max_queue=16)


@pytest.fixture(scope="module")
def params():
    return MODEL.init_params(0)


@pytest.fixture(scope="module")
def ccache(tmp_path_factory):
    # shared on-disk compile cache: the first engine pays the AOT
    # compiles, every later engine warms from disk
    return serving.CompileCache(str(tmp_path_factory.mktemp("cc")))


@pytest.fixture()
def make_engine(params, ccache):
    engines = []

    def _make(**overrides):
        kw = dict(ENGINE_KW, cache=ccache, **overrides)
        eng = DecodeEngine(MODEL, params, **kw)
        eng.warmup()
        eng.start()
        engines.append(eng)
        return eng

    yield _make
    for eng in engines:
        eng.close(drain=False)


@pytest.fixture()
def registry(ccache, tmp_path):
    reg = serving.ModelRegistry(cache_dir=str(tmp_path / "reg_cc"))
    reg._cache = ccache
    yield reg
    reg.shutdown(drain=True)


@pytest.fixture()
def counters():
    telemetry.enable()
    for prefix in ("decode.", "kvcache.", "serving.", "chaos."):
        telemetry.reset(prefix)
    yield telemetry
    for prefix in ("decode.", "kvcache.", "serving.", "chaos."):
        telemetry.reset(prefix)
    telemetry.disable()


def _reference(params, prompt, max_new, eos_id=None):
    return MODEL.reference_decode(params, prompt, max_new, eos_id=eos_id)


# ---------------------------------------------------------------------
# paged KV cache
# ---------------------------------------------------------------------

def test_kvcache_alloc_free_cycle():
    c = PagedKVCache(2, 2, 8, block_size=4, num_blocks=16)
    assert c.total_blocks == 15          # block 0 reserved as scratch
    t = c.allocate(10)                   # ceil(10/4) = 3 blocks
    assert len(t.blocks) == 3
    assert SCRATCH_BLOCK not in t.blocks
    assert c.blocks_in_use() == 3
    assert c.free_blocks() == 12
    c.free(t)
    assert c.blocks_in_use() == 0
    c.free(t)                            # idempotent
    assert c.blocks_in_use() == 0


def test_kvcache_exhaustion_and_can_admit():
    c = PagedKVCache(1, 1, 4, block_size=4, num_blocks=5)  # 4 usable
    t = c.allocate(12)                   # 3 of 4
    assert c.can_admit(4) and not c.can_admit(5)
    with pytest.raises(KVCacheExhausted):
        c.allocate(8)
    assert c.blocks_in_use() == 3        # failed alloc left no debris
    c.free(t)
    c.allocate(16)                       # the whole cache fits again


def test_kvcache_fragmentation_and_padded_table():
    c = PagedKVCache(1, 1, 4, block_size=4, num_blocks=16)
    t = c.allocate(6)                    # 2 blocks for 6 tokens
    c.note_tokens(t, 5)                  # 5 live of 8 allocated slots
    assert c.stats()["fragmentation"] == pytest.approx(3 / 8)
    padded = c.padded_table(t, 6)
    assert padded.shape == (6,) and padded.dtype == np.int32
    assert list(padded[:2]) == list(t.blocks)
    assert all(b == SCRATCH_BLOCK for b in padded[2:])
    c.free(t)


# ---------------------------------------------------------------------
# paged attention kernel
# ---------------------------------------------------------------------

def test_paged_attention_pallas_matches_reference():
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.paged_attention import (
        paged_attention_pallas, paged_attention_reference)
    rng = np.random.default_rng(0)
    nb, bs, h, d = 8, 4, 2, 8
    k = jnp.asarray(rng.normal(size=(nb, bs, h, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(nb, bs, h, d)).astype(np.float32))
    q = jnp.asarray(rng.normal(size=(3, h, d)).astype(np.float32))
    bt = jnp.asarray(np.array([[1, 2, 3, 0], [4, 5, 0, 0],
                               [6, 7, 1, 2]], np.int32))
    ctx = jnp.asarray(np.array([[10], [5], [16]], np.int32))
    ref = paged_attention_reference(q, k, v, bt, ctx, scale=0.35)
    pal = paged_attention_pallas(q, k, v, bt, ctx, scale=0.35,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(pal), np.asarray(ref),
                               atol=1e-5)


def test_paged_attention_reference_masks_dead_context():
    # tokens past context_lens must not contribute: poison them
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.paged_attention import (
        paged_attention_reference)
    rng = np.random.default_rng(1)
    k = rng.normal(size=(4, 4, 1, 4)).astype(np.float32)
    v = rng.normal(size=(4, 4, 1, 4)).astype(np.float32)
    q = jnp.asarray(rng.normal(size=(1, 1, 4)).astype(np.float32))
    bt = jnp.asarray(np.array([[1, 2]], np.int32))
    ctx = jnp.asarray(np.array([[5]], np.int32))
    base = paged_attention_reference(q, jnp.asarray(k), jnp.asarray(v),
                                     bt, ctx)
    k[2, 1:], v[2, 1:] = 1e6, 1e6        # positions 5..7: dead
    poisoned = paged_attention_reference(q, jnp.asarray(k),
                                         jnp.asarray(v), bt, ctx)
    np.testing.assert_allclose(np.asarray(poisoned), np.asarray(base),
                               atol=1e-6)


def test_paged_attention_is_registered():
    from mxnet_tpu import kernels
    assert "paged_attention" in kernels.list_kernels()
    ch = kernels.choose("paged_attention", heads=2, head_dim=8,
                        block_size=4)
    assert isinstance(ch.use_pallas, bool)


# ---------------------------------------------------------------------
# engine: numerics + streaming
# ---------------------------------------------------------------------

def test_engine_matches_full_forward_oracle(make_engine, params):
    eng = make_engine()
    for prompt in ([3, 7, 1, 9, 2], [5, 5, 6], [1]):
        stream = eng.submit(prompt, 8)
        assert stream.tokens() == _reference(params, prompt, 8)
    assert eng.cache.blocks_in_use() == 0


def test_engine_streams_incrementally(make_engine):
    eng = make_engine()
    stream = eng.submit([3, 7, 1], 6)
    seen = []
    for tok in stream:
        seen.append(tok)
        assert stream.ttft_s is not None and stream.ttft_s >= 0
    assert len(seen) == 6
    assert stream.finish_reason == "length"


def test_engine_eos_stops_and_frees(make_engine, params):
    eng = make_engine()
    ref = _reference(params, [5, 5, 6], 10)
    eos = ref[2]                         # an id the model will emit
    stream = eng.submit([5, 5, 6], 10, eos_id=eos)
    toks = stream.tokens()
    assert toks == _reference(params, [5, 5, 6], 10, eos_id=eos)
    assert toks[-1] == eos and len(toks) <= 10
    assert stream.finish_reason == "eos"
    assert eng.cache.blocks_in_use() == 0


def test_engine_rejects_over_budget_prompts(make_engine):
    eng = make_engine()
    with pytest.raises(mx.MXNetError):
        eng.submit(list(range(17)), 4)   # > largest prefill bucket
    with pytest.raises(mx.MXNetError):
        eng.submit([1, 2, 3], 30)        # 33 > max_seq 32
    with pytest.raises(mx.MXNetError):
        eng.submit([], 4)


# ---------------------------------------------------------------------
# continuous batching
# ---------------------------------------------------------------------

def test_join_mid_batch_is_bit_identical(make_engine, params, counters):
    eng = make_engine()
    prompts = [[3, 7, 1, 9, 2], [5, 5, 6], [1, 2, 3, 4], [9, 8, 7]]
    solo = [_reference(params, p, 10) for p in prompts]
    results = {}

    def run(i, delay):
        time.sleep(delay)
        results[i] = eng.submit(prompts[i], 10).tokens()

    # throttled steps pin the stagger inside the running batch (a fast
    # machine must not finish stream 0 before stream 1 arrives)
    with chaos.scenario(seed=0):
        chaos.on("serving.decode.step",
                 action=lambda ctx: time.sleep(0.02))
        threads = [threading.Thread(target=run, args=(i, 0.01 * i))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for i in range(len(prompts)):
        assert results[i] == solo[i], "slot %d diverged" % i
    # occupancy > 1 at some step <=> more tokens than iterations
    assert counters.counter("decode.tokens").value \
        > counters.counter("decode.steps").value
    assert eng.cache.blocks_in_use() == 0


def test_finished_sequences_vacate_immediately(make_engine, params):
    eng = make_engine()
    short = eng.submit([5, 5, 6], 2)
    long = eng.submit([3, 7, 1, 9, 2], 12)
    assert short.tokens() == _reference(params, [5, 5, 6], 2)
    # the long request keeps generating after the short one vacated
    assert long.tokens() == _reference(params, [3, 7, 1, 9, 2], 12)
    assert eng.cache.blocks_in_use() == 0


# ---------------------------------------------------------------------
# admission backpressure + lifecycle
# ---------------------------------------------------------------------

def test_admission_sheds_on_kv_exhaustion_never_midflight(
        make_engine, params, counters):
    # 9 usable blocks of 4 = 36 token-slots; one request budgets
    # 5 + 12 = 17 -> 5 blocks, so a second identical one must shed
    eng = make_engine(num_blocks=10)
    with chaos.scenario(seed=0):
        chaos.on("serving.decode.step",
                 action=lambda ctx: time.sleep(0.02))
        first = eng.submit([3, 7, 1, 9, 2], 12)
        time.sleep(0.05)                 # first is mid-generation now
        with pytest.raises(ServingQueueFull):
            eng.submit([3, 7, 1, 9, 2], 12)
        # the in-flight sequence is untouched by the shed
        assert first.tokens() == _reference(params, [3, 7, 1, 9, 2], 12)
    assert counters.counter("decode.shed").value == 1
    assert counters.counter("decode.shed.kvcache").value == 1
    assert counters.counter("kvcache.alloc_failures").value == 1
    assert eng.cache.blocks_in_use() == 0
    eng.submit([1], 2).tokens()          # sheds recover


def test_admission_sheds_on_queue_full(make_engine, counters):
    eng = make_engine(max_queue=1)
    with chaos.scenario(seed=0):
        chaos.on("serving.decode.step",
                 action=lambda ctx: time.sleep(0.05))
        streams, shed = [], 0
        for _ in range(12):              # 4 slots + 1 pending max
            try:
                streams.append(eng.submit([1], 8))
            except ServingQueueFull:
                shed += 1
        assert shed >= 1
        for s in streams:
            assert len(s.tokens()) == 8  # accepted work still completes
    assert counters.counter("decode.shed.queue").value >= 1


def test_cancel_frees_blocks(make_engine, counters):
    eng = make_engine()
    with chaos.scenario(seed=0):
        chaos.on("serving.decode.step",
                 action=lambda ctx: time.sleep(0.02))
        stream = eng.submit([3, 7, 1], 20)
        first = next(stream)
        stream.cancel()
        tail = list(stream)
    assert stream.finish_reason == "cancel"
    assert 1 + len(tail) < 20
    assert isinstance(first, int)
    assert eng.cache.blocks_in_use() == 0


def test_timeout_while_pending_frees_blocks(make_engine, counters):
    eng = make_engine(decode_buckets=(1,), max_queue=8)
    with chaos.scenario(seed=0):
        chaos.on("serving.decode.step",
                 action=lambda ctx: time.sleep(0.03))
        blocker = eng.submit([1], 10)    # owns the single slot
        time.sleep(0.02)
        late = eng.submit([2], 4, timeout=0.01)
        with pytest.raises(RequestTimeout):
            late.tokens()
        assert blocker.tokens()          # the running one is unharmed
    assert late.finish_reason == "timeout"
    assert counters.counter("serving.timeouts").value == 1
    assert eng.cache.blocks_in_use() == 0


def test_close_without_drain_resolves_streams(make_engine):
    eng = make_engine()
    with chaos.scenario(seed=0):
        chaos.on("serving.decode.step",
                 action=lambda ctx: time.sleep(0.02))
        stream = eng.submit([3, 7, 1], 20)
        next(stream)
        eng.close(drain=False)
        with pytest.raises(ServableClosed):
            list(stream)
    assert stream.finish_reason == "closed"
    assert eng.cache.blocks_in_use() == 0


# ---------------------------------------------------------------------
# tracing
# ---------------------------------------------------------------------

def test_decode_step_spans_under_request_root(make_engine):
    obs.enable_tracing()
    try:
        eng = make_engine()
        eng.submit([3, 7, 1], 5).tokens()
        spans = obs.spans()
    finally:
        obs.disable_tracing()
    roots = [s for s in spans if s["name"] == "serving.request"
             and s["attrs"].get("generative")]
    steps = [s for s in spans if s["name"] == "serving.decode_step"]
    assert len(roots) == 1
    assert roots[0]["attrs"]["tokens"] == 5
    assert len(steps) == 5
    assert {s["parent"] for s in steps} == {roots[0]["span"]}
    assert {s["trace"] for s in steps} == {roots[0]["trace"]}
    assert [s["attrs"]["token_index"] for s in steps] == list(range(5))


# ---------------------------------------------------------------------
# registry surface + hot swap
# ---------------------------------------------------------------------

def test_registry_generate_and_statusz_surface(registry, params):
    sv = registry.register_generative("gpt", MODEL, params=params,
                                      **ENGINE_KW)
    assert "gpt" in registry
    assert sv.queue_depth() == 0 and sv.queue_capacity == 16
    assert sv.kvcache_stats()["blocks_in_use"] == 0
    toks = registry.generate("gpt", [3, 7, 1], 5).tokens()
    assert toks == _reference(params, [3, 7, 1], 5)


def test_registry_generate_rejects_non_generative(registry):
    from mxnet_tpu import gluon
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    net.initialize(force_reinit=True)
    net.hybridize()
    net(mx.nd.array(np.zeros((1, 8), np.float32)))
    registry.register("mlp", block=net, input_shape=(8,),
                      buckets=(1, 2))
    with pytest.raises(mx.MXNetError, match="not generative"):
        registry.generate("mlp", [1, 2], 4)
    with pytest.raises(mx.MXNetError):
        registry.register_generative("both", MODEL)      # no source
    with pytest.raises(mx.MXNetError):
        registry.register_generative("both", MODEL, params={},
                                     checkpoint="/nope")  # two sources


def test_mid_decode_swap_drains_old_zero_dropped(registry, params,
                                                counters):
    p1 = MODEL.init_params(1)
    registry.register_generative("gpt", MODEL, params=params,
                                 **ENGINE_KW)
    old = registry._servables["gpt"]
    with chaos.scenario(seed=0):
        # gate every decode step until the REPLACEMENT servable has
        # installed: the swap then provably lands mid-generation
        # (install precedes old.close(drain=True) in the registry), and
        # the drain -- which only starts after install -- releases the
        # gate.  The first token comes from prefill, so next(stream)
        # never blocks on this.
        def _hold_until_swapped(ctx, deadline=None):
            deadline = deadline or time.monotonic() + 10.0
            while (registry._servables.get("gpt") is old
                   and time.monotonic() < deadline):
                time.sleep(0.002)
        chaos.on("serving.decode.step", action=_hold_until_swapped)
        stream = registry.generate("gpt", [3, 7, 1, 9, 2], 20)
        first = next(stream)             # mid-generation from here on
        registry.register_generative("gpt", MODEL, params=p1,
                                     **ENGINE_KW)
        drained = [first] + list(stream)
        # the half-generated sequence finished on the OLD weights
        assert drained == _reference(params, [3, 7, 1, 9, 2], 20)
        assert stream.finish_reason == "length"
        assert chaos.stats()["survived"].get("serving.decode_swap") == 1
        # new requests land on the new weights
        assert registry.generate("gpt", [3, 7, 1], 5).tokens() \
            == _reference(p1, [3, 7, 1], 5)
    assert counters.counter(
        "chaos.survived.serving.decode_swap").value == 1


def test_swap_abort_leaves_old_serving(registry, params):
    registry.register_generative("gpt", MODEL, params=params,
                                 **ENGINE_KW)
    with chaos.scenario(seed=0):
        chaos.on("serving.swap", action=chaos.RAISE, times=1)
        with pytest.raises(chaos.ChaosInjected):
            registry.register_generative("gpt", MODEL,
                                         params=MODEL.init_params(1),
                                         **ENGINE_KW)
    toks = registry.generate("gpt", [3, 7, 1], 5).tokens()
    assert toks == _reference(params, [3, 7, 1], 5)


def test_generative_watcher_swaps_on_new_step(registry, params,
                                              tmp_path):
    from mxnet_tpu.checkpoint import CheckpointManager
    p1 = MODEL.init_params(1)
    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, {"params": params})
    w = GenerativeWatcher(registry, "gpt", mgr, MODEL, **ENGINE_KW)
    assert w.poll_once() == 1
    assert registry.generate("gpt", [3, 7, 1], 5).tokens() \
        == _reference(params, [3, 7, 1], 5)
    assert w.poll_once() is None         # nothing new
    mgr.save(2, {"params": p1})
    assert w.poll_once() == 2
    assert registry.generate("gpt", [3, 7, 1], 5).tokens() \
        == _reference(p1, [3, 7, 1], 5)
    w.close()
