"""ctx_group model-parallel compat shim (reference:
``AttrScope(ctx_group=...)`` + ``bind(group2ctx=...)``,
``example/model-parallel-lstm``): per-node device placement with
explicit transfers at group boundaries; SPMD TP/PP is the native
training path."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.base import MXNetError


def _two_stage():
    with mx.AttrScope(ctx_group="stage1"):
        data = sym.var("data")
        h = sym.FullyConnected(data, num_hidden=16, name="fc1")
        h = sym.Activation(h, act_type="relu")
    with mx.AttrScope(ctx_group="stage2"):
        out = sym.FullyConnected(h, num_hidden=4, name="fc2")
    return out


def _args():
    return {"data": mx.nd.zeros((2, 8)),
            "fc1_weight": mx.nd.ones((16, 8)) * 0.1,
            "fc1_bias": mx.nd.zeros((16,)),
            "fc2_weight": mx.nd.ones((4, 16)) * 0.1,
            "fc2_bias": mx.nd.zeros((4,))}


def test_group2ctx_places_and_computes():
    out = _two_stage()
    g2c = {"stage1": mx.Context("cpu", 1), "stage2": mx.Context("cpu", 3)}
    exe = out.bind(ctx=mx.cpu(0), args=_args(), grad_req="null",
                   group2ctx=g2c)
    outs = exe.forward(data=mx.nd.ones((2, 8)))
    x = np.ones((2, 8), np.float32)
    h = np.maximum(x @ (np.ones((8, 16), np.float32) * 0.1), 0)
    want = h @ (np.ones((16, 4), np.float32) * 0.1)
    np.testing.assert_allclose(outs[0].asnumpy(), want, rtol=1e-5)
    # the final node ran on stage2's device
    assert jax.devices("cpu")[3] in outs[0]._data.devices()


def test_group2ctx_matches_ungrouped():
    out = _two_stage()
    rng = np.random.RandomState(0)
    args = {k: mx.nd.array(rng.randn(*v.shape).astype(np.float32))
            for k, v in _args().items()}
    plain = out.bind(ctx=mx.cpu(), args=dict(args), grad_req="null")
    want = plain.forward()[0].asnumpy()
    g2c = {"stage1": mx.Context("cpu", 2), "stage2": mx.Context("cpu", 5)}
    exe = out.bind(ctx=mx.cpu(0), args=dict(args), grad_req="null",
                   group2ctx=g2c)
    got = exe.forward()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_group2ctx_training_redirects_to_spmd():
    out = _two_stage()
    g2c = {"stage1": mx.Context("cpu", 1), "stage2": mx.Context("cpu", 3)}
    exe = out.bind(ctx=mx.cpu(0), args=_args(), grad_req="null",
                   group2ctx=g2c)
    with pytest.raises(MXNetError, match="parallel"):
        exe.forward(is_train=True)


def test_unknown_group_falls_back_to_default_ctx():
    with mx.AttrScope(ctx_group="nowhere"):
        data = sym.var("data")
        out = sym.FullyConnected(data, num_hidden=4, name="fc")
    exe = out.bind(ctx=mx.cpu(0),
                   args={"data": mx.nd.ones((2, 8)),
                         "fc_weight": mx.nd.ones((4, 8)),
                         "fc_bias": mx.nd.zeros((4,))},
                   grad_req="null", group2ctx={"stage1": mx.cpu(1)})
    outs = exe.forward()
    np.testing.assert_allclose(outs[0].asnumpy(), np.full((2, 4), 8.0))
