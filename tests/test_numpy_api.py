"""mx.np / mx.npx tests (reference: ``tests/python/unittest/
test_numpy_ndarray.py`` / ``test_numpy_op.py``)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd

np = mx.np
npx = mx.npx


def test_creation_and_props():
    a = np.array([[1.0, 2], [3, 4]])
    assert isinstance(a, np.ndarray)
    assert a.shape == (2, 2) and a.size == 4
    assert a.dtype == onp.float32
    onp.testing.assert_allclose(a.T.asnumpy(), [[1, 3], [2, 4]])
    assert np.zeros((2, 3)).asnumpy().sum() == 0
    assert np.ones(4).asnumpy().sum() == 4
    onp.testing.assert_allclose(np.eye(3).asnumpy(), onp.eye(3))
    onp.testing.assert_allclose(np.arange(2, 8, 2).asnumpy(), [2, 4, 6])
    onp.testing.assert_allclose(np.linspace(0, 1, 5).asnumpy(),
                                onp.linspace(0, 1, 5), rtol=1e-6)
    onp.testing.assert_allclose(np.full((2,), 7.0).asnumpy(), [7, 7])


def test_math_matches_numpy():
    x = onp.random.RandomState(0).rand(3, 4).astype(onp.float32) + 0.5
    a = np.array(x)
    onp.testing.assert_allclose(np.exp(a).asnumpy(), onp.exp(x),
                                rtol=1e-5)
    onp.testing.assert_allclose(np.sum(a, axis=1).asnumpy(), x.sum(1),
                                rtol=1e-5)
    onp.testing.assert_allclose(np.mean(a).asnumpy(), x.mean(),
                                rtol=1e-5)
    onp.testing.assert_allclose(np.var(a, ddof=1).asnumpy(),
                                x.var(ddof=1), rtol=1e-4)
    onp.testing.assert_allclose(np.std(a).asnumpy(), x.std(), rtol=1e-4)
    onp.testing.assert_allclose((a @ a.T).asnumpy(), x @ x.T, rtol=1e-5)
    onp.testing.assert_allclose(np.matmul(a, a.T).asnumpy(), x @ x.T,
                                rtol=1e-5)
    onp.testing.assert_allclose(
        np.tensordot(a, a, axes=([1], [1])).asnumpy(),
        onp.tensordot(x, x, axes=([1], [1])), rtol=1e-5)
    onp.testing.assert_allclose(
        np.einsum("ij,kj->ik", a, a).asnumpy(),
        onp.einsum("ij,kj->ik", x, x), rtol=1e-5)
    onp.testing.assert_allclose(np.power(a, 2).asnumpy(), x ** 2,
                                rtol=1e-5)
    onp.testing.assert_allclose(np.maximum(a, 1.0).asnumpy(),
                                onp.maximum(x, 1.0))


def test_shaping():
    a = np.arange(12).reshape(3, 4)
    assert a.shape == (3, 4)
    assert np.transpose(a).shape == (4, 3)
    assert np.expand_dims(a, 0).shape == (1, 3, 4)
    assert np.squeeze(np.expand_dims(a, 0)).shape == (3, 4)
    c = np.concatenate([a, a], axis=0)
    assert c.shape == (6, 4)
    s = np.stack([a, a])
    assert s.shape == (2, 3, 4)
    parts = np.split(a, 2, axis=1)
    assert len(parts) == 2 and parts[0].shape == (3, 2)
    assert np.vstack([a, a]).shape == (6, 4)
    assert np.hstack([a, a]).shape == (3, 8)


def test_autograd_through_np():
    """mx.np arrays ride the same tape as mx.nd."""
    a = np.array([[1.0, 2], [3, 4]])
    a.attach_grad()
    with autograd.record():
        loss = np.sum(np.square(a) * 3.0)
    loss.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), 6 * a.asnumpy())


def test_np_nd_interop():
    a = np.ones((2, 3))
    b = mx.nd.ones((2, 3))
    c = a + b          # mixes freely
    assert c.asnumpy().sum() == 12


def test_random():
    np.random.seed(0)
    u = np.random.uniform(size=(100,))
    assert 0 <= float(np.min(u).asnumpy()) and \
        float(np.max(u).asnumpy()) <= 1
    n = np.random.randn(50, 50)
    assert abs(float(np.mean(n).asnumpy())) < 0.1
    r = np.random.randint(0, 5, size=(20,))
    assert set(onp.unique(r.asnumpy())) <= {0, 1, 2, 3, 4}


def test_npx_ops():
    x = np.array([[1.0, -1.0], [0.5, -0.5]])
    onp.testing.assert_allclose(npx.relu(x).asnumpy(),
                                [[1, 0], [0.5, 0]])
    s = npx.softmax(x)
    onp.testing.assert_allclose(s.asnumpy().sum(axis=1), [1, 1],
                                rtol=1e-6)
    w = np.ones((4, 2))
    out = npx.fully_connected(x, w, num_hidden=4, no_bias=True)
    assert out.shape == (2, 4)
    oh = npx.one_hot(np.array([0.0, 1.0]), 3)
    onp.testing.assert_allclose(oh.asnumpy(),
                                [[1, 0, 0], [0, 1, 0]])


def test_npx_set_np_flag():
    assert not npx.is_np_array()
    try:
        npx.set_np()
        assert npx.is_np_array()
        # gluon blocks now speak mx.np
        from mxnet_tpu import gluon
        net = gluon.nn.Dense(3)
        net.initialize()
        out = net(np.ones((2, 4)))
        assert isinstance(out, np.ndarray)
        assert out.T.shape == (3, 2)
    finally:
        npx.reset_np()
    assert not npx.is_np_array()


def test_np_semantics_numpy_edge_cases():
    a = np.array([[1.0, 2], [3, 4]])
    # flip with no axis flips everything
    onp.testing.assert_allclose(np.flip(a).asnumpy(), [[4, 3], [2, 1]])
    # take with no axis flattens
    onp.testing.assert_allclose(
        np.take(np.arange(6).reshape(2, 3), np.array([0.0, 4.0]))
        .asnumpy(), [0, 4])
    # np.array copies the buffer; asarray shares it (note: writes
    # REBIND in this functional design, so sharing is at creation time)
    src = mx.nd.ones((2,))
    copied = np.array(src)
    viewed = np.asarray(src)
    assert viewed._data is src._data
    assert copied._data is not src._data


def test_npx_save_load(tmp_path):
    f = str(tmp_path / "x.params")
    npx.save(f, {"a": np.ones((2, 2))})
    back = npx.load(f)
    assert isinstance(back["a"], np.ndarray)
    onp.testing.assert_allclose(back["a"].asnumpy(), onp.ones((2, 2)))
