"""Multi-process distributed tests: REAL 2-process runs through
tools/launch.py + jax.distributed (reference: the nightly dist_sync
kvstore tests run via dmlc launcher)."""
import os
import subprocess
import sys

import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
# the env var alone is NOT enough here: the axon TPU plugin's
# sitecustomize imports jax before this script runs, so the tunneled
# TPU stays the default backend and any unplaced array drags these
# "cpu" workers through the (shared, contended) tunnel -- the config
# update pins the backend for real
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx

assert mx.distributed_init() is True
from mxnet_tpu.distributed import world
# the COORDINATION world spans both workers (the backend itself may
# stay single-process on CPU jaxlib without gloo -- host collectives
# ride the coordination service instead)
assert world()[0] == 2

# dist kvstore: each worker pushes rank+1; allreduce sums to 3
kv = mx.kv.create("dist_sync")
assert kv.num_workers == 2
kv.init("w", mx.nd.zeros((4,)))
g = mx.nd.ones((4,)) * (kv.rank + 1)
out = mx.nd.zeros((4,))
kv.pushpull("w", g, out=out)
np.testing.assert_allclose(out.asnumpy(), np.full(4, 3.0))

# horovod-style API over the same world
from mxnet_tpu import horovod as hvd
hvd.init()
assert hvd.size() == 2
s = hvd.allreduce(mx.nd.ones((3,)) * (hvd.rank() + 1), average=False)
np.testing.assert_allclose(s.asnumpy(), np.full(3, 3.0))
m = hvd.allreduce(mx.nd.ones((3,)) * (hvd.rank() + 1), average=True)
np.testing.assert_allclose(m.asnumpy(), np.full(3, 1.5))

# broadcast: every worker ends with root's weights
w = mx.nd.ones((2, 2)) * (hvd.rank() + 7)
class _P:
    def data(self):
        return w
hvd.broadcast_parameters([("w", _P())], root_rank=0)
np.testing.assert_allclose(w.asnumpy(), np.full((2, 2), 7.0))

kv.barrier()
print("WORKER_OK rank=%d" % kv.rank)
"""


_DEEP_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")   # see _WORKER's comment
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse as sp

assert mx.distributed_init() is True
N = 3

# --- dist_async: server-side optimizer, replicated updates ----------
# (async = async DISPATCH in this design: same converged weights as
# dist_sync, no staleness; see kvstore.py module docstring)
kv = mx.kv.create("dist_async")
assert kv.num_workers == N
rank = kv.rank
kv.init("w", mx.nd.zeros((4,)))
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
expected = np.zeros(4, np.float32)
for it in range(2):
    g = mx.nd.ones((4,)) * (rank + 1)
    kv.push("w", g)                       # allreduce-sum: 1+2+3 = 6
    expected -= 0.1 * 6.0
out = mx.nd.zeros((4,))
kv.pull("w", out=out)
np.testing.assert_allclose(out.asnumpy(), expected, rtol=1e-5)

# --- bigarray: a ~2 MB value through the dist pushpull path ----------
# (reference shards big arrays across servers at BIGARRAY_BOUND; the
# serverless allreduce has no shard split, but the transport must
# carry server-scale values correctly)
big = np.arange(512 * 1024, dtype=np.float32) / 1e6
bout = mx.nd.zeros((512 * 1024,))
kv2 = mx.kv.create("dist_sync")
kv2.init("big", mx.nd.zeros((512 * 1024,)))
kv2.pushpull("big", mx.nd.array(big), out=bout)
np.testing.assert_allclose(bout.asnumpy(), big * N, rtol=1e-6)

# --- row_sparse over dist: row-union merge, then dist reduce ---------
kv3 = mx.kv.create("dist_sync")
kv3.init("emb", mx.nd.zeros((6, 2)))
rows = np.array([rank, rank + 1], np.int64)
vals = np.full((2, 2), float(rank + 1), np.float32)
g = sp.RowSparseNDArray(vals, rows, (6, 2))
rout = mx.nd.zeros((6, 2))
kv3.pushpull("emb", g, out=rout)
dense = np.zeros((6, 2), np.float32)
for r in range(N):
    dense[r] += r + 1
    dense[r + 1] += r + 1
np.testing.assert_allclose(rout.asnumpy(), dense, rtol=1e-6)

# row_sparse_pull moves only the requested rows of the stored table
kv3.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
kv3.push("emb", g)                        # emb <- -1.0 * dense
picked = kv3.row_sparse_pull("emb", row_ids=mx.nd.array([1, 2]))
assert isinstance(picked, sp.RowSparseNDArray)
np.testing.assert_allclose(np.asarray(picked.indices), [1, 2])
np.testing.assert_allclose(np.asarray(picked.data), -dense[1:3],
                           rtol=1e-6)

# --- 2-bit compression with error feedback over the dist path --------
kv4 = mx.kv.create("dist_sync")
kv4.set_gradient_compression({"type": "2bit", "threshold": 0.5})
kv4.init("c", mx.nd.zeros((3,)))
cout = mx.nd.zeros((3,))
# round 1: |0.3| < threshold -> every worker sends 0, residual keeps 0.3
kv4.pushpull("c", mx.nd.ones((3,)) * 0.3, out=cout)
np.testing.assert_allclose(cout.asnumpy(), np.zeros(3), atol=1e-7)
# round 2: residual 0.3 + 0.3 = 0.6 >= threshold -> each sends 0.5
kv4.pushpull("c", mx.nd.ones((3,)) * 0.3, out=cout)
np.testing.assert_allclose(cout.asnumpy(), np.full(3, 0.5 * N),
                           rtol=1e-6)

kv.barrier()
print("DEEP_WORKER_OK rank=%d" % rank)
"""


_TRAINER_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")   # see _WORKER's comment
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

assert mx.distributed_init() is True
from mxnet_tpu.distributed import world
nproc, rank = world()
assert nproc == 2

# the standard distributed UX: gluon Trainer over a dist_sync kvstore,
# each rank feeding DIFFERENT data; gradients allreduce before the
# update so every rank must end with IDENTICAL weights
net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(1))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd",
                   {"learning_rate": 0.05}, kvstore="dist_sync")
loss_fn = gluon.loss.L2Loss()
rng = np.random.RandomState(100 + rank)      # per-rank data
w = np.random.RandomState(0).randn(5, 1).astype(np.float32)  # shared
xn = rng.randn(32, 5).astype(np.float32)
x = mx.nd.array(xn)
y = mx.nd.array(xn @ w)
first = last = None
for i in range(40):
    with autograd.record():
        l = loss_fn(net(x), y).mean()
    l.backward()
    tr.step(1)
    v = float(l.asnumpy())
    first = v if first is None else first
    last = v
assert last < first / 2, (first, last)

# weights identical across ranks: hash-reduce must equal 2x the local
from mxnet_tpu.distributed import host_allreduce
for name, p in sorted(net.collect_params().items()):
    local = np.asarray(p.data().asnumpy(), np.float64)
    summed = np.asarray(host_allreduce(local))
    np.testing.assert_allclose(summed, 2.0 * local, rtol=1e-6,
                               err_msg=name)

# --- legacy Module path: fit-style loop with kvstore='dist_sync' -----
data = mx.sym.Variable("data")
fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
act = mx.sym.Activation(fc, act_type="relu", name="relu1")
out = mx.sym.FullyConnected(act, num_hidden=4, name="fc2")
smx = mx.sym.SoftmaxOutput(out, name="softmax")
mod = mx.mod.Module(smx, context=mx.cpu())
mod.bind(data_shapes=[("data", (16, 6))],
         label_shapes=[("softmax_label", (16,))])
mod.init_params(initializer=mx.init.Xavier())
mod.init_optimizer(kvstore="dist_sync", optimizer="sgd",
                   optimizer_params={"learning_rate": 0.1})
mrng = np.random.RandomState(300 + rank)      # per-rank data
for i in range(6):
    batch = mx.io.DataBatch(
        data=[mx.nd.array(mrng.randn(16, 6).astype(np.float32))],
        label=[mx.nd.array(mrng.randint(0, 4, 16).astype(np.float32))])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
args, _aux = mod.get_params()
for name in sorted(args):
    local = np.asarray(args[name].asnumpy(), np.float64)
    summed = np.asarray(host_allreduce(local))
    np.testing.assert_allclose(summed, 2.0 * local, rtol=1e-6,
                               err_msg="module:" + name)

print("TRAINER_WORKER_OK rank=%d loss %.4f -> %.4f" % (rank, first, last))
"""


_GLOO_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")   # see _WORKER's comment
import numpy as np
import mxnet_tpu as mx

assert mx.distributed_init() is True
from mxnet_tpu import distributed as dist

# THE POD BRANCH, for real (ISSUE 7 satellite / VERDICT weak-4): with
# gloo CPU collectives wired by distributed_init, the BACKEND world is
# multi-process -- jax.process_count() matches the launcher world --
# so host_allreduce/host_broadcast take the process_allgather /
# broadcast_one_to_all path a TPU pod takes, NOT the O(N*P)
# coordination-service KV fallback.
assert jax.process_count() == 2, \
    "backend world is %d, not 2: the gloo collectives did not come up" \
    % jax.process_count()
nproc, rank = dist.world()
assert nproc == 2

out = dist.host_allreduce(np.ones((4,), np.float32) * (rank + 1))
np.testing.assert_allclose(np.asarray(out), np.full(4, 3.0))
mean = dist.host_allreduce(np.ones((2,), np.float32) * (rank + 1),
                           average=True)
np.testing.assert_allclose(np.asarray(mean), np.full(2, 1.5))
bc = dist.host_broadcast(np.full((3,), float(rank), np.float32))
np.testing.assert_allclose(np.asarray(bc), np.zeros(3))

# proof the fallback never ran: its one-shot warning latch is untouched
assert dist._KV_FALLBACK_WARNED[0] is False, \
    "host collectives fell back to the coordination-service KV path"
print("GLOO_WORKER_OK rank=%d" % rank)
"""


_SPMD_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_TPU_SHARD_CHECK"] = "1"     # arm executable capture
os.environ["MXNET_TPU_TELEMETRY"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")   # see _WORKER's comment
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon, telemetry
from mxnet_tpu import distributed as dist
from mxnet_tpu.analysis import sharding
from mxnet_tpu.parallel import TrainStep, global_mesh

# THE TENTPOLE (ISSUE 9): multi-host data-parallel training is ONE
# jit-compiled SPMD program over the global mesh -- gradients
# allreduced IN-GRAPH by GSPMD, kvstore a veneer whose push/pull move
# zero host bytes on the hot path.
assert mx.distributed_init() is True
assert jax.process_count() == 2, \
    "backend world is %d, not 2: gloo collectives did not come up" \
    % jax.process_count()
nproc, rank = dist.world()
assert nproc == 2

mesh = global_mesh()
assert mesh.shape["dp"] == 2 and not mesh.devices.flatten()[0] is None

net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd",
                   {"learning_rate": 0.05, "momentum": 0.9},
                   kvstore="dist_sync")
step = TrainStep(net, gluon.loss.L2Loss(), tr)  # mesh=None -> global mesh
assert step._mesh is mesh

rng = np.random.RandomState(100 + rank)          # per-rank LOCAL batch
w = np.random.RandomState(0).randn(8, 4).astype(np.float32)
x = rng.randn(8, 8).astype(np.float32)
y = (x @ w).astype(np.float32)

l0 = float(np.asarray(step(x, y)._data))         # compile + init sync
telemetry.reset("kvstore.")
# steady state under the transfer guard: host batches land through the
# EXPLICIT staging primitives, nothing implicit crosses host<->device
with sharding.transfer_guard("disallow"):
    for _ in range(10):
        loss = step(x, y)
    last = float(np.asarray(loss._data))
assert last < l0, (l0, last)

# the staged batch is the GLOBAL (nproc x local) batch, dp-sharded
assert step._last_call[1][2].shape[0] == 16, step._last_call[1][2].shape

# hot path moved ZERO host bytes through the kvstore...
for verb in ("push", "pull", "pushpull", "bytes"):
    assert telemetry.counter("kvstore." + verb).value == 0, verb
# ...and never touched the coordination-service KV fallback
assert dist._KV_FALLBACK_WARNED[0] is False

# the compiled program's collective contract carries the in-graph
# gradient all-reduce (5 = 4 param grads + the replicated mean loss)
cc = sharding.collective_contract()
kinds = cc["executables"]["train_step:HybridSequential"]
assert "all-reduce" in kinds and kinds["all-reduce"]["count"] >= 4, kinds

# post-update weights identical on every rank
for name, p in sorted(net.collect_params().items()):
    local = np.asarray(p.data()._data).astype(np.float64)
    summed = np.asarray(dist.host_allreduce(local))
    np.testing.assert_allclose(summed, 2.0 * local, rtol=1e-6,
                               err_msg=name)
print("SPMD_WORKER_OK rank=%d allreduce=%d" % (rank,
      kinds["all-reduce"]["count"]))
"""


_SPMD4_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["MXNET_TPU_SHARD_CHECK"] = "1"
import jax
jax.config.update("jax_platforms", "cpu")   # see _WORKER's comment
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu import distributed as dist
from mxnet_tpu.analysis import sharding
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.parallel import TrainStep, global_mesh
from jax.sharding import NamedSharding, PartitionSpec as P

CKDIR = os.environ["MXNET_TPU_TEST_CKDIR"]
assert mx.distributed_init() is True
assert jax.process_count() == 4, jax.process_count()
nproc, rank = dist.world()
assert nproc == 4

mesh = global_mesh()
assert mesh.shape["dp"] == 4

net = gluon.nn.HybridSequential()
net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
net.initialize(ctx=mx.cpu())
net.hybridize()
tr = gluon.Trainer(net.collect_params(), "sgd",
                   {"learning_rate": 0.1}, kvstore="dist_sync")
step = TrainStep(net, gluon.loss.L2Loss(), tr)
rng = np.random.RandomState(10 + rank)
x = rng.randn(4, 6).astype(np.float32)           # per-rank local batch
y = rng.randn(4, 2).astype(np.float32)
for _ in range(3):
    loss = step(x, y)
float(np.asarray(loss._data))

# the 4-way program carries the same in-graph gradient all-reduce
cc = sharding.collective_contract()
kinds = cc["executables"]["train_step:HybridSequential"]
assert "all-reduce" in kinds and kinds["all-reduce"]["count"] >= 4, kinds

# PR-3 sharded checkpoint over the GLOBAL mesh: every rank writes only
# its replica_id==0 addressable shards, rank 0 commits; restore
# reassembles and reshards onto the CURRENT global mesh
params = {p.name: p.data() for p in net.collect_params().values()}
want = {k: np.asarray(v._data) for k, v in params.items()}
mgr = CheckpointManager(CKDIR, sharded=True)
mgr.save(1, {"params": params}, metadata={"world": nproc})
dist.barrier("ckpt_saved")
assert mgr.latest_step() == 1

sh = NamedSharding(mesh, P())
ckpt = mgr.restore(sharding=lambda item, key, shape: sh)
for k, v in sorted(ckpt.items["params"].items()):
    arr = v._data
    assert arr.sharding.is_equivalent_to(sh, arr.ndim), (k, arr.sharding)
    assert len(arr.sharding.device_set) == 4, k
    np.testing.assert_allclose(np.asarray(arr), want[k], rtol=1e-6,
                               err_msg=k)
dist.barrier("ckpt_restored")
print("SPMD4_WORKER_OK rank=%d" % rank)
"""


def _scrub_device_count(flags):
    import re
    return re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                  flags).strip()


def _launch(script_path, n, env):
    # coordinator startup can race the free-port probe on a busy
    # machine; retry once before calling it a failure
    out = None
    for attempt in range(2):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "launch.py"),
             "-n", str(n), sys.executable, "-u", str(script_path)],
            capture_output=True, text=True, timeout=300, env=env)
        if out.returncode == 0:
            break
    return out


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_two_process_dist_kvstore(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep +
           os.environ.get("PYTHONPATH", "")}
    out = _launch(script, 2, env)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("WORKER_OK") == 2


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_three_process_dist_kvstore_deep(tmp_path):
    """3-process run covering dist_async updates, a ~2 MB bigarray
    value, row_sparse push + row_sparse_pull, and 2-bit compression
    with error feedback -- all over the real launcher + jax.distributed
    (reference: ``tests/nightly/dist_sync_kvstore.py``)."""
    script = tmp_path / "deep_worker.py"
    script.write_text(_DEEP_WORKER)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep +
           os.environ.get("PYTHONPATH", "")}
    out = _launch(script, 3, env)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("DEEP_WORKER_OK") == 3


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_two_process_gluon_trainer_dist_sync(tmp_path):
    """End-to-end distributed TRAINING through the standard UX:
    gluon.Trainer(kvstore='dist_sync'), per-rank data, replicated
    post-update weights (reference: the dist kvstore training loop in
    example/image-classification/common/fit.py)."""
    script = tmp_path / "trainer_worker.py"
    script.write_text(_TRAINER_WORKER)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep +
           os.environ.get("PYTHONPATH", "")}
    out = _launch(script, 2, env)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("TRAINER_WORKER_OK") == 2


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_two_process_backend_collectives_gloo(tmp_path):
    """The real `process_allgather` branch of distributed.host_allreduce
    runs in-suite: gloo CPU collectives make the backend world
    multi-process (jax.process_count() == launcher world), and the
    KV-fallback warning latch proves the coordinator-funnel path was
    never taken (ISSUE 7 satellite; was dead code per VERDICT weak-4)."""
    script = tmp_path / "gloo_worker.py"
    script.write_text(_GLOO_WORKER)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep +
           os.environ.get("PYTHONPATH", "")}
    out = _launch(script, 2, env)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("GLOO_WORKER_OK") == 2


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_two_process_spmd_train_step_gloo(tmp_path):
    """ISSUE 9 tentpole: the dist train step is ONE compiled SPMD
    program over the global mesh -- its collective contract lists the
    in-graph gradient all-reduce, kv push/pull byte counters stay at
    ZERO across steps (the kvstore is a veneer; the hot path moves no
    host bytes), the KV-fallback warn latch stays cold, and the
    steady-state loop runs under transfer_guard('disallow')."""
    script = tmp_path / "spmd_worker.py"
    script.write_text(_SPMD_WORKER)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep +
           os.environ.get("PYTHONPATH", ""),
           # one device per rank: the suite's 8-virtual-device flag
           # would make the global mesh 2x8 instead of 2
           "XLA_FLAGS": _scrub_device_count(os.environ.get("XLA_FLAGS",
                                                           ""))}
    out = _launch(script, 2, env)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("SPMD_WORKER_OK") == 2


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_four_process_spmd_checkpoint_reshard_gloo(tmp_path):
    """The pod branch at 4 ranks: same one-program contract, plus PR-3
    sharded checkpoint save/restore resharding across the new global
    mesh (each rank writes its replica_id==0 shards, rank 0 commits,
    restore reassembles onto the CURRENT 4-way mesh)."""
    script = tmp_path / "spmd4_worker.py"
    script.write_text(_SPMD4_WORKER)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep +
           os.environ.get("PYTHONPATH", ""),
           "MXNET_TPU_TEST_CKDIR": str(tmp_path / "ckpts"),
           "XLA_FLAGS": _scrub_device_count(os.environ.get("XLA_FLAGS",
                                                           ""))}
    out = _launch(script, 4, env)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("SPMD4_WORKER_OK") == 4


def test_horovod_single_process_api():
    from mxnet_tpu import horovod as hvd
    hvd.init()
    assert hvd.size() >= 1 and hvd.rank() >= 0
    x = hvd.allreduce(mx.nd.ones((2,)) * 4, average=True)
    assert x.asnumpy().tolist() == [4.0, 4.0]
    # DistributedTrainer degenerates to Trainer when single-process
    from mxnet_tpu import gluon
    net = gluon.nn.Dense(2)
    net.initialize()
    net(mx.nd.ones((1, 3)))
    tr = hvd.DistributedTrainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
    assert tr.learning_rate == 0.1
