"""Multi-process distributed tests: REAL 2-process runs through
tools/launch.py + jax.distributed (reference: the nightly dist_sync
kvstore tests run via dmlc launcher)."""
import os
import subprocess
import sys

import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WORKER = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
import mxnet_tpu as mx

assert mx.distributed_init() is True
from mxnet_tpu.distributed import world
# the COORDINATION world spans both workers (the backend itself may
# stay single-process on CPU jaxlib without gloo -- host collectives
# ride the coordination service instead)
assert world()[0] == 2

# dist kvstore: each worker pushes rank+1; allreduce sums to 3
kv = mx.kv.create("dist_sync")
assert kv.num_workers == 2
kv.init("w", mx.nd.zeros((4,)))
g = mx.nd.ones((4,)) * (kv.rank + 1)
out = mx.nd.zeros((4,))
kv.pushpull("w", g, out=out)
np.testing.assert_allclose(out.asnumpy(), np.full(4, 3.0))

# horovod-style API over the same world
from mxnet_tpu import horovod as hvd
hvd.init()
assert hvd.size() == 2
s = hvd.allreduce(mx.nd.ones((3,)) * (hvd.rank() + 1), average=False)
np.testing.assert_allclose(s.asnumpy(), np.full(3, 3.0))
m = hvd.allreduce(mx.nd.ones((3,)) * (hvd.rank() + 1), average=True)
np.testing.assert_allclose(m.asnumpy(), np.full(3, 1.5))

# broadcast: every worker ends with root's weights
w = mx.nd.ones((2, 2)) * (hvd.rank() + 7)
class _P:
    def data(self):
        return w
hvd.broadcast_parameters([("w", _P())], root_rank=0)
np.testing.assert_allclose(w.asnumpy(), np.full((2, 2), 7.0))

kv.barrier()
print("WORKER_OK rank=%d" % kv.rank)
"""


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_two_process_dist_kvstore(tmp_path):
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    env = {**os.environ, "PYTHONPATH": REPO + os.pathsep +
           os.environ.get("PYTHONPATH", "")}
    # coordinator startup can race the free-port probe on a busy
    # machine; retry once before calling it a failure
    for attempt in range(2):
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "launch.py"),
             "-n", "2", sys.executable, "-u", str(script)],
            capture_output=True, text=True, timeout=300, env=env)
        if out.returncode == 0:
            break
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert out.stdout.count("WORKER_OK") == 2


def test_horovod_single_process_api():
    from mxnet_tpu import horovod as hvd
    hvd.init()
    assert hvd.size() >= 1 and hvd.rank() >= 0
    x = hvd.allreduce(mx.nd.ones((2,)) * 4, average=True)
    assert x.asnumpy().tolist() == [4.0, 4.0]
    # DistributedTrainer degenerates to Trainer when single-process
    from mxnet_tpu import gluon
    net = gluon.nn.Dense(2)
    net.initialize()
    net(mx.nd.ones((1, 3)))
    tr = hvd.DistributedTrainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
    assert tr.learning_rate == 0.1
