"""Native recordio engine tests (reference: dmlc-core recordio framing
tests + ``test_recordio.py``)."""
import os

import numpy as np
import pytest

from mxnet_tpu import recordio
from mxnet_tpu._native import load

native = pytest.mark.skipif(load() is None,
                            reason="native library unavailable")


def _write_file(tmp_path, payloads, force_python=False):
    rec = str(tmp_path / "f.rec")
    idx = str(tmp_path / "f.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    if force_python:
        assert w._nh is None
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()
    return idx, rec


@native
def test_native_round_trip(tmp_path):
    rng = np.random.RandomState(0)
    payloads = [bytes(rng.bytes(rng.randint(1, 4096))) for _ in range(64)]
    idx, rec = _write_file(tmp_path, payloads)
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r._nh is not None
    for i in (0, 63, 31, 1):
        assert r.read_idx(i) == payloads[i]
    assert r.read_batch(list(range(64)), nthreads=4) == payloads
    r.close()


@native
def test_native_python_byte_compat(tmp_path, monkeypatch):
    """Files written natively parse with the Python reader and vice
    versa -- same dmlc framing on disk."""
    payloads = [b"a" * 7, b"bb", b"c" * 1000]
    idx, rec = _write_file(tmp_path, payloads)

    import mxnet_tpu._native as nat
    monkeypatch.setenv("MXNET_TPU_NATIVE", "0")
    monkeypatch.setattr(nat, "_TRIED", False)
    monkeypatch.setattr(nat, "_LIB", None)
    r = recordio.MXRecordIO(rec, "r")
    assert r._nh is None
    got = []
    while True:
        x = r.read()
        if x is None:
            break
        got.append(x)
    assert got == payloads

    # python-written file, native reader
    py_rec = str(tmp_path / "py.rec")
    w = recordio.MXRecordIO(py_rec, "w")
    assert w._nh is None
    for p in payloads:
        w.write(p)
    w.close()
    monkeypatch.setenv("MXNET_TPU_NATIVE", "1")
    monkeypatch.setattr(nat, "_TRIED", False)
    monkeypatch.setattr(nat, "_LIB", None)
    rn = recordio.MXRecordIO(py_rec, "r")
    assert rn._nh is not None
    assert [rn.read(), rn.read(), rn.read()] == payloads
    assert rn.read() is None


@native
def test_native_corrupt_detection(tmp_path):
    bad = str(tmp_path / "bad.rec")
    with open(bad, "wb") as f:
        f.write(b"\x00" * 16)
    r = recordio.MXRecordIO(bad, "r")
    with pytest.raises(Exception):
        r.read()


def test_pack_unpack_headers():
    hdr = recordio.IRHeader(0, 3.5, 42, 0)
    s = recordio.pack(hdr, b"payload")
    h2, body = recordio.unpack(s)
    assert body == b"payload"
    assert h2.label == 3.5 and h2.id == 42
