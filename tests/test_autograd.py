"""Autograd semantics (reference: ``tests/python/unittest/test_autograd.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.test_utils import assert_almost_equal


def test_record_pause():
    x = mx.nd.ones((2,))
    x.attach_grad()
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
        with autograd.pause():
            assert not autograd.is_recording()
        y = x * 2
    y.backward()
    assert x.grad.asnumpy().tolist() == [2, 2]


def test_train_predict_mode():
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
        with autograd.train_mode():
            assert autograd.is_training()
    with autograd.predict_mode():
        assert not autograd.is_training()


def test_grad_req_add():
    x = mx.nd.ones((3,))
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (x * 2).sum()
        y.backward()
    assert x.grad.asnumpy().tolist() == [6, 6, 6]


def test_grad_req_null():
    x = mx.nd.ones((3,))
    x.attach_grad(grad_req="null")
    w = mx.nd.ones((3,))
    w.attach_grad()
    with autograd.record():
        y = (x * w).sum()
    y.backward()
    assert w.grad.asnumpy().tolist() == [1, 1, 1]
    assert x.grad.asnumpy().tolist() == [0, 0, 0]


def test_multiple_use_accumulates():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x
    y.backward()
    assert x.grad.asscalar() == pytest.approx(5.0)


def test_head_grad():
    x = mx.nd.array([1., 2.])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(mx.nd.array([10., 100.]))
    assert x.grad.asnumpy().tolist() == [30, 300]


def test_detach_blocks():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert x.grad.asscalar() == pytest.approx(4.0)  # d(z)/dx = y = 4


def test_block_grad_op():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = mx.nd.BlockGrad(x * x) * x
    y.backward()
    assert x.grad.asscalar() == pytest.approx(4.0)


def test_deep_chain():
    x = mx.nd.array([1.5])
    x.attach_grad()
    with autograd.record():
        y = x
        for _ in range(30):
            y = y * 1.1
    y.backward()
    assert x.grad.asscalar() == pytest.approx(1.1 ** 30, rel=1e-4)


def test_autograd_grad_function():
    x = mx.nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    g = autograd.grad(y, x)
    assert g.asscalar() == pytest.approx(6.0)


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1 / (1 + mx.nd.exp(-x))
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = mx.nd.array([0.5, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with autograd.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert_almost_equal(x.grad, s * (1 - s), rtol=1e-4)


def test_backward_through_multiple_heads():
    x = mx.nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        a = x * 2
        b = x * 3
    autograd.backward([a, b])
    assert x.grad.asnumpy().tolist() == [5, 5]


def test_error_outside_record():
    x = mx.nd.ones((2,))
    y = x * 2  # not recorded
    with pytest.raises(Exception):
        y.backward()
