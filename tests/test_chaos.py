"""Chaos harness + always-on loop tests (ISSUE 12): deterministic fail
points, checkpoint quarantine, async-write retry, preemption re-entrancy,
batcher flood shedding, and the continuous-train -> hot-swap loop under
injected faults (zero dropped requests across a swap; kill-mid-commit
rolls the watcher back to the previous verified step)."""
import os
import signal
import subprocess
import sys
import threading
import time
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, gluon, serving, telemetry
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.checkpoint.async_writer import AsyncWriter
from mxnet_tpu.serving.loop import ContinuousTrainer, RegistryWatcher

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def counters():
    telemetry.enable()
    yield telemetry
    telemetry.disable()


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.disarm()
    chaos.reset()


def _loop_parts(tmp_path, publish_every=2):
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data,
                           str(tmp_path / "ck"),
                           publish_every=publish_every)
    return net, ct


# ---------------------------------------------------------------------
# fail-point core
# ---------------------------------------------------------------------

def test_fail_point_disarmed_is_noop():
    chaos.on("never", action=chaos.RAISE)   # rule present, not armed
    chaos.fail_point("never")               # must not fire
    assert chaos.stats()["hits"] == {}      # disarmed: not even counted


def test_nth_rule_fires_deterministically():
    with chaos.scenario(seed=3):
        chaos.on("pt", nth=(2, 3))
        chaos.fail_point("pt")
        for _ in range(2):
            with pytest.raises(chaos.ChaosInjected):
                chaos.fail_point("pt")
        chaos.fail_point("pt")              # hit 4: clean
    st = chaos.stats()
    assert st["hits"]["pt"] == 4 and st["injected"]["pt"] == 2


def test_prob_rule_replays_identically_for_a_seed():
    def run(seed):
        fired = []
        with chaos.scenario(seed=seed):
            chaos.on("p", prob=0.5)
            for i in range(32):
                try:
                    chaos.fail_point("p")
                    fired.append(False)
                except chaos.ChaosInjected:
                    fired.append(True)
        return fired

    a, b = run(7), run(7)
    assert a == b and any(a) and not all(a)
    assert run(8) != a                      # a different seed differs


def test_times_caps_fires():
    with chaos.scenario(seed=0):
        chaos.on("cap", times=1)
        with pytest.raises(chaos.ChaosInjected):
            chaos.fail_point("cap")
        chaos.fail_point("cap")             # capped: clean
    assert chaos.stats()["injected"]["cap"] == 1


def test_injection_counts_in_telemetry(counters):
    telemetry.reset("chaos.")
    with chaos.scenario(seed=0):
        chaos.on("t", times=1)
        with pytest.raises(chaos.ChaosInjected):
            chaos.fail_point("t")
    chaos.survived("t", "test")
    assert telemetry.counter("chaos.injected").value == 1
    assert telemetry.counter("chaos.injected.t").value == 1
    assert telemetry.counter("chaos.survived.t").value == 1


# ---------------------------------------------------------------------
# checkpoint: quarantine (satellite) + kill-mid-commit
# ---------------------------------------------------------------------

def _two_steps(tmp_path, **kwargs):
    mgr = CheckpointManager(str(tmp_path / "ck"), **kwargs)
    mgr.save(1, {"blob": b"one"})
    mgr.save(2, {"blob": b"two"})
    return mgr

def test_torn_newest_step_is_quarantined(tmp_path, counters):
    telemetry.reset("checkpoint.")
    mgr = _two_steps(tmp_path)
    with open(os.path.join(mgr.step_dir(2), "blob.bin"), "r+b") as f:
        f.truncate(1)
    with pytest.warns(RuntimeWarning, match="failed verification"):
        assert mgr.latest_step() == 1
    # renamed, not silently skipped: evidence survives, discovery is
    # clean on the next poll (no re-warn), and the counter records it
    assert not os.path.isdir(mgr.step_dir(2))
    assert os.path.isdir(mgr.step_dir(2) + ".corrupt")
    assert mgr.all_steps() == [1]
    assert telemetry.counter("checkpoint.quarantined").value == 1
    assert mgr.restore().step == 1


def test_quarantine_off_keeps_skip_only_discovery(tmp_path):
    mgr = _two_steps(tmp_path, quarantine=False)
    os.remove(os.path.join(mgr.step_dir(2), "manifest.json"))
    with pytest.warns(RuntimeWarning):
        assert mgr.latest_step() == 1
    assert os.path.isdir(mgr.step_dir(2))   # left in place


def test_chaos_truncate_action_tears_a_committed_step(tmp_path):
    mgr = CheckpointManager(str(tmp_path / "ck"))
    with chaos.scenario(seed=0):
        chaos.on("checkpoint.commit.post_commit", nth=2,
                 action=chaos.truncate("blob.bin", keep=1))
        mgr.save(1, {"blob": b"step-one"})
        mgr.save(2, {"blob": b"step-two"})  # torn after the commit
    with pytest.warns(RuntimeWarning):
        assert mgr.latest_step() == 1
    assert chaos.stats()["injected"] == \
        {"checkpoint.commit.post_commit": 1}
    assert chaos.stats()["survived"] == {"checkpoint.commit": 1}


@pytest.mark.slow
def test_kill_mid_commit_subprocess_costs_one_step(tmp_path):
    """A REAL kill (os._exit, SIGKILL-shaped) between the data files
    and the manifest commit: the staged step must never become
    loadable, discovery lands on the previous step, and the next
    manager sweeps the orphaned staging dir."""
    root = str(tmp_path / "ck")
    code = (
        "import mxnet_tpu as mx\n"
        "from mxnet_tpu import chaos\n"
        "mgr = mx.checkpoint.CheckpointManager(%r)\n"
        "chaos.arm(seed=0)\n"
        "chaos.on('checkpoint.commit.pre_manifest', nth=2,\n"
        "         action=chaos.KILL)\n"
        "mgr.save(1, {'blob': b'one'})\n"
        "mgr.save(2, {'blob': b'two'})\n"   # dies here
        "raise SystemExit('kill did not fire')\n" % root)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", code], env=env,
                         capture_output=True, text=True, timeout=300)
    assert out.returncode == 137, (out.returncode, out.stderr[-500:])
    leftover = [d for d in os.listdir(root) if d.endswith(".tmp")]
    assert leftover, "expected an orphaned staging dir"
    mgr = CheckpointManager(root)           # init sweeps dead-pid tmps
    assert mgr.latest_step() == 1
    assert not any(d.endswith(".tmp") for d in os.listdir(root))


# ---------------------------------------------------------------------
# async writer: bounded retry + surfaced failure (satellite)
# ---------------------------------------------------------------------

def test_async_write_retries_then_lands(tmp_path, counters):
    telemetry.reset("checkpoint.")
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr._writer = AsyncWriter(retries=2, backoff_s=0.01)
    with chaos.scenario(seed=0):
        chaos.on("checkpoint.async_write", nth=(1, 2))
        mgr.save(1, {"blob": b"retry-me"})
        mgr.wait_until_finished()           # no raise: 3rd attempt won
    assert mgr.latest_step() == 1
    assert telemetry.counter("checkpoint.write_retries").value == 2
    assert telemetry.counter("checkpoint.write_failures").value == 0
    assert chaos.stats()["survived"] == {"checkpoint.async_write": 1}


def test_async_write_final_failure_surfaces(tmp_path, counters):
    telemetry.reset("checkpoint.")
    mgr = CheckpointManager(str(tmp_path / "ck"))
    mgr._writer = AsyncWriter(retries=1, backoff_s=0.01)
    with chaos.scenario(seed=0):
        chaos.on("checkpoint.async_write")  # every attempt dies
        mgr.save(1, {"blob": b"doomed"})
        with pytest.raises(chaos.ChaosInjected):
            mgr.wait_until_finished()       # stored error re-raises
    assert mgr.latest_step() is None
    assert telemetry.counter("checkpoint.write_retries").value == 1
    assert telemetry.counter("checkpoint.write_failures").value == 1
    ev = telemetry.event("checkpoint.write_failed").recent[-1]
    assert ev["attempts"] == 2


# ---------------------------------------------------------------------
# preemption: re-entrant signal delivery (satellite)
# ---------------------------------------------------------------------

def test_reentrant_sigterm_cannot_tear_the_save(tmp_path, counters):
    telemetry.reset("preemption.")
    from mxnet_tpu import preemption
    net, trainer, _, _ = scenarios.train_fixtures(seed=0)
    prefix = str(tmp_path / "job")
    handler = preemption.PreemptionHandler(prefix, net, trainer,
                                           signals=(),
                                           save_in_handler=True)
    nested = []

    def deliver_nested(ctx):
        # a second SIGTERM landing while the first handler (and its
        # save) is still on this thread's stack
        nested.append(True)
        ctx["handler"]._on_signal(signal.SIGTERM, None)

    with chaos.scenario(seed=0):
        chaos.on("preemption.signal", nth=1, action=deliver_nested)
        handler._on_signal(signal.SIGTERM, None)
    assert nested and handler.saved
    assert telemetry.counter("preemption.reentrant_signals").value == 1
    assert chaos.stats()["survived"] == \
        {"preemption.signal": 1}
    # the checkpoint the ONE save wrote verifies and resumes
    net2, trainer2, _, _ = scenarios.train_fixtures(seed=1)
    meta = preemption.resume(prefix, net2, trainer2)
    assert meta is not None
    handler.uninstall()


def test_signal_during_boundary_save_is_suppressed(tmp_path, counters):
    """SIGTERM interrupting an in-progress save_now() (the boundary
    save a `triggered` read started) must not start a second commit."""
    telemetry.reset("preemption.")
    from mxnet_tpu import preemption
    net, trainer, _, _ = scenarios.train_fixtures(seed=0)
    prefix = str(tmp_path / "job2")
    handler = preemption.PreemptionHandler(prefix, net, trainer,
                                           signals=())
    orig = net.save_parameters
    calls = []

    def interrupted_save(path):
        calls.append(path)
        if len(calls) == 1:     # signal lands mid-commit, same thread
            handler._on_signal(signal.SIGTERM, None)
        return orig(path)

    net.save_parameters = interrupted_save
    handler.save_now(step=5)
    assert len(calls) == 1      # ONE commit: no nested re-save ran
    assert handler.saved and handler.triggered
    assert telemetry.counter("preemption.reentrant_signals").value == 1
    net2, trainer2, _, _ = scenarios.train_fixtures(seed=1)
    meta = preemption.resume(prefix, net2, trainer2)
    assert meta is not None and meta["step"] == 5
    handler.uninstall()


# ---------------------------------------------------------------------
# batcher: flood past the queue bound (satellite)
# ---------------------------------------------------------------------

def test_flood_past_queue_bound_sheds_and_completes(counters):
    telemetry.reset("serving.")
    rep = scenarios.flood_scenario(seed=0, max_queue=4, clients=8,
                                   per_client=8, hold_s=0.02)
    # sheds happened, carried the DISTINCT error (anything else lands
    # in rep["errors"]), and were counted
    assert rep["shed"] > 0 and rep["errors"] == []
    assert rep["shed_counter_delta"] == rep["shed"]
    # every accepted request still completed -- in-flight work is
    # never a casualty of backpressure
    assert rep["completed"] + rep["shed"] == rep["requests"]
    assert rep["completed"] > 0
    # the bounded queue bounds the tail: worst wait is queue-depth
    # stalls, not the flood's duration
    assert rep["max_latency_s"] < rep["latency_bound_s"]


def test_shed_error_is_distinct_and_inflight_completes():
    net = scenarios.make_mlp()
    reg = serving.ModelRegistry(compile_cache=False)
    with chaos.scenario(seed=0):
        chaos.on("serving.dispatch", action=chaos.sleep(0.05), times=1)
        s = reg.register("m", block=net, input_shape=(8,), buckets=(1,),
                         max_wait_ms=1, max_queue=1)
        x = np.ones(8, np.float32)
        first = s.submit(x)                 # dispatched (stalled 50ms)
        for _ in range(200):                # worker popped it?
            if s.queue_depth() == 0:
                break
            time.sleep(0.002)  # mxlint: disable=sleep-poll
        queued = s.submit(x)                # fills the queue
        with pytest.raises(serving.ServingQueueFull):
            s.submit(x)                     # the flood overflow
        assert first.result(timeout=10) is not None
        assert queued.result(timeout=10) is not None
    reg.shutdown(drain=True)


# ---------------------------------------------------------------------
# the always-on loop: continuous train -> hot swap, under chaos
# ---------------------------------------------------------------------

def test_hotswap_zero_dropped_requests(tmp_path):
    rep = scenarios.hotswap_scenario(str(tmp_path / "loop"), torn=False,
                                     seed=0)
    assert rep["first_swap_step"] == 2 and rep["second_swap_step"] == 4
    assert rep["served_step"] == 4
    # the acceptance gate: zero dropped (non-shed) requests across the
    # swap, with traffic provably overlapping it
    assert rep["errors"] == [] and rep["shed"] == 0
    assert rep["completed"] == rep["requests"]
    assert rep["completed_after_swap"] >= 1
    assert rep["quarantined"] == []


def test_kill_mid_commit_rolls_watcher_back(tmp_path):
    rep = scenarios.hotswap_scenario(str(tmp_path / "loop"), torn=True,
                                     seed=0)
    # the torn publish is quarantined and the watcher keeps serving
    # the previous verified step -- the rollback acceptance gate
    assert rep["second_swap_step"] is None
    assert rep["served_step"] == 2
    assert rep["published_step"] == 4
    assert rep["quarantined"] == ["step_00000004.corrupt"]
    assert rep["errors"] == []
    assert rep["chaos"]["injected"] == \
        {"checkpoint.commit.post_commit": 1}
    assert rep["chaos"]["survived"]["checkpoint.commit"] == 1


def test_watcher_swap_serves_new_params(tmp_path, counters):
    """After a swap the servable answers with the NEW step's weights."""
    telemetry.reset("serving.")
    net, ct = _loop_parts(tmp_path, publish_every=1)
    reg = serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1, 2),
                              max_wait_ms=1, poll_s=0.05)
    ct.run_steps(1)
    assert watcher.poll_once() == 1
    x = np.random.RandomState(3).rand(8).astype(np.float32)
    want1 = net(mx.nd.array(x[None])).asnumpy()[0]
    np.testing.assert_allclose(reg.infer("m", x, timeout=10), want1,
                               rtol=1e-5, atol=1e-6)
    ct.run_steps(1)                         # params moved; published
    assert watcher.poll_once() == 2
    want2 = net(mx.nd.array(x[None])).asnumpy()[0]
    assert not np.allclose(want1, want2)    # training really moved them
    np.testing.assert_allclose(reg.infer("m", x, timeout=10), want2,
                               rtol=1e-5, atol=1e-6)
    assert telemetry.counter("serving.swaps").value == 2
    assert telemetry.gauge("serving.served_step").value == 2
    ct.close()
    watcher.close()
    reg.shutdown(drain=True)


def test_swap_abort_retries_with_backoff(tmp_path, counters):
    telemetry.reset("serving.")
    net, ct = _loop_parts(tmp_path, publish_every=1)
    reg = serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1,),
                              max_wait_ms=1, swap_retries=1,
                              swap_backoff_s=0.01)
    ct.run_steps(1)
    with chaos.scenario(seed=0):
        chaos.on("serving.swap", nth=1)     # first attempt aborts
        assert watcher.poll_once() == 1     # retry lands it
    assert watcher.served_step == 1
    assert telemetry.counter("serving.swap_failures").value == 1
    assert telemetry.counter("serving.swaps").value == 1
    assert chaos.stats()["survived"]["serving.swap"] == 1
    ct.close()
    watcher.close()
    reg.shutdown(drain=True)


def test_swap_failure_budget_suspends_watcher(tmp_path, counters):
    telemetry.reset("serving.")
    net, ct = _loop_parts(tmp_path, publish_every=1)
    reg = serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1,),
                              max_wait_ms=1, swap_retries=1,
                              swap_backoff_s=0.01, failure_budget=2)
    ct.run_steps(1)
    with chaos.scenario(seed=0):
        chaos.on("serving.swap")            # every attempt aborts
        with pytest.warns(RuntimeWarning, match="swap to step 1"):
            assert watcher.poll_once() is None
        assert watcher.bad_steps() == [1]   # skipped, not retried ad
        assert watcher.poll_once() is None  # infinitum
        assert not watcher.suspended        # budget is 2
        ct.run_steps(1)                     # step 2 publishes
        with pytest.warns(RuntimeWarning, match="budget exhausted"):
            assert watcher.poll_once() is None
        assert watcher.suspended
    assert watcher.served_step is None
    assert "m" not in reg                   # nothing half-installed
    assert telemetry.counter("serving.swap_failures").value == 4
    ct.close()
    watcher.close()
    reg.shutdown(drain=True)


def test_continuous_trainer_resumes_from_published_step(tmp_path):
    net, ct = _loop_parts(tmp_path, publish_every=2)
    ct.run_steps(4)
    assert ct.published_step == 4
    ct.close()
    # a fresh incarnation (crash restart) resumes at the published step
    net2, trainer2, loss_fn2, data2 = scenarios.train_fixtures(seed=0)
    ct2 = ContinuousTrainer(net2, trainer2, loss_fn2, data2,
                            ct.manager.root, publish_every=2)
    ckpt = ct2.resume()
    assert ckpt is not None and ckpt.step == 4 and ct2.step == 4
    ct2.run_steps(2)
    assert ct2.published_step == 6
    ct2.close()


@pytest.mark.slow
def test_soak_background_loop_many_swaps(tmp_path):
    """Soak: trainer and watcher on their own threads, clients hammering
    throughout; every published step must eventually serve and no
    request may fail."""
    net, ct = _loop_parts(tmp_path, publish_every=3)
    reg = serving.ModelRegistry(compile_cache=False)
    watcher = RegistryWatcher(reg, "m", ct.manager, scenarios.make_mlp(),
                              input_shape=(8,), buckets=(1, 2, 4),
                              max_wait_ms=2, poll_s=0.05)
    errors = []
    stop = threading.Event()
    sample = np.random.RandomState(0).rand(8).astype(np.float32)

    def client():
        while not stop.is_set():
            try:
                reg.infer("m", sample, timeout=30)
            except Exception as e:
                errors.append(type(e).__name__)
            time.sleep(0.002)  # mxlint: disable=sleep-poll

    ct.run_steps(3)
    assert watcher.poll_once() == 3
    watcher.start()
    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(3)]
    for t in threads:
        t.start()
    ct.start(max_steps=12)                  # publishes steps 6..15
    deadline = time.monotonic() + 60
    while watcher.served_step != 15 and time.monotonic() < deadline:
        time.sleep(0.05)  # mxlint: disable=sleep-poll
    stop.set()
    for t in threads:
        t.join()
    ct.close()
    watcher.close()
    reg.shutdown(drain=True)
    assert watcher.served_step == 15
    assert errors == []
