"""Test harness config.

Tests run on CPU with 8 virtual devices (reference test strategy SURVEY.md
§4: cpu is the reference backend).  The multi-device tests
(tests/test_parallel.py) build a jax.sharding.Mesh over these virtual
devices and run the same shard_map/pjit code paths that run on a real
v5e-8, the way the reference's nightly dist tests use local
multi-process kvstore.
"""
import os

prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

# The env var alone does not pin the backend on hosts where a TPU
# plugin's sitecustomize imported jax before pytest (the tunneled TPU
# stays the default device, and any unplaced array silently routes
# through it) -- and on such hosts JAX_PLATFORMS itself is forced by
# the environment, so it can't express the user's intent either.  Pin
# the suite to its CPU contract; a deliberate on-device run says so
# explicitly via MXNET_TPU_TEST_PLATFORM.
import jax  # noqa: E402

jax.config.update("jax_platforms",
                  os.environ.get("MXNET_TPU_TEST_PLATFORM", "cpu"))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def paired_params(a, b):
    """Structurally-paired parameters of two same-architecture blocks.

    The obvious ``zip(sorted(a.collect_params().items()), ...)`` idiom
    is order-fragile: gluon's auto-name counter is process-global, and
    once it passes 9, ``dense10_weight`` sorts BEFORE ``dense9_weight``
    -- so whether the pairing is correct depends on how many blocks
    earlier tests created.  Structural prefixes are position-stable.
    """
    pa = a._collect_params_with_prefix()
    pb = b._collect_params_with_prefix()
    assert set(pa) == set(pb)
    return [(pa[k], pb[k]) for k in sorted(pa)]


@pytest.fixture(autouse=True)
def _seed_everything():
    """Per-test deterministic seeding (reference:
    ``tests/python/unittest/common.py :: with_seed``)."""
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
