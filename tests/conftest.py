"""Test harness config.

Tests run on CPU with 8 virtual devices (reference test strategy SURVEY.md
§4: cpu is the reference backend; multi-device paths are exercised the way
the reference's nightly dist tests use local multi-process -- here via
XLA's virtual host devices, which exercise the same Mesh/pjit sharding
code that runs on a real v5e-8).
"""
import os

prev = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8").strip()

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything():
    """Per-test deterministic seeding (reference:
    ``tests/python/unittest/common.py :: with_seed``)."""
    import mxnet_tpu as mx
    np.random.seed(0)
    mx.random.seed(0)
    yield
