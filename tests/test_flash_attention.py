"""Flash attention: Pallas forward AND backward kernels, masked variant
(reference: ``src/operator/contrib/transformer.cc`` fused attention).

Kernels run in interpret mode on the CPU test backend; the same code
compiles on TPU.  Every check is against the plain XLA reference and
its autodiff.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops.pallas import flash_attention as fa
from mxnet_tpu.ops.transformer import _attention_reference

pytestmark = pytest.mark.skipif(not fa._HAS_PALLAS,
                                reason="no pallas on this backend")


@pytest.fixture(autouse=True)
def _exact_matmuls():
    # the CPU backend runs fp32 matmuls in reduced precision on
    # avx512-bf16 hosts; force exact so kernel-vs-reference comparisons
    # measure the algorithm, not the hardware's fast path
    with jax.default_matmul_precision("highest"):
        yield

BH, SEQ, D, HEADS = 4, 64, 16, 2
B = BH // HEADS


def _qkv(seed=0):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.randn(BH, SEQ, D).astype(np.float32) * 0.5)
            for _ in range(3)]


def _mask(seed=1):
    rng = np.random.RandomState(seed)
    valid = rng.randint(SEQ // 2, SEQ + 1, (B,))
    m = np.zeros((B, SEQ, SEQ), np.float32)
    for i, n in enumerate(valid):
        m[i, :, :n] = 1.0
    return jnp.asarray(m)


def _ref_masked(q, k, v, mask, scale):
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    m = jnp.repeat(mask, HEADS, axis=0)
    s = jnp.where(m > 0, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_fwd_matches_reference(causal):
    q, k, v = _qkv()
    scale = 1.0 / np.sqrt(D)
    out, lse = fa.flash_attention_fwd_pallas(
        q, k, v, causal=causal, scale=scale, block_q=32, block_k=32,
        interpret=True)
    want = _attention_reference(q, k, v, causal, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)
    # lse really is the log-sum-exp of the (masked) score rows
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        rows = np.arange(SEQ)[:, None]
        cols = np.arange(SEQ)[None, :]
        s = jnp.where(jnp.asarray(rows >= cols), s, -1e30)
    want_lse = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(want_lse),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("causal", [False, True])
def test_bwd_matches_autodiff(causal):
    q, k, v = _qkv(2)
    scale = 1.0 / np.sqrt(D)

    def ref_loss(q, k, v):
        out = _attention_reference(q, k, v, causal, scale)
        return jnp.sum(out * jnp.cos(out))  # nontrivial cotangent

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

    out, lse = fa.flash_attention_fwd_pallas(
        q, k, v, causal=causal, scale=scale, block_q=32, block_k=32,
        interpret=True)
    dout = jnp.cos(out) - out * jnp.sin(out)
    delta = jnp.sum(dout * out, axis=-1)
    dq, dk, dv = fa.flash_attention_bwd_pallas(
        q, k, v, lse, dout, delta, causal=causal, scale=scale,
        block_q=32, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_ref),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_ref),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_ref),
                               rtol=2e-3, atol=2e-4)


def test_masked_fwd_bwd_match_reference():
    q, k, v = _qkv(3)
    mask = _mask()
    scale = 1.0 / np.sqrt(D)

    out, lse = fa.flash_attention_fwd_pallas(
        q, k, v, mask, causal=False, scale=scale, block_q=32, block_k=32,
        heads=HEADS, interpret=True)
    want = _ref_masked(q, k, v, mask, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=2e-4, atol=2e-5)

    def ref_loss(q, k, v):
        return jnp.sum(jnp.tanh(_ref_masked(q, k, v, mask, scale)))

    dq_ref, dk_ref, dv_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    dout = 1.0 - jnp.tanh(want) ** 2
    delta = jnp.sum(dout * out, axis=-1)
    dq, dk, dv = fa.flash_attention_bwd_pallas(
        q, k, v, lse, dout, delta, mask, causal=False, scale=scale,
        block_q=32, block_k=32, heads=HEADS, interpret=True)
    np.testing.assert_allclose(np.asarray(dq), np.asarray(dq_ref),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dk), np.asarray(dk_ref),
                               rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dv), np.asarray(dv_ref),
                               rtol=2e-3, atol=2e-4)


def test_op_level_masked_grad_matches_xla_path():
    """The registered op's custom_vjp (XLA fallback on CPU) agrees with
    autodiff through the unfused reference."""
    rng = np.random.RandomState(4)
    q = mx.nd.array(rng.randn(BH, SEQ, D).astype(np.float32))
    k = mx.nd.array(rng.randn(BH, SEQ, D).astype(np.float32))
    v = mx.nd.array(rng.randn(BH, SEQ, D).astype(np.float32))
    mask = mx.nd.array(np.asarray(_mask()))
    from mxnet_tpu import autograd
    for t in (q, k, v):
        t.attach_grad()
    with autograd.record():
        out = mx.nd.flash_attention_masked(q, k, v, mask, heads=HEADS,
                                           use_pallas=False)
        loss = (out * out).sum()
    loss.backward()

    qj, kj, vj = (jnp.asarray(t.asnumpy()) for t in (q, k, v))
    scale = 1.0 / np.sqrt(D)

    def ref_loss(qj, kj, vj):
        o = _ref_masked(qj, kj, vj, jnp.asarray(mask.asnumpy()), scale)
        return jnp.sum(o * o)

    g = jax.grad(ref_loss, argnums=(0, 1, 2))(qj, kj, vj)
    for got, want in zip((q.grad, k.grad, v.grad), g):
        np.testing.assert_allclose(got.asnumpy(), np.asarray(want),
                                   rtol=2e-3, atol=2e-3)


def test_mha_masked_uses_flash_path():
    """MultiHeadAttention with a mask and dropout=0 routes through the
    masked flash op and still matches the score-materializing path."""
    from mxnet_tpu.gluon.nn.transformer import MultiHeadAttention
    rng = np.random.RandomState(5)
    x = mx.nd.array(rng.randn(B, SEQ, 32).astype(np.float32))
    mask_np = np.asarray(_mask())
    mask = mx.nd.array(mask_np)

    att_flash = MultiHeadAttention(32, HEADS, dropout=0.0, use_flash=False)
    att_flash.initialize(ctx=mx.cpu())
    att_flash.hybridize()
    out1 = att_flash(x, mask).asnumpy()

    att_drop = MultiHeadAttention(32, HEADS, dropout=0.5, use_flash=False)
    att_drop.initialize(ctx=mx.cpu())
    # same weights; dropout path only activates in training mode
    from conftest import paired_params
    for p1, p2 in paired_params(att_flash, att_drop):
        p2.set_data(p1.data())
    out2 = att_drop(x, mask).asnumpy()
    np.testing.assert_allclose(out1, out2, rtol=2e-4, atol=2e-5)
