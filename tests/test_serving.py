"""Serving tier tests (ISSUE 8): model registry sources, per-bucket AOT
executor pool + persistent compile cache, dynamic batcher semantics
(batching, padding, timeout, shedding, drain), and the serving.* SLO
telemetry surface incl. the summarize CLI's percentile columns."""
import json
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, serving, telemetry
from mxnet_tpu.serving import (RequestTimeout, ServableClosed,
                               ServingQueueFull)


def _mlp(out=4):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(out))
    net.initialize(force_reinit=True)
    net.hybridize()
    net(mx.nd.array(np.zeros((1, 8), np.float32)))
    return net


def _convnet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, 3, padding=1, activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.Flatten(),
            gluon.nn.Dense(5))
    net.initialize(force_reinit=True)
    net.hybridize()
    net(mx.nd.array(np.zeros((1, 3, 8, 8), np.float32)))
    return net


@pytest.fixture()
def registry():
    reg = serving.ModelRegistry(compile_cache=False)
    yield reg
    reg.shutdown(drain=True)


@pytest.fixture()
def counters():
    telemetry.enable()
    telemetry.reset("serving.")
    yield telemetry
    telemetry.reset("serving.")
    telemetry.disable()


# ---------------------------------------------------------------------
# registry sources
# ---------------------------------------------------------------------

def test_register_block_numerics(registry):
    net = _mlp()
    s = registry.register("mlp", block=net, input_shape=(8,),
                          buckets=(1, 2), max_wait_ms=1)
    x = np.random.RandomState(0).rand(8).astype(np.float32)
    want = net(mx.nd.array(x[None])).asnumpy()[0]
    got = s.infer(x, timeout=10)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_register_symbol_params(registry, tmp_path):
    net = _convnet()
    x = np.random.RandomState(1).randn(3, 8, 8).astype(np.float32)
    want = net(mx.nd.array(x[None])).asnumpy()[0]
    prefix = str(tmp_path / "m")
    net.export(prefix)
    s = registry.register("sym", symbol=prefix + "-symbol.json",
                          params=prefix + "-0000.params",
                          input_shape=(3, 8, 8), buckets=(1,),
                          max_wait_ms=1)
    np.testing.assert_allclose(s.infer(x, timeout=10), want,
                               rtol=1e-4, atol=1e-4)
    assert s.source == "symbol"


def test_register_onnx(registry, tmp_path):
    from mxnet_tpu.onnx import export_model
    net = _convnet()
    x = np.random.RandomState(2).randn(3, 8, 8).astype(np.float32)
    want = net(mx.nd.array(x[None])).asnumpy()[0]
    prefix = str(tmp_path / "m")
    net.export(prefix)
    onnx_file = str(tmp_path / "m.onnx")
    export_model(prefix + "-symbol.json", prefix + "-0000.params",
                 in_shapes=[(1, 3, 8, 8)], onnx_file_path=onnx_file)
    s = registry.register("onnx", onnx=onnx_file, input_shape=(3, 8, 8),
                          buckets=(1, 4), max_wait_ms=1)
    np.testing.assert_allclose(s.infer(x, timeout=10), want,
                               rtol=1e-4, atol=1e-4)
    assert s.source == "onnx"


def test_register_checkpoint_manifest(registry, tmp_path):
    """The checkpoint source restores the newest INTACT manifest-
    verified step (PR 3 discovery) before serving."""
    net = _convnet()
    x = np.random.RandomState(3).randn(3, 8, 8).astype(np.float32)
    want = net(mx.nd.array(x[None])).asnumpy()[0]
    mgr = mx.checkpoint.CheckpointManager(str(tmp_path / "ckpts"))
    mgr.save_training(5, net)

    fresh = _convnet()                      # different random params
    assert not np.allclose(fresh(mx.nd.array(x[None])).asnumpy()[0],
                           want, atol=1e-4)
    s = registry.register("ckpt", block=fresh,
                          checkpoint=str(tmp_path / "ckpts"),
                          input_shape=(3, 8, 8), buckets=(1,),
                          max_wait_ms=1)
    np.testing.assert_allclose(s.infer(x, timeout=10), want,
                               rtol=1e-4, atol=1e-4)
    assert s.source == "checkpoint"


def test_register_validation(registry):
    net = _mlp()
    with pytest.raises(mx.MXNetError):           # no input_shape
        registry.register("a", block=net)
    with pytest.raises(mx.MXNetError):           # no source
        registry.register("a", input_shape=(8,))
    with pytest.raises(mx.MXNetError):           # two sources
        registry.register("a", block=net, onnx="x.onnx",
                          input_shape=(8,))
    with pytest.raises(mx.MXNetError):           # checkpoint needs block
        registry.register("a", checkpoint="/nope", input_shape=(8,))
    with pytest.raises(mx.MXNetError):
        registry.servable("never-registered")


def test_multi_tenant_registry(registry):
    a, b = _mlp(out=3), _mlp(out=6)
    registry.register("a", block=a, input_shape=(8,), buckets=(1, 2),
                      max_wait_ms=1)
    registry.register("b", block=b, input_shape=(8,), buckets=(1, 2),
                      max_wait_ms=1)
    assert registry.names() == ["a", "b"] and len(registry) == 2
    x = np.random.RandomState(4).rand(8).astype(np.float32)
    assert registry.infer("a", x, timeout=10).shape == (3,)
    assert registry.infer("b", x, timeout=10).shape == (6,)
    registry.unregister("a")
    assert "a" not in registry and "b" in registry


def test_multi_output_model(registry):
    class TwoHead(gluon.HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.a = gluon.nn.Dense(3)
                self.b = gluon.nn.Dense(2)

        def hybrid_forward(self, F, x):
            return self.a(x), self.b(x)

    net = TwoHead()
    net.initialize()
    net.hybridize()
    net(mx.nd.array(np.zeros((1, 8), np.float32)))
    s = serving.ModelRegistry(compile_cache=False).register(
        "two", block=net, input_shape=(8,), buckets=(1,), max_wait_ms=1)
    try:
        out = s.infer(np.ones(8, np.float32), timeout=10)
        assert isinstance(out, tuple) and len(out) == 2
        assert out[0].shape == (3,) and out[1].shape == (2,)
    finally:
        s.close()


# ---------------------------------------------------------------------
# executor pool: buckets, warm-up, compile cache
# ---------------------------------------------------------------------

def test_warmup_compiles_every_bucket_no_request_compile(registry):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(1, 2, 4), max_wait_ms=1)
    assert s._pool.compiled_buckets() == [1, 2, 4]

    def boom(bucket):
        raise AssertionError("request-path compile for bucket %d"
                             % bucket)
    s._pool._build = boom          # any post-warmup compile blows up
    got = s.infer(np.ones(8, np.float32), timeout=10)
    assert got.shape == (4,)


def test_bucket_padding_matches_unpadded_numerics(registry):
    """A 3-request micro-batch pads to bucket 4; the pad row must not
    leak into the real rows' outputs."""
    net = _mlp()
    s = registry.register("mlp", block=net, input_shape=(8,),
                          buckets=(4,), max_wait_ms=100, max_queue=16)
    rng = np.random.RandomState(5)
    xs = [rng.rand(8).astype(np.float32) for _ in range(3)]
    futs = [s.submit(x, timeout=10) for x in xs]
    for x, f in zip(xs, futs):
        want = net(mx.nd.array(x[None])).asnumpy()[0]
        np.testing.assert_allclose(f.result(timeout=10), want,
                                   rtol=1e-5, atol=1e-6)


def test_oversize_and_wrong_shape_rejected(registry):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(1, 2), max_wait_ms=1)
    with pytest.raises(mx.MXNetError):
        s.submit(np.ones((2, 8), np.float32))    # batched request
    with pytest.raises(mx.MXNetError):
        s.submit(np.ones(9, np.float32))         # wrong sample shape
    with pytest.raises(mx.MXNetError):
        s._pool.bucket_for(3)                    # beyond largest bucket


def test_compile_cache_roundtrip(tmp_path, counters):
    """Second process-equivalent registration (fresh registry, same
    cache dir) deserializes the committed artifacts -- hit counters
    move and numerics hold."""
    net = _mlp()
    x = np.random.RandomState(6).rand(8).astype(np.float32)
    want = net(mx.nd.array(x[None])).asnumpy()[0]
    reg1 = serving.ModelRegistry(cache_dir=str(tmp_path))
    reg1.register("mlp", block=net, input_shape=(8,), buckets=(1, 2),
                  max_wait_ms=1)
    reg1.shutdown()
    misses = telemetry.counter("serving.compile_cache_misses").value
    assert misses == 2                      # one per bucket

    reg2 = serving.ModelRegistry(cache_dir=str(tmp_path))
    s = reg2.register("mlp", block=net, input_shape=(8,),
                      buckets=(1, 2), max_wait_ms=1)
    try:
        assert telemetry.counter("serving.compile_cache_hits").value == 2
        np.testing.assert_allclose(s.infer(x, timeout=10), want,
                                   rtol=1e-5, atol=1e-6)
    finally:
        reg2.shutdown()


def test_compile_cache_corrupt_artifact_is_miss(tmp_path, counters):
    import os
    net = _mlp()
    reg1 = serving.ModelRegistry(cache_dir=str(tmp_path))
    reg1.register("mlp", block=net, input_shape=(8,), buckets=(1,),
                  max_wait_ms=1)
    reg1.shutdown()
    (artifact,) = [f for f in os.listdir(tmp_path)
                   if f.endswith(".mxe")]
    with open(tmp_path / artifact, "wb") as f:
        f.write(b"\x00garbage")
    telemetry.reset("serving.")
    reg2 = serving.ModelRegistry(cache_dir=str(tmp_path))
    s = reg2.register("mlp", block=net, input_shape=(8,), buckets=(1,),
                      max_wait_ms=1)
    try:
        assert telemetry.counter("serving.compile_cache_hits").value == 0
        assert s.infer(np.ones(8, np.float32), timeout=10).shape == (4,)
    finally:
        reg2.shutdown()


def test_stablehlo_fingerprint_normalizes_volatile_parts():
    text1 = ('module @jit_fn1 attributes {x = 1} {\n'
             '  %0 = stablehlo.add %a, %b : tensor<2xf32> loc(#loc3)\n'
             '}\n#loc3 = loc("file.py":10:2)\n')
    text2 = ('module @jit_other attributes {x = 1} {\n'
             '  %0 = stablehlo.add %a, %b : tensor<2xf32> loc(#loc7)\n'
             '}\n#loc7 = loc("elsewhere.py":99:1)\n')
    text3 = text1.replace("2xf32", "4xf32")
    fp = serving.stablehlo_fingerprint
    assert fp(text1) == fp(text2)
    assert fp(text1) != fp(text3)


def test_servable_fingerprints_per_bucket(registry):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(1, 2), max_wait_ms=1)
    f1, f2 = s.fingerprint(1), s.fingerprint(2)
    assert f1 and f2 and f1 != f2


# ---------------------------------------------------------------------
# dynamic batcher semantics
# ---------------------------------------------------------------------

def test_concurrent_requests_batch_dynamically(registry, counters):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(1, 2, 4, 8), max_wait_ms=100,
                          max_queue=64)
    n = 8
    barrier = threading.Barrier(n)
    outs = [None] * n

    def client(i):
        barrier.wait()
        outs[i] = s.infer(np.full(8, i, np.float32), timeout=10)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert all(o is not None for o in outs)
    batches = telemetry.counter("serving.batches").value
    responses = telemetry.counter("serving.responses").value
    assert responses == n
    assert responses / batches > 1, "no dynamic batching happened"


def test_per_request_timeout_sheds_queued_request(registry, counters):
    """A request whose deadline passes while still queued resolves with
    RequestTimeout and never occupies a batch slot."""
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(8,), max_wait_ms=500, max_queue=16)
    fut = s.submit(np.ones(8, np.float32), timeout=0.02)
    with pytest.raises(RequestTimeout):
        fut.result(timeout=10)
    assert telemetry.counter("serving.timeouts").value == 1
    assert telemetry.counter("serving.batches").value == 0


def test_queue_full_sheds_with_backpressure(registry, counters):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(1,), max_wait_ms=1, max_queue=2)
    gate = threading.Event()
    started = threading.Event()
    orig = s._pool.call

    def slow(bucket, x):
        started.set()
        gate.wait(20)
        return orig(bucket, x)

    s._pool.call = slow
    x = np.ones(8, np.float32)
    first = s.submit(x, timeout=None)
    assert started.wait(10)        # worker is busy inside dispatch
    q1 = s.submit(x)               # queue: 1
    q2 = s.submit(x)               # queue: 2 == max_queue
    with pytest.raises(ServingQueueFull):
        s.submit(x)                # shed
    assert telemetry.counter("serving.shed").value == 1
    gate.set()
    for f in (first, q1, q2):      # backlogged requests still complete
        assert f.result(timeout=20) is not None


def test_graceful_drain_loses_no_responses(registry):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(4,), max_wait_ms=2000, max_queue=64)
    futs = [s.submit(np.full(8, i, np.float32)) for i in range(10)]
    s.close(drain=True)            # returns after the queue is drained
    for f in futs:
        assert f.result(timeout=0.5) is not None
    with pytest.raises(ServableClosed):
        s.submit(np.ones(8, np.float32))


def test_close_without_drain_resolves_pending_as_closed(registry):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(4,), max_wait_ms=2000, max_queue=64)
    futs = [s.submit(np.ones(8, np.float32)) for _ in range(3)]
    s.close(drain=False)
    resolved = 0
    for f in futs:
        try:
            f.result(timeout=0.5)
            resolved += 1
        except ServableClosed:
            resolved += 1
    assert resolved == 3           # every future resolved, none dropped


def test_reregister_replaces_and_drains_old(registry):
    net1, net2 = _mlp(), _mlp()
    registry.register("m", block=net1, input_shape=(8,), buckets=(1,),
                      max_wait_ms=1)
    old = registry.servable("m")
    registry.register("m", block=net2, input_shape=(8,), buckets=(1,),
                      max_wait_ms=1)
    assert old.closed
    x = np.random.RandomState(7).rand(8).astype(np.float32)
    want = net2(mx.nd.array(x[None])).asnumpy()[0]
    np.testing.assert_allclose(registry.infer("m", x, timeout=10), want,
                               rtol=1e-5, atol=1e-6)


def test_dispatch_error_fails_requests_not_worker(registry):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(1,), max_wait_ms=1, max_queue=8)

    def boom(bucket, x):
        raise RuntimeError("device fell over")

    orig = s._pool.call
    s._pool.call = boom
    with pytest.raises(RuntimeError):
        s.infer(np.ones(8, np.float32), timeout=10)
    s._pool.call = orig            # worker survived; serving resumes
    assert s.infer(np.ones(8, np.float32), timeout=10).shape == (4,)


# ---------------------------------------------------------------------
# SLO telemetry + summarize CLI
# ---------------------------------------------------------------------

def test_serving_telemetry_instruments(registry, counters):
    s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                          buckets=(1, 2), max_wait_ms=1)
    for _ in range(3):
        s.infer(np.ones(8, np.float32), timeout=10)
    reg = telemetry.registry()
    assert reg.counter("serving.requests").value == 3
    assert reg.counter("serving.responses").value == 3
    assert reg.timer("serving.latency").count == 3
    assert reg.timer("serving.dispatch_time").count >= 1
    assert reg.counter("serving.models").value == 1
    assert reg.timer("serving.warmup_time").count == 1
    assert reg.gauge("serving.batch_occupancy").value >= 1


def test_summarize_serving_section_and_percentiles(registry, counters,
                                                   tmp_path):
    from mxnet_tpu.telemetry import cli as tcli
    path = str(tmp_path / "run.jsonl")
    telemetry.attach_jsonl(path)
    try:
        s = registry.register("mlp", block=_mlp(), input_shape=(8,),
                              buckets=(1, 2), max_wait_ms=1)
        for _ in range(5):
            s.infer(np.ones(8, np.float32), timeout=10)
        telemetry.flush()
    finally:
        telemetry._jsonl_sink.close()
    agg = tcli.summarize_file(path)
    sv = agg["serving"]
    assert sv["requests"] == 5 and sv["responses"] == 5
    assert sv["mean_occupancy"] >= 1
    assert sv["shed"] == 0 and sv["timeouts"] == 0
    for k in ("latency_p50_s", "latency_p95_s", "latency_p99_s"):
        assert sv[k] is not None and sv[k] > 0
    assert sv["latency_p50_s"] <= sv["latency_p99_s"]
    assert sv["qps"] is None or sv["qps"] > 0
    # the human rendering carries the serving line + percentile columns
    text = tcli._render_human(agg)
    assert "serving:" in text and "p50" in text and "p99" in text
    # machine shape is json-serializable end to end
    json.dumps(agg)


def test_timer_percentiles_live_snapshot():
    from mxnet_tpu.telemetry.core import Registry
    reg = Registry()
    t = reg.timer("t")
    for v in (0.001, 0.002, 0.004, 0.1):
        t.observe(v)
    snap = t.snapshot()
    assert snap["p50"] is not None
    assert snap["p50"] <= snap["p95"] <= snap["p99"]
    assert snap["p99"] <= snap["max"]
    assert t.percentile(0.5) >= snap["min"]
    # empty timer: no percentiles, no crash
    t2 = reg.timer("t2")
    assert t2.percentile(0.5) is None
    assert t2.snapshot()["p50"] is None


def test_summary_table_has_percentile_columns():
    from mxnet_tpu.telemetry.core import Registry
    from mxnet_tpu.telemetry.sinks import summary_table
    reg = Registry()
    for v in (0.01, 0.02, 0.03):
        reg.timer("lat").observe(v)
    table = summary_table(reg.snapshot())
    assert "p50" in table and "p95" in table and "p99" in table


def test_queue_depth_and_idle_worker_under_tsan():
    """The batcher's worker waits in bounded slices, so an idle
    servable under MXNET_TPU_TSAN=1 never trips the untimed-wait
    deadlock watchdog."""
    from mxnet_tpu import sync
    sync.enable(watchdog_s=60)
    try:
        reg = serving.ModelRegistry(compile_cache=False)
        s = reg.register("mlp", block=_mlp(), input_shape=(8,),
                         buckets=(1,), max_wait_ms=1)
        time.sleep(0.3)            # idle under the sanitizer
        assert s.queue_depth() == 0
        assert s.infer(np.ones(8, np.float32), timeout=10) is not None
        reg.shutdown(drain=True)
    finally:
        sync.disable()
        sync.reset_state()
