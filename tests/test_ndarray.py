"""NDArray API behavior (reference: ``tests/python/unittest/test_ndarray.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = mx.nd.zeros((2, 3))
    assert a.shape == (2, 3) and a.dtype == np.float32
    b = mx.nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    c = mx.nd.array([[1, 2], [3, 4]])
    assert_almost_equal(c, np.array([[1, 2], [3, 4]], np.float32))
    d = mx.nd.full((2, 2), 7.0)
    assert d.asnumpy().ravel().tolist() == [7, 7, 7, 7]
    e = mx.nd.arange(0, 10, 2)
    assert e.asnumpy().tolist() == [0, 2, 4, 6, 8]


def test_float64_downcast():
    a = mx.nd.array(np.zeros((2, 2), dtype=np.float64))
    assert a.dtype == np.float32


def test_arithmetic():
    a = mx.nd.array([[1., 2.], [3., 4.]])
    b = mx.nd.array([[10., 20.], [30., 40.]])
    assert_almost_equal(a + b, [[11, 22], [33, 44]])
    assert_almost_equal(b - a, [[9, 18], [27, 36]])
    assert_almost_equal(a * 2 + 1, [[3, 5], [7, 9]])
    assert_almost_equal(1 / a, [[1, .5], [1 / 3, .25]])
    assert_almost_equal(a ** 2, [[1, 4], [9, 16]])
    assert_almost_equal(-a, [[-1, -2], [-3, -4]])


def test_inplace_ops():
    a = mx.nd.ones((2, 2))
    a += 1
    assert_almost_equal(a, np.full((2, 2), 2.0))
    a *= 3
    assert_almost_equal(a, np.full((2, 2), 6.0))
    a /= 2
    assert_almost_equal(a, np.full((2, 2), 3.0))
    a -= 1
    assert_almost_equal(a, np.full((2, 2), 2.0))


def test_indexing():
    a = mx.nd.array(np.arange(12).reshape(3, 4))
    assert_almost_equal(a[1], np.arange(4, 8))
    assert_almost_equal(a[0:2, 1], np.array([1, 5]))
    idx = mx.nd.array([0, 2], dtype="int32")
    assert_almost_equal(a[idx], np.arange(12).reshape(3, 4)[[0, 2]])


def test_setitem():
    a = mx.nd.zeros((3, 3))
    a[1] = 5.0
    assert a.asnumpy()[1].tolist() == [5, 5, 5]
    a[0, 0] = 1.0
    assert a.asnumpy()[0, 0] == 1
    a[:] = 2.0
    assert (a.asnumpy() == 2).all()
    b = mx.nd.ones((3,))
    a[2] = b * 4
    assert a.asnumpy()[2].tolist() == [4, 4, 4]


def test_shape_methods():
    a = mx.nd.array(np.arange(24).reshape(2, 3, 4))
    assert a.reshape(6, 4).shape == (6, 4)
    assert a.reshape((-1, 4)).shape == (6, 4)
    assert a.reshape(0, -1).shape == (2, 12)
    assert a.transpose().shape == (4, 3, 2)
    assert a.transpose((0, 2, 1)).shape == (2, 4, 3)
    assert a.flatten().shape == (2, 12)
    assert a.expand_dims(0).shape == (1, 2, 3, 4)
    assert a.swapaxes(0, 2).shape == (4, 3, 2)
    assert a.T.shape == (4, 3, 2)


def test_mxnet_reshape_codes():
    a = mx.nd.zeros((2, 3, 4))
    assert a.reshape((0, -3)).shape == (2, 12)
    assert a.reshape((-2,)).shape == (2, 3, 4)
    assert a.reshape((-4, 1, 2, 0, 0)).shape == (1, 2, 3, 4)


def test_reductions():
    a = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    assert a.sum().asscalar() == 15
    assert_almost_equal(a.sum(axis=0), [3, 5, 7])
    assert_almost_equal(a.mean(axis=1), [1, 4])
    assert a.max().asscalar() == 5
    assert a.min().asscalar() == 0
    assert a.argmax(axis=1).asnumpy().tolist() == [2, 2]
    assert_almost_equal(a.norm(), np.sqrt((np.arange(6) ** 2).sum()), rtol=1e-4)


def test_comparison():
    a = mx.nd.array([1., 2., 3.])
    b = mx.nd.array([2., 2., 2.])
    assert (a == b).asnumpy().tolist() == [0, 1, 0]
    assert (a > b).asnumpy().tolist() == [0, 0, 1]
    assert (a <= b).asnumpy().tolist() == [1, 1, 0]


def test_scalar_conversion():
    assert float(mx.nd.array([3.5])) == 3.5
    assert int(mx.nd.array([3])) == 3
    assert mx.nd.array([[7.0]]).asscalar() == 7.0
    with pytest.raises(Exception):
        mx.nd.ones((2, 2)).asscalar()


def test_copy_context():
    a = mx.nd.ones((2, 2), ctx=mx.cpu())
    assert a.context == mx.cpu(0)
    b = a.copy()
    b[:] = 0
    assert (a.asnumpy() == 1).all()
    c = a.as_in_context(mx.cpu(0))
    assert c is a


def test_astype():
    a = mx.nd.ones((2,), dtype="float32")
    assert a.astype("int32").dtype == np.int32
    assert a.astype(np.float16).dtype == np.float16


def test_save_load(tmp_path):
    fname = str(tmp_path / "x.params")
    d = {"w": mx.nd.array(np.random.randn(3, 4)),
         "b": mx.nd.arange(0, 5, dtype="int32")}
    mx.nd.save(fname, d)
    ld = mx.nd.load(fname)
    assert sorted(ld) == ["b", "w"]
    assert_almost_equal(ld["w"], d["w"])
    assert ld["b"].dtype == np.int32
    mx.nd.save(fname, [mx.nd.ones((2,))])
    lst = mx.nd.load(fname)
    assert isinstance(lst, list) and len(lst) == 1


def test_concat_stack():
    a, b = mx.nd.ones((2, 3)), mx.nd.zeros((2, 3))
    assert mx.nd.concat(a, b, dim=0).shape == (4, 3)
    assert mx.nd.concat(a, b, dim=1).shape == (2, 6)
    assert mx.nd.stack(a, b, axis=0).shape == (2, 2, 3)


def test_waitall():
    a = mx.nd.ones((8, 8))
    for _ in range(5):
        a = mx.nd.dot(a, a)
    mx.nd.waitall()
    a.wait_to_read()
