"""Fleet observability plane tests (ISSUE 17): endpoint discovery
(atomic publish, dead-pid sweep, generation replacement), the scrape
client's failure modes (refused, mid-read death, garbage JSON, wrong
schema, hang), histogram-merge percentile correctness (and the proof
that averaging p99s is wrong), the burn-rate alert state machine
(fast AND slow to fire, sustained recovery to resolve, holddown-
bounded flapping), FleetMonitor aggregation + down/back transitions
over fake replicas, /alertz + the statusz fleet row, the Features
FLEET flip, and the `mxtelemetry fleet` exit-code contract."""
import json
import os
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

import mxnet_tpu as mx
from mxnet_tpu import obs, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.obs import alerts, fleet
from mxnet_tpu.obs.fleet import (FleetMonitor, MergedHistogram,
                                 SchemaMismatch, ScrapeError)
from mxnet_tpu.telemetry import cli as tcli
from mxnet_tpu.telemetry.core import _TIMER_BUCKETS
from mxnet_tpu.telemetry.sinks import prom_text


@pytest.fixture(autouse=True)
def _clean_fleet(monkeypatch):
    """Fleet state is process-global by design (published endpoints,
    live monitors, the obs server singleton): start and end clean."""
    monkeypatch.delenv("MXNET_TPU_OBS_ENDPOINTS_DIR", raising=False)
    telemetry.disable()
    telemetry.registry().clear()
    obs.status.reset()
    fleet._published.clear()
    for m in list(fleet._monitors):
        m.close()
    yield
    for m in list(fleet._monitors):
        try:
            m.close()
        except Exception:
            pass
    fleet._published.clear()
    obs.server.stop()
    obs.status.reset()
    telemetry.disable()
    telemetry.registry().clear()


def _bucketize(samples):
    """{le-string: n} per-bucket counts the way Timer.snapshot lays
    them out."""
    import bisect
    out = {}
    for s in samples:
        idx = min(bisect.bisect_left(_TIMER_BUCKETS, s),
                  len(_TIMER_BUCKETS) - 1)
        key = "%g" % _TIMER_BUCKETS[idx]
        out[key] = out.get(key, 0) + 1
    return out


class _FakeReplica:
    """A minimal obs-server stand-in with scriptable failure modes, so
    one test process can host a whole fleet."""

    def __init__(self, rank=0, generation=0, pid=None):
        self.rank = rank
        self.generation = generation
        self.pid = os.getpid() if pid is None else pid
        self.schema = "mxstatusz.v1"
        self.mode = "ok"     # ok|garbage|wrong_schema|partial|hang
        self.ready = True
        self.requests = 0
        self.responses = 0
        self.shed = 0
        self.errors = 0
        self.timeouts = 0
        self.served_step = 0
        self.queue_depth = 0
        self.latency = {}            # per-bucket {le-string: n}
        self.per_scrape = None       # called before each /metrics
        rep = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):
                pass

            def _send(self, code, body, ctype="application/json"):
                data = body.encode() if isinstance(body, str) else body
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):
                if rep.mode == "hang":
                    time.sleep(3.0)
                    return
                if rep.mode == "partial":
                    self.send_response(200)
                    self.send_header("Content-Length", "4096")
                    self.end_headers()
                    self.wfile.write(b'{"truncated')
                    self.wfile.flush()
                    self.connection.close()
                    return
                if self.path == "/healthz":
                    self._send(200 if rep.ready else 503, json.dumps(
                        {"status": "READY" if rep.ready
                         else "NOT_READY", "reasons": []}))
                elif self.path == "/statusz":
                    if rep.mode == "garbage":
                        self._send(200, "{definitely not json")
                    else:
                        self._send(200, json.dumps(rep.statusz()))
                elif self.path == "/metrics":
                    if rep.per_scrape is not None:
                        rep.per_scrape(rep)
                    self._send(200, rep.metrics_text(),
                               ctype="text/plain")
                else:
                    self._send(404, "{}")

        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.srv.daemon_threads = True
        self.port = self.srv.server_address[1]
        self.url = "http://127.0.0.1:%d" % self.port
        self._thread = threading.Thread(target=self.srv.serve_forever,
                                        daemon=True)
        self._thread.start()

    def add_latency(self, seconds, n=1):
        for key, cnt in _bucketize([seconds] * n).items():
            self.latency[key] = self.latency.get(key, 0) + cnt

    def statusz(self):
        return {
            "schema": ("bogus.v9" if self.mode == "wrong_schema"
                       else self.schema),
            "pid": self.pid, "rank": self.rank,
            "generation": self.generation, "ready": self.ready,
            "served_step": self.served_step, "published_step": None,
            "servables": [{"name": "m", "queue_depth": self.queue_depth,
                           "queue_capacity": 64}],
            "goodput": None,
        }

    def metrics_text(self):
        count = sum(self.latency.values())
        snap = [
            {"kind": "counter", "name": "serving.requests",
             "value": self.requests},
            {"kind": "counter", "name": "serving.responses",
             "value": self.responses},
            {"kind": "counter", "name": "serving.shed",
             "value": self.shed},
            {"kind": "counter", "name": "serving.errors",
             "value": self.errors},
            {"kind": "counter", "name": "serving.timeouts",
             "value": self.timeouts},
            {"kind": "timer", "name": "serving.latency",
             "count": count, "sum": 0.0, "buckets": dict(self.latency)},
        ]
        return prom_text(snap)

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()
        self._thread.join(timeout=10)


# ---------------------------------------------------------------------
# endpoint discovery contract
# ---------------------------------------------------------------------

def test_publish_discover_remove_roundtrip(tmp_path):
    d = str(tmp_path)
    path = fleet.publish_endpoint(4242, dirpath=d, rank=3, generation=7)
    assert os.path.basename(path) == "r3.%d.json" % os.getpid()
    eps = fleet.discover(d)
    assert len(eps) == 1
    ep = eps[0]
    assert (ep.rank, ep.generation, ep.port, ep.pid) \
        == (3, 7, 4242, os.getpid())
    assert ep.url == "http://127.0.0.1:4242"
    fleet.remove_endpoint(path)
    assert fleet.discover(d) == []
    assert path not in fleet._published


def test_publish_is_noop_without_dir(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_OBS_ENDPOINTS_DIR", raising=False)
    assert fleet.publish_endpoint(1234) is None
    assert fleet._published == []


def test_discover_skips_garbage_and_foreign_files(tmp_path):
    d = str(tmp_path)
    fleet.publish_endpoint(1111, dirpath=d, rank=0, generation=0)
    (tmp_path / "r1.99999.json").write_text("{torn")   # garbage body
    (tmp_path / "README.txt").write_text("not an endpoint")
    eps = fleet.discover(d)
    assert [e.rank for e in eps] == [0]


def test_newest_generation_wins_per_rank(tmp_path):
    d = str(tmp_path)
    # a relaunched rank 0: old generation's file still present
    (tmp_path / ("r0.%d.json" % os.getpid())).write_text(json.dumps(
        {"pid": os.getpid(), "rank": 0, "generation": 0, "port": 1000,
         "started_at": 1.0}))
    (tmp_path / ("r0.%d.json" % (os.getpid() + 1))).write_text(
        json.dumps({"pid": os.getpid() + 1, "rank": 0, "generation": 1,
                    "port": 2000, "started_at": 2.0}))
    eps = fleet.discover(d)
    assert len(eps) == 1
    assert (eps[0].generation, eps[0].port) == (1, 2000)


def test_sweep_removes_dead_pid_endpoints_only(tmp_path):
    d = str(tmp_path)
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    dead = p.pid
    live = fleet.publish_endpoint(2222, dirpath=d, rank=0, generation=0)
    (tmp_path / ("r1.%d.json" % dead)).write_text(json.dumps(
        {"pid": dead, "rank": 1, "generation": 0, "port": 1,
         "started_at": 0.0}))
    removed = fleet.sweep_endpoints(d)
    assert [os.path.basename(r) for r in removed] \
        == ["r1.%d.json" % dead]
    assert os.path.exists(live)


def test_serve_publishes_and_stop_withdraws(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_TPU_OBS_ENDPOINTS_DIR", d)
    port = obs.serve(0)
    eps = fleet.discover(d)
    assert len(eps) == 1 and eps[0].port == port
    obs.server.stop()
    assert fleet.discover(d) == []


# ---------------------------------------------------------------------
# scrape client
# ---------------------------------------------------------------------

def test_scrape_happy_path_typed_snapshot():
    rep = _FakeReplica(rank=2, generation=1)
    try:
        rep.requests = 10
        rep.shed = 3
        rep.served_step = 40
        rep.queue_depth = 5
        rep.add_latency(0.010, n=4)
        snap = fleet.scrape(rep.url, timeout_s=2.0)
        assert snap.rank == 2 and snap.generation == 1
        assert snap.ready is True
        assert snap.served_step == 40
        assert snap.queue_depth == 5
        assert snap.counters["requests"] == 10.0
        assert snap.counters["shed"] == 3.0
        # prom buckets come back cumulative with a +Inf entry
        assert snap.latency[float("inf")] == 4
    finally:
        rep.close()


def test_scrape_not_ready_healthz_is_an_answer():
    rep = _FakeReplica()
    try:
        rep.ready = False
        snap = fleet.scrape(rep.url, timeout_s=2.0)
        assert snap.ready is False
    finally:
        rep.close()


def test_scrape_connection_refused_raises_scrape_error():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    with pytest.raises(ScrapeError):
        fleet.scrape("http://127.0.0.1:%d" % port, timeout_s=0.5)


def test_scrape_garbage_json_raises_scrape_error():
    rep = _FakeReplica()
    try:
        rep.mode = "garbage"
        with pytest.raises(ScrapeError):
            fleet.scrape(rep.url, timeout_s=2.0)
    finally:
        rep.close()


def test_scrape_mid_read_death_raises_scrape_error():
    rep = _FakeReplica()
    try:
        rep.mode = "partial"
        with pytest.raises(ScrapeError):
            fleet.scrape(rep.url, timeout_s=2.0)
    finally:
        rep.close()


def test_scrape_hang_bounded_by_timeout():
    rep = _FakeReplica()
    try:
        rep.mode = "hang"
        t0 = time.monotonic()
        with pytest.raises(ScrapeError):
            fleet.scrape(rep.url, timeout_s=0.3)
        assert time.monotonic() - t0 < 2.5
    finally:
        rep.mode = "ok"
        rep.close()


def test_scrape_rejects_unknown_schema_loudly():
    rep = _FakeReplica()
    try:
        rep.mode = "wrong_schema"
        with pytest.raises(SchemaMismatch) as ei:
            fleet.scrape(rep.url, timeout_s=2.0)
        assert "bogus.v9" in str(ei.value)
        assert "mxstatusz.v1" in str(ei.value)
    finally:
        rep.close()


def test_statusz_carries_schema_rank_generation(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_PROC_ID", "3")
    monkeypatch.setenv("MXNET_TPU_GENERATION", "2")
    sz = obs.status.statusz()
    assert sz["schema"] == "mxstatusz.v1"
    assert sz["rank"] == 3
    assert sz["generation"] == 2


def test_prom_text_emits_timer_quantile_series():
    telemetry.enable()
    t = telemetry.registry().timer("serving.latency")
    for _ in range(100):
        t.observe(0.004)
    text = prom_text(telemetry.registry().snapshot())
    assert 'mxnet_tpu_serving_latency{quantile="0.5"}' in text
    assert 'mxnet_tpu_serving_latency{quantile="0.99"}' in text
    # and the quantile lines carry the estimator's values
    values, buckets = fleet._parse_prom(text)
    assert buckets["mxnet_tpu_serving_latency"][float("inf")] == 100


# ---------------------------------------------------------------------
# histogram merge -- NEVER average percentiles
# ---------------------------------------------------------------------

def _exact_percentile(samples, q):
    samples = sorted(samples)
    n = len(samples)
    return samples[min(n - 1, max(0, int(round(q * n)) - 1))]


def test_merged_percentile_matches_pooled_within_estimator_bound():
    # replica A: 1000 fast requests (~1ms); replica B: 20 slow (~1s)
    a = [0.001 * (1 + 0.3 * ((i * 7) % 10) / 10.0) for i in range(1000)]
    b = [1.0 * (1 + 0.1 * ((i * 3) % 10) / 10.0) for i in range(20)]
    hist = MergedHistogram()
    hist.add_buckets(_bucketize(a))
    hist.add_buckets(_bucketize(b))
    assert hist.count == 1020
    pooled = a + b
    for q in (0.5, 0.95, 0.99):
        exact = _exact_percentile(pooled, q)
        est = hist.percentile(q)
        # the estimator returns the bucket's upper bound: correct
        # within one power-of-2 bucket
        assert exact <= est <= 2.01 * exact, (q, exact, est)


def test_averaged_p99_would_be_wrong():
    a = [0.001] * 1000
    b = [1.0] * 20
    ha, hb = MergedHistogram(), MergedHistogram()
    ha.add_buckets(_bucketize(a))
    hb.add_buckets(_bucketize(b))
    merged = MergedHistogram().merge(ha).merge(hb)
    # pooled p99: rank 1009.8 of 1020 lands in the slow tail
    exact = _exact_percentile(a + b, 0.99)
    assert exact == 1.0
    assert merged.percentile(0.99) >= 1.0
    # the average of per-replica p99s splits the difference -- off by
    # ~500x from the fast replica's truth and 2x from the pooled one
    averaged = (ha.percentile(0.99) + hb.percentile(0.99)) / 2.0
    assert averaged > 2.01 * 0.001          # nowhere near replica A
    assert not (exact <= averaged <= 2.01 * exact)  # outside the bound
    # while the merged estimator stays inside it
    assert exact <= merged.percentile(0.99) <= 2.01 * exact


def test_cumulative_to_per_bucket_and_delta():
    cum = {0.001: 5, 0.002: 9, float("inf"): 10}
    per = fleet._per_bucket(cum)
    assert per == {0.001: 5, 0.002: 4, float("inf"): 1}
    later = {0.001: 7, 0.002: 12, float("inf"): 14}
    delta = fleet._delta_hist(later, cum)
    # per-bucket diffs: (7-5), (5-4), (2-1)
    assert delta == {0.001: 2, 0.002: 1, float("inf"): 1}
    h = MergedHistogram()
    h.add_cumulative(cum)
    assert h.count == 10


# ---------------------------------------------------------------------
# alert state machine
# ---------------------------------------------------------------------

def test_rule_validation():
    with pytest.raises(MXNetError):
        alerts.Rule("x", threshold=1.0, metric="not_a_metric")
    with pytest.raises(MXNetError):
        alerts.Rule("p99_latency_ms", threshold=1.0, fast_s=60,
                    slow_s=30)


def test_parse_rules_defaults_overrides_and_loud_failures(monkeypatch):
    names = {r.name for r in alerts.parse_rules("")}
    assert names == {"p99_latency_ms", "shed_ratio", "error_ratio",
                     "replica_down"}
    rules = {r.name: r for r in alerts.parse_rules(
        '[{"name": "p99_latency_ms", "threshold": 250}]')}
    assert rules["p99_latency_ms"].threshold == 250.0
    assert rules["shed_ratio"].threshold == 0.05    # untouched default
    with pytest.raises(MXNetError):
        alerts.parse_rules("{not json")
    with pytest.raises(MXNetError):
        alerts.parse_rules('{"name": "x"}')         # not a list
    with pytest.raises(MXNetError):
        alerts.parse_rules('[{"threshold": 1}]')    # no name
    with pytest.raises(MXNetError):
        alerts.parse_rules('[{"name": "p99_latency_ms", "bogus": 1}]')
    with pytest.raises(MXNetError):
        alerts.parse_rules('[{"name": "brand_new"}]')  # no threshold
    monkeypatch.setenv("MXNET_TPU_OBS_ALERT_RULES",
                       '[{"name": "shed_ratio", "threshold": 0.5}]')
    rules = {r.name: r for r in alerts.parse_rules()}
    assert rules["shed_ratio"].threshold == 0.5


def _engine(**kw):
    rule = alerts.Rule("p99_latency_ms", threshold=100.0, fast_s=30.0,
                       slow_s=300.0, fast_burn=0.5, slow_burn=0.5,
                       resolve_s=60.0, holddown_s=120.0, **kw)
    return alerts.AlertEngine(rules=[rule]), rule


def test_firing_requires_fast_and_slow_windows():
    eng, _ = _engine()
    t = 1000.0
    # 10 minutes of clean history, one observation per 10s
    for i in range(60):
        eng.observe({"p99_latency_ms": 10.0}, now=t + 10 * i)
    t2 = t + 600
    # 30s of breaches: fast window saturates, slow window is still
    # diluted by the clean history -> pending, NOT firing
    changed = []
    for i in range(4):
        changed += eng.observe({"p99_latency_ms": 900.0},
                               now=t2 + 10 * i)
    states = [a.state for a in eng.active()]
    assert states == ["pending"]
    assert all(a.state == "pending" for a in changed)
    # keep breaching until the slow window burns too -> fires
    fired = None
    for i in range(4, 40):
        for a in eng.observe({"p99_latency_ms": 900.0},
                             now=t2 + 10 * i):
            if a.state == "firing":
                fired = a
    assert fired is not None
    assert "p99_latency_ms" in fired.reason
    assert eng.firing()[0] is fired


def test_blip_cancels_without_paging():
    eng, _ = _engine()
    t = 1000.0
    for i in range(60):
        eng.observe({"p99_latency_ms": 10.0}, now=t + 10 * i)
    t2 = t + 600
    # a 20s blip: enough to burn the fast window and open pending...
    eng.observe({"p99_latency_ms": 900.0}, now=t2)
    eng.observe({"p99_latency_ms": 900.0}, now=t2 + 10)
    assert [a.state for a in eng.active()] == ["pending"]
    # ...but it clears before the slow window burns -> cancelled
    changed = []
    for i in range(2, 9):
        changed += eng.observe({"p99_latency_ms": 10.0},
                               now=t2 + 10 * i)
    assert eng.active() == []
    assert any(a.state == "cancelled" for a in changed)
    assert eng.history()[-1]["state"] == "cancelled"
    assert eng.firing() == []


def test_resolve_requires_sustained_recovery():
    eng, rule = _engine()
    t = 1000.0
    for i in range(40):
        eng.observe({"p99_latency_ms": 900.0}, now=t + 10 * i)
    assert [a.state for a in eng.firing()] == ["firing"]
    t2 = t + 400
    # 30s clean < resolve_s (60): still firing
    for i in range(4):
        eng.observe({"p99_latency_ms": 10.0}, now=t2 + 10 * i)
    assert eng.firing() != []
    # sustained recovery past resolve_s -> resolved
    resolved = []
    for i in range(4, 12):
        resolved += [a for a in eng.observe({"p99_latency_ms": 10.0},
                                            now=t2 + 10 * i)
                     if a.state == "resolved"]
    assert len(resolved) == 1
    assert "recovered" in resolved[0].reason
    assert eng.firing() == [] and eng.active() == []
    assert eng.history()[-1]["state"] == "resolved"


def test_holddown_bounds_flapping():
    eng, rule = _engine()
    t = 1000.0
    for i in range(40):
        eng.observe({"p99_latency_ms": 900.0}, now=t + 10 * i)
    t2 = t + 400
    for i in range(12):
        eng.observe({"p99_latency_ms": 10.0}, now=t2 + 10 * i)
    assert eng.active() == []           # resolved
    resolved_at = t2 + 110
    # an immediate re-breach inside holddown_s (120) must NOT open a
    # new alert -- flap frequency is bounded
    eng.observe({"p99_latency_ms": 900.0}, now=resolved_at + 5)
    assert eng.active() == []
    # past the holddown it may alert again
    t3 = resolved_at + rule.holddown_s + 10
    eng.observe({"p99_latency_ms": 900.0}, now=t3)
    assert [a.state for a in eng.active()] == ["pending"]


def test_replica_down_fires_and_resolves_in_one_round():
    eng = alerts.AlertEngine(rules=[r for r in alerts.default_rules()
                                    if r.name == "replica_down"])
    t = 1000.0
    changed = eng.observe(
        {"replica_down": 1.0},
        detail={"replica_down": "rank 1 generation 0 (pid 7) died"},
        now=t)
    assert [a.state for a in changed] == ["pending", "firing"][1:] \
        or [a.state for a in changed][-1] == "firing"
    assert eng.firing()[0].reason.endswith(
        "rank 1 generation 0 (pid 7) died")
    # first healthy round resolves it (resolve_s=0)
    changed = eng.observe({"replica_down": 0.0}, now=t + 1)
    assert [a.state for a in changed] == ["resolved"]
    assert eng.firing() == []


def test_none_value_is_no_observation():
    eng, _ = _engine()
    assert eng.observe({"p99_latency_ms": None}, now=1.0) == []
    assert eng.active() == []


def test_history_ring_is_bounded():
    rule = alerts.Rule("replica_down", threshold=0.0, fast_s=0.0,
                       slow_s=0.0, resolve_s=0.0, holddown_s=0.0)
    eng = alerts.AlertEngine(rules=[rule], history=4)
    for i in range(20):
        eng.observe({"replica_down": 1.0}, now=float(i))
        eng.observe({"replica_down": 0.0}, now=float(i) + 0.5)
    assert len(eng.history()) == 4


def test_alertz_payload_shape():
    eng, _ = _engine()
    az = eng.alertz()
    assert az["schema"] == "mxalertz.v1"
    assert set(az) >= {"firing", "pending", "history", "rules"}
    assert az["rules"][0]["name"] == "p99_latency_ms"


def test_alert_transitions_publish_telemetry():
    telemetry.enable()
    eng = alerts.AlertEngine(rules=[r for r in alerts.default_rules()
                                    if r.name == "replica_down"])
    eng.observe({"replica_down": 1.0}, now=1.0)
    eng.observe({"replica_down": 0.0}, now=2.0)
    reg = telemetry.registry()
    assert reg.get("fleet.alert").count >= 2
    assert reg.get("fleet.alerts_firing").value == 0


# ---------------------------------------------------------------------
# FleetMonitor
# ---------------------------------------------------------------------

def _drain(rep, n_req=100, shed=0, errors=0, latency=()):
    """Advance a fake replica's lifetime counters as one scrape-window
    of traffic would."""
    rep.requests += n_req
    rep.responses += n_req - shed
    rep.shed += shed
    rep.errors += errors
    for s in latency:
        rep.add_latency(s)


def test_monitor_aggregates_two_replicas():
    r0 = _FakeReplica(rank=0, generation=0)
    r1 = _FakeReplica(rank=1, generation=0)
    mon = FleetMonitor([r0.url, r1.url], scrape_ms=50, retries=0)
    try:
        r0.served_step = 10
        r1.served_step = 4
        mon.poll_once()
        # second round: deltas exist
        _drain(r0, n_req=80, shed=20, latency=[0.001] * 50)
        _drain(r1, n_req=100, errors=10, latency=[1.0] * 10)
        time.sleep(0.02)
        snap = mon.poll_once()
        agg = snap["aggregate"]
        assert agg["replicas"] == 2 and agg["up"] == 2
        assert agg["qps"] is not None and agg["qps"] > 0
        # shed_ratio = 20 / (180 + 20); error_ratio = 10 / (160 + 10)
        assert agg["shed_ratio"] == pytest.approx(0.1)
        assert agg["error_ratio"] == pytest.approx(10.0 / 170.0)
        assert agg["served_step"]["skew"] == 6
        # merged p99 lands in the slow replica's tail, not an average
        assert agg["latency_ms"]["samples"] == 60
        assert agg["latency_ms"]["p99"] >= 1000.0
        assert agg["latency_ms"]["p50"] <= 2.1
        states = {r["rank"]: r["state"] for r in snap["replicas"]}
        assert states == {0: "ok", 1: "ok"}
    finally:
        mon.close()
        r0.close()
        r1.close()


def test_monitor_ttl_flip_down_and_back():
    rep = _FakeReplica(rank=0, generation=0)
    mon = FleetMonitor([rep.url], scrape_ms=50, ttl_s=0.5, retries=0,
                       timeout_s=0.5)
    try:
        t0 = time.time()
        mon.poll_once(now=t0)
        assert mon.last["replicas"][0]["state"] == "ok"
        # replica goes bad but data is still fresh: sick, not down
        rep.mode = "garbage"
        mon.poll_once(now=time.time())
        assert mon.last["replicas"][0]["state"] == "sick"
        assert mon.engine.firing() == []
        # stale past TTL => presumed down; replica_down fires naming
        # rank + generation within the round
        mon.poll_once(now=time.time() + 10.0)
        assert mon.last["replicas"][0]["state"] == "down"
        firing = mon.engine.firing()
        assert [a.rule for a in firing] == ["replica_down"]
        assert "rank 0" in firing[0].reason
        assert "generation 0" in firing[0].reason
        # recovery: next clean scrape flips it back and resolves
        # (the engine's clock must keep moving forward)
        rep.mode = "ok"
        mon.poll_once(now=time.time() + 11.0)
        assert mon.last["replicas"][0]["state"] == "ok"
        assert mon.engine.firing() == []
        assert mon.engine.history()[-1]["state"] == "resolved"
    finally:
        mon.close()
        rep.close()


def test_monitor_never_crashes_on_sick_replicas():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    refused = s.getsockname()[1]
    s.close()
    garbage = _FakeReplica(rank=1)
    garbage.mode = "garbage"
    wrong = _FakeReplica(rank=2)
    wrong.mode = "wrong_schema"
    mon = FleetMonitor(["http://127.0.0.1:%d" % refused, garbage.url,
                        wrong.url],
                       scrape_ms=50, retries=1, backoff_s=0.01,
                       timeout_s=0.5)
    try:
        for _ in range(3):
            snap = mon.poll_once()     # must not raise
        assert {r["state"] for r in snap["replicas"]} <= {"sick",
                                                          "down"}
        assert all(r["failures"] >= 1 for r in snap["replicas"])
    finally:
        mon.close()
        garbage.close()
        wrong.close()


def test_monitor_dead_pid_is_down_within_one_round(tmp_path):
    d = str(tmp_path)
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    (tmp_path / ("r0.%d.json" % p.pid)).write_text(json.dumps(
        {"pid": p.pid, "rank": 0, "generation": 3, "port": port,
         "started_at": 0.0}))
    mon = FleetMonitor(d, scrape_ms=50, ttl_s=60.0, retries=0,
                       timeout_s=0.3)
    try:
        mon.poll_once()      # one round: dead pid skips the TTL grace
        assert mon.last["replicas"][0]["state"] == "down"
        firing = mon.engine.firing()
        assert [a.rule for a in firing] == ["replica_down"]
        assert "generation 3" in firing[0].reason
    finally:
        mon.close()


def test_monitor_generation_replacement_resolves(tmp_path):
    """The supervisor-relaunch contract in miniature: rank 0's gen-0
    registration goes stale (dead pid), the gen-1 replica re-registers
    under the same rank, and the alert resolves."""
    d = str(tmp_path)
    p = subprocess.Popen([sys.executable, "-c", "pass"])
    p.wait()
    (tmp_path / ("r0.%d.json" % p.pid)).write_text(json.dumps(
        {"pid": p.pid, "rank": 0, "generation": 0, "port": 1,
         "started_at": 0.0}))
    mon = FleetMonitor(d, scrape_ms=50, retries=0, timeout_s=0.3)
    rep = None
    try:
        mon.poll_once()
        assert [a.rule for a in mon.engine.firing()] == ["replica_down"]
        # generation 1 lands: same rank, live pid, real server
        rep = _FakeReplica(rank=0, generation=1)
        os.remove(str(tmp_path / ("r0.%d.json" % p.pid)))
        (tmp_path / ("r0.%d.json" % os.getpid())).write_text(json.dumps(
            {"pid": os.getpid(), "rank": 0, "generation": 1,
             "port": rep.port, "started_at": 1.0}))
        mon.poll_once()
        assert mon.last["replicas"][0]["state"] == "ok"
        assert mon.last["replicas"][0]["generation"] == 1
        assert mon.engine.firing() == []
    finally:
        mon.close()
        if rep is not None:
            rep.close()


def test_monitor_background_thread_starts_and_closes():
    rep = _FakeReplica(rank=0)
    mon = FleetMonitor([rep.url], scrape_ms=30, retries=0)
    try:
        mon.start()
        deadline = time.monotonic() + 5.0
        while mon.rounds < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert mon.rounds >= 2
    finally:
        mon.close()
        rep.close()
    assert mon._thread is None
    assert mon not in fleet._monitors


def test_goodput_skew_generalized_across_replicas():
    r0 = _FakeReplica(rank=0)
    r1 = _FakeReplica(rank=1)
    gp_fast = {"steps": 10, "wall_s": 1.0,
               "categories": {"device_compute": {"per_step_s": 0.08},
                              "input_wait": {"per_step_s": 0.01}}}
    gp_slow = {"steps": 10, "wall_s": 3.0,
               "categories": {"device_compute": {"per_step_s": 0.08},
                              "input_wait": {"per_step_s": 0.21}}}
    r0.statusz_goodput = gp_fast
    r1.statusz_goodput = gp_slow
    orig = _FakeReplica.statusz

    def patched(rep):
        sz = orig(rep)
        sz["goodput"] = rep.statusz_goodput
        return sz

    _FakeReplica.statusz = patched
    mon = FleetMonitor([r0.url, r1.url], scrape_ms=50, retries=0)
    try:
        snap = mon.poll_once()
        skew = snap["aggregate"]["goodput_skew"]
        assert skew["max_over_median"] == pytest.approx(3.0)
        assert skew["straggler_ranks"] == [1]
        attr = skew["attribution"][0]
        assert attr["rank"] == 1 and attr["category"] == "input_wait"
    finally:
        _FakeReplica.statusz = orig
        mon.close()
        r0.close()
        r1.close()


def test_fleet_instruments_published():
    telemetry.enable()
    rep = _FakeReplica(rank=0)
    mon = FleetMonitor([rep.url], scrape_ms=50, retries=0)
    try:
        mon.poll_once()
        reg = telemetry.registry()
        assert reg.get("fleet.scrapes").value >= 1
        assert reg.get("fleet.replicas").value == 1
        assert reg.get("fleet.alerts_firing").value == 0
    finally:
        mon.close()
        rep.close()


# ---------------------------------------------------------------------
# wiring: /alertz, statusz fleet row, Features, env, supervisor
# ---------------------------------------------------------------------

def test_alertz_endpoint_and_statusz_fleet_row(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_TPU_OBS_ENDPOINTS_DIR", d)
    port = obs.serve(0)
    # no monitor yet: /alertz serves the empty shell
    az = json.load(urllib.request.urlopen(
        "http://127.0.0.1:%d/alertz" % port))
    assert az["schema"] == "mxalertz.v1" and az["monitors"] == 0
    mon = FleetMonitor(d, scrape_ms=50, retries=0)
    try:
        mon.poll_once()
        az = json.load(urllib.request.urlopen(
            "http://127.0.0.1:%d/alertz" % port))
        assert az["monitors"] == 1
        assert az["fleet"]["replicas"] == 1
        assert az["fleet"]["alerts_firing"] == 0
        assert [r["name"] for r in az["rules"]]
        sz = json.load(urllib.request.urlopen(
            "http://127.0.0.1:%d/statusz" % port))
        assert sz["fleet"] == {"replicas": 1, "up": 1, "down": 0,
                               "alerts_firing": 0}
    finally:
        mon.close()


def test_features_fleet_row(tmp_path, monkeypatch):
    from mxnet_tpu import runtime
    monkeypatch.delenv("MXNET_TPU_OBS_ENDPOINTS_DIR", raising=False)
    assert runtime.Features().is_enabled("FLEET") is False
    monkeypatch.setenv("MXNET_TPU_OBS_ENDPOINTS_DIR", str(tmp_path))
    assert runtime.Features().is_enabled("FLEET") is True


def test_env_vars_registered():
    from mxnet_tpu import env
    assert env.get("MXNET_TPU_OBS_ENDPOINTS_DIR") == ""
    assert env.get("MXNET_TPU_OBS_SCRAPE_MS") == 1000.0
    assert env.get("MXNET_TPU_OBS_ALERT_RULES") == ""
    doc = env.generate_doc()
    for name in ("MXNET_TPU_OBS_ENDPOINTS_DIR",
                 "MXNET_TPU_OBS_SCRAPE_MS",
                 "MXNET_TPU_OBS_ALERT_RULES"):
        assert name in doc


def test_supervisor_threads_endpoints_dir(tmp_path):
    from mxnet_tpu.supervisor import Supervisor
    sup = Supervisor([sys.executable, "-c", "pass"], 2,
                     max_restarts=0, grace_s=1.0,
                     endpoints_dir=str(tmp_path))
    env = sup._worker_env(3, 1, "127.0.0.1:1")
    assert env["MXNET_TPU_OBS_ENDPOINTS_DIR"] == str(tmp_path)
    assert env["MXNET_TPU_GENERATION"] == "3"
    assert env["MXNET_TPU_PROC_ID"] == "1"
    # and the base-env fallback path
    sup2 = Supervisor([sys.executable, "-c", "pass"], 1,
                      max_restarts=0, grace_s=1.0,
                      env={"MXNET_TPU_OBS_ENDPOINTS_DIR": "/x"})
    assert sup2._worker_env(0, 0, "c")["MXNET_TPU_OBS_ENDPOINTS_DIR"] \
        == "/x"


# ---------------------------------------------------------------------
# the CLI exit-code contract
# ---------------------------------------------------------------------

def test_cli_fleet_usage_errors_exit_2(tmp_path, capsys):
    rc = tcli.main(["fleet", str(tmp_path), "http://127.0.0.1:1"])
    assert rc == 2
    rc = tcli.main(["fleet", str(tmp_path / "missing")])
    assert rc == 2


def test_cli_fleet_healthy_exits_0(tmp_path, monkeypatch, capsys):
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_TPU_OBS_ENDPOINTS_DIR", d)
    obs.serve(0)
    rc = tcli.main(["fleet", d, "--rounds", "2", "--interval-ms", "50"])
    out = capsys.readouterr().out
    assert rc == 0
    assert "fleet: 1 replica(s), 1 up / 0 down" in out
    assert "alerts: 0 firing" in out


def test_cli_fleet_firing_exits_1(capsys):
    rep = _FakeReplica(rank=0)

    def traffic(r):
        r.requests += 80
        r.responses += 80
        r.shed += 20          # 20% shed >> the 5% SLO

    rep.per_scrape = traffic
    try:
        rc = tcli.main(["fleet", rep.url, "--rounds", "3",
                        "--interval-ms", "40"])
        out = capsys.readouterr().out
        assert rc == 1
        assert "shed_ratio" in out
    finally:
        rep.close()


def test_cli_fleet_nothing_scrapeable_exits_1(tmp_path, capsys):
    rc = tcli.main(["fleet", str(tmp_path)])
    assert rc == 1
    assert "no scrapeable replica" in capsys.readouterr().err


def test_cli_fleet_json_output(tmp_path, monkeypatch, capsys):
    d = str(tmp_path)
    monkeypatch.setenv("MXNET_TPU_OBS_ENDPOINTS_DIR", d)
    obs.serve(0)
    rc = tcli.main(["fleet", d, "--rounds", "1", "--json"])
    assert rc == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["fleet"]["aggregate"]["replicas"] == 1
    assert payload["alerts"]["schema"] == "mxalertz.v1"
