"""Distributed resilience tier tests (ISSUE 15): typed collective
failures with rank attribution, cross-process chaos replay
(MXNET_TPU_CHAOS_SPEC), the rank-death-safe sharded commit (manifest
never renamed past a dead rank -- the cross-rank manifest-last
invariant), and the elastic restart supervisor.

The multi-process tests spawn REAL gloo worlds (the recipe of
docs/distributed.md) but WITHOUT tools/launch.py's fail-fast teardown,
so a survivor gets to raise -- and assert on -- its typed
BarrierTimeout before anything kills it.
"""
import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import chaos, telemetry
from mxnet_tpu import distributed as dist
from mxnet_tpu.base import MXNetError
from mxnet_tpu.checkpoint import CheckpointManager, sharded
from mxnet_tpu.obs import status as obs_status
from mxnet_tpu.serving.loop import ContinuousTrainer
from mxnet_tpu.supervisor import Supervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def counters():
    telemetry.enable()
    yield telemetry
    telemetry.disable()


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.reset()
    yield
    chaos.disarm()
    chaos.reset()


@pytest.fixture()
def fake_world(monkeypatch):
    """A fake 2-rank coordination world: rank 0 is us, the KV store is
    an in-process dict with the real deadline/directory-delete
    semantics, and the lockstep state is reset around the test."""
    class FakeKV:
        def __init__(self):
            self.store = {}
            self.deleted = []

        def key_value_set_bytes(self, key, val):
            self.store[key] = bytes(val)

        def blocking_key_value_get_bytes(self, key, timeout_ms):
            if key in self.store:
                return self.store[key]
            raise RuntimeError(
                "DEADLINE_EXCEEDED: GetKeyValue() timed out with key: "
                "%s and duration: %dms" % (key, timeout_ms))

        def key_value_delete(self, key):
            self.deleted.append(key)
            if key.endswith("/"):      # directory semantics
                for k in [k for k in self.store if k.startswith(key)]:
                    del self.store[k]
            else:
                self.store.pop(key, None)

    kv = FakeKV()
    monkeypatch.setattr(dist, "world", lambda: (2, 0))
    monkeypatch.setattr(dist, "_client", lambda: kv)
    monkeypatch.setattr(dist, "_seq", [0])
    monkeypatch.setattr(dist, "_my_old_keys", [])
    monkeypatch.setattr(dist, "_PREV_GEN_SWEPT", [False])
    return kv


# ---------------------------------------------------------------------
# chaos spec: serialize, scope, replay (satellite: cross-process chaos)
# ---------------------------------------------------------------------

def test_make_spec_arm_from_spec_roundtrip():
    spec = chaos.make_spec(seed=7, rules=[
        {"point": "a.b", "action": "raise", "nth": 2},
        {"point": "c.d", "action": "kill", "rank": 1},
    ])
    assert chaos.arm_from_spec(spec, rank=0, generation=0) is True
    assert chaos.armed()
    chaos.fail_point("a.b")                    # hit 1: no fire
    with pytest.raises(chaos.ChaosInjected) as e:
        chaos.fail_point("a.b")                # hit 2: fires
    assert e.value.point == "a.b"
    # the kill rule is scoped to rank 1 -- rank 0 must not have it
    chaos.fail_point("c.d")


def test_spec_rules_scope_by_rank_and_generation():
    spec = chaos.make_spec(rules=[
        {"point": "p", "rank": 1},
        {"point": "q", "generation": 0},
        {"point": "r", "generation": 1},
    ])
    chaos.arm_from_spec(spec, rank=1, generation=1)
    with pytest.raises(chaos.ChaosInjected):
        chaos.fail_point("p")                  # rank matches
    chaos.fail_point("q")                      # generation 0 only: inert
    with pytest.raises(chaos.ChaosInjected):
        chaos.fail_point("r")                  # generation matches


def test_arm_from_spec_env_is_explicit_opt_in(monkeypatch):
    """MXNET_TPU_CHAOS_SPEC in the environment arms NOTHING by itself
    -- production stays env-inert; only the explicit harness call
    replays it (and picks rank/generation from the launcher env)."""
    spec = chaos.make_spec(rules=[{"point": "x.y", "rank": 1}])
    monkeypatch.setenv("MXNET_TPU_CHAOS_SPEC", spec)
    monkeypatch.setenv("MXNET_TPU_PROC_ID", "1")
    chaos.fail_point("x.y")                    # env alone: inert
    assert not chaos.armed()
    assert chaos.arm_from_spec() is True       # the explicit call
    with pytest.raises(chaos.ChaosInjected):
        chaos.fail_point("x.y")


def test_arm_from_spec_empty_and_bad_action():
    assert chaos.arm_from_spec("") is False
    assert chaos.arm_from_spec("   ") is False
    assert not chaos.armed()
    with pytest.raises(MXNetError):
        chaos.make_spec(rules=[{"point": "p", "action": "explode"}])
    with pytest.raises(MXNetError):
        chaos.make_spec(rules=[{"action": "raise"}])   # no point
    # dict actions decode
    chaos.arm_from_spec(chaos.make_spec(rules=[
        {"point": "s", "action": {"sleep": 0.0}},
        {"point": "t", "action": {"truncate": {"fname": "f", "keep": 4}}},
    ]))
    chaos.fail_point("s")                      # sleep(0) fires harmlessly


# ---------------------------------------------------------------------
# typed failures + bounded KV retry (satellite: _kv_get/wait_at_barrier)
# ---------------------------------------------------------------------

def test_typed_error_hierarchy_and_fields():
    e = dist.BarrierTimeout("boom", tag="ckpt_written", seq=4,
                            ranks=[1, 3], elapsed_s=6.0,
                            presumed_dead=[3])
    assert isinstance(e, dist.RankFailure)
    assert isinstance(e, MXNetError)
    assert e.tag == "ckpt_written" and e.seq == 4
    assert e.ranks == (1, 3) and e.presumed_dead == (3,)
    assert e.elapsed_s == 6.0


def test_kv_attempt_retries_transient_then_succeeds(counters):
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise RuntimeError("UNAVAILABLE: socket closed")
        return 7

    assert dist._kv_attempt(flaky, "get:k", "test", 1) == 7
    assert len(calls) == 3
    # the tolerated transients are survival-counted against the
    # host-collective fail point (the injected-AND-survived pair)
    assert chaos.stats()["survived"]["dist.collective"] == 2


def test_kv_attempt_deadline_is_not_retried():
    calls = []

    def dead():
        calls.append(1)
        raise RuntimeError("DEADLINE_EXCEEDED: GetKeyValue() timed out")

    with pytest.raises(dist._KVTimeout):
        dist._kv_attempt(dead, "get:k", "test", 1)
    assert len(calls) == 1                     # immediate, no retry


def test_kv_attempt_exhausted_raises_rank_failure():
    def always():
        raise RuntimeError("UNAVAILABLE: nope")

    with pytest.raises(dist.RankFailure) as e:
        dist._kv_attempt(always, "set:k", "broadcast", 9)
    assert e.value.tag == "broadcast" and e.value.seq == 9
    assert "3 attempt(s)" in str(e.value)


def test_injected_fault_at_collective_is_absorbed_by_retry(counters):
    """A chaos RAISE at dist.collective sits INSIDE the retry domain:
    injected weather is tolerated exactly like real weather."""
    chaos.arm(0)
    chaos.on("dist.collective", nth=1, action=chaos.RAISE)
    assert dist._kv_attempt(lambda: 42, "get:k", "allreduce", 1) == 42
    st = chaos.stats()
    assert st["injected"]["dist.collective"] == 1
    assert st["survived"]["dist.collective"] == 1
    assert counters.counter("chaos.injected").value == 1


# ---------------------------------------------------------------------
# attributed barrier (fake world)
# ---------------------------------------------------------------------

def test_barrier_completes_when_peer_acks(fake_world, monkeypatch):
    monkeypatch.setenv("MXNET_TPU_DIST_BARRIER_TIMEOUT_MS", "2000")
    fake_world.store["mxbar/g0/t/1/1"] = b"ok"
    dist.barrier("t")                          # seq 1; must not raise
    assert "mxbar/g0/t/1/0" in fake_world.store


def test_barrier_timeout_names_missing_rank(fake_world, monkeypatch,
                                            counters):
    monkeypatch.setenv("MXNET_TPU_DIST_BARRIER_TIMEOUT_MS", "300")
    with pytest.raises(dist.BarrierTimeout) as e:
        dist.barrier("ckpt_written")
    err = e.value
    assert err.ranks == (1,)
    assert err.tag == "ckpt_written" and err.seq == 1
    assert err.elapsed_s is not None
    # no lease was ever beaten for rank 1 -> presumed dead
    assert err.presumed_dead == (1,)
    assert "rank(s) [1]" in str(err)
    assert counters.counter("dist.rank_failures").value == 1


def test_barrier_abort_ack_fails_fast_with_rank_failure(fake_world,
                                                        monkeypatch):
    monkeypatch.setenv("MXNET_TPU_DIST_BARRIER_TIMEOUT_MS", "5000")
    fake_world.store["mxbar/g0/ckpt_written/1/1"] = b"abort:IOError"
    t0 = time.monotonic()
    with pytest.raises(dist.RankFailure) as e:
        dist.barrier("ckpt_written")
    assert not isinstance(e.value, dist.BarrierTimeout)
    assert e.value.ranks == (1,)
    # the abort ack short-circuits: nowhere near the 5 s bound
    assert time.monotonic() - t0 < 2.0


def test_post_abort_consumes_lockstep_seq(fake_world):
    dist.post_abort("ckpt_written", reason="ChaosInjected")
    assert dist._seq[0] == 1
    assert fake_world.store["mxbar/g0/ckpt_written/1/0"] \
        .startswith(b"abort")


def test_generation_sweep_deletes_previous_gen_keys(fake_world,
                                                    monkeypatch):
    monkeypatch.setenv("MXNET_TPU_GENERATION", "2")
    monkeypatch.setenv("MXNET_TPU_DIST_BARRIER_TIMEOUT_MS", "300")
    fake_world.store["mxbar/g1/old/3/1"] = b"ok"
    fake_world.store["mxlive/g1/1"] = b"123.0"
    fake_world.store["mxbar/g2/t/1/1"] = b"ok"     # current gen: kept
    dist.barrier("t")
    assert "mxbar/g1/" in fake_world.deleted
    assert "mxlive/g1/" in fake_world.deleted
    assert "mxkv_ar/g1/" in fake_world.deleted
    assert not any(k.startswith("mxbar/g1/") for k in fake_world.store)
    # one-shot latch: a second barrier does not re-sweep
    ndel = len(fake_world.deleted)
    fake_world.store["mxbar/g2/t/2/1"] = b"ok"
    dist.barrier("t")
    assert not any(d == "mxbar/g1/" for d in fake_world.deleted[ndel:])


def test_lease_beat_age_and_stale(fake_world, monkeypatch):
    assert dist.beat_lease() is True
    assert "mxlive/g0/0" in fake_world.store
    age = dist.lease_age(0)
    assert age is not None and age < 5.0
    assert dist.lease_age(1) is None           # never beaten
    # backdate our own lease past the ttl
    fake_world.store["mxlive/g0/0"] = repr(time.time() - 60).encode()
    assert dist.stale_ranks(ttl_s=10.0) == [0, 1]
    assert dist.stale_ranks(ttl_s=10.0, ranks=[1]) == [1]


def test_lease_beater_is_none_single_process():
    assert dist.lease_beater() is None
    assert dist.beat_lease() is False


# ---------------------------------------------------------------------
# rank-death-safe sharded commit (single-process surface)
# ---------------------------------------------------------------------

def _params(scale=1.0):
    return {"w": mx.nd.array(np.arange(8, dtype=np.float32) * scale)}


def test_sharded_abort_on_injected_shard_write_fault(tmp_path, counters):
    """A RAISE at the shard write aborts the save CLEANLY: staging
    swept, commit_aborted counted, survived paired with the injecting
    point, and the manager keeps working afterwards."""
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save(1, {"params": _params()})
    chaos.arm(0)
    chaos.on("checkpoint.sharded.shard_write", nth=1, action=chaos.RAISE)
    with pytest.raises(chaos.ChaosInjected):
        mgr.save(2, {"params": _params(2.0)})
    chaos.disarm()
    assert mgr.latest_step() == 1
    assert not any(d.endswith(".shared.tmp")
                   for d in os.listdir(str(tmp_path)))
    assert counters.counter("checkpoint.commit_aborted").value == 1
    st = chaos.stats()
    assert st["injected"]["checkpoint.sharded.shard_write"] == 1
    assert st["survived"]["checkpoint.sharded.shard_write"] == 1
    chaos.reset()
    mgr.save(3, {"params": _params(3.0)})      # the manager recovered
    assert mgr.latest_step() == 3


def test_sharded_commit_kill_leaves_staging_next_manager_sweeps(
        tmp_path, counters):
    """A KILL at the merged-manifest commit (the coordinator dying) in
    a subprocess: exit 137, the shared staging dir is stranded WITHOUT
    a manifest inside the step namespace, the next manager init sweeps
    it (owner pid is dead), and discovery falls back one step."""
    code = r"""
import sys
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import chaos
from mxnet_tpu.checkpoint import CheckpointManager

root = sys.argv[1]
mgr = CheckpointManager(root, sharded=True)
p = {"w": mx.nd.array(np.arange(8, dtype=np.float32))}
mgr.save(1, {"params": p})
chaos.arm(0)
chaos.on("checkpoint.sharded.commit", nth=1, action=chaos.KILL)
mgr.save(2, {"params": p})
raise SystemExit("kill did not fire")
"""
    out = subprocess.run(
        [sys.executable, "-c", code, str(tmp_path)],
        capture_output=True, text=True, timeout=180,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")})
    assert out.returncode == 137, (out.stdout[-800:], out.stderr[-800:])
    leftovers = [d for d in os.listdir(str(tmp_path))
                 if d.endswith(".shared.tmp")]
    assert leftovers == ["step_00000002.shared.tmp"], leftovers
    mgr = CheckpointManager(str(tmp_path), sharded=True)  # init sweeps
    assert not any(d.endswith(".shared.tmp")
                   for d in os.listdir(str(tmp_path)))
    assert mgr.latest_step() == 1
    assert chaos.stats()["survived"][
        "checkpoint.sharded.shard_write"] >= 1   # the sweep's credit


def test_sweep_shared_staging_owner_liveness(tmp_path):
    root = str(tmp_path)
    # dead owner: a pid from an already-exited (reaped) subprocess
    proc = subprocess.Popen([sys.executable, "-c", "pass"])
    proc.wait()
    dead_pid = proc.pid
    d1 = os.path.join(root, "step_00000001.shared.tmp")
    os.makedirs(d1)
    open(os.path.join(d1, ".owner.%d" % dead_pid), "w").close()
    # live owner (us), with a dead rank's interior tmp crumb
    d2 = os.path.join(root, "step_00000002.shared.tmp")
    os.makedirs(d2)
    open(os.path.join(d2, ".owner.%d" % os.getpid()), "w").close()
    crumb = os.path.join(d2, "params.shard00001.params.%d.tmp"
                         % dead_pid)
    open(crumb, "w").close()
    # markerless dir (a pre-marker writer): swept
    d3 = os.path.join(root, "step_00000003.shared.tmp")
    os.makedirs(d3)
    removed = sharded.sweep_shared_staging(root)
    assert d1 in removed and d3 in removed
    assert os.path.isdir(d2) and not os.path.exists(crumb)
    assert crumb in removed


def test_disarmed_fail_points_make_zero_visits(tmp_path, monkeypatch):
    """The acceptance contract: disarmed chaos on the new sites costs
    ONE module-flag check -- a full sharded save makes zero calls into
    the chaos visit machinery."""
    from mxnet_tpu.chaos import core as chaos_core
    calls = []
    real_visit = chaos_core._visit
    monkeypatch.setattr(chaos_core, "_visit",
                        lambda *a: calls.append(a) or real_visit(*a))
    mgr = CheckpointManager(str(tmp_path), sharded=True)
    mgr.save(1, {"params": _params()})
    assert mgr.latest_step() == 1
    assert calls == []


def test_single_process_trainer_never_touches_leases(tmp_path,
                                                     monkeypatch):
    """Disabled-supervisor overhead contract: a single-process
    ContinuousTrainer binds no lease beater and makes zero calls into
    distributed.beat_lease (one attribute check per step)."""
    from mxnet_tpu.chaos import scenarios
    calls = []
    monkeypatch.setattr(dist, "beat_lease",
                        lambda: calls.append(1))
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data,
                           str(tmp_path / "ck"), publish_every=2)
    assert ct._lease_beat is None
    ct.run_steps(2)
    ct.close()
    assert calls == []


def test_publish_policy_continue_vs_raise(tmp_path):
    from mxnet_tpu.chaos import scenarios
    net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn, data,
                           str(tmp_path / "ck"), publish_every=1,
                           on_publish_error="continue")
    boom = dist.RankFailure("peer died", tag="ckpt_written", ranks=[1])
    fails = [2]                     # fail the publish of step 2 only

    real = ct.manager.save_training

    def flaky(step, *a, **kw):
        if step in fails:
            raise boom
        return real(step, *a, **kw)

    ct.manager.save_training = flaky
    with pytest.warns(RuntimeWarning, match="continuing past it"):
        ct.run_steps(3)             # survives the failed publish
    assert ct.step == 3
    assert ct.published_step == 3
    assert ct.manager.latest_step() == 3
    ct.close()

    # policy "raise" (the supervised default) surfaces the typed error
    ct2 = ContinuousTrainer(net, trainer, loss_fn, data,
                            str(tmp_path / "ck2"), publish_every=1)
    ct2.manager.save_training = flaky
    fails[0] = ct2.step + 2
    with pytest.raises(dist.RankFailure):
        ct2.run_steps(3)
    with pytest.raises(MXNetError):
        ContinuousTrainer(net, trainer, loss_fn, data,
                          str(tmp_path / "ck3"),
                          on_publish_error="shrug")


# ---------------------------------------------------------------------
# elastic restart supervisor
# ---------------------------------------------------------------------

def _gen_worker(fail_gen, fail_rank, exit_code=3):
    return (
        "import os,sys\n"
        "g=int(os.environ['MXNET_TPU_GENERATION'])\n"
        "r=int(os.environ['MXNET_TPU_PROC_ID'])\n"
        "print('WORKER g%%d r%%d' %% (g, r))\n"
        "sys.exit(%d if (g==%d and r==%d) else 0)\n"
        % (exit_code, fail_gen, fail_rank))


def test_supervisor_relaunches_with_bumped_generation(counters):
    obs_status.reset()
    sup = Supervisor([sys.executable, "-c", _gen_worker(0, 1)], 2,
                     max_restarts=2, grace_s=3)
    assert sup.run() == 0
    assert sup.restarts == 1 and sup.generation == 1
    assert not sup.generation_down
    assert counters.counter("supervisor.restarts").value == 1
    assert chaos.stats()["survived"]["supervisor.rank_exit"] == 1
    ready, reasons = obs_status.health()
    assert ready, reasons


def test_supervisor_budget_exhaustion_flips_healthz(counters):
    obs_status.reset()
    sup = Supervisor([sys.executable, "-c", "import sys; sys.exit(2)"],
                     2, max_restarts=1, grace_s=2)
    assert sup.run() == 2
    assert sup.exhausted and sup.generation_down
    assert sup.restarts == 1
    assert counters.counter("supervisor.budget_exhausted").value == 1
    ready, reasons = obs_status.health()
    assert not ready
    assert "restart_budget_exhausted:1" in reasons
    snap = obs_status.statusz()
    assert snap["supervisors"] == [{"generation": 1, "restarts": 1,
                                    "down": True, "exhausted": True}]
    obs_status.reset()


def test_launch_py_supervise_cli():
    worker = ("import os,sys;"
              "g=int(os.environ['MXNET_TPU_GENERATION']);"
              "r=int(os.environ['MXNET_TPU_PROC_ID']);"
              "print('W g%d r%d'%(g,r));"
              "sys.exit(5 if (g==0 and r==0) else 0)")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--supervise", "--max-restarts", "2",
         "--grace", "5", sys.executable, "-c", worker],
        capture_output=True, text=True, timeout=120,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", "")})
    assert out.returncode == 0, (out.stdout[-1500:], out.stderr[-500:])
    assert "relaunching generation 1" in out.stdout
    assert "[g1.0] W g1 r0" in out.stdout
    assert "[g1.1] W g1 r1" in out.stdout


# ---------------------------------------------------------------------
# REAL multi-process gloo scenarios (seeded cross-process chaos)
# ---------------------------------------------------------------------

_GLOO_PRELUDE = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")   # pin past TPU sitecustomize
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import chaos, telemetry
from mxnet_tpu import distributed as dist
from mxnet_tpu.checkpoint import CheckpointManager

outdir = sys.argv[1]
assert mx.distributed_init() is True
nproc, rank = dist.world()
telemetry.enable()
chaos.arm_from_spec()        # replay the launcher's seeded scenario
mgr = CheckpointManager(outdir + "/ckpts")
params = {"w": mx.nd.array(np.arange(8, dtype=np.float32))}
"""


def _spawn_world(tmp_path, script, n, extra_env, timeout=240):
    """Launch ``n`` ranks WITHOUT fail-fast teardown (unlike
    tools/launch.py) so survivors can finish their typed-error
    handling; returns ``[(rc, output), ...]`` by rank."""
    path = tmp_path / "worker.py"
    path.write_text(script)
    s = socket.socket()
    s.bind(("", 0))
    coord = "127.0.0.1:%d" % s.getsockname()[1]
    s.close()
    procs = []
    for rank in range(n):
        env = {**os.environ,
               "PYTHONPATH": REPO + os.pathsep
               + os.environ.get("PYTHONPATH", ""),
               "JAX_PLATFORMS": "cpu",
               "MXNET_TPU_COORDINATOR": coord,
               "MXNET_TPU_NUM_PROCS": str(n),
               "MXNET_TPU_PROC_ID": str(rank),
               "MXNET_TPU_DIST_BARRIER_TIMEOUT_MS": "6000",
               "MXNET_TPU_DIST_LEASE_TTL_S": "3",
               **extra_env}
        procs.append(subprocess.Popen(
            [sys.executable, "-u", str(path), str(tmp_path)],
            env=env, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True))
    out = []
    deadline = time.time() + timeout
    for p in procs:
        try:
            text, _ = p.communicate(timeout=max(1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            p.kill()
            text, _ = p.communicate()
        out.append((p.returncode, text))
    return out


_SURVIVOR_TAIL = r"""
try:
    mgr.save(2, {"params": params})
except dist.BarrierTimeout as e:
    assert 1 in e.ranks, e.ranks
    assert e.tag == EXPECT_TAG, e.tag
    assert 1 in e.presumed_dead, e.presumed_dead   # lease went stale
    assert e.elapsed_s is not None and e.elapsed_s < 10.0
    assert mgr.latest_step() == 1, mgr.all_steps()     # one-step fallback
    assert not os.path.isdir(mgr.step_dir(2)), "manifest committed!"
    assert not any(d.endswith(".shared.tmp")
                   for d in os.listdir(outdir + "/ckpts")), "staging left"
    assert telemetry.counter("checkpoint.commit_aborted").value == 1
    assert telemetry.counter("dist.rank_failures").value >= 1
    surv = chaos.stats()["survived"]
    assert surv.get(SURVIVED_POINT), surv
    print("SURVIVOR_OK rank=%d tag=%s ranks=%s dead=%s" % (
        rank, e.tag, list(e.ranks), list(e.presumed_dead)), flush=True)
    dist.failfast_exit(0)
raise SystemExit("kill did not fire (rank %d)" % rank)
"""


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_two_process_kill_mid_shard_write_gloo(tmp_path):
    """Chaos-KILL rank 1 mid-shard-write of step 2's save: the
    survivor raises a typed BarrierTimeout at the 'written' barrier
    NAMING rank 1 (presumed dead by its stale lease), the staging is
    swept, no manifest exists, and discovery falls back one step."""
    script = _GLOO_PRELUDE + r"""
mgr.save(1, {"params": params})
dist.barrier("step1_done")
assert mgr.latest_step() == 1
EXPECT_TAG = "ckpt_written"
SURVIVED_POINT = "checkpoint.sharded.barrier.written"
""" + _SURVIVOR_TAIL
    spec = chaos.make_spec(seed=0, rules=[
        {"point": "checkpoint.sharded.shard_write", "action": "kill",
         "nth": 2, "rank": 1}])
    results = _spawn_world(tmp_path, script, 2,
                           {"MXNET_TPU_CHAOS_SPEC": spec})
    assert results[1][0] == 137, results[1][1][-1500:]
    assert results[0][0] == 0, results[0][1][-1500:]
    assert "SURVIVOR_OK rank=0 tag=ckpt_written" in results[0][1]


@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_two_process_kill_between_barriers_gloo(tmp_path):
    """THE acceptance scenario: chaos-KILL rank 1 between the
    'written' and 'committed' barriers of step 2's sharded save.  The
    merged manifest was already STAGED by rank 0 -- but the commit
    gate means it is never renamed in: no step-2 dir exists, the
    survivor's typed BarrierTimeout names rank 1 within the bound,
    and latest_step() falls back one step."""
    script = _GLOO_PRELUDE + r"""
mgr.save(1, {"params": params})
dist.barrier("step1_done")
assert mgr.latest_step() == 1
EXPECT_TAG = "ckpt_committed"
SURVIVED_POINT = "checkpoint.sharded.barrier.committed"
""" + _SURVIVOR_TAIL
    spec = chaos.make_spec(seed=0, rules=[
        {"point": "checkpoint.sharded.barrier.committed",
         "action": "kill", "nth": 2, "rank": 1}])
    results = _spawn_world(tmp_path, script, 2,
                           {"MXNET_TPU_CHAOS_SPEC": spec})
    assert results[1][0] == 137, results[1][1][-1500:]
    assert results[0][0] == 0, results[0][1][-1500:]
    assert "SURVIVOR_OK rank=0 tag=ckpt_committed" in results[0][1]


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_four_process_kill_during_stage_gloo(tmp_path):
    """4-rank world, rank 2 killed AT the 'stage' rendezvous of the
    first save: every survivor aborts cleanly with a BarrierTimeout
    naming rank 2 and sweeps the staging."""
    script = _GLOO_PRELUDE + r"""
try:
    mgr.save(1, {"params": params})
except dist.BarrierTimeout as e:
    assert 2 in e.ranks, e.ranks
    assert e.tag == "ckpt_stage", e.tag
    assert telemetry.counter("checkpoint.commit_aborted").value == 1
    assert not any(d.endswith(".shared.tmp")
                   for d in os.listdir(outdir + "/ckpts"))
    surv = chaos.stats()["survived"]
    assert surv.get("checkpoint.sharded.barrier.stage"), surv
    print("SURVIVOR_OK rank=%d ranks=%s" % (rank, list(e.ranks)),
          flush=True)
    dist.failfast_exit(0)
raise SystemExit("kill did not fire (rank %d)" % rank)
"""
    spec = chaos.make_spec(seed=0, rules=[
        {"point": "checkpoint.sharded.barrier.stage", "action": "kill",
         "nth": 1, "rank": 2}])
    results = _spawn_world(tmp_path, script, 4,
                           {"MXNET_TPU_CHAOS_SPEC": spec})
    assert results[2][0] == 137, results[2][1][-1500:]
    for r in (0, 1, 3):
        assert results[r][0] == 0, (r, results[r][1][-1500:])
        assert "SURVIVOR_OK rank=%d" % r in results[r][1]


_ELASTIC_WORKER = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import mxnet_tpu as mx
from mxnet_tpu import chaos, telemetry
from mxnet_tpu import distributed as dist
from mxnet_tpu.chaos import scenarios
from mxnet_tpu.serving.loop import ContinuousTrainer

outdir = sys.argv[1]
assert mx.distributed_init() is True
nproc, rank = dist.world()
gen = dist.generation()
telemetry.enable()
chaos.arm_from_spec()            # generation-scoped: inert in gen 1

# identical replicated params on every rank (the SPMD contract the
# one-program path gets from its init-time broadcast): seed the init
np.random.seed(0)
mx.random.seed(0)
net, trainer, loss_fn, data = scenarios.train_fixtures(seed=0)
ct = ContinuousTrainer(net, trainer, loss_fn, data, outdir + "/ckpts",
                       publish_every=1)
ckpt = ct.resume()

def dump_params(tag):
    arrs = {k: p._reduce().asnumpy() for k, p in
            net._collect_params_with_prefix().items()}
    np.savez(outdir + "/%s_rank%d.npz" % (tag, rank), **arrs)

if gen == 0:
    assert ckpt is None
    ct.run_steps(1)                  # publish step 1 (verified)
    dump_params("step1")             # the bit-identical reference
    try:
        ct.run_steps(2)              # step-2 publish: rank 1 dies
    except dist.BarrierTimeout as e:
        assert 1 in e.ranks, e.ranks
        assert ct.manager.latest_step() == 1, ct.manager.all_steps()
        print("SURVIVOR_ABORT rank=%d %s: %s" % (
            rank, type(e).__name__, e), flush=True)
        dist.failfast_exit(3)        # surface to the supervisor
    raise SystemExit("kill did not fire (rank %d)" % rank)

assert gen == 1, gen
assert ckpt is not None and ckpt.step == 1, ckpt
side = np.load(outdir + "/step1_rank%d.npz" % rank)
for k, p in sorted(net._collect_params_with_prefix().items()):
    a = p.data().asnumpy()
    b = side[k]
    assert a.dtype == b.dtype and a.tobytes() == b.tobytes(), k
print("RESUME_BIT_IDENTICAL rank=%d generation=%d step=%d"
      % (rank, gen, ckpt.step), flush=True)
ct.run_steps(2)                      # steps 2..3 publish clean
# rank 0 renames AFTER the commit gate: rendezvous before reading
# (the read-after-save contract of checkpoint/sharded.py)
dist.barrier("gen1_steps_done")
assert ct.manager.latest_step() == 3, ct.manager.all_steps()
ct.close()
print("GEN1_DONE rank=%d" % rank, flush=True)
"""


@pytest.mark.slow
@pytest.mark.skipif(os.environ.get("MXNET_TPU_SKIP_DIST") == "1",
                    reason="dist tests disabled")
def test_supervised_elastic_restart_bit_identical_gloo(tmp_path):
    """The full ISSUE-15 loop through tools/launch.py --supervise:
    rank 1 chaos-KILLed between the 'written' and 'committed' barriers
    of step 2's publish (generation 0), the survivor aborts with a
    typed error and exits, the supervisor relaunches generation 1, and
    both ranks resume with parameters BIT-IDENTICAL to the last
    verified step."""
    worker = tmp_path / "elastic_worker.py"
    worker.write_text(_ELASTIC_WORKER)
    spec = chaos.make_spec(seed=0, rules=[
        {"point": "checkpoint.sharded.barrier.committed",
         "action": "kill", "nth": 2, "rank": 1, "generation": 0}])
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--supervise", "--max-restarts", "2",
         "--grace", "30",
         sys.executable, "-u", str(worker), str(tmp_path)],
        capture_output=True, text=True, timeout=420,
        env={**os.environ,
             "PYTHONPATH": REPO + os.pathsep
             + os.environ.get("PYTHONPATH", ""),
             "JAX_PLATFORMS": "cpu",
             "MXNET_TPU_CHAOS_SPEC": spec,
             "MXNET_TPU_DIST_BARRIER_TIMEOUT_MS": "8000",
             "MXNET_TPU_DIST_LEASE_TTL_S": "4"})
    assert out.returncode == 0, (out.stdout[-3000:], out.stderr[-800:])
    assert "SURVIVOR_ABORT rank=0 BarrierTimeout" in out.stdout
    assert "relaunching generation 1" in out.stdout
    assert "RESUME_BIT_IDENTICAL rank=0 generation=1 step=1" in out.stdout
    assert "RESUME_BIT_IDENTICAL rank=1 generation=1 step=1" in out.stdout
    assert out.stdout.count("GEN1_DONE") == 2
