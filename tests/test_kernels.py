"""Kernel tier (ISSUE 11): registry selection policy, fused BN+ReLU
numerics + vjp, flash-attention op-level pallas path (incl. the masked
backward), the bucket-flattened LARS/LAMB optimizer update, fallback
proof with Pallas monkeypatched unavailable, and the perf-audit
``remedy`` wiring.

Kernels run in interpret mode on the CPU test backend
(MXNET_TPU_KERNELS=1 + the registry's non-TPU policy); the same code
compiles on TPU.  Every numerics check is against the XLA reference
path and its autodiff.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, kernels
from mxnet_tpu.kernels import fused_bn_relu as fbr
from mxnet_tpu.kernels import optimizer_update as kopt
from mxnet_tpu.kernels import registry as kreg

pytestmark = pytest.mark.skipif(not kernels.available(),
                                reason="no pallas on this backend")


@pytest.fixture(autouse=True)
def _exact_matmuls():
    # kernel-vs-reference comparisons measure the algorithm, not the
    # CPU backend's reduced-precision matmul fast path
    with jax.default_matmul_precision("highest"):
        yield


@pytest.fixture()
def kernels_on(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_KERNELS", "1")


# ----------------------------------------------------------------------
# registry selection policy
# ----------------------------------------------------------------------

def test_registry_lists_the_three_kernels():
    names = kernels.list_kernels()
    for want in ("fused_bn_relu", "flash_attention", "bucket_optimizer"):
        assert want in names, names


def test_choose_off_mode_kills_everything(monkeypatch):
    monkeypatch.setenv("MXNET_TPU_KERNELS", "0")
    for name, kw in (("flash_attention",
                      dict(seq=512, block_q=256, block_k=256)),
                     ("fused_bn_relu", dict(axis=3, ndim=4)),
                     ("bucket_optimizer", {})):
        ch = kernels.choose(name, **kw)
        assert not ch.use_pallas and "MXNET_TPU_KERNELS=0" in ch.reason


def test_choose_auto_policy(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_KERNELS", raising=False)
    # flash below the measured crossover: declined regardless of backend
    ch = kernels.choose("flash_attention", seq=128, block_q=256,
                        block_k=256)
    assert not ch.use_pallas and "auto policy" in ch.reason
    # bucket optimizer is opt-in: auto never selects it
    assert not kernels.choose("bucket_optimizer").use_pallas
    # above the crossover on CPU: XLA fallback with the backend named
    if jax.default_backend() != "tpu":
        ch = kernels.choose("flash_attention", seq=512, block_q=256,
                            block_k=256)
        assert not ch.use_pallas and "backend" in ch.reason


def test_choose_forced_runs_interpret_off_tpu(kernels_on):
    ch = kernels.choose("fused_bn_relu", axis=3, ndim=4)
    assert ch.use_pallas
    if jax.default_backend() != "tpu":
        assert ch.interpret


def test_supports_gate_beats_force(kernels_on):
    # NCHW input: the NHWC-native kernel must decline even when forced
    ch = kernels.choose("fused_bn_relu", force=True, axis=1, ndim=4)
    assert not ch.use_pallas and "NHWC" in ch.reason
    # non-divisible seq: flash declines
    ch = kernels.choose("flash_attention", force=True, seq=100,
                        block_q=32, block_k=32)
    assert not ch.use_pallas and "divisible" in ch.reason


def test_fallback_when_pallas_unavailable(monkeypatch, kernels_on):
    """The fallback proof: with Pallas monkeypatched away, every choice
    lands on the XLA path and the fused op still computes correctly."""
    monkeypatch.setattr(kreg, "_has_pallas", lambda: False)
    ch = kernels.choose("fused_bn_relu", force=True, axis=3, ndim=4)
    assert not ch.use_pallas and "unavailable" in ch.reason
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 3, 3, 8).astype(np.float32))
    g = jnp.asarray(rng.rand(8).astype(np.float32) + 0.5)
    b = jnp.asarray(rng.randn(8).astype(np.float32))
    mm = jnp.zeros(8, jnp.float32)
    mv = jnp.ones(8, jnp.float32)
    out, _, _ = fbr.fused_bn_relu(x, g, b, mm, mv, fix_gamma=False,
                                  axis=3, training=True)
    ro, _, _ = fbr.xla_reference(x, g, b, mm, mv, fix_gamma=False,
                                 axis=3, training=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ro),
                               rtol=1e-6, atol=1e-6)


def test_remedy_mapping():
    assert kernels.remedy_for("unfused-elementwise") == \
        "kernels.fused_bn_relu"
    assert kernels.remedy_for("transpose-share") == \
        "kernels.fused_bn_relu"
    assert kernels.remedy_for("memory-bound") == \
        "kernels.flash_attention"
    assert kernels.remedy_for("no-such-kind") is None


def test_features_row(kernels_on):
    assert mx.runtime.Features().is_enabled("KERNELS")


def test_env_var_registered():
    from mxnet_tpu import env
    assert "MXNET_TPU_KERNELS" in env.REGISTRY


# ----------------------------------------------------------------------
# fused BN+ReLU: numerics + grad vs the XLA reference
# ----------------------------------------------------------------------

def _bn_inputs(seed=0, c=16, dtype=np.float32):
    rng = np.random.RandomState(seed)
    x = jnp.asarray((rng.randn(4, 5, 5, c) * 2 + 1).astype(dtype))
    gamma = jnp.asarray(rng.rand(c).astype(np.float32) + 0.5)
    beta = jnp.asarray(rng.randn(c).astype(np.float32))
    mm = jnp.asarray((rng.randn(c) * 0.1).astype(np.float32))
    mv = jnp.asarray(rng.rand(c).astype(np.float32) + 0.5)
    return x, gamma, beta, mm, mv


@pytest.mark.parametrize("training,use_global,fix_gamma", [
    (True, False, False), (True, False, True),
    (False, False, False), (True, True, False)])
def test_bn_relu_fwd_matches_reference(kernels_on, training, use_global,
                                       fix_gamma):
    x, gamma, beta, mm, mv = _bn_inputs()
    kw = dict(fix_gamma=fix_gamma, use_global_stats=use_global, axis=3,
              training=training)
    out, nm, nv = fbr.fused_bn_relu(x, gamma, beta, mm, mv, **kw)
    ro, rm, rv = fbr.xla_reference(x, gamma, beta, mm, mv, **kw)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ro),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(nm), np.asarray(rm),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(nv), np.asarray(rv),
                               rtol=1e-5, atol=1e-6)
    assert np.asarray(out).min() >= 0.0        # the relu epilogue


def test_bn_relu_grads_match_reference(kernels_on):
    """The custom-vjp backward (relu mask + training-stats backward
    folded into one dx pass) against autodiff of the unfused path."""
    x, gamma, beta, mm, mv = _bn_inputs(2)

    def loss(fn, x, g, b):
        o, _, _ = fn(x, g, b, mm, mv, fix_gamma=False, axis=3,
                     training=True)
        return jnp.sum(o * jnp.cos(o))         # nontrivial cotangent

    gf = jax.grad(lambda *a: loss(fbr.fused_bn_relu, *a),
                  argnums=(0, 1, 2))(x, gamma, beta)
    gr = jax.grad(lambda *a: loss(fbr.xla_reference, *a),
                  argnums=(0, 1, 2))(x, gamma, beta)
    for a, b, name in zip(gf, gr, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=2e-5, err_msg=name)


def test_bn_relu_bf16_activations_fp32_stats(kernels_on):
    """bf16 in, fp32 batch statistics: the running stats match the
    reference's fp32 accumulation and the output dtype stays bf16."""
    import jax.numpy as jnp2
    x, gamma, beta, mm, mv = _bn_inputs(3)
    xb = x.astype(jnp2.bfloat16)
    out, nm, nv = fbr.fused_bn_relu(xb, gamma, beta, mm, mv,
                                    fix_gamma=False, axis=3,
                                    training=True)
    ro, rm, rv = fbr.xla_reference(xb, gamma, beta, mm, mv,
                                   fix_gamma=False, axis=3,
                                   training=True)
    assert out.dtype == jnp2.bfloat16
    assert nm.dtype == jnp2.float32 and nv.dtype == jnp2.float32
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ro, np.float32),
                               rtol=2e-2, atol=2e-2)
    np.testing.assert_allclose(np.asarray(nm), np.asarray(rm),
                               rtol=1e-4, atol=1e-5)


def test_gluon_fusion_site_pairs_bn_relu(kernels_on):
    """HybridSequential pairs BatchNorm + relu Activation through the
    fused op; the training trajectory (params AND running stats) stays
    identical to the unfused path."""
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(2, 6, 6, 3).astype(np.float32))
    y = mx.nd.array(np.random.RandomState(1)
                    .rand(2, 4).astype(np.float32))

    def train3(on, monkey=None):
        import os
        if on:
            os.environ["MXNET_TPU_KERNELS"] = "1"
        else:
            os.environ.pop("MXNET_TPU_KERNELS", None)
        try:
            np.random.seed(0)
            mx.random.seed(0)
            net = gluon.nn.HybridSequential()
            net.add(gluon.nn.Conv2D(8, 3, padding=1, layout="NHWC"),
                    gluon.nn.BatchNorm(axis=3),
                    gluon.nn.Activation("relu"),
                    gluon.nn.Flatten(), gluon.nn.Dense(4))
            net.initialize(ctx=mx.cpu(), force_reinit=True)
            net.hybridize()
            tr = gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": 0.1}, kvstore=None)
            lf = gluon.loss.L2Loss()
            for _ in range(3):
                with autograd.record():
                    loss = lf(net(x), y).mean()
                loss.backward()
                tr.step(2)
            return (float(loss.asscalar()),
                    [p.data().asnumpy()
                     for p in net.collect_params().values()])
        finally:
            os.environ["MXNET_TPU_KERNELS"] = "1"
    l_off, p_off = train3(False)
    l_on, p_on = train3(True)
    assert abs(l_off - l_on) < 1e-5
    for a, b in zip(p_on, p_off):
        np.testing.assert_allclose(a, b, rtol=5e-4, atol=5e-5)


def test_fusion_plan_inactive_without_env(monkeypatch):
    monkeypatch.delenv("MXNET_TPU_KERNELS", raising=False)
    from mxnet_tpu.gluon.nn.basic_layers import _bn_relu_fusion_plan
    bn = gluon.nn.BatchNorm(axis=3)
    act = gluon.nn.Activation("relu")
    plan = _bn_relu_fusion_plan([bn, act])
    assert plan == [(bn, False), (act, False)]
    monkeypatch.setenv("MXNET_TPU_KERNELS", "1")
    plan = _bn_relu_fusion_plan([bn, act])
    assert plan == [(bn, True)]
    # a non-relu activation never pairs
    tanh = gluon.nn.Activation("tanh")
    assert _bn_relu_fusion_plan([bn, tanh]) == [(bn, False),
                                                (tanh, False)]


# ----------------------------------------------------------------------
# flash attention through the registry (op level, pallas interpret)
# ----------------------------------------------------------------------

BH, SEQ, D, HEADS = 4, 64, 16, 2
B = BH // HEADS


def _mask_np(seed=1):
    rng = np.random.RandomState(seed)
    valid = rng.randint(SEQ // 2, SEQ + 1, (B,))
    m = np.zeros((B, SEQ, SEQ), np.float32)
    for i, n in enumerate(valid):
        m[i, :, :n] = 1.0
    return m


def test_masked_flash_op_pallas_backward_matches_xla(kernels_on):
    """The previously untested path: the op-level masked flash
    attention with the PALLAS kernels selected (interpret on CPU),
    forward AND custom-vjp backward, against the XLA reference path."""
    from mxnet_tpu.ops.transformer import _attention_reference_masked
    rng = np.random.RandomState(4)
    mnp = _mask_np()
    arrs = [rng.randn(BH, SEQ, D).astype(np.float32) for _ in range(3)]

    def run(use_pallas):
        q, k, v = (mx.nd.array(a) for a in arrs)
        mask = mx.nd.array(mnp)
        for t in (q, k, v):
            t.attach_grad()
        with autograd.record():
            out = mx.nd.flash_attention_masked(
                q, k, v, mask, heads=HEADS, use_pallas=use_pallas,
                block_q=32, block_k=32)
            loss = (out * out).sum()
        loss.backward()
        return (out.asnumpy(), q.grad.asnumpy(), k.grad.asnumpy(),
                v.grad.asnumpy())

    got = run(True)          # pallas interpret: fwd + blockwise bwd
    want = run(False)        # XLA reference custom-vjp
    for a, b, name in zip(got, want, ("out", "dq", "dk", "dv")):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-3,
                                   err_msg=name)
    ref = _attention_reference_masked(
        jnp.asarray(arrs[0]), jnp.asarray(arrs[1]), jnp.asarray(arrs[2]),
        jnp.repeat(jnp.asarray(mnp), HEADS, axis=0), 1.0 / np.sqrt(D))
    np.testing.assert_allclose(got[0], np.asarray(ref), rtol=2e-4,
                               atol=2e-4)


def test_causal_flash_op_pallas_matches_xla(kernels_on):
    rng = np.random.RandomState(5)
    arrs = [rng.randn(BH, SEQ, D).astype(np.float32) for _ in range(3)]

    def run(use_pallas):
        q, k, v = (mx.nd.array(a) for a in arrs)
        for t in (q, k, v):
            t.attach_grad()
        with autograd.record():
            out = mx.nd.flash_attention(q, k, v, causal=True,
                                        use_pallas=use_pallas,
                                        block_q=32, block_k=32)
            loss = (out * out).sum()
        loss.backward()
        return out.asnumpy(), q.grad.asnumpy()

    got = run(True)
    want = run(False)
    for a, b in zip(got, want):
        np.testing.assert_allclose(a, b, rtol=2e-3, atol=2e-3)


def test_flash_selection_is_the_registry(monkeypatch):
    """One selection point: monkeypatching the registry's choose drives
    the op -- no residual per-call-site use_pallas branching."""
    calls = []
    real = kreg.choose

    def spy(name, force=None, **kw):
        ch = real(name, force=force, **kw)
        calls.append((name, force, ch.use_pallas))
        return ch
    # the op resolves `kernels.choose` at call time: patching the
    # package attribute intercepts every selection
    monkeypatch.setattr(kernels, "choose", spy)
    rng = np.random.RandomState(0)
    q = mx.nd.array(rng.randn(BH, SEQ, D).astype(np.float32))
    mx.nd.flash_attention(q, q, q, use_pallas=False)
    assert calls and calls[-1][0] == "flash_attention"


# ----------------------------------------------------------------------
# bucket-flattened optimizer update
# ----------------------------------------------------------------------

def _param_set(seed=0):
    rng = np.random.RandomState(seed)
    shapes = [(7, 5), (16,), (3, 4, 2), (9,)]
    ws = [jnp.asarray(rng.randn(*s).astype(np.float32)) for s in shapes]
    gs = [jnp.asarray(rng.randn(*s).astype(np.float32)) for s in shapes]
    ss = [jnp.asarray((rng.randn(*s) * 0.1).astype(np.float32))
          for s in shapes]
    return ws, gs, ss


def test_lars_bucket_matches_per_param_ops(kernels_on):
    """One flat buffer reproduces nd.lars_update / nd.sgd_mom_update
    per tensor, including the skip list, clip, and both momentum sign
    conventions (state stays checkpoint-compatible)."""
    from mxnet_tpu import nd
    ws, gs, ms = _param_set()
    lrs = [0.1, 0.2, 0.05, 0.15]
    wds = [1e-4, 0.0, 1e-4, 5e-5]
    skips = [False, True, False, True]
    ref_w, ref_m = [], []
    for i in range(4):
        if skips[i]:
            w2, m2 = nd.sgd_mom_update(
                nd.NDArray(ws[i]), nd.NDArray(gs[i]), nd.NDArray(ms[i]),
                momentum=0.9, lr=lrs[i], wd=wds[i], rescale_grad=0.5,
                clip_gradient=1.0)
        else:
            w2, m2 = nd.lars_update(
                nd.NDArray(ws[i]), nd.NDArray(gs[i]), nd.NDArray(ms[i]),
                momentum=0.9, eta=0.001, epsilon=1e-9, lr=lrs[i],
                wd=wds[i], rescale_grad=0.5, clip_gradient=1.0)
        ref_w.append(w2.asnumpy())
        ref_m.append(m2.asnumpy())
    nws, nms = kopt.lars_bucket_update(
        ws, gs, ms, lrs, wds, skips, momentum=0.9, eta=0.001,
        epsilon=1e-9, rescale=0.5, clip=1.0)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(nws[i]), ref_w[i],
                                   rtol=2e-5, atol=2e-6)
        sign = -1.0 if skips[i] else 1.0
        np.testing.assert_allclose(sign * np.asarray(nms[i]),
                                   sign * ref_m[i], rtol=2e-5,
                                   atol=2e-6)


def test_lamb_bucket_matches_per_param_ops(kernels_on):
    from mxnet_tpu import nd
    ws, gs, means = _param_set(1)
    _ws2, _gs2, vrs = _param_set(2)
    vrs = [jnp.abs(v) * 0.1 for v in vrs]
    lrs = [0.1, 0.2, 0.05, 0.15]
    wds = [1e-4, 0.0, 1e-4, 5e-5]
    t = 3
    ref_w, ref_m, ref_v = [], [], []
    for i in range(4):
        g2, m2, v2 = nd.lamb_update_phase1(
            nd.NDArray(ws[i]), nd.NDArray(gs[i]), nd.NDArray(means[i]),
            nd.NDArray(vrs[i]), beta1=0.9, beta2=0.999, epsilon=1e-6,
            t=t, bias_correction=True, wd=wds[i], rescale_grad=0.5,
            clip_gradient=1.0)
        w2 = nd.lamb_update_phase2(
            nd.NDArray(ws[i]), g2, nd.NDArray(ws[i]).norm(), g2.norm(),
            lr=lrs[i], lower_bound=0.01, upper_bound=10.0)
        ref_w.append(w2.asnumpy())
        ref_m.append(m2.asnumpy())
        ref_v.append(v2.asnumpy())
    nws, nmn, nvr = kopt.lamb_bucket_update(
        ws, gs, means, vrs, lrs, wds, t, beta1=0.9, beta2=0.999,
        epsilon=1e-6, bias_correction=True, lower_bound=0.01,
        upper_bound=10.0, rescale=0.5, clip=1.0)
    for i in range(4):
        np.testing.assert_allclose(np.asarray(nws[i]), ref_w[i],
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(nmn[i]), ref_m[i],
                                   rtol=2e-5, atol=2e-6)
        np.testing.assert_allclose(np.asarray(nvr[i]), ref_v[i],
                                   rtol=2e-5, atol=2e-7)


def test_bucket_groups_by_dtype(kernels_on):
    """Mixed-dtype parameter sets flatten into one buffer PER dtype
    (the shared mxnet_tpu.bucketing grouping)."""
    rng = np.random.RandomState(0)
    ws = [jnp.asarray(rng.randn(8).astype(np.float32)),
          jnp.asarray(rng.randn(4, 4).astype(np.float16)),
          jnp.asarray(rng.randn(6).astype(np.float32))]
    gs = [jnp.asarray(rng.randn(*w.shape).astype(w.dtype)) for w in ws]
    ms = [jnp.zeros_like(w) for w in ws]
    nws, nms = kopt.lars_bucket_update(
        ws, gs, ms, [0.1] * 3, [0.0] * 3, [False] * 3)
    for w, nw, nm in zip(ws, nws, nms):
        assert nw.dtype == w.dtype and nw.shape == w.shape
        assert nm.dtype == w.dtype


def test_trainstep_bucket_matches_loop():
    """The compiled train step with MXNET_TPU_KERNELS=1 (bucketed
    update) follows the identical trajectory as the per-parameter
    update loop, for LARS and LAMB."""
    import os
    from mxnet_tpu.parallel import TrainStep
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(8, 16).astype(np.float32))
    y = mx.nd.array(np.random.RandomState(1)
                    .rand(8, 4).astype(np.float32))

    def run(optname, kw, on):
        if on:
            os.environ["MXNET_TPU_KERNELS"] = "1"
        else:
            os.environ.pop("MXNET_TPU_KERNELS", None)
        try:
            np.random.seed(0)
            mx.random.seed(0)
            net = gluon.nn.HybridSequential()
            net.add(gluon.nn.Dense(16, activation="relu"),
                    gluon.nn.Dense(4))
            net.initialize(ctx=mx.cpu())
            net.hybridize()
            tr = gluon.Trainer(net.collect_params(), optname, kw,
                               kvstore=None)
            step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
            return [float(step(x, y).asscalar()) for _ in range(4)]
        finally:
            os.environ.pop("MXNET_TPU_KERNELS", None)

    for name, kw in (("lars", {"learning_rate": 0.05, "momentum": 0.9}),
                     ("lamb", {"learning_rate": 0.01})):
        l_off = run(name, kw, False)
        l_on = run(name, kw, True)
        assert all(abs(a - b) < 2e-5 for a, b in zip(l_off, l_on)), \
            (name, l_off, l_on)


def test_flat_lars_custom_vjp_matches_autodiff(kernels_on):
    """The flat kernel's custom-vjp backward equals autodiff of the
    plain math (trust folded into the lr input)."""
    rng = np.random.RandomState(0)
    W = jnp.asarray(rng.randn(300).astype(np.float32))
    G = jnp.asarray(rng.randn(300).astype(np.float32))
    M = jnp.asarray((rng.randn(300) * 0.1).astype(np.float32))
    lr = jnp.full((300,), 0.1, jnp.float32)
    wd = jnp.full((300,), 1e-4, jnp.float32)
    sg = jnp.ones((300,), jnp.float32)

    def f(impl_pallas, W, G, M):
        nw, nm = kopt._flat_lars(W, G, M, lr, wd, sg,
                                 jnp.float32(0.5), 0.9, 0.0,
                                 impl_pallas, impl_pallas)
        return jnp.sum(nw * nw) + jnp.sum(nm)

    def f_plain(W, G, M):
        nw, nm = kopt._lars_math(W, G, M, lr, wd, sg,
                                 jnp.float32(0.5), 0.9, 0.0)
        return jnp.sum(nw * nw) + jnp.sum(nm)

    want = jax.grad(f_plain, argnums=(0, 1, 2))(W, G, M)
    for impl in (True, False):
        got = jax.grad(lambda *a: f(impl, *a), argnums=(0, 1, 2))(W, G, M)
        for a, b in zip(got, want):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)


# ----------------------------------------------------------------------
# perf-audit remedy wiring
# ----------------------------------------------------------------------

def test_perf_advisories_carry_remedy():
    from mxnet_tpu.analysis import perf
    metrics = {"transpose_share": 0.5,
               "unfused_elementwise_share": 0.3,
               "unfused_elementwise_count": 4, "pad_waste": 0.0,
               "intensity": 100.0, "flops": 1e9, "bytes": 1e7}
    counters = {"transpose_ops": {"scope": 123}}
    adv = perf._advisories_for("lbl", metrics, counters, ridge=10.0,
                               thresholds=perf.THRESHOLDS)
    by_kind = {a["kind"]: a for a in adv}
    assert by_kind["unfused-elementwise"]["remedy"] == \
        "kernels.fused_bn_relu"
    assert by_kind["transpose-share"]["remedy"] == \
        "kernels.fused_bn_relu"
    # memory-bound advisory names the flash kernel
    metrics2 = dict(metrics, transpose_share=0.0,
                    unfused_elementwise_share=0.0, intensity=0.1)
    adv2 = perf._advisories_for("lbl", metrics2, counters, ridge=10.0,
                                thresholds=perf.THRESHOLDS)
    by_kind2 = {a["kind"]: a for a in adv2}
    assert by_kind2["memory-bound"]["remedy"] == \
        "kernels.flash_attention"


def test_perf_diff_renders_remedy():
    from mxnet_tpu.analysis import perf
    base = {"schema": perf.AUDIT_SCHEMA, "executables": {}}
    cur = {"schema": perf.AUDIT_SCHEMA, "executables": {
        "train_step:Net": {
            "metrics": {"transpose_share": 0.0,
                        "unfused_elementwise_share": 0.4,
                        "pad_waste": 0.0, "intensity": 1.0},
            "advisories": [{"kind": "unfused-elementwise",
                            "category": "elementwise_fusion",
                            "share": 0.4, "op_names": [],
                            "remedy": "kernels.fused_bn_relu",
                            "message": "40% unfused"}]}}}
    diags = perf.diff_audit(base, cur)
    assert any("remedy: kernels.fused_bn_relu" in d.message
               for d in diags), [d.message for d in diags]


# ----------------------------------------------------------------------
# bench probe (real, slow): the kernel-tier HLO diff contract
# ----------------------------------------------------------------------

@pytest.mark.slow
def test_bench_kernels_diff_real_probe(monkeypatch):
    import os
    import sys
    monkeypatch.syspath_prepend(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import bench
    diff = bench._kernels_diff("resnet")
    assert diff is not None
    for key in ("probe", "after_interpret", "before", "after", "delta"):
        assert key in diff, key
    assert diff["before"]["bytes_total"] > 0
