"""CPU-vs-TPU op consistency sweep (reference:
``tests/python/gpu/test_operator_gpu.py :: check_consistency``).

Runs a representative op set on every available backend and
cross-compares.  With only CPU visible this degenerates to a smoke run;
with the TPU attached (the normal driver environment) it is a real
cross-device numeric check.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import check_consistency

_R = np.random.RandomState(0)


def _x(*shape):
    return _R.rand(*shape).astype(np.float32) + 0.5


SWEEP = [
    ("relu", [_x(8, 16)], {}),
    ("sigmoid", [_x(8, 16)], {}),
    ("tanh", [_x(8, 16)], {}),
    ("exp", [_x(8, 16)], {}),
    ("log", [_x(8, 16)], {}),
    ("sqrt", [_x(8, 16)], {}),
    ("softmax", [_x(8, 16)], {}),
    ("log_softmax", [_x(8, 16)], {}),
    ("sum", [_x(8, 16)], {"axis": 1}),
    ("mean", [_x(8, 16)], {}),
    ("max", [_x(8, 16)], {"axis": 0}),
    ("argmax", [_x(8, 16)], {"axis": 1}),
    ("elemwise_add", [_x(4, 4), _x(4, 4)], {}),
    ("elemwise_mul", [_x(4, 4), _x(4, 4)], {}),
    ("broadcast_add", [_x(4, 1), _x(1, 4)], {}),
    ("dot", [_x(16, 32), _x(32, 8)], {}),
    ("batch_dot", [_x(4, 8, 16), _x(4, 16, 8)], {}),
    ("transpose", [_x(3, 5)], {}),
    ("clip", [_x(8, 8)], {"a_min": 0.6, "a_max": 1.2}),
    ("_plus_scalar", [_x(8,)], {"scalar": 2.0}),
    ("_power_scalar", [_x(8,)], {"scalar": 2.0}),
    ("FullyConnected", [_x(8, 32), _x(16, 32), np.zeros(16, np.float32)],
     {"num_hidden": 16}),
    ("Convolution", [_x(2, 3, 8, 8), _x(4, 3, 3, 3),
                     np.zeros(4, np.float32)],
     {"num_filter": 4, "kernel": (3, 3), "pad": (1, 1)}),
    ("Pooling", [_x(2, 3, 8, 8)],
     {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"}),
    ("LayerNorm", [_x(8, 32), np.ones(32, np.float32),
                   np.zeros(32, np.float32)], {}),
    ("Embedding", [np.array([[0, 1], [2, 3]], np.float32), _x(8, 4)],
     {"input_dim": 8, "output_dim": 4}),
]


@pytest.mark.parametrize("name,inputs,params",
                         SWEEP, ids=[s[0] for s in SWEEP])
def test_op_consistency(name, inputs, params):
    check_consistency(name, inputs, params)
