"""Sharding sanitizer (ISSUE 7): SPMD spec linter + donation auditor
fixtures, the compiled collective-contract round trip on the
data_parallel.TrainStep LeNet path, and the transfer-guard wiring."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import analysis as an
from mxnet_tpu import gluon
from mxnet_tpu.analysis import sharding
from mxnet_tpu.parallel import TrainStep, make_mesh

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules_of(diags):
    return sorted({d.rule for d in diags})


def _lint(src):
    return an.lint_source(src, "probe.py")


# ----------------------------------------------------------------------
# mesh-axis-unknown (project rule: declarations span the linted tree)
# ----------------------------------------------------------------------

def test_mesh_axis_unknown_fires_and_declared_twin_silent(tmp_path):
    (tmp_path / "a.py").write_text(
        "from mxnet_tpu.parallel import make_mesh\n"
        "from jax.sharding import PartitionSpec as P\n"
        "mesh = make_mesh({'dp': 8})\n"
        "good = P('dp', None)\n"
        "bad = P('dpp')\n")
    diags = sharding.audit_sharding([str(tmp_path)])
    assert _rules_of(diags) == ["mesh-axis-unknown"]
    assert len(diags) == 1 and diags[0].line == 5
    assert "did you mean" in diags[0].message


def test_mesh_axis_declarations_cross_files(tmp_path):
    # the axis is declared in ANOTHER file of the batch -- like
    # mesh.py declaring what data_parallel.py uses
    (tmp_path / "decl.py").write_text(
        "from jax.sharding import Mesh\n"
        "def build(devs):\n"
        "    return Mesh(devs, ('rows', 'cols'))\n")
    (tmp_path / "use.py").write_text(
        "from jax.sharding import PartitionSpec\n"
        "spec = PartitionSpec('rows', 'cols')\n")
    assert sharding.audit_sharding([str(tmp_path)]) == []
    # linted alone, the use has no declaration and no canonical match
    assert _rules_of(sharding.audit_sharding(
        [str(tmp_path / "use.py")])) == ["mesh-axis-unknown"]


def test_mesh_axis_resolves_variables_and_canonical_roles(tmp_path):
    # param defaults / self._axis attributes resolve; the canonical
    # AXIS_ROLES vocabulary (dp/tp/pp/sp/ep) needs no declaration
    (tmp_path / "v.py").write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "def ring(x, axis_name='sp'):\n"
        "    return P(None, axis_name, None)\n"
        "class Layer:\n"
        "    def __init__(self, axis='tp'):\n"
        "        self._axis = axis\n"
        "    def spec(self):\n"
        "        return P(self._axis, None)\n")
    assert sharding.audit_sharding([str(tmp_path)]) == []
    (tmp_path / "w.py").write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "def ring(x, axis_name='zz9'):\n"
        "    return P(None, axis_name)\n")
    diags = sharding.audit_sharding([str(tmp_path / "w.py")])
    assert _rules_of(diags) == ["mesh-axis-unknown"]
    assert "'zz9'" in diags[0].message


def test_mesh_axis_suppression_comment(tmp_path):
    (tmp_path / "s.py").write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "x = P('experimental9')  # mxlint: disable=mesh-axis-unknown\n")
    assert sharding.audit_sharding([str(tmp_path)]) == []


def test_parallel_package_axes_all_declared():
    """The real tree: every PartitionSpec axis in parallel/, gluon, and
    dataio resolves against the canonical vocabulary + mesh builds."""
    paths = [os.path.join(REPO, "mxnet_tpu")]
    assert sharding.audit_sharding(paths) == []


# ----------------------------------------------------------------------
# shard-map-spec-arity
# ----------------------------------------------------------------------

def test_shard_map_arity_fires_and_clean_twin_silent():
    bad = (
        "from mxnet_tpu.parallel._shard_map import shard_map\n"
        "def body(q, k):\n"
        "    return q\n"
        "def run(mesh, spec):\n"
        "    return shard_map(body, mesh=mesh,\n"
        "                     in_specs=(spec, spec, spec),\n"
        "                     out_specs=spec)\n")
    diags = _lint(bad)
    assert _rules_of(diags) == ["shard-map-spec-arity"]
    assert "2 positional arg(s)" in diags[0].message
    good = bad.replace("(spec, spec, spec)", "(spec, spec)")
    assert _lint(good) == []


def test_shard_map_arity_resolves_partial_bodies():
    # sequence.py's idiom: functools.partial binding keyword-only args
    # must NOT reduce the positional arity
    src = (
        "import functools\n"
        "from mxnet_tpu.parallel._shard_map import shard_map\n"
        "def body(q, k, v, *, scale):\n"
        "    return q\n"
        "def run(mesh, spec):\n"
        "    b = functools.partial(body, scale=2.0)\n"
        "    return shard_map(b, mesh=mesh, in_specs=(spec, spec, spec),\n"
        "                     out_specs=spec)\n")
    assert _lint(src) == []
    # a positionally-consumed arg DOES reduce arity
    src2 = src.replace("functools.partial(body, scale=2.0)",
                       "functools.partial(body, None, scale=2.0)")
    assert _rules_of(_lint(src2)) == ["shard-map-spec-arity"]


def test_shard_map_out_specs_tuple_arity():
    bad = (
        "from mxnet_tpu.parallel._shard_map import shard_map\n"
        "def body(q, k):\n"
        "    return q, k, q\n"
        "def run(mesh, spec):\n"
        "    return shard_map(body, mesh=mesh, in_specs=(spec, spec),\n"
        "                     out_specs=(spec,))\n")
    diags = _lint(bad)
    assert _rules_of(diags) == ["shard-map-spec-arity"]
    assert "returns a 3-tuple" in diags[0].message
    good = bad.replace("out_specs=(spec,)", "out_specs=(spec, spec, spec)")
    assert _lint(good) == []


def test_shard_map_arity_real_parallel_files_clean():
    """The in-repo shard_map call sites (ring attention, pipeline) must
    satisfy their own arity rule."""
    for rel in ("mxnet_tpu/parallel/sequence.py",
                "mxnet_tpu/parallel/pipeline.py",
                "mxnet_tpu/parallel/_shard_map.py"):
        diags = an.lint_file(os.path.join(REPO, rel))
        assert [d for d in diags if d.rule == "shard-map-spec-arity"] \
            == [], rel


# ----------------------------------------------------------------------
# undonated-train-state
# ----------------------------------------------------------------------

def test_undonated_train_state_fires_and_donated_twin_silent():
    bad = ("import jax\n"
           "def train_step(pvals, svals, data):\n"
           "    return pvals\n"
           "f = jax.jit(train_step)\n")
    diags = _lint(bad)
    assert _rules_of(diags) == ["undonated-train-state"]
    good = bad.replace("jax.jit(train_step)",
                       "jax.jit(train_step, donate_argnums=(0, 1))")
    assert _lint(good) == []


def test_undonated_fires_on_state_params_without_step_name():
    bad = ("import jax\n"
           "def apply(pvals, x):\n"
           "    return x\n"
           "f = jax.jit(apply)\n")
    assert _rules_of(_lint(bad)) == ["undonated-train-state"]
    # non-state params, non-step name: silent
    ok = ("import jax\n"
          "def apply(x, y):\n"
          "    return x + y\n"
          "f = jax.jit(apply)\n")
    assert _lint(ok) == []


def test_undonated_accepts_jit_kwargs_splat_donation():
    # the parallel.data_parallel idiom: donation assigned into the
    # kwargs dict the jit call splats
    src = ("import jax\n"
           "def build(donate):\n"
           "    def step_fn(pvals, svals):\n"
           "        return pvals\n"
           "    jit_kwargs = {}\n"
           "    if donate:\n"
           "        jit_kwargs['donate_argnums'] = (0, 1)\n"
           "    return jax.jit(step_fn, **jit_kwargs)\n")
    assert _lint(src) == []


def test_undonated_train_state_repo_sites_justified():
    """data_parallel donates; the Executor/hybridize/predictor caches
    carry justified suppressions -- the whole tree lints clean with the
    rule armed (the ISSUE 7 donation-sweep acceptance)."""
    for rel in ("mxnet_tpu/parallel/data_parallel.py",
                "mxnet_tpu/executor.py",
                "mxnet_tpu/gluon/block.py",
                "mxnet_tpu/predictor.py"):
        diags = an.lint_file(os.path.join(REPO, rel))
        assert [d for d in diags if d.rule == "undonated-train-state"] \
            == [], rel


# ----------------------------------------------------------------------
# donated-reuse
# ----------------------------------------------------------------------

def test_donated_reuse_fires_and_rebound_twin_silent():
    bad = ("import jax\n"
           "def go(w, g):\n"
           "    f = jax.jit(lambda a, b: a + b, donate_argnums=(0,))\n"
           "    out = f(w, g)\n"
           "    return w + out\n")
    diags = _lint(bad)
    assert _rules_of(diags) == ["donated-reuse"]
    assert "'w'" in diags[0].message
    # using the returned array (or rebinding the name) is the fix
    good = ("import jax\n"
            "def go(w, g):\n"
            "    f = jax.jit(lambda a, b: a + b, donate_argnums=(0,))\n"
            "    w = f(w, g)\n"
            "    return w + g\n")
    assert _lint(good) == []
    # reading the NON-donated operand is fine
    good2 = bad.replace("return w + out", "return g + out")
    assert _lint(good2) == []


# ----------------------------------------------------------------------
# implicit-reshard
# ----------------------------------------------------------------------

_RESHARD_BAD = (
    "import jax\n"
    "from jax.sharding import NamedSharding, PartitionSpec as P\n"
    "def loop(xs, mesh):\n"
    "    sh = NamedSharding(mesh, P('dp'))\n"
    "    out = []\n"
    "    for x in xs:\n"
    "        out.append(jax.device_put(x, sh))\n"
    "    return out\n")


def test_implicit_reshard_fires_and_guarded_twin_silent():
    assert _rules_of(_lint(_RESHARD_BAD)) == ["implicit-reshard"]
    guarded = _RESHARD_BAD.replace(
        "        out.append(jax.device_put(x, sh))\n",
        "        if not x.sharding.is_equivalent_to(sh, x.ndim):\n"
        "            x = jax.device_put(x, sh)\n"
        "        out.append(x)\n")
    assert _lint(guarded) == []
    # hoisted out of the loop: placement happens once, fine
    hoisted = (
        "import jax\n"
        "from jax.sharding import NamedSharding, PartitionSpec as P\n"
        "def place(x, mesh):\n"
        "    return jax.device_put(x, NamedSharding(mesh, P('dp')))\n")
    assert _lint(hoisted) == []


# ----------------------------------------------------------------------
# compiled layer: collective profile + contract round trip
# ----------------------------------------------------------------------

_HLO_FIXTURE = """\
HloModule probe

ENTRY %main (p0: f32[16,8]) -> f32[16,8] {
  %p0 = f32[16,8] parameter(0)
  %ag = f32[16,64] all-gather(f32[16,8] %p0), dimensions={1}
  %ar = f32[16,8] all-reduce(f32[16,8] %p0), to_apply=%add
  %ars = f32[16,8] all-reduce-start(f32[16,8] %ar)
  %ard = f32[16,8] all-reduce-done(f32[16,8] %ars)
  %pid = u32[] partition-id()
  ROOT %out = f32[16,8] add(f32[16,8] %ar, f32[16,8] %ard)
}
"""


def test_collective_profile_counts_kinds_and_bytes():
    prof = sharding.collective_profile(_HLO_FIXTURE)
    # start/done pairs count once; partition-id is metadata, not traffic
    assert prof["all-reduce"]["count"] == 2
    assert prof["all-gather"]["count"] == 1
    assert prof["all-gather"]["bytes"] == 16 * 64 * 4
    assert "partition-id" not in prof


def _lenet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(6, 5, padding=2, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Conv2D(16, 3, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    return net


def test_collective_contract_round_trip_lenet(tmp_path):
    """The CI shardlint gate's exact shape: LeNet TrainStep over a dp
    mesh -> baseline write -> self-diff zero -> a seeded spec mismatch
    (param sharded where it must be replicated) is flagged naming the
    executable."""
    from mxnet_tpu import profiling
    from jax.sharding import NamedSharding, PartitionSpec as P
    profiling.reset()
    profiling.enable()
    try:
        mesh = make_mesh({"dp": 8}, devices=jax.devices("cpu")[:8])
        net = _lenet()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=None)
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                         mesh=mesh)
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.rand(16, 1, 16, 16).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 10, (16,)).astype(np.float32))
        step(x, y)

        base_path = str(tmp_path / "baseline.json")
        base = sharding.save_contract(base_path)
        label = "train_step:HybridSequential"
        assert label in base["executables"]
        # the blessed collectives are the gradient psums: all-reduce
        # only, nothing else
        assert set(base["executables"][label]) == {"all-reduce"}, \
            base["executables"][label]
        # self-diff must be zero drift (both via API and via the CLI
        # file path CI uses)
        assert sharding.diff_contract(base, base) == []
        assert an.main(["--collective-diff", base_path, base_path]) == 0

        # seeded spec mismatch: shard a weight over dp (params must be
        # replicated) and rebuild -- GSPMD inserts resharding traffic.
        # Picked structurally (gluon's auto-name counter is process-
        # global, so name-based selection is order-fragile): the
        # Dense(32) weight, whose leading dim divides the 8-way mesh.
        dense = [c for c in net._children.values()
                 if isinstance(c, gluon.nn.Dense)][0]
        p = dense.weight
        p._data._data = jax.device_put(p._data._data,
                                       NamedSharding(mesh, P("dp")))
        step._cache.clear()
        step(x, y)
        cur_path = str(tmp_path / "current.json")
        cur = sharding.save_contract(cur_path)
        diags = sharding.diff_contract(base, cur)
        assert diags, "seeded spec mismatch not flagged"
        assert any(label in d.message for d in diags)
        assert an.main(["--collective-diff", base_path, cur_path]) == 1
    finally:
        profiling.disable()
        profiling.reset()


def test_contract_schema_and_load_rejects_foreign_json(tmp_path):
    p = tmp_path / "x.json"
    p.write_text(json.dumps({"schema": "other", "executables": {}}))
    with pytest.raises(ValueError, match="mxshard.collectives.v1"):
        sharding.load_contract(str(p))
    rc = an.main(["--collective-diff", str(p), str(p)])
    assert rc == 2


def test_diff_contract_new_executable_and_growth_flagged():
    base = {"schema": sharding.CONTRACT_SCHEMA, "executables": {
        "step": {"all-reduce": {"count": 2, "bytes": 100}}}}
    # growth of a blessed kind
    cur = {"schema": sharding.CONTRACT_SCHEMA, "executables": {
        "step": {"all-reduce": {"count": 3, "bytes": 150}}}}
    diags = sharding.diff_contract(base, cur)
    assert len(diags) == 1 and "2 -> 3" in diags[0].message
    # a brand-new executable with collectives is unblessed
    cur2 = {"schema": sharding.CONTRACT_SCHEMA, "executables": {
        "other": {"all-gather": {"count": 1, "bytes": 10}}}}
    diags2 = sharding.diff_contract(base, cur2)
    assert len(diags2) == 1 and "unblessed" in diags2[0].message
    # FEWER collectives than blessed is an improvement, not drift
    cur3 = {"schema": sharding.CONTRACT_SCHEMA, "executables": {
        "step": {"all-reduce": {"count": 1, "bytes": 50}}}}
    assert sharding.diff_contract(base, cur3) == []


# ----------------------------------------------------------------------
# transfer guard
# ----------------------------------------------------------------------

def test_transfer_guard_clean_step_passes_and_seeded_leak_raises():
    """The steady-state compiled step is guard-clean (scalar feeds ride
    explicit device_put), and a seeded IMPLICIT in-step host transfer
    raises -- the ISSUE 7 acceptance fixture."""
    mesh = make_mesh({"dp": 8}, devices=jax.devices("cpu")[:8])
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(10))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), tr,
                     mesh=mesh)
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.rand(16, 8).astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, (16,)).astype(np.float32))
    step(x, y)                        # compile + state init, unguarded
    with sharding.transfer_guard("disallow"):
        for _ in range(2):
            loss = step(x, y)         # clean steady state: must pass
        loss._data.block_until_ready()
    # seeded leak: a Python scalar mixed into eager dispatch is an
    # implicit host->device transfer every step
    with pytest.raises(Exception, match="[Dd]isallowed"):
        with sharding.transfer_guard("disallow"):
            bad = loss * 1.5
            bad._data.block_until_ready()


def test_transfer_guard_run_steps_clean():
    mesh = make_mesh({"dp": 8}, devices=jax.devices("cpu")[:8])
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=mesh)
    rng = np.random.RandomState(1)
    xs = mx.nd.array(rng.rand(2, 16, 4).astype(np.float32))
    ys = mx.nd.array(rng.rand(2, 16, 8).astype(np.float32))
    step.run_steps(xs, ys)            # warmup compile
    with sharding.transfer_guard("disallow"):
        losses = step.run_steps(xs, ys)
        losses._data.block_until_ready()
    assert losses.shape == (2,)


def test_transfer_guard_env_wiring_and_bad_mode():
    out = subprocess.run(
        [sys.executable, "-c",
         "import mxnet_tpu, jax; print(jax.config.jax_transfer_guard)"],
        env={**os.environ, "MXNET_TPU_TRANSFER_GUARD": "log",
             "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=240, cwd=REPO)
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.strip().splitlines()[-1] == "log"
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="TRANSFER_GUARD"):
        sharding.install_transfer_guard("definitely-not-a-mode")


# ----------------------------------------------------------------------
# donation accounting (the peak-HBM side of the donation sweep)
# ----------------------------------------------------------------------

def test_donated_step_aliases_state_and_mxprof_accounts_it():
    """The donation the `undonated-train-state` rule enforces is real
    in the compiled program: TrainStep(donate=True)'s HLO carries the
    input_output_alias directive (absent without donation), and
    mxprof's peak-HBM formula credits whatever alias bytes the backend
    reports (peak = arg + out + temp - alias) so the donation sweep is
    drift-checkable.  (XLA:CPU under forced multi-device reports
    alias_bytes=0 even for aliased programs, so the byte-level
    inequality is asserted only through the formula, not across the
    two programs.)"""
    from mxnet_tpu import profiling

    def build(donate):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(64, activation="relu"),
                gluon.nn.Dense(32))
        net.initialize(ctx=mx.cpu())
        net.hybridize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1}, kvstore=None)
        step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None,
                         donate=donate)
        rng = np.random.RandomState(0)
        x = mx.nd.array(rng.rand(8, 16).astype(np.float32))
        y = mx.nd.array(rng.rand(8, 32).astype(np.float32))
        step(x, y)
        fn, args = step._last_call
        text = fn.lower(*args).compile().as_text()
        return profiling.report_for(step), text

    donated, donated_text = build(True)
    undonated, undonated_text = build(False)
    assert donated is not None and undonated is not None
    assert "input_output_alias" in donated_text
    assert "input_output_alias" not in undonated_text
    for rep in (donated, undonated):
        m = rep["memory"]
        assert m["peak_hbm_bytes"] == max(
            0, m["argument_bytes"] + m["output_bytes"]
            + m["temp_bytes"] - m["alias_bytes"])


# ----------------------------------------------------------------------
# registration / env / Features surfaces
# ----------------------------------------------------------------------

def test_sharding_rules_registered_and_listed(capsys):
    ids = {"mesh-axis-unknown", "shard-map-spec-arity",
           "undonated-train-state", "donated-reuse", "implicit-reshard",
           "collective-drift"}
    assert ids <= set(an.RULES)
    assert an.main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ids:
        assert rid in out


def test_env_vars_registered():
    from mxnet_tpu import env
    assert env.get("MXNET_TPU_SHARD_CHECK") is False
    assert env.get("MXNET_TPU_TRANSFER_GUARD") == ""


def test_features_shard_check_row(monkeypatch):
    feats = mx.runtime.Features()
    assert "SHARD_CHECK" in feats
    assert feats.is_enabled("SHARD_CHECK") is False
    monkeypatch.setenv("MXNET_TPU_SHARD_CHECK", "1")
    assert mx.runtime.Features().is_enabled("SHARD_CHECK") is True
