"""Metrics (reference: metric tests inside ``test_metric.py``)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import metric


def test_accuracy():
    m = metric.Accuracy()
    pred = mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 0, 0])
    m.update(label, pred)
    assert m.get() == ("accuracy", 2 / 3)
    m.reset()
    assert np.isnan(m.get()[1])


def test_topk():
    m = metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.5, 0.4], [0.6, 0.3, 0.1]])
    label = mx.nd.array([2, 1])
    m.update(label, pred)
    assert m.get()[1] == 1.0


def test_mse_mae():
    mse = metric.MSE()
    mse.update(mx.nd.array([1.0, 2.0]), mx.nd.array([0.0, 0.0]))
    assert abs(mse.get()[1] - 2.5) < 1e-6
    mae = metric.MAE()
    mae.update(mx.nd.array([1.0, -3.0]), mx.nd.array([0.0, 0.0]))
    assert abs(mae.get()[1] - 2.0) < 1e-6


def test_crossentropy_perplexity():
    ce = metric.create("ce")
    prob = mx.nd.array([[0.2, 0.8], [0.9, 0.1]])
    label = mx.nd.array([1, 0])
    ce.update(label, prob)
    expect = -(np.log(0.8) + np.log(0.9)) / 2
    assert abs(ce.get()[1] - expect) < 1e-5
    p = metric.Perplexity()
    p.update(label, prob)
    assert abs(p.get()[1] - np.exp(expect)) < 1e-4


def test_f1():
    f1 = metric.F1()
    pred = mx.nd.array([[0.2, 0.8], [0.8, 0.2], [0.3, 0.7]])
    label = mx.nd.array([1, 1, 0])
    f1.update(label, pred)
    # tp=1 fp=1 fn=1 -> p=r=0.5 -> f1=0.5
    assert abs(f1.get()[1] - 0.5) < 1e-6


def test_composite_and_create():
    c = metric.create(["accuracy", metric.TopKAccuracy(top_k=2)])
    pred = mx.nd.array([[0.1, 0.9, 0.0]])
    c.update(mx.nd.array([1]), pred)
    names, values = c.get()
    assert "accuracy" in names[0]
    assert values[0] == 1.0 and values[1] == 1.0


def test_custom_metric():
    m = metric.CustomMetric(lambda l, p: float((l == p.argmax(-1)).mean()))
    m.update(mx.nd.array([1]), mx.nd.array([[0.0, 1.0]]))
    assert m.get()[1] == 1.0


def test_loss_metric():
    m = metric.Loss()
    m.update(None, mx.nd.array([2.0, 4.0]))
    assert m.get()[1] == 3.0
