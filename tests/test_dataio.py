"""Device-feed pipeline (ISSUE 4): ``mxnet_tpu.dataio.DeviceFeed`` --
overlapped host->device staging, on-device transforms, error/shutdown
semantics, and the integration paths (DataLoader ctx, ImageRecordIter
ctx, TrainStep fed batches, engine bulk wiring, batchify one-gather)."""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, io, recordio, telemetry
from mxnet_tpu.dataio import DeviceBatch, DeviceFeed, DeviceTransform


def _src(n, shape=(4, 3), dtype=np.float32, decode_s=0.0, fail_at=None):
    for i in range(n):
        if decode_s:
            time.sleep(decode_s)
        if fail_at is not None and i == fail_at:
            raise ValueError("decode blew up at %d" % i)
        yield (np.full(shape, i, dtype), np.full((shape[0],), i,
                                                 np.float32))


# -- core semantics ----------------------------------------------------

def test_ordering_under_prefetch_depth():
    feed = DeviceFeed(_src(10), ctx=mx.cpu(), depth=4)
    seen = [float(b.data.asnumpy()[0, 0]) for b in feed]
    assert seen == [float(i) for i in range(10)]


def test_yields_device_batches():
    feed = DeviceFeed(_src(2), ctx=mx.cpu())
    b = next(feed)
    assert isinstance(b, DeviceBatch)
    assert isinstance(b.data, mx.nd.NDArray)
    assert b.label.shape == (4,)
    x, y = b                     # tuple-style unpack
    assert x is b.data and y is b.label
    assert b[0] is b.data and len(b) == 2
    feed.close()


def test_producer_exception_reraises_at_next():
    feed = DeviceFeed(_src(10, fail_at=2), ctx=mx.cpu())
    next(feed)
    next(feed)
    with pytest.raises(ValueError, match="decode blew up"):
        next(feed)
    # the error sticks: every later next() re-raises (checkpoint/bulk
    # captured-exception precedent), and the producer thread is gone
    with pytest.raises(ValueError):
        next(feed)
    assert feed._thread is None


def test_clean_close_mid_epoch():
    feed = DeviceFeed(_src(100), ctx=mx.cpu(), depth=2)
    next(feed)
    th = feed._thread
    feed.close()
    assert not th.is_alive()
    feed.close()                 # idempotent


def test_no_leaked_thread_between_epochs():
    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    it = io.NDArrayIter(x, x[:, 0], batch_size=4)
    feed = DeviceFeed(it, ctx=mx.cpu())
    assert len(list(feed)) == 3
    assert feed._thread is None          # epoch end joined the producer
    feed.reset()
    assert len(list(feed)) == 3          # epoch 2 identical
    assert feed._thread is None


def test_uint8_stage_plus_device_cast_matches_host_cast():
    rng = np.random.RandomState(0)
    raw = rng.randint(0, 256, (6, 3, 5, 5), np.uint8)
    mean, std = (10.0, 20.0, 30.0), (2.0, 3.0, 4.0)
    tf = DeviceTransform(dtype="float32", mean=mean, std=std)
    feed = DeviceFeed(iter([(raw,)]), ctx=mx.cpu(), transform=tf)
    b = next(feed)
    # the wire format stayed compact ...
    assert b.raw[0].dtype == np.uint8
    # ... and the on-device expansion equals the host-side float math
    host = (raw.astype(np.float32)
            - np.asarray(mean, np.float32).reshape(1, 3, 1, 1)) \
        / np.asarray(std, np.float32).reshape(1, 3, 1, 1)
    np.testing.assert_allclose(b.data.asnumpy(), host, rtol=1e-6)
    feed.close()


def test_compact_off_precasts_host_side():
    raw = np.arange(12, dtype=np.uint8).reshape(1, 12)
    tf = DeviceTransform(dtype="float32")
    feed = DeviceFeed(iter([(raw,)]), ctx=mx.cpu(), transform=tf,
                      compact=False)
    b = next(feed)
    assert b.raw[0].dtype == np.float32  # fat wire format, by request
    np.testing.assert_allclose(b.data.asnumpy(), raw.astype(np.float32))
    feed.close()


def test_overlap_positive_on_threaded_path():
    """Acceptance gate: with real producer work overlapped against a
    slower consumer, consumer wait < producer busy, so the overlap
    fraction is strictly positive."""
    feed = DeviceFeed(_src(6, decode_s=0.01), ctx=mx.cpu(), depth=2)
    for _ in feed:
        time.sleep(0.03)         # stand-in for training compute
    s = feed.stats()
    assert s["batches"] == 6
    assert s["consumer_wait"] < s["producer_busy"]
    assert feed.overlap_frac() > 0


def test_feed_telemetry_instruments():
    telemetry.enable()
    try:
        telemetry.reset("feed.")
        feed = DeviceFeed(_src(3), ctx=mx.cpu())
        list(feed)
        assert telemetry.counter("feed.batches").value == 3
        assert telemetry.counter("feed.bytes_staged").value > 0
        assert telemetry.timer("feed.producer_busy").count == 3
        assert telemetry.timer("feed.consumer_wait").count >= 3
        assert telemetry.gauge("feed.overlap_frac").value is not None
    finally:
        telemetry.disable()


def test_random_transform_stages():
    rng = np.random.RandomState(1)
    raw = rng.randint(0, 256, (4, 3, 10, 10), np.uint8)
    tf = DeviceTransform(dtype="float32", rand_mirror=True, crop=(8, 8))
    feed = DeviceFeed(iter([(raw,)]), ctx=mx.cpu(), transform=tf)
    b = next(feed)
    assert b.data.shape == (4, 3, 8, 8)
    out = b.data.asnumpy()
    # every output row must be a crop of the input, mirrored or not
    found = 0
    for i in range(4):
        for y0 in range(3):
            for x0 in range(3):
                win = raw[i, :, y0:y0 + 8, x0:x0 + 8].astype(np.float32)
                if np.array_equal(out[i], win) or \
                        np.array_equal(out[i], win[..., ::-1]):
                    found += 1
                    break
            else:
                continue
            break
    assert found == 4
    feed.close()


def test_mesh_sharded_staging():
    devs = jax.devices("cpu")
    if len(devs) < 2:
        pytest.skip("needs multiple virtual devices")
    mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
    feed = DeviceFeed(_src(2, shape=(len(devs) * 2, 3)), mesh=mesh)
    b = next(feed)
    sh = b.data._data.sharding
    assert isinstance(sh, jax.sharding.NamedSharding)
    assert sh.spec[0] == "dp"
    assert len(b.data._data.devices()) == len(devs)
    feed.close()


def test_already_resident_batch_not_retransferred():
    x = mx.nd.ones((2, 2), ctx=mx.cpu())
    feed = DeviceFeed(iter([(x,)]), ctx=mx.cpu())
    b = next(feed)
    assert b.raw[0] is x._data          # same buffer, no copy
    assert feed.stats()["bytes_staged"] == 0
    feed.close()


# -- integration paths -------------------------------------------------

def test_dataloader_ctx_path_matches_host_path():
    X = np.random.RandomState(0).rand(10, 3).astype(np.float32)
    y = np.arange(10, dtype=np.float32)
    ds = gluon.data.ArrayDataset(X, y)
    host = [(x.asnumpy(), l.asnumpy())
            for x, l in gluon.data.DataLoader(ds, batch_size=4)]
    fed = [(x.asnumpy(), l.asnumpy())
           for x, l in gluon.data.DataLoader(ds, batch_size=4,
                                             ctx=mx.cpu())]
    assert len(host) == len(fed) == 3
    for (hx, hl), (fx, fl) in zip(host, fed):
        np.testing.assert_array_equal(hx, fx)
        np.testing.assert_array_equal(hl, fl)


def test_dataloader_ctx_path_workers_and_reiter():
    ds = gluon.data.ArrayDataset(np.arange(16, dtype=np.float32))
    loader = gluon.data.DataLoader(ds, batch_size=4, num_workers=2,
                                   ctx=mx.cpu())
    for _ in range(2):                   # re-iteration = fresh feed
        out = np.concatenate([b.asnumpy() for b in loader])
        np.testing.assert_array_equal(out,
                                      np.arange(16, dtype=np.float32))


def _make_rec(tmp_path, n=8, hw=(28, 30)):
    prefix = str(tmp_path / "ds")
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                     "w")
    rng = np.random.RandomState(0)
    for i in range(n):
        img = rng.randint(0, 255, hw + (3,), dtype=np.uint8)
        rec.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i % 3), i, 0), img))
    rec.close()
    return prefix


def test_image_record_iter_ctx_path(tmp_path):
    prefix = _make_rec(tmp_path)
    kw = dict(path_imgrec=prefix + ".rec", data_shape=(3, 24, 24),
              batch_size=4, mean_r=128, mean_g=128, mean_b=128,
              std_r=2, std_g=2, std_b=2, preprocess_threads=0)
    host = [b.data[0].asnumpy() for b in io.ImageRecordIter(**kw)]
    feed = io.ImageRecordIter(ctx=mx.cpu(), **kw)
    assert isinstance(feed, DeviceFeed)
    fed = []
    for b in feed:
        assert b.raw[0].dtype == np.uint8    # compact over the wire
        assert b.data.dtype == np.float32
        fed.append(b.data.asnumpy())
    assert len(fed) == len(host) == 2
    for h, f in zip(host, fed):
        np.testing.assert_allclose(h, f, rtol=1e-5)


def test_image_iter_device_feed_method(tmp_path):
    from mxnet_tpu.image import ImageIter
    prefix = _make_rec(tmp_path)
    it = ImageIter(4, (3, 24, 24), path_imgrec=prefix + ".rec",
                   preprocess_threads=0, dtype="uint8")
    with it:
        feed = it.device_feed(ctx=mx.cpu(),
                              transform=DeviceTransform(dtype="float32"))
        batches = list(feed)
        assert len(batches) == 2
        assert batches[0].data.dtype == np.float32
        assert batches[0].label.shape == (4,)


def test_trainstep_accepts_fed_batch():
    from mxnet_tpu.parallel import TrainStep
    net = gluon.nn.Dense(2)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer, mesh=None)
    src = iter([(np.ones((4, 3), np.float32), np.ones((4, 2), np.float32))
                for _ in range(2)])
    feed = DeviceFeed(src, ctx=mx.cpu())
    losses = [float(step(b).asscalar()) for b in feed]
    assert len(losses) == 2 and all(np.isfinite(losses))
    with pytest.raises(mx.MXNetError):
        step(mx.nd.ones((4, 3)))          # bare data without a label


# -- satellite: batchify single-gather ---------------------------------

def test_default_batchify_one_bulk_gather():
    from mxnet_tpu.gluon.data.dataloader import default_batchify_fn
    samples = [mx.nd.array(np.full((3,), i, np.float32))
               for i in range(8)]
    telemetry.enable()
    try:
        telemetry.reset("dispatch.host_sync")
        out = default_batchify_fn(samples)
        # one batched device_get, zero per-sample asnumpy round-trips
        assert telemetry.counter("dispatch.host_sync").value == 0
    finally:
        telemetry.disable()
    assert out.shape == (8, 3)
    np.testing.assert_array_equal(out.asnumpy()[:, 0],
                                  np.arange(8, dtype=np.float32))


def test_host_batchify_keeps_numpy_compact():
    from mxnet_tpu.gluon.data.dataloader import host_batchify_fn
    out = host_batchify_fn([np.full((2,), i, np.uint8) for i in range(4)])
    assert isinstance(out, np.ndarray) and out.dtype == np.uint8
    pair = host_batchify_fn([(np.ones(2, np.uint8), 1.0),
                             (np.zeros(2, np.uint8), 2.0)])
    assert pair[0].dtype == np.uint8
    assert pair[1].dtype == np.float32   # float64 scalars compact too


# -- satellite: engine bulk wiring -------------------------------------

def test_engine_set_bulk_size_wired():
    from mxnet_tpu import engine
    from mxnet_tpu.ndarray import bulk
    prev = engine.set_bulk_size(7)
    try:
        assert bulk._MAX_PENDING == 7 and bulk.enabled()
        assert engine.set_bulk_size(9) == 7
        assert engine.set_bulk_size(1) == 9   # <=1 disables
        assert not bulk.enabled()
    finally:
        engine.set_bulk_size(prev if prev else 1)
    assert bulk.enabled() == bool(prev)


def test_engine_bulk_scope_executes_and_restores():
    from mxnet_tpu import engine
    from mxnet_tpu.ndarray import bulk
    before = (bulk._MAX_PENDING, bulk.enabled())
    with engine.bulk(3):
        assert bulk._MAX_PENDING == 3 and bulk.enabled()
        a = mx.nd.ones((2, 2))
        c = (a + 1) * 2
    assert (bulk._MAX_PENDING, bulk.enabled()) == before
    assert c.asnumpy()[0, 0] == 4.0


# -- satellite: abandoned consumers cannot strand a producer -----------

def test_abandoned_feed_releases_producer_thread():
    """A consumer that walks away mid-epoch WITHOUT close() (plain GC)
    must not leave the producer parked forever on a full buffer: the
    producer holds the feed only weakly while blocked, and the
    weakref finalizer stops it."""
    import gc

    src = [np.ones((2, 2), np.float32) for _ in range(64)]
    feed = DeviceFeed(src, ctx=mx.cpu(), depth=1)
    next(feed)                       # producer running, buffer fills
    th = feed._thread
    assert th.is_alive()
    del feed                         # abandon: no close()
    gc.collect()
    th.join(timeout=10)
    assert not th.is_alive(), \
        "producer thread leaked after its consumer was GC'd"


def test_abandoned_prefetching_iter_releases_producer_thread():
    import gc

    from mxnet_tpu.io.io import NDArrayIter, PrefetchingIter

    inner = NDArrayIter(np.ones((64, 2), np.float32), batch_size=2)
    pf = PrefetchingIter(inner, prefetch_depth=1)
    pf.next()
    th = pf._thread
    assert th.is_alive()
    del pf
    gc.collect()
    th.join(timeout=10)
    assert not th.is_alive(), \
        "PrefetchingIter producer leaked after consumer GC"


def test_feed_close_detaches_finalizer_and_joins():
    src = [np.ones((2, 2), np.float32) for _ in range(8)]
    feed = DeviceFeed(src, ctx=mx.cpu(), depth=1)
    next(feed)
    fin = feed._finalizer
    feed.close()
    assert not fin.alive             # close() detached it
    assert feed._thread is None
    feed.close()                     # idempotent
