"""``.params`` container format: spec-fixture import proof + golden
byte lock (reference: ``src/ndarray/ndarray.cc :: NDArray::Save/Load``,
magics ``kMXAPINDArrayListMagic=0x112`` / ``NDARRAY_V2_MAGIC=
0xF993FAC9``).

The point of these tests (VERDICT r3 #9 / r4 #9): the format must be
demonstrated, not asserted.  ``_spec_write`` below is an INDEPENDENT
implementation of the documented binary layout -- written from the
spec, byte by byte with ``struct``, sharing no code with
``mx.nd.save`` -- and a file it produces must load into the zoo
ResNet-50.  The golden-bytes test then locks the writer's exact output
so the layout cannot drift silently.
"""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx

# documented layout constants (spec, not imported from the library)
LIST_MAGIC = 0x112
ND_MAGIC = 0xF993FAC9
DTYPE_FLAG = {np.dtype("float32"): 0, np.dtype("float64"): 1,
              np.dtype("float16"): 2, np.dtype("uint8"): 3,
              np.dtype("int32"): 4, np.dtype("int8"): 5,
              np.dtype("int64"): 6}


def _spec_write(f, named_arrays):
    """Write a .params container from the documented spec:

    header:   uint64 LE list-magic 0x112; uint64 reserved 0;
              uint64 array count
    per array: uint32 ndarray-magic 0xF993FAC9; int32 storage type
              (0 = dense); uint32 ndim; int64 x ndim dims;
              int32 dev_type (1 = cpu) + int32 dev_id; int32 dtype
              flag; raw C-order element bytes
    trailer:  uint64 name count; per name uint64 byte length + utf-8
    """
    names = list(named_arrays)
    f.write(struct.pack("<Q", LIST_MAGIC))
    f.write(struct.pack("<Q", 0))
    f.write(struct.pack("<Q", len(names)))
    for n in names:
        a = np.ascontiguousarray(named_arrays[n])
        f.write(struct.pack("<I", ND_MAGIC))
        f.write(struct.pack("<i", 0))
        f.write(struct.pack("<I", a.ndim))
        for d in a.shape:
            f.write(struct.pack("<q", d))
        f.write(struct.pack("<ii", 1, 0))
        f.write(struct.pack("<i", DTYPE_FLAG[a.dtype]))
        f.write(a.tobytes())
    f.write(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode("utf-8")
        f.write(struct.pack("<Q", len(b)))
        f.write(b)


def test_spec_fixture_loads_into_zoo_resnet50(tmp_path):
    """A container hand-written from the spec (not via mx.nd.save)
    must load into zoo ResNet-50 and install exactly the written
    weights."""
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    net = resnet50_v1()
    net.initialize(ctx=mx.cpu())
    x = mx.nd.ones((1, 3, 224, 224))
    net(x)                                   # materialize all shapes
    params = net._collect_params_with_prefix()
    rng = np.random.RandomState(7)
    fixture = {}
    for name, p in params.items():
        a = p.data().asnumpy()
        v = rng.randn(*a.shape) * 0.01
        if "var" in name:        # BN variances must stay positive
            v = np.abs(v) + 1.0
        fixture[name] = v.astype(a.dtype)
    path = str(tmp_path / "spec_resnet50.params")
    with open(path, "wb") as f:
        _spec_write(f, fixture)

    net.load_parameters(path, ctx=mx.cpu())
    for name, p in net._collect_params_with_prefix().items():
        np.testing.assert_array_equal(p.data().asnumpy(), fixture[name],
                                      err_msg=name)
    # and the loaded net must actually run
    out = net(x)
    assert out.shape == (1, 1000)
    assert np.isfinite(out.asnumpy()).all()


def test_spec_fixture_mx_nd_load_mixed_dtypes(tmp_path):
    """mx.nd.load must read a spec-written file across dtypes and
    ranks (including the empty-name list form)."""
    fixture = {
        "w": np.arange(12, dtype=np.float32).reshape(3, 4),
        "idx": np.array([3, 1, 2], dtype=np.int64),
        "bytes": np.arange(8, dtype=np.uint8).reshape(2, 2, 2),
        "scalar": np.array(2.5, dtype=np.float64).reshape(()),
    }
    path = str(tmp_path / "mixed.params")
    with open(path, "wb") as f:
        _spec_write(f, fixture)
    loaded = mx.nd.load(path)
    assert set(loaded) == set(fixture)
    # 64-bit values land as the package's canonical 32-bit device
    # dtypes (TPU-native convention, same as mx.nd.array's float64 ->
    # float32); values are preserved exactly for these fixtures
    canon = {np.dtype("int64"): np.dtype("int32"),
             np.dtype("float64"): np.dtype("float32")}
    for k, v in fixture.items():
        got = loaded[k].asnumpy()
        assert got.dtype == canon.get(v.dtype, v.dtype), k
        np.testing.assert_array_equal(got, v.astype(got.dtype),
                                      err_msg=k)


def test_save_matches_spec_writer_byte_for_byte(tmp_path):
    """mx.nd.save's output must equal the independent spec writer's,
    byte for byte -- the two implementations lock each other."""
    fixture = {
        "w": np.arange(6, dtype=np.float32).reshape(2, 3),
        "b": np.array([0.5, -1.5], dtype=np.float32),
    }
    lib_path = str(tmp_path / "lib.params")
    mx.nd.save(lib_path, {k: mx.nd.array(v) for k, v in fixture.items()})
    spec_path = str(tmp_path / "spec.params")
    with open(spec_path, "wb") as f:
        _spec_write(f, fixture)
    assert open(lib_path, "rb").read() == open(spec_path, "rb").read()


# Golden bytes for {"g": float32 [[1, 2]]}: locks the on-disk layout
# against silent drift in BOTH the library and the spec writer.
_GOLDEN_HEX = (
    "1201000000000000"          # uint64 list magic 0x112
    "0000000000000000"          # uint64 reserved
    "0100000000000000"          # uint64 count = 1
    "c9fa93f9"                  # uint32 ndarray magic 0xF993FAC9
    "00000000"                  # int32 stype = dense
    "02000000"                  # uint32 ndim = 2
    "0100000000000000"          # int64 dim 0 = 1
    "0200000000000000"          # int64 dim 1 = 2
    "01000000" "00000000"       # dev_type=1 (cpu), dev_id=0
    "00000000"                  # int32 dtype flag = float32
    "0000803f" "00000040"       # 1.0f, 2.0f LE
    "0100000000000000"          # uint64 name count = 1
    "0100000000000000"          # uint64 name length = 1
    "67"                        # "g"
)


def test_golden_bytes_lock(tmp_path):
    arr = np.array([[1.0, 2.0]], dtype=np.float32)
    path = str(tmp_path / "g.params")
    mx.nd.save(path, {"g": mx.nd.array(arr)})
    assert open(path, "rb").read().hex() == _GOLDEN_HEX
    loaded = mx.nd.load(path)
    np.testing.assert_array_equal(loaded["g"].asnumpy(), arr)
    # and the golden bytes themselves load
    gpath = str(tmp_path / "golden.params")
    open(gpath, "wb").write(bytes.fromhex(_GOLDEN_HEX))
    loaded2 = mx.nd.load(gpath)
    np.testing.assert_array_equal(loaded2["g"].asnumpy(), arr)
