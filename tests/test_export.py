"""Export / SymbolBlock round-trip tests (reference:
``test_gluon.py :: test_symbol_block`` + ``test_export``)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _net():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, kernel_size=3, padding=1,
                            activation="relu"),
            gluon.nn.BatchNorm(),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(10))
    return net


def test_export_symbolblock_roundtrip(tmp_path):
    mx.random.seed(0)
    net = _net()
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(0)
                    .randn(2, 3, 8, 8).astype(np.float32))
    want = net(x).asnumpy()

    prefix = str(tmp_path / "m")
    net.export(prefix)

    loaded = gluon.SymbolBlock.imports(prefix + "-symbol.json", ["data"],
                                       prefix + "-0000.params")
    got = loaded(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_exported_json_loads_as_module(tmp_path):
    """The exported -symbol.json + .params follow the reference
    checkpoint convention, so Module.load consumes them directly."""
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(3))
    net.initialize()
    net.hybridize()
    x = mx.nd.array(np.random.RandomState(1)
                    .randn(4, 6).astype(np.float32))
    want = net(x).asnumpy()

    prefix = str(tmp_path / "m")
    net.export(prefix)
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, 0)
    mod = mx.mod.Module(sym, data_names=("data",), label_names=())
    mod.bind(data_shapes=[("data", (4, 6))], for_training=False)
    mod.init_params(arg_params=arg_params, aux_params=aux_params)
    mod.forward(mx.io.DataBatch(data=[x]), is_train=False)
    got = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_symbol_json_schema(tmp_path):
    import json
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    j = json.loads(out.tojson())
    assert set(j) >= {"nodes", "arg_nodes", "heads"}
    ops = [n["op"] for n in j["nodes"]]
    assert "null" in ops and "FullyConnected" in ops
    # round trip through load_json
    s2 = mx.sym.load_json(out.tojson())
    assert s2.list_arguments() == out.list_arguments()
