"""Driver-contract test: run ``__graft_entry__.dryrun_multichip`` exactly
the way the driver does -- a fresh interpreter whose environment does NOT
preselect a JAX platform -- and require it to pass hermetically.

This is the regression test for the round-2 failure (MULTICHIP_r02
``ok:false``): the dryrun initialized the default backend (a real TPU
behind a tunnel) before falling back to CPU devices.  The wrapper now
re-execs its body in a scrubbed CPU-only env, so this must pass no matter
what backend the calling process would default to.
"""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dryrun_multichip_driver_contract():
    env = dict(os.environ)
    # Simulate the driver's raw environment: no explicit platform choice,
    # whatever XLA_FLAGS happen to be set (the wrapper must override the
    # virtual device count itself).
    env.pop("JAX_PLATFORMS", None)
    code = ("import sys; sys.path.insert(0, %r); "
            "import __graft_entry__ as g; g.dryrun_multichip(8)" % REPO)
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                          text=True, timeout=900)
    assert proc.returncode == 0, proc.stdout[-4000:]
    assert "dryrun_multichip OK" in proc.stdout, proc.stdout[-4000:]
