"""perflint (ISSUE 10): per-rule static fixtures, the compiled-HLO
audit contract on a transpose-seeded toy executable, the perf-baseline
round trip, the model_zoo layout threading, and regression tests for
the ride-along bugfixes (bench e2e constructor cleanup, bench
subprocess diagnostics, bulk enqueue stale-resolution outside the
lock, ImageIter's __main__.__file__ confinement)."""
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import analysis as an
from mxnet_tpu import gluon
from mxnet_tpu.analysis import perf

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules_of(diags):
    return sorted({d.rule for d in diags})


def _lint(src):
    return an.lint_source(src, "probe.py")


# ----------------------------------------------------------------------
# static rules: one positive and one negative fixture per rule
# ----------------------------------------------------------------------

def test_layout_hostile_conv_fires_and_explicit_layout_silent():
    bad = (
        "def build(nn):\n"
        "    net.add(nn.Conv2D(32, kernel_size=3))\n"
        "    net.add(nn.MaxPool2D(2))\n"
        "    net.add(nn.GlobalAvgPool2D())\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["layout-hostile-conv"]
    assert len(diags) == 3
    good = (
        "def build(nn, layout):\n"
        "    net.add(nn.Conv2D(32, kernel_size=3, layout=layout))\n"
        "    net.add(nn.MaxPool2D(2, layout='NHWC'))\n"
        "    net.add(nn.Dense(64))\n"          # Dense has no layout
    )
    assert _lint(good) == []


def test_layout_hostile_conv_kwargs_splat_not_decidable():
    src = (
        "def build(nn, kw):\n"
        "    net.add(nn.Conv2D(32, 3, **kw))\n"
    )
    assert _lint(src) == []


def test_pad_waste_fires_with_did_you_mean_and_aligned_silent():
    bad = "def build(nn, layout):\n    nn.Dense(500)\n"
    diags = _lint(bad)
    assert _rules_of(diags) == ["pad-waste"]
    assert "did you mean 512" in diags[0].message
    # aligned, non-literal, and structurally-small dims all pass
    good = (
        "def build(nn, c, layout):\n"
        "    nn.Dense(512)\n"
        "    nn.Dense(c)\n"
        "    nn.Dense(10)\n"                   # class head: < 16
        "    nn.Conv2D(64, 3, layout=layout)\n"
    )
    assert _lint(good) == []
    # sublane-misaligned conv channels name the sublane multiple
    d = _lint("def f(nn, layout):\n"
              "    nn.Conv2D(20, 5, layout=layout)\n")
    assert _rules_of(d) == ["pad-waste"]
    assert "did you mean 24" in d[0].message


def test_python_loop_unroll_fires_in_traced_scopes_only():
    bad = (
        "class M:\n"
        "    def hybrid_forward(self, F, x):\n"
        "        for i in range(8):\n"
        "            x = F.relu(x)\n"
        "        for cell in self.cells:\n"
        "            x = cell(x)\n"
        "        return x\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["python-loop-unroll"]
    assert len(diags) == 2
    good = (
        "class M:\n"
        "    def hybrid_forward(self, F, x):\n"
        "        for i in range(2):\n"         # below unroll threshold
        "            x = F.relu(x)\n"
        "        return x\n"
        "def driver(step, x, y):\n"
        "    for _ in range(100):\n"           # eager driver loop: fine
        "        loss = train(x, y)\n"
        "    return loss\n"
    )
    assert _lint(good) == []


def test_python_loop_unroll_fires_in_jitted_step_fn():
    bad = (
        "import jax\n"
        "def train_step(pvals, x):\n"
        "    for i in range(16):\n"
        "        x = x * 2\n"
        "    return x\n"
        "fn = jax.jit(train_step, donate_argnums=(0,))\n"
    )
    assert "python-loop-unroll" in _rules_of(_lint(bad))


def test_scalar_recompile_fires_outside_dynamic_set_only():
    bad = (
        "def update(nd, w, g, scale):\n"
        "    return nd.cast_scale(w, g, loss_scale=scale)\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["scalar-recompile"]
    assert "loss_scale" in diags[0].message
    good = (
        "def update(nd, w, g, cur_lr, scale):\n"
        "    a = nd.sgd_update(w, g, lr=cur_lr)\n"   # lr IS dynamic
        "    b = nd.cast_scale(w, g, loss_scale=2.0)\n"  # literal: one key
        "    helper(loss_scale=scale)\n"             # not an op invoke
        "    return a, b\n"
    )
    assert _lint(good) == []


def test_eager_in_step_loop_fires_and_ingest_exempt():
    bad = (
        "def train(step, nd, batches):\n"
        "    for x, y in batches:\n"
        "        x = nd.transpose(x, axes=(0, 2, 3, 1))\n"
        "        loss = step(x, y)\n"
        "    return loss\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["eager-in-step-loop"]
    assert "nd.transpose" in diags[0].message
    good = (
        "def train(step, mx, shards):\n"
        "    for s in shards:\n"
        "        x = mx.nd.array(s)\n"          # ingest: exempt
        "        loss = step(x)\n"
        "    for s in shards:\n"
        "        y = mx.nd.transpose(s)\n"      # no step() in this loop\n"
        "    return loss, y\n"
    )
    assert _lint(good) == []


def test_perf_rule_suppression_directive():
    src = ("def build(nn, layout):\n"
           "    nn.Dense(500)  # mxlint: disable=pad-waste\n")
    assert _lint(src) == []


def test_perf_rules_registered_and_self_lint_clean():
    for rid in ("layout-hostile-conv", "pad-waste", "python-loop-unroll",
                "scalar-recompile", "eager-in-step-loop", "perf-drift"):
        assert rid in an.RULES, rid
    # the armed-rules acceptance: the model code the rules forced into
    # shape stays clean (full --self runs in CI; model_zoo+bench here)
    diags = an.lint_paths([os.path.join(REPO, "mxnet_tpu", "gluon",
                                        "model_zoo"),
                           os.path.join(REPO, "bench.py")])
    assert [d.format() for d in diags] == []


# ----------------------------------------------------------------------
# compiled audit: advisory contract on a transpose-seeded toy
# ----------------------------------------------------------------------

def _register_toy(label, fn, *args):
    import jax
    from mxnet_tpu.profiling import store
    jfn = jax.jit(fn)
    jfn(*args)
    store.register((label,), label, jfn, args)
    return jfn


def test_perf_audit_transpose_advisory_contract():
    import jax.numpy as jnp
    from mxnet_tpu import profiling
    profiling.reset()
    _register_toy("toy:transpose",
                  lambda x: jnp.transpose(x, (1, 0)) + 0.0,
                  jnp.ones((256, 512), jnp.float32))
    audit = perf.perf_audit(peaks=(5e11, 5e10))
    assert audit["schema"] == perf.AUDIT_SCHEMA
    ex = audit["executables"]["toy:transpose"]
    assert ex["metrics"]["transpose_share"] > 0.9
    kinds = {a["kind"]: a for a in ex["advisories"]}
    assert "transpose-share" in kinds
    adv = kinds["transpose-share"]
    assert adv["category"] == "transpose_layout"
    assert adv["share"] > 0.9
    assert any("transpose" in nm for nm in adv["op_names"])
    # ranked advisories carry the executable name
    assert any(a["executable"] == "toy:transpose" and
               a["kind"] == "transpose-share"
               for a in audit["advisories"])
    profiling.reset()


def test_perf_audit_compute_bound_matmul_clean():
    import jax.numpy as jnp
    from mxnet_tpu import profiling
    profiling.reset()
    _register_toy("toy:matmul",
                  lambda a, b: a @ b,
                  jnp.ones((256, 256), jnp.float32),
                  jnp.ones((256, 256), jnp.float32))
    # generous peaks: ridge tiny, so a tile-aligned matmul audits clean
    audit = perf.perf_audit(peaks=(1e9, 1e12))
    ex = audit["executables"]["toy:matmul"]
    assert ex["advisories"] == [], ex
    assert ex["metrics"]["pad_waste"] == 0.0
    assert ex["metrics"]["flops"] > 0
    profiling.reset()


def test_audit_hlo_text_counters_direct():
    import jax
    import jax.numpy as jnp
    f = jax.jit(lambda x: jnp.transpose(x, (1, 0)).copy())
    x = jnp.ones((128, 128), jnp.float32)
    text = f.lower(x).compile().as_text()
    c = perf.audit_hlo_text(text)
    assert c["bytes_total"] > 0
    assert c["category_bytes"]["transpose_layout"] > 0
    assert c["mxu_padded_bytes"] == 0        # no conv/dot in the module


# ----------------------------------------------------------------------
# baseline round trip: bless -> self-diff zero -> seeded regression
# ----------------------------------------------------------------------

def test_perf_baseline_round_trip(tmp_path):
    import jax.numpy as jnp
    from mxnet_tpu import profiling
    profiling.reset()
    _register_toy("toy:roundtrip",
                  lambda x: jnp.transpose(x, (1, 0)) + 0.0,
                  jnp.ones((128, 256), jnp.float32))
    base_path = str(tmp_path / "perf_baseline.json")
    base = perf.save_audit(base_path, perf.perf_audit(peaks=(5e11, 5e10)))
    assert perf.load_audit(base_path)["schema"] == perf.AUDIT_SCHEMA

    # self-diff: zero drift, CLI exit 0
    assert perf.diff_audit(base, base) == []
    assert an.main(["--perf-diff", base_path, base_path]) == 0

    # seeded transpose regression: grown share + unblessed advisory kind
    cur = json.loads(json.dumps(base))
    row = cur["executables"]["toy:roundtrip"]
    row["metrics"]["transpose_share"] = \
        base["executables"]["toy:roundtrip"]["metrics"][
            "transpose_share"] + 0.1
    row["advisories"].append({"kind": "hlo-pad-waste",
                              "category": "conv_dot", "share": 0.5,
                              "op_names": [], "message": "seeded"})
    cur_path = str(tmp_path / "current.json")
    with open(cur_path, "w") as f:
        json.dump(cur, f)
    diags = perf.diff_audit(base, perf.load_audit(cur_path))
    kinds = {d.rule for d in diags}
    assert kinds == {"perf-drift"}
    msgs = "\n".join(d.message for d in diags)
    assert "transpose_share grew" in msgs
    assert "hlo-pad-waste" in msgs
    assert an.main(["--perf-diff", base_path, cur_path]) == 1

    # improvements pass: smaller share, advisory gone
    better = json.loads(json.dumps(base))
    better["executables"]["toy:roundtrip"]["metrics"][
        "transpose_share"] = 0.0
    better["executables"]["toy:roundtrip"]["advisories"] = []
    assert perf.diff_audit(base, better) == []
    profiling.reset()


def test_perf_audit_schema_reject(tmp_path):
    p = tmp_path / "bogus.json"
    p.write_text(json.dumps({"schema": "nope", "executables": {}}))
    with pytest.raises(ValueError, match="mxperf.audit.v1"):
        perf.load_audit(str(p))
    assert an.main(["--perf-diff", str(p), str(p)]) == 2


def test_committed_perf_baseline_is_loadable():
    base = perf.load_audit(os.path.join(REPO, "ci", "perf_baseline.json"))
    labels = set(base["executables"])
    assert "train_step:PerfLeNet" in labels
    assert "hybrid:ResNetV1" in labels


# ----------------------------------------------------------------------
# model_zoo layout threading (the layout-hostile-conv fixes)
# ----------------------------------------------------------------------

def _pair_and_copy(a, b):
    """Copy a's weights into b, permuting conv kernels OIHW -> OHWI."""
    from conftest import paired_params
    for pa, pb in paired_params(a, b):
        w = pa.data().asnumpy()
        if w.ndim == 4 and "conv" in pa.name:
            w = np.transpose(w, (0, 2, 3, 1))
        assert pb.shape == w.shape, (pa.name, pb.shape, w.shape)
        pb.set_data(mx.nd.array(w))


def test_densenet_nhwc_matches_nchw():
    """Tiny DenseNet: covers BatchNorm axis AND the dense-block concat
    following layout.index('C')."""
    from mxnet_tpu.gluon.model_zoo.vision.densenet import DenseNet
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 16, 16).astype(np.float32)

    a = DenseNet(8, 4, [2, 2], classes=7, layout="NCHW")
    a.initialize(ctx=mx.cpu())
    ya = a(mx.nd.array(x)).asnumpy()

    b = DenseNet(8, 4, [2, 2], classes=7, layout="NHWC")
    b.initialize(ctx=mx.cpu())
    xb = mx.nd.array(np.transpose(x, (0, 2, 3, 1)))
    b(xb)                                    # materialize deferred shapes
    _pair_and_copy(a, b)
    yb = b(xb).asnumpy()
    np.testing.assert_allclose(ya, yb, rtol=1e-4, atol=1e-4)


def test_fire_and_mixed_blocks_nhwc_match_nchw():
    """SqueezeNet fire paths + inception towers: the two remaining
    concat-on-channels code paths."""
    from mxnet_tpu.gluon.model_zoo.vision.inception import (_Mixed,
                                                            _Tower)
    from mxnet_tpu.gluon.model_zoo.vision.squeezenet import _FirePaths
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 8, 8).astype(np.float32)

    for build in (
            lambda lo: _FirePaths(8, 8, layout=lo),
            lambda lo: _Mixed([_Tower([(8, 1, 1, 0)], layout=lo),
                               _Tower([(4, 3, 1, 1)], layout=lo)],
                              layout=lo)):
        a = build("NCHW")
        a.initialize(ctx=mx.cpu())
        ya = a(mx.nd.array(x)).asnumpy()
        b = build("NHWC")
        b.initialize(ctx=mx.cpu())
        xb = mx.nd.array(np.transpose(x, (0, 2, 3, 1)))
        b(xb)
        _pair_and_copy(a, b)
        yb = b(xb).asnumpy()
        np.testing.assert_allclose(ya, np.transpose(yb, (0, 3, 1, 2)),
                                   rtol=1e-4, atol=1e-4)


def test_model_zoo_layout_kwarg_accepted_everywhere():
    """Every vision constructor takes layout= (the threading contract);
    construction alone must not raise."""
    from mxnet_tpu.gluon.model_zoo import vision
    for ctor in (vision.alexnet, vision.vgg11, vision.squeezenet1_1,
                 vision.densenet121, vision.mobilenet0_25,
                 vision.mobilenet_v2_0_25, vision.inception_v3,
                 vision.resnet18_v1):
        net = ctor(classes=10, layout="NHWC")
        assert net is not None


@pytest.mark.slow
def test_mobilenet_nhwc_matches_nchw():
    """Depthwise/grouped convs through the channels-last path."""
    from mxnet_tpu.gluon.model_zoo.vision.mobilenet import MobileNet
    rng = np.random.RandomState(2)
    x = rng.randn(1, 3, 32, 32).astype(np.float32)
    a = MobileNet(multiplier=0.25, classes=7, layout="NCHW")
    a.initialize(ctx=mx.cpu())
    ya = a(mx.nd.array(x)).asnumpy()
    b = MobileNet(multiplier=0.25, classes=7, layout="NHWC")
    b.initialize(ctx=mx.cpu())
    xb = mx.nd.array(np.transpose(x, (0, 2, 3, 1)))
    b(xb)
    _pair_and_copy(a, b)
    yb = b(xb).asnumpy()
    np.testing.assert_allclose(ya, yb, rtol=1e-4, atol=1e-4)


# ----------------------------------------------------------------------
# satellite regressions: bench e2e constructor cleanup + subprocess tail
# ----------------------------------------------------------------------

def _bench_mod():
    sys.path.insert(0, REPO)
    try:
        import bench
    finally:
        sys.path.pop(0)
    return bench


def test_subprocess_pair_failure_raises_with_stderr_tail():
    bench = _bench_mod()
    with pytest.raises(RuntimeError) as ei:
        bench._subprocess_pair("bench.no_such_function()", timeout=120)
    msg = str(ei.value)
    assert "exited" in msg and "AttributeError" in msg


def test_bench_e2e_constructor_failure_cleans_up(monkeypatch):
    """A constructor failing inside bench_resnet50_e2e must propagate
    immediately (no producer deadlock) with the tmp dir removed and the
    telemetry enable-state restored (ADVICE round-5 medium)."""
    import glob
    import mxnet_tpu.image as image_mod
    from mxnet_tpu import telemetry
    from mxnet_tpu.base import MXNetError
    import mxnet_tpu.gluon.model_zoo.vision as vision_mod
    bench = _bench_mod()

    def tiny_net(**kw):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Flatten(), gluon.nn.Dense(8))
        return net

    class BoomIter:
        def __init__(self, *a, **kw):
            raise MXNetError("seeded ImageIter constructor failure")

    monkeypatch.setattr(vision_mod, "resnet50_v1", tiny_net)
    monkeypatch.setattr(image_mod, "ImageIter", BoomIter)
    was_enabled = telemetry.enabled()
    before = set(glob.glob("/tmp/mxtpu_bench_e2e_*"))
    t0 = time.time()
    with pytest.raises(MXNetError, match="seeded ImageIter"):
        bench.bench_resnet50_e2e(batch_size=2, n_images=4, epochs=1)
    assert time.time() - t0 < 120          # surfaced, not a hang
    assert telemetry.enabled() == was_enabled
    assert set(glob.glob("/tmp/mxtpu_bench_e2e_*")) == before


# ----------------------------------------------------------------------
# satellite regression: bulk enqueue resolves stale inputs off-lock
# ----------------------------------------------------------------------

def test_bulk_enqueue_stale_wait_does_not_hold_lock():
    """An enqueue whose input belongs to another region's in-flight
    execution must park on that region's done event WITHOUT holding the
    global bulk lock -- other threads' eager dispatch keeps flowing."""
    import jax.numpy as jnp
    from mxnet_tpu.ndarray import bulk
    if not bulk.enabled():
        pytest.skip("bulking disabled")
    bulk.flush()

    fnc = lambda x: x + 1.0  # noqa: E731
    tag = "perrequire_stale_probe"
    x0 = jnp.ones((4,), jnp.float32)
    warm = bulk.enqueue(fnc, tag, (x0,))       # warmup: concrete out
    assert not isinstance(warm, bulk.LazyData)

    reg = bulk._Region()                       # an "executing" region
    ld = bulk.LazyData((4,), jnp.float32, 0, region=reg)
    out = {}

    def worker():
        out["val"] = bulk.enqueue(fnc, tag, (ld,))

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    time.sleep(0.3)                            # let it park on reg.done
    assert t.is_alive()
    got = bulk._LOCK.acquire(blocking=False)   # lock must be free
    assert got, "enqueue holds the bulk lock while waiting on a region"
    bulk._LOCK.release()
    ld._concrete = jnp.zeros((4,), jnp.float32)
    reg.done.set()
    t.join(timeout=10)
    assert not t.is_alive()
    res = bulk.materialize(out["val"])
    np.testing.assert_allclose(np.asarray(res), np.ones((4,)))
    bulk.flush()


def test_bulk_enqueue_recomputes_descr_after_resolution():
    """A resolved LazyData input keys the region as a concrete array
    ('arr'), not as 'lazyaval' -- the region replay cache cannot split
    on how the same value arrived."""
    import jax.numpy as jnp
    from mxnet_tpu.ndarray import bulk
    if not bulk.enabled():
        pytest.skip("bulking disabled")
    bulk.flush()

    fnc = lambda x: x * 2.0  # noqa: E731
    tag = "perfdescr_probe"
    x0 = jnp.ones((4,), jnp.float32)
    bulk.enqueue(fnc, tag, (x0,))              # warmup
    ld = bulk.enqueue(fnc, tag, (x0,))         # pending LazyData
    assert isinstance(ld, bulk.LazyData)
    bulk.flush()                               # resolves ld
    assert ld._concrete is not None
    out = bulk.enqueue(fnc, tag, (ld,))        # resolved input
    with bulk._LOCK:
        assert bulk._key_parts, "expected a pending entry"
        descr = bulk._key_parts[-1][3]
    assert descr[0][0] == "arr", descr
    np.testing.assert_allclose(np.asarray(bulk.materialize(out)),
                               4 * np.ones((4,)))
    bulk.flush()


def test_bulk_enqueue_failed_stale_input_reraises():
    """A LazyData poisoned by a prior failed flush re-raises ITS error
    when used as an input to a later enqueue."""
    import jax.numpy as jnp
    from mxnet_tpu.ndarray import bulk
    if not bulk.enabled():
        pytest.skip("bulking disabled")
    bulk.flush()
    fnc = lambda x: x + 1.0  # noqa: E731
    tag = "perffail_probe"
    x0 = jnp.ones((2,), jnp.float32)
    bulk.enqueue(fnc, tag, (x0,))              # warmup
    poisoned = bulk.LazyData((2,), jnp.float32, 0,
                             region=bulk._Region())
    poisoned._error = RuntimeError("seeded upstream failure")
    with pytest.raises(RuntimeError, match="seeded upstream"):
        bulk.enqueue(fnc, tag, (poisoned,))
    bulk.flush()


# ----------------------------------------------------------------------
# satellite regression: ImageIter restores __main__.__file__ on close
# ----------------------------------------------------------------------

def test_imageiter_restores_main_file_on_close(tmp_path):
    from mxnet_tpu import recordio
    from mxnet_tpu.image import ImageIter

    path = str(tmp_path / "probe")
    rec = recordio.MXIndexedRecordIO(path + ".idx", path + ".rec", "w")
    rng = np.random.RandomState(0)
    for i in range(8):
        img = rng.randint(0, 255, (8, 8, 3), dtype=np.uint8)
        rec.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), img.tobytes()))
    rec.close()

    main_mod = sys.modules["__main__"]
    had_file = hasattr(main_mod, "__file__")
    orig = getattr(main_mod, "__file__", None)
    bogus = str(tmp_path / "definitely_missing_main.py")
    main_mod.__file__ = bogus
    try:
        it = ImageIter(4, (3, 8, 8), path_imgrec=path + ".rec",
                       preprocess_procs=2, dtype="uint8",
                       aug_list=[])
        try:
            # the spawn workaround is CONFINED: removed while the pool
            # lives (respawned workers must not see the bogus path)...
            assert not hasattr(main_mod, "__file__")
            d, labels, pad = it.next_np()
            assert d.shape == (4, 3, 8, 8)
        finally:
            it.close()
        # ...and restored exactly once the pool is dead
        assert getattr(main_mod, "__file__", None) == bogus
    finally:
        if had_file:
            main_mod.__file__ = orig
        elif hasattr(main_mod, "__file__"):
            del main_mod.__file__
