"""Initializer distribution tests + model-zoo forward-shape tests
(reference: ``test_init.py`` / ``test_gluon_model_zoo.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon


def _init_arr(init, shape=(64, 64), name="weight"):
    arr = mx.nd.zeros(shape)
    init(mx.init.InitDesc(name), arr)
    return arr.asnumpy()


def test_constant_zero_one():
    assert (_init_arr(mx.init.Zero()) == 0).all()
    assert (_init_arr(mx.init.One()) == 1).all()
    assert (_init_arr(mx.init.Constant(2.5)) == 2.5).all()


def test_uniform_normal_ranges():
    u = _init_arr(mx.init.Uniform(0.3))
    assert u.min() >= -0.3 and u.max() <= 0.3 and u.std() > 0.05
    n = _init_arr(mx.init.Normal(0.5), shape=(128, 128))
    assert abs(n.std() - 0.5) < 0.05


def test_xavier_magnitude():
    x = _init_arr(mx.init.Xavier(factor_type="avg", magnitude=3),
                  shape=(100, 100))
    bound = np.sqrt(3.0 / 100)
    assert abs(x).max() <= bound + 1e-6
    assert x.std() > bound / 4


def test_orthogonal():
    w = _init_arr(mx.init.Orthogonal(scale=1.0), shape=(32, 32))
    np.testing.assert_allclose(w @ w.T, np.eye(32), atol=1e-4)
    # reference default scale is sqrt(2): W W^T = 2 I
    w2 = _init_arr(mx.init.Orthogonal(), shape=(16, 16))
    np.testing.assert_allclose(w2 @ w2.T, np.eye(16) * 1.414 ** 2,
                               atol=1e-3)


def test_name_dispatch():
    """gamma/beta/bias/moving stats get their canonical values."""
    init = mx.init.Xavier()
    assert (_init_arr(init, (8,), "bn_gamma") == 1).all()
    assert (_init_arr(init, (8,), "bn_beta") == 0).all()
    assert (_init_arr(init, (8,), "fc_bias") == 0).all()
    assert (_init_arr(init, (8,), "bn_moving_mean") == 0).all()
    assert (_init_arr(init, (8,), "bn_moving_var") == 1).all()


def test_mixed_initializer():
    # note: names like *_gamma dispatch to the Initializer's gamma rule,
    # so Mixed patterns are exercised with plain weight-like names
    mixed = mx.init.Mixed([".*special", ".*"],
                          [mx.init.Constant(3.0), mx.init.Zero()])
    assert (_init_arr(mixed, (4,), "x_special") == 3.0).all()
    assert (_init_arr(mixed, (4,), "weight") == 0.0).all()


# ----------------------------------------------------------------------
# model zoo forward shapes
# ----------------------------------------------------------------------

def test_get_model_registry():
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    for name in ("resnet18_v1", "resnet50_v1", "vgg11", "alexnet",
                 "squeezenet1.0", "mobilenet1.0", "densenet121",
                 "inceptionv3"):
        net = get_model(name, classes=10)
        assert net is not None
    with pytest.raises(Exception):
        get_model("not_a_model")


@pytest.mark.parametrize("name,size", [("resnet18_v1", 32),
                                       ("mobilenet0.25", 32)])
def test_zoo_forward_shape(name, size):
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    mx.random.seed(0)
    net = get_model(name, classes=7)
    net.initialize()
    x = mx.nd.zeros((2, 3, size, size))
    out = net(x)
    assert out.shape == (2, 7)


def test_resnet50_forward_shape():
    """The BASELINE config-2 model builds and runs (reference:
    ``test_gluon_model_zoo.py :: test_models``)."""
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    mx.random.seed(0)
    net = resnet50_v1(classes=1000)
    net.initialize()
    out = net(mx.nd.zeros((1, 3, 224, 224)))
    assert out.shape == (1, 1000)
