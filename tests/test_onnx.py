"""ONNX export/import round-trips (reference: ``mx.contrib.onnx``).

The serializer is a self-contained protobuf wire-format implementation
(``mxnet_tpu/onnx/wire.py``); these tests check (a) the wire level
against an independent minimal TLV parser written here, (b) numeric
round-trips export -> import -> forward for LeNet and ResNet-50,
(c) interop with the real ``onnx`` package when it is installed.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.onnx import export_model, get_model_metadata, import_model
from mxnet_tpu.onnx import wire


# -- independent TLV walker (deliberately not reusing wire.py) ---------

def _walk(buf):
    fields = []
    pos = 0
    while pos < len(buf):
        key = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        num, wt = key >> 3, key & 7
        if wt == 0:
            v = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            v = buf[pos:pos + ln]
            assert len(v) == ln, "truncated field"
            pos += ln
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise AssertionError("bad wire type %d" % wt)
        fields.append((num, wt, v))
    return fields


def _eval_sym(sym, arg_params, aux_params, **inputs):
    vals = dict(arg_params)
    vals.update(aux_params)
    vals.update({k: mx.nd.array(v) for k, v in inputs.items()})
    out = sym.eval(**vals)
    return (out[0] if isinstance(out, (list, tuple)) else out).asnumpy()


def _roundtrip_block(net, x, tmp_path, name):
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    want = net(mx.nd.array(x)).asnumpy()
    prefix = str(tmp_path / name)
    sym_file, params_file = net.export(prefix)
    onnx_file = str(tmp_path / (name + ".onnx"))
    export_model(sym_file, params_file, in_shapes=[x.shape],
                 in_types=[np.float32], onnx_file_path=onnx_file)

    # the file parses under an independent TLV walker and has a graph
    buf = open(onnx_file, "rb").read()
    top = dict((n, v) for n, wt, v in _walk(buf))
    assert 1 in top and 7 in top and 8 in top  # ir_version, graph, opset
    gfields = _walk(top[7])
    op_types = []
    for num, wt, v in gfields:
        if num == 1:  # NodeProto
            for n2, wt2, v2 in _walk(v):
                if n2 == 4:
                    op_types.append(v2.decode())
    assert op_types, "graph has no nodes"

    sym, arg_params, aux_params = import_model(onnx_file)
    got = _eval_sym(sym, arg_params, aux_params, data=x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    return onnx_file, op_types


def test_wire_tensor_attr_roundtrip():
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    name, back = wire.parse_tensor(wire.make_tensor("t", arr))
    assert name == "t"
    np.testing.assert_array_equal(back, arr)
    i64 = np.asarray([3, -1, 0], np.int64)
    _, back2 = wire.parse_tensor(wire.make_tensor("s", i64))
    np.testing.assert_array_equal(back2, i64)
    for val in (1.5, 7, "hello", [1, 2, 3], [1.0, 2.5], ["a", "b"]):
        k, v = wire.parse_attr(wire.make_attr("k", val))
        assert k == "k"
        if isinstance(val, list):
            assert list(v) == val
        else:
            assert v == val


def test_lenet_roundtrip(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(16, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
    _file, op_types = _roundtrip_block(net, x, tmp_path, "lenet")
    assert "Conv" in op_types and "Gemm" in op_types \
        and "MaxPool" in op_types


def test_resnet50_roundtrip(tmp_path):
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    net = resnet50_v1()
    x = np.random.RandomState(0).randn(1, 3, 64, 64).astype(np.float32)
    _file, op_types = _roundtrip_block(net, x, tmp_path, "resnet50")
    assert "BatchNormalization" in op_types \
        and "GlobalAveragePool" in op_types and "Add" in op_types


def test_metadata(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    x = np.zeros((2, 8), np.float32)
    onnx_file, _ = _roundtrip_block(net, x, tmp_path, "mlp")
    meta = get_model_metadata(onnx_file)
    (in_name, in_shape), = meta["input_tensor_data"]
    assert in_name == "data" and tuple(in_shape) == (2, 8)
    assert len(meta["output_tensor_data"]) == 1


def test_import_rejects_garbage(tmp_path):
    p = tmp_path / "bad.onnx"
    p.write_bytes(b"\xff\xff\xff\xff")
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        import_model(str(p))


def test_onnx_package_interop(tmp_path):
    onnx = pytest.importorskip("onnx")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, kernel_size=3, activation="relu"),
            gluon.nn.Flatten(), gluon.nn.Dense(10))
    x = np.zeros((1, 1, 8, 8), np.float32)
    onnx_file, _ = _roundtrip_block(net, x, tmp_path, "interop")
    model = onnx.load(onnx_file)
    onnx.checker.check_model(model)


def test_dot_export_rank_guard(tmp_path):
    """mx dot is tensordot(axes=1); ONNX MatMul diverges once the RHS
    has rank > 2, so such exports must be rejected, not silently wrong.
    Rank-2 dot exports fine and round-trips numerically."""
    from mxnet_tpu.base import MXNetError
    rng = np.random.RandomState(0)

    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.dot(a, b)

    # rank-2 x rank-2: representable; numeric round-trip
    av = rng.randn(3, 4).astype(np.float32)
    bv = rng.randn(4, 5).astype(np.float32)
    f = str(tmp_path / "dot2.onnx")
    export_model(out, {"b": mx.nd.array(bv)}, in_shapes=[av.shape],
                 onnx_file_path=f)
    isym, iargs, _iaux = import_model(f)
    feeds = {k: v for k, v in iargs.items()}
    feeds["a"] = mx.nd.array(av)
    got = isym.eval(**feeds)[0].asnumpy()
    np.testing.assert_allclose(got, av @ bv, rtol=1e-5, atol=1e-6)

    # rank-3 RHS: MatMul would broadcast batch dims -> must raise
    bv3 = rng.randn(2, 4, 5).astype(np.float32)
    with pytest.raises(MXNetError):
        export_model(out, {"b": mx.nd.array(bv3)},
                     in_shapes=[(3, 2, 4)],
                     onnx_file_path=str(tmp_path / "dot3.onnx"))

    # unknown rank (no in_shapes): conservative rejection
    with pytest.raises(MXNetError):
        export_model(out, {}, in_shapes=None,
                     onnx_file_path=str(tmp_path / "dotu.onnx"))
