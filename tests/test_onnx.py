"""ONNX export/import round-trips (reference: ``mx.contrib.onnx``).

The serializer is a self-contained protobuf wire-format implementation
(``mxnet_tpu/onnx/wire.py``); these tests check (a) the wire level
against an independent minimal TLV parser written here, (b) numeric
round-trips export -> import -> forward for LeNet and ResNet-50,
(c) interop with the real ``onnx`` package when it is installed.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.onnx import export_model, get_model_metadata, import_model
from mxnet_tpu.onnx import wire


# -- independent TLV walker (deliberately not reusing wire.py) ---------

def _walk(buf):
    fields = []
    pos = 0
    while pos < len(buf):
        key = 0
        shift = 0
        while True:
            b = buf[pos]
            pos += 1
            key |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        num, wt = key >> 3, key & 7
        if wt == 0:
            v = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                v |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
        elif wt == 2:
            ln = 0
            shift = 0
            while True:
                b = buf[pos]
                pos += 1
                ln |= (b & 0x7F) << shift
                if not b & 0x80:
                    break
                shift += 7
            v = buf[pos:pos + ln]
            assert len(v) == ln, "truncated field"
            pos += ln
        elif wt == 5:
            v = buf[pos:pos + 4]
            pos += 4
        elif wt == 1:
            v = buf[pos:pos + 8]
            pos += 8
        else:
            raise AssertionError("bad wire type %d" % wt)
        fields.append((num, wt, v))
    return fields


def _eval_sym(sym, arg_params, aux_params, **inputs):
    vals = dict(arg_params)
    vals.update(aux_params)
    vals.update({k: mx.nd.array(v) for k, v in inputs.items()})
    out = sym.eval(**vals)
    return (out[0] if isinstance(out, (list, tuple)) else out).asnumpy()


def _roundtrip_block(net, x, tmp_path, name):
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    want = net(mx.nd.array(x)).asnumpy()
    prefix = str(tmp_path / name)
    sym_file, params_file = net.export(prefix)
    onnx_file = str(tmp_path / (name + ".onnx"))
    export_model(sym_file, params_file, in_shapes=[x.shape],
                 in_types=[np.float32], onnx_file_path=onnx_file)

    # the file parses under an independent TLV walker and has a graph
    buf = open(onnx_file, "rb").read()
    top = dict((n, v) for n, wt, v in _walk(buf))
    assert 1 in top and 7 in top and 8 in top  # ir_version, graph, opset
    gfields = _walk(top[7])
    op_types = []
    for num, wt, v in gfields:
        if num == 1:  # NodeProto
            for n2, wt2, v2 in _walk(v):
                if n2 == 4:
                    op_types.append(v2.decode())
    assert op_types, "graph has no nodes"

    sym, arg_params, aux_params = import_model(onnx_file)
    got = _eval_sym(sym, arg_params, aux_params, data=x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    return onnx_file, op_types


def test_wire_tensor_attr_roundtrip():
    arr = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    name, back = wire.parse_tensor(wire.make_tensor("t", arr))
    assert name == "t"
    np.testing.assert_array_equal(back, arr)
    i64 = np.asarray([3, -1, 0], np.int64)
    _, back2 = wire.parse_tensor(wire.make_tensor("s", i64))
    np.testing.assert_array_equal(back2, i64)
    for val in (1.5, 7, "hello", [1, 2, 3], [1.0, 2.5], ["a", "b"]):
        k, v = wire.parse_attr(wire.make_attr("k", val))
        assert k == "k"
        if isinstance(val, list):
            assert list(v) == val
        else:
            assert v == val


def test_lenet_roundtrip(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(16, kernel_size=5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(32, activation="relu"),
            gluon.nn.Dense(10))
    x = np.random.RandomState(0).randn(2, 1, 28, 28).astype(np.float32)
    _file, op_types = _roundtrip_block(net, x, tmp_path, "lenet")
    assert "Conv" in op_types and "Gemm" in op_types \
        and "MaxPool" in op_types


def test_resnet50_roundtrip(tmp_path):
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    net = resnet50_v1()
    x = np.random.RandomState(0).randn(1, 3, 64, 64).astype(np.float32)
    _file, op_types = _roundtrip_block(net, x, tmp_path, "resnet50")
    assert "BatchNormalization" in op_types \
        and "GlobalAveragePool" in op_types and "Add" in op_types


def test_metadata(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(4))
    x = np.zeros((2, 8), np.float32)
    onnx_file, _ = _roundtrip_block(net, x, tmp_path, "mlp")
    meta = get_model_metadata(onnx_file)
    (in_name, in_shape), = meta["input_tensor_data"]
    assert in_name == "data" and tuple(in_shape) == (2, 8)
    assert len(meta["output_tensor_data"]) == 1


def test_import_rejects_garbage(tmp_path):
    p = tmp_path / "bad.onnx"
    p.write_bytes(b"\xff\xff\xff\xff")
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        import_model(str(p))


def test_onnx_package_interop(tmp_path):
    onnx = pytest.importorskip("onnx")
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(4, kernel_size=3, activation="relu"),
            gluon.nn.Flatten(), gluon.nn.Dense(10))
    x = np.zeros((1, 1, 8, 8), np.float32)
    onnx_file, _ = _roundtrip_block(net, x, tmp_path, "interop")
    model = onnx.load(onnx_file)
    onnx.checker.check_model(model)


def test_third_party_graph_idioms(tmp_path):
    """ISSUE 8 satellite: import_model must read the idioms third-party
    exporters emit that our own exporter never does -- Constant nodes
    as initializers, auto_pad=SAME_UPPER without kernel_shape, the
    opset default for count_include_pad, ReduceMean-as-global-pool,
    Reshape shape ATTRS, and initializers duplicated as graph inputs --
    and the result must load into SymbolBlock (the serving registry's
    ONNX path)."""
    from mxnet_tpu.gluon.block import SymbolBlock

    rng = np.random.RandomState(0)
    W = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.1
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32) * 0.1
    mean = rng.randn(4).astype(np.float32) * 0.1
    var = rng.rand(4).astype(np.float32) + 0.5
    Wfc = rng.randn(5, 4).astype(np.float32) * 0.1
    bfc = rng.randn(5).astype(np.float32) * 0.1

    nodes = [
        # no kernel_shape (weight dims rule), auto_pad instead of pads
        wire.make_node("Conv", ["data", "W"], ["c1"], "c1",
                       {"auto_pad": "SAME_UPPER"}),
        # spatial/training_mode attrs from older opsets are tolerated
        wire.make_node("BatchNormalization",
                       ["c1", "gamma", "beta", "mean", "var"],
                       ["bn1"], "bn1",
                       {"epsilon": 1e-5, "spatial": 1, "momentum": 0.9}),
        wire.make_node("Relu", ["bn1"], ["r1"], "r1"),
        wire.make_node("MaxPool", ["r1"], ["p1"], "p1",
                       {"kernel_shape": [2, 2], "strides": [2, 2]}),
        # torch spells global-average-pool as ReduceMean over [2, 3]
        wire.make_node("ReduceMean", ["p1"], ["gap"], "gap",
                       {"axes": [2, 3], "keepdims": 0}),
        # Constant node feeding Reshape (the dominant shape idiom)
        wire.make_node("Constant", [], ["shape_c"], "shape_c",
                       {"value": np.asarray([0, -1], np.int64)}),
        wire.make_node("Reshape", ["gap", "shape_c"], ["flat"], "flat"),
        wire.make_node("Gemm", ["flat", "Wfc", "bfc"], ["out"], "out",
                       {"alpha": 1.0, "beta": 1.0, "transB": 1}),
    ]
    weights = [("W", W), ("gamma", gamma), ("beta", beta),
               ("mean", mean), ("var", var), ("Wfc", Wfc), ("bfc", bfc)]
    inits = [wire.make_tensor(n, v) for n, v in weights]
    inputs = [wire.make_value_info("data", wire.DT_FLOAT, (1, 3, 8, 8))]
    # initializers ALSO listed as graph inputs (torch/tf idiom)
    inputs += [wire.make_value_info(n, wire.DT_FLOAT, v.shape)
               for n, v in weights]
    outputs = [wire.make_value_info("out", wire.DT_FLOAT, ())]
    model = wire.make_model(wire.make_graph(nodes, "tp", inputs,
                                            outputs, inits))
    path = str(tmp_path / "third_party.onnx")
    with open(path, "wb") as f:
        f.write(model)

    sym, arg_params, aux_params = import_model(path)
    assert set(aux_params) == {"mean", "var"}     # BN stats land as aux
    x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
    blk = SymbolBlock(sym, ["data"], {**arg_params, **aux_params})
    got = blk(mx.nd.array(x)).asnumpy()

    # independent numpy reference
    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    c = np.zeros((2, 4, 8, 8), np.float32)
    for n in range(2):
        for f in range(4):
            for i in range(8):
                for j in range(8):
                    c[n, f, i, j] = np.sum(xp[n, :, i:i + 3, j:j + 3]
                                           * W[f])
    bn = (gamma[None, :, None, None]
          * (c - mean[None, :, None, None])
          / np.sqrt(var[None, :, None, None] + 1e-5)
          + beta[None, :, None, None])
    r = np.maximum(bn, 0)
    p = r.reshape(2, 4, 4, 2, 4, 2).max(axis=(3, 5))
    want = p.mean(axis=(2, 3)) @ Wfc.T + bfc
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_third_party_attr_idioms(tmp_path):
    """Reshape-shape-as-attr (opset<5), multi-axis Unsqueeze, Squeeze,
    and the count_include_pad spec default (0 = exclude padding)."""
    rng = np.random.RandomState(0)
    x = rng.randn(1, 2, 4, 4).astype(np.float32)

    nodes = [
        # padded avg pool WITHOUT count_include_pad: the spec default
        # excludes the pad ring from the divisor
        wire.make_node("AveragePool", ["data"], ["ap"], "ap",
                       {"kernel_shape": [3, 3], "strides": [1, 1],
                        "pads": [1, 1, 1, 1]}),
        # legacy shape-as-attribute Reshape
        wire.make_node("Reshape", ["ap"], ["rs"], "rs",
                       {"shape": [1, 32]}),
        # multi-axis Unsqueeze via attr
        wire.make_node("Unsqueeze", ["rs"], ["un"], "un",
                       {"axes": [0, 3]}),
        wire.make_node("Squeeze", ["un"], ["out"], "out",
                       {"axes": [0, 3]}),
    ]
    inputs = [wire.make_value_info("data", wire.DT_FLOAT, (1, 2, 4, 4))]
    outputs = [wire.make_value_info("out", wire.DT_FLOAT, ())]
    model = wire.make_model(wire.make_graph(nodes, "attrs", inputs,
                                            outputs, []))
    path = str(tmp_path / "attr_idioms.onnx")
    with open(path, "wb") as f:
        f.write(model)
    sym, arg_params, aux_params = import_model(path)
    got = _eval_sym(sym, arg_params, aux_params, data=x)

    xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
    counts = np.pad(np.ones((1, 1, 4, 4), np.float32),
                    ((0, 0), (0, 0), (1, 1), (1, 1)))
    num = np.zeros((1, 2, 4, 4), np.float32)
    den = np.zeros((1, 1, 4, 4), np.float32)
    for i in range(4):
        for j in range(4):
            num[..., i, j] = xp[..., i:i + 3, j:j + 3].sum(axis=(-1, -2))
            den[..., i, j] = counts[..., i:i + 3, j:j + 3].sum(
                axis=(-1, -2))
    want = (num / den).reshape(1, 32)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_auto_pad_stride_rejected(tmp_path):
    """SAME_* with stride > 1 needs the input shape; reject loudly."""
    from mxnet_tpu.base import MXNetError
    W = np.zeros((2, 1, 3, 3), np.float32)
    nodes = [wire.make_node("Conv", ["data", "W"], ["c"], "c",
                            {"auto_pad": "SAME_UPPER",
                             "strides": [2, 2]})]
    inputs = [wire.make_value_info("data", wire.DT_FLOAT, (1, 1, 8, 8))]
    outputs = [wire.make_value_info("c", wire.DT_FLOAT, ())]
    model = wire.make_model(wire.make_graph(
        nodes, "g", inputs, outputs, [wire.make_tensor("W", W)]))
    path = str(tmp_path / "autopad.onnx")
    with open(path, "wb") as f:
        f.write(model)
    with pytest.raises(MXNetError):
        import_model(path)


def test_dot_export_rank_guard(tmp_path):
    """mx dot is tensordot(axes=1); ONNX MatMul diverges once the RHS
    has rank > 2, so such exports must be rejected, not silently wrong.
    Rank-2 dot exports fine and round-trips numerically."""
    from mxnet_tpu.base import MXNetError
    rng = np.random.RandomState(0)

    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    out = mx.sym.dot(a, b)

    # rank-2 x rank-2: representable; numeric round-trip
    av = rng.randn(3, 4).astype(np.float32)
    bv = rng.randn(4, 5).astype(np.float32)
    f = str(tmp_path / "dot2.onnx")
    export_model(out, {"b": mx.nd.array(bv)}, in_shapes=[av.shape],
                 onnx_file_path=f)
    isym, iargs, _iaux = import_model(f)
    feeds = {k: v for k, v in iargs.items()}
    feeds["a"] = mx.nd.array(av)
    got = isym.eval(**feeds)[0].asnumpy()
    np.testing.assert_allclose(got, av @ bv, rtol=1e-5, atol=1e-6)

    # rank-3 RHS: MatMul would broadcast batch dims -> must raise
    bv3 = rng.randn(2, 4, 5).astype(np.float32)
    with pytest.raises(MXNetError):
        export_model(out, {"b": mx.nd.array(bv3)},
                     in_shapes=[(3, 2, 4)],
                     onnx_file_path=str(tmp_path / "dot3.onnx"))

    # unknown rank (no in_shapes): conservative rejection
    with pytest.raises(MXNetError):
        export_model(out, {}, in_shapes=None,
                     onnx_file_path=str(tmp_path / "dotu.onnx"))
