"""mxnumerics (ISSUE 16): per-rule static fixtures for the five
precision rules, the compiled-HLO precision audit contract (handcrafted
HLO text -- XLA:CPU widens bf16 dots, so the half-accum counters need a
deterministic module), the numerics-baseline round trip, the SARIF
export, and the runtime non-finite sentinel: zero-touch when disarmed,
fused check + first-offender attribution when armed, chaos-NaN
detection through TrainStep and ContinuousTrainer, and scaler/sentinel
same-step agreement."""
import json
import os

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import amp, chaos, gluon, telemetry
from mxnet_tpu import analysis as an
from mxnet_tpu.analysis import numerics

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _rules_of(diags):
    return sorted({d.rule for d in diags})


def _lint(src):
    return an.lint_source(src, "probe.py")


@pytest.fixture(autouse=True)
def _numerics_state():
    """Snapshot/restore the sentinel flag and the /statusz counters."""
    prev_check = numerics._CHECK
    prev_state = dict(numerics._STATE)
    yield
    numerics._CHECK = prev_check
    numerics._STATE.clear()
    numerics._STATE.update(prev_state)


# ----------------------------------------------------------------------
# static rules: one positive and one negative fixture per rule
# ----------------------------------------------------------------------

def test_bf16_reduce_fires_and_fp32_accum_silent():
    bad = (
        "class M:\n"
        "    def hybrid_forward(self, F, x):\n"
        "        h = x.astype('bfloat16')\n"
        "        return h.sum(axis=-1)\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["bf16-sensitive-reduce"]
    assert "Did you mean" in diags[0].message
    good = (
        "class M:\n"
        "    def hybrid_forward(self, F, x):\n"
        "        h = x.astype('bfloat16')\n"
        "        a = h.astype('float32').sum(axis=-1)\n"
        "        b = F.sum(h, dtype='float32')\n"
        "        c = jnp.sum(h, preferred_element_type=jnp.float32)\n"
        "        return a, b, c\n"
    )
    assert _lint(good) == []


def test_bf16_reduce_fires_in_jitted_step_fn():
    bad = (
        "import jax\n"
        "def step_fn(params, x):\n"
        "    h = x.astype('bfloat16')\n"
        "    return h.mean()\n"
        "fn = jax.jit(step_fn, donate_argnums=(0,))\n"
    )
    assert "bf16-sensitive-reduce" in _rules_of(_lint(bad))
    # the same reduction in a plain eager helper is not gated
    eager = (
        "def helper(x):\n"
        "    h = x.astype('bfloat16')\n"
        "    return h.mean()\n"
    )
    assert _lint(eager) == []


def test_unscaled_half_loss_fires_and_amp_scaled_silent():
    bad = (
        "def train(net, loss_fn, x, y):\n"
        "    out = net(x).astype('float16')\n"
        "    loss = loss_fn(out, y).mean()\n"
        "    loss.backward()\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["unscaled-half-loss"]
    assert "amp.scale_loss" in diags[0].message
    good = (
        "def train(net, loss_fn, trainer, x, y):\n"
        "    out = net(x).astype('float16')\n"
        "    loss = loss_fn(out, y).mean()\n"
        "    with amp.scale_loss(loss, trainer) as scaled:\n"
        "        scaled.backward()\n"
    )
    assert _lint(good) == []
    # fp32 loss never fires
    fp32 = (
        "def train(net, loss_fn, x, y):\n"
        "    loss = loss_fn(net(x), y).mean()\n"
        "    loss.backward()\n"
    )
    assert _lint(fp32) == []


def test_half_optimizer_state_fires_and_fp32_silent():
    bad = (
        "def create_state(self, index, weight):\n"
        "    return zeros(weight.shape, dtype='float16')\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["half-optimizer-state"]
    assert "float32" in diags[0].message
    # state-named assignment outside a create_state fn also fires
    named = (
        "def setup(self, shape):\n"
        "    self.running_mean = zeros(shape, dtype='bfloat16')\n"
    )
    assert _rules_of(_lint(named)) == ["half-optimizer-state"]
    good = (
        "def create_state(self, index, weight):\n"
        "    return zeros(weight.shape, dtype='float32')\n"
        "def activations(shape):\n"
        "    return zeros(shape, dtype='bfloat16')\n"  # not state
    )
    assert _lint(good) == []


def test_implicit_downcast_tiny_const_and_narrowing_cast():
    bad = (
        "class M:\n"
        "    def hybrid_forward(self, F, x):\n"
        "        h = x.astype('bfloat16')\n"
        "        y = h + 1e-6\n"
        "        acc = h.astype('float32')\n"
        "        out = acc.astype('bfloat16')\n"
        "        return y, out\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["implicit-downcast"]
    assert len(diags) == 2
    msgs = "\n".join(d.message for d in diags)
    assert "weak-typed" in msgs          # form (a): absorbed constant
    assert "narrows" in msgs             # form (b): fp32 -> half cast
    good = (
        "class M:\n"
        "    def hybrid_forward(self, F, x):\n"
        "        h = x.astype('bfloat16')\n"
        "        y = h + 0.5\n"                       # representable
        "        z = h.astype('float32') + 1e-6\n"    # upcast first
        "        return y, z\n"
    )
    assert _lint(good) == []


def test_nonfinite_guard_fires_and_eps_guard_silent():
    bad = (
        "import jax\n"
        "def step_fn(params, x):\n"
        "    return jnp.log(x)\n"
        "fn = jax.jit(step_fn, donate_argnums=(0,))\n"
    )
    diags = _lint(bad)
    assert _rules_of(diags) == ["nonfinite-guard-missing"]
    assert "log" in diags[0].message
    good = (
        "import jax\n"
        "def step_fn(params, x, var, eps):\n"
        "    a = jnp.log(x + eps)\n"
        "    b = jnp.log(jnp.maximum(x, 1e-6))\n"
        "    c = jnp.rsqrt(var + 1e-5)\n"
        "    return a, b, c\n"
        "fn = jax.jit(step_fn, donate_argnums=(0,))\n"
    )
    assert _lint(good) == []


def test_numerics_rule_suppression_directive():
    src = (
        "import jax\n"
        "def step_fn(params, x):\n"
        "    return jnp.log(x)  # mxlint: disable=nonfinite-guard-missing\n"
        "fn = jax.jit(step_fn, donate_argnums=(0,))\n"
    )
    assert _lint(src) == []


def test_numerics_rules_registered_and_fixed_tree_clean():
    for rid in ("bf16-sensitive-reduce", "unscaled-half-loss",
                "half-optimizer-state", "implicit-downcast",
                "nonfinite-guard-missing", "numerics-drift"):
        assert rid in an.RULES, rid
    # the armed-rules acceptance: the nn/kernel code the BN-stats fix
    # brought into shape lints clean WITHOUT suppressions (full --self
    # runs in CI)
    diags = an.lint_paths([
        os.path.join(REPO, "mxnet_tpu", "ops", "nn.py"),
        os.path.join(REPO, "mxnet_tpu", "kernels", "fused_bn_relu.py"),
        os.path.join(REPO, "mxnet_tpu", "gluon", "model_zoo"),
    ])
    assert [d.format() for d in diags] == []


# ----------------------------------------------------------------------
# compiled audit: counters on a handcrafted module (deterministic --
# XLA:CPU widens bf16 dots, so real lowerings can't pin half-accum)
# ----------------------------------------------------------------------

_TOY_HLO = """HloModule toy

%add.1 (a: bf16[], b: bf16[]) -> bf16[] {
  %a = bf16[] parameter(0)
  %b = bf16[] parameter(1)
  ROOT %s = bf16[] add(bf16[] %a, bf16[] %b)
}

ENTRY %main.1 (p0: bf16[64,64], p1: bf16[64,64]) -> bf16[64] {
  %p0 = bf16[64,64]{1,0} parameter(0)
  %p1 = bf16[64,64]{1,0} parameter(1)
  %dot.1 = bf16[64,64]{1,0} dot(bf16[64,64]{1,0} %p0, bf16[64,64]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/dot_general"}
  %zero = bf16[] constant(0)
  %red.1 = bf16[64]{0} reduce(bf16[64,64]{1,0} %dot.1, bf16[] %zero), dimensions={0}, to_apply=%add.1, metadata={op_name="jit(f)/reduce_sum"}
  %cv.1 = f32[64]{0} convert(bf16[64]{0} %red.1), metadata={op_name="jit(f)/convert"}
  ROOT %cv.2 = bf16[64]{0} convert(f32[64]{0} %cv.1)
}
"""


def test_audit_hlo_numerics_counters_direct():
    c = numerics.audit_hlo_numerics(_TOY_HLO)
    # the bf16-accumulated dot: operand AND output dtype are half
    assert c["half_dot_bytes"] == c["mxu_bytes"] > 0
    assert c["half_dots"] == {"jit(f)/dot_general": c["half_dot_bytes"]}
    # the all-bf16 reduction, with op_name provenance
    assert c["half_reduce_bytes"] == c["reduce_bytes"] > 0
    assert list(c["half_reduces"]) == ["jit(f)/reduce_sum"]
    # convert traffic books per scope
    assert c["convert_bytes"] > 0
    assert "jit(f)/convert" in c["convert_ops"]
    m = numerics._metrics_of(c)
    assert m["half_accum_dot_share"] == 1.0
    assert m["half_reduce_share"] == 1.0
    kinds = [a["kind"] for a in numerics._advisories_for(
        "toy", m, c, numerics.THRESHOLDS)]
    assert set(kinds) == {"half-accum-dot", "half-reduce"}
    # the widened twin (fp32 accumulator) books NO half-dot bytes
    wide = _TOY_HLO.replace("%dot.1 = bf16[64,64]{1,0}",
                            "%dot.1 = f32[64,64]{1,0}")
    cw = numerics.audit_hlo_numerics(wide)
    assert cw["half_dot_bytes"] == 0
    assert cw["mxu_bytes"] > 0


def test_audit_pred_reduce_is_not_a_half_reduce():
    # any/all folds (the sentinel's own isfinite reduction) are
    # pred-typed: no accumulation precision to lose
    text = (
        "HloModule sentinel\n\n"
        "ENTRY %main.1 (p0: pred[4096]) -> pred[] {\n"
        "  %p0 = pred[4096]{0} parameter(0)\n"
        "  %t = pred[] constant(true)\n"
        "  ROOT %r = pred[] reduce(pred[4096]{0} %p0, pred[] %t), "
        "dimensions={0}, to_apply=%and.1\n"
        "}\n"
    )
    c = numerics.audit_hlo_numerics(text)
    assert c["reduce_bytes"] > 0
    assert c["half_reduce_bytes"] == 0


def _register_toy(label, fn, *args):
    import jax
    from mxnet_tpu.profiling import store
    jfn = jax.jit(fn)
    jfn(*args)
    store.register((label,), label, jfn, args)
    return jfn


def test_numerics_audit_registry_walk_and_convert_storm():
    from mxnet_tpu import profiling
    profiling.reset()
    # XLA:CPU widens the bf16 matmul through converts: on this backend
    # the toy audits as a convert-storm (>= 15% of bytes)
    _register_toy("toy:bf16mm",
                  lambda a, b: (a @ b).sum(axis=0),
                  jnp.ones((64, 64), jnp.bfloat16),
                  jnp.ones((64, 64), jnp.bfloat16))
    audit = numerics.numerics_audit()
    assert audit["schema"] == numerics.AUDIT_SCHEMA
    assert audit["thresholds"]["convert_share"] == 0.15
    ex = audit["executables"]["toy:bf16mm"]
    for key in ("convert_share", "half_accum_dot_share",
                "half_reduce_share", "bytes_total"):
        assert key in ex["metrics"]
    kinds = {a["kind"] for a in ex["advisories"]}
    assert "convert-storm" in kinds
    # ranked advisories carry the executable label
    assert any(a["executable"] == "toy:bf16mm"
               and a["kind"] == "convert-storm"
               for a in audit["advisories"])
    profiling.reset()


# ----------------------------------------------------------------------
# baseline round trip: bless -> self-diff zero -> seeded regression
# ----------------------------------------------------------------------

def test_numerics_baseline_round_trip(tmp_path):
    from mxnet_tpu import profiling
    profiling.reset()
    _register_toy("toy:numrt",
                  lambda a, b: (a @ b).sum(axis=0),
                  jnp.ones((64, 64), jnp.bfloat16),
                  jnp.ones((64, 64), jnp.bfloat16))
    base_path = str(tmp_path / "numerics_baseline.json")
    base = numerics.save_audit(base_path)
    assert numerics.load_audit(base_path)["schema"] == \
        numerics.AUDIT_SCHEMA

    # self-diff: zero drift, CLI exit 0
    assert numerics.diff_audit(base, base) == []
    assert an.main(["--numerics-diff", base_path, base_path]) == 0

    # seeded regression: grown share + unblessed advisory kind
    cur = json.loads(json.dumps(base))
    row = cur["executables"]["toy:numrt"]
    row["metrics"]["convert_share"] = \
        base["executables"]["toy:numrt"]["metrics"]["convert_share"] \
        + 0.1
    row["advisories"].append({"kind": "half-accum-dot", "share": 0.5,
                              "op_names": [], "message": "seeded"})
    cur_path = str(tmp_path / "current.json")
    with open(cur_path, "w") as f:
        json.dump(cur, f)
    diags = numerics.diff_audit(base, numerics.load_audit(cur_path))
    assert _rules_of(diags) == ["numerics-drift"]
    msgs = "\n".join(d.message for d in diags)
    assert "convert_share grew" in msgs
    assert "half-accum-dot" in msgs
    assert an.main(["--numerics-diff", base_path, cur_path]) == 1

    # improvements pass silently
    better = json.loads(json.dumps(base))
    better["executables"]["toy:numrt"]["metrics"]["convert_share"] = 0.0
    better["executables"]["toy:numrt"]["advisories"] = []
    assert numerics.diff_audit(base, better) == []
    profiling.reset()


def test_numerics_audit_schema_reject(tmp_path):
    p = tmp_path / "bogus.json"
    p.write_text(json.dumps({"schema": "nope", "executables": {}}))
    with pytest.raises(ValueError, match="mxnumerics.audit.v1"):
        numerics.load_audit(str(p))
    assert an.main(["--numerics-diff", str(p), str(p)]) == 2


def test_numerics_diff_tolerance_env(monkeypatch):
    base = {"executables": {"e": {"metrics": {"convert_share": 0.0},
                                  "advisories": []}}}
    cur = {"executables": {"e": {"metrics": {"convert_share": 0.3},
                                 "advisories": []}}}
    assert numerics.diff_audit(base, cur, tol=0.5) == []
    assert len(numerics.diff_audit(base, cur, tol=0.02)) == 1
    monkeypatch.setenv("MXNET_TPU_NUMERICS_AUDIT_TOL", "0.5")
    assert numerics.diff_audit(base, cur) == []


def test_committed_numerics_baseline_is_loadable():
    base = numerics.load_audit(
        os.path.join(REPO, "ci", "numerics_baseline.json"))
    labels = set(base["executables"])
    assert "train_step:NumLeNet" in labels
    assert "train_step:ResNetV1" in labels


# ----------------------------------------------------------------------
# SARIF export (ISSUE 16 satellite)
# ----------------------------------------------------------------------

def test_sarif_round_trip(tmp_path):
    diags = _lint("import jax\n"
                  "def step_fn(params, x):\n"
                  "    h = x.astype('bfloat16')\n"
                  "    return jnp.log(h.sum())\n"
                  "fn = jax.jit(step_fn, donate_argnums=(0,))\n")
    assert len(diags) >= 2            # bf16 reduce + unguarded log
    log = an.to_sarif(diags)
    assert log["version"] == "2.1.0"
    assert log["$schema"].endswith("sarif-schema-2.1.0.json")
    run = log["runs"][0]
    assert run["tool"]["driver"]["name"] == "mxlint"
    results = run["results"]
    assert {r["ruleId"] for r in results} == set(_rules_of(diags))
    for r in results:
        assert r["level"] in ("error", "warning")
        assert r["message"]["text"]
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "probe.py"
        assert isinstance(loc["region"]["startLine"], int)
    # rule metadata covers every ruleId present
    rule_ids = {m["id"] for m in run["tool"]["driver"]["rules"]}
    assert rule_ids == {r["ruleId"] for r in results}
    # write/read round trip
    out = str(tmp_path / "findings.sarif")
    assert an.write_sarif(out, diags) == log
    with open(out) as f:
        assert json.load(f) == log


def test_cli_sarif_export_and_exit_contract(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n"
                   "def step_fn(params, x):\n"
                   "    return jnp.log(x)\n"
                   "fn = jax.jit(step_fn, donate_argnums=(0,))\n")
    out = tmp_path / "out.sarif"
    # exit code is still the lint verdict; the SARIF file is a side
    # artifact
    assert an.main([str(bad), "--sarif", str(out), "--json"]) == 1
    with open(out) as f:
        log = json.load(f)
    assert [r["ruleId"] for r in log["runs"][0]["results"]] == \
        ["nonfinite-guard-missing"]
    clean = tmp_path / "clean.py"
    clean.write_text("def f(x):\n    return x\n")
    out2 = tmp_path / "clean.sarif"
    assert an.main([str(clean), "--sarif", str(out2), "--json"]) == 0
    with open(out2) as f:
        assert json.load(f)["runs"][0]["results"] == []


# ----------------------------------------------------------------------
# runtime sentinel: primitives
# ----------------------------------------------------------------------

def test_finite_tree_and_finite_all():
    clean = [jnp.ones((4, 4), jnp.float32),
             jnp.ones((8,), jnp.bfloat16),
             jnp.arange(3)]                      # int leaf: skipped
    assert bool(numerics.finite_tree(clean))
    assert bool(numerics.finite_all(clean))
    assert bool(numerics.finite_tree([]))
    dirty = clean + [jnp.array([1.0, np.nan], jnp.float32)]
    assert not bool(numerics.finite_tree(dirty))
    assert not bool(numerics.finite_all(dirty))
    # NDArray wrappers unwrap
    assert not bool(numerics.finite_all(
        [mx.nd.array(np.array([np.inf], np.float32))]))


def test_attribute_nonfinite_reports_nan_before_inf():
    named = [("a", jnp.ones((2,))),
             ("b", jnp.array([1.0, np.inf], jnp.float32)),
             ("c", jnp.array([np.nan], jnp.float32))]
    assert numerics.attribute_nonfinite(named) == ("c", "nan")
    assert numerics.attribute_nonfinite(named[:2]) == ("b", "inf")
    assert numerics.attribute_nonfinite([("a", jnp.ones((2,)))]) is None
    # int arrays are skipped even when huge
    assert numerics.attribute_nonfinite(
        [("i", jnp.array([2 ** 31 - 1]))]) is None


def test_sentinel_disarmed_is_zero_touch():
    class Boom:
        def __iter__(self):
            raise AssertionError("disarmed sentinel touched its input")

    numerics._set_check(False)
    assert numerics.finite_sentinel(Boom()) is True


def test_finite_sentinel_raises_with_attribution_and_status_row():
    numerics._set_check(True)
    checks0 = numerics._STATE["checks"]
    assert numerics.finite_sentinel([("w", jnp.ones((4,)))], step=7) \
        is True
    assert numerics._STATE["checks"] == checks0 + 1
    with pytest.raises(numerics.NonFiniteError) as ei:
        numerics.finite_sentinel(
            [("w", jnp.ones((4,))),
             ("g", jnp.array([np.nan, 1.0], jnp.float32))], step=9)
    e = ei.value
    assert (e.param, e.step, e.kind) == ("g", 9, "nan")
    assert "pre-step values" in str(e)
    row = numerics.status_row()
    assert row["armed"] is True
    assert row["checks"] == checks0 + 2
    assert row["last"] == {"param": "g", "step": 9, "kind": "nan"}


def test_poison_nd_preserves_wrapper_and_skips_ints():
    x = mx.nd.ones((2, 3))
    p = numerics.poison_nd(x)
    assert isinstance(p, type(x))
    flat = p.asnumpy().ravel()
    assert np.isnan(flat[0]) and np.isfinite(flat[1:]).all()
    ix = jnp.arange(4)
    assert numerics.poison_nd(ix) is ix


def test_numerics_telemetry_instruments_catalogued():
    from mxnet_tpu.telemetry import hooks
    rows = {i.name: i for i in hooks.INSTRUMENTS}
    assert rows["numerics.checks"].kind == "counter"
    assert rows["numerics.check_time"].kind == "timer"
    assert rows["numerics.nonfinite_steps"].kind == "counter"
    assert rows["numerics.nonfinite"].kind == "event"


def test_statusz_carries_numerics_row():
    from mxnet_tpu.obs import status
    row = status.statusz()["numerics"]
    assert set(row) == {"armed", "checks", "nonfinite", "last"}
    assert row["armed"] == numerics.check_enabled()


def test_runtime_features_numerics_row(monkeypatch):
    from mxnet_tpu import runtime
    monkeypatch.setenv("MXNET_TPU_NUMERICS_CHECK", "1")
    assert runtime.Features().is_enabled("NUMERICS")
    monkeypatch.delenv("MXNET_TPU_NUMERICS_CHECK")
    assert not runtime.Features().is_enabled("NUMERICS")


def test_numerics_env_vars_registered():
    from mxnet_tpu import env
    desc = env.describe()
    assert "MXNET_TPU_NUMERICS_CHECK" in desc
    assert "MXNET_TPU_NUMERICS_AUDIT_TOL" in desc
    _val, default, _doc = desc["MXNET_TPU_NUMERICS_AUDIT_TOL"]
    assert default == 0.02


# ----------------------------------------------------------------------
# chaos-NaN detection through the training surfaces
# ----------------------------------------------------------------------

@pytest.fixture
def _clean_chaos():
    chaos.reset()
    yield
    chaos.disarm()
    chaos.reset()


def _mlp(seed=0):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    return net


def test_trainstep_chaos_nan_attribution_and_weight_restore(_clean_chaos):
    from mxnet_tpu.parallel import TrainStep
    net = _mlp(seed=11)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer)
    rng = np.random.RandomState(3)
    x = mx.nd.array(rng.randn(8, 8).astype(np.float32))
    y = mx.nd.array(rng.randn(8, 4).astype(np.float32))
    numerics._set_check(True)
    pnames = set(net.collect_params())
    with chaos.scenario(seed=0):
        chaos.on("numerics.nonfinite", numerics.poison_action, nth=2)
        step(x, y)                               # step 1: clean
        before = {p.name: p.data().asnumpy().copy()
                  for p in net.collect_params().values()}
        with pytest.raises(numerics.NonFiniteError) as ei:
            step(x, y)                           # step 2: poisoned
    e = ei.value
    assert e.kind == "nan"
    assert e.step == 2
    assert e.param in pnames | {"loss"}
    # the branchless overflow-skip kept the pre-step weights
    for p in net.collect_params().values():
        np.testing.assert_array_equal(before[p.name],
                                      p.data().asnumpy())
    row = numerics.status_row()
    assert row["nonfinite"] >= 1
    assert row["last"]["kind"] == "nan"


def test_trainstep_sentinel_and_scaler_agree_same_step(_clean_chaos):
    """The fp16 LossScaler and the sentinel see the SAME fused finite
    bit: one poisoned step halves the scale, skips the update, AND
    raises the typed attribution error."""
    from mxnet_tpu.parallel import TrainStep
    net = _mlp(seed=13)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    amp.init_trainer(trainer, amp.LossScaler(init_scale=8.0,
                                             scale_window=10 ** 9))
    step = TrainStep(net, gluon.loss.L2Loss(), trainer)
    rng = np.random.RandomState(5)
    x = mx.nd.array(rng.randn(8, 8).astype(np.float32))
    y = mx.nd.array(rng.randn(8, 4).astype(np.float32))
    numerics._set_check(True)
    net(x)                            # materialize deferred params
    before = {p.name: p.data().asnumpy().copy()
              for p in net.collect_params().values()}
    with chaos.scenario(seed=0):
        chaos.on("numerics.nonfinite", numerics.poison_action, nth=1)
        with pytest.raises(numerics.NonFiniteError) as ei:
            step(x, y)
    assert ei.value.step == 1
    assert trainer._amp_loss_scaler.loss_scale == 4.0   # halved
    for p in net.collect_params().values():
        np.testing.assert_array_equal(before[p.name],
                                      p.data().asnumpy())


def test_continuous_trainer_sentinel_catches_chaos_nan(
        tmp_path, _clean_chaos):
    from mxnet_tpu.chaos import scenarios
    from mxnet_tpu.serving.loop import ContinuousTrainer
    net, trainer, loss_fn, (x, y) = scenarios.train_fixtures(seed=0)
    ct = ContinuousTrainer(net, trainer, loss_fn,
                           lambda step: (x, y),
                           str(tmp_path / "ck"), publish_every=5)
    numerics._set_check(True)
    with chaos.scenario(seed=0):
        chaos.on("numerics.nonfinite", numerics.poison_action, nth=2)
        assert ct.run_steps(1) is not None       # step 1: clean
        with pytest.raises(numerics.NonFiniteError) as ei:
            ct.run_steps(1)                      # step 2: poisoned
    e = ei.value
    assert e.kind == "nan"
    assert e.step == 2
    assert e.param in {p.name for p in trainer._params}


def test_trainstep_disarmed_sentinel_trains_through_chaos(_clean_chaos):
    """Disarmed (the default), the sentinel costs one flag check and a
    poisoned step trains through silently (the where-select still skips
    it) -- detection is strictly opt-in."""
    from mxnet_tpu.parallel import TrainStep
    net = _mlp(seed=17)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), trainer)
    rng = np.random.RandomState(7)
    x = mx.nd.array(rng.randn(8, 8).astype(np.float32))
    y = mx.nd.array(rng.randn(8, 4).astype(np.float32))
    numerics._set_check(False)
    nonfinite0 = numerics._STATE["nonfinite"]
    with chaos.scenario(seed=0):
        chaos.on("numerics.nonfinite", numerics.poison_action, nth=1)
        step(x, y)                               # poisoned, no raise
        step(x, y)
    assert numerics._STATE["nonfinite"] == nonfinite0
    for p in net.collect_params().values():
        assert np.isfinite(p.data().asnumpy()).all()


# ----------------------------------------------------------------------
# BatchNorm bf16 running stats accumulate in fp32 (ISSUE 16 satellite)
# ----------------------------------------------------------------------

def test_batch_norm_bf16_stats_blend_in_fp32():
    from mxnet_tpu.ops import nn as ops_nn
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(4, 3, 5, 5).astype(np.float32)) * 100.0
    gamma = jnp.ones((3,), jnp.float32)
    beta = jnp.zeros((3,), jnp.float32)
    mm = jnp.asarray(rng.randn(3).astype(np.float32)).astype(jnp.bfloat16)
    mv = jnp.abs(jnp.asarray(rng.randn(3).astype(np.float32))) \
        .astype(jnp.bfloat16) + 1.0
    out, new_mean, new_var = ops_nn._batch_norm.fcompute(
        x, gamma, beta, mm, mv, momentum=0.9, fix_gamma=False,
        training=True)
    # aux dtype preserved
    assert new_mean.dtype == jnp.bfloat16
    assert new_var.dtype == jnp.bfloat16
    # the EMA equals the fp32 blend rounded ONCE to bf16 (same shifted
    # one-pass moments, recomputed here in fp32)
    c = np.asarray(mm, np.float32).reshape(1, 3, 1, 1)
    yv = np.asarray(x, np.float32) - c
    mean_y = yv.mean(axis=(0, 2, 3))
    m2 = (yv * yv).mean(axis=(0, 2, 3))
    mean = mean_y + c.reshape(3)
    var = np.maximum(m2 - mean_y * mean_y, 0.0)
    ref_mean = (0.9 * np.asarray(mm, np.float32) + 0.1 * mean) \
        .astype(jnp.bfloat16.dtype)
    ref_var = (0.9 * np.asarray(mv, np.float32) + 0.1 * var) \
        .astype(jnp.bfloat16.dtype)
    np.testing.assert_allclose(
        np.asarray(new_mean, np.float32),
        ref_mean.astype(np.float32), rtol=2 ** -7)
    np.testing.assert_allclose(
        np.asarray(new_var, np.float32),
        ref_var.astype(np.float32), rtol=2 ** -7)


def test_batch_norm_bf16_eval_adds_eps_in_fp32():
    """In bf16, var + 1e-5 == var exactly; the eval path must upcast
    BEFORE the eps add.  With var == 1.0 the difference is visible at
    fp32 output precision on large activations."""
    from mxnet_tpu.ops import nn as ops_nn
    eps = 1e-5
    x = jnp.full((2, 1, 8, 8), 1000.0, jnp.float32)
    one = jnp.ones((1,), jnp.float32)
    zero = jnp.zeros((1,), jnp.float32)
    out, _m, _v = ops_nn._batch_norm.fcompute(
        x, one, zero, zero.astype(jnp.bfloat16),
        one.astype(jnp.bfloat16), eps=eps, momentum=0.9,
        fix_gamma=False, training=False)
    ref = 1000.0 / np.sqrt(np.float32(1.0) + np.float32(eps))
    wrong = 1000.0                     # eps absorbed: 1/sqrt(1.0)
    got = float(np.asarray(out).ravel()[0])
    assert abs(got - ref) < 1e-3
    assert abs(got - wrong) > 1e-3


def test_fused_bn_relu_bf16_stats_blend_in_fp32():
    from mxnet_tpu.kernels import fused_bn_relu as k
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(4, 6, 3).astype(np.float32))
    gamma = jnp.ones((3,), jnp.float32)
    beta = jnp.zeros((3,), jnp.float32)
    mm = jnp.zeros((3,), jnp.bfloat16)
    mv = jnp.ones((3,), jnp.bfloat16)
    out, new_mean, new_var = k.fused_bn_relu(
        x, gamma, beta, mm, mv, training=True, momentum=0.9,
        fix_gamma=False, axis=2)
    assert new_mean.dtype == jnp.bfloat16
    assert new_var.dtype == jnp.bfloat16
    batch_mean = np.asarray(x, np.float32).mean(axis=(0, 1))
    ref = (0.1 * batch_mean).astype(jnp.bfloat16.dtype)
    np.testing.assert_allclose(np.asarray(new_mean, np.float32),
                               ref.astype(np.float32), rtol=2 ** -7,
                               atol=2 ** -10)
    assert bool((np.asarray(out) >= 0).all())    # relu applied
