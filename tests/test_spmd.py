"""One-program SPMD building blocks (ISSUE 9), single-process half.

The 2-/4-process gloo contracts live in tests/test_distributed.py;
these tests pin the primitives they compose: the global mesh, the
multi-host-safe placement/staging helpers, the bucketed host
collectives (one flattened RPC per call site instead of one per
tensor), and the kvstore/Trainer veneer plumbing.
"""
import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import distributed as dist
from mxnet_tpu import gluon, telemetry
from mxnet_tpu.parallel import (TrainStep, global_mesh, make_mesh,
                                put_replicated, shard_batch,
                                stage_process_local)


# ----------------------------------------------------------------------
# global mesh + placement/staging helpers
# ----------------------------------------------------------------------

def test_global_mesh_default_and_2d():
    mesh = global_mesh()
    assert mesh.shape["dp"] == len(jax.devices())
    assert global_mesh() is mesh              # cached per (axes, world)
    mesh2 = global_mesh({"dp": -1, "tp": 2})
    assert mesh2.shape["tp"] == 2
    assert mesh2.shape["dp"] * 2 == len(jax.devices())
    with pytest.raises(mx.base.MXNetError):
        global_mesh({"tp": 2})                # dp axis is mandatory


def test_put_replicated_single_process_is_device_put():
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    sh = NamedSharding(mesh, P())
    out = put_replicated(np.arange(6, dtype=np.float32), sh)
    assert out.sharding.is_equivalent_to(sh, out.ndim)
    np.testing.assert_array_equal(np.asarray(out), np.arange(6))


def test_stage_process_local_noop_when_equivalent():
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    sh = NamedSharding(mesh, P("dp"))
    staged = stage_process_local(np.arange(8, dtype=np.float32), sh)
    assert staged.sharding.is_equivalent_to(sh, staged.ndim)
    assert stage_process_local(staged, sh) is staged


def test_shard_batch_accepts_host_numpy():
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    out = shard_batch(np.ones((8, 3), np.float32), mesh)
    assert out._data.sharding.is_equivalent_to(
        NamedSharding(mesh, P("dp", None)), 2)


def test_train_step_host_batches_guard_clean():
    """Host numpy batches land through the EXPLICIT staging primitives:
    the steady-state step loop stays clean under
    transfer_guard('disallow') -- the contract the multi-host feed
    depends on (docs/distributed.md)."""
    from mxnet_tpu.analysis import sharding as shard_mod
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    net = gluon.nn.Dense(3)
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=mesh)
    x = np.random.rand(8, 5).astype(np.float32)
    y = np.random.rand(8, 3).astype(np.float32)
    step(x, y)                                # compile outside the guard
    with shard_mod.transfer_guard("disallow"):
        loss = step(x, y)
        loss._data.block_until_ready()
    assert np.isfinite(float(np.asarray(loss._data)))


# ----------------------------------------------------------------------
# bucketed host collectives
# ----------------------------------------------------------------------

def test_bucketed_world1_passthrough():
    arrs = [np.arange(4, dtype=np.float32),
            np.ones((2, 2), np.int32)]
    out = dist.host_allreduce_bucketed(arrs)
    for a, b in zip(arrs, out):
        np.testing.assert_array_equal(np.asarray(b), a)
    out = dist.host_broadcast_bucketed(arrs)
    for a, b in zip(arrs, out):
        np.testing.assert_array_equal(np.asarray(b), a)


def test_bucketed_one_collective_per_dtype_group(monkeypatch):
    """3 fp32 + 2 int32 tensors coalesce into exactly TWO flattened
    collectives (one per dtype), results split back by shape."""
    calls = []

    def fake_allreduce(buf, average=False, timeout_ms=0, _ntensors=1):
        calls.append((buf.dtype, buf.size, _ntensors))
        return buf * 2

    monkeypatch.setattr(dist, "world", lambda: (2, 0))
    monkeypatch.setattr(dist, "host_allreduce", fake_allreduce)
    arrs = [np.arange(4, dtype=np.float32),
            np.ones((2, 3), np.float32),
            np.full(5, 7.0, np.float32),
            np.arange(6, dtype=np.int32).reshape(2, 3),
            np.ones(2, np.int32)]
    out = dist.host_allreduce_bucketed(arrs)
    assert len(calls) == 2
    assert {c[0].name for c in calls} == {"float32", "int32"}
    assert {(c[1], c[2]) for c in calls} == {(15, 3), (8, 2)}
    for a, b in zip(arrs, out):
        assert np.asarray(b).shape == a.shape
        np.testing.assert_array_equal(np.asarray(b), a * 2)


def test_bucketed_broadcast_places_back_on_sharding(monkeypatch):
    """Results land back on each input's own sharding (mesh-replicated
    params keep their layout through the init-time sync)."""
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    sh = NamedSharding(mesh, P())
    dev = jax.device_put(np.arange(3, dtype=np.float32), sh)
    monkeypatch.setattr(dist, "world", lambda: (2, 0))
    monkeypatch.setattr(
        dist, "host_broadcast",
        lambda buf, root=0, timeout_ms=0, _ntensors=1: buf)
    out = dist.host_broadcast_bucketed([dev])[0]
    assert out.sharding.is_equivalent_to(sh, out.ndim)


def test_dist_collective_telemetry(monkeypatch):
    """The real collective sites feed dist.* counters: collectives vs
    tensors_coalesced is the call-count-drop proof."""
    monkeypatch.setattr(dist, "world", lambda: (2, 0))
    # short-circuit at the pod branch boundary: count telemetry only
    monkeypatch.setattr(jax, "process_count", lambda: 1)
    monkeypatch.setattr(dist, "_warn_kv_fallback", lambda: None)
    monkeypatch.setattr(dist, "_client", lambda: None)

    was = telemetry.enabled()
    telemetry.enable()
    telemetry.reset("dist.")
    try:
        calls = []
        monkeypatch.setattr(
            dist, "host_allreduce",
            lambda buf, average=False, timeout_ms=0, _ntensors=1:
            (dist._telemetry_collective("allreduce", buf.nbytes,
                                        _ntensors), buf)[1])
        arrs = [np.ones(3, np.float32), np.ones(4, np.float32),
                np.ones(5, np.float32)]
        dist.host_allreduce_bucketed(arrs)
        assert telemetry.counter("dist.collectives").value == 1
        assert telemetry.counter("dist.tensors_coalesced").value == 3
        assert telemetry.counter("dist.bytes").value == 12 * 4
    finally:
        if not was:
            telemetry.disable()


# ----------------------------------------------------------------------
# kvstore / Trainer veneer
# ----------------------------------------------------------------------

def test_kvstore_pushpull_bucket_values_and_telemetry():
    kv = mx.kv.create("dist_sync")           # world == 1 in-suite
    kv.init("a", mx.nd.zeros((3,)))
    kv.init("b", mx.nd.zeros((2, 2)))
    va = mx.nd.ones((3,)) * 2
    vb = mx.nd.ones((2, 2)) * 5
    oa, ob = mx.nd.zeros((3,)), mx.nd.zeros((2, 2))
    was = telemetry.enabled()
    telemetry.enable()
    telemetry.reset("kvstore.")
    try:
        kv.pushpull_bucket(["a", "b"], [va, vb], [oa, ob])
        # ONE pushpull for the whole bucket (the kv.bytes call-count
        # drop), bytes covering both tensors
        assert telemetry.counter("kvstore.pushpull").value == 1
        assert telemetry.counter("kvstore.bytes").value == (3 + 4) * 4
    finally:
        if not was:
            telemetry.disable()
    np.testing.assert_allclose(oa.asnumpy(), np.full(3, 2.0))
    np.testing.assert_allclose(ob.asnumpy(), np.full((2, 2), 5.0))


def test_kvstore_pushpull_bucket_updater_fallback():
    kv = mx.kv.create("dist_sync")
    kv.init("w", mx.nd.zeros((4,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0))
    g = mx.nd.ones((4,))
    out = mx.nd.zeros((4,))
    kv.pushpull_bucket(["w"], [g], [out])
    np.testing.assert_allclose(out.asnumpy(), np.full(4, -1.0))


def test_trainer_dist_allreduce_is_bucketed():
    """The legacy eager dist path coalesces the WHOLE gradient set into
    one kvstore call per step (the compiled TrainStep path makes even
    that zero)."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore="dist_sync")
    from mxnet_tpu import autograd
    x = mx.nd.ones((4, 6))
    y = mx.nd.ones((4, 2))
    loss_fn = gluon.loss.L2Loss()
    was = telemetry.enabled()
    telemetry.enable()
    telemetry.reset("kvstore.")
    try:
        with autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        tr.step(4)
        # 4 gradient tensors, ONE bucketed pushpull
        assert telemetry.counter("kvstore.pushpull").value == 1
    finally:
        if not was:
            telemetry.disable()
    assert np.isfinite(float(loss.asnumpy()))


def test_metric_get_global(monkeypatch):
    m = mx.metric.Accuracy()
    m.update([mx.nd.array([0, 1])], [mx.nd.array([[0.9, 0.1],
                                                  [0.2, 0.8]])])
    name, val = m.get_global()               # world == 1: same as get()
    assert (name, val) == m.get()
    # simulate a 2-rank world where the peer got 0/2 right: the global
    # accuracy pools (sum_metric, num_inst) in ONE bucketed collective
    monkeypatch.setattr(dist, "world", lambda: (2, 0))
    calls = []

    def fake_bucketed(arrs, average=False, timeout_ms=0):
        calls.append(len(arrs))
        return [np.asarray(a) * 2 for a in arrs]  # peer mirrors local

    monkeypatch.setattr(dist, "host_allreduce_bucketed", fake_bucketed)
    name, val = m.get_global()
    assert calls == [1]
    assert val == pytest.approx(m.get()[1])


def test_horovod_grouped_allreduce_world1():
    from mxnet_tpu import horovod as hvd
    outs = hvd.grouped_allreduce([mx.nd.ones((2,)) * 3,
                                  mx.nd.ones((3,)) * 4])
    np.testing.assert_allclose(outs[0].asnumpy(), np.full(2, 3.0))
    np.testing.assert_allclose(outs[1].asnumpy(), np.full(3, 4.0))


def test_context_of_mesh_sharded_array_is_addressable():
    """NDArray.context on a mesh-global array names an addressable
    device by LOCAL ordinal (a raw global id breaks eager state
    creation on non-zero ranks)."""
    mesh = make_mesh({"dp": 4}, devices=jax.devices()[:4])
    x = mx.nd.NDArray(jax.device_put(np.ones(4, np.float32),
                                     NamedSharding(mesh, P())))
    ctx = x.context
    assert ctx.jax_device() in jax.local_devices()