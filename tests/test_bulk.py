"""Bulked eager dispatch: concurrency + failure-transparency contract
(reference: ``tests/cpp/engine/threaded_engine_test.cc`` -- the engine
was the reference's concurrency mechanism; here the bulk queue is the
shared mutable analog and must survive multi-threaded eager use, and a
failed region must surface the ORIGINAL op error at the sync point, the
``threaded_engine.cc :: OnCompleteStatic`` captured-exception contract).
"""
import threading

import jax.numpy as jnp
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import bulk


def _bulk_or_skip():
    if not bulk.enabled():
        pytest.skip("MXNET_TPU_EAGER_BULK=0")


def test_bulk_basic_region_replay():
    _bulk_or_skip()
    a = mx.nd.ones((4, 4))
    # warmup pass (concrete), then the bulked pass (pending LazyData)
    for _ in range(2):
        b = a * 2.0
        c = b + 1.0
        d = c.sum()
    np.testing.assert_allclose(d.asnumpy(), 4 * 4 * 3.0)
    np.testing.assert_allclose(c.asnumpy(), 3.0)


def test_bulk_two_thread_stress():
    """Concurrent eager dispatch from several threads (DataLoader
    workers, Horovod callbacks) must neither corrupt the queue nor
    cross-wire regions: each thread checks its own arithmetic."""
    _bulk_or_skip()
    errs = []

    def worker(seed):
        try:
            a = mx.nd.full((8,), float(seed))
            for i in range(60):
                a = a + 1.0
                if i % 13 == 0:
                    # mid-loop sync: flushes whatever region is pending,
                    # possibly containing the other threads' ops
                    np.testing.assert_allclose(
                        a.asnumpy(), seed + i + 1.0)
            np.testing.assert_allclose(a.asnumpy(), seed + 60.0)
        except Exception as e:  # noqa: BLE001 -- collected for assert
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


def test_bulk_cross_thread_materialize():
    """An NDArray whose buffer is pending in a region enqueued on one
    thread must be readable from another thread (producer/consumer
    handoff)."""
    _bulk_or_skip()
    box = {}

    def producer():
        a = mx.nd.ones((4,))
        for _ in range(2):          # second pass is the bulked one
            b = a * 3.0
        box["arr"] = b

    t = threading.Thread(target=producer)
    t.start()
    t.join()
    np.testing.assert_allclose(box["arr"].asnumpy(), 3.0)


def test_bulk_flush_failure_surfaces_original_error():
    """If the jitted replay fails, the sync point must raise the
    failing op's OWN error; ops not downstream of the failure still
    resolve; downstream reads re-raise the captured exception."""
    _bulk_or_skip()
    fail = {"on": False}

    def good(x):
        return x + 1.0

    def bad(x):
        if fail["on"]:
            raise ValueError("boom-op")
        return x * 2.0

    a = jnp.ones((4,))
    # round 1: concrete warmups for the "arr"-descr signatures
    g = bulk.enqueue(good, "tb_good", (a,))
    b = bulk.enqueue(bad, "tb_bad", (a,))
    bulk.enqueue(good, "tb_good2", (b,))
    # round 2: g/b go pending; g2-on-lazy-b is its own signature and
    # warms up here (its warmup materializes b, flushing the region)
    g = bulk.enqueue(good, "tb_good", (a,))
    b = bulk.enqueue(bad, "tb_bad", (a,))
    bulk.enqueue(good, "tb_good2", (b,))
    bulk.flush()
    # round 3: every signature cached -- all three ops go pending
    g = bulk.enqueue(good, "tb_good", (a,))
    b = bulk.enqueue(bad, "tb_bad", (a,))
    g2 = bulk.enqueue(good, "tb_good2", (b,))
    assert isinstance(b, bulk.LazyData) and isinstance(g2, bulk.LazyData)

    fail["on"] = True
    with pytest.raises(ValueError, match="boom-op"):
        bulk.flush()
    # independent op resolved despite the region failure
    np.testing.assert_allclose(np.asarray(bulk.materialize(g)), 2.0)
    # the failing op and its downstream re-raise the captured original
    with pytest.raises(ValueError, match="boom-op"):
        bulk.materialize(b)
    with pytest.raises(ValueError, match="boom-op"):
        bulk.materialize(g2)
    # reusing a FAILED LazyData as the input of a new op must re-raise
    # the captured error, not wire its stale slot into the new region
    # ("tb_good2" has the lazy-input signature cached, so this exercises
    # the steady-state marker path, not the warmup path)
    with pytest.raises(ValueError, match="boom-op"):
        bulk.enqueue(good, "tb_good2", (b,))
    fail["on"] = False
    # the queue must be clean afterwards: fresh ops work
    h = bulk.enqueue(good, "tb_good", (a,))
    np.testing.assert_allclose(np.asarray(bulk.materialize(h)), 2.0)


def test_bulk_cache_bounded():
    assert bulk._CACHE_MAX >= 64
    d = {}
    for i in range(bulk._CACHE_MAX + 10):
        bulk._cache_put(d, ("k", i), i)
    assert len(d) <= bulk._CACHE_MAX


def test_bulked_cotangents_through_control_flow():
    """Advisor r4 (high): backward through a lax.scan-based construct
    (contrib.foreach) receives cotangents that may be pending
    bulk.LazyData from the bulked backward of downstream eager ops; the
    raw jax.vjp pull must materialize them.  The crash was latent --
    warmup returns concrete outputs -- so the SECOND and THIRD
    iterations with a matching signature are the actual test."""
    _bulk_or_skip()
    from mxnet_tpu import autograd
    from mxnet_tpu.ndarray import contrib as ndc

    for rep in range(3):
        data = mx.nd.array(
            np.arange(20, dtype=np.float32).reshape(5, 4) + rep)
        s0 = mx.nd.zeros((4,))
        data.attach_grad()
        with autograd.record():
            outs, fin = ndc.foreach(
                lambda d, s: (d * 2 + s, s + d), data, s0)
            # downstream EAGER ops: their backward enqueues into the
            # bulk queue, producing LazyData cotangents for foreach
            tot = (outs * 3.0).sum() + (fin * 2.0).sum()
        tot.backward()
        g = data.grad.asnumpy()
        assert np.isfinite(g).all()
    # gradient value check (last rep): d tot / d data[t] =
    # 3*2 (direct) + 3*(rows below, via state) + 2 (fin) per element
    rows_below = np.arange(4, -1, -1)[:, None]  # t contributes to t+1..4
    expect = 6.0 + 3.0 * (rows_below - 0) + 2.0
    expect = np.broadcast_to(expect, (5, 4))
    np.testing.assert_allclose(g, expect, rtol=1e-5)


def test_bulk_with_threaded_dataloader_training():
    """The realistic combined scenario the bulk lock exists for:
    DataLoader WORKER THREADS produce batches (touching mx.nd eagerly)
    while the main thread trains with bulked eager dispatch + autograd
    -- queue handoff, concurrent enqueue/flush, cotangent bulking all
    at once."""
    _bulk_or_skip()
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.data import ArrayDataset, DataLoader

    rng = np.random.RandomState(0)
    xs = rng.randn(64, 6).astype(np.float32)
    w = rng.randn(6, 1).astype(np.float32)
    ys = (xs @ w).astype(np.float32)
    loader = DataLoader(ArrayDataset(xs, ys), batch_size=16,
                        shuffle=True, num_workers=2)

    net = gluon.nn.Dense(1)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    loss_fn = gluon.loss.L2Loss()
    first = last = None
    for epoch in range(8):
        for bx, by in loader:
            with autograd.record():
                loss = loss_fn(net(bx), by).mean()
            loss.backward()
            tr.step(1)
            v = float(loss.asnumpy())
            first = v if first is None else first
            last = v
    assert np.isfinite(last)
    assert last < first, (first, last)
