"""Driver-contract tests for bench.py (VERDICT r4 #1: the artifact
died at rc=124 with the headline lines unprinted; this locks the
headline-first emission order and the self-budget so that regression
class cannot ship silently)."""
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def bench_mod(monkeypatch):
    monkeypatch.syspath_prepend(REPO)   # cleaned up at teardown
    import bench
    # stub every device-touching benchmark
    monkeypatch.setattr(bench, "bench_env_health",
                        lambda **k: {"h2d_mb_per_s": 1.0,
                                     "dispatch_roundtrip_us": 2.0})
    monkeypatch.setattr(bench, "bench_resnet50_scan",
                        lambda *a, **k: (2600.0, 0.29, [2590.0, 2610.0]))
    monkeypatch.setattr(bench, "bench_bert_base",
                        lambda *a, **k: (126000.0, 0.43,
                                         [125000.0, 127000.0]))
    monkeypatch.setattr(bench, "bench_lenet", lambda *a, **k: 30000.0)
    monkeypatch.setattr(bench, "bench_resnet50_lars",
                        lambda *a, **k: (2400.0, 0.27, [2390.0, 2410.0]))
    monkeypatch.setattr(bench, "bench_serving",
                        lambda *a, **k: [
                            {"offered_qps": 100, "qps": 99.0,
                             "p50_ms": 3.0, "p95_ms": 5.0, "p99_ms": 7.0,
                             "mean_occupancy": 2.5, "shed": 0}])
    monkeypatch.setattr(bench, "bench_serving_hotswap",
                        lambda *a, **k: {
                            "swap_step": 4, "swap_latency_ms": 120.0,
                            "p50_steady_ms": 3.0, "p99_steady_ms": 7.0,
                            "p50_during_swap_ms": 3.5,
                            "p99_during_swap_ms": 9.0,
                            "requests": 1000,
                            "requests_during_swap": 80, "dropped": 0})
    monkeypatch.setattr(bench, "bench_serving_decode",
                        lambda *a, **k: {
                            "tokens_per_s": 4200.0, "streams": 60,
                            "ttft_p50_ms": 8.0, "ttft_p99_ms": 20.0,
                            "inter_token_p50_ms": 2.0,
                            "inter_token_p99_ms": 6.0,
                            "mean_occupancy": 3.1, "shed": 0})
    monkeypatch.setattr(bench, "bench_lenet_imperative",
                        lambda *a, **k: 25000.0)
    monkeypatch.setattr(bench, "bench_resnet50", lambda *a, **k: 1500.0)
    monkeypatch.setattr(bench, "bench_pipeline",
                        lambda *a, **k: (1500.0, 5000.0, {}))
    monkeypatch.setattr(bench, "_cpu_subprocess_value",
                        lambda *a, **k: 1000.0)
    monkeypatch.setattr(bench, "bench_batch_hbm_sweep",
                        lambda *a, **k: {
                            "probe": "resnet50v1-nchw-sgd-224",
                            "hbm_budget_bytes": 16 << 30,
                            "const_bytes": 98000000,
                            "per_item_bytes": 2000000,
                            "buckets": [
                                {"batch": 64,
                                 "predicted_peak_hbm_bytes": 226000000,
                                 "measured_peak_hbm_bytes": 230000000,
                                 "rel_error": -0.0174, "fits": True}],
                            "largest_fit_bucket": 64})
    monkeypatch.setattr(bench, "_multichip_scaling_rows",
                        lambda *a, **k: [
                            {"n_devices": 1, "img_per_s": 1000.0,
                             "per_device_img_per_s": 1000.0,
                             "efficiency": 1.0, "collectives": {},
                             "collective_bytes": 0},
                            {"n_devices": 2, "img_per_s": 1800.0,
                             "per_device_img_per_s": 900.0,
                             "efficiency": 0.9,
                             "collectives": {"all-reduce":
                                             {"count": 7,
                                              "bytes": 67884}},
                             "collective_bytes": 67884}])
    monkeypatch.setattr(bench, "_subprocess_pair",
                        lambda *a, **k: (2000.0, 0.8))
    # the e2e subprocess now ships rate + overlap + goodput breakdown
    # as one JSON object (ISSUE 14)
    _e2e_goodput = {
        "steps": 32, "wall_s": 4.1, "mfu": 0.21,
        "shares": {"device_compute": 0.41, "input_wait": 0.46,
                   "host_sync": 0.02, "checkpoint_stall": 0.0,
                   "recompile": 0.0, "other": 0.11},
        "verdict": "input-bound: feed supplies 47% of device demand",
        "bound": "input", "reconciled": True, "env_degraded": False}
    monkeypatch.setattr(
        bench, "_subprocess_json",
        lambda *a, **k: {"img_per_s": 2000.0,
                         "staging_overlap_frac": 0.8,
                         "goodput": _e2e_goodput})
    # the scan/LARS configs stash their ledger windows here (stubbed
    # fns skip the real ledger; the shape is the contract)
    monkeypatch.setattr(bench, "_GOODPUT", {
        "resnet50_bf16": {
            "steps": 40, "wall_s": 3.9, "mfu": 0.29,
            "shares": {"device_compute": 0.93, "input_wait": 0.0,
                       "host_sync": 0.01, "checkpoint_stall": 0.0,
                       "recompile": 0.0, "other": 0.06},
            "verdict": "compute-bound: device busy 93% of wall",
            "bound": "compute", "reconciled": True,
            "env_degraded": False},
        "resnet50_lars_bf16": {
            "steps": 30, "wall_s": 3.2, "mfu": 0.27,
            "shares": {"device_compute": 0.9, "input_wait": 0.0,
                       "host_sync": 0.01, "checkpoint_stall": 0.0,
                       "recompile": 0.0, "other": 0.09},
            "verdict": "compute-bound: device busy 90% of wall",
            "bound": "compute", "reconciled": True,
            "env_degraded": False}})
    # the kernel-tier HLO diff compiles two probe models; stub it with
    # the contract shape (the REAL probe is covered by test_kernels.py)
    monkeypatch.setattr(
        bench, "_kernels_diff",
        lambda model: {
            "probe": model, "after_interpret": False,
            "before": {"transpose_layout": 1000,
                       "unfused_elementwise": 500, "bytes_total": 4000},
            "after": {"transpose_layout": 400,
                      "unfused_elementwise": 100, "bytes_total": 3000},
            "delta": {"transpose_layout": -600,
                      "unfused_elementwise": -400, "bytes_total": -1000}})
    # _emit_with_retry sleeps between real retries; stubs don't need it
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    import mxnet_tpu as mx
    monkeypatch.setattr(mx, "num_tpus", lambda: 1)
    return bench


def _metrics(capsys):
    lines = [json.loads(ln) for ln in
             capsys.readouterr().out.strip().splitlines()]
    return [ln["metric"] for ln in lines], lines


def test_headline_lines_emit_first(bench_mod, capsys):
    bench_mod.main()
    metrics, lines = _metrics(capsys)
    # the contract: health, then resnet scan + bert + vs_baseline,
    # BEFORE any garnish -- a driver timeout can only cost the tail
    assert metrics[0] == "env_health"
    assert metrics[1] == "resnet50_imagenet_train_bf16_scan"
    assert metrics[2] == "bert_base_pretrain_bfloat16"
    assert metrics[3] == "resnet50_imagenet_train"
    by = {ln["metric"]: ln for ln in lines}
    scan = by["resnet50_imagenet_train_bf16_scan"]
    assert scan["mfu"] == 0.29 and scan["min"] and scan["max"]
    bert = by["bert_base_pretrain_bfloat16"]
    assert bert["mfu"] == 0.43 and "windows" in bert
    head = by["resnet50_imagenet_train"]
    assert head["vs_baseline"] == round(2600.0 / 3000.0, 4)
    assert metrics[-1] == "bench_complete"


def test_every_emitted_line_carries_degraded_env(bench_mod, capsys):
    """ISSUE 11 satellite (bench hygiene): every emitted JSONL line
    carries a `degraded_env` boolean derived from the env_health
    probe's dispatch_roundtrip threshold, so an r05-style tunnel
    collapse can never again be read as a perf regression."""
    bench_mod.main()
    _names, lines = _metrics(capsys)
    for ln in lines:
        if ln["metric"] == "bench_complete" or ln.get("skipped"):
            continue
        assert "degraded_env" in ln, ln["metric"]
    by = {ln["metric"]: ln for ln in lines}
    # the stub probe reports a 2us dispatch RTT: healthy
    assert by["env_health"]["degraded_env"] is False
    assert by["resnet50_imagenet_train_bf16_scan"]["degraded_env"] is False
    assert by["resnet50_imagenet_train"]["degraded_env"] is False


def test_degraded_env_flips_on_slow_dispatch(bench_mod, capsys,
                                             monkeypatch):
    """A collapsed-tunnel dispatch RTT (r05: ~90ms) marks EVERY line
    degraded, headline included."""
    monkeypatch.setattr(bench_mod, "bench_env_health",
                        lambda **k: {"h2d_mb_per_s": 1.0,
                                     "dispatch_roundtrip_us": 90000.0})
    bench_mod.main()
    _names, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    assert by["env_health"]["degraded_env"] is True
    assert by["resnet50_imagenet_train_bf16_scan"]["degraded_env"] is True
    assert by["resnet50_imagenet_train"]["degraded_env"] is True


def test_scan_line_carries_kernels_diff(bench_mod, capsys):
    """ISSUE 11 acceptance: the resnet50-scan line carries the kernel
    tier's before/after mxprof category deltas (transpose_layout /
    unfused-elementwise bytes)."""
    bench_mod.main()
    _names, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    kd = by["resnet50_imagenet_train_bf16_scan"]["kernels_diff"]
    for key in ("probe", "after_interpret", "before", "after", "delta"):
        assert key in kd, key
    assert kd["delta"]["transpose_layout"] < 0
    assert kd["delta"]["unfused_elementwise"] < 0


def test_budget_exhaustion_skips_garnish_only(bench_mod, capsys,
                                              monkeypatch):
    monkeypatch.setattr(bench_mod, "_BUDGET_S", 0.001)
    bench_mod.main()
    metrics, lines = _metrics(capsys)
    # headline metrics always emit regardless of budget
    assert metrics[1] == "resnet50_imagenet_train_bf16_scan"
    assert metrics[3] == "resnet50_imagenet_train"
    skipped = [ln for ln in lines if ln.get("skipped")]
    assert skipped, "optional configs must emit skip lines, not die"
    for ln in skipped:
        assert "budget" in ln["reason"]
    # nothing headline may be in the skipped set
    names = {ln["metric"] for ln in skipped}
    assert not names & {"resnet50_imagenet_train_bf16_scan",
                        "bert_base_pretrain_bfloat16",
                        "resnet50_imagenet_train", "env_health"}


def test_batch_hbm_sweep_line_contract(bench_mod, capsys):
    """ISSUE 20 bench contract (ROADMAP item 1's sweep): the
    batch_hbm_sweep line carries predicted-vs-measured peak HBM per
    bucket, the fitted const/per-item line, the budget, the largest
    fitting bucket -- and the degraded_env flag like every line."""
    bench_mod.main()
    _names, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    rec = by["batch_hbm_sweep"]
    assert "degraded_env" in rec
    assert rec["hbm_budget_bytes"] > 0
    assert rec["const_bytes"] >= 0 and rec["per_item_bytes"] >= 0
    for b in rec["buckets"]:
        assert {"batch", "predicted_peak_hbm_bytes",
                "measured_peak_hbm_bytes", "rel_error",
                "fits"} <= set(b)
    assert rec["largest_fit_bucket"] == 64


def test_batch_hbm_sweep_is_hbm_plan_driven(monkeypatch):
    """The sweep's predictions must come from analysis.memory.hbm_plan
    and its measurements from executable_memory (the planner's accuracy
    contract) -- not bench-local extrapolation.  Uses the UNPATCHED
    module (the bench_mod fixture stubs the function)."""
    import inspect
    monkeypatch.syspath_prepend(REPO)
    import bench
    src = inspect.getsource(bench.bench_batch_hbm_sweep)
    assert "hbm_plan" in src
    assert "executable_memory" in src
    assert "device_hbm_bytes" in src


def test_e2e_runs_on_library_device_feed(bench_mod):
    """ISSUE 4: the e2e config must measure the PRODUCT's staging path
    (mxnet_tpu.dataio.DeviceFeed), not bench-local scaffolding -- no
    private producer thread, no hand-rolled slab queue, and the overlap
    fraction must come from the feed.* telemetry instruments."""
    import inspect
    src = inspect.getsource(bench_mod.bench_resnet50_e2e)
    assert "DeviceFeed" in src
    assert "threading.Thread" not in src
    assert "slab_q" not in src
    assert "feed.producer_busy" in src and "feed.consumer_wait" in src


def test_headline_configs_persist_cost_reports(monkeypatch):
    """ISSUE 6: the ResNet-50 and BERT configs must persist CostReport
    artifacts next to their JSONL lines via the library path
    (mx.profiling.report_for), not bench-local accounting.  Uses the
    UNPATCHED module (the bench_mod fixture stubs these functions)."""
    import inspect
    monkeypatch.syspath_prepend(REPO)
    import bench
    src = inspect.getsource(bench.bench_resnet50_scan)
    assert "_persist_cost_report" in src
    src = inspect.getsource(bench.bench_bert_base)
    assert "_persist_cost_report" in src
    src = inspect.getsource(bench._persist_cost_report)
    assert "profiling.report_for" in src


def test_cost_report_schema_locked(bench_mod, tmp_path, monkeypatch):
    """The persisted artifact's schema is the mxprof contract: totals,
    reconciled categories, memory, roofline with bound labels."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep
    monkeypatch.setenv("MXNET_TPU_PROFILING_DIR", str(tmp_path))
    net = gluon.nn.Dense(4)
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1}, kvstore=None)
    step = TrainStep(net, gluon.loss.L2Loss(), tr, mesh=None)
    step(mx.nd.array(np.ones((8, 6), np.float32)),
         mx.nd.array(np.ones((8, 4), np.float32)))
    path = bench_mod._persist_cost_report("contract_probe", step,
                                          step_time_s=0.01,
                                          items_per_step=8)
    assert path and os.path.isfile(path)
    rep = json.load(open(path))
    assert rep["schema"] == "mxprof.cost_report.v1"
    for key in ("label", "fingerprint", "totals", "memory",
                "categories", "provenance", "roofline"):
        assert key in rep, key
    assert set(rep["categories"]) == {
        "conv_dot", "collective", "transpose_layout",
        "elementwise_fusion", "other"}
    f_sum = sum(c["flops"] for c in rep["categories"].values())
    assert abs(f_sum - rep["totals"]["flops"]) < 1
    for v in rep["roofline"]["categories"].values():
        assert v["bound"] in ("compute", "memory")
    # and the emitted line's extra fields resolve from the artifact
    extra = bench_mod._cost_extra("contract_probe")
    assert extra["cost_report"] == path
    assert extra["hlo_top_category"] in rep["categories"]


def test_lars_baseline_config5_emits(bench_mod, capsys):
    """ISSUE 8 satellite: BASELINE config 5 (bf16 AMP + LARS
    large-batch ResNet-50) emits img/s + MFU into the BENCH JSONL."""
    bench_mod.main()
    _metrics_list, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    rec = by["resnet50_imagenet_train_bf16_lars_largebatch"]
    assert rec["value"] == 2400.0 and rec["unit"] == "img/s"
    assert rec["mfu"] == 0.27 and rec["optimizer"] == "lars"
    assert rec["windows"] == [2390.0, 2410.0]


def test_lars_and_serving_use_library_paths(monkeypatch):
    """Source contract on the UNPATCHED module: the LARS config trains
    through the registered 'lars' optimizer, and bench_serving drives
    the product serving path (mx.serving.ModelRegistry + serving.*
    telemetry), not bench-local scaffolding."""
    import inspect
    monkeypatch.syspath_prepend(REPO)
    import bench
    src = inspect.getsource(bench.bench_resnet50_lars)
    assert '"lars"' in src and "TrainStep" in src
    assert "_persist_cost_report" in src
    sv = inspect.getsource(bench.bench_serving)
    assert "ModelRegistry" in sv
    assert "serving.batches" in sv and "serving.responses" in sv


def test_serving_curve_emits(bench_mod, capsys):
    """The bench contract: a latency-vs-QPS curve rides one JSONL line
    with per-level percentiles and occupancy."""
    bench_mod.main()
    _metrics_list, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    rec = by["serving_latency_qps"]
    assert isinstance(rec["curve"], list) and rec["curve"]
    level = rec["curve"][0]
    for key in ("offered_qps", "qps", "p50_ms", "p95_ms", "p99_ms",
                "mean_occupancy", "shed"):
        assert key in level, key


def test_serving_hotswap_line_emits(bench_mod, capsys):
    """ISSUE 12 bench contract: the hot-swap line carries swap latency,
    p99-during-swap vs steady, and the zero-dropped count."""
    bench_mod.main()
    _metrics_list, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    rec = by["serving_hotswap"]
    assert rec["unit"] == "ms"
    for key in ("swap_step", "swap_latency_ms", "p99_during_swap_ms",
                "p99_steady_ms", "p50_during_swap_ms", "p50_steady_ms",
                "requests_during_swap", "dropped"):
        assert key in rec, key
    assert rec["dropped"] == 0


def test_serving_decode_line_emits(bench_mod, capsys):
    """ISSUE 18 bench contract: the generative-tier line carries
    tokens/s, TTFT and inter-token percentiles, occupancy, and shed."""
    bench_mod.main()
    _metrics_list, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    rec = by["serving_decode"]
    assert rec["unit"] == "tokens/s"
    for key in ("tokens_per_s", "streams", "ttft_p50_ms",
                "ttft_p99_ms", "inter_token_p50_ms",
                "inter_token_p99_ms", "mean_occupancy", "shed"):
        assert key in rec, key
    assert "degraded_env" in rec


def test_serving_decode_bench_uses_product_path(monkeypatch):
    """Source contract on the UNPATCHED module: the generative bench
    streams through ModelRegistry.register_generative/generate and
    reads the decode.* telemetry counters, not bench-local
    scaffolding."""
    import inspect
    monkeypatch.syspath_prepend(REPO)
    import bench
    src = inspect.getsource(bench.bench_serving_decode)
    assert "register_generative" in src and "reg.generate" in src
    assert "decode.steps" in src and "decode.tokens" in src


def test_hotswap_bench_uses_product_loop(monkeypatch):
    """Source contract on the UNPATCHED module: the hot-swap bench
    drives the PRODUCT always-on loop (ContinuousTrainer publishing
    checkpoints + RegistryWatcher re-registering), not bench-local
    scaffolding."""
    import inspect
    monkeypatch.syspath_prepend(REPO)
    import bench
    src = inspect.getsource(bench.bench_serving_hotswap)
    assert "ContinuousTrainer" in src and "RegistryWatcher" in src
    assert "poll_once" in src


def test_multichip_scaling_line_emits(bench_mod, capsys):
    """ISSUE 9 bench contract: the MULTICHIP scaling line rides one
    JSONL line with img/s, per-device efficiency, and in-graph
    collective bytes per device count."""
    bench_mod.main()
    _metrics_list, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    rec = by["multichip_scaling"]
    assert rec["unit"] == "img/s"
    rows = rec["scaling"]
    assert [r["n_devices"] for r in rows] == [1, 2]
    for r in rows:
        for key in ("img_per_s", "per_device_img_per_s", "efficiency",
                    "collectives", "collective_bytes"):
            assert key in r, key
    # multi-device rows must carry the in-graph gradient all-reduce
    assert rows[1]["collectives"]["all-reduce"]["bytes"] > 0


def test_multichip_scaling_real_two_device(monkeypatch):
    """The UNSTUBBED sweep on the suite's virtual devices: the 2-device
    compiled step's collective profile lists the GSPMD gradient
    all-reduce with non-zero bytes (in-graph, not host kvstore)."""
    monkeypatch.syspath_prepend(REPO)
    import bench
    rows = bench.bench_multichip_scaling(device_counts=(1, 2),
                                         batch_per_device=8, iters=2,
                                         warmup=1)
    assert rows[0]["collective_bytes"] == 0
    assert rows[0]["efficiency"] == 1.0
    two = rows[1]
    assert two["n_devices"] == 2
    assert two["collectives"]["all-reduce"]["count"] > 0
    assert two["collective_bytes"] > 0
    assert two["img_per_s"] > 0 and two["efficiency"] > 0


def test_scan_and_e2e_lines_carry_goodput_breakdown(bench_mod, capsys):
    """ISSUE 14 acceptance: the scan, LARS, and e2e lines carry the
    StepLedger breakdown (per-category shares + the attribution
    verdict), so the synthetic-vs-e2e gap is auto-attributed -- the
    e2e stub reads input-bound while the synthetic scan reads
    compute-bound, which IS the r04 1258-vs-2474 attribution."""
    bench_mod.main()
    _names, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    for metric, bound in (
            ("resnet50_imagenet_train_bf16_scan", "compute"),
            ("resnet50_imagenet_train_bf16_lars_largebatch", "compute"),
            ("resnet50_imagenet_train_e2e_bf16", "input")):
        gp = by[metric].get("goodput")
        assert gp, "%s line missing goodput" % metric
        for key in ("steps", "wall_s", "shares", "verdict", "bound",
                    "reconciled", "env_degraded"):
            assert key in gp, (metric, key)
        assert gp["bound"] == bound, (metric, gp)
        assert set(gp["shares"]) == {
            "device_compute", "input_wait", "host_sync",
            "checkpoint_stall", "recompile", "other"}
    e2e = by["resnet50_imagenet_train_e2e_bf16"]["goodput"]
    assert "feed supplies" in e2e["verdict"]


def test_e2e_bench_runs_the_ledger(monkeypatch):
    """Source contract on the UNPATCHED module: the e2e config measures
    through the library StepLedger (obs.goodput), not bench-local
    accounting, and the scan/LARS configs do the same."""
    import inspect
    monkeypatch.syspath_prepend(REPO)
    import bench
    for fn in (bench.bench_resnet50_e2e, bench.bench_resnet50_scan,
               bench.bench_resnet50_lars):
        src = inspect.getsource(fn)
        assert "_goodput_begin" in src and "_goodput_end" in src, \
            fn.__name__
    src = inspect.getsource(bench._goodput_begin)
    assert "StepLedger" in src
    src = inspect.getsource(bench._goodput_end)
    assert "line_summary" in src


def test_degraded_env_flag_agrees_with_goodput_env_guard(monkeypatch):
    """ISSUE 14 satellite (contract-locked): the JSONL degraded_env
    flag and the sentinel's goodput.env_degraded event derive from ONE
    threshold -- when the env guard trips, both say degraded; when
    healthy, both say healthy."""
    import numpy as np  # noqa: F401
    from mxnet_tpu import telemetry
    from mxnet_tpu.obs import goodput
    monkeypatch.syspath_prepend(REPO)
    import bench
    was = telemetry.enabled()
    telemetry.enable()
    monkeypatch.setattr(bench, "_ENV_DEGRADED", {"flag": None})
    try:
        telemetry.reset("goodput.")
        telemetry.reset("env.")
        # collapsed tunnel: the probe marks the line degraded AND sets
        # the gauge the sentinel's env guard reads
        flag = bench._mark_env_health(
            {"dispatch_roundtrip_us": 90000.0, "h2d_mb_per_s": 1.0})
        assert flag is True
        led = goodput.StepLedger(window_steps=2)
        telemetry.timer("profiling.step_time").observe(0.004)
        win = led.step(2)
        assert win["env_degraded"] is flag is True
        assert telemetry.counter(
            "goodput.env_degraded_windows").value == 1
        ev = telemetry.event("goodput.env_degraded").recent[-1]
        assert ev["dispatch_roundtrip_us"] == 90000.0
        assert win["regressions"] == []       # env, never regression
        # healthy probe: both sides flip together
        flag = bench._mark_env_health(
            {"dispatch_roundtrip_us": 2.0, "h2d_mb_per_s": 100.0})
        telemetry.timer("profiling.step_time").observe(0.004)
        win = led.step(2)
        assert win["env_degraded"] is flag is False
        assert telemetry.counter(
            "goodput.env_degraded_windows").value == 1
    finally:
        telemetry.reset("goodput.")
        telemetry.reset("env.")
        if not was:
            telemetry.disable()


def test_scan_failure_falls_back_for_headline(bench_mod, capsys,
                                              monkeypatch):
    def boom(*a, **k):
        raise RuntimeError("compile dropped")
    monkeypatch.setattr(bench_mod, "bench_resnet50_scan", boom)
    monkeypatch.setattr(bench_mod, "_BUDGET_S", 0.001)
    bench_mod.main()
    metrics, lines = _metrics(capsys)
    by = {ln["metric"]: ln for ln in lines}
    # the final line still carries a real number from the fallback
    head = by["resnet50_imagenet_train"]
    assert head["value"] == 1500.0
    assert head["vs_baseline"] == 0.5
