"""hbmlint (ISSUE 20): per-rule static fixtures for the five
HBM-hazard rules, the compiled peak-HBM audit contract (registry walk,
same-label merge, baseline round trip, schema reject), the hbm_plan
batch-bucket extrapolation against real compiles, the SARIF export,
the mxprof max-of-peaks merge convention, and the live-buffer leak
sentinel: zero-touch when disarmed, chaos-pinned growth flagged within
three windows when armed, publish-guarded windows neither judged nor
taught."""
import json
import os

import pytest

import jax.numpy as jnp

from mxnet_tpu import chaos
from mxnet_tpu import analysis as an
from mxnet_tpu.analysis import memory

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MEM_RULES = {"device-ref-accumulation", "unbounded-shape-cache",
             "host-materialize-large", "retained-temp-across-step",
             "feed-depth-unbounded"}


def _lint(src):
    return an.lint_source(src, "probe.py")


def _mem(diags):
    """The memory-rule subset -- fixtures may legitimately trip other
    passes (a jit without donation is also PR 7's business)."""
    return sorted({d.rule for d in diags if d.rule in MEM_RULES})


@pytest.fixture(autouse=True)
def _memory_state():
    """Snapshot/restore the watch flag, /statusz counters, sentinel,
    and chaos-pinned arrays."""
    prev_watch = memory._WATCH
    prev_state = dict(memory._STATE)
    prev_sentinel = memory._SENTINEL
    yield
    memory._WATCH = prev_watch
    memory._STATE.clear()
    memory._STATE.update(prev_state)
    memory._SENTINEL = prev_sentinel
    memory._PINNED.clear()


# ----------------------------------------------------------------------
# static rules: one positive and one negative fixture per rule
# ----------------------------------------------------------------------

def test_device_ref_accumulation_fires_and_host_scalar_silent():
    bad = (
        "def train(step, data):\n"
        "    losses = []\n"
        "    for x, y in data:\n"
        "        loss = step(x, y)\n"
        "        losses.append(loss)\n"
    )
    diags = [d for d in _lint(bad)
             if d.rule == "device-ref-accumulation"]
    assert len(diags) == 1
    assert diags[0].line == 5
    assert "float(x)" in diags[0].message
    assert "deque(maxlen=N)" in diags[0].message
    good = (
        "def train(step, data):\n"
        "    losses = []\n"
        "    for x, y in data:\n"
        "        loss = step(x, y)\n"
        "        losses.append(float(loss))\n"
    )
    assert "device-ref-accumulation" not in _mem(_lint(good))
    # outside a training loop the accumulation is someone's business,
    # not this rule's
    eager = (
        "def collect(make, n):\n"
        "    outs = []\n"
        "    for i in range(n):\n"
        "        outs.append(make(i))\n"
    )
    assert _mem(_lint(eager)) == []


def test_device_ref_accumulation_augassign_and_derived_taint():
    bad = (
        "def train(step, data):\n"
        "    hist = []\n"
        "    for x, y in data:\n"
        "        loss = step(x, y)\n"
        "        smooth = loss\n"        # taint flows through reuse
        "        hist += [smooth]\n"
    )
    assert "device-ref-accumulation" in _mem(_lint(bad))


def test_unbounded_shape_cache_fires_and_evicting_silent():
    bad = (
        "_CACHE = {}\n"
        "def compiled_for(x, build):\n"
        "    key = (x.shape, str(x.dtype))\n"
        "    if key not in _CACHE:\n"
        "        _CACHE[key] = build(x)\n"
        "    return _CACHE[key]\n"
    )
    diags = [d for d in _lint(bad) if d.rule == "unbounded-shape-cache"]
    assert len(diags) == 1
    assert "'_CACHE'" in diags[0].message
    # setdefault keyed on a sig-named expression fires too
    sd = (
        "_PROGRAMS = dict()\n"
        "def get(sig, make):\n"
        "    return _PROGRAMS.setdefault(sig, make())\n"
    )
    assert "unbounded-shape-cache" in _mem(_lint(sd))
    # an eviction bound anywhere in the file clears the cache
    good = (
        "_CACHE = {}\n"
        "def compiled_for(x, build):\n"
        "    key = (x.shape, str(x.dtype))\n"
        "    while len(_CACHE) >= 64:\n"
        "        _CACHE.pop(next(iter(_CACHE)))\n"
        "    _CACHE[key] = build(x)\n"
        "    return _CACHE[key]\n"
    )
    assert _mem(_lint(good)) == []
    # a dict not keyed on shape/dtype is not this rule's business
    named = (
        "_BY_NAME = {}\n"
        "def register(name, obj):\n"
        "    _BY_NAME[name] = obj\n"
    )
    assert _mem(_lint(named)) == []


def test_host_materialize_large_fires_and_small_or_hoisted_silent():
    bad = (
        "def monitor(n, nd):\n"
        "    big = nd.zeros((2048, 2048))\n"
        "    for i in range(n):\n"
        "        snap = big.asnumpy()\n"
    )
    diags = [d for d in _lint(bad) if d.rule == "host-materialize-large"]
    assert len(diags) == 1
    assert "'big'" in diags[0].message and "4,194,304" in diags[0].message
    # small tensors and hoisted materialization stay silent
    small = (
        "def monitor(n, nd):\n"
        "    little = nd.zeros((64, 64))\n"
        "    for i in range(n):\n"
        "        snap = little.asnumpy()\n"
    )
    assert _mem(_lint(small)) == []
    hoisted = (
        "def monitor(n, nd):\n"
        "    big = nd.zeros((2048, 2048))\n"
        "    snap = big.asnumpy()\n"
        "    for i in range(n):\n"
        "        use(snap)\n"
    )
    assert _mem(_lint(hoisted)) == []


def test_retained_temp_across_step_fires_and_donated_silent():
    bad = (
        "import jax\n"
        "step = jax.jit(update)\n"
        "class Loop:\n"
        "    def run(self, data):\n"
        "        for x, y in data:\n"
        "            self.state = step(x, y)\n"
    )
    diags = [d for d in _lint(bad)
             if d.rule == "retained-temp-across-step"]
    assert len(diags) == 1
    assert "self.state" in diags[0].message
    assert "donate_argnums" in diags[0].message
    donated = (
        "import jax\n"
        "step = jax.jit(update, donate_argnums=(0,))\n"
        "class Loop:\n"
        "    def run(self, data):\n"
        "        for x, y in data:\n"
        "            self.state = step(x, y)\n"
    )
    assert "retained-temp-across-step" not in _mem(_lint(donated))
    released = (
        "import jax\n"
        "step = jax.jit(update)\n"
        "class Loop:\n"
        "    def run(self, data):\n"
        "        for x, y in data:\n"
        "            del self.state\n"
        "            self.state = step(x, y)\n"
    )
    assert "retained-temp-across-step" not in _mem(_lint(released))


def test_feed_depth_unbounded_fires_and_bounded_silent():
    bad = (
        "import collections\n"
        "import queue\n"
        "class Feeder:\n"
        "    def __init__(self):\n"
        "        self.feed_q = collections.deque()\n"
        "        self.prefetch = queue.Queue()\n"
    )
    diags = [d for d in _lint(bad) if d.rule == "feed-depth-unbounded"]
    assert len(diags) == 2
    msgs = "\n".join(d.message for d in diags)
    assert "'feed_q'" in msgs and "'prefetch'" in msgs
    assert "MXNET_TPU_FEED_DEPTH" in msgs
    # ctor bounds are the blessed form
    good = (
        "import collections\n"
        "import queue\n"
        "class Feeder:\n"
        "    def __init__(self, depth):\n"
        "        self.feed_q = collections.deque(maxlen=depth)\n"
        "        self.prefetch = queue.Queue(maxsize=depth)\n"
    )
    assert _mem(_lint(good)) == []
    # a len() shed check anywhere in the file bounds as surely as a
    # ctor maxlen (the serving batcher's pattern)
    shed = (
        "import collections\n"
        "class Feeder:\n"
        "    def __init__(self):\n"
        "        self.feed_q = collections.deque()\n"
        "    def put(self, item):\n"
        "        if len(self.feed_q) >= 8:\n"
        "            raise RuntimeError('full')\n"
        "        self.feed_q.append(item)\n"
    )
    assert _mem(_lint(shed)) == []


def test_feed_depth_device_staging_evidence_gates_plain_names():
    # a neutrally-named deque is gated only when the scope stages
    # device arrays into it
    staging = (
        "import collections\n"
        "def producer(batches):\n"
        "    buf = collections.deque()\n"
        "    buf.append(jnp.zeros((4,)))\n"
    )
    assert "feed-depth-unbounded" in _mem(_lint(staging))
    plain = (
        "import collections\n"
        "def producer(items):\n"
        "    buf = collections.deque()\n"
        "    buf.append(items[0])\n"
    )
    assert _mem(_lint(plain)) == []


def test_memory_rules_registered_and_suppressible():
    from mxnet_tpu.analysis import core
    for rid in sorted(MEM_RULES):
        assert core.RULES[rid].kind == "ast"
        assert core.RULES[rid].doc
    assert core.RULES["memory-drift"].kind == "compiled"
    suppressed = (
        "_CACHE = {}\n"
        "def compiled_for(x, build):\n"
        "    key = (x.shape, str(x.dtype))\n"
        "    _CACHE[key] = build(x)  "
        "# mxlint: disable=unbounded-shape-cache\n"
        "    return _CACHE[key]\n"
    )
    assert _mem(_lint(suppressed)) == []


# ----------------------------------------------------------------------
# compiled layer: registry walk, same-label merge, baseline round trip
# ----------------------------------------------------------------------

def _register_toy(label, fn, *args):
    import jax
    from mxnet_tpu.profiling import store
    jfn = jax.jit(fn)
    jfn(*args)
    store.register((label,), label, jfn, args)
    return jfn


def test_memory_audit_registry_walk():
    from mxnet_tpu import profiling
    profiling.reset()
    _register_toy("toy:memaudit",
                  lambda a, b: (a @ b).sum(axis=0),
                  jnp.ones((64, 64), jnp.float32),
                  jnp.ones((64, 64), jnp.float32))
    audit = memory.memory_audit()
    assert audit["schema"] == memory.AUDIT_SCHEMA == "mxmemory.audit.v1"
    assert audit["thresholds"]["temp_args_factor"] == 2.0
    m = audit["executables"]["toy:memaudit"]["metrics"]
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "alias_bytes", "donatable_bytes", "peak_hbm_bytes",
                "temp_share", "alias_coverage"):
        assert key in m
    assert m["argument_bytes"] >= 2 * 64 * 64 * 4
    # the peak identity the planner and the drift gate both lean on
    assert m["peak_hbm_bytes"] == max(
        0, m["argument_bytes"] + m["output_bytes"] + m["temp_bytes"]
        - m["alias_bytes"])
    # ranked advisories carry the executable label
    for a in audit["advisories"]:
        assert "executable" in a and "kind" in a and "share" in a
    profiling.reset()


def test_memory_audit_same_label_merge_sums_bytes_peak_is_max():
    import jax
    from mxnet_tpu import profiling
    from mxnet_tpu.profiling import store
    profiling.reset()
    f1, x1 = jax.jit(lambda a: a * 2.0), jnp.ones((128, 128))
    f2, x2 = jax.jit(lambda a: a + 1.0), jnp.ones((32, 32))
    f1(x1), f2(x2)
    store.register(("merge", 1), "toy:merge", f1, (x1,))
    store.register(("merge", 2), "toy:merge", f2, (x2,))
    p1 = memory.executable_memory(f1.lower(x1).compile())
    p2 = memory.executable_memory(f2.lower(x2).compile())
    m = memory.memory_audit()["executables"]["toy:merge"]["metrics"]
    # two programs under one label: byte totals SUM, peak takes MAX --
    # distinct dispatches' live sets never coexist
    assert m["argument_bytes"] == \
        p1["argument_bytes"] + p2["argument_bytes"]
    assert m["peak_hbm_bytes"] == \
        max(p1["peak_hbm_bytes"], p2["peak_hbm_bytes"])
    profiling.reset()


def test_memory_baseline_round_trip(tmp_path):
    from mxnet_tpu import profiling
    profiling.reset()
    _register_toy("toy:memrt",
                  lambda a, b: (a @ b).sum(axis=0),
                  jnp.ones((64, 64), jnp.float32),
                  jnp.ones((64, 64), jnp.float32))
    base_path = str(tmp_path / "memory_baseline.json")
    base = memory.save_audit(base_path)
    assert memory.load_audit(base_path)["schema"] == memory.AUDIT_SCHEMA

    # self-diff: zero drift, CLI exit 0
    assert memory.diff_audit(base, base) == []
    assert an.main(["--memory-diff", base_path, base_path]) == 0

    # seeded regression: peak HBM +50%
    cur = json.loads(json.dumps(base))
    row = cur["executables"]["toy:memrt"]["metrics"]
    row["peak_hbm_bytes"] = int(row["peak_hbm_bytes"] * 1.5)
    cur_path = str(tmp_path / "current.json")
    with open(cur_path, "w") as f:
        json.dump(cur, f)
    diags = memory.diff_audit(base, memory.load_audit(cur_path))
    assert sorted({d.rule for d in diags}) == ["memory-drift"]
    assert "peak HBM grew" in diags[0].message
    assert "+50.0%" in diags[0].message
    assert an.main(["--memory-diff", base_path, cur_path]) == 1

    # an executable the baseline never blessed is a drift error
    new = json.loads(json.dumps(base))
    new["executables"]["toy:unblessed"] = \
        json.loads(json.dumps(base["executables"]["toy:memrt"]))
    diags = memory.diff_audit(base, new)
    assert len(diags) == 1 and "unblessed executable" in diags[0].message

    # an advisory kind the baseline doesn't carry is a drift error
    adv = json.loads(json.dumps(base))
    adv["executables"]["toy:memrt"]["advisories"].append(
        {"kind": "temp-share", "share": 0.9, "dominant_category": None,
         "message": "seeded"})
    diags = memory.diff_audit(base, adv)
    assert len(diags) == 1 and "temp-share" in diags[0].message

    # shrinkage passes silently
    better = json.loads(json.dumps(base))
    brow = better["executables"]["toy:memrt"]["metrics"]
    brow["peak_hbm_bytes"] = int(brow["peak_hbm_bytes"] * 0.5)
    better["executables"]["toy:memrt"]["advisories"] = []
    assert memory.diff_audit(base, better) == []
    profiling.reset()


def test_memory_audit_schema_reject(tmp_path):
    p = tmp_path / "bogus.json"
    p.write_text(json.dumps({"schema": "nope", "executables": {}}))
    with pytest.raises(ValueError, match="mxmemory.audit.v1"):
        memory.load_audit(str(p))
    assert an.main(["--memory-diff", str(p), str(p)]) == 2


def test_memory_diff_tolerance_env(monkeypatch):
    base = {"executables": {"e": {"metrics": {"peak_hbm_bytes": 1000},
                                  "advisories": []}}}
    cur = {"executables": {"e": {"metrics": {"peak_hbm_bytes": 1300},
                                 "advisories": []}}}
    assert memory.diff_audit(base, cur, tol=0.5) == []
    assert len(memory.diff_audit(base, cur, tol=0.02)) == 1
    monkeypatch.setenv("MXNET_TPU_MEMORY_AUDIT_TOL", "0.5")
    assert memory.diff_audit(base, cur) == []


def test_committed_memory_baseline_is_loadable():
    base = memory.load_audit(
        os.path.join(REPO, "ci", "memory_baseline.json"))
    labels = set(base["executables"])
    assert "train_step:MemLeNet" in labels
    for row in base["executables"].values():
        assert "peak_hbm_bytes" in row["metrics"]


# ----------------------------------------------------------------------
# hbm_plan: extrapolation anchored on two real compiles
# ----------------------------------------------------------------------

def test_hbm_plan_extrapolation_matches_real_compiles():
    import jax

    def f(w, x):
        return jnp.tanh(x @ w).sum()

    jfn = jax.jit(f)
    w = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    x8 = jax.ShapeDtypeStruct((8, 64), jnp.float32)
    plan = memory.hbm_plan("probe:tanhmm", buckets=(8, 16, 32),
                           batch_size=8, fn=jfn, args=(w, x8),
                           device_hbm_bytes=1 << 30)
    measured = {}
    for b in (8, 16, 32):
        xb = jax.ShapeDtypeStruct((b, 64), jnp.float32)
        measured[b] = memory.executable_memory(
            jfn.lower(w, xb).compile())["peak_hbm_bytes"]
    # the two anchor buckets ARE real compiles: prediction is exact
    assert plan["measured"] == {"8": measured[8], "16": measured[16]}
    pred = {r["batch"]: r["predicted_peak_hbm_bytes"]
            for r in plan["buckets"]}
    assert abs(pred[8] - measured[8]) <= 1
    assert abs(pred[16] - measured[16]) <= 1
    # the extrapolated bucket tracks the actual compile
    assert abs(pred[32] - measured[32]) <= max(0.25 * measured[32], 64)
    assert plan["per_item_bytes"] > 0
    assert all(r["fits"] for r in plan["buckets"])
    assert plan["largest_fit_bucket"] == 32
    # a budget below the smallest bucket fits nothing
    tight = memory.hbm_plan("probe:tanhmm", buckets=(8, 16),
                            batch_size=8, fn=jfn, args=(w, x8),
                            device_hbm_bytes=1)
    assert tight["largest_fit_bucket"] is None
    assert not any(r["fits"] for r in tight["buckets"])


def test_hbm_plan_errors():
    import jax
    from mxnet_tpu import profiling
    profiling.reset()
    with pytest.raises(ValueError, match="no registered executable"):
        memory.hbm_plan("nope:missing")
    jfn = jax.jit(lambda w: w * 2.0)
    w = jax.ShapeDtypeStruct((16, 16), jnp.float32)
    with pytest.raises(ValueError, match="batch dim"):
        memory.hbm_plan("probe:nobatch", batch_size=8, fn=jfn,
                        args=(w,))


# ----------------------------------------------------------------------
# mxprof drive-by: peak HBM merges as MAX, never as a sum
# ----------------------------------------------------------------------

def test_mxprof_merge_peak_is_max(tmp_path):
    from mxnet_tpu.profiling import cli as pcli

    def _combined(peak):
        return {"schema": pcli.COMBINED_SCHEMA, "steps": {},
                "executables": [],
                "totals": {"flops": 1.0, "bytes_accessed": 10.0,
                           "peak_hbm_bytes": peak},
                "categories": {}}

    p1, p2 = str(tmp_path / "a.json"), str(tmp_path / "b.json")
    with open(p1, "w") as f:
        json.dump(_combined(300), f)
    with open(p2, "w") as f:
        json.dump(_combined(100), f)
    merged = pcli._collect([p1, p2], None)
    assert merged["totals"]["peak_hbm_bytes"] == 300   # max, not 400
    assert merged["totals"]["flops"] == 2.0            # flops DO add
    text = "\n".join(pcli._render_report(merged)) \
        if isinstance(pcli._render_report(merged), list) \
        else pcli._render_report(merged)
    assert "peak HBM 300 B" in text
    assert "max over executables" in text


# ----------------------------------------------------------------------
# runtime layer: census, sentinel, chaos, statusz
# ----------------------------------------------------------------------

def test_live_census_buckets_known_array():
    memory.reset_watch()
    marker = jnp.ones((977, 3), jnp.float32)
    census = memory.live_census()
    key = "(977, 3)/float32"
    assert key in census["buckets"]
    bucket = census["buckets"][key]
    assert bucket["count"] >= 1
    assert bucket["bytes"] >= 977 * 3 * 4
    assert census["bytes_total"] >= bucket["bytes"]
    assert census["arrays"] >= bucket["count"]
    assert memory._STATE["censuses"] == 1
    assert memory._STATE["live_bytes"] == census["bytes_total"]
    del marker


def test_watch_disarmed_is_one_flag_check():
    memory._set_watch(False)
    memory.reset_watch()
    assert memory.watch_enabled() is False
    # the trainer's hot-path pattern: the guard is a single module-flag
    # read, so the sentinel is never constructed and no census runs
    if memory.watch_enabled():
        memory.sentinel().step()
    assert memory._SENTINEL is None
    assert memory._STATE["censuses"] == 0
    row = memory.status_row()
    assert row["armed"] is False and row["censuses"] == 0


@pytest.fixture
def _clean_chaos():
    chaos.reset()
    yield
    chaos.disarm()
    chaos.reset()


def test_leak_sentinel_flags_chaos_pins_within_three_windows(
        _clean_chaos):
    memory._set_watch(True)
    memory.reset_watch()
    s = memory.sentinel(window_steps=1, min_baseline=3,
                        min_growth_frac=0.01)
    chaos.on("memory.leak", memory.pin_action)
    # warm the baseline on clean windows (chaos still disarmed)
    for i in range(4):
        chaos.fail_point("memory.leak", step=i)
        s.step()
    assert memory.pinned_count() == 0
    assert s.baseline()["n"] == 4
    assert memory._STATE["leaks"] == 0
    # pin size scaled to the ambient live set so the MAD threshold is
    # crossed regardless of what earlier tests left alive
    nbytes = int(memory._STATE["live_bytes"] * 0.3) + (16 << 20)
    chaos.arm(seed=0)
    flagged_at = None
    for i in range(6):
        chaos.fail_point("memory.leak", step=i, nbytes=nbytes)
        s.step()
        if memory._STATE["leaks"]:
            flagged_at = i
            break
    assert memory.pinned_count() >= 1
    assert flagged_at is not None and flagged_at < 3, \
        "chaos-pinned growth not flagged within 3 windows"
    leak = memory._STATE["last_leak"]
    # the report NAMES the pinned shape bucket
    assert leak["bucket"] == \
        "(%d,)/float32" % max(1, nbytes // 4)
    assert leak["growth_bytes"] > 0
    assert leak["live_bytes"] > leak["baseline_bytes"]
    assert s.last()["leak"] is not None
    assert memory.status_row()["leaks"] == 1
    assert memory.unpin_all() >= 1


def test_leak_sentinel_clean_run_never_flags(_clean_chaos):
    memory._set_watch(True)
    memory.reset_watch()
    s = memory.sentinel(window_steps=1, min_baseline=3,
                        min_growth_frac=0.01)
    for i in range(10):
        s.step()
    assert memory._STATE["leaks"] == 0
    assert memory._STATE["censuses"] == 10
    assert s.last()["leak"] is None


def test_leak_sentinel_publish_guard_skips_judge_and_baseline():
    memory._set_watch(True)
    memory.reset_watch()
    s = memory.LeakSentinel(window_steps=1, min_baseline=1,
                            min_growth_frac=0.01)
    for _ in range(3):
        s.step()
    n0 = s.baseline()["n"]
    # a checkpoint-sized spike inside a publish-guarded window: the
    # window neither flags nor teaches the baseline
    memory.pin_action({"nbytes": int(
        memory._STATE["live_bytes"] * 0.5) + (32 << 20)})
    s.note_publish()
    s.step()
    report = s.last()
    assert report["publishes"] == 1
    assert report["leak"] is None
    assert s.baseline()["n"] == n0
    assert memory._STATE["leaks"] == 0
    memory.unpin_all()


def test_trainer_wiring_is_guarded():
    import inspect
    from mxnet_tpu.serving import loop
    run_src = inspect.getsource(loop.ContinuousTrainer.run_steps)
    assert '_chaos.fail_point("memory.leak"' in run_src
    assert "_memory.watch_enabled()" in run_src
    assert "_memory.sentinel().step()" in run_src
    assert "note_publish" in \
        inspect.getsource(loop.ContinuousTrainer.publish)
    assert "sentinel().flush()" in \
        inspect.getsource(loop.ContinuousTrainer.close)


# ----------------------------------------------------------------------
# surfaces: statusz, runtime features, env vars, telemetry, SARIF
# ----------------------------------------------------------------------

def test_statusz_carries_memory_row():
    from mxnet_tpu.obs import status
    row = status.statusz()["memory"]
    assert set(row) == {"armed", "censuses", "live_bytes",
                        "live_arrays", "leaks", "last_leak", "pinned"}
    assert row["armed"] == memory.watch_enabled()


def test_runtime_features_memory_watch_row(monkeypatch):
    from mxnet_tpu import runtime
    monkeypatch.setenv("MXNET_TPU_MEMORY_WATCH", "1")
    assert runtime.Features().is_enabled("MEMORY_WATCH")
    monkeypatch.delenv("MXNET_TPU_MEMORY_WATCH")
    assert not runtime.Features().is_enabled("MEMORY_WATCH")


def test_memory_env_vars_registered():
    from mxnet_tpu import env
    desc = env.describe()
    assert "MXNET_TPU_MEMORY_WATCH" in desc
    assert "MXNET_TPU_MEMORY_AUDIT_TOL" in desc
    _val, default, _doc = desc["MXNET_TPU_MEMORY_AUDIT_TOL"]
    assert default == 0.02


def test_memory_telemetry_instruments_catalogued():
    from mxnet_tpu.telemetry import hooks
    rows = {i.name: i for i in hooks.INSTRUMENTS}
    assert rows["memory.censuses"].kind == "counter"
    assert rows["memory.live_bytes"].kind == "gauge"
    assert rows["memory.live_arrays"].kind == "gauge"
    assert rows["memory.leaks"].kind == "counter"
    assert rows["memory.leak"].kind == "event"


def test_memory_rules_sarif_export(tmp_path):
    src = (
        "import collections\n"
        "_CACHE = {}\n"
        "class Feeder:\n"
        "    def __init__(self):\n"
        "        self.feed_q = collections.deque()\n"
        "def compiled_for(x, build):\n"
        "    key = (x.shape, str(x.dtype))\n"
        "    _CACHE[key] = build(x)\n"
        "    return _CACHE[key]\n"
    )
    diags = _lint(src)
    fired = set(_mem(diags))
    assert fired == {"unbounded-shape-cache", "feed-depth-unbounded"}
    log = an.to_sarif(diags)
    results = log["runs"][0]["results"]
    assert fired <= {r["ruleId"] for r in results}
    # rule metadata covers the new rules
    rule_ids = {m["id"] for m in log["runs"][0]["tool"]["driver"]["rules"]}
    assert fired <= rule_ids
    out = str(tmp_path / "memory.sarif")
    assert an.write_sarif(out, diags) == log
    with open(out) as f:
        assert json.load(f) == log
