"""KVStore tests (reference: ``tests/python/unittest/test_kvstore.py``)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def test_init_push_pull_aggregation():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones((2, 3)))
    # push without optimizer accumulates; pull drains
    kv.push(3, [mx.nd.ones((2, 3)), mx.nd.ones((2, 3)) * 2])
    out = mx.nd.zeros((2, 3))
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((2, 3), 3.0))
    # after drain, pull returns the stored value
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), np.ones((2, 3)))


def test_pushpull_allreduce_semantics():
    kv = mx.kv.create("device")
    kv.init("g", mx.nd.zeros((4,)))
    out = mx.nd.zeros((4,))
    kv.pushpull("g", [mx.nd.ones((4,)), mx.nd.ones((4,))], out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(4, 2.0))


def test_optimizer_on_store():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((3,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.0))
    kv.push("w", mx.nd.ones((3,)))
    out = mx.nd.zeros((3,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full(3, 0.9), rtol=1e-6)


def test_gradient_compression_error_feedback():
    """2-bit compression quantizes pushes to {-t, 0, +t} and carries the
    residual (reference: ``gradient_compression.cc``)."""
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((4,)))
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    g = mx.nd.array(np.array([0.3, 0.7, -0.9, 0.0], np.float32))
    out = mx.nd.zeros((4,))
    kv.pushpull("w", g, out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.0, 0.5, -0.5, 0.0])
    # second identical push: residual (0.3, 0.2, -0.4, 0) + g crosses
    # the threshold for the first element now
    kv.pushpull("w", g, out=out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.5, -0.5, 0.0])

    with pytest.raises(mx.MXNetError):
        kv.set_gradient_compression({"type": "1bit"})


def test_optimizer_state_save_load(tmp_path):
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones((3,)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    for _ in range(3):
        kv.push("w", mx.nd.ones((3,)))
    fname = str(tmp_path / "kv.states")
    kv.save_optimizer_states(fname)

    kv2 = mx.kv.create("local")
    kv2.init("w", mx.nd.ones((3,)))
    kv2.set_optimizer(mx.optimizer.SGD(learning_rate=0.1, momentum=0.9))
    kv2.load_optimizer_states(fname)
    s1 = kv._updater.states["w"]
    s2 = kv2._updater.states["w"]
    np.testing.assert_allclose(s1.asnumpy(), s2.asnumpy())


def test_uninitialized_key_raises():
    kv = mx.kv.create("local")
    with pytest.raises(mx.MXNetError):
        kv.push("nope", mx.nd.ones((2,)))
    with pytest.raises(mx.MXNetError):
        kv.pull("nope", out=mx.nd.zeros((2,)))


def test_rank_and_type():
    kv = mx.kv.create("local")
    assert kv.rank == 0 and kv.num_workers == 1
    with pytest.raises(mx.MXNetError):
        mx.kv.create("bogus_type")
