#!/usr/bin/env python
"""im2rec: build .lst / .rec image datasets (reference:
``tools/im2rec.py``).

Two phases, same CLI shape as the reference:

1. ``--list``: walk an image directory, assign integer labels per
   subdirectory, write ``prefix.lst`` ("index\\tlabel\\trelpath"), with
   optional train/val split and shuffling.
2. default: read ``prefix.lst`` and pack each image into
   ``prefix.rec`` + ``prefix.idx`` via the recordio engine (native C++
   fast path when available), resizing/re-encoding on the fly.
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

from mxnet_tpu import recordio  # noqa: E402

_EXTS = (".jpg", ".jpeg", ".png", ".bmp")


def list_images(root):
    cat = {}
    out = []
    for path, _dirs, files in sorted(os.walk(root, followlinks=True)):
        for name in sorted(files):
            if os.path.splitext(name)[1].lower() not in _EXTS:
                continue
            label_dir = os.path.relpath(path, root).split(os.sep)[0]
            if label_dir not in cat:
                cat[label_dir] = len(cat)
            out.append((os.path.relpath(os.path.join(path, name), root),
                        cat[label_dir]))
    return out, cat


def write_lst(fname, items):
    with open(fname, "w") as f:
        for i, (rel, label) in enumerate(items):
            f.write("%d\t%f\t%s\n" % (i, float(label), rel))


def read_lst(fname):
    with open(fname) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) >= 3:
                yield int(parts[0]), float(parts[1]), parts[2]


def make_lists(args):
    items, cat = list_images(args.root)
    if args.shuffle:
        random.seed(100)
        random.shuffle(items)
    n_val = int(len(items) * args.test_ratio)
    if n_val:
        write_lst(args.prefix + "_val.lst", items[:n_val])
        write_lst(args.prefix + "_train.lst", items[n_val:])
    else:
        write_lst(args.prefix + ".lst", items)
    print("categories:", {v: k for k, v in cat.items()})


def _load_and_encode(path, args):
    from PIL import Image
    img = Image.open(path)
    img = img.convert("L" if args.color == 0 else "RGB")
    if args.resize:
        w, h = img.size
        scale = args.resize / min(w, h)
        img = img.resize((max(1, int(w * scale)), max(1, int(h * scale))))
    if args.center_crop:
        w, h = img.size
        s = min(w, h)
        left, top = (w - s) // 2, (h - s) // 2
        img = img.crop((left, top, left + s, top + s))
    import io as _io
    if args.encoding == ".raw":
        # raw decoded payload (HWC uint8): trades file size for decode
        # throughput -- the fast path for codec-bound hosts (ImageIter
        # detects it by payload length)
        import numpy as _np
        return _np.asarray(img, dtype=_np.uint8).tobytes()
    buf = _io.BytesIO()
    if args.encoding in (".jpg", ".jpeg"):
        img.save(buf, "JPEG", quality=args.quality)
    else:
        img.save(buf, "PNG")
    return buf.getvalue()


def make_record(args, lst_file):
    prefix = os.path.splitext(lst_file)[0]
    rec = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    count = 0
    for idx, label, rel in read_lst(lst_file):
        path = os.path.join(args.root, rel)
        try:
            payload = _load_and_encode(path, args)
        except Exception as e:
            print("skip %s: %s" % (rel, e), file=sys.stderr)
            continue
        header = recordio.IRHeader(0, label, idx, 0)
        rec.write_idx(idx, recordio.pack(header, payload))
        count += 1
        if count % 1000 == 0:
            print("packed %d images" % count)
    rec.close()
    print("wrote %s.rec (%d records)" % (prefix, count))


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("prefix", help="output prefix (or .lst path to pack)")
    p.add_argument("root", help="image directory root")
    p.add_argument("--list", action="store_true",
                   help="create .lst instead of packing .rec")
    p.add_argument("--shuffle", type=int, default=1)
    p.add_argument("--test-ratio", type=float, default=0.0)
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--center-crop", action="store_true")
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--encoding", default=".jpg",
                   help=".jpg / .png / .raw (raw = pre-decoded uint8)")
    p.add_argument("--color", type=int, default=1, choices=[0, 1])
    args = p.parse_args(argv)
    if args.list:
        make_lists(args)
    else:
        if args.prefix.endswith(".lst"):
            lsts = [args.prefix]
        else:
            # a --test-ratio split produces prefix_train/_val.lst; pack
            # exactly this tool's own outputs, never sibling datasets
            lsts = [f for f in (args.prefix + ".lst",
                                args.prefix + "_train.lst",
                                args.prefix + "_val.lst")
                    if os.path.exists(f)]
        if not lsts:
            p.error("no .lst file found for prefix %r" % args.prefix)
        for lst in lsts:
            make_record(args, lst)


if __name__ == "__main__":
    main()
