#!/usr/bin/env python
"""Parse training logs into a metric table (reference:
``tools/parse_log.py``): extracts epoch, train/validation metrics, and
Speedometer samples/sec from the logging format ``callback.py`` emits.

    python tools/parse_log.py train.log
    python tools/parse_log.py train.log --format json
"""
from __future__ import annotations

import argparse
import json
import re
import sys

_EPOCH = re.compile(
    r"Epoch\[(\d+)\]\s+(Train|Validation)-([\w\-]+)=([\d.eE+-]+)")
_SPEED = re.compile(
    r"Epoch\[(\d+)\].*Speed:\s*([\d.]+)\s*samples/sec")
_TIME = re.compile(r"Epoch\[(\d+)\]\s+Time cost=([\d.]+)")


def parse(lines):
    epochs = {}
    for line in lines:
        m = _EPOCH.search(line)
        if m:
            e = int(m.group(1))
            key = "%s-%s" % (m.group(2).lower(), m.group(3))
            epochs.setdefault(e, {})[key] = float(m.group(4))
            continue
        m = _SPEED.search(line)
        if m:
            e = int(m.group(1))
            d = epochs.setdefault(e, {})
            d.setdefault("_speeds", []).append(float(m.group(2)))
            continue
        m = _TIME.search(line)
        if m:
            epochs.setdefault(int(m.group(1)), {})["time_s"] = \
                float(m.group(2))
    for d in epochs.values():
        speeds = d.pop("_speeds", None)
        if speeds:
            d["samples_per_sec"] = sum(speeds) / len(speeds)
    return epochs


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("logfile")
    p.add_argument("--format", choices=("table", "json"),
                   default="table")
    args = p.parse_args(argv)
    with open(args.logfile) as f:
        epochs = parse(f)
    if args.format == "json":
        print(json.dumps(epochs, indent=2, sort_keys=True))
        return
    keys = sorted({k for d in epochs.values() for k in d})
    print("\t".join(["epoch"] + keys))
    for e in sorted(epochs):
        row = [str(e)] + ["%.6g" % epochs[e][k] if k in epochs[e]
                          else "-" for k in keys]
        print("\t".join(row))


if __name__ == "__main__":
    main()
