#!/usr/bin/env python
"""KVStore push/pull bandwidth measurement (reference:
``tools/bandwidth/measure.py``).

Measures the aggregate bytes/s of pushpull rounds over the configured
kvstore type -- single-process this exercises device<->host and the
reduce path; launched under ``tools/launch.py`` with ``dist_sync`` it
measures the cross-process (coordination service / collective) path.

    python tools/bandwidth.py --size-mb 64 --rounds 10
    python tools/launch.py -n 2 python tools/bandwidth.py --kv dist_sync
"""
from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np                  # noqa: E402


def main():
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--kv", default="device")
    p.add_argument("--size-mb", type=float, default=16.0)
    p.add_argument("--rounds", type=int, default=10)
    args = p.parse_args()

    import mxnet_tpu as mx
    mx.distributed_init()
    kv = mx.kv.create(args.kv)
    n = int(args.size_mb * (1 << 20) / 4)
    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    grad = mx.nd.ones((n,), ctx=ctx)
    out = mx.nd.zeros((n,), ctx=ctx)
    kv.init("x", mx.nd.zeros((n,), ctx=ctx))

    kv.pushpull("x", grad, out=out)       # warmup
    mx.nd.waitall()
    t0 = time.perf_counter()
    for _ in range(args.rounds):
        kv.pushpull("x", grad, out=out)
    mx.nd.waitall()
    dt = time.perf_counter() - t0
    gb = args.size_mb / 1024 * args.rounds * 2   # push + pull
    print("rank %d: %.2f GB moved in %.3fs -> %.2f GB/s"
          % (kv.rank, gb, dt, gb / dt))


if __name__ == "__main__":
    main()
