#!/usr/bin/env python
"""Thin launcher for the static-analysis CLI (``mxnet_tpu.analysis``),
for trees where the ``mxlint`` console script is not installed (CI
containers running from a source checkout).  Same flags, same exit
codes: ``python tools/mxlint.py --self --json``."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from mxnet_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
