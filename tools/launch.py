#!/usr/bin/env python
"""Distributed launcher (reference: ``tools/launch.py`` over dmlc-core
trackers).

TPU-native redesign: there are no parameter-server/scheduler roles --
every worker is a ``jax.distributed`` process and gradient reduction is
an XLA collective over ICI/DCN (see ``mxnet_tpu/kvstore.py``).  The
launcher therefore only has to start N identical processes with the
coordinator's address and each process's index:

  local mode:   ``launch.py -n 4 python train.py``      (one host)
  ssh mode:     ``launch.py -n 8 -H hostfile python train.py``
  supervised:   ``launch.py -n 4 --supervise python train.py``

Each worker gets MXNET_TPU_COORDINATOR / MXNET_TPU_NUM_PROCS /
MXNET_TPU_PROC_ID; ``mxnet_tpu.distributed_init()`` (or user code) maps
them onto ``jax.distributed.initialize``.

``--supervise`` (local mode) routes through the elastic restart
supervisor (``mxnet_tpu.supervisor``): a rank death tears the world
down (survivors get their typed BarrierTimeout within ``--grace``),
the generation id is bumped (MXNET_TPU_GENERATION -- workers resume
via ``ContinuousTrainer.resume()``), and the world relaunches under a
bounded ``--max-restarts`` budget.
"""
from __future__ import annotations

import argparse
import os
import shlex
import socket
import subprocess
import sys
import threading

_print_lock = threading.Lock()


def _relay(pipe, prefix):
    """Line-buffered prefixed relay (the dmlc tracker behavior): each
    worker line becomes ONE atomic write under a lock, so two workers'
    output can never interleave mid-line."""
    out = sys.stdout.buffer
    with pipe:
        for line in iter(pipe.readline, b""):
            if not line.endswith(b"\n"):
                line += b"\n"
            with _print_lock:
                out.write(prefix + line)
                out.flush()


def _spawn_relayed(cmd, env, rank):
    p = subprocess.Popen(cmd, env=env, start_new_session=True,
                         stdout=subprocess.PIPE,
                         stderr=subprocess.STDOUT)
    t = threading.Thread(target=_relay,
                         args=(p.stdout, b"[%d] " % rank), daemon=True)
    t.start()
    p._relay_thread = t
    return p


def _free_port():
    s = socket.socket()
    s.bind(("", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _kill_tree(procs):
    """SIGTERM each worker's whole process group (workers start in
    their own session, so wrapper scripts' grandchildren die too),
    escalating to SIGKILL after a grace period."""
    import signal
    import time
    for q in procs:
        try:
            os.killpg(q.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            q.terminate()
    deadline = time.time() + 10
    for q in procs:
        try:
            q.wait(timeout=max(0.0, deadline - time.time()))
        except subprocess.TimeoutExpired:
            pass
        if q.poll() is None:
            try:
                os.killpg(q.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                q.kill()
            q.wait()


def _wait_all(procs):
    """Wait for every worker, failing FAST: the first nonzero exit
    tears down the survivors (a dead peer would otherwise wedge the
    rest inside jax.distributed collectives); Ctrl-C tears all down."""
    import time
    try:
        while procs:
            for p in list(procs):
                rc = p.poll()
                if rc is None:
                    continue
                procs.remove(p)
                t = getattr(p, "_relay_thread", None)
                if t is not None:
                    t.join(timeout=10)
                if rc != 0:
                    _kill_tree(procs)
                    return rc
            # fail-FAST over N children needs a poll round-robin: a
            # blocking wait on any single child would hide a sibling's
            # death behind it (os.wait reaps relay threads' pipes too)
            time.sleep(0.1)  # mxlint: disable=sleep-poll
        return 0
    except KeyboardInterrupt:
        _kill_tree(procs)
        raise


def launch_local(args, command):
    coord = "127.0.0.1:%d" % _free_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "MXNET_TPU_COORDINATOR": coord,
            "MXNET_TPU_NUM_PROCS": str(args.num_workers),
            "MXNET_TPU_PROC_ID": str(rank),
            # legacy names some scripts read
            "DMLC_ROLE": "worker",
            "DMLC_NUM_WORKER": str(args.num_workers),
        })
        procs.append(_spawn_relayed(command, env, rank))
    return _wait_all(procs)


def launch_ssh(args, command):
    with open(args.hostfile) as f:
        hosts = [h.strip() for h in f if h.strip()
                 and not h.startswith("#")]
    if len(hosts) < args.num_workers:
        # round-robin workers over hosts
        hosts = [hosts[i % len(hosts)] for i in range(args.num_workers)]
    # per-job coordinator port: a fixed port would collide across jobs
    # (or a restart racing its predecessor's TIME_WAIT socket)
    port = args.port or (40000 + os.getpid() % 20000)
    coord = "%s:%d" % (hosts[0].split(":")[0], port)
    procs = []
    cwd = os.getcwd()
    for rank in range(args.num_workers):
        host = hosts[rank].split(":")[0]
        envs = " ".join("%s=%s" % kv for kv in [
            ("MXNET_TPU_COORDINATOR", coord),
            ("MXNET_TPU_NUM_PROCS", str(args.num_workers)),
            ("MXNET_TPU_PROC_ID", str(rank)),
        ])
        remote = "cd %s && env %s %s" % (
            shlex.quote(cwd), envs, " ".join(map(shlex.quote, command)))
        procs.append(_spawn_relayed(
            ["ssh", "-o", "StrictHostKeyChecking=no", "-tt", host,
             remote], None, rank))
    # -tt allocates a tty so terminating the ssh client also kills the
    # remote command instead of orphaning it
    return _wait_all(procs)


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("-H", "--hostfile", default=None,
                   help="one host per line; omit for single-host local")
    p.add_argument("--port", type=int, default=0,
                   help="coordinator port for ssh mode (default: derived "
                        "per job)")
    p.add_argument("--supervise", action="store_true",
                   help="elastic restart supervision (local mode): on "
                        "any rank exit, tear down, bump the generation "
                        "id, and relaunch under --max-restarts")
    p.add_argument("--max-restarts", type=int, default=None,
                   help="restart budget for --supervise (default: "
                        "MXNET_TPU_SUPERVISOR_RESTARTS)")
    p.add_argument("--grace", type=float, default=None,
                   help="seconds survivors get to exit on their own "
                        "typed error before the tree is killed "
                        "(default: MXNET_TPU_SUPERVISOR_GRACE_S)")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")
    if args.supervise:
        if args.hostfile:
            p.error("--supervise is local-mode only (ssh worlds need "
                    "an external supervisor per host)")
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        from mxnet_tpu.supervisor import Supervisor
        return Supervisor(args.command, args.num_workers,
                          max_restarts=args.max_restarts,
                          grace_s=args.grace).run()
    if args.hostfile:
        return launch_ssh(args, args.command)
    return launch_local(args, args.command)


if __name__ == "__main__":
    sys.exit(main())
