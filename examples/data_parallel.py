#!/usr/bin/env python
"""Data-parallel training over a device mesh (reference:
``example/image-classification`` multi-GPU via kvstore; here the
TPU-native path: ONE compiled step with batch sharding + XLA-inserted
gradient reduction over ICI).

With one real chip this still runs (1-device mesh); to exercise real
sharding on CPU:

    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/data_parallel.py --ndev 8
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np                          # noqa: E402

import mxnet_tpu as mx                      # noqa: E402
from mxnet_tpu import gluon                 # noqa: E402
from mxnet_tpu.parallel import TrainStep, make_mesh  # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ndev", type=int, default=0,
                   help="devices in the dp mesh (0 = all available)")
    p.add_argument("--steps", type=int, default=30)
    p.add_argument("--batch-size", type=int, default=256)
    args = p.parse_args()

    import jax
    devices = jax.devices()
    n = args.ndev or len(devices)
    if len(devices) < n:
        devices = jax.devices("cpu")
        if len(devices) < n:
            sys.exit("need %d devices but only %d available; run with\n"
                     "  XLA_FLAGS=--xla_force_host_platform_device_count"
                     "=%d JAX_PLATFORMS=cpu" % (n, len(devices), n))
    mesh = make_mesh({"dp": n}, devices=devices[:n]) if n > 1 else None
    print("mesh:", mesh or "single device (%s)" % devices[0])

    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu",
                            layout="NCHW"),
            gluon.nn.BatchNorm(),
            gluon.nn.MaxPool2D(2, layout="NCHW"),
            gluon.nn.Flatten(),
            gluon.nn.Dense(64, activation="relu"),
            gluon.nn.Dense(10))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            kvstore=None)
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(), trainer,
                     mesh=mesh)

    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(args.batch_size, 3, 16, 16)
                    .astype(np.float32))
    y = mx.nd.array(rng.randint(0, 10, args.batch_size)
                    .astype(np.float32))

    loss0 = float(step(x, y).asscalar())
    tic = time.time()
    for _ in range(args.steps):
        loss = step(x, y)
    mx.nd.waitall()
    dt = (time.time() - tic) / args.steps
    print("loss %.4f -> %.4f | %.1f ms/step | %.0f img/s"
          % (loss0, float(loss.asscalar()), dt * 1e3,
             args.batch_size / dt))


if __name__ == "__main__":
    main()
