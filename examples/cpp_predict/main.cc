// C++ edge inference example (reference: amalgamation/ +
// example/image-classification/predict-cpp/): run a model exported with
// mx.onnx.export_model from pure C++ -- no Python anywhere.
//
// Build (after building the runtime library):
//   g++ -O2 -shared -fPIC -std=c++17 \
//       ../../mxnet_tpu/_native/predict_native.cc -o libmxtpu_predict.so
//   g++ -O2 -std=c++17 main.cc -o cpp_predict -L. -lmxtpu_predict \
//       -Wl,-rpath,'$ORIGIN'
// Run:
//   ./cpp_predict model.onnx N C H W [weights.params]
// With the optional .params argument, the parameter container is also
// loaded through the MXNDList* ABI and summarized -- the full
// model+weights artifact pair, no Python anywhere.
#include <cstdio>
#include <cstdlib>
#include <vector>

#include "../../mxnet_tpu/_native/mxnet_predict.h"

int main(int argc, char** argv) {
  if (argc < 6) {
    fprintf(stderr, "usage: %s model.onnx N C H W [weights.params]\n",
            argv[0]);
    return 2;
  }
  PredictorHandle h;
  if (MXPredCreateFromFile(argv[1], &h) != 0) {
    fprintf(stderr, "create failed: %s\n", MXPredGetLastError());
    return 1;
  }
  int64_t shape[4];
  for (int i = 0; i < 4; ++i) shape[i] = atoll(argv[2 + i]);
  int64_t numel = shape[0] * shape[1] * shape[2] * shape[3];
  std::vector<float> input(static_cast<size_t>(numel), 0.f);
  // deterministic pseudo-input so runs are comparable against Python
  unsigned s = 12345;
  for (auto& v : input) {
    s = s * 1664525u + 1013904223u;
    v = float(s >> 16) / 65536.0f - 0.5f;
  }
  if (MXPredSetInput(h, nullptr, input.data(), shape, 4) != 0 ||
      MXPredForward(h) != 0) {
    fprintf(stderr, "forward failed: %s\n", MXPredGetLastError());
    return 1;
  }
  int ndim;
  if (MXPredGetOutputShape(h, 0, nullptr, &ndim) != 0) {
    fprintf(stderr, "shape failed: %s\n", MXPredGetLastError());
    return 1;
  }
  std::vector<int64_t> oshape(static_cast<size_t>(ndim), 0);
  MXPredGetOutputShape(h, 0, oshape.data(), &ndim);
  int64_t on = 1;
  printf("output shape: (");
  for (int i = 0; i < ndim; ++i) {
    on *= oshape[size_t(i)];
    printf("%s%lld", i ? ", " : "", (long long)oshape[size_t(i)]);
  }
  printf(")\n");
  std::vector<float> out(static_cast<size_t>(on), 0.f);
  MXPredGetOutput(h, 0, out.data(), on);
  printf("first outputs:");
  for (int i = 0; i < (on < 8 ? int(on) : 8); ++i)
    printf(" %.6f", out[size_t(i)]);
  printf("\n");
  MXPredFree(h);

  if (argc > 6) {  // optional: read the .params container too
    NDListHandle nd;
    int64_t count;
    if (MXNDListCreateFromFile(argv[6], &nd, &count) != 0) {
      fprintf(stderr, "params load failed: %s\n", MXPredGetLastError());
      return 1;
    }
    printf("params: %lld arrays\n", (long long)count);
    for (int64_t i = 0; i < count && i < 4; ++i) {
      const char* key;
      const float* data;
      const int64_t* shp;
      int nd_rank;
      if (MXNDListGet(nd, i, &key, &data, &shp, &nd_rank) != 0) continue;
      int64_t pn = 1;
      for (int d = 0; d < nd_rank; ++d) pn *= shp[d];
      printf("  %s rank=%d first=%.6f\n", key, nd_rank,
             pn > 0 ? data[0] : 0.f);
    }
    MXNDListFree(nd);
  }
  return 0;
}
