#!/usr/bin/env python
"""Variable-length sequence training with BucketingModule (reference:
``example/rnn/lstm_bucketing.py``).

Buckets are static shape classes: each bucket gets its own jitted
executor compiled once, while every bucket shares one parameter set --
the TPU answer to ragged batches.

    python examples/rnn_bucketing.py --epochs 3
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np                          # noqa: E402

import mxnet_tpu as mx                      # noqa: E402
from mxnet_tpu import sym                   # noqa: E402

BUCKETS = (8, 16, 32)
VOCAB = 64


def sym_gen(seq_len):
    """Embedding -> mean-pool -> classifier per bucket (the graph shape
    is the bucket; weights are shared across buckets by name)."""
    data = sym.var("data")
    emb = sym.Embedding(data, input_dim=VOCAB, output_dim=32,
                        name="embed")
    pooled = sym.mean(emb, axis=1)
    fc1 = sym.FullyConnected(pooled, num_hidden=32, name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, num_hidden=2, name="fc2")
    net = sym.SoftmaxOutput(fc2, name="softmax")
    return net, ("data",), ("softmax_label",)


def make_batches(n_batches, batch_size, seed=0):
    """Synthetic task: label = whether token 0 appears in the sequence."""
    rng = np.random.RandomState(seed)
    batches = []
    for _ in range(n_batches):
        seq_len = BUCKETS[rng.randint(len(BUCKETS))]
        toks = rng.randint(1, VOCAB, size=(batch_size, seq_len))
        has_zero = rng.rand(batch_size) < 0.5
        for i in np.nonzero(has_zero)[0]:
            toks[i, rng.randint(seq_len)] = 0
        batch = mx.io.DataBatch(
            data=[mx.nd.array(toks.astype(np.float32))],
            label=[mx.nd.array(has_zero.astype(np.float32))],
            provide_data=[mx.io.DataDesc("data", toks.shape)],
            provide_label=[mx.io.DataDesc("softmax_label",
                                          (batch_size,))])
        batch.bucket_key = seq_len
        batches.append(batch)
    return batches


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32)
    args = p.parse_args()

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=max(BUCKETS),
                                 context=ctx)
    mod.bind(data_shapes=[("data", (args.batch_size, max(BUCKETS)))],
             label_shapes=[("softmax_label", (args.batch_size,))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})

    metric = mx.metric.Accuracy()
    batches = make_batches(30, args.batch_size)
    for epoch in range(args.epochs):
        metric.reset()
        for batch in batches:
            mod.forward(batch, is_train=True)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        print("epoch %d: %s=%.4f (buckets compiled: %s)"
              % (epoch, *metric.get(), mod.bucket_keys))


if __name__ == "__main__":
    main()
