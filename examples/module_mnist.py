#!/usr/bin/env python
"""Train an MLP with the legacy Module API (reference:
``example/image-classification/train_mnist.py``): symbolic graph,
``mod.fit`` with Speedometer and checkpointing.

    python examples/module_mnist.py --epochs 2
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import numpy as np                          # noqa: E402

import mxnet_tpu as mx                      # noqa: E402
from mxnet_tpu import sym                   # noqa: E402


def mlp_symbol():
    data = sym.var("data")
    net = sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=10, name="fc3")
    return sym.SoftmaxOutput(net, name="softmax")


def synthetic_mnist(n=2048, seed=0):
    # linearly separable synthetic digits: one fixed blob per class
    centers = np.random.RandomState(42).randn(10, 784).astype(np.float32)
    rng = np.random.RandomState(seed)
    y = rng.randint(0, 10, n)
    x = centers[y] + 0.3 * rng.randn(n, 784).astype(np.float32)
    return x, y.astype(np.float32)


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--save-prefix", default="/tmp/mnist_module")
    args = p.parse_args()

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    x, y = synthetic_mnist()
    train = mx.io.NDArrayIter(x, y, args.batch_size, shuffle=True)
    val = mx.io.NDArrayIter(*synthetic_mnist(512, seed=1),
                            batch_size=args.batch_size)

    mod = mx.mod.Module(mlp_symbol(), context=ctx)
    mod.fit(train, eval_data=val,
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(args.batch_size,
                                                       10),
            epoch_end_callback=mx.callback.do_checkpoint(
                args.save_prefix),
            num_epoch=args.epochs)
    score = mod.score(val, mx.metric.Accuracy())
    print("final validation:", score)


if __name__ == "__main__":
    import logging
    logging.basicConfig(level=logging.INFO)
    main()
