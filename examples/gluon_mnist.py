#!/usr/bin/env python
"""Train a convnet on MNIST with the Gluon API (reference:
``example/gluon/mnist/mnist.py``).

Runs on the TPU when one is attached, else CPU; uses the synthetic
MNIST fallback when the dataset cannot be downloaded (offline image).

    python examples/gluon_mnist.py --epochs 2 --hybridize
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

import mxnet_tpu as mx                      # noqa: E402
from mxnet_tpu import autograd, gluon       # noqa: E402


def build_net(layout="NCHW"):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(32, kernel_size=3, activation="relu",
                            layout=layout),
            gluon.nn.Conv2D(64, kernel_size=3, activation="relu",
                            layout=layout),
            gluon.nn.MaxPool2D(2, layout=layout),
            gluon.nn.Flatten(),
            gluon.nn.Dense(128, activation="relu"),
            gluon.nn.Dropout(0.5),
            gluon.nn.Dense(10))
    return net


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=128)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--hybridize", action="store_true")
    p.add_argument("--max-batches", type=int, default=0,
                   help="cap batches per epoch (0 = full epoch)")
    args = p.parse_args()

    ctx = mx.tpu() if mx.num_tpus() else mx.cpu()
    print("training on", ctx)

    train_data = gluon.data.DataLoader(
        gluon.data.vision.MNIST(train=True).transform_first(
            lambda d: mx.nd.array(
                d.asnumpy().reshape(1, 28, 28) / 255.0)),
        batch_size=args.batch_size, shuffle=True, last_batch="discard")

    net = build_net()
    net.initialize(mx.init.Xavier(), ctx=ctx)
    if args.hybridize:
        net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        tic = time.time()
        n = 0
        nb = 0
        for data, label in train_data:
            nb += 1
            if args.max_batches and nb > args.max_batches:
                break
            data = data.as_in_context(ctx)
            label = label.as_in_context(ctx)
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(args.batch_size)
            metric.update([label], [out])
            n += args.batch_size
        name, acc = metric.get()
        print("epoch %d: %s=%.4f (%.0f samples/s)"
              % (epoch, name, acc, n / (time.time() - tic)))


if __name__ == "__main__":
    main()
