#!/usr/bin/env python
"""Multi-process data-parallel training with a dist_sync kvstore
(reference: ``example/image-classification/train_mnist.py`` run under
``tools/launch.py`` with ``--kv-store dist_sync``).

Each worker trains on its own shard of the data; gradients allreduce
across processes through the kvstore before every update, and rank 0's
initial weights are broadcast so all ranks train the same model.

Run (2 workers on one host):

    python tools/launch.py -n 2 python examples/dist_sync_train.py

Workers print per-epoch loss; after training every rank holds
byte-identical parameters (asserted).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))

if os.environ.get("JAX_PLATFORMS") == "cpu":
    # honor an explicit CPU request even where a TPU plugin's
    # sitecustomize pre-imported jax (the env var alone is ignored then)
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np                          # noqa: E402

import mxnet_tpu as mx                      # noqa: E402
from mxnet_tpu import autograd, gluon       # noqa: E402


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=3)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--lr", type=float, default=0.05)
    p.add_argument("--samples", type=int, default=256)
    args = p.parse_args()

    mx.distributed_init()
    kv = mx.kv.create("dist_sync")
    rank, nworker = kv.rank, kv.num_workers

    # synthetic regression task; the DATA is sharded by rank
    # (num_parts/part_index semantics), the TARGET FUNCTION is shared
    rng = np.random.RandomState(0)
    w_true = rng.randn(16, 1).astype(np.float32)
    xs = rng.randn(args.samples, 16).astype(np.float32)
    ys = xs @ w_true
    shard_x = xs[rank::nworker]
    shard_y = ys[rank::nworker]
    # every rank must run the SAME number of steps: trainer.step is a
    # collective, so uneven shards would desequence the allreduces --
    # truncate to the minimum shard length
    common = len(xs) // nworker
    shard_x, shard_y = shard_x[:common], shard_y[:common]

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(1))
    net.initialize(ctx=mx.cpu())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr},
                            kvstore="dist_sync")
    loss_fn = gluon.loss.L2Loss()

    n = len(shard_x)
    for epoch in range(args.epochs):
        total, nbatch = 0.0, 0
        for s in range(0, n, args.batch_size):
            x = mx.nd.array(shard_x[s:s + args.batch_size])
            y = mx.nd.array(shard_y[s:s + args.batch_size])
            with autograd.record():
                loss = loss_fn(net(x), y).mean()
            loss.backward()
            trainer.step(1)
            total += float(loss.asnumpy())
            nbatch += 1
        print("[rank %d] epoch %d loss %.4f"
              % (rank, epoch, total / max(1, nbatch)), flush=True)

    # every rank must hold identical weights (allreduced training)
    from mxnet_tpu.distributed import host_allreduce
    for name, param in sorted(net.collect_params().items()):
        local = np.float64(param.data().asnumpy())
        summed = np.asarray(host_allreduce(local))
        np.testing.assert_allclose(summed, nworker * local, rtol=1e-6,
                                   err_msg=name)
    kv.barrier()
    print("[rank %d] TRAINED OK (replicated weights verified)" % rank,
          flush=True)


if __name__ == "__main__":
    main()
