"""Runtime feature detection (reference: ``python/mxnet/runtime.py ::
Features`` over ``src/libinfo.cc``).

The reference reports compile-time flags (CUDA, MKLDNN, OPENMP, ...).
Here features are runtime properties of the JAX/XLA substrate: which
PJRT backends are live, whether a TPU is attached, which optional
subsystems (Pallas kernels, native recordio) loaded.
"""
from __future__ import annotations

from collections import namedtuple

Feature = namedtuple("Feature", ["name", "enabled"])


def _detect():
    import jax

    def has_backend(name):
        try:
            return len(jax.devices(name)) > 0
        except Exception:
            return False

    tpu = has_backend("tpu") or has_backend("axon")
    feats = {
        "TPU": tpu,
        "GPU": has_backend("gpu"),
        "CPU": True,
        "CUDA": False,          # by design: XLA/PJRT, not CUDA
        "CUDNN": False,
        "MKLDNN": False,        # XLA:CPU is the CPU backend
        "XLA": True,
        "PALLAS": _try_import("jax.experimental.pallas"),
        "BF16": True,           # native MXU dtype
        "INT64_TENSOR_SIZE": True,
        "SIGNAL_HANDLER": True,
        # cheap probe -- must not trigger a blocking g++ build
        "NATIVE_RECORDIO": _native_built(),
        "DIST_KVSTORE": True,   # jax.distributed + collectives
        "OPENMP": False,
        "F16C": True,
        # runtime telemetry subsystem (mx.telemetry): reports the LIVE
        # enable state, so feature_list() answers "is this run
        # instrumented" rather than "was it compiled in"
        "TELEMETRY": _telemetry_enabled(),
        # concurrency sanitizer (mx.sync): LIVE arm state, same
        # contract as the TELEMETRY row
        "TSAN": _tsan_enabled(),
        # compiled-step cost accounting (mx.profiling): LIVE enable
        # state, same contract as the TELEMETRY row
        "PROFILING": _profiling_enabled(),
        # sharding sanitizer compiled layer (analysis.sharding):
        # whether MXNET_TPU_SHARD_CHECK armed collective-contract
        # capture for this run
        "SHARD_CHECK": _shard_check_enabled(),
        # Pallas kernel tier (mx.kernels): whether MXNET_TPU_KERNELS=1
        # armed the full tier for this run (fusion sites + bucketed
        # optimizer + interpret-mode kernels off-TPU); auto mode still
        # selects profitable kernels on TPU with this row False
        "KERNELS": _kernels_armed(),
        # chaos fault injection (mx.chaos): LIVE arm state -- True only
        # inside a chaos.arm()/chaos.scenario() window, never in a
        # production process (no env var arms it)
        "CHAOS": _chaos_armed(),
        # non-finite sentinel (analysis.numerics): whether
        # MXNET_TPU_NUMERICS_CHECK armed the fused per-step isfinite
        # check + first-offender attribution for this run
        "NUMERICS": _numerics_check_enabled(),
        # live-buffer leak sentinel (analysis.memory): whether
        # MXNET_TPU_MEMORY_WATCH armed the per-window live-array
        # census + leak sentinel for this run
        "MEMORY_WATCH": _memory_watch_enabled(),
        # request/step tracing (mx.obs): LIVE arm state, same contract
        # as the TELEMETRY row
        "OBS_TRACE": _obs_tracing(),
        # goodput ledger (mx.obs.goodput): LIVE arm state of the
        # per-window step-time attribution + regression sentinel
        "OBS_GOODPUT": _obs_goodput(),
        # fleet observability plane (mx.obs.fleet): whether this
        # process publishes a discovery endpoint or runs a
        # FleetMonitor (MXNET_TPU_OBS_ENDPOINTS_DIR or a live monitor)
        "FLEET": _fleet_active(),
    }
    return {k: Feature(k, bool(v)) for k, v in feats.items()}


def _telemetry_enabled():
    from . import telemetry
    return telemetry.enabled()


def _obs_tracing():
    from . import obs
    return obs.tracing_enabled()


def _obs_goodput():
    from . import obs
    return obs.goodput_enabled()


def _fleet_active():
    from .obs import fleet
    return fleet.active()


def _tsan_enabled():
    from . import sync
    return sync.tsan_enabled()


def _profiling_enabled():
    from . import profiling
    return profiling.enabled()


def _kernels_armed():
    from . import kernels
    return kernels.mode() == "on"


def _chaos_armed():
    from . import chaos
    return chaos.armed()


def _shard_check_enabled():
    # env-read directly (the sharding module's shard_check_enabled()
    # reads the same variable); importing mxnet_tpu.analysis here would
    # drag the whole lint stack into feature probing
    import os
    return os.environ.get("MXNET_TPU_SHARD_CHECK", "0") != "0"


def _numerics_check_enabled():
    # env-read directly (analysis.numerics.check_enabled() reads the
    # same variable at import); importing mxnet_tpu.analysis here would
    # drag the whole lint stack into feature probing
    import os
    return os.environ.get("MXNET_TPU_NUMERICS_CHECK", "0") != "0"


def _memory_watch_enabled():
    # env-read directly (analysis.memory.watch_enabled() reads the
    # same variable at import); importing mxnet_tpu.analysis here would
    # drag the whole lint stack into feature probing
    import os
    return os.environ.get("MXNET_TPU_MEMORY_WATCH", "0") != "0"


def _try_import(mod):
    import importlib
    try:
        importlib.import_module(mod)
        return True
    except Exception:
        return False


def _native_built():
    try:
        from ._native import available
        return available()
    except Exception:
        return False


class Features(dict):
    """Reference: ``mx.runtime.Features()`` -- mapping of feature name to
    Feature(name, enabled) with ``is_enabled``."""

    def __init__(self):
        super().__init__(_detect())

    def is_enabled(self, feature_name):
        feature_name = feature_name.upper()
        if feature_name not in self:
            raise RuntimeError("unknown feature %r" % feature_name)
        return self[feature_name].enabled

    def __repr__(self):
        return "[%s]" % ", ".join(
            "✔ %s" % k if v.enabled else "✖ %s" % k
            for k, v in sorted(self.items()))


def feature_list():
    """Reference: ``libinfo_features``."""
    return list(Features().values())


def env_vars():
    """Every registered ``MXNET_*`` env var with its current (typed)
    value, default, and doc -- backed by the ``mx.env`` registry, so
    this listing and the generated doc page cannot drift from what the
    code reads."""
    from . import env as _env
    return _env.describe()
