"""HybridBlock -> (-symbol.json, -NNNN.params) export (reference:
``gluon/block.py :: HybridBlock.export``).

The block's ``hybrid_forward`` is re-traced with ``F = mx.sym`` (the
reference's dual-F contract), producing a graph over the shared op
registry; parameters are saved with the reference's ``arg:``/``aux:`` key
prefixes so ``SymbolBlock.imports`` and third-party loaders interoperate.
"""
from __future__ import annotations

from ..base import MXNetError
from .. import ndarray as nd_mod


def symbolic_forward(block, *input_syms):
    """Run a block's forward in Symbol mode."""
    return block(*input_syms)


def export_block(block, path, epoch=0, input_names=("data",)):
    from . import symbol as sym_api
    from .symbol import var
    inputs = [var(n) for n in input_names]
    out = symbolic_forward(block, *inputs)
    if isinstance(out, (list, tuple)):
        from .symbol import Group
        out = Group(list(out))
    sym_file = "%s-symbol.json" % path
    out.save(sym_file)
    arg = {}
    for p in block._all_params():
        if p._data is None:
            continue
        prefix = "aux:" if p._grad_req == "null" else "arg:"
        arg[prefix + p.name] = p.data()
    params_file = "%s-%04d.params" % (path, epoch)
    nd_mod.save(params_file, arg)
    return sym_file, params_file
