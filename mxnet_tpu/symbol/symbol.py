"""Symbol: declarative graph composition.

TPU-native re-design of the reference's nnvm Symbol world
(``3rdparty/tvm/nnvm :: nnvm::Graph/Node``, ``python/mxnet/symbol/
symbol.py``).  A Symbol is a DAG of op nodes over the SAME op registry as
``mx.nd`` -- execution is a topological walk of pure JAX calls, jitted by
the Executor (the XLA answer to GraphExecutor+PlanMemory: buffer
assignment and fusion come from the compiler).

Serialization keeps the reference's ``-symbol.json`` schema (``nodes`` /
``arg_nodes`` / ``heads``) so exported models interoperate.
"""
from __future__ import annotations

import ast
import json

import numpy as np

from ..base import MXNetError, _NameManager
from ..ops.registry import OP_REGISTRY, get_op

__all__ = ["Symbol", "var", "Variable", "Group", "load", "load_json",
           "_eval_symbol"]


class _Node:
    __slots__ = ("op", "name", "attrs", "inputs", "num_outputs")

    def __init__(self, op, name, attrs, inputs, num_outputs=1):
        self.op = op            # op name string, or None for variable
        self.name = name
        self.attrs = attrs      # dict[str, str-able]
        self.inputs = inputs    # list[(Node, out_index)]
        self.num_outputs = num_outputs


class Symbol:
    """One or more output entries of a graph (reference: ``Symbol``)."""

    def __init__(self, outputs):
        self._outputs = outputs  # list[(Node, out_index)]

    # -- composition ---------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __repr__(self):
        return "<Symbol %s>" % (self.name or "group[%d]" % len(self._outputs))

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output %r not found" % index)
            index = names.index(index)
        return Symbol([self._outputs[index]])

    def __len__(self):
        return len(self._outputs)

    def __iter__(self):
        return (self[i] for i in range(len(self._outputs)))

    def _binop(self, other, opname, reverse=False):
        if isinstance(other, Symbol):
            lhs, rhs = (other, self) if reverse else (self, other)
            return _make_node(opname, [lhs, rhs], {})
        scalar_map = {"elemwise_add": "_plus_scalar",
                      "elemwise_sub": "_rminus_scalar" if reverse else "_minus_scalar",
                      "elemwise_mul": "_mul_scalar",
                      "elemwise_div": "_rdiv_scalar" if reverse else "_div_scalar",
                      "broadcast_power": "_rpower_scalar" if reverse else "_power_scalar"}
        return _make_node(scalar_map[opname], [self], {"scalar": float(other)})

    def __add__(self, o):
        return self._binop(o, "elemwise_add")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul")

    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binop(o, "elemwise_div")

    def __rtruediv__(self, o):
        return self._binop(o, "elemwise_div", reverse=True)

    def __pow__(self, o):
        return self._binop(o, "broadcast_power")

    def __neg__(self):
        return _make_node("negative", [self], {})

    # -- graph queries -------------------------------------------------
    def _topo(self):
        # Iterative DFS: graph depth is unbounded (deep sequential models),
        # so recursion would hit the Python stack limit.
        order = []
        seen = set()
        for root, _ in self._outputs:
            if id(root) in seen:
                continue
            stack = [(root, False)]
            while stack:
                node, expanded = stack.pop()
                if expanded:
                    order.append(node)
                    continue
                if id(node) in seen:
                    continue
                seen.add(id(node))
                stack.append((node, True))
                for inp, _ in reversed(node.inputs):
                    if id(inp) not in seen:
                        stack.append((inp, False))
        return order

    def list_arguments(self):
        """Variable names in topo order (reference: ``list_arguments``)."""
        return [n.name for n in self._topo() if n.op is None]

    def list_outputs(self):
        out = []
        for node, idx in self._outputs:
            if node.num_outputs > 1:
                out.append("%s_output%d" % (node.name, idx))
            else:
                out.append(node.name + "_output")
        return out

    def list_auxiliary_states(self):
        return []

    def get_internals(self):
        nodes = self._topo()
        return Symbol([(n, i) for n in nodes for i in range(n.num_outputs)])

    def attr(self, key):
        return self._outputs[0][0].attrs.get(key)

    # -- shape/type inference -----------------------------------------
    def infer_shape(self, **kwargs):
        """Reference: ``infer_shape`` (nnvm InferShape pass) -- here via
        jax.eval_shape over the graph."""
        import jax
        arg_names = self.list_arguments()
        known = {k: tuple(v) for k, v in kwargs.items()}
        missing = [a for a in arg_names if a not in known]
        if missing:
            return None, None, None
        specs = {a: jax.ShapeDtypeStruct(known[a], np.float32)
                 for a in arg_names}
        outs = _eval_symbol_abstract(self, specs)
        arg_shapes = [known[a] for a in arg_names]
        out_shapes = [tuple(o.shape) for o in outs]
        return arg_shapes, out_shapes, []

    def infer_type(self, **kwargs):
        arg_names = self.list_arguments()
        return ([np.float32] * len(arg_names),
                [np.float32] * len(self._outputs), [])

    # -- execution -----------------------------------------------------
    def eval(self, ctx=None, **kwargs):
        from ..ndarray import NDArray
        feed = {k: v for k, v in kwargs.items()}
        outs = _eval_symbol(self, feed)
        return outs

    def bind(self, ctx=None, args=None, args_grad=None, grad_req="write",
             aux_states=None, **kwargs):
        from ..executor import Executor
        return Executor(self, ctx, args, args_grad, grad_req)

    def simple_bind(self, ctx=None, grad_req="write", **shapes):
        from ..executor import Executor
        from ..ndarray import zeros
        args = {}
        for name in self.list_arguments():
            if name in shapes:
                args[name] = zeros(shapes[name], ctx=ctx)
            else:
                raise MXNetError("simple_bind: missing shape for %r" % name)
        args_grad = {k: zeros(v.shape, ctx=ctx) for k, v in args.items()} \
            if grad_req != "null" else None
        return Executor(self, ctx, args, args_grad, grad_req)

    # -- serialization (reference: nnvm saveload_json.cc) -------------
    def tojson(self):
        nodes = self._topo()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append({
                "op": n.op if n.op is not None else "null",
                "name": n.name,
                "attrs": {k: str(v) for k, v in n.attrs.items()},
                "inputs": [[node_ids[id(src)], oi, 0] for src, oi in n.inputs],
            })
        heads = [[node_ids[id(n)], oi, 0] for n, oi in self._outputs]
        arg_nodes = [i for i, n in enumerate(nodes) if n.op is None]
        return json.dumps({
            "nodes": jnodes, "arg_nodes": arg_nodes, "heads": heads,
            "attrs": {"mxnet_version": ["int", 10700],
                      "mxnet_tpu": ["str", "1"]},
        }, indent=2)

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())


def var(name, shape=None, dtype=None, **kwargs):
    """Create a variable symbol (reference: ``symbol.var``)."""
    attrs = {}
    if shape is not None:
        attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        attrs["__dtype__"] = str(dtype)
    attrs.update({k: str(v) for k, v in kwargs.items()})
    return Symbol([(_Node(None, name, attrs, []), 0)])


Variable = var


def Group(symbols):
    outs = []
    for s in symbols:
        outs.extend(s._outputs)
    return Symbol(outs)


def _parse_attr_value(v):
    # Attrs loaded from -symbol.json are untrusted; literal_eval covers the
    # tuples/numbers/bools they contain without an eval() code-exec surface
    # (the reference parses attrs with typed dmlc parameter parsing).
    s = str(v)
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def _make_node(opname, input_syms, params, name=None):
    op = get_op(opname)
    hint = opname.lower().lstrip("_")
    name = _NameManager.current().get(name, hint)
    inputs = []
    for s in input_syms:
        if not isinstance(s, Symbol):
            raise MXNetError("op %s: expected Symbol input, got %r"
                             % (opname, s))
        if len(s._outputs) != 1:
            raise MXNetError("op %s: cannot take group symbol" % opname)
        inputs.append(s._outputs[0])
    # count outputs via an abstract probe later; store param attrs now
    node = _Node(opname, name, dict(params), inputs)
    node.num_outputs = _probe_num_outputs(op, node)
    return Symbol([(node, i) for i in range(node.num_outputs)]) \
        if node.num_outputs > 1 else Symbol([(node, 0)])


def _probe_num_outputs(op, node):
    # cheap static probes for known multi-output ops
    if op.name == "split" or op.name == "SliceChannel":
        return int(node.attrs.get("num_outputs", 1))
    if op.name == "BatchNorm":
        return 3
    if op.name == "RNN":
        return 3 if node.attrs.get("mode", "lstm") == "lstm" else 2
    if op.name == "topk":
        return 2 if node.attrs.get("ret_typ") == "both" else 1
    return 1


def _eval_node_value(node, values, op_params_override=None):
    """Evaluate one node given input values."""
    from .. import random as _random_mod
    op = get_op(node.op)
    params = op.param_defaults()
    for k, v in node.attrs.items():
        if k.startswith("__"):
            continue
        if any(p.name == k for p in op.params):
            params[k] = _parse_attr_value(v)
    args = [values[(id(src), oi)] for src, oi in node.inputs]
    if not op.variadic and len(args) < len(op.arg_names):
        # optional trailing tensor inputs (e.g. bias with no_bias=True)
        args = args + [None] * (len(op.arg_names) - len(args))
    fn = op.fcompute
    if op.stateful_rng:
        import functools
        fn = functools.partial(fn, _random_mod.next_key())
    from .. import autograd
    if any(p.name == "training" for p in op.params) and \
            "training" not in node.attrs:
        params["training"] = autograd.is_training()
    return fn(*args, **params)


def _eval_symbol(sym, feed):
    """Execute a symbol graph eagerly against a name->NDArray feed."""
    from ..ndarray import NDArray
    values = {}
    for node in sym._topo():
        if node.op is None:
            if node.name not in feed:
                raise MXNetError("missing input %r" % node.name)
            v = feed[node.name]
            values[(id(node), 0)] = getattr(v, "_data", v)
        else:
            out = _eval_node_value(node, values)
            if isinstance(out, (tuple, list)):
                for i, o in enumerate(out):
                    values[(id(node), i)] = o
            else:
                values[(id(node), 0)] = out
    return [NDArray(values[(id(n), oi)]) for n, oi in sym._outputs]


def _eval_symbol_abstract(sym, specs):
    import jax

    names = sym.list_arguments()

    def fn(vals):
        feed = {n: _FakeND(vals[n]) for n in names}
        outs = _eval_symbol(sym, feed)
        return [o._data for o in outs]

    class _FakeND:
        def __init__(self, data):
            self._data = data

    return jax.eval_shape(fn, {n: specs[n] for n in names})


def load_json(json_str):
    """Parse a ``-symbol.json`` graph (reference: ``sym.load_json``)."""
    data = json.loads(json_str)
    jnodes = data["nodes"]
    nodes = []
    for jn in jnodes:
        attrs = jn.get("attrs", jn.get("param", {})) or {}
        if jn["op"] == "null":
            node = _Node(None, jn["name"], attrs, [])
        else:
            opname = jn["op"]
            if opname not in OP_REGISTRY:
                raise MXNetError("symbol json references unknown op %r"
                                 % opname)
            node = _Node(opname, jn["name"], attrs, [])
        nodes.append(node)
    for jn, node in zip(jnodes, nodes):
        node.inputs = [(nodes[i], oi) for i, oi, *_ in jn["inputs"]]
        if node.op is not None:
            node.num_outputs = _probe_num_outputs(get_op(node.op), node)
    heads = data.get("heads", [[len(nodes) - 1, 0, 0]])
    return Symbol([(nodes[i], oi) for i, oi, *_ in heads])


def load(fname):
    with open(fname) as f:
        return load_json(f.read())
